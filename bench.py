"""North-star benchmark: conflict-resolution throughput on the TPU backend.

Workload: the shape of the reference's in-tree conflict-set microbench
(`fdbserver -r skiplisttest`, fdbserver/SkipList.cpp:1412-1551 — 1 read
conflict range + 1 write conflict range per transaction, uniform random
keys), streamed as commit batches with snapshots one VERSION_STEP
behind (GRV lag) and the MVCC window advancing per
MAX_WRITE_TRANSACTION_LIFE_VERSIONS. The default backend is the
point-op resolve kernel (ops/point_kernel.py) — the ranges here are
single keys, exactly FDB's commit hot path — whose verdicts are
parity-locked to the CPU baselines by tests/test_point_resolver.py.
Steady-state history spans WINDOW_BATCHES batches (~330k live point
writes at the default shape; the reference microbench holds ~125k live
ranges: 50-batch window x 2500 txns).

Prints exactly one JSON line:
  metric       resolver_throughput
  value/unit   conflict-checked transactions per second (sustained)
  vs_baseline  ratio vs the north-star target of 1e6 txn/s on v5e-1
               (BASELINE.json north_star; the reference's published
               figures are per-cluster, see BASELINE.md)

Env overrides: FDBTPU_BENCH_TXNS (batch size), FDBTPU_BENCH_BATCHES
(timed batches), FDBTPU_BENCH_KEYS (keyspace), FDBTPU_BENCH_READS
(reads per txn), FDBTPU_BENCH_BACKEND (tpu-point|tpu|tpu-streamed|
tpu-pipelined|tpu-packed|python|native|native-streamed — CPU
baselines for comparison runs; tpu-packed is the packed single-buffer
interval feed vs its unpacked baseline; native-streamed is the
first-class C-ABI row with pre-marshalled batches and its own
ABI-call ceiling math, ROADMAP item 1's tunnel-down pivot),
FDBTPU_BENCH_PIPELINE_DEPTH (headline K for the tpu-pipelined
submit/drain window; `all` mode sweeps K in {1,2,4,8}).

`bench.py --dry` runs the packed/unpacked interval parity gate instead
of a bench round (CI: a feed-path divergence fails the gate, not a
hardware round) — see run_dry.
"""

import json
import os
import sys
import time

import numpy as np

TARGET_TXN_PER_S = 1_000_000.0  # north star (BASELINE.json)
MWTLV = 5_000_000
KEY_BYTES = 16
N_WORDS = KEY_BYTES // 4
READS_PER_TXN = int(os.environ.get("FDBTPU_BENCH_READS", 1))
VERSION_STEP = 250_000
WINDOW_BATCHES = MWTLV // VERSION_STEP


def make_batch(rng, n_txns, keyspace, version):
    """Pre-encoded arrays for one batch: 16-byte big-endian point keys
    (the reference microbench's key width, SkipList.cpp:1429-1502 —
    round-2 VERDICT asked for the matching shape)."""
    rk = rng.integers(0, keyspace, size=n_txns * READS_PER_TXN, dtype=np.int64)
    wk = rng.integers(0, keyspace, size=n_txns, dtype=np.int64)

    def enc(idx, end):
        k = np.zeros((idx.shape[0], N_WORDS + 1), np.uint32)
        # low words carry the id -> full-width 16-byte keys; the end key
        # is key + b"\x00", encoded as the same words + length 17 (the
        # row compare is lexicographic over (words, length))
        k[:, N_WORDS - 2] = (idx >> 32).astype(np.uint32)
        k[:, N_WORDS - 1] = (idx & 0xFFFFFFFF).astype(np.uint32)
        k[:, N_WORDS] = KEY_BYTES + 1 if end else KEY_BYTES
        return k

    snapshots = np.full(n_txns, version - VERSION_STEP, np.int64)
    has_reads = np.ones(n_txns, bool)
    rt = np.repeat(np.arange(n_txns, dtype=np.int32), READS_PER_TXN)
    wt = np.arange(n_txns, dtype=np.int32)
    return (snapshots, has_reads, enc(rk, False), enc(rk, True), rt,
            enc(wk, False), enc(wk, True), wt)


def _measure_device_run(run, probe_count, init_state, n_batches, cap, slack):
    """Shared timing harness for the device-driven bench loops.

    `run(*init_state, nb)` executes nb chained resolve steps in one
    dispatch and returns a carry whose [3] is the conflict count;
    `probe_count(*carry[:3], nb)` runs one more step on the final state
    and returns the live-row count (the capacity audit, outside the
    timed region). Remote-link latency fluctuates wildly, so the floor
    of an empty sync round-trip is measured per repeat and subtracted —
    but never more than 70% of a run — and the best repeat wins.
    """
    import jax
    import jax.numpy as jnp

    first_elem = jax.jit(lambda a: a.reshape(-1)[0])  # jit once: sync()
    # must measure the link round-trip, not retrace/recompile time

    def sync(x):
        return np.asarray(first_elem(x))

    out = run(*init_state, jnp.int32(2))
    sync(out[3])
    elapsed = float("inf")
    n_conflicts = 0
    for _ in range(int(os.environ.get("FDBTPU_BENCH_REPEATS", 4))):
        t0 = time.perf_counter()
        sync(jnp.int32(0))
        sync_floor = time.perf_counter() - t0
        t0 = time.perf_counter()
        out = run(*init_state, jnp.int32(n_batches))
        n_conflicts = int(sync(out[3]))
        raw = time.perf_counter() - t0
        elapsed = min(elapsed, max(raw - sync_floor, 0.3 * raw, 1e-3))
    final_count = int(sync(probe_count(out[0], out[1], out[2],
                                       jnp.int32(n_batches))))
    if final_count > cap - slack:
        raise RuntimeError(
            f"bench state capacity overflow: count {final_count} vs cap "
            f"{cap} — rows would silently drop; raise cap sizing")
    return elapsed, n_conflicts


def bench_tpu_point(n_txns, n_batches, keyspace):
    """Device-driven point-mode bench: batches generated on-device, all
    n_batches resolve steps chained in one fori_loop dispatch. 16-byte
    point keys (id in the low words), READS_PER_TXN point reads + 1
    point write per txn."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from foundationdb_tpu.ops.keys import next_pow2
    from foundationdb_tpu.ops.point_kernel import make_point_resolve_core

    n_txns = next_pow2(n_txns)
    if (n_batches + 4) * VERSION_STEP >= (1 << 30):
        raise ValueError("FDBTPU_BENCH_BATCHES too large for int32 offsets")
    n_words = N_WORDS  # 16-byte point keys (reference microbench width)
    nr = next_pow2(n_txns * READS_PER_TXN)
    nw = n_txns
    # steady state: one write row per txn per batch, live for
    # WINDOW_BATCHES batches (+1 pending prune, + merge slack)
    cap = next_pow2((WINDOW_BATCHES + 2) * n_txns + 2)
    # verdict-only variant: the bench never reads attribution, and a
    # jitted output is never DCE'd — opting out statically keeps the
    # measured ratios free of the attribution pass
    core = make_point_resolve_core(cap, n_txns, nr, nw, n_words,
                                   attribute=False)

    def gen_keys(key, slots):
        idx = jax.random.randint(key, (slots,), 0, keyspace, dtype=jnp.int32)
        k = jnp.zeros((slots, n_words + 1), jnp.uint32)
        k = k.at[:, n_words - 1].set(idx.astype(jnp.uint32))
        return k.at[:, n_words].set(KEY_BYTES)

    rt = jnp.asarray(np.minimum(
        np.arange(nr) // READS_PER_TXN, n_txns).astype(np.int32))
    wt = jnp.arange(nw, dtype=jnp.int32)
    rvalid = jnp.asarray(np.arange(nr) < n_txns * READS_PER_TXN)
    wvalid = jnp.ones(nw, bool)
    too_old = jnp.zeros(n_txns, bool)

    def body(i, carry):
        sk, sv, key, nconf = carry
        key, kr, kw = jax.random.split(key, 3)
        rk = gen_keys(kr, nr)
        wk = gen_keys(kw, nw)
        commit = (jnp.int32(i) + 2) * VERSION_STEP
        snap = jnp.full((n_txns,), 1, jnp.int32) * (commit - VERSION_STEP)
        oldest = jnp.maximum(commit - MWTLV, 0)
        sk, sv, _count, conflict = core(
            sk, sv, snap, too_old, rk, rt, rvalid, wk, wt, wvalid,
            commit, oldest, jnp.int32(0))
        return sk, sv, key, nconf + jnp.sum(conflict.astype(jnp.int32))

    @jax.jit
    def run(sk, sv, key, nb):
        return lax.fori_loop(0, nb, body, (sk, sv, key, jnp.int32(0)))

    @jax.jit
    def probe_count(sk, sv, key, nb):
        out = body(nb, (sk, sv, key, jnp.int32(0)))
        key2, kr, kw = jax.random.split(out[2], 3)
        rk = gen_keys(kr, nr)
        wk = gen_keys(kw, nw)
        commit = (nb + 3) * VERSION_STEP
        snap = jnp.full((n_txns,), 1, jnp.int32) * (commit - VERSION_STEP)
        _, _, count, _ = core(
            out[0], out[1], snap, too_old, rk, rt, rvalid, wk, wt, wvalid,
            commit, jnp.maximum(commit - MWTLV, 0), jnp.int32(0))
        return count

    sk0 = np.full((cap, n_words + 1), 0xFFFFFFFF, np.uint32)
    sv0 = np.full((cap,), -(1 << 30), np.int32)
    elapsed, n_conflicts = _measure_device_run(
        run, probe_count,
        (jnp.asarray(sk0), jnp.asarray(sv0), jax.random.PRNGKey(7)),
        n_batches, cap, slack=2)
    return n_batches * n_txns / elapsed, n_conflicts


def bench_tpu(n_txns, n_batches, keyspace):
    """Device-driven: batches are generated on-device (jax PRNG) and
    n_batches resolve steps are chained inside one fori_loop — one
    dispatch for the whole run, mirroring the reference's in-process
    skiplisttest harness (fdbserver/SkipList.cpp:1412-1551). The
    host-fed streamed path is FDBTPU_BENCH_BACKEND=tpu-streamed."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from foundationdb_tpu.ops.conflict_kernel import make_resolve_core
    from foundationdb_tpu.ops.keys import next_pow2

    n_txns = next_pow2(n_txns)  # kernel shape buckets are powers of two
    if (n_batches + 4) * VERSION_STEP >= (1 << 30):
        raise ValueError("FDBTPU_BENCH_BATCHES too large: device versions "
                         "are int32 offsets and the bench loop never rebases")
    # steady-state boundary count: one write (2 boundaries) per txn per
    # batch, live for MWTLV/VERSION_STEP batches, plus merge slack
    window_batches = MWTLV // VERSION_STEP
    cap = max(1 << 17, next_pow2(3 * window_batches * n_txns))
    n_words = N_WORDS
    # exact power-of-two slot counts: a single extra slot doubles
    # every padded dimension (and quadruples the overlap matrix)
    nr = next_pow2(n_txns * READS_PER_TXN)
    nw = next_pow2(n_txns)
    core = make_resolve_core(cap, n_txns, nr, nw, n_words,
                             attribute=False)   # verdict-only bench

    def gen_keys(key, slots):
        idx = jax.random.randint(key, (slots,), 0, keyspace, dtype=jnp.int32)
        k = jnp.zeros((slots, n_words + 1), jnp.uint32)
        k = k.at[:, n_words - 1].set(idx.astype(jnp.uint32))
        return k.at[:, n_words].set(KEY_BYTES)

    rt = jnp.asarray(np.minimum(
        np.arange(nr) // READS_PER_TXN, n_txns).astype(np.int32))
    wt = jnp.asarray(np.minimum(np.arange(nw), n_txns).astype(np.int32))
    rvalid = jnp.asarray(np.arange(nr) < n_txns * READS_PER_TXN)
    wvalid = jnp.asarray(np.arange(nw) < n_txns)
    too_old = jnp.zeros(n_txns, bool)

    def one_step(i, hk, hv, key):
        key, kr, kw = jax.random.split(key, 3)
        rb = gen_keys(kr, nr)
        re = rb.at[:, n_words].set(KEY_BYTES + 1)  # end = key + b"\x00"
        wb = gen_keys(kw, nw)
        we = wb.at[:, n_words].set(KEY_BYTES + 1)
        commit = (jnp.int32(i) + 2) * VERSION_STEP
        snap = jnp.full((n_txns,), 1, jnp.int32) * (commit - VERSION_STEP)
        oldest = jnp.maximum(commit - MWTLV, 0)
        return key, core(hk, hv, snap, too_old, rb, re, rt, rvalid,
                         wb, we, wt, wvalid, commit, oldest)

    def body(i, carry):
        hk, hv, key, nconf = carry
        key, (hk, hv, _count, conflict) = one_step(i, hk, hv, key)
        # NB: _count must stay out of the carry — a loop-carried scalar
        # depending on the compaction tail measurably breaks fusion (6x).
        return hk, hv, key, nconf + jnp.sum(conflict.astype(jnp.int32))

    @jax.jit
    def run(hk, hv, key, nb):
        return lax.fori_loop(0, nb, body, (hk, hv, key, jnp.int32(0)))

    @jax.jit
    def probe_count(hk, hv, key, nb):
        _, (_, _, count, _) = one_step(nb, hk, hv, key)
        return count

    hk0 = np.full((cap, n_words + 1), 0xFFFFFFFF, np.uint32)
    hk0[0] = 0
    hv0 = np.full((cap,), -(1 << 30), np.int32)
    hv0[0] = 0
    elapsed, n_conflicts = _measure_device_run(
        run, probe_count,
        (jnp.asarray(hk0), jnp.asarray(hv0), jax.random.PRNGKey(7)),
        n_batches, cap, slack=2 * n_txns + 2)
    return n_batches * n_txns / elapsed, n_conflicts


def bench_tpu_streamed(n_txns, n_batches, keyspace, backend="point"):
    """Host-fed path: per-batch H2D + dispatch through resolve_arrays —
    what a real resolver role pays per batch, marshalling and transfer
    included. JAX's async dispatch double-buffers naturally: batch i+1's
    host prep and H2D overlap batch i's device compute because nothing
    blocks on a result until the very end (verdict readbacks are
    deferred device arrays). `backend` picks the point kernel (the FDB
    hot-path shape, default) or the general interval kernel."""
    from foundationdb_tpu.models.point_resolver import PointConflictSet
    from foundationdb_tpu.models.tpu_resolver import TpuConflictSet
    from foundationdb_tpu.ops.keys import next_pow2

    rng = np.random.default_rng(20260729)
    cap = next_pow2((WINDOW_BATCHES + 2) * n_txns + 2)
    if backend == "point":
        cs = PointConflictSet(key_bytes=KEY_BYTES, capacity=cap)
    else:
        cs = TpuConflictSet(key_bytes=KEY_BYTES, capacity=next_pow2(2 * cap))
    version = VERSION_STEP
    warmup = 3

    batches = [make_batch(rng, n_txns, keyspace, version + i * VERSION_STEP)
               for i in range(warmup + n_batches)]

    results = []
    t0 = None
    for i, b in enumerate(batches):
        v = version + i * VERSION_STEP
        conflict, too_old = cs.resolve_arrays(
            *b, commit_version=v, new_oldest_version=max(0, v - MWTLV))
        results.append(conflict)
        if i + 1 == warmup:
            np.asarray(results[-1])
            t0 = time.perf_counter()
    n_conflicts = int(sum(np.asarray(c)[:n_txns].sum()
                          for c in results[warmup:]))
    elapsed = time.perf_counter() - t0
    # the h2d transfer/bytes counters are the bench record's evidence
    # that the packed single-buffer feed actually ran (ISSUE 14: the
    # gain is COUNTED, not inferred)
    return (n_batches * n_txns / elapsed, n_conflicts,
            cs.kernel_stats()["h2d"])


def bench_tpu_packed(n_txns, n_batches, keyspace):
    """The packed interval feed path vs its unpacked baseline: the SAME
    seeded streamed interval batches through resolve_arrays with
    INTERVAL_PACKED_FEED=1 (one H2D transfer per batch) and =0 (the
    legacy ~12-transfer feed). Divergent conflict counts REFUSE to
    publish — the two paths are bit-identical by construction, so a
    divergence is a bug, not a data point."""
    from foundationdb_tpu.flow.knobs import SERVER_KNOBS
    saved = int(SERVER_KNOBS.interval_packed_feed)
    try:
        SERVER_KNOBS.set("INTERVAL_PACKED_FEED", 1)
        tps_p, nc_p, h2d_p = bench_tpu_streamed(n_txns, n_batches,
                                                keyspace, "interval")
        SERVER_KNOBS.set("INTERVAL_PACKED_FEED", 0)
        tps_u, nc_u, h2d_u = bench_tpu_streamed(n_txns, n_batches,
                                                keyspace, "interval")
    finally:
        SERVER_KNOBS.set("INTERVAL_PACKED_FEED", saved)
    if nc_p != nc_u:
        raise RuntimeError(
            f"packed/unpacked interval conflict counts diverged: "
            f"{nc_p} vs {nc_u} — refusing to publish")
    return tps_p, nc_p, {
        "unpacked_txn_per_s": round(tps_u, 1),
        "speedup_vs_unpacked": round(tps_p / tps_u, 2) if tps_u else None,
        "h2d_packed": h2d_p, "h2d_unpacked": h2d_u}


def bench_tpu_pipelined(n_txns, n_batches, keyspace, depth):
    """Host-fed resolve through the split submit/drain pipeline at a
    FIXED in-flight window of K = `depth` batches: submit batch i, then
    once K tickets are pending drain the oldest before submitting the
    next — exactly the resolver role's behavior after the pipelined
    PR. K=1 is the serial role path (submit, block on the verdict, read
    back, repeat: one dispatch round-trip paid per batch); larger K
    amortizes that round-trip across the window, so on a remote-
    attached chip throughput approaches min(compute ceiling,
    K x serial ceiling). History chains on device across the window
    (donated carry), so verdicts are bit-identical at every depth —
    the sweep asserts equal conflict counts."""
    from foundationdb_tpu.flow.knobs import SERVER_KNOBS
    from foundationdb_tpu.models.point_resolver import PointConflictSet
    from foundationdb_tpu.ops.keys import next_pow2

    rng = np.random.default_rng(20260729)
    cap = next_pow2((WINDOW_BATCHES + 2) * n_txns + 2)
    # the backend's own backpressure must not cut the window short
    SERVER_KNOBS.set("RESOLVE_PIPELINE_DEPTH", depth)
    cs = PointConflictSet(key_bytes=KEY_BYTES, capacity=cap)
    version = VERSION_STEP
    warmup = 3

    batches = [make_batch(rng, n_txns, keyspace, version + i * VERSION_STEP)
               for i in range(warmup + n_batches)]

    def submit(i):
        v = version + i * VERSION_STEP
        return cs.submit_arrays(*batches[i], commit_version=v,
                                new_oldest_version=max(0, v - MWTLV))

    for i in range(warmup):   # compile + settle, fully drained
        cs.drain_arrays(submit(i))

    from collections import deque
    pending: deque = deque()
    n_conflicts = 0
    t0 = time.perf_counter()
    for j in range(n_batches):
        pending.append(submit(warmup + j))
        if len(pending) >= depth:
            conflict, _too_old = cs.drain_arrays(pending.popleft())
            n_conflicts += int(conflict.sum())
    while pending:   # tail drains stay inside the timed region
        conflict, _too_old = cs.drain_arrays(pending.popleft())
        n_conflicts += int(conflict.sum())
    elapsed = time.perf_counter() - t0
    return (n_batches * n_txns / elapsed, n_conflicts,
            _compact_pipeline_stats(cs.pipeline_stats()))


def _compact_pipeline_stats(pipe: dict) -> dict:
    """The resolve-pipeline window accounting for the BENCH json
    (occupancy/peak/forced drains + submit/drain wall percentiles):
    the observability the still-owed tunnel-up round ships with, so a
    depth sweep's numbers come with evidence the window actually ran
    full instead of degenerating to serial."""
    lat = pipe.get("latency") or {}
    out = {k: pipe.get(k) for k in ("depth", "occupancy",
                                    "peak_in_flight", "submits",
                                    "drains", "forced_drains")}
    for stage in ("submit", "drain"):
        snap = lat.get(stage) or {}
        out[f"{stage}_p50_s"] = snap.get("p50")
        out[f"{stage}_p99_s"] = snap.get("p99")
    return out


def _obj_batch(rng, n_txns, keyspace, v):
    """One object-API batch (shared by the CPU baselines and the
    native streamed row so their conflict counts are comparable:
    same rng, same draw order, same 16-byte point keys)."""
    from foundationdb_tpu.models import ResolverTransaction

    txns = []
    for _ in range(n_txns):
        reads = []
        for _ in range(READS_PER_TXN):
            k = int(rng.integers(0, keyspace))
            kb = k.to_bytes(KEY_BYTES, "big")
            reads.append((kb, kb + b"\x00"))
        k = int(rng.integers(0, keyspace))
        kb = k.to_bytes(KEY_BYTES, "big")
        txns.append(ResolverTransaction(v - VERSION_STEP, tuple(reads),
                                        ((kb, kb + b"\x00"),)))
    return txns


def bench_cpu(backend, n_txns, n_batches, keyspace):
    """CPU baselines through the generic object API (for comparison)."""
    from foundationdb_tpu.models import create_conflict_set

    rng = np.random.default_rng(20260729)
    cs = create_conflict_set(backend)
    version = VERSION_STEP

    def obj_batch(v):
        return _obj_batch(rng, n_txns, keyspace, v)

    # batch construction stays OUTSIDE the timed region (the streamed
    # device path pre-encodes its batches too) so the baseline measures
    # resolution, not Python object churn
    prebuilt = [(version + i * VERSION_STEP,
                 obj_batch(version + i * VERSION_STEP))
                for i in range(n_batches)]
    n_conflicts = 0
    t0 = time.perf_counter()
    for v, txns in prebuilt:
        verdicts = cs.resolve(txns, v, max(0, v - MWTLV))
        n_conflicts += sum(1 for x in verdicts if x == 0)
    return n_batches * n_txns / (time.perf_counter() - t0), n_conflicts


def bench_native_streamed(n_txns, n_batches, keyspace):
    """First-class native row (ROADMAP item 1 pivot): the C-ABI hot
    path measured the way the device streamed rows are — marshalling
    hoisted OUT of the timed region, so the loop pays exactly what a
    native resolver role pays per batch: one ctypes call into
    libfdbtpu_native.so plus the skip-probe kernel. The object-API
    `native` baseline re-marshals every batch inside resolve(), so it
    measures Python flattening more than the kernel; this row is the
    backend's honest number.

    Ceiling math (this backend has no link ceiling — the bound is the
    per-batch ABI call): the floor of an EMPTY-batch call (ctypes
    dispatch + GC-window advance, zero conflict work) is measured
    after the timed region, and `abi_ceiling_txn_per_s` =
    n_txns / floor is the throughput if the kernel were free — the
    native analog of `dispatch_roundtrip_ms` bounding the streamed
    device path. `pct_of_abi_ceiling` says how far the kernel itself
    is from that bound.

    Returns (txn_per_s, n_conflicts, detail). Conflict counts are
    comparable to the object-API `native` row at equal batch counts
    (same rng seed + draw order) — `all` mode refuses to publish on a
    divergence."""
    import ctypes

    from foundationdb_tpu.models.native_backend import (NativeConflictSet,
                                                        _marshal)

    rng = np.random.default_rng(20260729)
    cs = NativeConflictSet()
    lib, handle = cs._lib, cs._handle
    version = VERSION_STEP

    p = lambda a, t: a.ctypes.data_as(ctypes.POINTER(t))  # noqa: E731

    # pre-marshalled C-ABI arrays, outside the timed region (the
    # device streamed path pre-encodes with make_batch for the same
    # reason); the transient Python objects are dropped immediately.
    # NO keyed warmup batches: this row must stay bit-comparable to
    # the object-API `native` row (same rng stream, same versions,
    # same window state), so the warmup below uses empty batches at
    # version 0 — they draw nothing and insert nothing
    pre = []
    for i in range(n_batches):
        v = version + i * VERSION_STEP
        arrays = _marshal(_obj_batch(rng, n_txns, keyspace, v))
        pre.append((v, arrays, np.empty(n_txns, np.uint8)))

    def call(v, arrays, out, n):
        snapshots, rc, wc, blob, rr, wr = arrays
        lib.fdbtpu_conflictset_resolve(
            handle, v, max(0, v - MWTLV), n,
            p(snapshots, ctypes.c_int64), p(rc, ctypes.c_int32),
            p(wc, ctypes.c_int32), p(blob, ctypes.c_uint8),
            p(rr, ctypes.c_int64), p(wr, ctypes.c_int64),
            p(out, ctypes.c_uint8))

    empty = _marshal([])
    eout = np.empty(1, np.uint8)
    for _ in range(10):           # warm icache/ctypes, window untouched
        call(0, empty, eout, 0)
    t0 = time.perf_counter()
    for v, arrays, out in pre:
        call(v, arrays, out, n_txns)
    elapsed = time.perf_counter() - t0
    txn_per_s = n_batches * n_txns / elapsed
    # verdict 0 == conflict (the ConflictSetBase convention)
    n_conflicts = int(sum(int((out == 0).sum())
                          for _v, _arrays, out in pre))

    # ABI call floor: empty batches at still-advancing versions (the
    # window keeps moving exactly like a real idle resolver tick)
    v = pre[-1][0]
    n_probe = 500
    t0 = time.perf_counter()
    for j in range(n_probe):
        call(v + (j + 1) * VERSION_STEP, empty, eout, 0)
    abi_floor_s = (time.perf_counter() - t0) / n_probe
    ceiling = n_txns / abi_floor_s if abi_floor_s > 0 else None
    return txn_per_s, n_conflicts, {
        "abi_call_floor_us": round(abi_floor_s * 1e6, 2),
        "abi_ceiling_txn_per_s": round(ceiling, 1) if ceiling else None,
        "pct_of_abi_ceiling": round(100.0 * txn_per_s / ceiling, 2)
        if ceiling else None,
        "batch_wall_us": round(elapsed / n_batches * 1e6, 1),
    }


def _jax_platform() -> str:
    try:
        import jax
        return jax.default_backend()
    except Exception:
        return "none"


def _pipeline_depth() -> int:
    return max(1, int(os.environ.get("FDBTPU_BENCH_PIPELINE_DEPTH", 4)))


def _run_backend(backend, n_txns, n_batches, keyspace):
    if backend == "tpu-point":
        return bench_tpu_point(n_txns, n_batches, keyspace)
    if backend == "tpu":
        return bench_tpu(n_txns, n_batches, keyspace)
    if backend == "tpu-streamed":
        return bench_tpu_streamed(n_txns, n_batches, keyspace)[:2]
    if backend == "tpu-streamed-interval":
        return bench_tpu_streamed(n_txns, n_batches, keyspace,
                                  "interval")[:2]
    if backend == "tpu-packed":
        return bench_tpu_packed(n_txns, n_batches, keyspace)[:2]
    if backend == "native-streamed":
        return bench_native_streamed(n_txns, n_batches, keyspace)[:2]
    return bench_cpu(backend, n_txns, n_batches, keyspace)


def _probe_device(timeout_s: float = 120.0) -> bool:
    """True iff the accelerator answers a trivial computation within
    the timeout. The axon TPU tunnel can hang indefinitely inside
    backend init (device listing still works!) — without this probe a
    dead tunnel turns the bench into an unbounded hang instead of an
    honest error record. The probe runs in a SUBPROCESS: a hung
    attempt inside this process would hold jax's init lock forever and
    make every retry block on the lock instead of re-trying the
    tunnel."""
    import subprocess
    code = ("import jax, jax.numpy as jnp; "
            "x = jnp.ones((8, 8), jnp.float32); "
            "(x @ x).block_until_ready(); print('probe-ok')")
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # probe the accelerator path
    try:
        r = subprocess.run([sys.executable, "-c", code],
                           timeout=timeout_s, capture_output=True, env=env)
        return r.returncode == 0 and b"probe-ok" in r.stdout
    except (subprocess.TimeoutExpired, OSError):
        return False


def _probe_with_retries() -> bool:
    """Bounded retry (~10 min worst case by default): one transient
    tunnel hiccup must not zero a whole round's perf evidence
    (round-3 VERDICT: the watchdog fired once and the round recorded
    an error instead of a number)."""
    attempts = int(os.environ.get("FDBTPU_BENCH_PROBE_RETRIES", 3))
    timeout_s = float(os.environ.get("FDBTPU_BENCH_PROBE_TIMEOUT", 120.0))
    sleep_s = float(os.environ.get("FDBTPU_BENCH_PROBE_SLEEP", 120.0))
    for i in range(attempts):
        if _probe_device(timeout_s):
            return True
        if i + 1 < attempts:
            time.sleep(sleep_s)
    return False


def _init_device_guarded(timeout_s: float = 240.0) -> bool:
    """Initialize THIS process's jax backend under a watchdog. The
    subprocess probe only proves the tunnel was alive a moment ago; if
    it dies between probe and first real jax call, this is the line
    that would otherwise hang unboundedly."""
    import threading

    ok = []

    def attempt():
        try:
            import jax
            import jax.numpy as jnp
            x = jnp.ones((8, 8), jnp.float32)
            (x @ x).block_until_ready()
            ok.append(True)
        except Exception:
            pass

    t = threading.Thread(target=attempt, daemon=True)
    t.start()
    t.join(timeout_s)
    return bool(ok)


def _enable_compile_cache() -> None:
    """Persistent XLA compilation cache (verified working through the
    axon remote-compile path: 12.8s -> 0.8s on a repeat run). The
    kernels here take minutes to compile over the tunnel; caching them
    on disk means one warm run makes every later bench invocation
    measure the kernels, not the compiler."""
    try:
        import jax
        cache_dir = os.environ.get(
            "FDBTPU_JAX_CACHE",
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         ".cache", "jax"))
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass   # cache is an optimization, never a failure


def _measure_transport() -> dict:
    """Host<->device link figures for the JSON record: the streamed
    path's ceiling on a REMOTE-attached chip is the per-dispatch
    round-trip, not the kernels — publish the evidence next to the
    number (a 70ms dispatch bounds 16384-txn streamed batches at ~230k
    txn/s regardless of kernel speed; a local PCIe chip pays ~0.1ms)."""
    import jax
    import jax.numpy as jnp
    f = jax.jit(lambda x: x + 1)
    x = jnp.zeros((8,), jnp.int32)
    np.asarray(f(x))
    t0 = time.perf_counter()
    n_disp = 10
    for _ in range(n_disp):
        np.asarray(f(x))
    dispatch_ms = (time.perf_counter() - t0) / n_disp * 1e3
    host = np.zeros(2 * 1024 * 1024, np.uint32)   # 8MB
    jax.device_put(host).block_until_ready()
    t0 = time.perf_counter()
    for _ in range(3):
        jax.device_put(host).block_until_ready()
    h2d = (time.perf_counter() - t0) / 3
    return {"dispatch_roundtrip_ms": round(dispatch_ms, 2),
            "h2d_mb_s": round(8.0 / h2d, 1)}


def run_dry() -> int:
    """Packed/unpacked parity gate (`bench.py --dry`, CI): seeded
    random INTERVAL batches — mixed widths, empty ranges, tooOld
    snapshots, growth — resolved with attribution through the same
    TpuConflictSet feed path under INTERVAL_PACKED_FEED=1 and =0, plus
    PyConflictSet and BruteForce cross-checks. Verdicts AND attribution
    must match bit-exactly; a divergence fails THIS gate instead of
    poisoning a hardware bench round. No timing is published."""
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")
    import random

    from foundationdb_tpu.flow.knobs import SERVER_KNOBS
    from foundationdb_tpu.models import (BruteForceConflictSet,
                                         PyConflictSet,
                                         ResolverTransaction)
    from foundationdb_tpu.models.tpu_resolver import TpuConflictSet

    rng = random.Random(20260804)

    def rrange():
        a = bytes([rng.randrange(256), rng.randrange(8)])
        b = bytes([rng.randrange(256), rng.randrange(8)])
        if a > b:
            a, b = b, a
        if a == b:
            b = a + (b"\x00" if rng.random() < 0.9 else b"")  # some empty
        return a, b

    n_batches = int(os.environ.get("FDBTPU_BENCH_DRY_BATCHES", 40))
    version, batches = 0, []
    for _ in range(n_batches):
        version += rng.randrange(1, 400_000)
        batch = [ResolverTransaction(
            max(0, version - rng.randrange(0, int(1.4 * MWTLV))),
            tuple(rrange() for _ in range(rng.randrange(0, 5))),
            tuple(rrange() for _ in range(rng.randrange(0, 5))))
            for _ in range(rng.randrange(1, 24))]
        batches.append((version, max(0, version - MWTLV), batch))

    runs = {}
    saved = int(SERVER_KNOBS.interval_packed_feed)
    try:
        for label, knob in (("packed", 1), ("unpacked", 0)):
            SERVER_KNOBS.set("INTERVAL_PACKED_FEED", knob)
            cs = TpuConflictSet(capacity=1 << 10)  # small: forces growth
            out = [cs.resolve_with_attribution(b, v, o)
                   for v, o, b in batches]
            runs[label] = out
    finally:
        SERVER_KNOBS.set("INTERVAL_PACKED_FEED", saved)
    py = PyConflictSet()
    runs["python"] = [py.resolve_with_attribution(b, v, o)
                      for v, o, b in batches]
    bf = BruteForceConflictSet()
    bf_verdicts = [bf.resolve(b, v, o) for v, o, b in batches]

    ok = True
    detail = ""
    for label in ("unpacked", "python"):
        for i, (a, b) in enumerate(zip(runs["packed"], runs[label])):
            if a != b:
                ok = False
                detail = (f"packed vs {label} diverged at batch {i}: "
                          f"{a} != {b}")
                break
        if not ok:
            break
    if ok:
        for i, (a, v) in enumerate(zip(runs["packed"], bf_verdicts)):
            if a[0] != v:
                ok = False
                detail = (f"packed vs brute-force verdicts diverged at "
                          f"batch {i}: {a[0]} != {v}")
                break
    n_conf = sum(sum(1 for x in v if x == 0)
                 for v, _a in runs["packed"])
    print(json.dumps({
        "metric": "packed_interval_parity", "dry": True, "ok": ok,
        "batches": n_batches,
        "txns": sum(len(b) for _v, _o, b in batches),
        "conflicts": n_conf,
        **({"error": detail} if detail else {})}))
    sys.stdout.flush()
    return 0 if ok else 1


def main():
    if "--dry" in sys.argv[1:]:
        return run_dry()
    backend_env = os.environ.get("FDBTPU_BENCH_BACKEND", "all")
    needs_device = backend_env in ("all", "tpu", "tpu-point",
                                   "tpu-streamed", "tpu-streamed-interval",
                                   "tpu-pipelined", "tpu-packed")
    _enable_compile_cache()
    # the periodic kernel-profiling fence (KERNEL_PROFILE_EVERY) drains
    # the async dispatch pipeline the streamed path depends on — the
    # bench measures the unfenced pipeline, so profiling stays off here
    from foundationdb_tpu.flow.knobs import SERVER_KNOBS
    SERVER_KNOBS.set("KERNEL_PROFILE_EVERY", 0)
    n_txns = int(os.environ.get("FDBTPU_BENCH_TXNS", 16384))
    n_batches = int(os.environ.get("FDBTPU_BENCH_BATCHES", 100))
    keyspace = int(os.environ.get("FDBTPU_BENCH_KEYS", 4_000_000))
    backend = backend_env

    def cpu_sub_metrics():
        # the reference's skiplisttest self-comparison (SkipList.cpp:
        # 1412-1551) measures the CPU conflict set on the same host —
        # record the native C++ and pure-Python backends next to the
        # device numbers so "beats the CPU baseline by Nx" is measured,
        # not asserted (round-3 VERDICT weak item 2)
        out = {}
        # batch counts are capped: the prebuilt object batches (kept out
        # of the timed region for honesty) are ~16k Python txn objects
        # per batch — uncapped at 100 batches that is multi-GB RSS
        for name, nb in (("native", min(n_batches, 25)),
                         ("python", min(n_batches, 10))):
            try:
                tps, nc = bench_cpu(name, n_txns, nb, keyspace)
            except Exception as e:       # e.g. .so missing on this host
                out[name] = {"error": str(e)}
                continue
            out[name] = {"txn_per_s": round(tps, 1),
                         "vs_baseline": round(tps / TARGET_TXN_PER_S, 4),
                         "batches": nb, "conflicts": nc}
        # the first-class native streamed row (ROADMAP item 1 pivot):
        # same batch count and seed as the object-API `native` row, so
        # equal conflict counts are a parity gate, and the row carries
        # its own ceiling math (the empty-batch ABI call floor)
        nb = min(n_batches, 25)
        try:
            tps, nc, detail = bench_native_streamed(n_txns, nb, keyspace)
        except Exception as e:
            out["native-streamed"] = {"error": str(e)}
            return out
        obj_nc = out.get("native", {}).get("conflicts")
        if obj_nc is not None and nc != obj_nc:
            raise RuntimeError(
                f"native streamed vs object-API conflict counts "
                f"diverged: {nc} vs {obj_nc} — refusing to publish")
        out["native-streamed"] = {
            "txn_per_s": round(tps, 1),
            "vs_baseline": round(tps / TARGET_TXN_PER_S, 4),
            "batches": nb, "conflicts": nc, **detail}
        return out

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # env-only JAX_PLATFORMS=cpu wedges device init when the axon
        # TPU plugin was registered at interpreter start; the explicit
        # config update (what tests/conftest.py does) actually sticks
        import jax
        jax.config.update("jax_platforms", "cpu")
    elif needs_device and not (_probe_with_retries()
                               and _init_device_guarded()):
        # the device is gone (or died between the subprocess probe and
        # this process's own backend init), but the round's perf
        # evidence need not be an empty record: measure the CPU
        # baselines (jax-free imports)
        print(json.dumps({
            "metric": "resolver_throughput", "value": 0, "unit": "txn/s",
            "vs_baseline": 0.0,
            "error": "accelerator unreachable: device init hung past the "
                     "probe timeout on every retry (axon tunnel down); "
                     "prior recorded TPU result is BENCH_r02.json "
                     "(tpu-point 2.56x)",
            "sub_metrics": cpu_sub_metrics(),
        }))
        sys.stdout.flush()   # piped stdout is block-buffered; the
        os._exit(2)          # possibly-hung jax thread rules out sys.exit

    sub = {}
    if backend == "all":
        # the honest triple (round-2 VERDICT task 5): peak device-driven
        # point + interval kernels, and the host-streamed pipeline —
        # all with 16-byte keys — plus the CPU baselines on the same
        # host. The STREAMED number is the headline: it is what a
        # resolver role actually pays per batch.
        for name in ("tpu-point", "tpu"):
            tps, nc = _run_backend(name, n_txns, n_batches, keyspace)
            sub[name] = {"txn_per_s": round(tps, 1),
                         "vs_baseline": round(tps / TARGET_TXN_PER_S, 4),
                         "conflicts": nc}
        tps, nc, h2d = bench_tpu_streamed(n_txns, n_batches, keyspace)
        sub["tpu-streamed"] = {"txn_per_s": round(tps, 1),
                               "vs_baseline": round(tps / TARGET_TXN_PER_S,
                                                    4),
                               "conflicts": nc, "h2d": h2d}
        # the packed interval feed joins the matrix (ISSUE 14): packed
        # vs unpacked on the SAME batches, plus a cross-mode refusal —
        # the streamed point batches are identical (same rng seed), so
        # the interval backend must see the same conflicts the point
        # backend did, at every feed discipline
        tps_pk, nc_pk, packed_detail = bench_tpu_packed(
            n_txns, n_batches, keyspace)
        if nc_pk != sub["tpu-streamed"]["conflicts"]:
            raise RuntimeError(
                f"per-mode conflict counts diverged: tpu-packed "
                f"{nc_pk} vs tpu-streamed "
                f"{sub['tpu-streamed']['conflicts']} — refusing to "
                f"publish")
        sub["tpu-packed"] = {
            "txn_per_s": round(tps_pk, 1),
            "vs_baseline": round(tps_pk / TARGET_TXN_PER_S, 4),
            "conflicts": nc_pk, **packed_detail}
        # pipelined submit/drain depth sweep: K=1 is the serial
        # role path (one dispatch round-trip per batch); the ratio
        # K=headline / K=1 is the pipelining win the PR claims, and
        # identical conflict counts across depths are the correctness
        # evidence (verdicts are order-chained on device regardless of K)
        pdepth = _pipeline_depth()
        by_depth = {}
        conflicts_by_depth = {}
        pipe_by_depth = {}
        for k in sorted({1, 2, 4, 8} | {pdepth}):
            tps, nc, pstats = bench_tpu_pipelined(n_txns, n_batches,
                                                  keyspace, k)
            by_depth[str(k)] = round(tps, 1)
            conflicts_by_depth[str(k)] = nc
            pipe_by_depth[str(k)] = pstats
        if len(set(conflicts_by_depth.values())) != 1:
            raise RuntimeError(
                f"pipelined conflict counts diverged across depths: "
                f"{conflicts_by_depth}")
        sub["tpu-pipelined"] = {
            "txn_per_s": by_depth[str(pdepth)],
            "vs_baseline": round(by_depth[str(pdepth)]
                                 / TARGET_TXN_PER_S, 4),
            "depth": pdepth,
            "txn_per_s_by_depth": by_depth,
            "conflicts": conflicts_by_depth[str(pdepth)],
            # window-occupancy evidence per depth (ROADMAP item 1: the
            # tunnel-up round lands with pipeline observability)
            "pipeline_stats": pipe_by_depth[str(pdepth)],
            "pipeline_stats_by_depth": pipe_by_depth,
            "speedup_vs_serial": round(by_depth[str(pdepth)]
                                       / by_depth["1"], 2)
            if by_depth["1"] else None,
        }
        sub["transport"] = _measure_transport()
        sub.update(cpu_sub_metrics())
        txn_per_s = sub["tpu-streamed"]["txn_per_s"]
        n_conflicts = sub["tpu-streamed"]["conflicts"]
        backend_name = "tpu-streamed"
    elif backend == "tpu-pipelined":
        # single-backend pipelined run: the window-occupancy evidence
        # rides sub_metrics here too, not only in the `all` depth sweep
        pdepth = _pipeline_depth()
        txn_per_s, n_conflicts, pstats = bench_tpu_pipelined(
            n_txns, n_batches, keyspace, pdepth)
        sub["tpu-pipelined"] = {"depth": pdepth,
                                "pipeline_stats": pstats}
        backend_name = backend
    elif backend == "tpu-packed":
        # single-backend packed run: the unpacked baseline and the h2d
        # transfer evidence ride sub_metrics here too, not only in the
        # `all` matrix — the comparison IS the mode
        txn_per_s, n_conflicts, packed_detail = bench_tpu_packed(
            n_txns, n_batches, keyspace)
        sub["tpu-packed"] = packed_detail
        backend_name = backend
    elif backend == "native-streamed":
        # single-backend native streamed run: the ABI ceiling evidence
        # rides sub_metrics here too, plus the object-API `native`
        # baseline at the same shape so the marshalling tax is a
        # measured delta, not an assertion
        txn_per_s, n_conflicts, native_detail = bench_native_streamed(
            n_txns, n_batches, keyspace)
        sub["native-streamed"] = native_detail
        nb_obj = min(n_batches, 25)
        tps_obj, nc_obj = bench_cpu("native", n_txns, nb_obj, keyspace)
        sub["native"] = {"txn_per_s": round(tps_obj, 1),
                         "batches": nb_obj, "conflicts": nc_obj,
                         "note": "object API: per-batch Python "
                                 "marshalling inside the timed region"}
        sub["native-streamed"]["speedup_vs_object_api"] = \
            round(txn_per_s / tps_obj, 2) if tps_obj else None
        backend_name = backend
    else:
        txn_per_s, n_conflicts = _run_backend(backend, n_txns, n_batches,
                                              keyspace)
        backend_name = backend

    print(json.dumps({
        "metric": "resolver_throughput",
        "value": round(txn_per_s, 1),
        "unit": "txn/s",
        "vs_baseline": round(txn_per_s / TARGET_TXN_PER_S, 4),
        "config": {
            "backend": backend_name, "batch_txns": n_txns,
            "batches": n_batches, "reads_per_txn": READS_PER_TXN,
            "writes_per_txn": 1, "keyspace": keyspace,
            "window_batches": WINDOW_BATCHES, "key_bytes": KEY_BYTES,
            "conflicts": n_conflicts,
            # which jax platform the device modes actually ran on —
            # "cpu" marks a tunnel-down round honestly in the artifact
            "platform": _jax_platform(),
        },
        "sub_metrics": sub,
    }))
    # piped stdout is block-buffered and jax's CPU runtime can abort
    # during interpreter teardown — flush so the record survives it
    sys.stdout.flush()


if __name__ == "__main__":
    sys.exit(main())
