"""Sim2-grade cluster chaos: scenario storms with deterministic seed
replay (server/chaos.py, ISSUE 7 / ROADMAP item 5).

What is pinned here, in order:

- **The storm matrix**: every named scenario (partition / swizzle /
  kill-mid-commit / machine power loss / disk corruption / coordinator
  loss / region failover) runs green under open-loop traffic, heals,
  quiesces inside the recovery bound, and passes `check_consistency` +
  shadow-validation cleanliness — AND replaying the same seed
  reproduces an identical chaos event schedule and an identical
  post-quiesce keyspace digest. Determinism is asserted, not assumed.
- **Kill-mid-commit atomicity**: a role death armed at each exact
  commit-pipeline station leaves every multi-key transaction
  commit-or-abort — never a partial write (ref: the recovery
  version's all-or-nothing contract over a commit's mutation set).
- **The corruption oracles**: DETECTED corruption (bad payload, intact
  CRC chain) surfaces as a recoverable role death; UNDETECTED
  corruption (payload rotted with the CRC recomputed) is caught by
  `check_consistency`'s replica sweep; torn writes at power loss
  recover through the CRC cut.
- **Triage ergonomics**: `quiet_database` timeouts diagnose which
  roles/counters never quiesced; the failure hook in conftest.py makes
  any red sim test replayable via `--seed`.
- **The shared chaos schema**: network/disk/kill injections AND PR 5's
  device-fault seams roll into one `status.cluster.chaos` document and
  one `fdbtpu_chaos_*` exporter family.
"""

import pytest

from foundationdb_tpu import flow
from foundationdb_tpu.client import run_transaction
from foundationdb_tpu.ops.fault_injection import g_device_faults
from foundationdb_tpu.rpc import SimNetwork
from foundationdb_tpu.server import SimCluster
from foundationdb_tpu.server.chaos import (SCENARIOS, KillMidCommit,
                                           arm_station, clear_stations,
                                           corrupt_record_payload,
                                           corrupt_value_bytes,
                                           get_scenario, wait_fully_recovered)
from foundationdb_tpu.server.consistency import (ConsistencyError,
                                                 check_consistency)
from foundationdb_tpu.server.workloads import ChaosStorm

#: per-scenario default seeds for the matrix (any seed must pass — the
#: nightly grid sweeps others; these are the deterministic tier-1 picks,
#: overridable with --seed for replay)
SCENARIO_SEEDS = {
    "partition_minority": 101,
    "swizzle_links": 102,
    "kill_mid_commit": 103,
    "machine_power_loss": 104,
    "disk_corruption_recovery": 105,
    "coordinator_loss_recovery_storm": 106,
    "region_failover": 107,
}


def run_storm(scenario: str, seed: int) -> dict:
    """One full ChaosStorm run in a fresh simulation (the repro unit
    the conftest failure hook points at)."""
    kwargs = dict(SCENARIOS[scenario].cluster_kwargs)
    c = SimCluster(seed=seed, **kwargs)
    try:
        dbs = [c.client(f"chaos{i}") for i in range(3)]
        storm = ChaosStorm(c, dbs, flow.g_random, scenario)
        return c.run(storm.run(), timeout_time=900)
    finally:
        c.shutdown()


# -- the storm matrix + seed replay --------------------------------------

@pytest.mark.parametrize("scenario", sorted(SCENARIOS))
def test_storm_matrix_replays_identically(scenario, sim_seed):
    seed = sim_seed(SCENARIO_SEEDS[scenario])
    first = run_storm(scenario, seed)

    # the storm went green: traffic flowed, the scenario fired, the
    # cluster healed inside the bound, and the oracle swept real rows
    assert first["storm"]["issued"] > 0, first["storm"]
    assert first["storm"]["completed"] > 0, first["storm"]
    assert first["chaos"]["scenarios"].get(scenario) == 1, first["chaos"]
    assert first["chaos"]["injected"].get("scenario") == 1, first["chaos"]
    assert len(first["events"]) >= 2, first["events"]
    assert first["consistency"]["shards"] > 0, first["consistency"]
    assert first["consistency"]["rows"] > 0, first["consistency"]
    assert first["recovery_seconds"] <= \
        flow.SERVER_KNOBS.chaos_recovery_bound

    # seed replay: identical fault schedule (kind, sim-time, detail —
    # the whole event log) and identical final keyspace digest
    second = run_storm(scenario, seed)
    assert second["events"] == first["events"], (
        scenario, seed, first["events"], second["events"])
    assert second["digest"] == first["digest"], (scenario, seed)


def test_unknown_scenario_rejected():
    with pytest.raises(ValueError, match="unknown chaos scenario"):
        get_scenario("does_not_exist")


# -- kill-mid-commit atomicity -------------------------------------------

@pytest.mark.parametrize("station,kind", KillMidCommit.STATION_VICTIMS)
def test_kill_mid_commit_atomicity(station, kind, sim_seed):
    """A role death at an exact commit station leaves the multi-key
    transaction all-or-nothing: committed => every key present; an
    abort => none; only an UNKNOWN commit outcome may legitimately go
    either way (but still never partially)."""
    seed = sim_seed(500 + list(KillMidCommit.STATION_VICTIMS).index(
        (station, kind)))
    c = SimCluster(seed=seed, durable=True, n_workers=6, n_logs=2,
                   n_storage=2, storage_replicas=2)
    # keys straddling the shard boundary (n_storage=2 splits at 0x80):
    # a partial write would leave the shards visibly disagreeing
    keys = (b"atomic/a", b"atomic/b", b"\xc0atomic/c")
    try:
        db = c.client("atomic")

        async def main():
            async def baseline(tr):
                tr.set(b"baseline", b"1")
            await run_transaction(db, baseline)

            armed = {}

            def on_station(_loc):
                try:
                    armed["victim"] = c.kill_role(kind)
                except KeyError:
                    armed["victim"] = None
            # armed AFTER boot so recruitment-time pipeline traffic
            # cannot trip it before the transaction under test
            arm_station(station, on_station)

            tr = db.create_transaction()
            phase, err = "grv", None
            try:
                await tr.get_read_version()
                phase = "commit"
                for k in keys:
                    tr.set(k, b"present")
                await tr.commit()
                phase = "committed"
            except flow.FdbError as e:
                err = e.name
            clear_stations()
            await wait_fully_recovered(c)

            async def read_all(tr2):
                return [await tr2.get(k) for k in keys]
            vals = await run_transaction(db, read_all, max_retries=300)
            present = [v is not None for v in vals]
            if phase == "committed":
                assert all(present), (station, kind, vals, armed)
            elif phase == "grv" or err == "not_committed":
                # the commit never reached the pipeline / was rejected
                assert not any(present), (station, kind, err, vals, armed)
            else:
                # an unknown outcome may land either way — but never
                # partially (the recovery version takes the whole
                # mutation set or none of it)
                assert all(present) or not any(present), (
                    station, kind, err, vals, armed)
            await check_consistency(c)
            return phase, err

        c.run(main(), timeout_time=600)
    finally:
        clear_stations()
        c.shutdown()


# -- corruption oracles --------------------------------------------------

def _committed_rows(c, db, n=30, prefix=b"c"):
    async def main():
        for i in range(n):
            async def w(tr, i=i):
                tr.set(prefix + b"%02d" % i, b"v%02d" % i)
            await run_transaction(db, w)
        await c.quiet_database()
    c.run(main(), timeout_time=300)


def test_detected_corruption_is_recoverable_role_death(sim_seed):
    """Payload bytes rotted under an intact CRC chain: the recovery
    scan raises checksum_failed, the worker drops the store (a counted,
    recoverable role death) and replication heals — the data survives
    on the peer replica and check_consistency stays clean."""
    c = SimCluster(seed=sim_seed(600), durable=True, n_workers=7,
                   n_logs=2, n_storage=2, storage_replicas=2)
    try:
        db = c.client("corr")
        _committed_rows(c, db)

        async def main():
            corrupted_machine = None
            for w in c.workers.values():
                disk = c.net.disks.get(w.process.machine)
                if disk is None:
                    continue
                for fname in sorted(disk.files):
                    if not fname.startswith("storage-"):
                        continue
                    f = disk.files[fname]
                    if corrupt_record_payload(f, flow.g_random):
                        corrupted_machine = w.process.machine
                        break
                if corrupted_machine:
                    break
            assert corrupted_machine, "no corruptible storage record"
            assert c.net.chaos_counters.get("disk_corruption"), \
                c.net.chaos_counters

            before = c.net.chaos_counters.get("corrupt_store_lost", 0)
            c.kill_machine(corrupted_machine)
            for _ in range(400):
                if c.net.chaos_counters.get(
                        "corrupt_store_lost", 0) > before:
                    break
                await flow.delay(0.25)
            assert c.net.chaos_counters.get(
                "corrupt_store_lost", 0) > before, c.net.chaos_counters
            await wait_fully_recovered(c)

            async def r(tr):
                return await tr.get(b"c00")
            assert await run_transaction(db, r, max_retries=300) == b"v00"
            await check_consistency(c)

        c.run(main(), timeout_time=600)
    finally:
        c.shutdown()


def test_undetected_corruption_caught_by_check_consistency(sim_seed):
    """Bit rot the disk format cannot see (payload flipped AND the
    record CRC recomputed): nothing dies, recovery succeeds — and the
    replica sweep is the net that catches it."""
    marker = b"UNDETECTABLE-ROT-MARKER"
    c = SimCluster(seed=sim_seed(601), durable=True, n_workers=7,
                   n_logs=2, n_storage=2, storage_replicas=2)
    try:
        db = c.client("rot")

        async def seed_marker(tr):
            tr.set(b"rot/target", marker)
        c.run(run_transaction(db, seed_marker), timeout_time=60)
        _committed_rows(c, db, n=10, prefix=b"rot/fill")

        async def main():
            rotted_machine = None
            for w in c.workers.values():
                disk = c.net.disks.get(w.process.machine)
                if disk is None:
                    continue
                for fname in sorted(disk.files):
                    if not fname.startswith("storage-"):
                        continue
                    if corrupt_value_bytes(disk.files[fname], marker,
                                           flow.g_random):
                        rotted_machine = w.process.machine
                        break
                if rotted_machine:
                    break
            assert rotted_machine, "marker not found in any durable store"
            # power-cycle so the storage server re-reads the rotted
            # bytes (a live server serves from memory)
            c.kill_machine(rotted_machine)
            await flow.delay(flow.SERVER_KNOBS.sim_reboot_delay + 1.0)
            await wait_fully_recovered(c)
            with pytest.raises(ConsistencyError):
                await check_consistency(c)

        c.run(main(), timeout_time=600)
    finally:
        c.shutdown()


def test_torn_write_recovers_through_crc_cut():
    """With SIM_TORN_WRITE_PROB=1 the write in flight at power loss is
    TORN — only a prefix of it lands. Recovery's checksum scan cuts the
    torn tail (tail damage, NOT mid-log corruption: no checksum_failed,
    no store drop) and every synced record survives."""
    from foundationdb_tpu.flow import coverage
    from foundationdb_tpu.server.diskqueue import DiskQueue
    flow.set_seed(9)
    s = flow.Scheduler(virtual=True)
    flow.set_scheduler(s)
    saved = {n: getattr(flow.SERVER_KNOBS, n) for n in
             ("sim_torn_write_prob", "sim_power_loss_drop_prob")}
    flow.SERVER_KNOBS.set("sim_torn_write_prob", 1.0)
    flow.SERVER_KNOBS.set("sim_power_loss_drop_prob", 0.0)
    try:
        net = SimNetwork(s, flow.g_random)
        disk = net.disk("m")
        before_torn = coverage.hits("disk.torn_write")

        async def main():
            dq = DiskQueue(disk, "torn")
            await dq.recover()
            synced = [b"rec%02d" % i * 8 for i in range(5)]
            for payload in synced:
                await dq.push(payload)
            await dq.commit()
            await dq.push(b"UNSYNCED-IN-FLIGHT" * 16)
            disk.power_loss(flow.g_random)
            assert coverage.hits("disk.torn_write") > before_torn
            assert net.chaos_counters.get("torn_write") == 1
            dq2 = DiskQueue(disk, "torn")
            recovered = await dq2.recover()
            # the torn record is gone, every synced one survives, and
            # nothing was (mis)classified as mid-log corruption
            assert recovered == synced, recovered
            return True

        task = s.spawn(main())
        assert s.run(until=task, timeout_time=60)
    finally:
        for n, v in saved.items():
            flow.SERVER_KNOBS.set(n, v)
        flow.set_scheduler(None)


def test_raw_sector_rot_never_silently_regresses(sim_seed):
    """`SimDisk.corrupt_file` flips CHAOS_CORRUPT_BYTES seeded bytes
    with no format awareness: a payload hit is detected at recovery
    (store drop), a header hit is CRC-cut like a torn tail and healed
    from replication. Either way the cluster must end consistent with
    the committed data intact — raw rot may cost a store, never a
    row."""
    c = SimCluster(seed=sim_seed(607), durable=True, n_workers=7,
                   n_logs=2, n_storage=2, storage_replicas=2)
    try:
        db = c.client("rawrot")
        _committed_rows(c, db)

        async def main():
            machine, fname = next(
                (w.process.machine, f)
                for w in c.workers.values()
                for f in sorted(c.net.disks.get(w.process.machine,
                                                _EMPTY_DISK).files)
                if f.startswith("storage-") and f.endswith(".dq0"))
            flips = c.net.disks[machine].corrupt_file(fname, flow.g_random)
            assert flips, "no durable bytes to rot"
            assert c.net.chaos_counters.get("disk_corruption"), \
                c.net.chaos_counters
            c.kill_machine(machine)
            await flow.delay(flow.SERVER_KNOBS.sim_reboot_delay + 1.0)
            await wait_fully_recovered(c)

            async def r(tr):
                return await tr.get(b"c00")
            assert await run_transaction(db, r, max_retries=300) == b"v00"
            await check_consistency(c)

        c.run(main(), timeout_time=600)
    finally:
        c.shutdown()


class _EMPTY_DISK:
    files = ()


# -- triage ergonomics ---------------------------------------------------

def test_quiet_database_timeout_diagnoses_stuck_roles(sim_seed):
    """A quiesce that cannot finish says WHY: the error names the dead
    replica / undrained counters instead of a bare timed_out."""
    c = SimCluster(seed=sim_seed(603), durable=True, auto_reboot=False,
                   n_workers=6, n_logs=2, n_storage=2,
                   storage_replicas=2)
    try:
        db = c.client("diag")

        async def main():
            async def w(tr):
                tr.set(b"k", b"v")
            await run_transaction(db, w)
            c.kill_role("storage")
            with pytest.raises(flow.FdbError) as ei:
                await c.quiet_database(max_wait=4.0)
            assert ei.value.name == "timed_out"
            msg = str(ei.value)
            assert "quiet_database timed out" in msg, msg
            # the diagnosis names what was stuck, not just that it was
            assert "storage" in msg or "tlog" in msg or \
                "recovery_state" in msg, msg

        c.run(main(), timeout_time=300)
    finally:
        c.shutdown()


def test_sim_seed_is_recorded_for_replay_hook(sim_seed):
    """The conftest failure hook prints cluster.last_sim_seed — pin
    that every SimCluster records it."""
    from foundationdb_tpu.server import cluster as cluster_mod
    c = SimCluster(seed=sim_seed(604))
    try:
        assert cluster_mod.last_sim_seed == sim_seed(604)
    finally:
        c.shutdown()


# -- chaos primitives, directed ------------------------------------------

def test_partition_unreachability_ends_epoch_and_heals(sim_seed):
    """A partitioned (alive!) tlog machine must end the epoch through
    the CC's unreachability watchdog — the reference's failure
    detection is network-based — and rejoin after heal."""
    c = SimCluster(seed=sim_seed(605), durable=True, n_workers=6,
                   n_logs=2, n_storage=2)
    try:
        db = c.client("part")

        async def main():
            from foundationdb_tpu.flow import coverage
            from foundationdb_tpu.server.dbinfo import FULLY_RECOVERED

            async def w(tr):
                tr.set(b"k", b"v")
            await run_transaction(db, w)
            e0 = c.cc.dbinfo.get().epoch
            machine = next(wi.process.machine
                           for wi in c.workers.values()
                           for r in wi.roles if r.startswith("tlog-e"))
            pid = c.net.partition([machine])
            for _ in range(240):
                info = c.cc.dbinfo.get()
                if info.epoch > e0 and \
                        info.recovery_state == FULLY_RECOVERED:
                    break
                await flow.delay(0.25)
            info = c.cc.dbinfo.get()
            assert info.epoch > e0, "partition never ended the epoch"
            assert coverage.hits("cc.epoch_unreachable") > 0
            # the partitioned processes never died — only unreachable
            assert all(p.alive for p in c.net.processes.values()
                       if p.machine == machine)
            c.net.heal(pid)
            await c.quiet_database()
            await check_consistency(c, quiesce=False)

        c.run(main(), timeout_time=600)
    finally:
        c.shutdown()


def _raw_net():
    s = flow.Scheduler(virtual=True)
    flow.set_scheduler(s)
    return s, SimNetwork(s, flow.g_random)


def test_clog_send_delays_inflight_reply():
    """A send clog installed AFTER the request went out still delays
    the answer: reply latency is drawn at reply time."""
    from foundationdb_tpu.rpc import RequestStream
    from foundationdb_tpu.server.types import MutationRef, SET_VALUE
    flow.set_seed(7)
    s, net = _raw_net()
    try:
        server = net.new_process("server", machine="ms")
        client = net.new_process("client", machine="mc")
        stream = RequestStream(server)

        async def serve():
            req, reply = await stream.pop()
            # the request is already here; clog the RESPONDER's sends
            # before answering — the in-flight reply must honor it
            net.clog_send("ms", 5.0)
            reply.send(req)

        async def main():
            t = flow.spawn(serve())
            t0 = s.now()
            await stream.ref().get_reply(
                MutationRef(SET_VALUE, b"k", b"v"), client)
            await t
            return s.now() - t0

        task = s.spawn(main())
        elapsed = s.run(until=task, timeout_time=60)
        assert elapsed >= 5.0, elapsed
        assert net.chaos_counters.get("clog_send") == 1
    finally:
        flow.set_scheduler(None)


def test_swizzle_duplicates_oneway_datagrams():
    """Inside a swizzle window one-way datagrams may deliver twice,
    each copy drawing its own scrambled latency."""
    from foundationdb_tpu.rpc import RequestStream
    from foundationdb_tpu.server.types import MutationRef, SET_VALUE
    flow.set_seed(8)
    s, net = _raw_net()
    flow.SERVER_KNOBS.set("chaos_swizzle_dup_prob", 1.0)
    try:
        server = net.new_process("server", machine="ms")
        client = net.new_process("client", machine="mc")
        stream = RequestStream(server)
        net.swizzle("mc", "ms", 30.0)

        async def main():
            stream.ref().send(MutationRef(SET_VALUE, b"k", b"v"), client)
            got = []
            for _ in range(2):
                req, _reply = await stream.pop()
                got.append(req)
            return got

        task = s.spawn(main())
        got = s.run(until=task, timeout_time=60)
        assert len(got) == 2 and got[0] == got[1]
        assert net.messages_duplicated == 1
        assert net.chaos_counters.get("swizzle") == 1
    finally:
        flow.SERVER_KNOBS.set("chaos_swizzle_dup_prob", 0.25)
        flow.set_scheduler(None)


# -- the shared chaos schema ---------------------------------------------

def test_device_faults_share_chaos_schema(sim_seed):
    """PR 5's device-fault injector and the new scenario storms report
    through ONE status/exporter schema: a seam fault shows up as
    `device_<point>` beside the network/disk kinds."""
    from foundationdb_tpu.tools.exporter import (parse_prometheus,
                                                 render_prometheus)
    c = SimCluster(seed=sim_seed(606), durable=True,
                   conflict_backend="tpu", n_workers=5)
    try:
        db = c.client("dev")
        before = dict(g_device_faults.injected)

        async def main():
            g_device_faults.schedule("submit")
            for i in range(3):
                async def w(tr, i=i):
                    tr.set(b"d%d" % i, b"v")
                await run_transaction(db, w)
            return await db.get_status()

        status = c.run(main(), timeout_time=300)
        assert g_device_faults.injected["submit"] > before.get(
            "submit", 0), g_device_faults.injected
        chaos = status["cluster"]["chaos"]
        assert chaos["injected"].get("device_submit", 0) >= \
            g_device_faults.injected["submit"], chaos

        samples = parse_prometheus(render_prometheus(status))
        kinds = {l["kind"]: v for n, l, v in samples
                 if n == "fdbtpu_chaos_injected"}
        assert kinds.get("device_submit", 0) >= 1, kinds
    finally:
        c.shutdown()


def test_storm_chaos_counters_reach_status_and_exporter(sim_seed):
    """After a storm, status.cluster.chaos and the fdbtpu_chaos_*
    exporter family answer 'did it actually fire' without trace greps,
    and the cli renders a chaos section."""
    from foundationdb_tpu.tools.cli import Cli
    from foundationdb_tpu.tools.exporter import (parse_prometheus,
                                                 render_prometheus)
    seed = sim_seed(SCENARIO_SEEDS["partition_minority"])
    kwargs = dict(SCENARIOS["partition_minority"].cluster_kwargs)
    c = SimCluster(seed=seed, **kwargs)
    try:
        cli = Cli.for_cluster(c)
        dbs = [c.client(f"chaos{i}") for i in range(3)]
        storm = ChaosStorm(c, dbs, flow.g_random, "partition_minority")

        async def main():
            rep = await storm.run()
            status = await dbs[0].get_status()
            return rep, status

        rep, status = c.run(main(), timeout_time=900)
        chaos = status["cluster"]["chaos"]
        assert chaos["scenarios"].get("partition_minority") == 1, chaos
        assert chaos["injected"].get("partition") == 1, chaos
        assert chaos["injected"].get("heal") == 1, chaos
        assert chaos["messages_dropped"] > 0, chaos
        assert chaos["events"] >= len(rep["events"]), chaos

        samples = parse_prometheus(render_prometheus(status))
        names = {n for n, _l, _v in samples}
        for need in ("fdbtpu_chaos_injected", "fdbtpu_chaos_scenario_runs",
                     "fdbtpu_chaos_events",
                     "fdbtpu_chaos_messages_dropped"):
            assert need in names, f"exporter missing {need}"
        runs = {l["scenario"]: v for n, l, v in samples
                if n == "fdbtpu_chaos_scenario_runs"}
        assert runs.get("partition_minority") == 1, runs

        details = cli.execute("status details")
        assert "Chaos (injected faults):" in details, details
        assert "scenario partition_minority" in details, details
    finally:
        c.shutdown()
