"""Conflict attribution (report_conflicting_keys): directed semantics +
randomized cross-backend parity.

The acceptance criterion for the feature: every backend — Python
baseline, brute-force model, native C++, TPU interval kernel, TPU point
kernel, sharded TPU — attributes the SAME read ranges as the cause of
the SAME verdicts on the same batch (ref: fdbclient
report_conflicting_keys + the SkipList self-check pattern,
fdbserver/SkipList.cpp:1412-1551)."""

import importlib.util
import random

import pytest

from foundationdb_tpu.models import (
    COMMITTED,
    CONFLICT,
    TOO_OLD,
    BruteForceConflictSet,
    PyConflictSet,
    ResolverTransaction,
    native_available,
)

MWTLV = 5_000_000


def txn(snapshot, reads=(), writes=()):
    return ResolverTransaction(snapshot, tuple(reads), tuple(writes))


def backends():
    out = [("python", PyConflictSet), ("brute", BruteForceConflictSet)]
    if native_available():
        from foundationdb_tpu.models import NativeConflictSet
        out.append(("native", NativeConflictSet))
    if importlib.util.find_spec("jax") is not None:
        from foundationdb_tpu.models.tpu_resolver import TpuConflictSet
        out.append(("tpu", TpuConflictSet))
    return out


@pytest.fixture(params=[name for name, _ in backends()])
def cs_factory(request):
    return dict(backends())[request.param]


# ---------------------------------------------------------------- directed --
def test_external_conflict_attributes_only_the_hit_range(cs_factory):
    cs = cs_factory()
    cs.resolve([txn(0, writes=[(b"k", b"k\x00")])], 100, 0)
    v, a = cs.resolve_with_attribution(
        [txn(50, reads=[(b"a", b"b"), (b"k", b"k\x00")],
             writes=[(b"x", b"y")])], 200, 0)
    assert v == [CONFLICT]
    assert a[0] == (1,)


def test_intra_batch_attribution(cs_factory):
    cs = cs_factory()
    v, a = cs.resolve_with_attribution(
        [txn(0, writes=[(b"k", b"k\x00")]),
         txn(0, reads=[(b"a", b"b"), (b"k", b"k\x00")],
             writes=[(b"z", b"z\x00")])], 100, 0)
    assert v == [COMMITTED, CONFLICT]
    assert a == [(), (1,)]


def test_union_of_external_and_intra_causes(cs_factory):
    """A txn conflicting BOTH against history (range 0) and an earlier
    txn's write (range 1) attributes both — the order-insensitive union
    every backend computes identically."""
    cs = cs_factory()
    cs.resolve([txn(0, writes=[(b"h", b"h\x00")])], 100, 0)
    v, a = cs.resolve_with_attribution(
        [txn(150, writes=[(b"w", b"w\x00")]),
         txn(50, reads=[(b"h", b"h\x00"), (b"w", b"w\x00")])], 200, 0)
    assert v == [COMMITTED, CONFLICT]
    assert a == [(), (0, 1)]


def test_conflicted_txn_writes_not_attributed_to_later_reads(cs_factory):
    """A conflicted txn's writes never become causes (ref:
    checkIntraBatchConflicts skipping conflicted txns' writes)."""
    cs = cs_factory()
    cs.resolve([txn(0, writes=[(b"a", b"a\x00")])], 100, 0)
    v, a = cs.resolve_with_attribution(
        [txn(50, reads=[(b"a", b"a\x00")], writes=[(b"b", b"b\x00")]),
         txn(150, reads=[(b"b", b"b\x00")])], 200, 0)
    assert v == [CONFLICT, COMMITTED]
    assert a == [(0,), ()]


def test_too_old_attributes_nothing(cs_factory):
    cs = cs_factory()
    cs.resolve([txn(0, writes=[(b"a", b"b")])], 10_000_000,
               10_000_000 - MWTLV)
    v, a = cs.resolve_with_attribution(
        [txn(4_000_000, reads=[(b"q", b"r")])],
        11_000_000, 11_000_000 - MWTLV)
    assert v == [TOO_OLD]
    assert a == [()]


def test_indices_are_original_positions(cs_factory):
    """Empty/inverted ranges keep their slot: attribution indexes the
    caller's read_ranges tuple, not the marshalled survivors."""
    cs = cs_factory()
    cs.resolve([txn(0, writes=[(b"k", b"k\x00")])], 100, 0)
    v, a = cs.resolve_with_attribution(
        [txn(50, reads=[(b"m", b"m"), (b"k", b"k\x00")],
             writes=[(b"x", b"y")])], 200, 0)
    assert v == [CONFLICT]
    assert a[0] == (1,)


def test_committed_txns_attribute_nothing(cs_factory):
    cs = cs_factory()
    v, a = cs.resolve_with_attribution(
        [txn(0, reads=[(b"a", b"b")], writes=[(b"c", b"c\x00")])], 100, 0)
    assert v == [COMMITTED]
    assert a == [()]


# -------------------------------------------------------------- randomized --
def _random_range(rng, space, klen):
    if rng.random() < 0.5:
        k = bytes(rng.randrange(space) for _ in range(klen))
        return (k, k + b"\x00")
    a = bytes(rng.randrange(space) for _ in range(klen))
    b = bytes(rng.randrange(space) for _ in range(klen))
    if a > b:
        a, b = b, a
    return (a, b + b"\x00") if a == b else (a, b)


@pytest.mark.parametrize("seed", [21, 22, 23])
def test_randomized_attribution_parity(seed):
    """Tiny keyspace maximizes collisions; verdicts AND attributed
    index sets must agree with the brute-force model everywhere."""
    rng = random.Random(seed)
    impls = {name: cls() for name, cls in backends()}
    version = 0
    for batch_idx in range(50):
        version += rng.randrange(1, 300_000)
        oldest = max(0, version - MWTLV)
        batch = [
            txn(max(0, version - rng.randrange(0, int(1.2 * MWTLV))),
                [_random_range(rng, 5, 2)
                 for _ in range(rng.randrange(0, 4))],
                [_random_range(rng, 5, 2)
                 for _ in range(rng.randrange(0, 4))])
            for _ in range(rng.randrange(1, 10))]
        results = {name: cs.resolve_with_attribution(batch, version, oldest)
                   for name, cs in impls.items()}
        vref, aref = results["brute"]
        for name, (v, a) in results.items():
            assert v == vref, (
                f"{name} verdicts diverged at batch {batch_idx}: "
                f"{v} != {vref}\n{batch}")
            assert [tuple(x) for x in a] == [tuple(x) for x in aref], (
                f"{name} attribution diverged at batch {batch_idx}: "
                f"{a} != {aref}\n{batch}")


def test_point_backend_attribution_parity():
    from foundationdb_tpu.models.point_resolver import PointConflictSet
    rng = random.Random(31)
    brute, pt = BruteForceConflictSet(), PointConflictSet()
    version = 0

    def rpoint():
        k = bytes([rng.randrange(6)])
        return (k, k + b"\x00")

    for batch_idx in range(40):
        version += rng.randrange(1, 300_000)
        oldest = max(0, version - MWTLV)
        batch = [txn(max(0, version - rng.randrange(0, MWTLV)),
                     [rpoint() for _ in range(rng.randrange(0, 3))],
                     [rpoint() for _ in range(rng.randrange(0, 3))])
                 for _ in range(rng.randrange(1, 8))]
        v1, a1 = brute.resolve_with_attribution(batch, version, oldest)
        v2, a2 = pt.resolve_with_attribution(batch, version, oldest)
        assert v1 == v2, (batch_idx, v1, v2, batch)
        assert [tuple(x) for x in a1] == [tuple(x) for x in a2], (
            batch_idx, a1, a2, batch)


def test_sharded_backend_attribution_parity():
    """Clipped per-shard attribution psum-unions back to the global
    answer — bit-identical to the single-shard backends."""
    from foundationdb_tpu.parallel.sharded_resolver import \
        ShardedTpuConflictSet
    rng = random.Random(41)
    brute, sh = BruteForceConflictSet(), ShardedTpuConflictSet(n_shards=4)
    version = 0

    def rrange():
        a = bytes(rng.randrange(250) for _ in range(2))
        b = bytes(rng.randrange(250) for _ in range(2))
        if a > b:
            a, b = b, a
        return (a, b + b"\x00") if a == b else (a, b)

    for batch_idx in range(20):
        version += rng.randrange(1, 300_000)
        oldest = max(0, version - MWTLV)
        batch = [txn(max(0, version - rng.randrange(0, MWTLV)),
                     [rrange() for _ in range(rng.randrange(0, 3))],
                     [rrange() for _ in range(rng.randrange(0, 3))])
                 for _ in range(rng.randrange(1, 6))]
        v1, a1 = brute.resolve_with_attribution(batch, version, oldest)
        v2, a2 = sh.resolve_with_attribution(batch, version, oldest)
        assert v1 == v2, (batch_idx, v1, v2, batch)
        assert [tuple(x) for x in a1] == [tuple(x) for x in a2], (
            batch_idx, a1, a2, batch)


# -------------------------------------------------------------- hot spots --
def test_hot_spot_table_decay_and_topk():
    from foundationdb_tpu import flow
    from foundationdb_tpu.server.resolver_role import ConflictHotSpots

    sched = flow.Scheduler()
    flow.set_scheduler(sched)
    try:
        async def main():
            hs = ConflictHotSpots(half_life=1.0, max_entries=3)
            for _ in range(4):
                hs.record(b"a", b"a\x00")
            hs.record(b"b", b"b\x00")
            top = hs.top(10)
            assert top[0]["begin"] == b"a".hex()
            assert top[0]["total"] == 4
            # decay: after 2 half-lives the score quarters, totals stay
            s0 = top[0]["score"]
            await flow.delay(2.0)
            top2 = hs.top(10)
            assert top2[0]["total"] == 4
            assert top2[0]["score"] == pytest.approx(s0 / 4, rel=0.01)
            # bounded: the coldest entry is evicted past max_entries
            hs.record(b"c", b"c\x00")
            hs.record(b"d", b"d\x00")
            assert len(hs.top(10)) == 3
            return True

        task = flow.spawn(main())
        assert sched.run(until=task, timeout_time=60)
    finally:
        flow.set_scheduler(None)
