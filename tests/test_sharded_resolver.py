"""Key-range sharded resolver over the virtual 8-device mesh: verdicts
must be bit-identical to the single-shard TPU backend and to the
brute-force model (the multi-resolver parity criterion; ref combine rule
MasterProxyServer.actor.cpp:585-592, here made exact via ICI collectives)."""

import random

import jax
import pytest

from foundationdb_tpu.models import (
    BruteForceConflictSet,
    ResolverTransaction,
)
from foundationdb_tpu.models.tpu_resolver import TpuConflictSet
from foundationdb_tpu.parallel import ShardedTpuConflictSet, default_split_keys

MWTLV = 5_000_000


def txn(snapshot, reads=(), writes=()):
    return ResolverTransaction(snapshot, tuple(reads), tuple(writes))


def test_mesh_has_eight_devices():
    assert len(jax.devices()) == 8


def test_default_split_keys():
    ks = default_split_keys(4)
    assert ks == [b"\x40", b"\x80", b"\xc0"]
    assert ks == sorted(ks)


def test_cross_shard_range_conflict():
    """A single range spanning every shard boundary must behave as one."""
    sh = ShardedTpuConflictSet(capacity=1024)
    assert sh._n_shards == 8
    sh.resolve([txn(0, writes=[(b"\x01", b"\xfe")])], 100, 0)
    got = sh.resolve(
        [txn(50, reads=[(b"\x70", b"\x90")]),   # crosses the 0x80 split
         txn(50, reads=[(b"\x00", b"\x01")]),   # before the write
         txn(100, reads=[(b"\x01", b"\xfe")])], 200, 0)
    assert got == [0, 2, 2]


def test_intra_batch_across_shards():
    """Writer on one shard, reader on another, in the same batch: the
    psum'd fixpoint must see the dependency."""
    sh = ShardedTpuConflictSet(capacity=1024)
    got = sh.resolve(
        [txn(0, writes=[(b"\x10", b"\x11")]),                   # shard 0
         txn(0, reads=[(b"\x10", b"\x11")],
             writes=[(b"\xf0", b"\xf1")]),                      # reads s0, writes s7
         txn(0, reads=[(b"\xf0", b"\xf1")])], 100, 0)           # reads s7
    # t1 conflicts on t0's write; t1's own write is therefore dropped,
    # so t2 commits — requires cross-shard knowledge of t1's conflict.
    assert got == [2, 0, 2]


@pytest.mark.parametrize("seed", [31, 32, 33])
def test_randomized_sharded_parity(seed):
    rng = random.Random(seed)
    sh = ShardedTpuConflictSet(capacity=1024)
    single = TpuConflictSet(capacity=1024)
    brute = BruteForceConflictSet()

    def rrange():
        a = bytes([rng.randrange(256), rng.randrange(8)])
        b = bytes([rng.randrange(256), rng.randrange(8)])
        if a > b:
            a, b = b, a
        if a == b:
            b = a + b"\x00"
        return a, b

    version = 0
    for bi in range(20):
        version += rng.randrange(1, 300_000)
        oldest = max(0, version - MWTLV)
        batch = [txn(max(0, version - rng.randrange(0, int(1.2 * MWTLV))),
                     [rrange() for _ in range(rng.randrange(0, 4))],
                     [rrange() for _ in range(rng.randrange(0, 4))])
                 for _ in range(rng.randrange(1, 16))]
        vs = sh.resolve(batch, version, oldest)
        v1 = single.resolve(batch, version, oldest)
        vb = brute.resolve(batch, version, oldest)
        assert vs == v1 == vb, (bi, vs, v1, vb)


def test_sharded_growth():
    sh = ShardedTpuConflictSet(capacity=1024)
    v = 0
    for i in range(30):
        v += 10
        writes = [(bytes([j % 256]) + b"%04d" % (i * 50 + j),
                   bytes([j % 256]) + b"%04d\x00" % (i * 50 + j))
                  for j in range(50)]
        sh.resolve([txn(v - 10, writes=writes)], v, 0)
    # all shards share one capacity; shard 0 only grows if its own load did,
    # so just assert correctness after sustained load:
    got = sh.resolve([txn(0, reads=[(b"\x00", b"\xff")])], v + 1, 0)
    assert got == [0]
