"""Client-side conflict-window cache (ISSUE 8 satellite,
server/scheduler.py ConflictWindowCache + client/transaction.py early
abort): staleness expiry, survival across on_error, the GRV piggyback
plumbing end to end, and the indistinguishability contract — a
cache-driven early abort must look exactly like a resolver abort to
retry loops, reporting, and profiling.

Ref: *Early Detection for MVCC Conflicts in Hyperledger Fabric*
(PAPERS.md) — abort doomed transactions before commit submission.
"""

from foundationdb_tpu import flow
from foundationdb_tpu.client import run_transaction
from foundationdb_tpu.server import SimCluster
from foundationdb_tpu.server.scheduler import (ConflictWindowCache,
                                               client_window_counters)

HOT = (b"hot", b"hot\x00")


def _env():
    flow.set_seed(0)
    s = flow.Scheduler()
    flow.set_scheduler(s)
    flow.reset_server_knobs(randomize=False)
    return s


def _teardown():
    flow.reset_server_knobs(randomize=False)
    flow.set_scheduler(None)


# -- unit: the cache itself --------------------------------------------

def test_window_staleness_expiry():
    _env()
    try:
        flow.SERVER_KNOBS.set("conflict_window_ttl", 2.0)
        cache = ConflictWindowCache()
        cache.update([(b"hot", b"hot\x00", 500)], now=10.0)
        # fresh + snapshot below the window version -> doomed
        assert cache.doomed([HOT], snapshot=100, now=10.5) == (HOT,)
        # snapshot at/after the window's last conflict -> clean
        assert cache.doomed([HOT], snapshot=500, now=10.5) == ()
        # non-overlapping read -> clean
        assert cache.doomed([(b"cold", b"cold\x00")], 100, 10.5) == ()
        # past the TTL the window expires — and is physically dropped
        assert cache.doomed([HOT], snapshot=100, now=12.5) == ()
        assert cache.live_rows(12.5) == ()
        # a later update repopulates (wholesale replacement)
        cache.update([(b"hot", b"hot\x00", 900)], now=20.0)
        assert cache.doomed([HOT], snapshot=100, now=20.1) == (HOT,)
    finally:
        _teardown()


def test_window_ttl_knob_is_live_read():
    _env()
    try:
        flow.SERVER_KNOBS.set("conflict_window_ttl", 0.1)
        cache = ConflictWindowCache()
        cache.update([(b"hot", b"hot\x00", 500)], now=0.0)
        assert cache.doomed([HOT], 100, 0.05) == (HOT,)
        assert cache.doomed([HOT], 100, 0.2) == ()
    finally:
        _teardown()


# -- end to end: GRV piggyback + early abort ---------------------------

def _heat_and_refresh(db):
    """Produce real conflicts on b"hot" so the resolver attributes
    them, wait for the CC push, then refresh the client cache via a
    fresh GRV."""
    async def inner():
        async def seed(tr):
            tr.set(b"hot", b"0")
        await run_transaction(db, seed)
        for _ in range(6):
            tr = db.create_transaction()
            await tr.get(b"hot")
            tr.set(b"mine", b"v")

            async def bump(t2):
                t2.set(b"hot", b"x")
            await run_transaction(db, bump)
            try:
                await tr.commit()
            except flow.FdbError as e:
                assert e.name == "not_committed", e.name
        await flow.delay(0.3)        # CC hot push lands at the proxy
        probe = db.create_transaction()
        await probe.get_read_version()   # windows ride THIS reply
    return inner


def test_windows_ride_grv_and_early_abort_end_to_end():
    """Full stack: conflicts heat the table, the CC pushes windows,
    they ride a GRV reply into the Database cache, and a stale-
    snapshot transaction overlapping the window aborts CLIENT-side —
    the proxy's conflict counter does not move."""
    c = SimCluster(seed=921, durable=True)
    flow.SERVER_KNOBS.set("client_conflict_windows", 1)
    flow.SERVER_KNOBS.set("sched_hot_push_interval", 0.05)
    flow.SERVER_KNOBS.set("conflict_window_score_min", 0.1)
    try:
        db = c.client()

        async def main():
            # the victim takes its snapshot FIRST
            victim = db.create_transaction()
            victim.set_option("report_conflicting_keys")
            await victim.get_read_version()
            await _heat_and_refresh(db)()
            assert db._conflict_cache is not None, \
                "windows never reached the client cache"
            assert db._conflict_cache._rows, "cache empty after refresh"
            before = (await db.get_status())["cluster"]["proxies"][0][
                "counters"].get("transactions_conflicted", 0)
            ca_before = client_window_counters().get("early_aborts", 0)
            await victim.get(b"hot")
            victim.set(b"w", b"v")
            try:
                await victim.commit()
                raise AssertionError("expected early abort")
            except flow.FdbError as e:
                assert e.name == "not_committed", e.name
            # reporting surface matches the resolver-abort shape
            assert victim.get_conflicting_ranges() == (HOT,), \
                victim.get_conflicting_ranges()
            after = (await db.get_status())["cluster"]["proxies"][0][
                "counters"].get("transactions_conflicted", 0)
            ca_after = client_window_counters().get("early_aborts", 0)
            # the abort was client-side: no proxy/resolver involvement
            assert after == before, (before, after)
            assert ca_after == ca_before + 1, (ca_before, ca_after)
            status = await db.get_status()
            return status

        status = c.run(main(), timeout_time=300)
        client = status["cluster"]["conflict_scheduling"]["client"]
        assert client.get("early_aborts", 0) >= 1, client
        assert client.get("windows_cached", 0) >= 1, client
    finally:
        flow.reset_server_knobs(randomize=False)
        c.shutdown()


def test_cache_survives_on_error_and_retry_succeeds():
    """The cache is Database-scoped: on_error's reset cannot drop it;
    the RETRY attempt (fresh snapshot, newer than the window) then
    commits — the retry-loop experience is identical to recovering
    from a resolver conflict."""
    c = SimCluster(seed=922, durable=True)
    flow.SERVER_KNOBS.set("client_conflict_windows", 1)
    flow.SERVER_KNOBS.set("sched_hot_push_interval", 0.05)
    flow.SERVER_KNOBS.set("conflict_window_score_min", 0.1)
    try:
        db = c.client()

        async def main():
            victim = db.create_transaction()
            await victim.get_read_version()
            await _heat_and_refresh(db)()
            cache = db._conflict_cache
            assert cache is not None and cache._rows
            await victim.get(b"hot")
            victim.set(b"w", b"v")
            try:
                await victim.commit()
                raise AssertionError("expected early abort")
            except flow.FdbError as e:
                await victim.on_error(e)     # retryable, like any abort
            # the DB cache survived the transaction reset
            assert db._conflict_cache is cache
            assert cache._rows
            # the retry's fresh snapshot postdates the window: commits
            await victim.get(b"hot")
            victim.set(b"w", b"v2")
            await victim.commit()

            async def read(tr):
                return await tr.get(b"w")
            assert await run_transaction(db, read) == b"v2"
            return True

        assert c.run(main(), timeout_time=300)
    finally:
        flow.reset_server_knobs(randomize=False)
        c.shutdown()


def test_early_abort_indistinguishable_to_profiling():
    """A sampled transaction whose commit early-aborts must record the
    SAME conflicted CommitEvent a resolver abort records — the
    profiling pipeline cannot tell the two apart."""
    from foundationdb_tpu.client.profiling import CommitEvent
    c = SimCluster(seed=923, durable=True)
    flow.SERVER_KNOBS.set("client_conflict_windows", 1)
    flow.SERVER_KNOBS.set("sched_hot_push_interval", 0.05)
    flow.SERVER_KNOBS.set("conflict_window_score_min", 0.1)
    try:
        db = c.client()

        async def main():
            victim = db.create_transaction()
            victim.set_option("transaction_logging_enable", "early")
            victim.set_option("report_conflicting_keys")
            await victim.get_read_version()
            await _heat_and_refresh(db)()
            await victim.get(b"hot")
            victim.set(b"w", b"v")
            try:
                await victim.commit()
                raise AssertionError("expected early abort")
            except flow.FdbError as e:
                assert e.name == "not_committed", e.name
            commits = [ev for ev in victim._profile.events
                       if isinstance(ev, CommitEvent)]
            assert commits, victim._profile.events
            ev = commits[-1]
            assert ev.verdict == "conflicted", ev
            assert ev.version == 0, ev
            assert ev.conflicting_ranges == (HOT,), ev
            return True

        assert c.run(main(), timeout_time=300)
    finally:
        flow.reset_server_knobs(randomize=False)
        c.shutdown()


def test_windows_off_by_default_reply_is_bare():
    """With CLIENT_CONFLICT_WINDOWS off (the default), GRV replies
    carry no windows, the cache is never created, and commit pays
    nothing."""
    c = SimCluster(seed=924, durable=True)
    try:
        db = c.client()

        async def main():
            async def body(tr):
                tr.set(b"k", b"v")
            await run_transaction(db, body)
            assert db._conflict_cache is None
            return True

        assert c.run(main(), timeout_time=120)
    finally:
        c.shutdown()
