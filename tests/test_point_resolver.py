"""Point-mode TPU conflict-set backend: bit-exact parity vs the CPU
baselines on randomized point workloads, plus point-specific edges
(duplicate keys in a batch, same-txn read+write of one key, init_version
baseline, GC pruning, growth, version rebasing).

Acceptance mirrors the interval backend's (ref self-check pattern:
fdbserver/SkipList.cpp:1412-1551 skipListTest vs SlowConflictSet).
"""

import random

import pytest

from foundationdb_tpu.models import (
    COMMITTED,
    CONFLICT,
    TOO_OLD,
    BruteForceConflictSet,
    PyConflictSet,
    ResolverTransaction,
    create_conflict_set,
)
from foundationdb_tpu.models.point_resolver import PointConflictSet

MWTLV = 5_000_000


def txn(snapshot, reads=(), writes=()):
    return ResolverTransaction(snapshot, tuple(reads), tuple(writes))


def pt(k: bytes):
    return (k, k + b"\x00")


def random_point_batch(rng, n_txns, keyspace, version, spread,
                       max_reads=3, max_writes=2):
    batch = []
    for _ in range(n_txns):
        reads = [pt(b"%07d" % rng.randrange(keyspace))
                 for _ in range(rng.randrange(max_reads + 1))]
        writes = [pt(b"%07d" % rng.randrange(keyspace))
                  for _ in range(rng.randrange(max_writes + 1))]
        snap = version - rng.randrange(spread)
        batch.append(txn(snap, reads, writes))
    return batch


def test_factory_builds_point_backend():
    cs = create_conflict_set("tpu-point")
    assert isinstance(cs, PointConflictSet)
    assert cs.resolve([txn(0, writes=[pt(b"a")])], 100, 0) == [COMMITTED]


def test_rejects_non_point_ranges():
    cs = PointConflictSet()
    with pytest.raises(ValueError):
        cs.resolve([txn(0, reads=[(b"a", b"c")])], 10, 0)
    with pytest.raises(ValueError):
        cs.resolve([txn(0, writes=[(b"a" * 9, b"a" * 9 + b"\x00")])], 10, 0)


def test_point_basics_and_intra_batch_order():
    cs = PointConflictSet()
    # write k at v=100
    assert cs.resolve([txn(0, writes=[pt(b"k")])], 100, 0) == [COMMITTED]
    # read k at old snapshot conflicts; at new snapshot commits
    out = cs.resolve([txn(50, reads=[pt(b"k")]),
                      txn(100, reads=[pt(b"k")])], 200, 0)
    assert out == [CONFLICT, COMMITTED]
    # intra-batch: earlier writer aborts later reader; own write is fine
    out = cs.resolve([txn(200, reads=[pt(b"x")], writes=[pt(b"x")]),
                      txn(200, reads=[pt(b"x")]),
                      txn(200, reads=[pt(b"y")], writes=[pt(b"y")])], 300, 0)
    assert out == [COMMITTED, CONFLICT, COMMITTED]
    # chain: t0 writes a; t1 reads a (conflict) so t1's write of b is dead;
    # t2 reads b and must NOT conflict with the dead write
    out = cs.resolve([txn(300, writes=[pt(b"a")]),
                      txn(300, reads=[pt(b"a")], writes=[pt(b"b")]),
                      txn(300, reads=[pt(b"b")])], 400, 0)
    assert out == [COMMITTED, CONFLICT, COMMITTED]


def test_too_old_and_init_version():
    cs = PointConflictSet(init_version=500)
    brute = BruteForceConflictSet(init_version=500)
    batch = [txn(400, reads=[pt(b"q")]),  # below init baseline -> conflict
             txn(600, reads=[pt(b"q")]),  # above -> committed
             txn(400, writes=[pt(b"w")])]  # write-only: baseline irrelevant
    for impl in (cs, brute):
        assert impl.resolve(batch, 1000, 0) == [CONFLICT, COMMITTED, COMMITTED]
    # advance the window first; then a pre-window snapshot with reads
    # is TOO_OLD (the new_oldest of a batch applies to LATER batches)
    batch2 = [txn(100, reads=[pt(b"q")]), txn(100, writes=[pt(b"r")])]
    for impl in (cs, brute):
        impl.resolve([], 1500, 900)
        assert impl.resolve(batch2, 2000, 950) == [TOO_OLD, COMMITTED]


@pytest.mark.parametrize("baseline", ["brute", "python"])
@pytest.mark.parametrize("seed", [1, 2, 3, 4])
def test_randomized_point_parity(baseline, seed):
    rng = random.Random(seed)
    cs = PointConflictSet()
    ref = (BruteForceConflictSet() if baseline == "brute"
           else PyConflictSet())
    version = 0
    for batch_i in range(25):
        version += rng.randrange(1, 50_000)
        oldest = max(0, version - rng.randrange(20_000, 120_000))
        batch = random_point_batch(
            rng, n_txns=rng.randrange(0, 24), keyspace=200,
            version=version, spread=100_000)
        got = cs.resolve(batch, version, oldest)
        want = ref.resolve(batch, version, oldest)
        assert got == want, f"batch {batch_i} diverged: {got} vs {want}"


def test_duplicate_keys_same_batch_and_txn():
    cs = PointConflictSet()
    brute = BruteForceConflictSet()
    # several txns write the same key; duplicates within one txn too
    batch = [txn(0, writes=[pt(b"d"), pt(b"d")]),
             txn(0, reads=[pt(b"d"), pt(b"d")]),
             txn(0, writes=[pt(b"d")])]
    for impl in (cs, brute):
        assert impl.resolve(batch, 10, 0) == [COMMITTED, CONFLICT, COMMITTED]
    # history now holds duplicate rows for d; newest must win
    batch2 = [txn(5, reads=[pt(b"d")]), txn(10, reads=[pt(b"d")])]
    for impl in (cs, brute):
        assert impl.resolve(batch2, 20, 0) == [CONFLICT, COMMITTED]


def test_gc_prunes_and_growth_preserves():
    cs = PointConflictSet(capacity=1024)
    v = 0
    for i in range(40):
        v += 10
        writes = [pt(b"g%05d" % (i * 40 + j)) for j in range(40)]
        assert cs.resolve([txn(v - 10, writes=writes)], v, 0) == [COMMITTED]
    assert cs._cap > 1024
    rng = random.Random(11)
    for _ in range(20):
        k = b"g%05d" % rng.randrange(40 * 40)
        assert cs.resolve([txn(0, reads=[pt(k)])], v + 1, 0) == [CONFLICT]
    # advance the window past everything: entries must be pruned away
    v2 = v + MWTLV + 1000
    cs.resolve([], v2, v2 - 10)
    cs.resolve([txn(v2 - 5, writes=[pt(b"zz")])], v2 + 1, v2 - 10)
    cs._sync_count()
    assert cs._count_hint <= 4  # only the fresh write (+ slack) remains


def test_rebase_at_large_versions_point():
    cs = PointConflictSet()
    brute = BruteForceConflictSet()
    v = 0
    rng = random.Random(3)
    for _ in range(12):
        v += 300_000_000  # crosses the 2^30 rebase threshold repeatedly
        oldest = v - MWTLV
        batch = [txn(v - rng.randrange(0, MWTLV // 2),
                     reads=[pt(b"a")] if rng.random() < 0.5 else [],
                     writes=[pt(b"b")] if rng.random() < 0.5 else [])
                 for _ in range(5)]
        assert cs.resolve(batch, v, oldest) == brute.resolve(batch, v, oldest)
    assert cs._base > 0


def test_recovery_style_version_jump_point():
    cs = PointConflictSet()
    brute = BruteForceConflictSet()
    for impl in (cs, brute):
        impl.resolve([txn(0, writes=[pt(b"a")])], 100, 0)
    v = (1 << 31) + 500
    old = v - MWTLV
    batch = [txn(v - 10, reads=[pt(b"a")]), txn(50, reads=[pt(b"a")]),
             txn(v - 10, writes=[pt(b"c")])]
    assert cs.resolve(batch, v, old) == brute.resolve(batch, v, old)
    # post-jump: the jumped write must be visible at its true version
    batch2 = [txn(v - 1, reads=[pt(b"c")]), txn(v + 1, reads=[pt(b"c")])]
    assert cs.resolve(batch2, v + 10, old) == \
        brute.resolve(batch2, v + 10, old)


def test_searchsorted_i32_full_array_exact():
    """Counts must reach len(table) for queries above every element
    (regression: the branchless loop alone caps at len-1, silently
    emptying the LAST txn's read segment in pad-free kernel drives)."""
    import numpy as np
    from foundationdb_tpu.ops.keys import searchsorted_i32
    import jax.numpy as jnp

    rng = np.random.default_rng(5)
    for n in (1, 2, 8, 64):
        tab = np.sort(rng.integers(0, 50, n).astype(np.int32))
        q = np.arange(-1, 52, dtype=np.int32)
        for side in ("left", "right"):
            got = np.asarray(searchsorted_i32(jnp.asarray(tab),
                                              jnp.asarray(q), side=side))
            want = np.searchsorted(tab, q, side=side)
            assert (got == want).all(), (n, side, tab, got, want)


def test_kernel_direct_no_pad_last_txn_checked():
    """Drive the kernel exactly like the bench: nr == n_txns with every
    slot valid (no pad row). The LAST txn's read must still be
    conflict-checked (regression for the bench-shape segment bug)."""
    import numpy as np
    import jax.numpy as jnp
    from foundationdb_tpu.ops.keys import encode_keys
    from foundationdb_tpu.ops.point_kernel import make_point_resolve_fn

    n = 16
    fn = make_point_resolve_fn(64, n, n, n, 2)
    sk = np.full((64, 3), 0xFFFFFFFF, np.uint32)
    sv = np.full((64,), -(1 << 30), np.int32)
    keys = encode_keys([b"k%02d" % i for i in range(n)], 8)
    rt = np.arange(n, dtype=np.int32)
    valid = np.ones(n, bool)
    # batch 1: txn i writes key i
    sk2, sv2, _cnt, conflict, _hit = fn(
        jnp.asarray(sk), jnp.asarray(sv),
        jnp.zeros(n, jnp.int32), jnp.zeros(n, bool),
        jnp.asarray(np.zeros((n, 3), np.uint32)),  # reads: all-zero keys
        jnp.asarray(rt), jnp.asarray(np.zeros(n, bool)),
        jnp.asarray(keys), jnp.asarray(rt), jnp.asarray(valid),
        jnp.int32(100), jnp.int32(0), jnp.int32(0))
    assert not np.asarray(conflict).any()
    # batch 2: txn i reads key i at a pre-write snapshot -> ALL conflict,
    # including txn n-1 (the one a pad-free segment table would skip)
    _sk3, _sv3, _c, conflict, read_hit = fn(
        sk2, sv2, jnp.full(n, 50, jnp.int32), jnp.zeros(n, bool),
        jnp.asarray(keys), jnp.asarray(rt), jnp.asarray(valid),
        jnp.asarray(np.zeros((n, 3), np.uint32)), jnp.asarray(rt),
        jnp.asarray(np.zeros(n, bool)),
        jnp.int32(200), jnp.int32(0), jnp.int32(0))
    assert np.asarray(conflict).all(), np.asarray(conflict)
    # every read slot is the cause of its txn's conflict
    assert np.asarray(read_hit).all(), np.asarray(read_hit)


def test_large_batch_parity():
    """One big batch through the padded shape buckets (512 txns)."""
    rng = random.Random(99)
    cs = PointConflictSet()
    brute = BruteForceConflictSet()
    version = 1000
    for _ in range(3):
        version += 40_000
        batch = random_point_batch(rng, 512, keyspace=600, version=version,
                                   spread=60_000)
        assert cs.resolve(batch, version, version - 80_000) == \
            brute.resolve(batch, version, version - 80_000)


def test_point_resolve_arrays_parity():
    """The pre-encoded array path (pipeline/bench fast path) yields
    verdicts bit-identical to the object path and the CPU baseline on
    random point workloads (round-2 VERDICT weak #9)."""
    import numpy as np

    from foundationdb_tpu.ops.keys import encode_keys

    rng = random.Random(991)
    keyspace, spread = 300, 400_000
    obj_cs = PyConflictSet()
    arr_cs = PointConflictSet(key_bytes=8)
    version = 0
    for _round in range(12):
        version += 250_000
        batch = random_point_batch(rng, 24, keyspace, version, spread)
        oldest = max(0, version - MWTLV)
        want = obj_cs.resolve(batch, version, oldest)

        # flatten to the encoded-array shape
        snaps, has_reads, rk, rt, wk, wt = [], [], [], [], [], []
        for t, tr in enumerate(batch):
            snaps.append(tr.read_snapshot)
            has_reads.append(bool(tr.read_ranges))
            for b, _e in tr.read_ranges:
                rk.append(b)
                rt.append(t)
            for b, _e in tr.write_ranges:
                wk.append(b)
                wt.append(t)
        rb = encode_keys(rk, 8)[:len(rk)]
        wb = encode_keys(wk, 8)[:len(wk)]
        conflict, too_old = arr_cs.resolve_arrays(
            np.asarray(snaps, np.int64), np.asarray(has_reads),
            rb, None, np.asarray(rt, np.int32),
            wb, None, np.asarray(wt, np.int32),
            commit_version=version, new_oldest_version=oldest)
        got = arr_cs.finalize_verdicts(conflict, too_old)
        assert got == want, (_round, got, want)


def test_point_resolve_arrays_rejects_wrong_width():
    import numpy as np

    cs = PointConflictSet(key_bytes=8)
    bad = np.zeros((1, 6), np.uint32)  # 20-byte-bucket row
    with pytest.raises(ValueError):
        cs.resolve_arrays(np.zeros(1, np.int64), np.ones(1, bool),
                          bad, None, np.zeros(1, np.int32),
                          bad, None, np.zeros(1, np.int32),
                          commit_version=100, new_oldest_version=0)
