"""MultiVersion client: a client built against protocol N connects to
an N+1 cluster via the shim.

Ref: fdbclient/MultiVersionTransaction.h:351 — MultiVersionApi dlopens
versioned libfdb_c copies, discovers the cluster's protocol, and
routes through the matching one, so applications survive cluster
upgrades. The contract under test: protocol discovery works with NO
compatible library (the probe), a mismatched library alone cannot
connect, and the shim picks the right copy and runs real transactions
through it.
"""

import os
import subprocess

import pytest

from test_c_binding import GatewayedCluster

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_CDIR = os.path.join(_REPO, "bindings", "c")


def _build_versioned(tag: str) -> str:
    subprocess.run(["make", "-C", _CDIR, "versioned", f"PROTOCOL={tag}"],
                   check=True, capture_output=True)
    return os.path.join(_CDIR, "build", f"libfdb_tpu_c_{tag}.so")


def test_multiversion_client_connects_across_protocols():
    lib01 = _build_versioned("fdbtpu01")
    lib02 = _build_versioned("fdbtpu02")

    from foundationdb_tpu.bindings.c_client import (CClientError,
                                                    load_library_at)
    from foundationdb_tpu.bindings.multiversion import (
        MultiVersionClient, probe_cluster_protocol)

    # the libraries report their build-time tags
    assert load_library_at(lib01).fdb_tpu_get_protocol() == b"fdbtpu01"
    assert load_library_at(lib02).fdb_tpu_get_protocol() == b"fdbtpu02"

    # an "upgraded" cluster speaking protocol 02
    with GatewayedCluster(gateway_protocol=b"fdbtpu02", seed=41) as gc:
        # discovery needs no compatible library at all
        assert probe_cluster_protocol("127.0.0.1", gc.port) == b"fdbtpu02"

        # the protocol-01 library alone CANNOT connect (a handshake
        # rejection looks like connection death, so the client's
        # bounded connect-retry runs out rather than erroring instantly)
        from foundationdb_tpu.bindings.c_client import CDatabase
        with pytest.raises(CClientError):
            CDatabase("127.0.0.1", gc.port,
                      lib=load_library_at(lib01), connect_timeout=1.0)

        # the shim holds both and selects 02
        mv = MultiVersionClient([lib01, lib02])
        assert mv.protocols() == [b"fdbtpu01", b"fdbtpu02"]
        db = mv.open("127.0.0.1", gc.port)
        try:
            tr = db.create_transaction()
            tr.set(b"mv-key", b"via-02")
            v = tr.commit()
            assert v > 0
            tr.reset()
            assert tr.get(b"mv-key") == b"via-02"
            tr.destroy()
        finally:
            db.close()

        # no matching library -> the incompatible-client error
        mv01 = MultiVersionClient([lib01])
        with pytest.raises(RuntimeError, match="no client library"):
            mv01.open("127.0.0.1", gc.port)


def test_default_protocol_unchanged():
    """The default build still speaks fdbtpu01 — existing peers are
    unaffected by the versioning seam."""
    with GatewayedCluster(seed=42) as gc:
        from foundationdb_tpu.bindings.c_client import CDatabase
        from foundationdb_tpu.bindings.multiversion import \
            probe_cluster_protocol
        assert probe_cluster_protocol("127.0.0.1", gc.port) == b"fdbtpu01"
        db = CDatabase("127.0.0.1", gc.port)
        try:
            tr = db.create_transaction()
            tr.set(b"plain", b"ok")
            assert tr.commit() > 0
            tr.destroy()
        finally:
            db.close()
