"""VersionedMap unit tests — especially intra-version mutation ordering.

Ref: fdbserver/storageserver.actor.cpp:1664 (applyMutation applies a
version's mutations strictly in order) and fdbclient/VersionedMap.h.
"""

from foundationdb_tpu.server.storage import VersionedMap
from foundationdb_tpu.server.types import (CLEAR_RANGE, MutationRef,
                                           SET_VALUE)


def _set(vm, v, k, val):
    vm.apply(v, MutationRef(SET_VALUE, k, val))


def _clear(vm, v, b, e):
    vm.apply(v, MutationRef(CLEAR_RANGE, b, e))


def test_set_then_clear_same_version_hides_key():
    vm = VersionedMap()
    _set(vm, 5, b"a", b"1")
    _clear(vm, 5, b"a", b"b")
    assert vm.get(b"a", 5) is None
    assert vm.get(b"a", 10) is None


def test_clear_then_set_same_version_keeps_key():
    vm = VersionedMap()
    _clear(vm, 5, b"a", b"z")
    _set(vm, 5, b"a", b"1")
    assert vm.get(b"a", 5) == b"1"
    assert vm.get(b"a", 10) == b"1"


def test_set_clear_set_same_version():
    vm = VersionedMap()
    _set(vm, 5, b"k", b"old")
    _clear(vm, 5, b"a", b"z")
    _set(vm, 5, b"k", b"new")
    assert vm.get(b"k", 5) == b"new"
    # another key in the cleared range stays hidden
    _set(vm, 4, b"m", b"x")  # applied earlier in a lower version
    assert vm.get(b"m", 5) is None
    assert vm.get(b"m", 4) == b"x"


def test_clear_hides_older_version_set():
    vm = VersionedMap()
    _set(vm, 3, b"a", b"1")
    _clear(vm, 5, b"a", b"b")
    assert vm.get(b"a", 3) == b"1"
    assert vm.get(b"a", 4) == b"1"
    assert vm.get(b"a", 5) is None
    _set(vm, 7, b"a", b"2")
    assert vm.get(b"a", 7) == b"2"


def test_get_range_respects_same_version_clear():
    vm = VersionedMap()
    _set(vm, 2, b"a", b"1")
    _set(vm, 2, b"b", b"2")
    _set(vm, 4, b"c", b"3")
    _clear(vm, 4, b"a", b"c")  # clears a,b but not c (set earlier at v4)
    out = vm.get_range(b"", b"\xff", 4, 100)
    assert out == [(b"c", b"3")]
    out = vm.get_range(b"", b"\xff", 3, 100)
    assert out == [(b"a", b"1"), (b"b", b"2")]


def test_forget_drops_window_prefix():
    vm = VersionedMap()
    _set(vm, 2, b"a", b"1")
    _set(vm, 5, b"a", b"2")
    _clear(vm, 3, b"b", b"c")
    vm.forget(3)
    assert vm.get(b"a", 5) == b"2"
    assert not any(c[0] <= 3 for c in vm._clears)
