"""VersionedMap unit tests — especially intra-version mutation ordering.

Ref: fdbserver/storageserver.actor.cpp:1664 (applyMutation applies a
version's mutations strictly in order) and fdbclient/VersionedMap.h.
"""

from foundationdb_tpu.server.storage import VersionedMap
from foundationdb_tpu.server.types import (CLEAR_RANGE, MutationRef,
                                           SET_VALUE)


def _set(vm, v, k, val):
    vm.apply(v, MutationRef(SET_VALUE, k, val))


def _clear(vm, v, b, e):
    vm.apply(v, MutationRef(CLEAR_RANGE, b, e))


def test_set_then_clear_same_version_hides_key():
    vm = VersionedMap()
    _set(vm, 5, b"a", b"1")
    _clear(vm, 5, b"a", b"b")
    assert vm.get(b"a", 5) is None
    assert vm.get(b"a", 10) is None


def test_clear_then_set_same_version_keeps_key():
    vm = VersionedMap()
    _clear(vm, 5, b"a", b"z")
    _set(vm, 5, b"a", b"1")
    assert vm.get(b"a", 5) == b"1"
    assert vm.get(b"a", 10) == b"1"


def test_set_clear_set_same_version():
    vm = VersionedMap()
    _set(vm, 5, b"k", b"old")
    _clear(vm, 5, b"a", b"z")
    _set(vm, 5, b"k", b"new")
    assert vm.get(b"k", 5) == b"new"
    # another key in the cleared range stays hidden
    _set(vm, 4, b"m", b"x")  # applied earlier in a lower version
    assert vm.get(b"m", 5) is None
    assert vm.get(b"m", 4) == b"x"


def test_clear_hides_older_version_set():
    vm = VersionedMap()
    _set(vm, 3, b"a", b"1")
    _clear(vm, 5, b"a", b"b")
    assert vm.get(b"a", 3) == b"1"
    assert vm.get(b"a", 4) == b"1"
    assert vm.get(b"a", 5) is None
    _set(vm, 7, b"a", b"2")
    assert vm.get(b"a", 7) == b"2"


def test_get_range_respects_same_version_clear():
    vm = VersionedMap()
    _set(vm, 2, b"a", b"1")
    _set(vm, 2, b"b", b"2")
    _set(vm, 4, b"c", b"3")
    _clear(vm, 4, b"a", b"c")  # clears a,b but not c (set earlier at v4)
    out = vm.get_range(b"", b"\xff", 4, 100)
    assert out == [(b"c", b"3")]
    out = vm.get_range(b"", b"\xff", 3, 100)
    assert out == [(b"a", b"1"), (b"b", b"2")]


def test_forget_drops_window_prefix():
    vm = VersionedMap()
    _set(vm, 2, b"a", b"1")
    _set(vm, 5, b"a", b"2")
    _clear(vm, 3, b"b", b"c")
    vm.forget(3)
    assert vm.get(b"a", 5) == b"2"
    assert not any(c[0] <= 3 for c in vm._clears)


class _CountingKV:
    """Base-engine wrapper counting get_range rows served (the unit of
    scan work a storage read costs)."""

    def __init__(self, inner):
        self.inner = inner
        self.rows = 0

    def get(self, key):
        return self.inner.get(key)

    def get_range(self, begin, end, limit=1 << 30, reverse=False):
        out = self.inner.get_range(begin, end, limit=limit, reverse=reverse)
        self.rows += len(out)
        return out


def test_scalability_bounded_work_at_100k_keys():
    """Selectors, limited range reads, and gets on a 100k-key base must
    not enumerate the keyspace (round-2 VERDICT weak #5 regression)."""
    from foundationdb_tpu.server.kvstore import EphemeralKeyValueStore
    from foundationdb_tpu.server.types import KeySelector

    base = EphemeralKeyValueStore()
    for i in range(100_000):
        base.set(b"k%06d" % i, b"v")
    counting = _CountingKV(base)
    vm = VersionedMap(base=counting)
    # window activity: some sets and stamped clears
    for i in range(50):
        _set(vm, 10 + i, b"k%06d" % (i * 1000), b"w")
        _clear(vm, 10 + i, b"k%06d" % (i * 2000 + 500),
               b"k%06d" % (i * 2000 + 510))

    counting.rows = 0
    # point get: no base range scan at all
    assert vm.get(b"k050000", 100) == b"v"
    assert counting.rows == 0

    # limited range read: rows served bounded by ~limit + chunk
    got = vm.get_range(b"k000100", b"k099999", 100, 10)
    assert len(got) == 10
    assert counting.rows <= 200, counting.rows

    # selector with small offset: bounded walk, not a shard enumeration
    counting.rows = 0
    k, leftover = vm.resolve_selector(KeySelector(b"k050000", False, 5), 100)
    assert leftover == 0 and k == b"k050004"
    assert counting.rows <= 200, counting.rows

    counting.rows = 0
    k, leftover = vm.resolve_selector(KeySelector(b"k050000", False, -3), 100)
    assert leftover == 0 and k == b"k049996"
    assert counting.rows <= 200, counting.rows

    # many stamped clears stay cheap per get (indexed, not scanned)
    counting.rows = 0
    for i in range(100):
        vm.get(b"k%06d" % (i * 7), 100)
    assert counting.rows == 0
