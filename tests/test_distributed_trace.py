"""Cross-process trace propagation pins (ISSUE 16).

Four contracts: (1) with TRACE_PROPAGATION off (the default) the TCP
wire bytes are BYTE-IDENTICAL to the pre-feature framing — a request
carrying a debug id still rides a plain K_REQUEST frame whose payload
is exactly `wire.to_bytes(request)`; (2) with the knob armed, a span
chain survives a hop between two REAL OS processes and
tools/tracemerge.py reassembles the parent->child tree with the
process identities attached; (3) tracemerge's NTP-style offset
estimator recovers a deliberately skewed process clock within bound
from the WireHop timestamp quads alone; (4) merging the SAME seeded
in-sim run twice yields bit-identical report and folded output — the
merge adds no nondeterminism of its own.
"""

import json
import os
import subprocess
import sys

import pytest

from foundationdb_tpu import flow
from foundationdb_tpu.flow import trace as trace_mod
from foundationdb_tpu.tools import tracemerge


@pytest.fixture
def wall_loop():
    """A wall-clock scheduler for real-socket tests, with the ambient
    scheduler, knob set, and trace state restored afterwards."""
    prev_trace_path = trace_mod.g_trace.path
    flow.set_seed(0)
    s = flow.Scheduler(virtual=False)
    flow.set_scheduler(s)
    try:
        yield s
    finally:
        flow.SERVER_KNOBS.set("trace_propagation", 0)
        trace_mod.clear_process_identity()
        flow.reset_trace(prev_trace_path)
        flow.g_trace_batch.clear()
        flow.set_scheduler(None)


def test_knob_off_wire_bytes_identical(wall_loop, monkeypatch):
    """Off posture: a debug-id-carrying request with an OPEN client
    span — everything that would trigger propagation — still produces
    only kinds {REQUEST, REPLY} on the wire, and the request payload
    is exactly wire.to_bytes(request): no context envelope, no new
    fields, nothing for an old peer to choke on."""
    from foundationdb_tpu.rpc import tcp as tcp_mod
    from foundationdb_tpu.rpc import wire
    from foundationdb_tpu.rpc.tcp import TcpRequestStream, TcpTransport
    from foundationdb_tpu.server.types import StorageGetRequest

    assert flow.SERVER_KNOBS.trace_propagation == 0  # the default
    frames = []
    orig = tcp_mod._Conn.enqueue

    def spy(self, kind, req_id, token, payload):
        frames.append((kind, bytes(payload)))
        orig(self, kind, req_id, token, payload)

    monkeypatch.setattr(tcp_mod._Conn, "enqueue", spy)
    server = TcpTransport()
    client = TcpTransport()
    s = wall_loop
    try:
        stream = TcpRequestStream(server)
        server.start()
        client.start()
        req = StorageGetRequest(b"k", 7, debug_id=41)

        async def serve():
            while True:
                got, reply = await stream.pop()
                reply.send(got.key)

        async def main():
            flow.spawn(serve())
            ref = client.ref("127.0.0.1", server.port, stream.token)
            span = flow.g_trace_batch.begin_span(41, "NativeAPI.commit")
            try:
                assert await ref.get_reply(req) == b"k"
            finally:
                span.finish()
            return True

        t = s.spawn(main())
        assert s.run(until=t, timeout_time=30)
    finally:
        server.close()
        client.close()
    kinds = {k for k, _p in frames}
    assert kinds <= {tcp_mod.K_REQUEST, tcp_mod.K_REPLY}, frames
    req_payloads = [p for k, p in frames if k == tcp_mod.K_REQUEST]
    assert wire.to_bytes(req) in req_payloads, \
        "request bytes differ from the plain encoding"


def test_knob_on_traced_frames_round_trip(wall_loop, monkeypatch):
    """Armed posture: the same exchange rides the NEW frame kinds
    (TRACED request, TRACED reply), the server still sees the bare
    request, and the client logs a WireHop event with the four
    monotonically ordered per-side timestamps."""
    from foundationdb_tpu.rpc import tcp as tcp_mod
    from foundationdb_tpu.rpc.tcp import TcpRequestStream, TcpTransport
    from foundationdb_tpu.server.types import StorageGetRequest

    frames = []
    orig = tcp_mod._Conn.enqueue

    def spy(self, kind, req_id, token, payload):
        frames.append(kind)
        orig(self, kind, req_id, token, payload)

    monkeypatch.setattr(tcp_mod._Conn, "enqueue", spy)
    flow.SERVER_KNOBS.set("trace_propagation", 1)
    server = TcpTransport()
    client = TcpTransport()
    s = wall_loop
    try:
        stream = TcpRequestStream(server)
        server.start()
        client.start()

        async def serve():
            while True:
                got, reply = await stream.pop()
                assert got.key == b"k"   # bare request, not [ctx, req]
                reply.send(got.key)

        async def main():
            flow.spawn(serve())
            ref = client.ref("127.0.0.1", server.port, stream.token)
            span = flow.g_trace_batch.begin_span(43, "NativeAPI.commit")
            try:
                got = await ref.get_reply(
                    StorageGetRequest(b"k", 7, debug_id=43))
            finally:
                span.finish()
            assert got == b"k"
            return True

        t = s.spawn(main())
        assert s.run(until=t, timeout_time=30)
    finally:
        server.close()
        client.close()
    assert tcp_mod.K_TRACED in frames, frames
    assert tcp_mod.K_TRACED_REPLY in frames, frames
    hops = [e for e in trace_mod.g_trace.events
            if e.get("Type") == "WireHop"]
    assert hops, "traced exchange logged no WireHop"
    h = hops[-1]
    assert h["T0"] <= h["T3"] and h["T1"] <= h["T2"], h
    assert "43" in h["DebugIDs"], h


_CHILD_SRC = r"""
import json, os, sys
from foundationdb_tpu import flow
from foundationdb_tpu.flow import trace as trace_mod
from foundationdb_tpu.rpc.tcp import TcpRequestStream, TcpTransport
import foundationdb_tpu.server.types  # registers wire message types

run_dir = sys.argv[1]
flow.set_seed(1)
s = flow.Scheduler(virtual=False)
flow.set_scheduler(s)
flow.reset_trace(os.path.join(
    run_dir, "trace.childsrv.%d.jsonl" % os.getpid()))
trace_mod.set_process_identity("childsrv")
flow.SERVER_KNOBS.set("trace_propagation", 1)
transport = TcpTransport()
stream = TcpRequestStream(transport)

async def main():
    transport.start()
    print(json.dumps({"port": transport.port, "token": stream.token}),
          flush=True)
    while True:
        req, reply = await stream.pop()
        if req.key == b"quit":
            reply.send(b"bye")
            # let the writer thread flush the frame before the
            # transport (and process) goes away
            await flow.delay(0.2)
            return
        # no explicit parent anywhere: the remote parent the traced
        # frame carried must attach by itself
        span = flow.g_trace_batch.begin_span(req.debug_id, "ChildWork")
        await flow.delay(0.01)
        span.finish()
        reply.send(b"ok")

t = s.spawn(main())
s.run(until=t, timeout_time=60)
flow.g_trace_batch.dump()
flow.g_trace.flush()
transport.close()
"""


def test_span_tree_across_two_os_processes(wall_loop, tmp_path):
    """The tentpole shape in miniature: a client span opened in THIS
    process parents a server span opened in a real child OS process,
    and tracemerge reassembles the two per-process trace files into
    one tree with both process identities and a measured hop."""
    from foundationdb_tpu.rpc.tcp import TcpTransport
    from foundationdb_tpu.server.types import StorageGetRequest

    run_dir = str(tmp_path)
    child = subprocess.Popen(
        [sys.executable, "-c", _CHILD_SRC, run_dir],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    client = None
    try:
        hello = json.loads(child.stdout.readline())
        port, token = hello["port"], hello["token"]
        flow.reset_trace(os.path.join(
            run_dir, f"trace.parentcli.{os.getpid()}.jsonl"))
        trace_mod.set_process_identity("parentcli")
        flow.SERVER_KNOBS.set("trace_propagation", 1)
        client = TcpTransport()
        s = wall_loop

        async def main():
            client.start()
            ref = client.ref("127.0.0.1", port, token)
            span = flow.g_trace_batch.begin_span(5, "ParentWork")
            try:
                assert await ref.get_reply(
                    StorageGetRequest(b"k", 1, debug_id=5)) == b"ok"
            finally:
                span.finish()
            try:
                await ref.get_reply(StorageGetRequest(b"quit", 1))
            except flow.FdbError:
                pass   # the reply may race the child's clean exit
            return True

        t = s.spawn(main())
        assert s.run(until=t, timeout_time=60)
        assert child.wait(timeout=30) == 0, child.stderr.read()
        flow.g_trace_batch.dump()
        flow.g_trace.flush()
    finally:
        if client is not None:
            client.close()
        if child.poll() is None:
            child.kill()

    merged = tracemerge.merge(run_dir)
    me = f"parentcli:{os.getpid()}"
    them = f"childsrv:{child.pid}"
    assert set(merged["processes"]) == {me, them}
    assert merged["wire_hops"] >= 1
    chains = tracemerge.cross_process_chains(merged)
    assert len(chains) == 1, merged["chains"]
    rows = chains[0]["spans"]
    assert [(r["location"], r["process"], r["depth"]) for r in rows] \
        == [("ParentWork", me, 0), ("ChildWork", them, 1)]
    # the hop's offset estimate maps the child's clock into the
    # parent's: the nested child span must land INSIDE the parent span
    assert rows[0]["begin"] <= rows[1]["begin"] + 0.005
    assert rows[1]["end"] <= rows[0]["end"] + 0.005


def test_offset_estimator_recovers_skewed_clock(tmp_path):
    """A synthetic run where process b's clock runs 3.7s ahead: the
    estimator must recover the offset within a couple of milliseconds
    from the hop quads, and the merged tree must place b's span inside
    a's despite the raw timestamps saying otherwise."""
    skew = 3.7
    a_rows = [{"Type": "ProcessIdentity", "ID": "a:1"},
              {"Type": "Span", "Process": "a:1", "SpanID": 1,
               "ParentID": None, "ID": "d1", "Location": "ParentWork",
               "Begin": 10.0, "End": 10.03}]
    rng_jitter = [0.0, 0.001, -0.0015, 0.0005, -0.0005]
    for i, j in enumerate(rng_jitter):
        t0 = 10.0 + i * 0.004
        t3 = t0 + 0.012
        a_rows.append({"Type": "WireHop", "Client": "a:1",
                       "Server": "b:2", "DebugIDs": ["d1"],
                       "T0": t0, "T1": t0 + 0.005 + skew + j,
                       "T2": t0 + 0.007 + skew + j, "T3": t3})
    b_rows = [{"Type": "ProcessIdentity", "ID": "b:2"},
              {"Type": "Span", "Process": "b:2", "SpanID": 1,
               "ParentID": None, "RemoteParentProcess": "a:1",
               "RemoteParentID": 1, "ID": "d1",
               "Location": "ChildWork",
               "Begin": 10.005 + skew, "End": 10.007 + skew}]
    for name, rows in (("trace.a.1.jsonl", a_rows),
                       ("trace.b.2.jsonl", b_rows)):
        with open(tmp_path / name, "w") as fh:
            for r in rows:
                fh.write(json.dumps(r) + "\n")
    # a corrupt tail (kill -9 mid-write) must be skipped, not fatal
    with open(tmp_path / "trace.c.3.jsonl", "w") as fh:
        fh.write('{"Type": "Span", "Proc')

    merged = tracemerge.merge(str(tmp_path))
    assert merged["root_process"] == "a:1"
    assert merged["skipped_lines"] == 1
    assert abs(merged["clock_offsets_s"]["b:2"] - skew) < 0.002, \
        merged["clock_offsets_s"]
    [chain] = merged["chains"]
    assert chain["cross_process"]
    parent, childrow = chain["spans"]
    assert (parent["location"], childrow["location"]) == \
        ("ParentWork", "ChildWork")
    assert childrow["depth"] == 1
    # after offset correction the child sits inside the parent window
    assert parent["begin"] <= childrow["begin"] <= parent["end"]
    assert childrow["end"] <= parent["end"] + 0.005


def test_same_seed_sim_merge_bit_identical(tmp_path):
    """Two same-seed in-sim runs, each traced into its own run dir,
    must merge to bit-identical report and folded output (modulo the
    run-dir path on the report's first line): the whole
    trace->merge->render path is deterministic."""
    from foundationdb_tpu.server import SimCluster

    def run_once(run_dir: str):
        prev_trace_path = trace_mod.g_trace.path
        os.makedirs(run_dir, exist_ok=True)
        flow.reset_trace(os.path.join(run_dir, "trace.sim.0.jsonl"))
        cluster = SimCluster(seed=1234, n_resolvers=2, n_proxies=2)
        try:
            db = cluster.client("tm")

            async def main():
                for i in range(8):
                    tr = db.create_transaction()
                    tr.set_option("debug_transaction_identifier",
                                  f"tm-{i}")
                    tr.set(b"tm/%d" % i, b"v")
                    await tr.commit()
                flow.g_trace_batch.dump()
                return True

            assert cluster.run(main(), timeout_time=600)
        finally:
            cluster.shutdown()
            flow.reset_trace(prev_trace_path)
            flow.g_trace_batch.clear()
        merged = tracemerge.merge(run_dir)
        return (tracemerge.render_report(merged, top=10),
                tracemerge.render_folded(merged))

    rep1, fold1 = run_once(str(tmp_path / "r1"))
    rep2, fold2 = run_once(str(tmp_path / "r2"))
    strip = lambda rep: rep.split("\n", 1)[1]   # noqa: E731 — run dir line
    assert strip(rep1) == strip(rep2)
    assert fold1 == fold2
    assert "tm-0" in rep1 and "chains: 8" in rep1
    # single-process files without identity merge under one synthetic
    # process name, never a host-specific one
    assert tracemerge.LOCAL_PROCESS in fold1
