"""Multi-proxy, replicated-log, and sharded-storage cluster shapes.

Ref: §2.6 items 2 (data parallelism across proxies), 4 (tag-partitioned
log replication), 5 (storage shard parallelism);
MasterProxyServer.actor.cpp:1019 getLiveCommittedVersion (causal GRV),
TagPartitionedLogSystem.actor.cpp:404 (wait-all quorum push).
"""

import pytest

from foundationdb_tpu import flow
from foundationdb_tpu.client import run_transaction
from foundationdb_tpu.server import SimCluster
from foundationdb_tpu.server.types import KeySelector


def test_two_proxies_causal_reads():
    """A commit acked through one proxy is visible to a read whose GRV
    came from the other (getLiveCommittedVersion confirmation)."""
    c = SimCluster(seed=301, n_proxies=2)
    try:
        db = c.client()

        async def main():
            # many sequential read-own-write rounds: each round's GRV
            # lands on a random proxy, so both orders get exercised
            for i in range(30):
                async def wbody(tr, i=i):
                    tr.set(b"c", b"%d" % i)
                await run_transaction(db, wbody)
                tr = db.create_transaction()
                got = await tr.get(b"c")
                assert got == b"%d" % i, (i, got)
            return True

        assert c.run(main(), timeout_time=300)
    finally:
        c.shutdown()


def test_two_proxies_concurrent_increment():
    c = SimCluster(seed=307, n_proxies=2)
    try:
        dbs = [c.client(f"cl{i}") for i in range(4)]

        async def incr(db, n):
            for _ in range(n):
                async def body(tr):
                    cur = await tr.get(b"n")
                    tr.set(b"n", b"%d" % (int(cur or b"0") + 1))
                await run_transaction(db, body, max_retries=500)

        async def main():
            await flow.wait_for_all([flow.spawn(incr(d, 8)) for d in dbs])
            tr = dbs[0].create_transaction()
            assert await tr.get(b"n") == b"32"
            return True

        assert c.run(main(), timeout_time=600)
    finally:
        c.shutdown()


def test_replicated_logs_survive_one_tlog_loss():
    """n_logs=2: every ack required both logs, so after one dies the
    survivor has every acked commit; recovery rebuilds on it and
    nothing is lost (VERDICT r2 task 7)."""
    c = SimCluster(seed=311, durable=True, n_logs=2, n_workers=6)
    try:
        db = c.client()

        async def main():
            acked = {}
            for i in range(10):
                async def body(tr, i=i):
                    tr.set(b"r%02d" % i, b"v%d" % i)
                await run_transaction(db, body, max_retries=300)
                acked[b"r%02d" % i] = b"v%d" % i
                if i == 4:
                    c.kill_role("tlog")

            async def check(tr):
                got = dict(await tr.get_range(b"r", b"s"))
                assert got == acked, (len(got), len(acked))
            await run_transaction(db, check, max_retries=100)
            info = c.cc.dbinfo.get()
            assert len(info.logs.logs) == 2
            return True

        assert c.run(main(), timeout_time=600)
    finally:
        c.shutdown()


def test_sharded_storage_cross_shard_ops():
    """n_storage=3: writes land on their shards, range reads stitch
    across boundaries, clears span shards, selectors walk over
    boundaries (VERDICT r2 task 4)."""
    c = SimCluster(seed=313, n_storage=3)
    try:
        db = c.client()

        async def main():
            keys = [b"\x10a", b"\x55b", b"\x55c", b"\xaad", b"\xaae",
                    b"\xf0f"]
            async def setup(tr):
                for i, k in enumerate(keys):
                    tr.set(k, b"v%d" % i)
            await run_transaction(db, setup)

            tr = db.create_transaction()
            # cross-shard range read
            got = await tr.get_range(b"", b"\xff")
            assert got == [(k, b"v%d" % i) for i, k in enumerate(keys)]
            # reverse, limited
            got = await tr.get_range(b"", b"\xff", limit=3, reverse=True)
            assert [k for k, _ in got] == [b"\xf0f", b"\xaae", b"\xaad"]
            # selector walking across a shard boundary:
            # first_greater_or_equal(\x55b) + 2 present keys -> \xaad
            sel = KeySelector(b"\x55b", False, 3)
            assert await tr.get_key(sel) == b"\xaad"
            # backward across the boundary: last_less_than(\xaad) - 1
            sel = KeySelector(b"\xaad", False, -1)
            assert await tr.get_key(sel) == b"\x55b"

            # cross-shard clear
            async def clr(tr):
                tr.clear_range(b"\x40", b"\xc0")
            await run_transaction(db, clr)
            tr2 = db.create_transaction()
            got = await tr2.get_range(b"", b"\xff")
            assert got == [(b"\x10a", b"v0"), (b"\xf0f", b"v5")]
            return True

        assert c.run(main(), timeout_time=300)
    finally:
        c.shutdown()


def test_sharded_and_durable_with_kill():
    """Shards + replication + kills together: the full round-3 shape."""
    c = SimCluster(seed=317, durable=True, n_storage=2, n_logs=2,
                   n_resolvers=2, n_workers=6)
    try:
        db = c.client()

        async def main():
            acked = {}
            for i in range(12):
                k = bytes([i * 20]) + b"k%02d" % i
                async def body(tr, k=k, i=i):
                    tr.set(k, b"v%d" % i)
                await run_transaction(db, body, max_retries=300)
                acked[k] = b"v%d" % i
                if i == 5:
                    c.kill_role("tlog")
                if i == 8:
                    c.kill_role("storage")

            async def check(tr):
                got = dict(await tr.get_range(b"", b"\xff"))
                assert got == acked, (sorted(got), sorted(acked))
            await run_transaction(db, check, max_retries=200)
            return True

        assert c.run(main(), timeout_time=600)
    finally:
        c.shutdown()


def test_grv_degrades_on_dead_peer_without_erroring():
    """A dead GRV-confirmation peer must not error the batch: the proxy
    marks the peer suspect and falls back to the TLogs' durable
    frontier — min(frontier) across logs is >= every acknowledged commit
    and is reachable by storage — so clients see a valid read version,
    never an error (ref: the reference degrading
    by recruitment, MasterProxyServer.actor.cpp:1019)."""
    from foundationdb_tpu.rpc import RequestStream

    c = SimCluster(seed=311, n_proxies=2)
    try:
        db = c.client()

        async def main():
            async def wbody(tr):
                tr.set(b"k", b"v")
            await run_transaction(db, wbody)

            proxies = c.cc._current_proxies()
            assert len(proxies) == 2
            a, b = proxies
            floor = max(p.committed_version.get() for p in proxies)

            # replace a's view of its peer with an endpoint that never
            # answers (peer process dead, recovery not yet rotated)
            dead = RequestStream(db.process)
            a.set_peers([dead.ref()])

            t0 = flow.now()
            reply = await a.grvs.ref().get_reply(None, db.process)
            assert reply.version >= floor, (reply.version, floor)
            assert a.stats.counter("grv_degraded").value >= 1

            # suspect cache: the next batch skips the dead peer and
            # answers well inside one confirm-timeout
            t1 = flow.now()
            reply2 = await a.grvs.ref().get_reply(None, db.process)
            assert reply2.version >= reply.version
            assert flow.now() - t1 < flow.SERVER_KNOBS.grv_confirm_timeout, (
                flow.now() - t1)
            # the first, suspect-discovering batch pays at most one
            # confirm-timeout plus the fallback round-trip
            assert flow.now() - t0 < 3 * flow.SERVER_KNOBS.grv_confirm_timeout
            return True

        assert c.run(main(), timeout_time=120)
    finally:
        c.shutdown()
