"""The standalone backup/restore driver (fdbtpu-backup).

Ref: fdbbackup/backup.actor.cpp:74 — one multiplexed binary
(start/status/wait/abort + fdbrestore) that drives backups through the
database's backup control subspace while cluster-side agents do the
work. The contract under test: the tool speaks ONLY the client surface
(control rows + container IO), the cluster-side BackupDriver executes
the lifecycle, and a full round trip — populate, back up to
blobstore://, wipe, restore — works both in-sim and from the command
line against a separate server process.
"""

import subprocess
import sys

import pytest

from foundationdb_tpu import flow
from foundationdb_tpu.client import run_transaction
from foundationdb_tpu.layers.backup_container import BlobStoreServer
from foundationdb_tpu.server import SimCluster
from foundationdb_tpu.tools import backup_tool as bt


def test_backup_tool_roundtrip_in_sim():
    store = BlobStoreServer()
    url = f"blobstore://{store.host}:{store.port}"
    c = SimCluster(seed=901, durable=True, backup_driver=True)
    try:
        db = c.client()

        async def main():
            # pre-backup data
            for i in range(10):
                async def body(tr, i=i):
                    tr.set(b"pre%02d" % i, b"v%d" % i)
                await run_transaction(db, body, max_retries=500)

            out = await bt.backup_start(db, url)
            assert out["state"] == "submitted"
            # double-start is refused while one is active
            with pytest.raises(RuntimeError):
                await bt.backup_start(db, url)

            st = await bt.backup_wait(db, max_wait=120)
            assert st["state"] in ("running", "stopped")

            # post-snapshot writes ride the mutation log
            last = 0
            for i in range(10):
                tr = db.create_transaction()
                tr.set(b"post%02d" % i, b"v%d" % i)
                last = await tr.commit()

            st = await bt.backup_wait(db, version=last, max_wait=120)
            assert st["restorable_version"] >= last

            status = await bt.backup_status(db)
            assert status["state"] == "running"
            assert status["dest"] == url
            assert status["container"]["snapshot_versions"]

            st = await bt.backup_abort(db, max_wait=120)
            assert st["state"] == "stopped"
            assert st["restorable_version"] >= last

            # wipe, then restore from the container
            async def wipe(tr):
                tr.clear_range(b"", b"\xff")
            await run_transaction(db, wipe, max_retries=500)

            async def check_empty(tr):
                return await tr.get_range(b"", b"\xff", limit=5)
            assert await run_transaction(db, check_empty,
                                         max_retries=500) == []

            out = await bt.backup_restore(db, url)
            assert out["restored_to_version"] >= last

            async def read_all(tr):
                return dict(await tr.get_range(b"", b"\xff"))
            rows = await run_transaction(db, read_all, max_retries=500)
            for i in range(10):
                assert rows.get(b"pre%02d" % i) == b"v%d" % i
                assert rows.get(b"post%02d" % i) == b"v%d" % i

            # a second backup may start after the first stopped
            out = await bt.backup_start(db, url)
            assert out["state"] == "submitted"
            await bt.backup_abort(db, max_wait=120)
            return True

        assert c.run(main(), timeout_time=900)
    finally:
        c.shutdown()
        store.close()


def test_backup_tool_from_command_line():
    """The verdict's Done criterion: round-trip through blobstore://
    FROM THE COMMAND LINE — a tools.server subprocess hosts the
    cluster (its BackupDriver included), and every step is a real
    `python -m foundationdb_tpu.tools.backup_tool ...` invocation."""
    import os
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    store = BlobStoreServer()
    url = f"blobstore://{store.host}:{store.port}"
    proc = subprocess.Popen(
        [sys.executable, "-m", "foundationdb_tpu.tools.server",
         "--port", "0", "--seed", "87"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        env=env)
    try:
        line = proc.stdout.readline().strip()
        assert line.startswith("LISTENING "), line
        port = int(line.split()[1])
        connect = f"127.0.0.1:{port}"

        def tool(*args):
            r = subprocess.run(
                [sys.executable, "-m",
                 "foundationdb_tpu.tools.backup_tool", *args,
                 "-C", connect],
                capture_output=True, text=True, env=env, timeout=300)
            assert r.returncode == 0, (args, r.stdout, r.stderr)
            import json
            return json.loads(r.stdout)

        from foundationdb_tpu.tools.cli import main as cli_main
        import io
        from contextlib import redirect_stdout

        def cli(script):
            buf = io.StringIO()
            with redirect_stdout(buf):
                rc = cli_main(["--connect", connect, "--exec", script])
            assert rc == 0, buf.getvalue()
            return buf.getvalue()

        cli("set alpha one; set beta two")
        out = tool("start", "-d", url)
        assert out["state"] == "submitted"
        tool("wait", "--timeout", "120")
        cli("set gamma three")
        st = tool("status")
        assert st["state"] == "running" and st["dest"] == url
        out = tool("abort", "--timeout", "120")
        assert out["state"] == "stopped"

        cli("clearrange \\x00 \\xfe")
        assert "`alpha': not found" in cli("get alpha")
        out = tool("restore", "-r", url)
        assert out["restored_to_version"] > 0
        got = cli("get alpha; get beta")
        assert "`alpha' is `one'" in got and "`beta' is `two'" in got
    finally:
        proc.terminate()
        proc.wait(timeout=30)
        store.close()
