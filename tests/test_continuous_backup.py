"""Continuous backup: the mutation-log tail + snapshot gives
point-in-time restore; the tag survives epoch recoveries (ref:
fdbclient/FileBackupAgent.actor.cpp + design/backup.md)."""

from foundationdb_tpu import flow
from foundationdb_tpu.client import run_transaction
from foundationdb_tpu.layers import backup_agent as ba
from foundationdb_tpu.server import SimCluster


def test_point_in_time_restore():
    c = SimCluster(seed=1501, durable=True)
    try:
        db = c.client()

        async def main():
            async def write_kv(k, v):
                async def body(tr):
                    tr.set(k, v)
                await run_transaction(db, body)

            await write_kv(b"pre", b"1")

            agent = ba.BackupAgent(c, c.client("agent"))
            base_v = await agent.start()

            # era A
            for i in range(5):
                await write_kv(b"a%d" % i, b"A")
            tr = db.create_transaction()
            await tr.get(b"a0")
            v_mid = await tr.get_read_version()

            # era B (after the point we'll restore to)
            for i in range(5):
                await write_kv(b"b%d" % i, b"B")
            async def clr(tr):
                tr.clear(b"pre")
            await run_transaction(db, clr)

            await agent.wait_tailed_to(v_mid)
            tr2 = db.create_transaction()
            await tr2.get(b"b0")
            v_end = await tr2.get_read_version()
            await agent.wait_tailed_to(v_end)
            await agent.stop()
            snapshot, log = agent.base_blob, agent.write_log()

            # wipe, then restore to v_mid: era A present, era B absent
            async def wipe(tr):
                tr.clear_range(b"", b"\xff")
            await run_transaction(db, wipe)
            await ba.restore_to_version(db, snapshot, log, v_mid)

            async def check_mid(tr):
                got = dict(await tr.get_range(b"", b"\xff"))
                assert got.get(b"pre") == b"1"
                assert all(got.get(b"a%d" % i) == b"A" for i in range(5))
                assert not any(k.startswith(b"b") for k in got), got
            await run_transaction(db, check_mid, max_retries=200)

            # restore to the end: everything incl. the clear of `pre`
            await run_transaction(db, wipe)
            await ba.restore_to_version(db, snapshot, log, v_end)

            async def check_end(tr):
                got = dict(await tr.get_range(b"", b"\xff"))
                assert b"pre" not in got
                assert all(got.get(b"a%d" % i) == b"A" for i in range(5))
                assert all(got.get(b"b%d" % i) == b"B" for i in range(5))
            await run_transaction(db, check_end, max_retries=200)
            return True

        assert c.run(main(), timeout_time=600)
    finally:
        c.shutdown()


def test_backup_tail_survives_recovery():
    """A TLog kill mid-backup: the tag carries into the new epoch's
    logs and the tail drains the old generation — nothing is lost."""
    c = SimCluster(seed=1507, durable=True)
    try:
        db = c.client()

        async def main():
            agent = ba.BackupAgent(c, c.client("agent"))
            await agent.start()

            async def write_k(k):
                async def body(tr):
                    tr.set(k, b"v")
                await run_transaction(db, body, max_retries=300)
            for i in range(4):
                await write_k(b"k%d" % i)
            c.kill_role("tlog")
            for i in range(4, 8):
                await write_k(b"k%d" % i)

            tr = db.create_transaction()
            await tr.get(b"k7")
            v_end = await tr.get_read_version()
            await agent.wait_tailed_to(v_end, max_wait=120)
            await agent.stop()
            snapshot, log = agent.base_blob, agent.write_log()

            async def wipe(tr):
                tr.clear_range(b"", b"\xff")
            await run_transaction(db, wipe, max_retries=300)
            await ba.restore_to_version(db, snapshot, log, v_end)

            async def check(tr):
                got = await tr.get_range(b"k", b"l")
                assert len(got) == 8, got
            await run_transaction(db, check, max_retries=200)
            return True

        assert c.run(main(), timeout_time=900)
    finally:
        c.shutdown()


def test_dr_replicates_to_second_cluster():
    """Continuous DR: a destination CLUSTER (second cluster in the
    same simulation) converges to the source's state across a source
    TLog kill mid-stream (ref: DatabaseBackupAgent)."""
    src = SimCluster(seed=1601, durable=True)
    dest = SimCluster(share_with=src, name_prefix="dr-", durable=True)
    try:
        db = src.client()
        dest_db = dest.client()

        async def main():
            async def write_k(k, v):
                async def body(tr):
                    tr.set(k, v)
                await run_transaction(db, body, max_retries=300)

            await write_k(b"seed", b"0")
            agent = ba.DrAgent(src, src.client("agent"), dest_db)
            await agent.start()

            for i in range(4):
                await write_k(b"d%d" % i, b"v%d" % i)
            src.kill_role("tlog")
            for i in range(4, 8):
                await write_k(b"d%d" % i, b"v%d" % i)

            tr = db.create_transaction()
            await tr.get(b"d7")
            v_end = await tr.get_read_version()
            await agent.wait_tailed_to(v_end, max_wait=120)
            await agent.wait_applied_to(v_end, max_wait=120)
            await agent.stop()

            async def check(tr):
                got = dict(await tr.get_range(b"", b"\xff"))
                # stop() clears the idempotency markers: the destination
                # must be byte-identical to the replicated range, with
                # no \x02dr-mark/ residue
                assert not any(k.startswith(b"\x02") for k in got), got
                assert got.get(b"seed") == b"0"
                assert all(got.get(b"d%d" % i) == b"v%d" % i
                           for i in range(8)), got
            await run_transaction(dest_db, check, max_retries=200)
            return True

        assert src.run(main(), timeout_time=900)
    finally:
        dest.shutdown()
        src.shutdown()
