"""Dynamic resolver split/merge with live state handoff (ISSUE 15).

Directed: KeyResolverMap expire/release/apply retention semantics, the
clip/graft checkpoint math. Randomized: a two-resolver SPLIT ENSEMBLE
(the proxy's clip + min-combine mirrored exactly) driven through a
dynamic split → window → early-release → merge cycle must produce
verdicts AND attribution unions bit-identical to a single unsplit
resolver — on every backend, including tooOld and empty-range
transactions. Cluster-level: the armed balance loop on a seeded skewed
workload makes ≥1 automatic split with exact increments, and the
off posture spawns nothing.

Ref: resolutionBalancing (masterserver.actor.cpp:1008), keyResolvers
history (MasterProxyServer.actor.cpp:204), the ResolverInterface
split/merge fan-out; state handoff via PR 5's ConflictSetCheckpoint.
"""

import importlib.util
import random

import pytest

from foundationdb_tpu import flow
from foundationdb_tpu.models import (
    BruteForceConflictSet,
    PyConflictSet,
    create_conflict_set,
    native_available,
)
from foundationdb_tpu.models.conflict_set import (
    COMMITTED,
    CONFLICT,
    TOO_OLD,
    ResolverTransaction,
    clip_checkpoint,
    graft_checkpoint,
)
from foundationdb_tpu.server.proxy import MWTLV, KeyResolverMap

HAVE_JAX = importlib.util.find_spec("jax") is not None
WINDOW = 5000


def txn(snapshot, reads=(), writes=()):
    return ResolverTransaction(snapshot, tuple(reads), tuple(writes))


# ------------------------------------------------------ KeyResolverMap --
def test_expire_is_pruned_from_the_gc_watermark():
    m = KeyResolverMap([b"\x80"], 2)
    m.move(b"\x10", b"\x11", 1, 1000)
    # the watermark has not passed the move: both owners stay
    m.expire(1000)
    assert m.clip_per_resolver([(b"\x10", b"\x11")], 2) == \
        [[(b"\x10", b"\x11")], [(b"\x10", b"\x11")]]
    # watermark past the move version: the former owner retires —
    # identical semantics to prune(move + window)
    m.expire(1001)
    assert m.clip_per_resolver([(b"\x10", b"\x11")], 2) == \
        [[], [(b"\x10", b"\x11")]]


def test_long_idle_history_is_bounded_by_expire():
    """A burst of moves followed by idleness must not retain owner
    history forever: one expire() at the GC watermark trims every
    former owner, however many moves landed (the satellite's leak)."""
    m = KeyResolverMap([b"\x80"], 2)
    for i in range(50):
        m.move(b"\x10", b"\x11", (i + 1) % 2, 1000 + i)
    assert max(len(ow) for ow in m.owners) > 2
    m.expire(1000 + 50)
    assert max(len(ow) for ow in m.owners) == 1
    # current ownership survived the trim (last move was to 0)
    assert m.clip_per_resolver([(b"\x10", b"\x11")], 2)[0]


def test_release_retires_former_owner_early_and_apply_dispatches():
    m = KeyResolverMap([b"\x80"], 2)
    m.apply((1000, b"\x10", b"\x11", 1))          # 4-tuple = move
    assert m.clip_per_resolver([(b"\x10", b"\x11")], 2) == \
        [[(b"\x10", b"\x11")], [(b"\x10", b"\x11")]]
    m.apply((1500, b"\x10", b"\x11", 0, "release"))
    # double delivery over, a full window early
    assert m.clip_per_resolver([(b"\x10", b"\x11")], 2) == \
        [[], [(b"\x10", b"\x11")]]
    # a release never drops the CURRENT owner
    m.release(b"\x10", b"\x11", 1)
    assert m.clip_per_resolver([(b"\x10", b"\x11")], 2)[1]


def test_owned_ranges_and_buckets_track_moves():
    m = KeyResolverMap([b"\x80"], 2)
    assert m.owned_ranges(2) == [1, 1]
    assert 0x10 in m.owned_buckets(0) and 0x90 in m.owned_buckets(1)
    m.move(b"\x10", b"\x11", 1, 100)
    assert m.owned_ranges(2) == [2, 2]   # [,10) [10,11) [11,80) [80,)
    assert 0x10 in m.owned_buckets(1)


# ------------------------------------------------------- clip / graft --
def test_clip_graft_roundtrip_and_max_semantics():
    a = PyConflictSet()
    a.resolve([txn(0, writes=[(b"\x20a", b"\x20b"), (b"\x90x", b"\x90y")])],
              100, 0)
    ck = a.checkpoint()
    piece = clip_checkpoint(ck, b"\x20", b"\x30")
    assert piece.keys[0] == b"\x20"
    b = PyConflictSet()
    # the recipient already recorded a NEWER write inside the span:
    # the graft's pointwise max must keep it
    b.resolve([txn(0, writes=[(b"\x20a", b"\x20a\x01")])], 300, 0)
    b.restore(graft_checkpoint(b.checkpoint(), piece))
    v = b.resolve([txn(250, reads=[(b"\x20a", b"\x20a\x01")], writes=()),
                   txn(150, reads=[(b"\x20a\x01", b"\x20b")], writes=()),
                   txn(150, reads=[(b"\x90x", b"\x90y")], writes=())],
                  400, 0)
    # newer write (300) survived; piece write (100) grafted; outside
    # the span untouched (no phantom [90x,90y) history)
    assert v == [CONFLICT, COMMITTED, COMMITTED]


def test_clip_graft_keyspace_tail():
    a = PyConflictSet()
    a.resolve([txn(0, writes=[(b"\xf0", b"\xf1")])], 100, 0)
    piece = clip_checkpoint(a.checkpoint(), b"\x80", None)
    b = PyConflictSet()
    b.restore(graft_checkpoint(b.checkpoint(), piece))
    assert b.resolve([txn(50, reads=[(b"\xf0", b"\xf1")], writes=())],
                     200, 0) == [CONFLICT]


# ------------------------------------------------- split-ensemble parity --
def _clip_with_index(kmap, ranges, n):
    """clip_per_resolver, but each piece carries its ORIGINAL range
    index — the attribution-union bookkeeping the proxy keeps via its
    (idx, req) lists."""
    out = [[] for _ in range(n)]
    from bisect import bisect_right
    nb = len(kmap.bounds)
    for ri, (b, e) in enumerate(ranges):
        k = max(0, bisect_right(kmap.bounds, b) - 1)
        while k < nb and kmap.bounds[k] < e:
            lo = kmap.bounds[k]
            hi = kmap.bounds[k + 1] if k + 1 < nb else None
            b2 = max(b, lo)
            e2 = e if hi is None else min(e, hi)
            if b2 < e2:
                for idx in kmap.live_owners(k):
                    out[idx].append((b2, e2, ri))
            k += 1
    return out


class SplitEnsemble:
    """Two (or more) conflict-set backends behind a KeyResolverMap —
    the proxy's _resolve_split + the resolver role's handoff endpoint,
    mirrored exactly: per-resolver clipped sub-transactions, min-
    combined verdicts, attribution mapped back to ORIGINAL range
    indices and unioned, prune per batch."""

    def __init__(self, factory, splits=(b"\x80",)):
        self.n = len(splits) + 1
        self.sets = [factory() for _ in range(self.n)]
        self.map = KeyResolverMap(list(splits), self.n, window=WINDOW)
        # the resolvers' shared GC watermark BEFORE the next batch
        # (what the proxy derives from prev_version): the split path
        # decides tooOld itself and withholds those txns, or a
        # writes-only slice would commit phantom writes
        self._prev_oldest = 0

    def handoff(self, begin, end, src, dst, at_version,
                release=True) -> None:
        """One live split/merge: move at `at_version` (the NEXT batch's
        version), checkpoint-clip the donor, graft the recipient, and
        (optionally) release the donor early — exactly the master's
        _handoff protocol run synchronously between batches."""
        self.map.move(begin, end, dst, at_version)
        piece = clip_checkpoint(self.sets[src].checkpoint(), begin, end)
        self.sets[dst].restore(
            graft_checkpoint(self.sets[dst].checkpoint(), piece))
        if release:
            self.map.release(begin, end, src)

    def resolve_with_attribution(self, txns, version, oldest):
        self.map.prune(version)
        per = [[] for _ in range(self.n)]   # (orig_idx, txn, ri_map)
        withheld = set()
        for idx, t in enumerate(txns):
            if t.read_ranges and t.read_snapshot < self._prev_oldest:
                withheld.add(idx)
                continue
            rr = _clip_with_index(self.map, t.read_ranges, self.n)
            wr = _clip_with_index(self.map, t.write_ranges, self.n)
            placed = False
            for i in range(self.n):
                if rr[i] or wr[i]:
                    per[i].append((idx, ResolverTransaction(
                        t.read_snapshot,
                        tuple((b, e) for b, e, _ in rr[i]),
                        tuple((b, e) for b, e, _ in wr[i])),
                        [ri for _b, _e, ri in rr[i]]))
                    placed = True
            if not placed:
                # no clippable ranges at all (degenerate/empty): the
                # proxy routes the ORIGINAL ranges to resolver 0 so
                # tooOld semantics survive (len(read_ranges) matters)
                per[0].append((idx, ResolverTransaction(
                    t.read_snapshot, t.read_ranges, t.write_ranges),
                    list(range(len(t.read_ranges)))))
        verdicts = [TOO_OLD if i in withheld else COMMITTED
                    for i in range(len(txns))]
        attrib = [set() for _ in txns]
        for i in range(self.n):
            batch = [t for _idx, t, _m in per[i]]
            v, a = self.sets[i].resolve_with_attribution(
                batch, version, oldest)
            for (idx, _t, rmap), verdict, idxs in zip(per[i], v, a):
                verdicts[idx] = min(verdicts[idx], verdict)
                for ci in idxs:
                    attrib[idx].add(rmap[ci])
        self._prev_oldest = max(self._prev_oldest, oldest)
        return verdicts, [tuple(sorted(s)) for s in attrib]


def _rand_batches(seed, n_batches, point=False, max_txns=6):
    rng = random.Random(seed)
    out = []
    v = 0

    def key():
        return bytes([rng.randrange(1, 250)]) + b"%02d" % rng.randrange(30)

    def rd():
        k = key()
        if point:
            return (k, k + b"\x00")
        if rng.random() < 0.1:
            return (k, k)            # degenerate (empty) range
        return (k, k + bytes([rng.randrange(1, 8)]))

    for _ in range(n_batches):
        v += rng.randrange(1, 2000)
        batch = []
        for _ in range(rng.randrange(0, max_txns)):
            reads = [rd() for _ in range(rng.randrange(0, 3))]
            writes = [rd() for _ in range(rng.randrange(0, 3))]
            snap = max(0, v - rng.randrange(0, 2 * WINDOW))
            batch.append(txn(snap, reads, writes))
        out.append((batch, v, max(0, v - WINDOW)))
    return out


def _backend_params():
    out = [("python", False), ("brute-oracle", False)]
    if native_available():
        out.append(("native", False))
    if HAVE_JAX:
        out += [("tpu", False), ("tpu-point", True),
                ("sharded-tpu", False)]
    return out


@pytest.mark.parametrize("backend,point",
                         _backend_params(),
                         ids=[b for b, _p in _backend_params()])
def test_split_merge_cycle_attribution_parity(backend, point):
    """Randomized parity across a DYNAMIC split/merge cycle: verdicts
    and attribution unions (original-index level — the order-
    insensitive union the proxy assembles) bit-identical to a single
    unsplit resolver at every batch, through: static split → live
    split with graft+early release → window-mode split (no release,
    double delivery until prune) → merge back. Includes tooOld and
    empty-range transactions."""

    def factory():
        if backend == "brute-oracle":
            return BruteForceConflictSet()
        if backend == "python":
            return PyConflictSet()
        if backend == "native":
            return create_conflict_set("native")
        if backend == "tpu":
            from foundationdb_tpu.models.tpu_resolver import \
                TpuConflictSet
            return TpuConflictSet()
        if backend == "tpu-point":
            from foundationdb_tpu.models.point_resolver import \
                PointConflictSet
            return PointConflictSet()
        from foundationdb_tpu.parallel import ShardedTpuConflictSet
        return ShardedTpuConflictSet(n_shards=2)

    if backend == "brute-oracle":
        # the ensemble is brute-force sets; the oracle is python —
        # cross-model parity, not just self-consistency
        oracle = PyConflictSet()
    else:
        oracle = factory()
    ens = SplitEnsemble(
        PyConflictSet if backend == "brute-oracle" else factory)
    batches = _rand_batches(31337, 40, point=point)
    phase_at = {10: "split", 20: "window_split", 30: "merge"}
    for bi, (batch, v, oldest) in enumerate(batches):
        phase = phase_at.get(bi)
        if phase == "split":
            # live handoff: [40,80) moves 0 -> 1 with graft + release
            ens.handoff(b"\x40", b"\x80", 0, 1, v, release=True)
        elif phase == "window_split":
            # window-only mode (a timed-out handoff): the graft still
            # runs but the donor keeps double delivery until prune
            ens.handoff(b"\xc0", None, 1, 0, v, release=False)
        elif phase == "merge":
            # the symmetric stitch: [40,80) returns to resolver 0
            ens.handoff(b"\x40", b"\x80", 1, 0, v, release=True)
        v1, a1 = oracle.resolve_with_attribution(batch, v, oldest)
        v2, a2 = ens.resolve_with_attribution(batch, v, oldest)
        assert v1 == v2, (backend, bi, phase, v1, v2, batch)
        assert [tuple(x) for x in a1] == list(a2), (
            backend, bi, phase, a1, a2, batch)


# ---------------------------------------------------------- cluster e2e --
def test_off_posture_spawns_nothing_and_counts_nothing():
    """RESOLVER_BALANCE=0 (default): the balance loop is never
    spawned — not one timer event, not one counter — and the status
    rollup reports the off posture."""
    from foundationdb_tpu.client import run_transaction
    from foundationdb_tpu.server import SimCluster
    c = SimCluster(seed=900, n_resolvers=2)
    try:
        db = c.client("off")

        async def main():
            async def body(tr):
                tr.set(b"\x10k", b"v")
            await run_transaction(db, body)
            return await db.get_status()

        status = c.run(main(), timeout_time=120)
        assert c.cc.balance_stats.snapshot() == {}
        bal = status["cluster"]["resolver_balance"]
        assert bal == {"enabled": 0, "splits": 0, "merges": 0,
                       "releases": 0, "handoff_timeouts": 0,
                       "last_split": None}
        aux_names = [t.name for t in c.cc._recovery.aux.tasks]
        assert not any("resolverBalance" in n for n in aux_names), \
            aux_names
        # the legacy work-histogram balancer still runs (unchanged
        # reference behavior)
        assert any("resolutionBalancing" in n for n in aux_names), \
            aux_names
    finally:
        c.shutdown()


def test_forced_split_cluster_end_to_end():
    """Armed + one-shot FORCE on a seeded skewed workload: >=1
    automatic split with live handoff (install + early release), all
    increments exact across the handoff window, and the donor sheds
    owned ranges."""
    from foundationdb_tpu.client import run_transaction
    from foundationdb_tpu.server import SimCluster
    c = SimCluster(seed=901, n_resolvers=2)
    flow.SERVER_KNOBS.set("resolver_balance", 1)
    flow.SERVER_KNOBS.set("resolver_balance_force", 1)
    flow.SERVER_KNOBS.set("resolver_balance_interval", 0.5)
    flow.SERVER_KNOBS.set("resolver_balance_merge_work", -1)
    try:
        dbs = [c.client(f"cl{i}") for i in range(3)]

        async def incr(db, key, n):
            for _ in range(n):
                async def body(tr):
                    cur = await tr.get(key)
                    tr.set(key, b"%d" % (int(cur or b"0") + 1))
                await run_transaction(db, body, max_retries=500)
                await flow.delay(0.05)

        async def main():
            await flow.wait_for_all([
                flow.spawn(incr(dbs[0], b"\x10hot", 30)),
                flow.spawn(incr(dbs[1], b"\x20hot", 30)),
                flow.spawn(incr(dbs[2], b"\x20hot2", 30))])
            vals = []

            async def rd(tr):
                vals.clear()
                for k in (b"\x10hot", b"\x20hot", b"\x20hot2"):
                    vals.append(await tr.get(k))
            await run_transaction(dbs[0], rd)
            return vals, await dbs[0].get_status()

        vals, status = c.run(main(), timeout_time=600)
        assert vals == [b"30", b"30", b"30"], vals
        bal = status["cluster"]["resolver_balance"]
        assert bal["enabled"] == 1
        assert bal["splits"] >= 1, bal
        assert bal["releases"] >= 1, bal
        assert bal["last_split"] is not None
        resolvers = status["cluster"]["resolvers"]
        installs = sum(r["splits"]["installs"] for r in resolvers)
        checkpoints = sum(r["splits"]["checkpoints_served"]
                          for r in resolvers)
        assert installs >= 1 and checkpoints >= 1, resolvers
        owned = [r["splits"].get("owned_ranges") for r in resolvers]
        assert all(o and o >= 1 for o in owned), owned
    finally:
        c.shutdown()


# ------------------------------------------------- networktest satellite --
def test_networktest_restores_ambient_scheduler_and_rng():
    """run_networktest hosts its own wall-clock loop and reseeds the
    ambient RNG; the caller's scheduler AND deterministic stream must
    survive a run exactly (the satellite's leak: set_seed(0) +
    set_scheduler(None) used to clobber both)."""
    from foundationdb_tpu.tools.networktest import run_networktest
    sched = flow.Scheduler()
    flow.set_scheduler(sched)
    try:
        flow.set_seed(12345)
        flow.g_random.random01()            # advance the stream
        st = flow.g_random._r.getstate()
        expected_next = flow.g_random.random01()
        flow.g_random._r.setstate(st)       # rewind the peek
        result = run_networktest(requests=40, parallel=4,
                                 payload_bytes=16)
        assert result["requests"] == 40
        assert flow.get_scheduler() is sched
        assert flow.g_random.seed == 12345
        assert flow.g_random.random01() == expected_next
    finally:
        flow.set_scheduler(None)
