"""Test config: force JAX onto a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding correctness is
validated on virtual CPU devices (the driver separately dry-run-compiles the
multi-chip path via __graft_entry__.dryrun_multichip). Must run before jax
is imported anywhere.
"""

import os

# Force, don't setdefault: the environment pins JAX_PLATFORMS to the real
# TPU tunnel, and tests must run on the virtual CPU mesh regardless.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

try:  # the platform may already be initialized via sitecustomize
    import jax

    jax.config.update("jax_platforms", "cpu")
except Exception:
    pass


def pytest_sessionfinish(session, exitstatus):
    """Dump the TEST() coverage report (flow/coverage.py) so CI can
    archive it alongside /tmp/_t1.log — the suite-level record of which
    annotated rare paths actually fired this run."""
    import json

    try:
        from foundationdb_tpu.flow import coverage

        with open("/tmp/_coverage.json", "w") as f:
            json.dump(coverage.report(), f, indent=2, sort_keys=True)
    except Exception:
        pass  # a missing dump must never fail the suite
