"""Test config: force JAX onto a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding correctness is
validated on virtual CPU devices (the driver separately dry-run-compiles the
multi-chip path via __graft_entry__.dryrun_multichip). Must run before jax
is imported anywhere.
"""

import os

# Force, don't setdefault: the environment pins JAX_PLATFORMS to the real
# TPU tunnel, and tests must run on the virtual CPU mesh regardless.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

try:  # the platform may already be initialized via sitecustomize
    import jax

    jax.config.update("jax_platforms", "cpu")
except Exception:
    pass


def pytest_addoption(parser):
    parser.addoption(
        "--seed", type=int, default=None,
        help="Force every sim_seed-driven test onto this simulation "
             "seed — the one-line replay knob the failure hook prints "
             "(same seed => identical event schedule).")


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: heavyweight end-to-end cells tier-1 skips "
        "(-m 'not slow'); nightly/full runs include them")


import pytest  # noqa: E402  (after the JAX env pinning above)


@pytest.fixture
def sim_seed(request):
    """Seed chooser for deterministic sim tests: `sim_seed(default)`
    returns the test's own default seed unless the run forces one with
    `--seed=N` — which is exactly what the failure hook's printed repro
    command does."""
    forced = request.config.getoption("--seed")

    def pick(default: int) -> int:
        return default if forced is None else forced

    return pick


def pytest_runtest_setup(item):
    # a stale seed from the previous test must never be blamed for
    # this test's failure
    try:
        from foundationdb_tpu.server import cluster as _cluster_mod

        _cluster_mod.last_sim_seed = None
    except Exception:
        pass


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_makereport(item, call):
    """Every red sim test is immediately replayable: print the sim seed
    the test actually ran under and the one-line repro command."""
    outcome = yield
    rep = outcome.get_result()
    if rep.when != "call" or not rep.failed:
        return
    try:
        from foundationdb_tpu.server import cluster as _cluster_mod

        seed = _cluster_mod.last_sim_seed
    except Exception:
        seed = None
    if seed is None:
        return
    path, _sep, selector = item.nodeid.partition("::")
    rep.sections.append((
        "sim seed replay",
        f"sim seed: {seed}\n"
        f"replay:   pytest {path} -k '{selector}' --seed={seed}\n"))


def pytest_sessionfinish(session, exitstatus):
    """Dump the TEST() coverage report (flow/coverage.py) so CI can
    archive it alongside /tmp/_t1.log — the suite-level record of which
    annotated rare paths actually fired this run."""
    import json

    try:
        from foundationdb_tpu.flow import coverage

        with open("/tmp/_coverage.json", "w") as f:
            json.dump(coverage.report(), f, indent=2, sort_keys=True)
    except Exception:
        pass  # a missing dump must never fail the suite
