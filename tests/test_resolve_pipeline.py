"""Split submit/drain resolve pipeline: bit-exact parity of pipelined
vs serial verdicts across every device backend (interval, point,
sharded), out-of-order drains, depth-1 degeneration to the synchronous
path, capacity growth and version rebasing mid-window, and the
buggified tiny-depth cluster stress under proxy/small_batch_window.

The pipeline's correctness claim is structural — history updates chain
functionally on device (batch N+1's kernel consumes batch N's output
arrays), so verdict order equals submission order regardless of how
many batches are in flight — and these tests are the evidence."""

import random

import numpy as np
import pytest

from foundationdb_tpu.flow.knobs import SERVER_KNOBS
from foundationdb_tpu.models import (
    BruteForceConflictSet,
    PyConflictSet,
    ResolverTransaction,
    create_conflict_set,
)
from foundationdb_tpu.models.point_resolver import PointConflictSet
from foundationdb_tpu.models.tpu_resolver import TpuConflictSet
from foundationdb_tpu.parallel import ShardedTpuConflictSet

MWTLV = 5_000_000


def txn(snapshot, reads=(), writes=()):
    return ResolverTransaction(snapshot, tuple(reads), tuple(writes))


@pytest.fixture
def depth_knob():
    """Set RESOLVE_PIPELINE_DEPTH for a test and restore it after."""
    prev = SERVER_KNOBS.resolve_pipeline_depth

    def set_depth(d):
        SERVER_KNOBS.set("resolve_pipeline_depth", d)

    yield set_depth
    SERVER_KNOBS.set("resolve_pipeline_depth", prev)


def rand_batches(seed, n_batches, point=False, n_keys=40, max_txns=8,
                 version_stride=2000, window=5000):
    """[(batch, commit_version, new_oldest_version)] with keys spread
    over the whole byte range (so the sharded backend's splits all see
    traffic), occasional empty batches, and snapshots that sometimes
    fall below the window (tooOld coverage)."""
    rng = random.Random(seed)
    out = []
    v = 0

    def key():
        return bytes([rng.randrange(256)]) + b"%02d" % rng.randrange(n_keys)

    def rd():
        k = key()
        if point:
            return (k, k + b"\x00")
        return (k, k + bytes([rng.randrange(1, 8)]))

    for _ in range(n_batches):
        v += rng.randrange(1, version_stride)
        batch = []
        for _ in range(rng.randrange(0, max_txns)):
            reads = [rd() for _ in range(rng.randrange(0, 3))]
            writes = [rd() for _ in range(rng.randrange(0, 3))]
            snap = max(0, v - rng.randrange(0, 2 * window))
            batch.append(txn(snap, reads, writes))
        out.append((batch, v, max(0, v - window)))
    return out


def make_backend(name, **kw):
    if name == "interval":
        return TpuConflictSet(**kw)
    if name == "point":
        return PointConflictSet(**kw)
    return ShardedTpuConflictSet(capacity=kw.pop("capacity", 1024), **kw)


def run_serial(cs, batches):
    return [cs.resolve(b, v, o) for b, v, o in batches]


def run_pipelined(cs, batches, window=4, attribute=False):
    """Submit with up to `window` tickets pending, drain in order."""
    got = []
    pending = []
    for b, v, o in batches:
        pending.append(cs.submit(b, v, o, attribute=attribute))
        if len(pending) >= window:
            t = pending.pop(0)
            got.append(cs.drain_with_attribution(t) if attribute
                       else cs.drain(t))
    for t in pending:
        got.append(cs.drain_with_attribution(t) if attribute
                   else cs.drain(t))
    return got


BACKENDS = ("interval", "point", "sharded")


@pytest.mark.parametrize("backend", BACKENDS)
def test_pipelined_matches_serial_directed(backend, depth_knob):
    """Write in batch 1, conflicting + clean reads in later batches,
    with an intra-batch write->read dependency chain in flight."""
    depth_knob(4)
    point = backend == "point"

    def pt(k):
        return (k, k + b"\x00") if point else (k, k + b"\x08")

    batches = [
        ([txn(0, writes=[pt(b"\x10aa")]), txn(0, writes=[pt(b"\x90bb")])],
         100, 0),
        ([txn(50, reads=[pt(b"\x10aa")]),          # conflicts (v100 > 50)
          txn(150, reads=[pt(b"\x10aa")]),         # clean
          txn(150, reads=[pt(b"\x90bb")], writes=[pt(b"\x90cc")])],
         200, 0),
        # intra-batch: t0 writes cc, t1 reads cc -> conflict; t2 reads
        # cc but t1's write never lands (t1 has no write)
        ([txn(250, writes=[pt(b"\x90cc")]),
          txn(250, reads=[pt(b"\x90cc")]),
          txn(250, reads=[pt(b"\x90bb")])],
         300, 0),
        ([], 400, 0),                              # empty batch in flight
        ([txn(350, reads=[pt(b"\x90cc")]),         # conflicts (v300)
          txn(450, reads=[pt(b"\x90cc")])],
         500, 0),
    ]
    serial = make_backend(backend)
    piped = make_backend(backend)
    brute = BruteForceConflictSet()
    want = run_serial(serial, batches)
    assert want == [brute.resolve(b, v, o) for b, v, o in batches]
    got = run_pipelined(piped, batches, window=4)
    assert got == want


@pytest.mark.parametrize("backend", BACKENDS)
@pytest.mark.parametrize("seed", [1, 2])
def test_pipelined_matches_serial_randomized(backend, seed, depth_knob):
    depth_knob(4)
    batches = rand_batches(seed, 30, point=(backend == "point"))
    serial = make_backend(backend)
    piped = make_backend(backend)
    brute = BruteForceConflictSet()
    want = run_serial(serial, batches)
    assert want == [brute.resolve(b, v, o) for b, v, o in batches]
    assert run_pipelined(piped, batches, window=4) == want


@pytest.mark.parametrize("backend", ("interval", "point"))
def test_pipelined_attribution_parity(backend, depth_knob):
    """drain_with_attribution on in-flight tickets returns the same
    (verdicts, causes) as the synchronous resolve_with_attribution."""
    depth_knob(4)
    batches = rand_batches(5, 20, point=(backend == "point"))
    serial = make_backend(backend)
    piped = make_backend(backend)
    want = [serial.resolve_with_attribution(b, v, o)
            for b, v, o in batches]
    got = run_pipelined(piped, batches, window=4, attribute=True)
    assert [g[0] for g in got] == [w[0] for w in want]
    assert [g[1] for g in got] == [w[1] for w in want]


@pytest.mark.parametrize("backend", BACKENDS)
def test_out_of_order_drain(backend, depth_knob):
    depth_knob(8)
    batches = rand_batches(3, 8, point=(backend == "point"))
    serial = make_backend(backend)
    piped = make_backend(backend)
    want = run_serial(serial, batches)
    tickets = [piped.submit(b, v, o) for b, v, o in batches]
    order = list(range(len(tickets)))
    random.Random(9).shuffle(order)
    got = [None] * len(tickets)
    for i in order:
        got[i] = piped.drain(tickets[i])
    assert got == want
    # draining again returns the cached result, not a recompute
    assert piped.drain(tickets[0]) == want[0]
    assert piped.pipeline.stats()["drains"] == len(tickets)


def test_depth_one_degenerates_to_serial_path(depth_knob):
    """At depth 1 every submit force-drains its predecessor: at most
    one batch in flight (today's synchronous path), verdicts unchanged."""
    depth_knob(1)
    batches = rand_batches(4, 12)
    serial = TpuConflictSet()
    piped = TpuConflictSet()
    want = run_serial(serial, batches)
    tickets = []
    for b, v, o in batches:
        tickets.append(piped.submit(b, v, o))
        assert len(piped.pipeline.in_flight) <= 1
    got = [piped.drain(t) for t in tickets]
    assert got == want
    stats = piped.pipeline.stats()
    assert stats["depth"] == 1
    assert stats["forced_drains"] > 0
    assert stats["peak_in_flight"] <= 1


def test_submit_requires_nondecreasing_versions(depth_knob):
    depth_knob(4)
    cs = TpuConflictSet()
    cs.submit([txn(0, writes=[(b"a", b"b")])], 100, 0)
    with pytest.raises(ValueError):
        cs.submit([txn(0, writes=[(b"c", b"d")])], 50, 0)


@pytest.mark.parametrize("backend", BACKENDS)
def test_capacity_growth_mid_pipeline(backend, depth_knob):
    """A tiny initial capacity forces doubling while tickets are in
    flight; the grow (which must wait for the chained state) cannot
    corrupt already-submitted batches' verdicts."""
    depth_knob(4)
    point = backend == "point"
    rng = random.Random(6)
    batches = []
    v = 0
    for i in range(24):
        v += 10
        writes = []
        for j in range(24):
            k = bytes([rng.randrange(256)]) + b"%04d" % (i * 24 + j)
            writes.append((k, k + b"\x00") if point else (k, k + b"\x02"))
        reads = []
        if i > 2:
            k = bytes([rng.randrange(256)]) + b"%04d" % rng.randrange(i * 24)
            reads.append((k, k + b"\x00") if point else (k, k + b"\x02"))
        batches.append(([txn(v - 10, reads, writes)], v, 0))
    kw = {"capacity": 64} if backend != "sharded" else {"capacity": 64}
    serial = make_backend(backend, **kw)
    piped = make_backend(backend, **kw)
    want = run_serial(serial, batches)
    assert run_pipelined(piped, batches, window=4) == want
    assert piped._cap > 64


def test_rebase_mid_pipeline(depth_knob):
    """Version offsets crossing the 2^30 rebase threshold while the
    window is full: the rebase rides the same async chain."""
    depth_knob(4)
    serial = TpuConflictSet()
    piped = TpuConflictSet()
    brute = BruteForceConflictSet()
    rng = random.Random(13)
    batches = []
    v = 0
    for _ in range(12):
        v += 300_000_000
        batch = [txn(v - rng.randrange(0, MWTLV // 2),
                     reads=[(b"a", b"c")] if rng.random() < 0.5 else [],
                     writes=[(b"b", b"b\x00")] if rng.random() < 0.5 else [])
                 for _ in range(5)]
        batches.append((batch, v, v - MWTLV))
    want = run_serial(serial, batches)
    assert want == [brute.resolve(b, v, o) for b, v, o in batches]
    assert run_pipelined(piped, batches, window=4) == want
    assert piped._base > 0


def test_submit_arrays_matches_resolve_arrays(depth_knob):
    """The pre-encoded pipelined path (what bench.py drives) returns
    the same conflict flags as the synchronous array path."""
    depth_knob(4)
    from foundationdb_tpu.ops.keys import encode_keys

    rng = np.random.default_rng(11)
    n, kb = 32, 8
    a = PointConflictSet(key_bytes=kb, capacity=1 << 12)
    b = PointConflictSet(key_bytes=kb, capacity=1 << 12)

    def enc_batch(v):
        rk = [b"%06d" % k for k in rng.integers(0, 200, n)]
        wk = [b"%06d" % k for k in rng.integers(0, 200, n)]
        keys = encode_keys(rk + wk, kb)
        snaps = np.full(n, max(0, v - 150), np.int64)
        tids = np.arange(n, dtype=np.int32)
        return (snaps, np.ones(n, bool), keys[:n], None, tids,
                keys[n:], None, tids)

    serial_out, piped_tickets, batches = [], [], []
    for i in range(10):
        v = (i + 1) * 100
        batches.append((enc_batch(v), v))
    for arrs, v in batches:
        conflict, too_old = a.resolve_arrays(
            *arrs, commit_version=v, new_oldest_version=0)
        serial_out.append((np.asarray(conflict)[:n].copy(),
                           np.asarray(too_old).copy()))
    for arrs, v in batches:
        piped_tickets.append(b.submit_arrays(
            *arrs, commit_version=v, new_oldest_version=0))
    for (want_c, want_t), t in zip(serial_out, piped_tickets):
        got_c, got_t = b.drain_arrays(t)
        assert (got_c == want_c).all()
        assert (got_t == want_t).all()


def test_pipeline_stats_and_kernel_stats(depth_knob):
    depth_knob(3)
    cs = PointConflictSet()
    batches = rand_batches(8, 10, point=True)
    run_pipelined(cs, batches, window=3)
    stats = cs.pipeline_stats()
    assert stats["submits"] == 10
    assert stats["drains"] == 10
    assert stats["in_flight"] == 0
    assert 1 <= stats["peak_in_flight"] <= 3
    assert stats["occupancy"] is not None and 0 < stats["occupancy"] <= 1
    assert stats["latency"]["submit"]["total"] == 10
    # drain latency only counts drains that actually blocked
    assert stats["latency"]["drain"]["total"] <= 10
    kstats = cs.kernel_stats()
    assert kstats["pipeline"]["submits"] == 10


def test_base_backend_submit_drain_parity(depth_knob):
    """Host backends get the same ticket API (eager, depth-free): the
    resolver role runs one code path whatever the backend."""
    depth_knob(4)
    batches = rand_batches(2, 15)
    serial = PyConflictSet()
    piped = PyConflictSet()
    want = [serial.resolve_with_attribution(b, v, o) for b, v, o in batches]
    got = run_pipelined(piped, batches, window=4, attribute=True)
    assert got == want
    stats = piped.pipeline_stats()
    assert stats["submits"] == 15
    assert stats["drains"] == 15
    assert stats["in_flight"] == 0        # eager tickets never queue


def test_interval_count_does_not_drain_pipeline(depth_knob):
    """The capacity audit / row-count surface must not force a full
    pipeline drain: with tickets in flight, reading interval_count
    leaves the un-arrived tail of the async-count list pending."""
    depth_knob(4)
    cs = TpuConflictSet()
    batches = rand_batches(7, 6)
    pending = [cs.submit(b, v, o) for b, v, o in batches]
    n0 = cs.interval_count          # must not raise, must not hang
    assert n0 >= 0
    for t in pending:
        cs.drain(t)
    cs._sync_count()
    exact = cs._count_hint
    # after a full sync the non-draining estimate converges to exact
    assert cs.interval_count == exact


def test_buggified_tiny_depth_under_small_batch_window():
    """Cluster stress: one-or-two txn batches (proxy/small_batch_window
    buggified ON) through a tiny resolve pipeline — commits, conflicts,
    duplicate-safe replies, and the pipeline counters all hold up."""
    from foundationdb_tpu import flow
    from foundationdb_tpu.client import run_transaction
    from foundationdb_tpu.flow import rng as flow_rng
    from foundationdb_tpu.server import SimCluster

    cluster = SimCluster(seed=777, durable=True)
    # force the tiny-batch stressor deterministically (site activation
    # happens at proxy recruitment, during recovery inside run()), and
    # shrink the pipeline to the buggified depth
    flow_rng.g_buggify.enabled = True
    flow_rng.g_buggify.fire_p = 1.0
    flow_rng.g_buggify._sites["proxy/small_batch_window"] = True
    SERVER_KNOBS.set("resolve_pipeline_depth", 2)
    try:
        db = cluster.client("pipe")

        async def workload():
            async def seed(tr):
                tr.set(b"hot", b"0")
            await run_transaction(db, seed)
            conflicts = 0
            for i in range(8):
                tr = db.create_transaction()
                await tr.get(b"hot")
                tr.set(b"mine%d" % i, b"v")

                async def bump(t2):
                    t2.set(b"hot", b"x")
                await run_transaction(db, bump)
                try:
                    await tr.commit()
                except flow.FdbError as e:
                    assert e.name == "not_committed", e.name
                    conflicts += 1
            assert conflicts == 8, conflicts
            return await db.get_status()

        status = cluster.run(workload(), timeout_time=300)
        resolvers = status["cluster"]["resolvers"]
        assert resolvers
        for r in resolvers:
            pipe = r["pipeline"]
            assert pipe["depth"] == 2
            assert pipe["submits"] > 0
            assert pipe["drains"] == pipe["submits"]
    finally:
        flow_rng.g_buggify.enabled = False
        flow_rng.g_buggify._sites.clear()
        SERVER_KNOBS.set("resolve_pipeline_depth", 4)
        cluster.shutdown()
