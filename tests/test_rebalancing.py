"""Resolver rebalancing: the keyResolvers owner-history map, the
double-delivery window that keeps conflict detection exact across a
move, and the master's resolutionBalancing actor shifting a hotspot.

Moves are VERSIONED THROUGH THE COMMIT STREAM: the master stamps each
move with the next version it will assign and piggybacks unseen moves
on every version reply, so all proxies apply a move at the same
effective version (no cross-proxy apply skew, no slack margin).

Ref: masterserver.actor.cpp:1008 (resolutionBalancing),
MasterProxyServer.actor.cpp:204 (keyResolvers riding the commit
stream via ApplyMetadataMutation), ResolverInterface.h:121.
"""

import pytest

from foundationdb_tpu import flow
from foundationdb_tpu.client import run_transaction
from foundationdb_tpu.server import SimCluster
from foundationdb_tpu.server.proxy import MWTLV, KeyResolverMap


def test_key_resolver_map_move_and_window():
    m = KeyResolverMap([b"\x80"], 2)   # resolver 0: [..80), 1: [80..)
    # initially single owners
    assert m.clip_per_resolver([(b"\x10", b"\x11")], 2) == \
        [[(b"\x10", b"\x11")], []]
    # move [10,11) to resolver 1 at version 1000
    m.move(b"\x10", b"\x11", 1, 1000)
    # both owners see it during the window (double delivery)
    clipped = m.clip_per_resolver([(b"\x10", b"\x11")], 2)
    assert clipped[0] == [(b"\x10", b"\x11")]
    assert clipped[1] == [(b"\x10", b"\x11")]
    # untouched ranges unchanged
    assert m.clip_per_resolver([(b"\x90", b"\x91")], 2) == \
        [[], [(b"\x90", b"\x91")]]
    # exactly one MVCC window after the move, only the new owner
    # remains — no skew slack (moves are version-stamped)
    m.prune(1000 + MWTLV)
    clipped = m.clip_per_resolver([(b"\x10", b"\x11")], 2)
    assert clipped[0] == [(b"\x10", b"\x11")]  # still within horizon
    m.prune(1000 + MWTLV + 1)
    clipped = m.clip_per_resolver([(b"\x10", b"\x11")], 2)
    assert clipped[0] == []
    assert clipped[1] == [(b"\x10", b"\x11")]
    # a range spanning the moved bucket splits correctly
    clipped = m.clip_per_resolver([(b"\x0f", b"\x12")], 2)
    assert clipped[0] == [(b"\x0f", b"\x10"), (b"\x11", b"\x12")]
    assert clipped[1] == [(b"\x10", b"\x11")]


def test_hotspot_moves_bucket_and_stays_correct():
    """All load on two byte-prefixes owned by resolver 0; the balancer
    moves one to resolver 1; the increments stay exact throughout
    (round-2 VERDICT task 8)."""
    c = SimCluster(seed=501, n_resolvers=2)
    try:
        dbs = [c.client(f"cl{i}") for i in range(3)]

        def moved():
            for w in c.workers.values():
                for rn, role in w.roles.items():
                    if rn.startswith("proxy-e"):
                        return len(role.key_resolvers.bounds) > 2
            return False

        async def incr(db, key, n):
            for _ in range(n):
                async def body(tr):
                    cur = await tr.get(key)
                    tr.set(key, b"%d" % (int(cur or b"0") + 1))
                await run_transaction(db, body, max_retries=500)
                await flow.delay(0.05)

        async def main():
            # hot prefixes 0x10 and 0x20, both on resolver 0
            tasks = [flow.spawn(incr(dbs[0], b"\x10hot", 60)),
                     flow.spawn(incr(dbs[1], b"\x20hot", 60)),
                     flow.spawn(incr(dbs[2], b"\x20hot2", 60))]
            await flow.wait_for_all(tasks)
            assert moved(), "balancer never moved a bucket"
            tr = dbs[0].create_transaction()
            a = int(await tr.get(b"\x10hot"))
            b = int(await tr.get(b"\x20hot"))
            b2 = int(await tr.get(b"\x20hot2"))
            assert (a, b, b2) == (60, 60, 60), (a, b, b2)
            return True

        assert c.run(main(), timeout_time=600)
    finally:
        c.shutdown()


def _proxy_roles(c):
    out = []
    for w in c.workers.values():
        for rn, role in w.roles.items():
            if rn.startswith("proxy-e"):
                out.append(role)
    return out


def test_conflict_detected_across_move():
    """A write committed BEFORE a boundary move must still conflict
    with a stale-snapshot transaction committed AFTER the move — the
    double-delivery window means some live resolver holds the write's
    history (the exactness property of the transition)."""
    c = SimCluster(seed=503, n_resolvers=2)
    try:
        db = c.client()

        async def main():
            setup = db.create_transaction()
            setup.set(b"\x10k", b"0")
            await setup.commit()

            # t_stale reads before the conflicting write
            t_stale = db.create_transaction()
            assert await t_stale.get(b"\x10k") == b"0"

            # W commits (resolver 0 records it)
            w = db.create_transaction()
            w.set(b"\x10k", b"1")
            await w.commit()

            # boundary moves: bucket 0x10 now owned by resolver 1,
            # stamped into the version chain by the master
            c.cc._recovery.master.register_move(b"\x10", b"\x11", 1)

            # the stale transaction must CONFLICT, not commit
            t_stale.set(b"\x10k", b"2")
            with pytest.raises(flow.FdbError) as ei:
                await t_stale.commit()
            assert ei.value.name == "not_committed"
            tr = db.create_transaction()
            assert await tr.get(b"\x10k") == b"1"
            return True

        assert c.run(main(), timeout_time=300)
    finally:
        c.shutdown()


def test_move_applies_at_same_version_despite_skewed_proxies():
    """Round-3 VERDICT task 4: artificially skew the proxies' apply
    points — proxy A processes commits (and thus applies the move)
    long before proxy B sees any traffic — then prove (a) a stale
    transaction routed through the laggard still conflicts, and (b)
    both proxies recorded the move at the SAME effective version."""
    from foundationdb_tpu.server.types import (CommitRequest, MutationRef,
                                               SET_VALUE)
    c = SimCluster(seed=507, n_resolvers=2, n_proxies=2)
    try:
        db = c.client()

        async def commit_via(proxy, snapshot, reads, writes, mutations):
            return await proxy.commits.ref().get_reply(
                CommitRequest(snapshot, tuple(reads), tuple(writes),
                              tuple(mutations)), db.process)

        async def main():
            # wait for recovery (roles exist only once recruited)
            boot = db.create_transaction()
            boot.set(b"boot", b"1")
            await boot.commit()
            pa, pb = _proxy_roles(c)
            key = b"\x10k"
            kr = (key, key + b"\x00")
            # seed through proxy A
            v0 = (await commit_via(pa, 0, (), (kr,),
                                   (MutationRef(SET_VALUE, key, b"0"),))
                  ).version

            # stale snapshot: v0 (before the conflicting write)
            v1 = (await commit_via(pa, v0, (), (kr,),
                                   (MutationRef(SET_VALUE, key, b"1"),))
                  ).version

            # version-stamped move of bucket 0x10 to resolver 1
            eff = c.cc._recovery.master.register_move(b"\x10", b"\x11", 1)

            # SKEW: proxy A processes several commits (applying the
            # move); proxy B gets no traffic at all
            for i in range(3):
                await commit_via(pa, v1, (), ((b"other", b"other\x00"),),
                                 (MutationRef(SET_VALUE, b"other",
                                              b"%d" % i),))
            assert any(v == eff for own in pa.key_resolvers.owners
                       for v, _ in own), "proxy A never applied the move"
            assert not any(v == eff for own in pb.key_resolvers.owners
                           for v, _ in own), "test setup: B applied early"

            # the stale txn (snapshot v0, conflicts with the v1 write)
            # goes through the LAGGARD proxy B — it must still abort
            with pytest.raises(flow.FdbError) as ei:
                await commit_via(pb, v0, (kr,), (kr,),
                                 (MutationRef(SET_VALUE, key, b"2"),))
            assert ei.value.name == "not_committed"

            # and B applied the move at the SAME effective version as A
            def applied_at(proxy):
                for own in proxy.key_resolvers.owners:
                    for v, idx in own:
                        if v == eff and idx == 1:
                            return v
                return None
            assert applied_at(pa) == applied_at(pb) == eff
            return True

        assert c.run(main(), timeout_time=300)
    finally:
        c.shutdown()
