"""Byte-sample storage metrics + bandwidth-driven shard splits.

Ref: storageserver.actor.cpp:310-312 (byteSample — probabilistic size
sampling), StorageMetrics.actor.h:302 (splitMetrics byte-balanced
split points), Knobs.cpp SHARD_MAX_BYTES / SHARD_MAX_BYTES_PER_KSEC
(size- and bandwidth-triggered splits). Round-4 VERDICT Missing #8:
DD decisions must run on sampled bytes, not row counts.
"""

import pytest

from foundationdb_tpu import flow
from foundationdb_tpu.client import run_transaction
from foundationdb_tpu.server import SimCluster
from foundationdb_tpu.server.storage import StorageMetrics


@pytest.fixture
def knobs():
    flow.set_seed(2)
    yield flow.SERVER_KNOBS
    flow.reset_server_knobs()


def test_sample_is_unbiased_estimator(knobs):
    """Sampled totals track true totals within a sane tolerance at
    both dense (big values) and sparse (tiny values) extremes."""
    m = StorageMetrics()
    true = 0
    for i in range(2000):
        k = b"k%05d" % i
        v = b"x" * (7 + (i * 37) % 50)     # 7..56-byte values
        m.note_set(k, len(k) + len(v))
        true += len(k) + len(v)
    est = m.sampled_bytes()
    assert abs(est - true) / true < 0.25, (est, true)
    # overwriting with a smaller value re-samples, never double-counts
    for i in range(2000):
        m.note_set(b"k%05d" % i, 8)
    est2 = m.sampled_bytes()
    assert est2 < est
    # clears drop the sampled range
    m.note_clear(b"k00000", b"k99999")
    assert m.sampled_bytes() == 0


def test_sample_unbiased_across_factor_regimes(knobs):
    """Directed unbiasedness (ISSUE 13 satellite): the estimator
    tracks true bytes at every factor regime — all-big values (every
    row recorded exactly), all-tiny (probabilistic inclusion), and a
    mix — including after a live factor change."""
    for factor, sizes in ((10, (4, 7, 9)),        # all below factor
                          (100, (150, 400, 999)),  # all at/above
                          (100, (20, 80, 150, 600))):  # mixed
        flow.SERVER_KNOBS.set("byte_sample_factor", factor)
        m = StorageMetrics()
        true = 0
        for i in range(3000):
            k = b"u%05d" % i
            n = sizes[i % len(sizes)]
            m.note_set(k, n)
            true += n
        est = m.sampled_bytes()
        assert abs(est - true) / true < 0.25, (factor, est, true)
        # range queries agree with the total (prefix-sum consistency)
        mid = b"u01500"
        assert m.sampled_bytes(b"", mid) + m.sampled_bytes(mid) == est


def test_split_key_deterministic_across_replicas(knobs):
    """Two replicas applying the same rows (in different orders) hold
    identical samples and name the IDENTICAL split key — the
    deterministic-inclusion contract DD and sim replay rely on."""
    rows = [(b"d%04d" % i, 11 + (i * 13) % 70) for i in range(500)]
    a, b = StorageMetrics(), StorageMetrics()
    for k, n in rows:
        a.note_set(k, n)
    for k, n in reversed(rows):
        b.note_set(k, n)
    assert a.sampled_bytes() == b.sampled_bytes()
    assert a.split_key(b"", None) == b.split_key(b"", None)
    assert a.split_key(b"d0100", b"d0400") == \
        b.split_key(b"d0100", b"d0400")
    # and the split point genuinely byte-balances the sample
    s = a.split_key(b"", None)
    left = a.sampled_bytes(b"", s)
    assert abs(2 * left - a.sampled_bytes()) <= \
        a.sampled_bytes() * 0.2 + 2 * flow.SERVER_KNOBS.byte_sample_factor


def test_note_clear_and_rebuild_total_consistency(knobs):
    """note_clear drops exactly the range's sampled weight (the total
    equals a fresh rebuild of the surviving rows), and rebuild()
    resets rather than accumulates."""
    rows = [(b"c%04d" % i, 9 + (i * 29) % 120) for i in range(800)]
    m = StorageMetrics()
    for k, n in rows:
        m.note_set(k, n)
    m.note_clear(b"c0200", b"c0600")
    survivors = [(k, b"x" * (n - len(k))) for k, n in rows
                 if not b"c0200" <= k < b"c0600"]
    fresh = StorageMetrics()
    fresh.rebuild(survivors)
    assert m.sampled_bytes() == fresh.sampled_bytes()
    assert m._keys == fresh._keys
    # rebuild over the same rows twice: identical, not doubled
    fresh.rebuild(survivors)
    assert m.sampled_bytes() == fresh.sampled_bytes()
    # empty-range clear is a no-op
    before = m.sampled_bytes()
    m.note_clear(b"c0600", b"c0600")
    assert m.sampled_bytes() == before


def test_prefix_sums_match_naive_after_mutation_mix(knobs):
    """The lazily-rebuilt prefix sums (ISSUE 13 satellite: sub-linear
    sampled_bytes/split_key) stay exact through interleaved queries,
    overwrites, deletions and clears."""
    m = StorageMetrics()
    for i in range(300):
        m.note_set(b"p%04d" % i, 30 + (i * 7) % 90)
    def naive(b, e):
        i = 0
        return sum(w for k, w in m._sample.items()
                   if b <= k and (e is None or k < e))
    assert m.sampled_bytes(b"p0050", b"p0250") == naive(b"p0050",
                                                        b"p0250")
    m.note_set(b"p0100", 500)          # overwrite between queries
    m.note_clear(b"p0200", b"p0220")
    assert m.sampled_bytes(b"p0050", b"p0250") == naive(b"p0050",
                                                        b"p0250")
    assert m.sampled_bytes(b"", None) == naive(b"", None)


def test_split_key_is_byte_balanced(knobs):
    """With 100 tiny rows and 5 huge rows at the end, the byte-
    balanced split point lands inside the huge tail — a row-median
    would put it mid-keyspace (the skew the row-count knobs missed)."""
    m = StorageMetrics()
    for i in range(100):
        m.note_set(b"a%03d" % i, 10)
    for i in range(5):
        m.note_set(b"z%03d" % i, 2000)
    split = m.split_key(b"", None)
    assert split is not None and split >= b"z", split


def test_bandwidth_meter_decays(knobs):
    m = StorageMetrics()
    for t in range(10):
        m.note_write(1000, float(t))       # 1000 B/s steady
    r = m.write_bytes_per_sec(10.0)
    assert 500 < r < 1500, r
    assert m.write_bytes_per_sec(60.0) < 10   # decays when idle


def test_skewed_values_split_at_byte_balanced_key():
    """VERDICT r4 done-criterion: a shard hot by BYTES (few rows, huge
    values at one end) splits, and the boundary lands where bytes —
    not rows — balance. 160 one-byte-value rows plus 8 rows of 400B
    values: row-median splits near a0080; byte-median must land in the
    big-value tail (>= b"big")."""
    c = SimCluster(seed=1501, durable=True, n_storage=1, n_workers=5)
    flow.SERVER_KNOBS.init("DD_SHARD_SPLIT_BYTES", 2500)
    try:
        db = c.client()

        async def main():
            async def seed(tr):
                for i in range(160):
                    tr.set(b"a%04d" % i, b"v")        # ~8 B/row
            await run_transaction(db, seed)

            async def seed_big(tr):
                for i in range(8):
                    tr.set(b"big%02d" % i, b"X" * 400)  # ~3.2 KB
            await run_transaction(db, seed_big)

            for _ in range(120):
                await flow.delay(0.5)
                info = c.cc.dbinfo.get()
                if len(info.storages) >= 2:
                    break
            else:
                raise AssertionError("byte-hot shard never split")
            info = c.cc.dbinfo.get()
            boundary = info.storages[1].begin
            assert boundary >= b"big", boundary

            async def check(tr):
                rows = await tr.get_range(b"a", b"c")
                assert len(rows) == 168, len(rows)
            await run_transaction(db, check)
            return True

        assert c.run(main(), timeout_time=600)
    finally:
        flow.reset_server_knobs()
        c.shutdown()


def test_write_bandwidth_triggers_split():
    """A shard small in bytes but hammered by writes splits on the
    bandwidth ceiling (ref: SHARD_MAX_BYTES_PER_KSEC)."""
    c = SimCluster(seed=1503, durable=True, n_storage=1, n_workers=5)
    flow.SERVER_KNOBS.init("DD_SHARD_SPLIT_BYTES_PER_KSEC", 40_000)
    try:
        db = c.client()

        async def main():
            stop = [False]

            async def hammer():
                i = 0
                while not stop[0]:
                    async def body(tr, i=i):
                        # overwrite a small keyset: bytes stay low,
                        # bandwidth stays high
                        tr.set(b"h%02d" % (i % 20), b"W" * 40)
                    await run_transaction(db, body, max_retries=500)
                    i += 1
                    await flow.delay(0.02)

            t = flow.spawn(hammer())
            ok = False
            for _ in range(240):
                await flow.delay(0.5)
                if len(c.cc.dbinfo.get().storages) >= 2:
                    ok = True
                    break
            stop[0] = True
            await flow.catch_errors(t)
            assert ok, "bandwidth-hot shard never split"
            return True

        assert c.run(main(), timeout_time=600)
    finally:
        flow.reset_server_knobs()
        c.shutdown()
