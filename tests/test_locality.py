"""Machine/zone locality model + policy-driven DD teams.

Ref: fdbrpc/simulator.h:47-147 (processes belong to machines; machine
kills correlate), fdbserver/DataDistribution.actor.cpp:68,563
(TCMachineTeamInfo — teams built across machines with locality
diversity through the configured storagePolicy), SimulatedCluster
setupSimulatedSystem (machines spread over zones/DCs).
"""

import pytest

from foundationdb_tpu import flow
from foundationdb_tpu.client import run_transaction
from foundationdb_tpu.server import SimCluster
from foundationdb_tpu.server.replication_policy import (PolicyAcross,
                                                        PolicyOne)


def _team_zones(c):
    """zone of every replica's worker, per shard."""
    info = c.cc.dbinfo.get()
    out = []
    for s in info.storages:
        zones = []
        for rep in s.replicas:
            wname, wi = c.cc._worker_of_role(rep.name)
            assert wname is not None, rep.name
            zones.append(wi.zone or wi.machine)
        out.append(zones)
    return out


def test_processes_share_machines_and_zones():
    """workers_per_machine/n_zones lay workers onto a machine grid;
    kill_machine takes out every co-located process at once."""
    c = SimCluster(seed=701, workers_per_machine=2, n_zones=3,
                   n_workers=12)
    try:
        machines = {}
        for name, w in c.workers.items():
            machines.setdefault(w.process.machine, []).append(name)
        assert len(machines) == 6
        assert all(len(v) == 2 for v in machines.values())
        zones = {w.process.zone for w in c.workers.values()}
        assert zones == {"z0", "z1", "z2"}

        async def main():
            m = c.workers["worker0"].process.machine
            names = set(c.kill_machine(m))
            # both co-located workers died in the same event
            assert {"worker0", "worker1"} <= names
            assert not c.net.processes["worker0"].alive
            assert not c.net.processes["worker1"].alive
            return True

        assert c.run(main(), timeout_time=60)
    finally:
        c.shutdown()


def test_storage_teams_built_across_zones():
    """With a 3-zone grid and triple replication, every shard's team
    lands in 3 distinct zones (the policy algebra drives placement)."""
    c = SimCluster(seed=703, storage_replicas=3, n_storage=2,
                   workers_per_machine=2, n_zones=3, n_workers=12,
                   durable=True)
    try:
        async def main():
            while c.cc.dbinfo.get().recovery_state != "fully_recovered":
                await flow.delay(0.1)
            for zones in _team_zones(c):
                assert len(set(zones)) == 3, zones
            return True

        assert c.run(main(), timeout_time=120)
    finally:
        c.shutdown()


def test_policy_violating_team_unconstructible():
    """An explicitly configured policy is strict: a pool that cannot
    satisfy it refuses the team (no silent degradation), both through
    pick_workers and validate()."""
    c = SimCluster(seed=705, workers_per_machine=2, n_zones=2,
                   n_workers=8)
    try:
        async def main():
            while c.cc.dbinfo.get().recovery_state != "fully_recovered":
                await flow.delay(0.1)
            pol = PolicyAcross(3, "zoneid", PolicyOne())
            with pytest.raises(flow.FdbError) as ei:
                c.cc.pick_workers(3, role="storage", policy=pol,
                                  strict=True)
            assert ei.value.name == "no_more_servers"
            # the same pool satisfies a 2-zone policy
            team = c.cc.pick_workers(2, role="storage",
                                     policy=PolicyAcross(2, "zoneid",
                                                         PolicyOne()),
                                     strict=True)
            assert len(team) == 2
            # machine-level diversity: 4 machines can host 4-across
            team4 = c.cc.pick_workers(
                4, role="storage",
                policy=PolicyAcross(4, "machineid", PolicyOne()),
                strict=True)
            assert len(team4) == 4
            return True

        assert c.run(main(), timeout_time=120)
    finally:
        c.shutdown()


def test_machine_kill_zero_data_loss():
    """Triple replication across 3 zones survives a whole-machine kill
    (two storage-hosting processes at once) with zero data loss; the
    team heals back to 3 distinct zones."""
    c = SimCluster(seed=707, storage_replicas=3, n_storage=1,
                   workers_per_machine=2, n_zones=3, n_workers=12,
                   durable=True, auto_reboot=False)
    try:
        db = c.client()

        async def main():
            async def put(i):
                async def body(tr):
                    tr.set(b"mk%04d" % i, b"v%d" % i)
                await run_transaction(db, body, max_retries=500)

            for i in range(60):
                await put(i)

            # kill the whole machine hosting the first replica
            info = c.cc.dbinfo.get()
            rep0 = info.storages[0].replicas[0].name
            wname, _wi = c.cc._worker_of_role(rep0)
            machine = c.machine_of(wname)
            killed = c.kill_machine(machine)
            assert wname in killed

            # writes keep working through the surviving replicas
            for i in range(60, 90):
                await put(i)

            # DD heals the team back to full strength on live zones
            deadline = flow.now() + 120
            while flow.now() < deadline:
                info = c.cc.dbinfo.get()
                objs = [c.cc._storage_objs.get(r.name)
                        for r in info.storages[0].replicas]
                if all(o is not None and o.process.alive for o in objs):
                    break
                await flow.delay(1.0)
            zones = _team_zones(c)[0]
            assert len(set(zones)) == 3, zones

            # zero data loss: every acknowledged row readable
            async def check(tr):
                rows = await tr.get_range(b"mk", b"ml")
                assert len(rows) == 90, len(rows)
                for i in range(90):
                    assert (b"mk%04d" % i, b"v%d" % i) in rows
            await run_transaction(db, check, max_retries=500)
            return True

        assert c.run(main(), timeout_time=600)
    finally:
        c.shutdown()


@pytest.mark.parametrize("seed", range(20))
def test_machine_kill_sweep(seed):
    """20-seed sweep (VERDICT r4 done-criterion): triple replication
    across 3 zones + a whole-machine kill mid-traffic never loses an
    acknowledged write; the cluster recovers to a fully-replicated
    state on every seed."""
    c = SimCluster(seed=7100 + seed, storage_replicas=3, n_storage=1,
                   workers_per_machine=2, n_zones=3, n_workers=12,
                   durable=True)
    try:
        db = c.client()

        async def main():
            acked = []

            async def put(i):
                async def body(tr):
                    tr.set(b"s%04d" % i, b"v%d" % i)
                await run_transaction(db, body, max_retries=500)
                acked.append(i)

            for i in range(25):
                await put(i)
            # pick a VICTIM machine actually hosting storage
            info = c.cc.dbinfo.get()
            rep = info.storages[0].replicas[seed % 3].name
            wname, _wi = c.cc._worker_of_role(rep)
            c.kill_machine(c.machine_of(wname))
            for i in range(25, 50):
                await put(i)

            async def check(tr):
                rows = dict(await tr.get_range(b"s", b"t"))
                for i in acked:
                    assert rows.get(b"s%04d" % i) == b"v%d" % i, i
            await run_transaction(db, check, max_retries=500)
            return True

        assert c.run(main(), timeout_time=600)
    finally:
        c.shutdown()
