"""ConsistencyCheck: the full-replica sweep (ref:
fdbserver/workloads/ConsistencyCheck.actor.cpp, tester.actor.cpp:741).

Proves the three properties the round-3 verdict asked for: the sweep
passes on a healthy replicated cluster after faults, it CAN fail (an
injected single-replica divergence is detected), and it validates
shard accounting."""

import pytest

from foundationdb_tpu import flow
from foundationdb_tpu.client import run_transaction
from foundationdb_tpu.server import SimCluster
from foundationdb_tpu.server.consistency import (ConsistencyError,
                                                 check_consistency)


def test_sweep_passes_on_replicated_cluster_after_faults():
    c = SimCluster(seed=701, durable=True, n_storage=2,
                   storage_replicas=2, n_workers=6)
    try:
        db = c.client()

        async def main():
            for i in range(30):
                async def body(tr, i=i):
                    tr.set(b"k%03d" % i, b"v%d" % i)
                await run_transaction(db, body)
            # a storage kill + recovery in the middle
            c.kill_role("storage")
            for i in range(30, 60):
                async def body(tr, i=i):
                    tr.set(b"k%03d" % i, b"v%d" % i)
                await run_transaction(db, body, max_retries=500)
            stats = await check_consistency(c)
            assert stats["shards"] >= 2
            assert stats["replicas"] == stats["shards"] * 2
            assert stats["rows"] >= 60
            return True

        assert c.run(main(), timeout_time=600)
    finally:
        c.shutdown()


def test_sweep_detects_injected_divergence():
    """The check must be able to FAIL: silently corrupt one replica's
    in-memory data and require the sweep to notice."""
    c = SimCluster(seed=703, n_storage=2, storage_replicas=2,
                   n_workers=6)
    try:
        db = c.client()

        async def main():
            for i in range(10):
                async def body(tr, i=i):
                    tr.set(b"d%02d" % i, b"x%d" % i)
                await run_transaction(db, body)
            await c.quiet_database()
            # inject: flip one row on ONE replica, bypassing the
            # commit path entirely
            victim = next(iter(c.cc._storage_objs.values()))
            v = victim.version.get()
            from foundationdb_tpu.server.types import (MutationRef,
                                                       SET_VALUE)
            victim.data.apply(v, MutationRef(SET_VALUE, b"d05",
                                             b"CORRUPT"))
            with pytest.raises(ConsistencyError) as ei:
                await check_consistency(c, quiesce=False)
            assert b"d05" in str(ei.value).encode() or \
                "d05" in str(ei.value)
            return True

        assert c.run(main(), timeout_time=300)
    finally:
        c.shutdown()


def test_sweep_detects_shard_map_violations():
    """Shard accounting: a published map with a gap must fail."""
    c = SimCluster(seed=705, n_storage=2)
    try:
        db = c.client()

        async def main():
            async def body(tr):
                tr.set(b"a", b"1")
            await run_transaction(db, body)
            await c.quiet_database()
            # hand the SWEEP's client a picture whose shard map has a
            # gap (injected into the handle, not published — the
            # always-on sim validator would rightly fail the broken
            # broadcast before the sweep could demonstrate its own
            # accounting check)
            info = await db.info()
            broken = info._replace(
                storages=(info.storages[0]._replace(end=b"zzz"),)
                + info.storages[1:])
            # the first shard now ends at b"zzz" while the second
            # still begins at the original split: gap or overlap
            db._info = broken
            with pytest.raises(ConsistencyError):
                await check_consistency(db, quiesce=False)
            return True

        assert c.run(main(), timeout_time=300)
    finally:
        c.shutdown()


def test_sweep_over_tcp_against_server_process():
    """The round-4 de-sim criterion: ConsistencyCheck runnable against
    a tools.server cluster OVER TCP — the sweep reads the broadcast
    shard refs, GRVs, status, and every replica's ranges through the
    wire protocol only (no role-object access)."""
    import os
    import subprocess
    import sys
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [sys.executable, "-m", "foundationdb_tpu.tools.server",
         "--port", "0", "--seed", "71", "--storage", "2",
         "--replicas", "2"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        env=env)
    try:
        line = proc.stdout.readline().strip()
        assert line.startswith("LISTENING "), line
        port = int(line.split()[1])

        from foundationdb_tpu.client.remote import RemoteCluster
        rc = RemoteCluster("127.0.0.1", port)
        try:
            async def seed():
                for i in range(20):
                    tr = rc.db.create_transaction()
                    tr.set(b"tcp%02d" % i, b"v%d" % i)
                    await tr.commit()
                return True
            assert rc.call(seed(), timeout=60)
            stats = rc.call(check_consistency(rc.db), timeout=120)
            assert stats["shards"] == 2
            assert stats["replicas"] == 4
            assert stats["rows"] >= 20
        finally:
            rc.close()

        # ...and through the CLI's --connect mode
        import io
        from contextlib import redirect_stdout
        from foundationdb_tpu.tools.cli import main as cli_main
        buf = io.StringIO()
        with redirect_stdout(buf):
            code = cli_main(["--connect", f"127.0.0.1:{port}", "--exec",
                             "consistencycheck"])
        assert code == 0
        assert "Consistency check passed" in buf.getvalue(), buf.getvalue()
    finally:
        proc.terminate()
        proc.wait(timeout=30)
