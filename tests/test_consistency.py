"""ConsistencyCheck: the full-replica sweep (ref:
fdbserver/workloads/ConsistencyCheck.actor.cpp, tester.actor.cpp:741).

Proves the three properties the round-3 verdict asked for: the sweep
passes on a healthy replicated cluster after faults, it CAN fail (an
injected single-replica divergence is detected), and it validates
shard accounting."""

import pytest

from foundationdb_tpu import flow
from foundationdb_tpu.client import run_transaction
from foundationdb_tpu.server import SimCluster
from foundationdb_tpu.server.consistency import (ConsistencyError,
                                                 check_consistency)


def test_sweep_passes_on_replicated_cluster_after_faults():
    c = SimCluster(seed=701, durable=True, n_storage=2,
                   storage_replicas=2, n_workers=6)
    try:
        db = c.client()

        async def main():
            for i in range(30):
                async def body(tr, i=i):
                    tr.set(b"k%03d" % i, b"v%d" % i)
                await run_transaction(db, body)
            # a storage kill + recovery in the middle
            c.kill_role("storage")
            for i in range(30, 60):
                async def body(tr, i=i):
                    tr.set(b"k%03d" % i, b"v%d" % i)
                await run_transaction(db, body, max_retries=500)
            stats = await check_consistency(c)
            assert stats["shards"] >= 2
            assert stats["replicas"] == stats["shards"] * 2
            assert stats["rows"] >= 60
            return True

        assert c.run(main(), timeout_time=600)
    finally:
        c.shutdown()


def test_sweep_detects_injected_divergence():
    """The check must be able to FAIL: silently corrupt one replica's
    in-memory data and require the sweep to notice."""
    c = SimCluster(seed=703, n_storage=2, storage_replicas=2,
                   n_workers=6)
    try:
        db = c.client()

        async def main():
            for i in range(10):
                async def body(tr, i=i):
                    tr.set(b"d%02d" % i, b"x%d" % i)
                await run_transaction(db, body)
            await c.quiet_database()
            # inject: flip one row on ONE replica, bypassing the
            # commit path entirely
            victim = next(iter(c.cc._storage_objs.values()))
            v = victim.version.get()
            from foundationdb_tpu.server.types import (MutationRef,
                                                       SET_VALUE)
            victim.data.apply(v, MutationRef(SET_VALUE, b"d05",
                                             b"CORRUPT"))
            with pytest.raises(ConsistencyError) as ei:
                await check_consistency(c, quiesce=False)
            assert b"d05" in str(ei.value).encode() or \
                "d05" in str(ei.value)
            return True

        assert c.run(main(), timeout_time=300)
    finally:
        c.shutdown()


def test_sweep_detects_shard_map_violations():
    """Shard accounting: a published map with a gap must fail."""
    c = SimCluster(seed=705, n_storage=2)
    try:
        db = c.client()

        async def main():
            async def body(tr):
                tr.set(b"a", b"1")
            await run_transaction(db, body)
            await c.quiet_database()
            # publish a picture whose shard map has a gap
            info = c.cc.dbinfo.get()
            broken = info._replace(
                storages=(info.storages[0]._replace(end=b"zzz"),)
                + info.storages[1:])
            # the first shard now ends at b"zzz" while the second
            # still begins at the original split: gap or overlap
            c.cc.publish(broken)
            with pytest.raises(ConsistencyError):
                await check_consistency(c, quiesce=False)
            # restore so shutdown paths see a sane picture
            c.cc.publish(info)
            return True

        assert c.run(main(), timeout_time=300)
    finally:
        c.shutdown()
