"""Backup containers: the abstract target + blob-store HTTP target.

Ref: fdbclient/BackupContainer.actor.cpp (file layout + describe),
BlobStore.actor.cpp / HTTP.actor.cpp (the S3-style object client the
blobstore:// URL scheme selects). The round-3 verdict asked for a
backup/restore round-trip THROUGH the container API, including an
HTTP object-store target.
"""

import pytest

import foundationdb_tpu.layers.backup_agent as ba
from foundationdb_tpu import flow
from foundationdb_tpu.client import run_transaction
from foundationdb_tpu.layers.backup_container import (BlobStoreContainer,
                                                      BlobStoreServer,
                                                      DirectoryContainer,
                                                      MemoryContainer,
                                                      open_container,
                                                      restore_from_container)
from foundationdb_tpu.server import SimCluster


def _run_backup_workload(c, db):
    """Write two eras under a continuous backup; returns
    (agent, v_mid, v_end) once the tail covers everything."""
    async def work():
        async def write_kv(k, v):
            async def body(tr):
                tr.set(k, v)
            await run_transaction(db, body)

        agent = ba.BackupAgent(c, c.client("agent"))
        await agent.start()
        for i in range(6):
            await write_kv(b"a%d" % i, b"A")
        tr = db.create_transaction()
        await tr.get(b"a0")
        v_mid = await tr.get_read_version()
        for i in range(6):
            await write_kv(b"b%d" % i, b"B")
        tr2 = db.create_transaction()
        await tr2.get(b"b0")
        v_end = await tr2.get_read_version()
        await agent.wait_tailed_to(v_end)
        await agent.stop()
        return agent, v_mid, v_end
    return c.run(work(), timeout_time=600)


def _check_restore(c, db, container, to_version, expect_a, expect_b):
    async def main():
        async def wipe(tr):
            tr.clear_range(b"", b"\xff")
        await run_transaction(db, wipe)
        await restore_from_container(db, container, to_version)

        async def check(tr):
            got = dict(await tr.get_range(b"", b"\xff"))
            for i in range(6):
                assert (got.get(b"a%d" % i) == b"A") == expect_a, got
                assert (got.get(b"b%d" % i) == b"B") == expect_b, got
        await run_transaction(db, check, max_retries=200)
        return True
    assert c.run(main(), timeout_time=600)


def test_memory_container_roundtrip_and_pitr():
    c = SimCluster(seed=1601, durable=True)
    try:
        db = c.client()
        agent, v_mid, v_end = _run_backup_workload(c, db)
        cont = MemoryContainer()
        desc = agent.save_to(cont, chunk_records=3)  # force chunking
        assert desc["snapshot_versions"] == [agent.base_version]
        assert len(desc["log_ranges"]) >= 2          # actually chunked
        assert desc["max_restorable_version"] >= v_end

        # point-in-time: era A only
        _check_restore(c, db, cont, v_mid, expect_a=True, expect_b=False)
        # full: both eras
        _check_restore(c, db, cont, None, expect_a=True, expect_b=True)

        # a HOLE in the log chain makes the target unreachable, loudly
        middle = cont.list_objects("logs/")[1]
        cont.delete_object(middle)
        with pytest.raises(ValueError):
            cont.latest_restorable(v_end)
    finally:
        c.shutdown()


def test_directory_container_roundtrip(tmp_path):
    c = SimCluster(seed=1603, durable=True)
    try:
        db = c.client()
        agent, _v_mid, v_end = _run_backup_workload(c, db)
        cont = open_container(f"file://{tmp_path}/bk")
        agent.save_to(cont)
        # a fresh handle over the same directory sees the objects
        cont2 = DirectoryContainer(str(tmp_path / "bk"))
        assert cont2.describe()["max_restorable_version"] >= v_end
        _check_restore(c, db, cont2, None, expect_a=True, expect_b=True)
    finally:
        c.shutdown()


def test_blobstore_container_over_real_http():
    """The blobstore:// target: objects round-trip through a real HTTP
    object server on localhost (PUT/GET/LIST/DELETE), and restore
    consumes them through the same container API."""
    server = BlobStoreServer()
    c = SimCluster(seed=1605, durable=True)
    try:
        db = c.client()
        agent, _v_mid, v_end = _run_backup_workload(c, db)
        cont = open_container(f"blobstore://{server.host}:{server.port}")
        assert isinstance(cont, BlobStoreContainer)
        agent.save_to(cont, chunk_records=4)

        # raw object semantics
        cont.put_object("properties/unittest", b"hello")
        assert cont.get_object("properties/unittest") == b"hello"
        assert "properties/unittest" in cont.list_objects("properties/")
        cont.delete_object("properties/unittest")
        assert cont.get_object("properties/unittest") is None
        assert cont.get_object("no/such/object") is None

        desc = cont.describe()
        assert desc["max_restorable_version"] >= v_end

        # the sim fetches are separable from the HTTP IO: pull the
        # restorable set over HTTP first, then restore inside the sim
        blob, records, target = cont.latest_restorable()
        from foundationdb_tpu.layers.backup_container import \
            _records_to_log_blob

        async def main():
            async def wipe(tr):
                tr.clear_range(b"", b"\xff")
            await run_transaction(db, wipe)
            await ba.restore_to_version(
                db, blob, _records_to_log_blob(records, 0), target)

            async def check(tr):
                got = dict(await tr.get_range(b"", b"\xff"))
                assert all(got.get(b"a%d" % i) == b"A" for i in range(6))
                assert all(got.get(b"b%d" % i) == b"B" for i in range(6))
            await run_transaction(db, check, max_retries=200)
            return True
        assert c.run(main(), timeout_time=600)
    finally:
        c.shutdown()
        server.close()


def test_blobstore_hmac_auth():
    """Requests are HMAC-signed per (verb, date, resource); the server
    rejects missing, wrong-secret, and stale-date requests (ref:
    BlobStore.actor.cpp setAuthHeaders — S3 V2 shape)."""
    from foundationdb_tpu.layers.backup_container import (
        BlobStoreContainer, BlobStoreServer)

    srv = BlobStoreServer(secrets={"acct": "s3cret"})
    try:
        good = BlobStoreContainer(srv.host, srv.port,
                                  key="acct", secret="s3cret")
        good.put_object("a/b", b"payload")
        assert good.get_object("a/b") == b"payload"
        assert good.list_objects("a/") == ["a/b"]

        bad = BlobStoreContainer(srv.host, srv.port,
                                 key="acct", secret="wrong")
        with pytest.raises(IOError):
            bad.put_object("a/c", b"x")
        anon = BlobStoreContainer(srv.host, srv.port)
        with pytest.raises(IOError):
            anon.get_object("a/b")
        # the object store was not touched by the rejects
        assert good.list_objects("") == ["a/b"]
    finally:
        srv.close()


def test_blobstore_multipart_upload():
    """Objects above the multipart threshold upload in parts and appear
    atomically at completion (ref: S3 multipart via BlobStore client)."""
    from foundationdb_tpu import flow
    from foundationdb_tpu.layers.backup_container import (
        BlobStoreContainer, BlobStoreServer)

    srv = BlobStoreServer()
    try:
        c = BlobStoreContainer(srv.host, srv.port)
        big = bytes(range(256)) * 4096   # 1MB > 256KB threshold
        assert len(big) > flow.SERVER_KNOBS.blobstore_multipart_threshold
        c.put_object("big", big)
        assert c.get_object("big") == big
        # several parts were actually used
        assert len(big) > flow.SERVER_KNOBS.blobstore_multipart_part_bytes
    finally:
        srv.close()


def test_blobstore_retries_transient_failures():
    """Connection errors and 5xx retry with backoff under the try
    budget; 4xx answers do not retry (ref: BlobStore doRequest)."""
    from foundationdb_tpu.layers.backup_container import (
        BlobStoreContainer, BlobStoreServer, _BlobHandler)
    import threading
    from http.server import ThreadingHTTPServer

    fail_n = {"n": 2, "seen": 0}

    class Flaky(_BlobHandler):
        store = {}
        lock = threading.Lock()
        secrets = {}
        uploads = {}
        upload_names = {}

        def do_GET(self):
            with self.lock:
                fail_n["seen"] += 1
                if fail_n["seen"] <= fail_n["n"]:
                    return self._ok(status=503)
            return super().do_GET()

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), Flaky)
    host, port = httpd.server_address[:2]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        c = BlobStoreContainer(host, port)
        c.put_object("k", b"v")
        # first GET eats the two 503s, then succeeds
        assert c.get_object("k") == b"v"
        assert fail_n["seen"] >= 3
    finally:
        httpd.shutdown()
        httpd.server_close()
        t.join(timeout=10)


def test_blobstore_authenticated_url_round_trip():
    """open_container parses credentials from the URL and the whole
    backup surface works through an authenticated store."""
    from foundationdb_tpu.layers.backup_container import (
        BlobStoreServer, open_container)

    srv = BlobStoreServer(secrets={"k1": "sec1"})
    try:
        c = open_container(f"blobstore://k1:sec1@{srv.host}:{srv.port}")
        c.put_object("snap/1", b"data1")
        c.put_object("snap/2", b"data2")
        assert c.list_objects("snap/") == ["snap/1", "snap/2"]
        c.delete_object("snap/1")
        assert c.list_objects("snap/") == ["snap/2"]
    finally:
        srv.close()
