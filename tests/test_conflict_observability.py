"""End-to-end conflict attribution + cluster health observability.

The acceptance path for this round: a rejected transaction with
report_conflicting_keys enabled surfaces the conflicting key range(s)
through resolver -> proxy -> client; `status details` shows non-empty
conflict hot-spot and latency-probe sections after a conflicting
workload; the health rollup raises messages; the trace file rolls at
trace_roll_size."""

import json
import os

from foundationdb_tpu import flow
from foundationdb_tpu.client import run_transaction
from foundationdb_tpu.server import SimCluster
from foundationdb_tpu.tools.cli import Cli
from foundationdb_tpu.tools.exporter import (parse_prometheus,
                                             render_prometheus)


async def _conflict_once(db, key=b"hot"):
    """One reported conflict on `key`; returns the transaction."""
    tr = db.create_transaction()
    tr.set_option("report_conflicting_keys")
    await tr.get(key)
    tr.set(b"mine", b"v")

    async def bump(t2, key=key):
        t2.set(key, b"x")
    await run_transaction(db, bump)
    try:
        await tr.commit()
        raise AssertionError("expected not_committed")
    except flow.FdbError as e:
        assert e.name == "not_committed", e.name
    return tr


def test_report_conflicting_keys_end_to_end():
    c = SimCluster(seed=901)
    try:
        db = c.client()

        async def main():
            async def seed(tr):
                tr.set(b"hot", b"0")
            await run_transaction(db, seed)
            tr = await _conflict_once(db)
            assert tr.get_conflicting_ranges() == \
                ((b"hot", b"hot\x00"),)
            # survives on_error's reset (the retry loop reads it)
            await tr.on_error(flow.error("not_committed"))
            assert tr.get_conflicting_ranges() == \
                ((b"hot", b"hot\x00"),)
            # ...and a successful commit clears it
            tr.set(b"fresh", b"1")
            await tr.commit()
            assert tr.get_conflicting_ranges() is None
            return True

        assert c.run(main(), timeout_time=120)
    finally:
        c.shutdown()


def test_unreported_conflict_keeps_plain_error_path():
    c = SimCluster(seed=902)
    try:
        db = c.client()

        async def main():
            async def seed(tr):
                tr.set(b"k", b"0")
            await run_transaction(db, seed)
            tr = db.create_transaction()
            await tr.get(b"k")
            tr.set(b"m", b"v")

            async def bump(t2):
                t2.set(b"k", b"x")
            await run_transaction(db, bump)
            try:
                await tr.commit()
                raise AssertionError("expected not_committed")
            except flow.FdbError as e:
                assert e.name == "not_committed"
            assert tr.get_conflicting_ranges() is None
            return True

        assert c.run(main(), timeout_time=120)
    finally:
        c.shutdown()


def test_split_resolvers_union_conflicting_ranges():
    """With key-range split resolvers, a txn conflicting on BOTH sides
    of the split gets the union of each resolver's attribution."""
    c = SimCluster(seed=903, n_resolvers=2, n_workers=4)
    try:
        db = c.client()

        async def main():
            async def seed(tr):
                tr.set(b"a-left", b"0")
                tr.set(b"z-right", b"0")
            await run_transaction(db, seed)
            tr = db.create_transaction()
            tr.set_option("report_conflicting_keys")
            await tr.get(b"a-left")
            await tr.get(b"z-right")
            tr.set(b"mine", b"v")

            async def bump(t2):
                t2.set(b"a-left", b"x")
                t2.set(b"z-right", b"x")
            await run_transaction(db, bump)
            try:
                await tr.commit()
                raise AssertionError("expected not_committed")
            except flow.FdbError as e:
                assert e.name == "not_committed"
            got = set(tr.get_conflicting_ranges())
            assert got == {(b"a-left", b"a-left\x00"),
                           (b"z-right", b"z-right\x00")}, got
            return True

        assert c.run(main(), timeout_time=120)
    finally:
        c.shutdown()


def test_status_details_and_exporter_after_conflicts():
    c = SimCluster(seed=904, durable=True)
    cli = Cli.for_cluster(c)
    try:
        db = c.client()

        async def main():
            async def seed(tr):
                tr.set(b"hot", b"0")
            await run_transaction(db, seed)
            for _ in range(6):
                await _conflict_once(db)
            await flow.delay(12.0)   # past probe interval + sampler
            return await db.get_status()

        status = c.run(main(), timeout_time=180)
        cl = status["cluster"]
        # acceptance: non-empty hot-spot and probe sections
        assert cl["conflict_hot_spots"]
        assert cl["conflict_hot_spots"][0]["begin"] == b"hot".hex()
        assert cl["latency_probe"].get("rounds", 0) >= 1
        assert cl["latency_probe"]["bands"]["grv"]["total"] >= 1
        assert any(r["hot_spots"] for r in cl["resolvers"])
        assert cl["coverage"]["declared"] > 0
        json.dumps(cl)   # the document stays JSON-serializable

        details = cli.execute("status details")
        assert "Conflict hot spots" in details
        assert b"hot".hex() in details
        assert "Latency probe" in details
        assert "cluster-probe" in details
        top = cli.execute("top")
        assert b"hot".hex() in top

        # exporter covers resolver, proxy, tlog, and kernel metrics
        text = render_prometheus(status)
        samples = parse_prometheus(text)
        kinds = {l.get("kind") for n, l, _ in samples
                 if n == "fdbtpu_role_counter"}
        assert {"proxy", "resolver", "tlog", "storage"} <= kinds
        names = {n for n, _, _ in samples}
        assert "fdbtpu_conflict_hot_spot_score" in names
        assert "fdbtpu_latency_probe_seconds" in names
    finally:
        c.shutdown()


def test_health_messages_fire():
    c = SimCluster(seed=905)
    try:
        db = c.client()

        async def main():
            async def seed(tr):
                tr.set(b"hot", b"0")
            await run_transaction(db, seed)
            # enough conflicts to cross HEALTH_CONFLICT_RATE with >= 10
            # sampled transactions in the tail window; spread across
            # sampler ticks — the rollup measures the WINDOW's deltas,
            # not lifetime totals
            for _ in range(14):
                await _conflict_once(db)
                await flow.delay(0.4)
            await flow.delay(2.0)   # let the metric sampler see them
            st = (await db.get_status())["cluster"]
            names = {m["name"] for m in st["messages"]}
            assert "high_conflict_rate" in names, st["messages"]
            m = next(mm for mm in st["messages"]
                     if mm["name"] == "high_conflict_rate")
            assert m["conflict_rate"] > 0.25
            assert "description" in m and "severity" in m
            return True

        assert c.run(main(), timeout_time=180)
    finally:
        c.shutdown()


def test_saturated_resolver_message():
    c = SimCluster(seed=906)
    try:
        db = c.client()

        async def main():
            async def seed(tr):
                tr.set(b"x", b"1")
            await run_transaction(db, seed)
            # shrink the limit so the live resolver reads as saturated
            old = flow.SERVER_KNOBS.resolver_state_memory_limit
            flow.SERVER_KNOBS.set("resolver_state_memory_limit", 1)
            try:
                st = (await db.get_status())["cluster"]
                names = {m["name"] for m in st["messages"]}
                assert "saturated_resolver" in names, st["messages"]
            finally:
                flow.SERVER_KNOBS.set("resolver_state_memory_limit", old)
            return True

        assert c.run(main(), timeout_time=120)
    finally:
        c.shutdown()


def test_trace_file_rolls_at_size(tmp_path):
    from foundationdb_tpu.flow.trace import TraceCollector

    path = str(tmp_path / "trace.json")
    col = TraceCollector(path, roll_size=512)
    for i in range(40):
        col.emit({"Severity": 10, "Time": float(i), "Type": "RollTest",
                  "ID": "x", "Filler": "y" * 40})
    col.close()
    assert col.rolled_files, "expected at least one roll"
    # every rolled file exists, is under ~roll_size + one line, and
    # holds intact JSON lines; the live file has the newest events
    total = 0
    for f in col.rolled_files + [path]:
        assert os.path.exists(f), f
        with open(f) as fh:
            lines = fh.read().splitlines()
        total += len(lines)
        for line in lines:
            assert json.loads(line)["Type"] == "RollTest"
        if f != path:
            assert os.path.getsize(f) <= 512 + 120
    assert total == 40


def test_trace_roll_keeps_flush_and_atexit_semantics(tmp_path):
    """After a roll the collector still flushes to the CURRENT file and
    close() (the atexit hook's body) targets it."""
    from foundationdb_tpu.flow.trace import TraceCollector

    path = str(tmp_path / "t.json")
    col = TraceCollector(path, roll_size=256)
    for i in range(10):
        col.emit({"Severity": 10, "Time": 0.0, "Type": "T", "ID": "",
                  "Pad": "z" * 30})
    col.flush()
    assert os.path.exists(path)
    col.emit({"Severity": 10, "Time": 0.0, "Type": "Last", "ID": ""})
    col.close()
    with open(path) as fh:
        tail = fh.read()
    assert "Last" in tail
    # reset() retargets and clears roll history
    col.reset(None)
    assert col._fh is None
