"""Tools + surfaces: the fdbcli analogue, backup/restore, and the
fdb-style binding (ref: fdbcli/fdbcli.actor.cpp,
fdbclient/FileBackupAgent.actor.cpp, bindings/python/fdb)."""

import pytest

from foundationdb_tpu import bindings as fdb
from foundationdb_tpu.client import run_transaction
from foundationdb_tpu.layers import backup as bk
from foundationdb_tpu.server import SimCluster
from foundationdb_tpu.tools.cli import Cli


def test_cli_commands():
    c = SimCluster(seed=801)
    cli = Cli.for_cluster(c)
    try:
        assert cli.execute("set apple red") == "Committed"
        assert cli.execute("set banana yellow") == "Committed"
        assert cli.execute("get apple") == "`apple' is `red'"
        assert "not found" in cli.execute("get missing")
        out = cli.execute("getrange a z")
        assert "`apple' is `red'" in out and "`banana' is `yellow'" in out
        assert cli.execute("getkey ge apple 1") == "`banana'"
        assert cli.execute("clear apple") == "Committed"
        assert "not found" in cli.execute("get apple")
        # escapes
        assert cli.execute("set \\x00k v") == "Committed"
        assert cli.execute("get \\x00k") == "`\\x00k' is `v'"
        st = cli.execute("status")
        assert "fully_recovered" in st
        assert "transactions committed" in st
        cli.writemode = False
        assert "writemode" in cli.execute("set a b")
        assert "unknown command" in cli.execute("frobnicate")
    finally:
        c.shutdown()


def test_cli_exec_mode(tmp_path):
    from foundationdb_tpu.tools.cli import main
    import contextlib
    import io

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = main(["--exec", "set k v; get k; status"])
    out = buf.getvalue()
    assert rc == 0
    assert "`k' is `v'" in out
    assert "fully_recovered" in out


def test_backup_restore_roundtrip(tmp_path):
    c = SimCluster(seed=803, n_storage=2)
    try:
        db = c.client()
        path = str(tmp_path / "snap.fdbtpu")

        async def main():
            async def seed(tr):
                for i in range(120):
                    tr.set(b"bk%03d" % i, b"v%d" % i)
            await run_transaction(db, seed)

            blob, version, n = await bk.backup(db)
            assert n == 120 and version > 0
            bk.backup_to_file(blob, path)

            # diverge: mutate + add garbage
            async def mutate(tr):
                tr.clear_range(b"bk", b"bk\xff")
                tr.set(b"junk", b"x")
            await run_transaction(db, mutate)

            restored = await bk.restore(db, path)
            assert restored == 120
            tr = db.create_transaction()
            got = await tr.get_range(b"", b"\xff")
            assert got == [(b"bk%03d" % i, b"v%d" % i) for i in range(120)]
            return True

        assert c.run(main(), timeout_time=300)
    finally:
        c.shutdown()


def test_backup_rejects_garbage(tmp_path):
    p = tmp_path / "bad.bin"
    p.write_bytes(b"not a backup")
    with pytest.raises(ValueError):
        bk.read_backup(str(p))


def test_fdb_binding_surface():
    c = SimCluster(seed=805)
    try:
        db = fdb.open(c)
        users = fdb.Subspace(("users",))

        @fdb.transactional
        async def add_user(tr, uid, name):
            tr.set(users.pack((uid,)), name)

        @fdb.transactional
        async def get_user(tr, uid):
            return await tr.get(users.pack((uid,)))

        @fdb.transactional
        async def composed(tr, uid):
            # a transactional called with a Transaction composes without
            # a nested retry loop
            await add_user(tr, uid, b"inner")
            return await get_user(tr, uid)

        async def main():
            await add_user(db, 1, b"alice")
            assert await get_user(db, 1) == b"alice"
            assert await composed(db, 2) == b"inner"
            assert fdb.tuple.unpack(users.pack((1,)))[-1] == 1
            return True

        assert c.run(main(), timeout_time=120)
    finally:
        c.shutdown()


def test_cli_operator_commands():
    """coordinators / consistencycheck / profile (ref: the fdbcli
    command table + `-r consistencycheck` + ProfilerRequest)."""
    from foundationdb_tpu.tools.cli import Cli

    c = SimCluster(seed=73, durable=True, n_coordinators=3)
    try:
        cli = Cli.for_cluster(c)
        assert cli.execute("set alpha 1") == "Committed"
        out = cli.execute("consistencycheck")
        assert out.startswith("Consistency check passed"), out

        assert cli.execute("profile on") == "Profiler on"
        for i in range(5):
            cli.execute(f"set p{i} x")
        out = cli.execute("profile off")
        assert out.startswith("Profiler off"), out
        assert any(ch.isdigit() for ch in out)

        out = cli.execute("coordinators 3")
        assert "3 new coordinators" in out, out
        # the cluster still serves traffic on the new quorum
        assert cli.execute("set beta 2") == "Committed"
        assert "2" in cli.execute("get beta")
    finally:
        c.shutdown()
