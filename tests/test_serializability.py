"""Serializability + FuzzApiCorrectness workloads.

Ref: fdbserver/workloads/Serializability.actor.cpp (an equivalent
serial order must exist — here the versionstamp order IS the claimed
serial order and every committed read is re-checked against it) and
workloads/FuzzApiCorrectness.actor.cpp (invalid API inputs produce
exact errors, never crashes, and never poison the client).
"""

import pytest

from foundationdb_tpu import flow
from foundationdb_tpu.server import SimCluster
from foundationdb_tpu.server.workloads import (FuzzApiCorrectness,
                                               Serializability)


@pytest.mark.parametrize("seed", [1201, 1203, 1205, 1207])
def test_serializability_sweep(seed):
    c = SimCluster(seed=seed, n_proxies=2, n_resolvers=2, n_storage=2)
    try:
        dbs = [c.client(f"cl{i}") for i in range(4)]

        async def main():
            w = Serializability(dbs, flow.g_random)
            stats = await w.run(txns_per_client=15)
            assert stats["replayed"] >= stats["committed"] > 0
            return True

        assert c.run(main(), timeout_time=600)
    finally:
        c.shutdown()


@pytest.mark.parametrize("seed", [1301, 1303])
def test_serializability_under_attrition(seed):
    """The serial-order guarantee holds across role kills + recovery:
    every attempt that landed — including unknown-outcome retries that
    double-landed — replays consistently."""
    c = SimCluster(seed=seed, durable=True, n_workers=5, n_logs=2,
                   buggify=True)
    try:
        dbs = [c.client(f"cl{i}") for i in range(3)]

        async def killer():
            for role in ("proxy", "tlog", "resolver"):
                await flow.delay(2.0 + flow.g_random.random01())
                try:
                    c.kill_role(role)
                except Exception:
                    pass

        async def main():
            kt = flow.spawn(killer(), name="attrition")
            w = Serializability(dbs, flow.g_random)
            stats = await w.run(txns_per_client=10)
            await flow.catch_errors(kt)
            assert stats["replayed"] > 0
            return True

        assert c.run(main(), timeout_time=900)
    finally:
        c.shutdown()


def test_serializability_catches_seeded_bug():
    """Sabotage conflict detection (every transaction commits) and the
    checker must detect a serializability violation — proof it can
    fail."""
    from foundationdb_tpu.models import conflict_set as cs_mod

    c = SimCluster(seed=1401, n_proxies=2)
    try:
        # patch the shared core (_resolve) — both the plain and the
        # attribution entry points the resolver may use route through it
        orig = cs_mod.PyConflictSet._resolve

        from foundationdb_tpu.models.conflict_set import COMMITTED, CONFLICT

        def sabotage(self, txns, commit_version, new_oldest_version,
                     collect=None):
            # flip CONFLICT -> COMMITTED, but only for the workload's
            # keyspace and only genuine conflicts: forcing TooOld to
            # commit corrupts version-window invariants cluster-wide,
            # and touching system transactions wedges the control loops
            # — either would test the sabotage, not the checker
            out = list(orig(self, txns, commit_version, new_oldest_version,
                            collect))
            for i, t in enumerate(txns):
                if out[i] == CONFLICT and t.write_ranges and all(
                        b.startswith(b"ser/") for b, _e in t.write_ranges):
                    out[i] = COMMITTED
            return out
        cs_mod.PyConflictSet._resolve = sabotage
        try:
            dbs = [c.client(f"cl{i}") for i in range(6)]

            async def main():
                w = Serializability(dbs, flow.g_random, keyspace=4)
                try:
                    await w.run(txns_per_client=25)
                except AssertionError as e:
                    assert "serializability violation" in repr(e)
                    return True
                raise AssertionError(
                    "sabotaged conflict detection went unnoticed")

            assert c.run(main(), timeout_time=600)
        finally:
            cs_mod.PyConflictSet._resolve = orig
    finally:
        c.shutdown()


@pytest.mark.parametrize("seed", [1501, 1503])
def test_fuzz_api_correctness(seed):
    c = SimCluster(seed=seed)
    try:
        db = c.client()

        async def main():
            w = FuzzApiCorrectness(db, flow.g_random)
            stats = await w.run(rounds=24)
            assert stats["invalid_ops"] >= 24
            assert stats["valid_commits"] == 24
            return True

        assert c.run(main(), timeout_time=300)
    finally:
        c.shutdown()
