"""Directory layer, TaskBucket, MetricLogger, QuietDatabase
(ref: bindings/python/fdb/directory_impl.py, fdbclient/TaskBucket
.actor.cpp, fdbclient/MetricLogger.actor.cpp,
fdbserver/QuietDatabase.actor.cpp)."""

import pytest

from foundationdb_tpu import flow
from foundationdb_tpu.client import run_transaction
from foundationdb_tpu.layers import metrics as metrics_layer
from foundationdb_tpu.layers.directory import DirectoryLayer
from foundationdb_tpu.layers.subspace import Subspace
from foundationdb_tpu.layers.taskbucket import TaskBucket
from foundationdb_tpu.server import SimCluster


def test_directory_layer():
    c = SimCluster(seed=1401)
    try:
        db = c.client()
        dl = DirectoryLayer()

        async def main():
            async def mk(tr):
                users = await dl.create_or_open(tr, ("app", "users"))
                logs = await dl.create_or_open(tr, ("app", "logs"))
                tr.set(users.pack((1,)), b"alice")
                tr.set(logs.pack((1,)), b"started")
                return users.subspace.key, logs.subspace.key
            up, lp = await run_transaction(db, mk)
            assert up != lp and not up.startswith(lp)

            async def reopen(tr):
                users = await dl.open(tr, ("app", "users"))
                assert users.subspace.key == up   # stable prefix
                assert await tr.get(users.pack((1,))) == b"alice"
                assert await dl.list(tr, ("app",)) == ["logs", "users"]
                with pytest.raises(flow.FdbError):
                    await dl.open(tr, ("app", "missing"))
            await run_transaction(db, reopen)

            async def mv(tr):
                moved = await dl.move(tr, ("app", "users"),
                                      ("app", "members"))
                assert moved.subspace.key == up  # data untouched
            await run_transaction(db, mv)

            async def after_move(tr):
                members = await dl.open(tr, ("app", "members"))
                assert await tr.get(members.pack((1,))) == b"alice"
                assert await dl.list(tr, ("app",)) == ["logs", "members"]
            await run_transaction(db, after_move)

            async def rm(tr):
                await dl.remove(tr, ("app", "members"))
            await run_transaction(db, rm)

            async def gone(tr):
                assert not await dl.exists(tr, ("app", "members"))
                assert await tr.get(up + b"\x15\x01") is None
            await run_transaction(db, gone)
            return True

        assert c.run(main(), timeout_time=300)
    finally:
        c.shutdown()


def test_taskbucket_claim_lease_finish():
    c = SimCluster(seed=1403)
    try:
        db = c.client()
        tb = TaskBucket(Subspace(("tasks",)), lease=1.0)

        async def main():
            async def add(tr):
                await tb.add(tr, {b"op": b"backup", b"n": b"1"})
                await tb.add(tr, {b"op": b"restore", b"n": b"2"})
            await run_transaction(db, add)

            async def claim(tr):
                return await tb.claim_one(tr)
            t1 = await run_transaction(db, claim)
            t2 = await run_transaction(db, claim)
            assert {t1.params[b"op"], t2.params[b"op"]} == \
                {b"backup", b"restore"}
            assert await run_transaction(db, claim) is None  # all claimed

            # finish one; let the other's lease expire and reclaim it
            async def fin(tr, t=t1):
                await tb.finish(tr, t)
            await run_transaction(db, fin)
            await flow.delay(1.5)
            t3 = await run_transaction(db, claim)
            assert t3 is not None and t3.params == t2.params

            async def fin2(tr, t=t3):
                await tb.finish(tr, t)
            await run_transaction(db, fin2)

            async def empty(tr):
                assert await tb.is_empty(tr)
            await run_transaction(db, empty)
            return True

        assert c.run(main(), timeout_time=300)
    finally:
        c.shutdown()


def test_metric_logger_persists_counters():
    c = SimCluster(seed=1405)
    try:
        db = c.client()

        async def main():
            for i in range(4):
                async def body(tr, i=i):
                    tr.set(b"m%d" % i, b"v")
                await run_transaction(db, body)
            # persist the proxies' counters into the DB itself
            proxies = c.cc._current_proxies()
            n = await metrics_layer.log_counters(
                db, [p.stats for p in proxies])
            assert n >= 2
            series = await metrics_layer.read_series(
                db, "proxy", "transactions_committed")
            assert series and series[-1][1] >= 4
            return True

        assert c.run(main(), timeout_time=300)
    finally:
        c.shutdown()


def test_quiet_database_settles():
    c = SimCluster(seed=1407, durable=True, n_storage=2)
    try:
        db = c.client()

        async def main():
            async def body(tr):
                for i in range(50):
                    tr.set(b"q%02d" % i, b"v")
            await run_transaction(db, body)
            await c.quiet_database()
            logs = c.cc.tlog_objs()
            assert all(len(t.entries) == 0 for t in logs)
            return True

        assert c.run(main(), timeout_time=300)
    finally:
        c.shutdown()
