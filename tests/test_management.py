"""ManagementAPI: live reconfiguration and worker exclusion
(ref: fdbclient/ManagementAPI.actor.cpp changeConfig/excludeServers)."""

from foundationdb_tpu.client import run_transaction
from foundationdb_tpu.server import SimCluster


def test_configure_changes_shape_through_recovery():
    c = SimCluster(seed=901, n_workers=5)
    try:
        db = c.client()

        async def main():
            async def body(tr):
                tr.set(b"a", b"1")
            await run_transaction(db, body)
            st = await db.get_status()
            assert st["cluster"]["configuration"]["resolvers"] == 1
            e0 = st["cluster"]["epoch"]

            await db.configure(n_resolvers=2, n_logs=2)

            # data survives; the new epoch runs the new shape (the
            # config change lands on the monitor's next tick, like the
            # reference's changeConfig returning before recovery)
            from foundationdb_tpu import flow
            for _ in range(200):
                st = await db.get_status()
                if st["cluster"]["epoch"] > e0 and \
                        st["cluster"]["recovery_state"] == "fully_recovered":
                    break
                await flow.delay(0.1)

            async def body2(tr):
                assert await tr.get(b"a") == b"1"
                tr.set(b"b", b"2")
            await run_transaction(db, body2, max_retries=300)
            st = await db.get_status()
            cl = st["cluster"]
            assert cl["epoch"] > e0
            assert cl["configuration"]["resolvers"] == 2
            assert cl["configuration"]["logs"] == 2
            assert len(cl["logs"]) == 2
            return True

        assert c.run(main(), timeout_time=600)
    finally:
        c.shutdown()


def test_exclude_worker_moves_roles_off_it():
    c = SimCluster(seed=903, durable=True, n_workers=5)
    try:
        db = c.client()

        async def main():
            async def body(tr):
                tr.set(b"k", b"v")
            await run_transaction(db, body)
            # find the worker hosting the current tlog and exclude it
            st = await db.get_status()
            victim = None
            for wname, w in st["cluster"]["workers"].items():
                if any(r.startswith("tlog-e") for r in w["roles"]):
                    victim = wname
                    break
            assert victim is not None
            e0 = st["cluster"]["epoch"]
            await db.exclude(victim)

            from foundationdb_tpu import flow
            for _ in range(200):
                st = await db.get_status()
                if st["cluster"]["epoch"] > e0 and \
                        st["cluster"]["recovery_state"] == "fully_recovered":
                    break
                await flow.delay(0.1)

            async def body2(tr):
                assert await tr.get(b"k") == b"v"
                tr.set(b"k2", b"v2")
            await run_transaction(db, body2, max_retries=300)
            st = await db.get_status()
            cl = st["cluster"]
            # the new epoch's transaction roles avoid the excluded worker
            cur = f"-e{cl['epoch']}-"
            roles_on_victim = [r for r in cl["workers"][victim]["roles"]
                               if cur in r]
            assert roles_on_victim == [], roles_on_victim
            # include it back: eligible again (no immediate role change)
            await db.exclude(victim, exclude=False)
            return True

        assert c.run(main(), timeout_time=600)
    finally:
        c.shutdown()


def test_conf_sync_survives_committed_exclusion_rows():
    """Regression: the conf-sync reconcile loop must keep running with
    committed \\xff/excluded/ rows present (a crash there permanently
    stops config adoption) — proven by excluding a worker, letting
    several sync rounds pass, then committing a config change and
    seeing it adopted."""
    from foundationdb_tpu import flow

    c = SimCluster(seed=907, n_workers=4)
    try:
        db = c.client()

        async def main():
            async def body(tr):
                tr.set(b"k", b"v")
            await run_transaction(db, body)
            st = await db.get_status()
            workers = st["cluster"]["workers"]
            # prefer a role-less worker; any worker is excludable here
            victim = min((w for w, info in workers.items()
                          if not info["roles"]), default=max(workers))
            await db.exclude(victim)
            # several sync intervals with the row present
            await flow.delay(3 * flow.SERVER_KNOBS.conf_sync_interval)
            # the sync actor must still adopt config changes
            await db.configure(n_proxies=2)
            deadline = flow.now() + 60
            while True:
                st = await db.get_status()
                cfg = st["cluster"]["configuration"]
                if cfg.get("proxies") == 2 and \
                        st["cluster"]["recovery_state"] == "fully_recovered":
                    break
                assert flow.now() < deadline, cfg
                await flow.delay(0.5)
            assert victim in set(cfg.get("excluded", ()))
            return True

        assert c.run(main(), timeout_time=300)
    finally:
        c.shutdown()
