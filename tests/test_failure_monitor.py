"""Pushed failure-monitor state (ref: fdbrpc/FailureMonitor.h:123 —
per-address up/down pushed from the cluster controller;
fdbclient/FailureMonitorClient.actor.cpp). The round-3 verdict noted
clients discovered failures only by RPC timeout, inflating the
failover tail; the CC now heartbeats workers and pushes the failed set
through the dbinfo broadcast, and clients order known-down replicas
last."""

from foundationdb_tpu import flow
from foundationdb_tpu.client import run_transaction
from foundationdb_tpu.server import SimCluster


def test_pushed_failure_state_avoids_clogged_replica():
    """A replica that is alive but unreachable (clogged links — a
    liveness flag would miss it) gets pushed as failed; client reads
    then go to the healthy replica FIRST, so with backup requests made
    expensive, reads still complete fast."""
    c = SimCluster(seed=901, n_storage=1, storage_replicas=2,
                   n_workers=5, auto_reboot=False)
    try:
        db = c.client()

        async def main():
            async def put(k, v):
                async def body(tr):
                    tr.set(k, v)
                await run_transaction(db, body, max_retries=500)
            for i in range(5):
                await put(b"k%d" % i, b"v%d" % i)

            # clog EVERY link to one replica's machine, both ways, for
            # a long time: alive but unreachable
            info = c.cc.dbinfo.get()
            victim = info.storages[0].replicas[0].name
            vmachine = None
            for name, w in c.workers.items():
                if victim in w.roles:
                    vmachine = w.process.machine
            assert vmachine is not None
            machines = {w.process.machine for w in c.workers.values()}
            machines.add(c.cc.process.machine)
            machines.add(db.process.machine)
            for m in machines:
                if m != vmachine:
                    c.net.clog_pair(m, vmachine, 1000.0)
                    c.net.clog_pair(vmachine, m, 1000.0)

            # the failure monitor's heartbeat times out and pushes
            deadline = flow.now() + 30
            while victim not in c.cc.dbinfo.get().failed:
                assert flow.now() < deadline, "failure never pushed"
                await flow.delay(0.1)

            # make backup-request masking expensive so first-choice
            # ordering is what the test measures
            flow.SERVER_KNOBS.set("LOAD_BALANCE_BACKUP_DELAY", 2.0)
            db2 = c.client("fresh")   # empty latency model
            t0 = flow.now()
            tr = db2.create_transaction()
            for i in range(5):
                assert await tr.get(b"k%d" % i) == b"v%d" % i
            elapsed = flow.now() - t0
            # without the pushed state, random rotation sends ~half the
            # first attempts into the clog and each pays the 2s backup
            # delay; with it, every read goes healthy-first
            assert elapsed < 1.0, elapsed
            return True

        assert c.run(main(), timeout_time=600)
    finally:
        flow.SERVER_KNOBS.set("LOAD_BALANCE_BACKUP_DELAY", 0.005)
        c.shutdown()


def test_failure_state_clears_when_worker_recovers():
    c = SimCluster(seed=903, n_workers=7)
    try:
        db = c.client()

        async def main():
            async def body(tr):
                tr.set(b"x", b"1")
            await run_transaction(db, body)
            # pick an idle worker and kill it (auto-reboot revives it)
            victim = None
            for name, w in c.workers.items():
                if not w.roles:
                    victim = name
                    break
            assert victim
            c.kill_worker(victim)
            deadline = flow.now() + 30
            while victim not in c.cc.dbinfo.get().failed:
                assert flow.now() < deadline
                await flow.delay(0.1)
            # after the reboot re-registers, the push clears
            deadline = flow.now() + 60
            while victim in c.cc.dbinfo.get().failed:
                assert flow.now() < deadline
                await flow.delay(0.1)
            return True

        assert c.run(main(), timeout_time=600)
    finally:
        c.shutdown()
