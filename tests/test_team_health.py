"""DD team-health tracking (ref: DDTeamCollection,
DataDistribution.actor.cpp:539): a team that stays below its
replication target past the rebuild delay gets a fresh replica built
from a live teammate — no operator exclusion required."""

from foundationdb_tpu import flow
from foundationdb_tpu.client import run_transaction
from foundationdb_tpu.server import SimCluster
from foundationdb_tpu.server.consistency import check_consistency


def test_dead_replica_is_rebuilt():
    c = SimCluster(seed=921, durable=True, n_storage=1,
                   storage_replicas=2, n_workers=6, auto_reboot=False)
    try:
        db = c.client()

        async def main():
            for i in range(20):
                async def body(tr, i=i):
                    tr.set(b"h%03d" % i, b"v%d" % i)
                await run_transaction(db, body)

            info = c.cc.dbinfo.get()
            victim = info.storages[0].replicas[0].name
            wname = c.cc._worker_of_role(victim)[0]
            c.kill_worker(wname)

            # past the rebuild delay, DD builds a replacement replica
            deadline = flow.now() + 120
            while True:
                assert flow.now() < deadline, "team never rebuilt"
                info = c.cc.dbinfo.get()
                team = info.storages[0].replicas
                objs = [c.cc._storage_objs.get(r.name) for r in team]
                if victim not in [r.name for r in team] and \
                        all(o is not None and o.process.alive
                            for o in objs) and len(team) == 2:
                    break
                # keep a trickle of commits so frontiers advance
                async def body(tr):
                    tr.set(b"nudge", b"x")
                await run_transaction(db, body, max_retries=500)
                await flow.delay(0.5)

            # more writes land on the healed team
            for i in range(20, 30):
                async def body(tr, i=i):
                    tr.set(b"h%03d" % i, b"v%d" % i)
                await run_transaction(db, body, max_retries=500)

            # both replicas byte-agree over everything
            stats = await check_consistency(c)
            assert stats["replicas"] >= 2

            async def check(tr):
                rows = dict(await tr.get_range(b"h", b"i"))
                for i in range(30):
                    assert rows.get(b"h%03d" % i) == b"v%d" % i, i
            await run_transaction(db, check, max_retries=500)
            return True

        assert c.run(main(), timeout_time=900)
    finally:
        c.shutdown()


def test_rebooting_worker_wins_the_grace_race():
    """With auto-reboot ON, a crashed worker comes back inside the
    rebuild delay and the team heals by REJOINING — DD must not burn a
    rebuild on it."""
    c = SimCluster(seed=923, durable=True, n_storage=1,
                   storage_replicas=2, n_workers=6)
    try:
        db = c.client()

        async def main():
            async def body(tr):
                tr.set(b"x", b"1")
            await run_transaction(db, body)
            info = c.cc.dbinfo.get()
            victim = info.storages[0].replicas[0].name
            before = [r.name for r in info.storages[0].replicas]
            wname = c.cc._worker_of_role(victim)[0]
            c.kill_worker(wname)
            # wait for reboot + re-registration (sim_reboot_delay 0.5)
            deadline = flow.now() + 60
            while True:
                assert flow.now() < deadline
                obj = c.cc._storage_objs.get(victim)
                if obj is not None and obj.process.alive:
                    break
                await flow.delay(0.2)
            await flow.delay(flow.SERVER_KNOBS.dd_team_rebuild_delay + 2)
            after = [r.name for r in
                     c.cc.dbinfo.get().storages[0].replicas]
            assert after == before    # same team: no rebuild happened
            return True

        assert c.run(main(), timeout_time=600)
    finally:
        c.shutdown()


def test_stuck_replica_is_rebuilt():
    """A replica that is ALIVE but cannot make progress (it recovered
    at a version whose covering log generation retired while it was
    down) must be detected as stuck and rebuilt — found by a fresh-seed
    sweep where exactly this wedged quiet_database forever."""
    c = SimCluster(seed=925, durable=True, n_storage=1,
                   storage_replicas=2, n_workers=6)
    try:
        db = c.client()

        async def main():
            for i in range(10):
                async def body(tr, i=i):
                    tr.set(b"s%03d" % i, b"v%d" % i)
                await run_transaction(db, body)

            # wedge one replica: no log source ever covers its needs
            info = c.cc.dbinfo.get()
            victim = info.storages[0].replicas[0].name
            obj = c.cc._storage_objs[victim]
            obj.version.rollback(0)          # "recovered at version 0"
            obj._pick_source = lambda needed: None   # nothing covers it

            # commits keep flowing; the healer detects the stuck
            # replica and rebuilds the team
            deadline = flow.now() + 120
            while True:
                assert flow.now() < deadline, "stuck replica never healed"
                info = c.cc.dbinfo.get()
                team = info.storages[0].replicas
                if victim not in [r.name for r in team]:
                    break
                async def body(tr):
                    tr.set(b"nudge", b"x")
                await run_transaction(db, body, max_retries=500)
                await flow.delay(0.5)

            await c.quiet_database()
            stats = await check_consistency(c, quiesce=False)
            assert stats["replicas"] >= 2
            return True

        assert c.run(main(), timeout_time=900)
    finally:
        c.shutdown()
