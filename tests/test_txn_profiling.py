"""Sampled transaction profiling: the client sampler, the chunked
\\xff\\x02/fdbClientInfo/client_latency/ keyspace, the analyzer, and
the janitor.

Ref: fdbclient/ClientLogEvents.h + the CSI sampling path in NativeAPI
and contrib/transaction_profiling_analyzer.py. The load-bearing
property: every sampled transaction's event stream, written through
chunked system keys and read back by tools/profiler.py, reassembles
BIT-IDENTICALLY to what the client emitted — and with sampling
disabled the hot paths execute zero profiling code."""

import pytest

from foundationdb_tpu import flow
from foundationdb_tpu.client import run_transaction
from foundationdb_tpu.client.profiling import (CommitEvent, ErrorEvent,
                                               GetEvent, GetRangeEvent,
                                               GetVersionEvent,
                                               TransactionProfile,
                                               decode_events,
                                               encode_events,
                                               record_rows,
                                               sample_decision,
                                               split_chunks)
from foundationdb_tpu.server import SimCluster
from foundationdb_tpu.server.systemkeys import (CLIENT_LATENCY_END,
                                                CLIENT_LATENCY_PREFIX,
                                                client_latency_key,
                                                parse_client_latency_key)
from foundationdb_tpu.tools.profiler import (analyze, profile_analysis,
                                             scan_records)


def _random_events(rng, n):
    """A randomized event stream covering every type, with hostile
    byte payloads (keys are arbitrary bytes, not UTF-8)."""
    evs = []
    for _ in range(n):
        kind = rng.random_int(0, 5)
        t = rng.random01() * 100
        if kind == 0:
            evs.append(GetVersionEvent(t, rng.random01(), 0))
        elif kind == 1:
            evs.append(GetEvent(t, rng.random01(),
                                rng.random_bytes(rng.random_int(1, 40)),
                                rng.random_int(-1, 1000)))
        elif kind == 2:
            evs.append(GetRangeEvent(t, rng.random01(),
                                     rng.random_bytes(8),
                                     rng.random_bytes(8) + b"\xff",
                                     rng.random_int(0, 50)))
        elif kind == 3:
            evs.append(CommitEvent(
                t, rng.random01(), rng.random_int(0, 9),
                rng.random_int(0, 4096),
                ((rng.random_bytes(5), rng.random_bytes(5) + b"\x00"),),
                "committed" if rng.random_int(0, 2) else "conflicted",
                rng.random_int(0, 1 << 40),
                ((rng.random_bytes(4), rng.random_bytes(4) + b"\x00"),)))
        else:
            evs.append(ErrorEvent(t, "commit", "not_committed"))
    return tuple(evs)


def test_event_stream_chunk_roundtrip_bit_identical():
    """encode -> split -> join -> decode is the identity, for every
    chunk size — including sizes that split mid-field."""
    rng = flow.DeterministicRandom(1234)
    for trial in range(20):
        evs = _random_events(rng, rng.random_int(1, 30))
        blob = encode_events(evs)
        for chunk_bytes in (1, 7, 64, 4096):
            chunks = split_chunks(blob, chunk_bytes)
            assert all(len(c) <= chunk_bytes for c in chunks)
            assert b"".join(chunks) == blob
        assert decode_events(blob) == evs
        # typed, not just equal: the analyzer dispatches on type
        assert all(type(a) is type(b)
                   for a, b in zip(decode_events(blob), evs))


def test_client_latency_key_schema_roundtrip():
    k = client_latency_key(123456789, "ab" * 14, 3, 7)
    assert k.startswith(CLIENT_LATENCY_PREFIX)
    assert parse_client_latency_key(k) == (1, 123456789, "ab" * 14, 3, 7)
    # keys order by (start_ts, rec_id, chunk)
    assert client_latency_key(1, "aa", 1, 2) < \
        client_latency_key(1, "aa", 2, 2) < \
        client_latency_key(2, "aa", 1, 1)
    # foreign rows in the range never crash the parser
    assert parse_client_latency_key(CLIENT_LATENCY_PREFIX + b"junk") is None
    assert parse_client_latency_key(b"\xff\x02/other") is None


def test_sample_decision_deterministic_and_rate_shaped():
    hits = [sample_decision(0xDEAD, i, 0.25) for i in range(4000)]
    assert hits == [sample_decision(0xDEAD, i, 0.25) for i in range(4000)]
    frac = sum(hits) / len(hits)
    assert 0.18 < frac < 0.32, frac
    assert not any(sample_decision(0xDEAD, i, 0.0) for i in range(100))
    assert all(sample_decision(0xDEAD, i, 1.0) for i in range(100))


def _sampled_cluster(seed, **kw):
    """Cluster with the sampler on. The knob must be set AFTER boot:
    SimCluster re-initializes SERVER_KNOBS."""
    c = SimCluster(seed=seed, durable=True, **kw)
    flow.SERVER_KNOBS.set("profile_sample_rate", 1.0)
    return c


def _teardown(c):
    flow.SERVER_KNOBS.set("profile_sample_rate", 0.0)
    c.shutdown()


def test_sampled_transaction_roundtrips_through_cluster():
    """The acceptance property: what the client emitted is exactly
    what the analyzer reads back, through real commits."""
    c = _sampled_cluster(seed=501)
    try:
        db = c.client("prof")

        async def main():
            tr = db.create_transaction()
            assert tr._profile is not None   # rate = 1.0
            await tr.get(b"alpha")
            tr.set(b"alpha", b"A" * 100)
            tr.set(b"beta\x00\xfe", b"B")
            await tr.commit()
            emitted = list(tr._profile.events)   # pre-drain copy
            rec_id_prefix = tr._profile.rec_id
            await flow.delay(1.0)                # background flush
            assert tr._profile.events == []      # drained by the flush

            async def body(t2):
                t2.set_option("read_system_keys")
                return await scan_records(t2)
            records, stats = await run_transaction(db, body)
            mine = [r for r in records
                    if r.rec_id.startswith(rec_id_prefix)]
            assert len(mine) == 1, (stats, [r.rec_id for r in records])
            assert list(mine[0].events) == emitted
            return True

        assert c.run(main(), timeout_time=120)
    finally:
        _teardown(c)


def test_multi_chunk_record_reassembles_across_page_boundaries():
    """A record bigger than PROFILE_CHUNK_BYTES splits into many
    chunks; the scan reassembles it even when its chunk run straddles
    scan pages (page_rows=2 forces the straddle)."""
    c = _sampled_cluster(seed=502)
    flow.SERVER_KNOBS.set("profile_chunk_bytes", 48)
    try:
        db = c.client("prof")

        async def main():
            tr = db.create_transaction()
            for i in range(6):
                await tr.get(b"key-%d" % i)
                tr.set(b"key-%d" % i, b"x" * 30)
            await tr.commit()
            emitted = list(tr._profile.events)
            await flow.delay(1.0)

            async def body(t2):
                t2.set_option("read_system_keys")
                return await scan_records(t2, page_rows=2)
            records, stats = await run_transaction(db, body)
            big = [r for r in records if list(r.events) == emitted]
            assert len(big) == 1, stats
            # it really was multi-chunk
            n = len(split_chunks(encode_events(emitted), 48))
            assert n > 1
            assert stats["chunks_seen"] >= n
            assert stats["skipped_missing_chunks"] == 0
            return True

        assert c.run(main(), timeout_time=120)
    finally:
        _teardown(c)


def test_missing_chunk_skipped_and_counted_not_crashed():
    """Deleting one chunk of a multi-chunk record: the analyzer skips
    that record, counts it, and still decodes every intact record."""
    c = _sampled_cluster(seed=503)
    try:
        db = c.client("prof")

        async def main():
            # two hand-written records: one intact, one to be damaged
            intact = TransactionProfile("aaaa", 10.0)
            damaged = TransactionProfile("bbbb", 11.0)
            evs = _random_events(flow.DeterministicRandom(9), 12)
            rows_a = record_rows(intact, evs, chunk_bytes=32)
            rows_b = record_rows(damaged, evs, chunk_bytes=32)
            assert len(rows_b) > 2

            async def write(tr):
                tr.set_option("access_system_keys")
                for k, v in rows_a + rows_b:
                    tr.set(k, v)
                tr.clear(rows_b[1][0])     # knock out a middle chunk
            await run_transaction(db, write)

            async def body(tr):
                tr.set_option("read_system_keys")
                return await scan_records(tr)
            records, stats = await run_transaction(db, body)
            assert stats["skipped_missing_chunks"] == 1, stats
            assert [r for r in records if r.rec_id.startswith("aaaa")]
            assert not [r for r in records
                        if r.rec_id.startswith("bbbb")]
            ok = [r for r in records if r.rec_id.startswith("aaaa")][0]
            assert list(ok.events) == list(evs)
            return True

        assert c.run(main(), timeout_time=120)
    finally:
        _teardown(c)


def test_janitor_trims_to_retention():
    """trim_client_log removes records older than the cutoff and
    counts them; newer records survive. The periodic janitor drives the
    same trim off the retention knobs."""
    from foundationdb_tpu.layers.clientlog import trim_client_log
    c = SimCluster(seed=504, durable=True)
    try:
        db = c.client("prof")

        async def main():
            old = TransactionProfile("aaaa", 1.0)
            new = TransactionProfile("bbbb", 1000.0)
            evs = _random_events(flow.DeterministicRandom(5), 4)

            async def write(tr):
                tr.set_option("access_system_keys")
                for k, v in record_rows(old, evs, chunk_bytes=64) + \
                        record_rows(new, evs, chunk_bytes=64):
                    tr.set(k, v)
            await run_transaction(db, write)

            trimmed = await trim_client_log(db, cutoff_ts=500.0)
            assert trimmed == 1, trimmed

            async def body(tr):
                tr.set_option("read_system_keys")
                return await scan_records(tr)
            records, _stats = await run_transaction(db, body)
            ids = {r.rec_id for r in records}
            assert not any(i.startswith("aaaa") for i in ids), ids
            assert any(i.startswith("bbbb") for i in ids), ids
            # idempotent: nothing older remains
            assert await trim_client_log(db, cutoff_ts=500.0) == 0
            return True

        assert c.run(main(), timeout_time=120)
    finally:
        c.shutdown()


def test_janitor_actor_runs_on_interval():
    c = SimCluster(seed=505, durable=True, profile_janitor=True)
    flow.SERVER_KNOBS.set("profile_sample_rate", 1.0)
    flow.SERVER_KNOBS.set("profile_retention_seconds", 5.0)
    flow.SERVER_KNOBS.set("profile_janitor_interval", 1.0)
    try:
        db = c.client("prof")

        async def main():
            async def w(tr):
                tr.set(b"k", b"v")
            await run_transaction(db, w)
            await flow.delay(1.0)   # flush lands

            async def count(tr):
                tr.set_option("read_system_keys")
                return len(await tr.get_range(CLIENT_LATENCY_PREFIX,
                                              CLIENT_LATENCY_END))
            assert await run_transaction(db, count) > 0
            # sampling off; past retention + a janitor round, all gone
            flow.SERVER_KNOBS.set("profile_sample_rate", 0.0)
            await flow.delay(10.0)
            assert await run_transaction(db, count) == 0
            assert c.client_log_janitor.rounds >= 1
            assert c.client_log_janitor.records_trimmed >= 1
            return True

        assert c.run(main(), timeout_time=300)
    finally:
        _teardown(c)


def test_sampling_disabled_is_zero_overhead():
    """rate=0 (the default): no TransactionProfile is ever allocated,
    no profiling event exists, and the system keyspace stays empty —
    the bench's hot path guarantee."""
    c = SimCluster(seed=506, durable=True)
    assert float(flow.SERVER_KNOBS.profile_sample_rate) == 0.0
    try:
        db = c.client("plain")

        async def main():
            for i in range(5):
                tr = db.create_transaction()
                assert tr._profile is None
                await tr.get(b"z%d" % i)
                tr.set(b"z%d" % i, b"v")
                await tr.commit()
                assert tr._profile is None
            assert db._txn_seq == 0          # sampler never consulted

            async def count(tr):
                tr.set_option("read_system_keys")
                return len(await tr.get_range(CLIENT_LATENCY_PREFIX,
                                              CLIENT_LATENCY_END))
            assert await run_transaction(db, count) == 0
            return True

        assert c.run(main(), timeout_time=120)
    finally:
        c.shutdown()


def test_transaction_logging_enable_option_forces_sampling():
    """set_option("transaction_logging_enable", id) samples ONE
    transaction even with the database rate at 0, and the identifier
    names the record."""
    c = SimCluster(seed=507, durable=True)
    try:
        db = c.client("opt")

        async def main():
            tr = db.create_transaction()
            assert tr._profile is None
            tr.set_option("transaction_logging_enable", "my-txn")
            assert tr._profile is not None
            await tr.get(b"a")
            tr.set(b"a", b"1")
            await tr.commit()
            await flow.delay(1.0)

            async def body(t2):
                t2.set_option("read_system_keys")
                return await scan_records(t2)
            records, _stats = await run_transaction(db, body)
            mine = [r for r in records if r.rec_id.startswith("my-txn")]
            assert len(mine) == 1, [r.rec_id for r in records]
            kinds = {type(e).__name__ for e in mine[0].events}
            assert "CommitEvent" in kinds and "GetEvent" in kinds
            return True

        assert c.run(main(), timeout_time=120)
    finally:
        c.shutdown()


def test_conflicted_commit_records_verdict_and_attribution():
    """A conflicted sampled commit persists verdict="conflicted" with
    the resolver's attributed ranges (PR 2's report_conflicting_keys),
    and the analyzer counts it."""
    c = _sampled_cluster(seed=508)
    try:
        db = c.client("prof")

        async def main():
            async def seed(tr):
                tr.set(b"hot", b"0")
            await run_transaction(db, seed)
            tr = db.create_transaction()
            tr.set_option("report_conflicting_keys")
            await tr.get(b"hot")
            tr.set(b"mine", b"v")

            async def bump(t2):
                t2.set(b"hot", b"x")
            await run_transaction(db, bump)
            try:
                await tr.commit()
                raise AssertionError("expected conflict")
            except flow.FdbError as e:
                assert e.name == "not_committed"
            commits = [e for e in tr._profile.events
                       if isinstance(e, CommitEvent)]
            assert commits and commits[-1].verdict == "conflicted"
            assert commits[-1].conflicting_ranges == \
                ((b"hot", b"hot\x00"),)
            await flow.delay(1.0)
            analysis, _stats = await profile_analysis(db)
            assert analysis["conflicted"] >= 1
            assert analysis["committed"] >= 1
            assert any(r["key"] == b"hot".hex()
                       for r in analysis["hottest_keys"])
            assert any(r["key"] == b"hot".hex()
                       for r in analysis["hottest_written"])
            return True

        assert c.run(main(), timeout_time=120)
    finally:
        _teardown(c)


def test_analyzer_orders_slowest_and_histograms():
    """Pure-analysis unit: slowest ordering, per-op histograms, and
    outcome counts over synthetic records."""
    from foundationdb_tpu.tools.profiler import TxnRecord
    fast = TxnRecord(1.0, "fast", (
        GetVersionEvent(1.0, 0.001, 0),
        CommitEvent(1.0, 0.002, 1, 10, ((b"a", b"a\x00"),),
                    "committed", 7, ())))
    slow = TxnRecord(2.0, "slow", (
        GetEvent(2.0, 0.5, b"k", 3),
        CommitEvent(2.0, 0.25, 1, 10, ((b"a", b"a\x00"),),
                    "conflicted", 0, ((b"k", b"k\x00"),))))
    out = analyze([fast, slow], top_n=5)
    assert out["records"] == 2
    assert out["committed"] == 1 and out["conflicted"] == 1
    assert out["slowest"][0]["rec_id"] == "slow"
    assert out["per_op"]["get"]["total"] == 1
    assert out["per_op"]["commit"]["total"] == 2
    assert out["hottest_keys"][0]["key"] == b"k".hex()


def test_cli_profile_commands():
    """`profile on` arms the sampler, `profile analyze` renders the
    report, `profile off` disarms (and keeps the legacy run-loop
    profiler contract)."""
    from foundationdb_tpu.tools.cli import Cli
    c = SimCluster(seed=509, durable=True)
    try:
        cli = Cli.for_cluster(c)
        assert cli.execute("profile on") == "Profiler on"
        assert float(flow.SERVER_KNOBS.profile_sample_rate) == 1.0
        for i in range(3):
            assert cli.execute(f"set pk{i} v") == "Committed"
        out = cli.execute("profile analyze")
        assert "Transaction profile:" in out, out
        assert "Slowest transactions:" in out, out
        out = cli.execute("profile off")
        assert out.startswith("Profiler off"), out
        assert float(flow.SERVER_KNOBS.profile_sample_rate) == 0.0
        assert cli.execute("profile bogus").startswith("usage:")
    finally:
        flow.SERVER_KNOBS.set("profile_sample_rate", 0.0)
        c.shutdown()


def test_status_and_exporter_surface_sampler_counters():
    from foundationdb_tpu.tools.exporter import (parse_prometheus,
                                                 render_prometheus)
    c = _sampled_cluster(seed=510)
    try:
        db = c.client("prof")

        async def main():
            async def w(tr):
                tr.set(b"a", b"b")
            await run_transaction(db, w)
            await flow.delay(1.0)
            return await db.get_status()

        status = c.run(main(), timeout_time=120)
        prof = status["cluster"]["client_profile"]
        assert prof["transactions_sampled"] >= 1, prof
        assert prof["records_written"] >= 1, prof
        samples = parse_prometheus(render_prometheus(status))
        got = {l["counter"]: v for n, l, v in samples
               if n == "fdbtpu_client_profile"}
        assert got.get("transactions_sampled", 0) >= 1, got
    finally:
        _teardown(c)
