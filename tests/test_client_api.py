"""Client API surface: atomic ops, key selectors, watches, reverse
ranges, versionstamps (ref workloads: AtomicOps.actor.cpp,
WatchAndWait.actor.cpp, SelectorCorrectness.actor.cpp; semantics:
fdbclient/Atomic.h)."""

import struct

import pytest

from foundationdb_tpu import flow
from foundationdb_tpu.client import run_transaction
from foundationdb_tpu.server import SimCluster
from foundationdb_tpu.server.types import (ADD_VALUE, AND, APPEND_IF_FITS,
                                           BYTE_MAX, BYTE_MIN,
                                           COMPARE_AND_CLEAR, KeySelector,
                                           MAX, MIN, OR,
                                           SET_VERSIONSTAMPED_KEY,
                                           SET_VERSIONSTAMPED_VALUE, XOR)


@pytest.fixture
def cluster():
    c = SimCluster(seed=5)
    yield c
    c.shutdown()


def le8(n):
    return struct.pack("<q", n)


def test_atomic_add(cluster):
    db = cluster.client()

    async def main():
        tr = db.create_transaction()
        tr.atomic_op(b"ctr", le8(5), ADD_VALUE)
        await tr.commit()
        tr = db.create_transaction()
        tr.atomic_op(b"ctr", le8(7), ADD_VALUE)
        # RYW: the computed value is visible before commit
        assert struct.unpack("<q", await tr.get(b"ctr"))[0] == 12
        await tr.commit()
        tr = db.create_transaction()
        assert struct.unpack("<q", await tr.get(b"ctr"))[0] == 12
        return True

    assert cluster.run(main(), timeout_time=30)


def test_atomic_concurrent_adds_all_count(cluster):
    """Blind atomic adds never conflict; all increments land
    (ref: AtomicOps workload invariant)."""
    dbs = [cluster.client(f"c{i}") for i in range(4)]

    async def add_loop(db, n):
        for _ in range(n):
            async def body(tr):
                tr.atomic_op(b"sum", le8(1), ADD_VALUE)
            await run_transaction(db, body)

    async def main():
        await flow.wait_for_all([flow.spawn(add_loop(d, 10)) for d in dbs])
        tr = dbs[0].create_transaction()
        assert struct.unpack("<q", await tr.get(b"sum"))[0] == 40
        return True

    assert cluster.run(main(), timeout_time=120)


def test_atomic_ops_matrix(cluster):
    db = cluster.client()

    async def main():
        cases = [
            (AND, b"\x0f\xff", b"\xf1\x10", b"\x01\x10"),
            (OR, b"\x0f\x00", b"\xf1\x10", b"\xff\x10"),
            (XOR, b"\x0f\xff", b"\xf1\x10", b"\xfe\xef"),
            (MAX, le8(10), le8(7), le8(10)),
            (MIN, le8(10), le8(7), le8(7)),
            (BYTE_MIN, b"abc", b"abd", b"abc"),
            (BYTE_MAX, b"abc", b"abd", b"abd"),
            (APPEND_IF_FITS, b"foo", b"bar", b"foobar"),
        ]
        for i, (op, initial, param, want) in enumerate(cases):
            k = b"mx%d" % i
            tr = db.create_transaction()
            tr.set(k, initial)
            await tr.commit()
            tr = db.create_transaction()
            tr.atomic_op(k, param, op)
            await tr.commit()
            tr = db.create_transaction()
            got = await tr.get(k)
            assert got == want, (i, op, got, want)
        # compare-and-clear
        tr = db.create_transaction()
        tr.set(b"cc", b"x")
        await tr.commit()
        tr = db.create_transaction()
        tr.atomic_op(b"cc", b"y", COMPARE_AND_CLEAR)
        await tr.commit()
        tr = db.create_transaction()
        assert await tr.get(b"cc") == b"x"   # mismatch: untouched
        tr = db.create_transaction()
        tr.atomic_op(b"cc", b"x", COMPARE_AND_CLEAR)
        await tr.commit()
        tr = db.create_transaction()
        assert await tr.get(b"cc") is None   # match: cleared
        return True

    assert cluster.run(main(), timeout_time=60)


def test_key_selectors(cluster):
    db = cluster.client()

    async def main():
        tr = db.create_transaction()
        for k in (b"a", b"c", b"e", b"g"):
            tr.set(k, b"v" + k)
        await tr.commit()
        tr = db.create_transaction()
        assert await tr.get_key(KeySelector.first_greater_or_equal(b"c")) == b"c"
        assert await tr.get_key(KeySelector.first_greater_than(b"c")) == b"e"
        assert await tr.get_key(KeySelector.last_less_than(b"c")) == b"a"
        assert await tr.get_key(KeySelector.last_less_or_equal(b"c")) == b"c"
        assert await tr.get_key(KeySelector.last_less_or_equal(b"d")) == b"c"
        assert await tr.get_key(KeySelector.first_greater_or_equal(b"zz")) == b"\xff"
        assert await tr.get_key(KeySelector.last_less_than(b"a")) == b""
        # offsets walk present keys
        assert await tr.get_key(KeySelector(b"a", True, 2)) == b"e"
        # selector-bounded range
        got = await tr.get_range(KeySelector.first_greater_than(b"a"),
                                 KeySelector.first_greater_or_equal(b"g"))
        assert [k for k, _ in got] == [b"c", b"e"]
        return True

    assert cluster.run(main(), timeout_time=30)


def test_reverse_and_limited_ranges(cluster):
    db = cluster.client()

    async def main():
        tr = db.create_transaction()
        for i in range(10):
            tr.set(b"r%02d" % i, b"%d" % i)
        await tr.commit()
        tr = db.create_transaction()
        fwd = await tr.get_range(b"r", b"s", limit=3)
        assert [k for k, _ in fwd] == [b"r00", b"r01", b"r02"]
        rev = await tr.get_range(b"r", b"s", limit=3, reverse=True)
        assert [k for k, _ in rev] == [b"r09", b"r08", b"r07"]
        return True

    assert cluster.run(main(), timeout_time=30)


def test_watch_fires_on_change(cluster):
    db = cluster.client()
    db2 = cluster.client("other")

    async def main():
        tr = db.create_transaction()
        tr.set(b"w", b"0")
        w = tr.watch(b"w")
        await tr.commit()
        assert not w.is_ready

        async def later_write():
            await flow.delay(0.5)
            tr2 = db2.create_transaction()
            tr2.set(b"w", b"1")
            await tr2.commit()

        flow.spawn(later_write())
        fired_at = await w
        assert fired_at > 0
        tr3 = db.create_transaction()
        assert await tr3.get(b"w") == b"1"
        return True

    assert cluster.run(main(), timeout_time=60)


def test_watch_cancelled_on_failed_commit(cluster):
    db = cluster.client()
    db2 = cluster.client("other")

    async def main():
        setup = db.create_transaction()
        setup.set(b"k", b"0")
        await setup.commit()
        t1 = db.create_transaction()
        t2 = db2.create_transaction()
        await t1.get(b"k")
        await t2.get(b"k")
        t1.set(b"k", b"1")
        t2.set(b"k", b"2")
        w = t2.watch(b"w2")
        await t1.commit()
        try:
            await t2.commit()
        except flow.FdbError:
            pass
        assert w.is_ready and w.is_error
        assert w.exception().name == "transaction_cancelled"
        return True

    assert cluster.run(main(), timeout_time=60)


def test_versionstamped_key_and_value(cluster):
    db = cluster.client()

    async def main():
        tr = db.create_transaction()
        # key = prefix + 10-byte placeholder; offset (4B LE) = len(prefix)
        key = b"log/" + b"\x00" * 10 + struct.pack("<I", 4)
        tr.atomic_op(key, b"entry1", SET_VERSIONSTAMPED_KEY)
        await tr.commit()
        stamp = tr.get_versionstamp()
        assert len(stamp) == 10
        tr = db.create_transaction()
        got = await tr.get_range(b"log/", b"log0")
        assert got == [(b"log/" + stamp, b"entry1")]

        # versionstamped value
        tr = db.create_transaction()
        val = b"v:" + b"\x00" * 10 + struct.pack("<I", 2)
        tr.atomic_op(b"vs", val, SET_VERSIONSTAMPED_VALUE)
        await tr.commit()
        stamp2 = tr.get_versionstamp()
        tr = db.create_transaction()
        assert await tr.get(b"vs") == b"v:" + stamp2
        assert stamp2 > stamp  # stamps are monotone in commit order
        return True

    assert cluster.run(main(), timeout_time=30)


def test_atomic_in_range_read(cluster):
    db = cluster.client()

    async def main():
        tr = db.create_transaction()
        tr.set(b"q1", le8(1))
        await tr.commit()
        tr = db.create_transaction()
        tr.atomic_op(b"q1", le8(10), ADD_VALUE)   # existing key
        tr.atomic_op(b"q2", le8(5), ADD_VALUE)    # materializes
        got = await tr.get_range(b"q", b"r")
        assert got == [(b"q1", le8(11)), (b"q2", le8(5))]
        await tr.commit()
        tr = db.create_transaction()
        assert await tr.get_range(b"q", b"r") == \
            [(b"q1", le8(11)), (b"q2", le8(5))]
        return True

    assert cluster.run(main(), timeout_time=30)


def test_size_limits_enforced_client_side():
    """(ref: NativeAPI key/value/transaction size checks)"""
    import pytest

    from foundationdb_tpu import flow
    from foundationdb_tpu.server import SimCluster

    c = SimCluster(seed=95)
    try:
        db = c.client()

        async def main():
            tr = db.create_transaction()
            with pytest.raises(flow.FdbError) as ei:
                tr.set(b"k" * 10_001, b"v")
            assert ei.value.name == "key_too_large"
            with pytest.raises(flow.FdbError) as ei:
                tr.set(b"k", b"v" * 100_001)
            assert ei.value.name == "value_too_large"
            tr2 = db.create_transaction()
            with pytest.raises(flow.FdbError) as ei:
                for i in range(200):
                    tr2.set(b"big%03d" % i, b"x" * 99_000)
            assert ei.value.name == "transaction_too_large"
            return True

        assert c.run(main(), timeout_time=60)
    finally:
        c.shutdown()
