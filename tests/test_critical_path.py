"""Latency-forensics pins (ISSUE 18).

The contracts: (1) the pure pieces behave — dominant-station tiebreak
follows path order, the decaying cause table forgets, the CPU-share
fold groups task rows by role, a ProcessMetrics sample always carries
the full field vocabulary; (2) the flight recorder is a bounded ring
that auto-dumps on SevError with a hard cap on unattended dumps;
(3) the default CRITICAL_PATH=0 posture adds NOTHING — disabled status
stanzas, no CC table, a disarmed recorder, and same-seed runs stay
bit-identical across digest/steps/messages; (4) armed, EVERY commit
batch decomposes into consecutive pipeline stations whose segments
telescope to the end-to-end latency within the pinned tolerance;
(5) an injected tlog fsync stall — via the knob or via a clogged tlog
NIC — is ATTRIBUTED: tlog_fsync dominates the per-commit counts and
the decayed cause table; (6) the cli `path`/`flightrec` views render;
(7) tools/tracemerge.py decomposes merged cross-process chains into
the same station vocabulary offline.
"""

import json
import os

from foundationdb_tpu import flow
from foundationdb_tpu.client import run_transaction
from foundationdb_tpu.flow import trace as trace_mod
from foundationdb_tpu.flow.flightrec import (AUTO_DUMP_SEVERITY,
                                             MAX_AUTO_DUMPS,
                                             FlightRecorder)
from foundationdb_tpu.server import SimCluster
from foundationdb_tpu.server.chaos import database_digest
from foundationdb_tpu.server.critical_path import (STATIONS,
                                                   CriticalPathTable,
                                                   dominant_station)
from foundationdb_tpu.server.process_metrics import (SAMPLE_FIELDS,
                                                     ProcessMetrics,
                                                     role_cpu_share)
from foundationdb_tpu.tools import tracemerge
from foundationdb_tpu.tools.cli import Cli


# -- pure pieces -----------------------------------------------------------

def test_dominant_station_path_order_tiebreak():
    assert dominant_station({s: 0.0 for s in STATIONS}) == STATIONS[0]
    segs = {s: 0.001 for s in STATIONS}
    segs["tlog_fsync"] = 0.5
    assert dominant_station(segs) == "tlog_fsync"
    # an exact tie resolves to the EARLIER pipeline station — stable
    # attribution, never dict-order luck
    tie = {s: 0.0 for s in STATIONS}
    tie["commit_version"] = tie["reply"] = 0.25
    assert dominant_station(tie) == "commit_version"


def test_cause_table_decays_and_ranks():
    t = CriticalPathTable(half_life=10.0)
    t.record("tlog_fsync", 0.08, now=0.0)
    t.record("resolve", 0.01, now=0.0)
    top = t.top(now=0.0)
    assert top[0]["station"] == "tlog_fsync"
    assert top[0]["count"] == 1 and top[0]["seconds"] > 0
    # ten half-lives later the old cause has decayed ~1024x: fresh
    # evidence for another station takes rank 0
    t.record("resolve", 0.01, now=100.0)
    assert t.top(now=100.0)[0]["station"] == "resolve"


def test_role_cpu_share_folds_task_rows():
    rows = [{"task": "proxy.commit", "busy_us": 600},
            {"task": "proxy.grv", "busy_us": 150},
            {"task": "resolver-e3-1.batch", "busy_us": 200},
            {"task": "tlog.push", "busy_us": 50}]
    share = role_cpu_share(rows)
    assert share["proxy"] == 0.75
    assert share["resolver"] == 0.2
    assert share["tlog"] == 0.05
    assert list(share) == ["proxy", "resolver", "tlog"]  # heaviest first
    assert role_cpu_share([]) == {}
    assert role_cpu_share(None) == {}


def test_process_metrics_sample_shape():
    m = ProcessMetrics(role="tester")
    s1 = m.sample()
    for field in SAMPLE_FIELDS:
        assert field in s1, field
    assert s1["role"] == "tester" and s1["pid"] == os.getpid()
    assert s1["samples"] == 1
    m.observe_loop_lag(0.002)
    s2 = m.sample()
    assert s2["loop_lag_ms"] == 2.0
    assert s2["samples"] == 2
    assert s2["cpu_seconds"] >= s1["cpu_seconds"]


# -- flight recorder (pure, tmp_path) --------------------------------------

def test_flightrec_ring_is_bounded():
    rec = FlightRecorder()
    rec.arm(size=4)
    for i in range(10):
        rec.note({"Type": "Ev", "N": i})
    st = rec.status()
    assert st == {"armed": 1, "size": 4, "buffered": 4, "noted": 10,
                  "dumps": 0}
    assert [e["N"] for e in rec.snapshot()] == [6, 7, 8, 9]
    rec.disarm()
    assert rec.status()["armed"] == 0 and rec.status()["buffered"] == 0


def test_flightrec_dump_and_auto_dump_cap(tmp_path):
    rec = FlightRecorder()
    rec.arm(size=8, dump_dir=str(tmp_path), name="tester.1")
    rec.note({"Type": "Before", "Severity": 10})
    path = rec.dump(reason="manual")
    assert path and os.path.exists(path)
    rows = [json.loads(line) for line in open(path)]
    assert rows[0]["Type"] == "FlightRecorderDump"
    assert rows[0]["Reason"] == "manual" and rows[0]["Events"] == 1
    assert rows[1]["Type"] == "Before"
    # a SevError note auto-dumps, but only MAX_AUTO_DUMPS times — a
    # crash loop must not fill the disk
    for i in range(MAX_AUTO_DUMPS + 3):
        rec.note({"Type": "Boom", "Severity": AUTO_DUMP_SEVERITY,
                  "N": i})
    assert rec.status()["dumps"] == 1 + MAX_AUTO_DUMPS
    # every dump got a distinct numbered file
    assert len({os.path.basename(p) for p in rec.dumps}) == \
        1 + MAX_AUTO_DUMPS
    # dumping with nowhere to write is a no-op, never a crash
    bare = FlightRecorder()
    bare.arm(size=2)
    bare.note({"Type": "X"})
    assert bare.dump() is None


def test_flightrec_rides_trace_emit(tmp_path):
    """The live wiring: while armed, every TraceCollector.emit lands in
    the ring; a SevError event dumps it."""
    rec = flow.g_flightrec
    prev = (rec.armed, rec.dump_dir, rec.name)
    rec.arm(size=32, dump_dir=str(tmp_path), name="emit.test")
    try:
        trace_mod.TraceEvent("FlightRecPing", "a").detail(K=1).log()
        assert rec.status()["buffered"] >= 1
        trace_mod.TraceEvent("FlightRecBoom", "b",
                             severity=trace_mod.SevError).log()
        dumps = [p for p in os.listdir(str(tmp_path))
                 if p.startswith("flightrec.")]
        assert dumps, os.listdir(str(tmp_path))
        rows = [json.loads(line)
                for line in open(os.path.join(str(tmp_path), dumps[0]))]
        assert rows[0]["Reason"] == "sev_error"
        assert any(r.get("Type") == "FlightRecBoom" for r in rows)
    finally:
        rec.disarm()
        rec.dump_dir, rec.name = prev[1], prev[2]
        if prev[0]:
            rec.arm()


# -- sim: off posture ------------------------------------------------------

def _commit_workload(c, n=30, capture=None):
    db = c.client("cp")

    async def main():
        for i in range(n):
            async def w(tr, i=i):
                tr.set(b"cp/%04d" % i, b"%d" % i)
            await run_transaction(db, w)
        # past CRITICAL_PATH_INTERVAL so the CC fold loop (when armed)
        # drains the proxies' samples into the decaying cause table
        await flow.delay(5.0)
        if capture is not None:
            return await capture(db)
        return True

    return db, main


def test_off_posture_adds_nothing(sim_seed):
    """CRITICAL_PATH=0 (the default): disabled status stanzas, no CC
    table, a disarmed flight recorder, and two same-seed runs stay
    bit-identical — the plane's presence is unobservable until armed."""
    seed = sim_seed(1801)

    def run_off():
        c = SimCluster(seed=seed)
        try:
            async def capture(db):
                status = await db.get_status()
                digest = await database_digest(db)
                return status, digest

            _db, main = _commit_workload(c, n=12, capture=capture)
            status, digest = c.run(main(), timeout_time=600)
            cl = status["cluster"]
            assert cl["critical_path"] == {"enabled": 0}
            assert cl["process_metrics"] == {"enabled": 0}
            assert c.cc.critical_path_table is None
            assert flow.g_flightrec.armed is False
            for p in cl.get("proxies", ()):
                assert "path" not in p, p.keys()
            return digest, c.sched.tasks_run, c.net.messages_sent
        finally:
            c.shutdown()

    a, b = run_off(), run_off()
    assert a == b, "off-posture same-seed runs must stay bit-identical"


# -- sim: armed decomposition ----------------------------------------------

def _armed_status(seed, n=30, **cluster_kw):
    c = SimCluster(seed=seed, critical_path=True, **cluster_kw)
    try:
        async def capture(db):
            return await db.get_status()

        _db, main = _commit_workload(c, n=n, capture=capture)
        status = c.run(main(), timeout_time=600)
        return c, status
    finally:
        c.shutdown()


def test_armed_decomposition_telescopes(sim_seed):
    seed = sim_seed(1802)
    _c, status = _armed_status(seed)
    cl = status["cluster"]
    cp = cl["critical_path"]
    assert cp["enabled"] == 1
    assert cp["samples"] >= 30, cp
    assert cp["samples_folded"] > 0, cp
    # the invariant: per-txn station segments sum to the end-to-end
    # latency within the pinned tolerance (same clock reads on both
    # sides — the residual is exactly zero by construction)
    assert cp["max_residual_seconds"] <= cp["tolerance"], cp
    assert set(cp["station_seconds"]) == set(STATIONS)
    assert sum(cp["dominant"].values()) == cp["samples"], cp
    # per-proxy: station seconds telescope to the e2e band sum
    for p in cl["proxies"]:
        path = p["path"]
        station_sum = sum(ent["seconds"]
                          for ent in path["stations"].values())
        e2e_sum = path["end_to_end"]["sum_seconds"]
        assert abs(station_sum - e2e_sum) <= \
            cp["tolerance"] * max(1.0, e2e_sum), path
    # the role splits observed every commit: wait + service counted
    for role_key in ("resolve", "tlog_fsync"):
        split = cp["splits"][role_key]
        assert split["service"]["total"] > 0, (role_key, split)
        assert split["wait"]["total"] == split["service"]["total"]
    pm = cl["process_metrics"]
    assert pm["enabled"] == 1
    assert pm["host"].get("samples", 0) >= 1, pm
    for field in SAMPLE_FIELDS:
        assert field in pm["host"], field


def test_injected_fsync_stall_is_attributed(sim_seed):
    """TLOG_FSYNC_INJECTION stalls every fsync: the tlog durability
    hop must dominate per-commit, now, and in the decayed table, and
    the tlog's queue-vs-service split must carry the stall as SERVICE
    time (the disk was busy, not the queue)."""
    seed = sim_seed(1803)
    c = SimCluster(seed=seed, critical_path=True, durable=True)
    try:
        flow.SERVER_KNOBS.set("tlog_fsync_injection", 0.004)

        async def capture(db):
            return await db.get_status()

        _db, main = _commit_workload(c, n=30, capture=capture)
        status = c.run(main(), timeout_time=600)
    finally:
        c.shutdown()
    cp = status["cluster"]["critical_path"]
    assert cp["max_residual_seconds"] <= cp["tolerance"], cp
    share = cp["dominant"].get("tlog_fsync", 0) / max(1, cp["samples"])
    assert share >= 0.9, cp["dominant"]
    assert cp["dominant_now"] == "tlog_fsync", cp
    assert cp["top"][0]["station"] == "tlog_fsync", cp["top"]
    split = cp["splits"]["tlog_fsync"]
    assert split["service"]["sum_seconds"] > \
        split["wait"]["sum_seconds"], split


def test_clogged_tlog_nic_is_attributed(sim_seed):
    """The same verdict from a NETWORK cause: clogging the tlog
    machine's inbound side delays the proxy's log push, and the
    decomposition must still name tlog_fsync (the resolve-done ->
    push-acked hop) dominant — cause-agnostic attribution."""
    seed = sim_seed(1804)
    c = SimCluster(seed=seed, critical_path=True)
    try:
        db = c.client("cp")

        async def main():
            from foundationdb_tpu.server import dbinfo as dbi
            while c.cc.dbinfo.get().recovery_state != \
                    dbi.FULLY_RECOVERED:
                await c.cc.dbinfo.on_change()
            machines = {lr.machine
                        for lr in c.cc.dbinfo.get().logs.logs}
            assert machines
            for i in range(24):
                for m in machines:
                    c.net.clog_recv(m, 0.03)

                async def w(tr, i=i):
                    tr.set(b"cp/%04d" % i, b"%d" % i)
                await run_transaction(db, w)
            await flow.delay(5.0)
            return await db.get_status()

        status = c.run(main(), timeout_time=600)
    finally:
        c.shutdown()
    cp = status["cluster"]["critical_path"]
    assert cp["samples"] >= 24, cp
    assert cp["max_residual_seconds"] <= cp["tolerance"], cp
    # roles can share machines in the default topology, so the clog
    # also taxes other hops — the pin is that tlog_fsync is still the
    # SINGLE largest attributed cause, live counts and decayed table
    dom = cp["dominant"]
    assert dom["tlog_fsync"] == max(dom.values()), dom
    assert dom["tlog_fsync"] / max(1, cp["samples"]) >= 0.5, dom
    assert cp["top"][0]["station"] == "tlog_fsync", cp["top"]


def test_armed_same_seed_is_deterministic(sim_seed):
    """The armed plane samples the SIM clock only: two same-seed armed
    runs must produce the identical critical-path document."""
    seed = sim_seed(1805)

    def fingerprint():
        c, status = _armed_status(seed, n=20)
        return (status["cluster"]["critical_path"],
                c.sched.tasks_run, c.net.messages_sent)

    assert fingerprint() == fingerprint()


# -- cli views -------------------------------------------------------------

def test_cli_path_and_flightrec_render(sim_seed, tmp_path):
    seed = sim_seed(1806)
    c = SimCluster(seed=seed, critical_path=True)
    cli = Cli.for_cluster(c)
    try:
        db = c.client("cp")

        async def warm():
            for i in range(15):
                async def w(tr, i=i):
                    tr.set(b"cp/%04d" % i, b"v")
                await run_transaction(db, w)
            await flow.delay(5.0)
            return True

        c.run(warm(), timeout_time=600)
        view = cli.execute("path")
        assert "Critical path" in view, view
        for s in STATIONS:
            assert s in view, (s, view)
        assert "commits decomposed" in view
        rec_view = cli.execute("flightrec")
        assert "armed" in rec_view, rec_view
        dump_view = cli.execute(f"flightrec dump {tmp_path}")
        assert "flightrec." in dump_view, dump_view
        dumped = [p for p in os.listdir(str(tmp_path))
                  if p.startswith("flightrec.")]
        assert len(dumped) == 1, dumped
    finally:
        c.shutdown()


def test_cli_path_renders_disabled_posture(sim_seed):
    c = SimCluster(seed=sim_seed(1807))
    cli = Cli.for_cluster(c)
    try:
        view = cli.execute("path")
        assert "critical-path decomposition off" in view, view
    finally:
        c.shutdown()


# -- tracemerge offline decomposition --------------------------------------

def _merged_doc(chain_spans):
    chains = []
    for i, spans in enumerate(chain_spans):
        rows = [dict(s) for s in spans]
        t0 = min(r["begin"] for r in rows)
        t1 = max(r["end"] for r in rows)
        chains.append({"debug_id": f"d{i}", "begin": t0,
                       "end_to_end_s": round(t1 - t0, 6),
                       "processes": sorted({r["process"]
                                            for r in rows}),
                       "cross_process": True, "spans": rows})
    return {"chains": chains}


def test_tracemerge_path_decomposition():
    def span(loc, proc, begin, end, depth):
        return {"location": loc, "process": proc, "span_id": 1,
                "begin": begin, "end": end, "depth": depth}

    merged = _merged_doc([[
        span("NativeAPI.commit", "client", 0.000, 0.100, 0),
        span("MasterProxyServer.commitBatch", "host", 0.010, 0.090, 1),
        span("Resolver.resolveBatch", "host", 0.020, 0.030, 2),
        span("TLog.tLogCommit", "host", 0.035, 0.085, 2),
    ]])
    doc = tracemerge.path_decomposition(merged)
    assert doc["chains"] == 1 and doc["decomposed"] == 1
    row = doc["rows"][0]
    segs = row["segments"]
    assert abs(segs["client_to_proxy"] - 0.010) < 1e-9
    assert abs(segs["proxy_batcher"] - 0.010) < 1e-9
    assert abs(segs["resolve"] - 0.010) < 1e-9
    assert abs(segs["log_push"] - 0.005) < 1e-9
    assert abs(segs["tlog_fsync"] - 0.050) < 1e-9
    assert abs(segs["reply"] - 0.015) < 1e-9
    # the telescoping invariant: segments sum to the client extent
    assert abs(sum(segs.values()) - row["end_to_end_s"]) <= 1e-6
    assert row["dominant"] == "tlog_fsync"
    assert row["residual_s"] == 0.0
    assert doc["dominant"] == {"tlog_fsync": 1}

    # residual clock skew pushing a boundary BACKWARDS zeroes that
    # station but keeps every segment non-negative and telescoping
    skewed = _merged_doc([[
        span("NativeAPI.commit", "client", 0.000, 0.100, 0),
        span("MasterProxyServer.commitBatch", "host", 0.050, 0.090, 1),
        span("Resolver.resolveBatch", "host", 0.030, 0.040, 2),
        span("TLog.tLogCommit", "host", 0.060, 0.080, 2),
    ]])
    doc2 = tracemerge.path_decomposition(skewed)
    segs2 = doc2["rows"][0]["segments"]
    assert segs2["proxy_batcher"] == 0.0   # resolver "began" earlier
    assert all(v >= 0.0 for v in segs2.values()), segs2
    assert abs(sum(segs2.values())
               - doc2["rows"][0]["end_to_end_s"]) <= 1e-6

    # a chain missing a leg is not a full commit chain: skipped
    partial = _merged_doc([[
        span("NativeAPI.commit", "client", 0.0, 0.1, 0),
        span("MasterProxyServer.commitBatch", "host", 0.01, 0.09, 1),
    ]])
    doc3 = tracemerge.path_decomposition(partial)
    assert doc3["chains"] == 0 and doc3["rows"] == []
