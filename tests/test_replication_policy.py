"""Replication policy algebra + locality-aware recruitment.

Ref: fdbrpc/ReplicationPolicy.h:101-168 (PolicyOne/Across/And trees over
LocalityData), fdbserver/ClusterController recruitment applying the
configured policies to worker placement.
"""

import pytest

from foundationdb_tpu.server.replication_policy import (Locality, PolicyAnd,
                                                        PolicyAcross,
                                                        PolicyOne)


def _cands(spec):
    """spec: list of (name, zoneid, dcid)"""
    return [(name, Locality(processid=name, zoneid=z, dcid=d))
            for name, z, d in spec]


def test_policy_one():
    p = PolicyOne()
    assert p.replica_count() == 1
    assert p.select(_cands([("a", "z1", "dc1")])) == ["a"]
    assert p.select([]) is None
    assert p.validate([Locality(zoneid="z")])
    assert not p.validate([])


def test_policy_across_zones():
    p = PolicyAcross(2, "zoneid", PolicyOne())
    assert p.replica_count() == 2
    team = p.select(_cands([("a", "z1", "dc1"), ("b", "z1", "dc1"),
                            ("c", "z2", "dc1")]))
    assert team == ["a", "c"]  # two distinct zones, candidate order
    # one zone only: unsatisfiable
    assert p.select(_cands([("a", "z1", "dc1"), ("b", "z1", "dc1")])) is None
    assert p.validate([Locality(zoneid="z1"), Locality(zoneid="z2")])
    assert not p.validate([Locality(zoneid="z1"), Locality(zoneid="z1")])


def test_policy_across_nested():
    # two dcs, each with two distinct zones
    p = PolicyAcross(2, "dcid", PolicyAcross(2, "zoneid", PolicyOne()))
    assert p.replica_count() == 4
    spec = [("a", "z1", "dc1"), ("b", "z2", "dc1"),
            ("c", "z3", "dc2"),                      # dc2: one zone only
            ("d", "z4", "dc3"), ("e", "z5", "dc3")]
    team = p.select(_cands(spec))
    # dc2 cannot satisfy the inner policy and is skipped for dc3
    assert team == ["a", "b", "d", "e"]
    assert p.validate([Locality(zoneid="z1", dcid="dc1"),
                       Locality(zoneid="z2", dcid="dc1"),
                       Locality(zoneid="z4", dcid="dc3"),
                       Locality(zoneid="z5", dcid="dc3")])
    assert not p.validate([Locality(zoneid="z1", dcid="dc1"),
                           Locality(zoneid="z2", dcid="dc1"),
                           Locality(zoneid="z3", dcid="dc2")])


def test_policy_and():
    # three replicas AND at least two zones
    p = PolicyAnd([PolicyAcross(3, "processid", PolicyOne()),
                   PolicyAcross(2, "zoneid", PolicyOne())])
    team = p.select(_cands([("a", "z1", "dc1"), ("b", "z1", "dc1"),
                            ("c", "z2", "dc1")]))
    assert team is not None and len(team) == 3
    # three processes but a single zone fails the zone clause
    assert p.select(_cands([("a", "z1", "dc1"), ("b", "z1", "dc1"),
                            ("d", "z1", "dc1")])) is None


def test_missing_attribute_is_skipped():
    p = PolicyAcross(1, "zoneid", PolicyOne())
    assert p.select([("a", Locality(processid="a"))]) is None


def test_recruitment_places_logs_across_machines():
    """n_logs=2 TLogs land on two distinct machines whenever the worker
    pool spans two, across repeated recoveries (ref: tLogPolicy
    placement in recruitEverything)."""
    from foundationdb_tpu.server.cluster import SimCluster

    c = SimCluster(seed=31, n_logs=2, n_workers=5)
    try:
        async def main():
            import foundationdb_tpu.flow as fl
            while c.cc.dbinfo.get().recovery_state != "fully_recovered":
                await c.cc.dbinfo.on_change()
            for _ in range(3):
                info = c.cc.dbinfo.get()
                machines = {lr.machine for lr in info.logs.logs}
                assert len(machines) == 2, info.logs
                c.kill_role("tlog")
                await fl.delay(3.0)
                while c.cc.dbinfo.get().recovery_state != "fully_recovered":
                    await c.cc.dbinfo.on_change()
            return True

        assert c.run(main(), timeout_time=300)
    finally:
        c.shutdown()


def test_recruitment_degrades_on_single_machine():
    """A one-zone pool still recruits (degraded mode) instead of
    stalling recovery."""
    from foundationdb_tpu.server.cluster import SimCluster

    c = SimCluster(seed=32, n_logs=2, n_workers=4)
    try:
        async def main():
            while c.cc.dbinfo.get().recovery_state != "fully_recovered":
                await c.cc.dbinfo.on_change()
            # collapse every registered worker onto one zone: the policy
            # becomes unsatisfiable and selection must fall back instead
            # of raising
            c.cc.workers = {name: wi._replace(machine="onezone")
                            for name, wi in c.cc.workers.items()}
            team = c.cc.pick_workers(2, role="tlog")
            assert len(team) == 2
            assert len(set(map(id, team))) == 2  # still distinct workers
            return True

        assert c.run(main(), timeout_time=60)
    finally:
        c.shutdown()
