"""Packed single-buffer interval feed path (ISSUE 14): bit-exact
parity of the packed feed (one H2D transfer per batch) against the
unpacked multi-transfer baseline and the CPU models — verdicts AND
attribution — across the interval, point, and sharded backends;
out-of-order pipelined drains; capacity growth and version rebasing
mid-window; and the directed feed-path invariants the PR claims:
exactly one counted transfer per batch, allocation-flat staging reuse,
and the no-alias canary the reuse discipline depends on.

The packed path is the DEFAULT (INTERVAL_PACKED_FEED=1); the unpacked
path stays behind the knob as the parity baseline and rollback, which
is exactly what these tests drive it as."""

import random

import numpy as np
import pytest

from foundationdb_tpu.flow.knobs import SERVER_KNOBS
from foundationdb_tpu.models import (
    BruteForceConflictSet,
    PyConflictSet,
    ResolverTransaction,
)
from foundationdb_tpu.models.point_resolver import PointConflictSet
from foundationdb_tpu.models.tpu_resolver import TpuConflictSet, \
    _unaliasable_u32
from foundationdb_tpu.parallel import ShardedTpuConflictSet

MWTLV = 5_000_000


def txn(snapshot, reads=(), writes=()):
    return ResolverTransaction(snapshot, tuple(reads), tuple(writes))


@pytest.fixture
def packed_knob():
    """Flip INTERVAL_PACKED_FEED for a test and restore it after."""
    prev = int(SERVER_KNOBS.interval_packed_feed)

    def set_packed(v):
        SERVER_KNOBS.set("interval_packed_feed", int(v))

    yield set_packed
    SERVER_KNOBS.set("interval_packed_feed", prev)


def rand_batches(seed, n_batches, point=False, n_keys=40, max_txns=10,
                 version_stride=2000, window=5000):
    """[(batch, commit_version, new_oldest_version)]: keys over the
    whole byte range (all sharded splits see traffic), interval widths
    mixed, occasional EMPTY ranges (b == e, must be skipped without a
    slot), empty batches, and snapshots below the window (tooOld)."""
    rng = random.Random(seed)
    out = []
    v = 0

    def key():
        return bytes([rng.randrange(256)]) + b"%02d" % rng.randrange(n_keys)

    def rd():
        k = key()
        if point:
            return (k, k + b"\x00")
        if rng.random() < 0.1:
            return (k, k)          # empty range: contributes no slot
        return (k, k + bytes([rng.randrange(1, 8)]))

    for _ in range(n_batches):
        v += rng.randrange(1, version_stride)
        batch = []
        for _ in range(rng.randrange(0, max_txns)):
            reads = [rd() for _ in range(rng.randrange(0, 3))]
            writes = [rd() for _ in range(rng.randrange(0, 3))]
            snap = max(0, v - rng.randrange(0, 2 * window))
            batch.append(txn(snap, reads, writes))
        out.append((batch, v, max(0, v - window)))
    return out


def run_attributed(cs, batches):
    return [cs.resolve_with_attribution(b, v, o) for b, v, o in batches]


# ---------------------------------------------------------------------------
# packed vs unpacked vs CPU models: bit-exact verdicts + attribution
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("seed", [1, 2, 3])
def test_packed_unpacked_bit_exact_interval(seed, packed_knob):
    batches = rand_batches(seed, 30)
    packed_knob(1)
    got_p = run_attributed(TpuConflictSet(capacity=1 << 10), batches)
    packed_knob(0)
    got_u = run_attributed(TpuConflictSet(capacity=1 << 10), batches)
    assert got_p == got_u
    got_py = run_attributed(PyConflictSet(), batches)
    assert got_p == got_py
    bf = BruteForceConflictSet()
    for (verdicts, _attr), (b, v, o) in zip(got_p, batches):
        assert verdicts == bf.resolve(b, v, o)


@pytest.mark.parametrize("seed", [11, 12])
def test_packed_parity_sharded(seed, packed_knob):
    batches = rand_batches(seed, 20)
    packed_knob(1)
    got_sh = run_attributed(ShardedTpuConflictSet(capacity=1 << 10),
                            batches)
    packed_knob(0)
    got_sh_u = run_attributed(ShardedTpuConflictSet(capacity=1 << 10),
                              batches)
    assert got_sh == got_sh_u
    got_py = run_attributed(PyConflictSet(), batches)
    assert got_sh == got_py


def test_packed_parity_point_backend():
    """The point backend rides the same staging/feed discipline (its
    packed buffer now carries the version scalars too); parity vs the
    interval backend and the CPU model on point-shaped batches."""
    batches = rand_batches(21, 25, point=True)
    got_pt = run_attributed(PointConflictSet(key_bytes=8), batches)
    got_iv = run_attributed(TpuConflictSet(), batches)
    got_py = run_attributed(PyConflictSet(), batches)
    assert got_pt == got_iv == got_py


def test_packed_attribution_with_filtered_and_tooold_ranges(packed_knob):
    """Directed read_map routing: empty ranges BETWEEN real ones shift
    the surviving-slot -> original-range-index mapping, and tooOld txns
    contribute no slots at all — attribution must name the ORIGINAL
    read_ranges indices identically on both feed paths."""
    probe = [
        # earlier writer in the same batch (intra-batch dependency)
        txn(150, writes=[(b"\x12", b"\x13"), (b"\x85", b"\x86")]),
        # reader: range 0 EMPTY (skipped — no slot), ranges 1 and 3
        # hit the writer, range 2 clean
        txn(150, reads=[(b"\x30", b"\x30"), (b"\x12", b"\x13"),
                        (b"\x40", b"\x41"), (b"\x85", b"\x86")]),
        # tooOld: snapshot below the advanced window
        txn(50, reads=[(b"\x12", b"\x13")]),
    ]
    out = {}
    for knob in (1, 0):
        packed_knob(knob)
        cs = TpuConflictSet()
        cs.resolve([], 100, 120)         # advance the MVCC window
        verdicts, attr = cs.resolve_with_attribution(probe, 200, 120)
        out[knob] = (verdicts, attr)
    assert out[1] == out[0]
    verdicts, attr = out[1]
    assert verdicts == [2, 0, 1]         # COMMITTED, CONFLICT, TOO_OLD
    assert attr[0] == ()
    assert attr[1] == (1, 3)             # ORIGINAL range indices
    assert attr[2] == ()


@pytest.mark.parametrize("backend", ["interval", "sharded"])
def test_growth_and_rebase_mid_window_packed(backend, packed_knob):
    """Capacity growth (tiny initial cap) and a >2^30 version jump land
    mid-stream on the packed path; verdicts stay identical to the
    unpacked path and the CPU model throughout."""
    rng = random.Random(99)
    batches = []
    v = 0
    for i in range(12):
        # huge strides force _prepare_versions re-basing; tiny cap
        # forces _grow under the packed feed
        v += rng.randrange(1, 300_000_000)
        batch = [txn(max(0, v - rng.randrange(0, MWTLV)),
                     [(bytes([rng.randrange(250)]), bytes([251]))],
                     [(bytes([rng.randrange(250)]), bytes([251]))])
                 for _ in range(rng.randrange(1, 6))]
        batches.append((batch, v, max(0, v - MWTLV)))

    def mk():
        if backend == "interval":
            return TpuConflictSet(capacity=1 << 10)
        return ShardedTpuConflictSet(capacity=1 << 10)

    packed_knob(1)
    got_p = run_attributed(mk(), batches)
    packed_knob(0)
    got_u = run_attributed(mk(), batches)
    assert got_p == got_u
    assert got_p == run_attributed(PyConflictSet(), batches)


def test_pipeline_out_of_order_drain_packed(packed_knob):
    """Submit/drain parity through the packed feed: a full in-flight
    window drained in REVERSE order must match the serial unpacked
    resolve (tickets are idempotent and order-free; history chains on
    device either way)."""
    packed_knob(1)
    SERVER_KNOBS.set("resolve_pipeline_depth", 4)
    try:
        batches = rand_batches(31, 12, max_txns=6)
        cs = TpuConflictSet(capacity=1 << 10)
        results = {}
        pending = []
        for i, (b, v, o) in enumerate(batches):
            pending.append((i, cs.submit(b, v, o)))
            if len(pending) == 3:
                for j, t in reversed(pending):
                    results[j] = cs.drain(t)
                pending.clear()
        for j, t in reversed(pending):
            results[j] = cs.drain(t)
        packed_knob(0)
        serial = TpuConflictSet(capacity=1 << 10)
        for i, (b, v, o) in enumerate(batches):
            assert results[i] == serial.resolve(b, v, o), i
    finally:
        SERVER_KNOBS.set("resolve_pipeline_depth",
                         SERVER_KNOBS._defaults["RESOLVE_PIPELINE_DEPTH"])


# ---------------------------------------------------------------------------
# directed feed-path invariants: counted transfers, staging reuse, no-alias
# ---------------------------------------------------------------------------

def test_one_transfer_per_batch_counted(packed_knob):
    packed_knob(1)
    cs = TpuConflictSet()
    batches = rand_batches(41, 15, max_txns=6)
    for b, v, o in batches:
        cs.resolve(b, v, o)
    st = cs.kernel_stats()
    dispatched = st["batches"]
    assert dispatched > 0
    assert st["h2d"]["transfers"] == dispatched
    assert st["h2d"]["per_batch"] == 1.0
    assert st["h2d"]["bytes"] > 0


def test_unpacked_fallback_counts_many_transfers(packed_knob):
    """The fallback really is the multi-transfer path — ~12 counted
    H2D per batch — so the packed counter's ==1 is meaningful."""
    packed_knob(0)
    cs = TpuConflictSet()
    for b, v, o in rand_batches(42, 6, max_txns=6):
        cs.resolve(b, v, o)
    st = cs.kernel_stats()
    assert st["batches"] > 0
    assert st["h2d"]["per_batch"] >= 10


def test_staging_allocation_flat(packed_knob):
    """Steady-state same-shape batch stream: staging allocations stop
    once the rotating pool (pipeline depth + 2) and the encode scratch
    exist, while transfers keep climbing 1:1 with batches."""
    packed_knob(1)
    cs = TpuConflictSet()
    rng = random.Random(5)

    def batch(v):
        return [txn(max(0, v - 500),
                    [(bytes([rng.randrange(200)]), bytes([201]))],
                    [(bytes([rng.randrange(200)]), bytes([201]))])
                for _ in range(4)]

    v = 0
    for _ in range(8):     # warmup: fills the rotating pool
        v += 100
        cs.resolve(batch(v), v, max(0, v - 5000))
    warm = cs.kernel_stats()["h2d"]["staging_allocs"]
    assert warm > 0
    for _ in range(20):
        v += 100
        cs.resolve(batch(v), v, max(0, v - 5000))
    st = cs.kernel_stats()
    assert st["h2d"]["staging_allocs"] == warm, \
        "steady-state batches must not allocate staging"
    assert st["h2d"]["transfers"] == st["batches"]


def test_staging_buffer_never_aliased_by_device():
    """THE invariant staging reuse depends on: a transferred staging
    buffer must be COPIED, never zero-copy aliased, by the device
    runtime — _unaliasable_u32 forces that by handing jax a deliberately
    unaligned buffer. If a future jax aliases it anyway, this canary
    fails loudly instead of letting reuse corrupt in-flight batches."""
    import jax.numpy as jnp
    buf = _unaliasable_u32(4096)
    assert buf.ctypes.data % 64 == 4      # off-alignment by construction
    buf[:] = 7
    dev = jnp.asarray(buf)
    buf[:] = 9                            # mutate AFTER the transfer
    assert int(np.asarray(dev)[0]) == 7, \
        "device runtime aliased the staging buffer"


def test_resolve_arrays_rides_packed_path(packed_knob):
    """The pre-encoded bench/pipeline entry (resolve_arrays) uses the
    same packed feed: one transfer per batch, and verdicts identical
    to the unpacked knob setting."""
    from foundationdb_tpu.ops.keys import encode_keys

    def arrays(seed, v):
        rng = np.random.default_rng(seed)
        n = 8
        ks = rng.integers(0, 30, size=2 * n)
        enc = encode_keys([b"%02d" % k for k in ks], 8)
        ends = enc.copy()
        ends[:, -1] += 1       # end = key + b"\x00"
        snapshots = np.full(n, v - 50, np.int64)
        has_reads = np.ones(n, bool)
        ids = np.arange(n, dtype=np.int32)
        return (snapshots, has_reads, enc[:n], ends[:n], ids,
                enc[n:], ends[n:], ids)

    outs = {}
    for knob in (1, 0):
        packed_knob(knob)
        cs = TpuConflictSet(key_bytes=8)
        got = []
        for i in range(6):
            v = 100 * (i + 1)
            conflict, too_old = cs.resolve_arrays(
                *arrays(i, v), commit_version=v, new_oldest_version=0)
            got.append((np.asarray(conflict)[:8].tolist(),
                        np.asarray(too_old).tolist()))
        outs[knob] = got
        if knob == 1:
            st = cs.kernel_stats()
            assert st["h2d"]["transfers"] == st["batches"] == 6
    assert outs[1] == outs[0]
