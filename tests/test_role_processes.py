"""Role-per-process commit pipeline (ISSUE 19): a REAL externally-
hosted resolver — its own OS process behind fixed TCP tokens
(tools/rolehost.py) — driven over rpc/tcp.py and held bit-identical
to the in-process oracle.

Directed parity: the same randomized batch stream (tooOld, degenerate
and empty ranges included — the test_resolver_splits discipline) is
sent over the wire to the TCP-hosted resolver AND resolved by an
in-process PyConflictSet; verdicts and per-transaction attribution
unions must match exactly at every batch.

Chaos: kill -9 of the live resolver process, respawn on the pinned
port, and the recovery plane (checkpoint + gapless journal replay)
must restore the version chain and the duplicate-delivery reply cache
— a resend of the last pre-kill batch returns the bit-identical
cached payload (the digest-consistency property: no divergent verdict
can ever have been exposed), and the continued chain keeps oracle
parity through the respawn.

Ref: fdbserver Resolver.actor.cpp resolveBatch ordering + the
reference's per-role fdbserver processes (one process per recruited
role); recovery via the PR 5 checkpoint/replay discipline moved
across the process boundary.
"""

import random

import pytest

from foundationdb_tpu import flow
from foundationdb_tpu.models import PyConflictSet
from foundationdb_tpu.models.conflict_set import ResolverTransaction
from foundationdb_tpu.server.proxy import MWTLV
from foundationdb_tpu.server.types import (CommitRequest, ResolveReply,
                                           ResolveRequest)
from foundationdb_tpu.tools.clusterbench import RoleProcs
from foundationdb_tpu.tools.rolehost import ExternalRoles


def _rand_batches(seed, n_batches, max_txns=5):
    """Randomized ordered batch stream: versions march far enough to
    move the MVCC window (read snapshots sometimes fall below
    version - MWTLV -> tooOld), ranges sometimes degenerate/empty."""
    rng = random.Random(seed)
    out = []
    v = int(MWTLV)          # window moves from the very first batch

    def key():
        return bytes([rng.randrange(1, 250)]) + b"%02d" % rng.randrange(30)

    def rd():
        k = key()
        if rng.random() < 0.1:
            return (k, k)                   # degenerate (empty) range
        return (k, k + bytes([rng.randrange(1, 8)]))

    prev = 0
    for _ in range(n_batches):
        v += rng.randrange(1, MWTLV // 3)
        batch = []
        for _ in range(rng.randrange(1, max_txns)):
            reads = tuple(rd() for _ in range(rng.randrange(0, 3)))
            writes = tuple(rd() for _ in range(rng.randrange(0, 3)))
            snap = max(0, v - rng.randrange(0, 2 * MWTLV))
            batch.append(CommitRequest(snap, reads, writes, (),
                                       report_conflicting_keys=True))
        out.append((prev, v, tuple(batch)))
        prev = v
    return out


def _oracle_resolve(oracle, batch, version):
    """The resolver role's exact per-batch semantics run in-process:
    same backend, same window advance, attribution mapped to the
    transactions' actual read ranges (resolver_role._build_payload)."""
    txns = [ResolverTransaction(t.read_snapshot, t.read_conflict_ranges,
                                t.write_conflict_ranges) for t in batch]
    oldest = max(0, version - MWTLV)
    verdicts, attr = oracle.resolve_with_attribution(txns, version, oldest)
    ranges = tuple(tuple(batch[i].read_conflict_ranges[j] for j in idxs)
                   for i, idxs in enumerate(attr))
    return list(verdicts), ranges


async def _send(ref, prev, version, batch, timeout=30.0):
    reply = await flow.timeout_error(
        ref.get_reply(ResolveRequest(prev, version, batch)), timeout)
    assert isinstance(reply, ResolveReply), reply
    return reply


def _run(body, timeout=120.0):
    """Wall-clock harness (the networktest discipline): host a real-
    time loop for real sockets, restore the ambient scheduler after."""
    flow.set_seed(0)
    s = flow.Scheduler(virtual=False)
    flow.set_scheduler(s)
    try:
        t = s.spawn(body())
        return s.run(until=t, timeout_time=timeout)
    finally:
        flow.set_scheduler(None)


def test_tcp_resolver_matches_in_process_oracle(tmp_path):
    """Every batch's verdicts AND attribution unions from the
    TCP-hosted resolver process are bit-identical to the in-process
    oracle's — the across-the-wire half of the split-ensemble parity
    contract."""
    roles = RoleProcs(n_resolvers=1, run_dir=str(tmp_path), seed=41)
    ext = None
    try:
        roles.spawn_all().wait_ready()
        ext = roles.external_roles()
        oracle = PyConflictSet()
        batches = _rand_batches(424242, 30)

        async def body():
            resolves, _m, _h = await ext.recruit_resolver(
                0, "parity-r0", recovery_version=0, backend="python")
            for prev, v, batch in batches:
                reply = await _send(resolves, prev, v, batch)
                want_v, want_r = _oracle_resolve(oracle, batch, v)
                assert list(reply.verdicts) == want_v, (v, reply)
                assert tuple(tuple(sorted(r))
                             for r in reply.conflicting_ranges) == \
                    tuple(tuple(sorted(r)) for r in want_r), (v, reply)
            return True

        assert _run(body)
    finally:
        if ext is not None:
            ext.close()
        roles.terminate_all()


def test_kill9_recovers_checkpoint_replay_and_reply_cache(tmp_path):
    """SIGKILL the live resolver process mid-chain: the respawn (same
    port) restores state from checkpoint + journal replay, a duplicate
    delivery of the last pre-kill batch returns the bit-identical
    cached payload, and the continued version chain keeps oracle
    parity — so no client-visible verdict can diverge across the
    crash (the database-digest consistency property, directed)."""
    run_dir = str(tmp_path)
    roles = RoleProcs(n_resolvers=1, run_dir=run_dir,
                      state_root=str(tmp_path / "state"), seed=43,
                      checkpoint_every=0.2)
    ext = None
    try:
        roles.spawn_all().wait_ready()
        assert roles.ready[("resolver", 0)]["recovered"] is False
        ext = roles.external_roles()
        oracle = PyConflictSet()
        batches = _rand_batches(31338, 24)
        pre, post = batches[:16], batches[16:]
        seen = []

        async def phase_a():
            resolves, _m, _h = await ext.recruit_resolver(
                0, "chaos-r0", recovery_version=0, backend="python")
            for prev, v, batch in pre:
                reply = await _send(resolves, prev, v, batch)
                want_v, _r = _oracle_resolve(oracle, batch, v)
                assert list(reply.verdicts) == want_v, (v, reply)
                seen.append(reply)
            # let the wall-clock checkpoint actor land at least one
            # checkpoint with the pipeline idle, so the recovery below
            # exercises checkpoint restore + replay of the tail —
            # not a cold full-journal replay
            await flow.delay(0.6)
            return True

        assert _run(phase_a)
        ext.close()
        ext = None

        # pre-kill evidence: every batch journaled, and the wall-clock
        # checkpoint actor landed at least one checkpoint — so the
        # recovery below restores from checkpoint and replays only the
        # (possibly empty) journal tail above it
        from foundationdb_tpu.tools import exporter
        pre_docs = exporter.fetch_process_docs(
            run_dir, stubs=roles.status_stubs())
        pre_ctr = pre_docs[0]["counters"]
        assert pre_ctr["journaled"] >= len(pre), pre_ctr
        assert pre_ctr["checkpoints"] >= 1, pre_ctr

        dead = roles.kill("resolver", 0)
        roles.respawn("resolver", 0)
        roles.wait_ready()
        rdoc = roles.ready[("resolver", 0)]
        assert rdoc["pid"] != dead
        assert rdoc["recovered"] is True      # journaled state found
        ext = ExternalRoles([rdoc], [])

        async def phase_b():
            resolves = ext._ref(rdoc, "resolves")
            # duplicate delivery of the last pre-kill batch: the
            # recovered reply cache must answer bit-identically
            prev, v, batch = pre[-1]
            dup = await _send(resolves, prev, v, batch)
            assert dup == seen[-1], (dup, seen[-1])
            # the chain continues gaplessly through the respawn
            for prev, v, batch in post:
                reply = await _send(resolves, prev, v, batch)
                want_v, want_r = _oracle_resolve(oracle, batch, v)
                assert list(reply.verdicts) == want_v, (v, reply)
                assert tuple(tuple(sorted(r))
                             for r in reply.conflicting_ranges) == \
                    tuple(tuple(sorted(r)) for r in want_r), (v, reply)
            return True

        assert _run(phase_b)

        # the recovery actually ran the recovery plane: the respawned
        # incarnation (counters reset at boot) reports the restored —
        # and then continued — chain position, and journals the
        # post-kill batches into its own segment
        docs = exporter.fetch_process_docs(run_dir,
                                           stubs=roles.status_stubs())
        assert len(docs) == 1 and docs[0]["up"] == 1, docs
        assert docs[0]["version"] == post[-1][1], docs[0]
        ctr = docs[0]["counters"]
        assert ctr["requests"] >= len(post), ctr
        assert ctr["journaled"] >= len(post), ctr
    finally:
        if ext is not None:
            ext.close()
        roles.terminate_all()


def test_resolver_process_rejects_unknown_control_op(tmp_path):
    """The control endpoint's error path: an unknown op answers
    client_invalid_operation instead of wedging the stream, and the
    process keeps serving afterwards (ping)."""
    roles = RoleProcs(n_resolvers=1, run_dir=str(tmp_path), seed=47)
    ext = None
    try:
        roles.spawn_all().wait_ready()
        ext = roles.external_roles()
        entry = roles.ready[("resolver", 0)]

        async def body():
            ctrl = ext._ref(entry, "control")
            with pytest.raises(flow.FdbError) as ei:
                await flow.timeout_error(
                    ctrl.get_reply({"type": "no_such_op"}), 30.0)
            assert ei.value.name == "client_invalid_operation"
            pong = await flow.timeout_error(
                ctrl.get_reply({"type": "ping"}), 30.0)
            assert pong["ok"] and pong["ready"] is False
            flushed = await flow.timeout_error(
                ctrl.get_reply({"type": "trace_flush"}), 30.0)
            assert flushed["ok"]
            return True

        assert _run(body)
    finally:
        if ext is not None:
            ext.close()
        roles.terminate_all()
