"""\xff system keyspace + API version gating.

Ref: fdbclient/SystemData.cpp (keyServers/, conf/, excluded/ prefixes),
system-key write protection (key_outside_legal_range without
ACCESS_SYSTEM_KEYS), fdb.api_version selection.
"""

import pytest

import foundationdb_tpu.bindings as fdb
from foundationdb_tpu import flow
from foundationdb_tpu.client import run_transaction
from foundationdb_tpu.server import SimCluster


def test_system_keyspace_reads_and_write_protection():
    c = SimCluster(seed=51, n_storage=2, storage_replicas=2)
    try:
        db = c.client()

        async def main():
            async def seed(tr):
                tr.set(b"user", b"row")
            await run_transaction(db, seed)

            tr = db.create_transaction()
            # \xff reads are gated (ref: key_outside_legal_range
            # without READ/ACCESS_SYSTEM_KEYS)
            with pytest.raises(flow.FdbError) as ei:
                await tr.get(b"\xff/keyServers/user")
            assert ei.value.name == "key_outside_legal_range"
            with pytest.raises(flow.FdbError):
                await tr.get_range(b"\xff/conf/", b"\xff/conf0")
            tr.set_option("read_system_keys")
            # keyServers: one row per shard, value = the replica team
            rows = await tr.get_range(b"\xff/keyServers/",
                                      b"\xff/keyServers0")
            assert len(rows) == 2
            assert rows[0][0] == b"\xff/keyServers/"
            for _k, team in rows:
                assert len(team.split(b",")) == 2
            # point lookup: the team owning an arbitrary user key
            team = await tr.get(b"\xff/keyServers/user")
            assert team == rows[0][1] or team == rows[1][1]

            # conf rows are REAL stored rows now, seeded by the CC
            # after recovery (VERDICT r4 Missing #7) — poll with fresh
            # read versions until the seed transaction lands
            for _ in range(100):
                tr2 = db.create_transaction()
                tr2.set_option("read_system_keys")
                conf = dict(await tr2.get_range(b"\xff/conf/",
                                                b"\xff/conf0"))
                if conf:
                    break
                await flow.delay(0.2)
            assert conf[b"\xff/conf/storage_shards"] == b"2"
            assert conf[b"\xff/conf/proxies"] == b"1"

            # exclusion shows up under \xff/excluded/ — committed data,
            # so a FRESH read version is needed to observe it
            info = c.cc.dbinfo.get()
            victim = None
            for name, wi in c.cc.workers.items():
                if not any(rn.startswith(("storage", "tlog", "proxy",
                                          "resolver", "ratekeeper"))
                           for rn in wi.worker.roles):
                    victim = name
                    break
            if victim is not None:
                await db.exclude(victim)
                tr3 = db.create_transaction()
                tr3.set_option("read_system_keys")
                rows = await tr3.get_range(b"\xff/excluded/",
                                           b"\xff/excluded0")
                assert (b"\xff/excluded/" + victim.encode(), b"") in rows

            # system keys are write-protected
            with pytest.raises(flow.FdbError) as ei:
                tr.set(b"\xff/conf/proxies", b"9")
            assert ei.value.name == "key_outside_legal_range"
            with pytest.raises(flow.FdbError):
                tr.clear_range(b"\xff", b"\xff\xff")
            with pytest.raises(flow.FdbError):
                tr.atomic_op(b"\xff/x", b"\x01", 2)

            # the user-space scan convention b"" .. b"\xff" is untouched
            user = await tr.get_range(b"", b"\xff")
            assert user == [(b"user", b"row")]
            # with the option, a scan crossing the \xff boundary sees
            # the same materialized rows an \xff-anchored scan serves
            crossing = await tr.get_range(b"", b"\xff/keyServers0")
            assert (b"user", b"row") in crossing
            anchored = await tr.get_range(b"\xff/keyServers/",
                                          b"\xff/keyServers0")
            for row in anchored:
                assert row in crossing
            return True

        assert c.run(main(), timeout_time=120)
    finally:
        c.shutdown()


def test_api_version_selection():
    fdb._selected_api_version = None
    with pytest.raises(RuntimeError):
        fdb.api_version(200)     # out of range
    fdb.api_version(710)
    fdb.api_version(710)         # idempotent re-selection is fine
    with pytest.raises(RuntimeError):
        fdb.api_version(630)     # conflicting re-selection is not
    fdb._selected_api_version = None


def test_clear_range_cannot_reach_system_space():
    """A clear whose END crosses \xff must be rejected — it would wipe
    the storage engine's own \xff\xff metadata (review finding)."""
    c = SimCluster(seed=52, durable=True)
    try:
        db = c.client()

        async def main():
            tr = db.create_transaction()
            tr.set(b"safe", b"1")
            await tr.commit()
            tr.reset()
            with pytest.raises(flow.FdbError) as ei:
                tr.clear_range(b"b", b"\xff\xffzz")
            assert ei.value.name == "key_outside_legal_range"
            # the legal full-wipe bound is untouched
            tr.clear_range(b"", b"\xff")
            await tr.commit()
            return True

        assert c.run(main(), timeout_time=60)
    finally:
        c.shutdown()


def test_access_system_keys_option_and_stored_subspace():
    """ACCESS_SYSTEM_KEYS admits \xff\x02 stored-system writes (the
    latency-probe subspace); without it they reject; \xff\xff engine
    space rejects always; user scans never see system rows."""
    c = SimCluster(seed=53, durable=True)
    try:
        db = c.client()

        async def main():
            tr = db.create_transaction()
            with pytest.raises(flow.FdbError):
                tr.set(b"\xff\x02/own", b"x")       # no option
            tr.set_option("access_system_keys")
            tr.set(b"\xff\x02/own", b"x")           # option: allowed
            with pytest.raises(flow.FdbError):
                tr.set(b"\xff\xff/engine", b"x")    # never
            tr.set(b"user", b"1")
            await tr.commit()

            tr2 = db.create_transaction()
            tr2.set_option("read_system_keys")
            assert await tr2.get(b"\xff\x02/own") == b"x"  # stored read
            rows = await tr2.get_range(b"", b"\xff")
            assert rows == [(b"user", b"1")]        # user scan is clean
            # a plain scan whose end crosses \xff is rejected outright
            # (ref: validateKeyRange — no silent leak of stored rows
            # through the last shard's open end)
            tr3 = db.create_transaction()
            with pytest.raises(flow.FdbError) as ei:
                await tr3.get_range(b"", b"\xff\xf0")
            assert ei.value.name == "key_outside_legal_range"
            # selectors can't walk into stored system space either
            from foundationdb_tpu.server.types import KeySelector
            k = await tr3.get_key(KeySelector(b"zzz", False, 5))
            assert k == b"\xff"
            # ...but the canonical last_less_than(\xff) "last key"
            # idiom stays legal without any option
            k = await tr3.get_key(KeySelector(b"\xff", False, 0))
            assert k == b"user"
            # a stored-subspace scan anchored ABOVE \xff\x02 must not
            # return rows below its begin
            tr4 = db.create_transaction()
            tr4.set_option("read_system_keys")
            rows = await tr4.get_range(b"\xff\x03", b"\xff\x10")
            assert all(k >= b"\xff\x03" for k, _v in rows), rows
            # option state resets with the transaction
            tr2.reset()
            with pytest.raises(flow.FdbError):
                tr2.set(b"\xff\x02/own", b"y")
            return True

        assert c.run(main(), timeout_time=60)
    finally:
        c.shutdown()


def test_timeout_and_retry_limit_options():
    """TIMEOUT bounds the whole retry loop; RETRY_LIMIT caps on_error
    resets (ref: fdb_transaction_set_option TIMEOUT/RETRY_LIMIT — the
    options survive resets so the loop actually terminates)."""
    c = SimCluster(seed=54)
    try:
        db = c.client()

        async def main():
            # retry_limit: a perpetually-conflicting transaction stops
            # after exactly N retries
            tr = db.create_transaction()
            tr.set_option("retry_limit", 3)
            attempts = [0]
            for _ in range(50):
                attempts[0] += 1
                await tr.get(b"rl")
                # sabotage: commit something conflicting from the side
                side = db.create_transaction()
                side.set(b"rl", b"x%d" % attempts[0])
                await side.commit()
                tr.set(b"rl", b"mine")
                try:
                    await tr.commit()
                    raise AssertionError("should have conflicted")
                except flow.FdbError as e:
                    assert e.name == "not_committed"
                    try:
                        await tr.on_error(e)
                    except flow.FdbError as e2:
                        assert e2.name == "not_committed"
                        break
            else:
                raise AssertionError("retry_limit never enforced")
            assert attempts[0] == 4  # initial + 3 retries

            # timeout: the loop dies with transaction_timed_out once the
            # deadline passes, regardless of retryable errors
            # the deadline can surface from on_error OR clip any
            # in-flight operation directly (the reference's semantics:
            # every pending future errors with transaction_timed_out)
            tr2 = db.create_transaction()
            tr2.set_option("timeout", 0.5)
            for _ in range(100):
                try:
                    await tr2.get(b"to")
                    side = db.create_transaction()
                    side.set(b"to", b"y")
                    await side.commit()
                    tr2.set(b"to", b"mine")
                    await tr2.commit()
                    raise AssertionError("should have conflicted")
                except flow.FdbError as e:
                    if e.name == "transaction_timed_out":
                        return True
                    try:
                        await tr2.on_error(e)
                    except flow.FdbError as e2:
                        assert e2.name == "transaction_timed_out"
                        return True
            raise AssertionError("timeout never enforced")

        assert c.run(main(), timeout_time=120)
    finally:
        c.shutdown()
