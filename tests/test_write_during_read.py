"""WriteDuringRead-class model checker.

Ref: fdbserver/workloads/WriteDuringRead.actor.cpp:29-143 (random op
mix replayed against an in-memory model, reads asserted mid-txn),
FuzzApiCorrectness (selector/limit/option fuzz), RyowCorrectness.
Round-4 VERDICT Missing #6: the op mix must cover the FULL client
surface — selectors, limits, reverse, atomics, range clears, watches —
under faults and BUGGIFY, and the checker must provably catch a seeded
storage bug.
"""

import pytest

from foundationdb_tpu import flow
from foundationdb_tpu.server import SimCluster
from foundationdb_tpu.server.workloads import WriteDuringRead

from test_fault_workloads import _attrition


@pytest.mark.parametrize("seed", range(20))
def test_wdr_sweep(seed):
    """20-seed clean sweep: ~50 transactions of full-surface ops per
    seed, every read checked against the model, watches verified."""
    c = SimCluster(seed=8000 + seed, durable=(seed % 2 == 0),
                   n_storage=1 + seed % 2, n_proxies=1 + seed % 3,
                   n_resolvers=1 + seed % 2)
    try:
        db = c.client()

        async def main():
            w = WriteDuringRead(db, flow.g_random)
            stats = await w.run(rounds=50)
            assert stats["txns"] == 50
            assert stats["ops"] > 100
            return True

        assert c.run(main(), timeout_time=600)
    finally:
        c.shutdown()


@pytest.mark.parametrize("seed", (8101, 8102, 8103, 8104))
def test_wdr_under_attrition(seed):
    """The same checker stacked with role kills and link clogs: the
    model must stay exact through retries, recoveries, and
    commit_unknown_result resolution (watch liveness is exempt — a
    dead replica parks a watch legitimately)."""
    c = SimCluster(seed=seed, durable=True, n_storage=2, n_workers=7)
    try:
        db = c.client()
        machines = [f"w{i}" for i in range(c.n_workers)]

        async def main():
            w = WriteDuringRead(db, flow.g_random, check_watches=False)
            at = flow.spawn(_attrition(c, 6, machines))
            stats = await w.run(rounds=60)
            await at
            assert stats["txns"] == 60
            return True

        assert c.run(main(), timeout_time=900)
    finally:
        c.shutdown()


@pytest.mark.parametrize("seed", (8201, 8202))
def test_wdr_with_buggify(seed):
    """BUGGIFY distorts knobs + injects delays under the checker."""
    c = SimCluster(seed=seed, durable=True, buggify=True, n_storage=2,
                   n_workers=6)
    try:
        db = c.client()

        async def main():
            w = WriteDuringRead(db, flow.g_random, check_watches=False)
            stats = await w.run(rounds=40)
            assert stats["txns"] == 40
            return True

        assert c.run(main(), timeout_time=900)
    finally:
        c.shutdown()


def test_wdr_catches_seeded_storage_bug():
    """Prove the checker can fail: corrupt the storage read path (drop
    the newest version of every 7th key) and the model must notice
    within one run (ref: the reference's practice of validating
    workloads by breaking the code under test)."""
    from foundationdb_tpu.server.storage import VersionedMap

    c = SimCluster(seed=8301, n_storage=2)
    try:
        db = c.client()
        import zlib
        orig = VersionedMap.get

        def corrupted(self, key, version):
            val = orig(self, key, version)
            if val is not None and key.startswith(b"wdr/") and \
                    zlib.crc32(key) % 7 == 0:
                return val + b"\x00CORRUPT"
            return val

        VersionedMap.get = corrupted
        try:
            db2 = c.client("canary")

            async def main():
                w = WriteDuringRead(db2, flow.g_random,
                                    check_watches=False)
                with pytest.raises(AssertionError):
                    await w.run(rounds=80)
                return True

            assert c.run(main(), timeout_time=600)
        finally:
            VersionedMap.get = orig
    finally:
        c.shutdown()
