"""Coordinator change: MovableCoordinatedState + the `coordinators`
management command.

Ref: fdbserver/CoordinatedState.actor.cpp:220 (MovableCoordinatedState),
fdbclient/ManagementAPI.actor.cpp (changeQuorum), and the coordinators'
ForwardRequest (fdbserver/CoordinationInterface.h) that keeps a
decommissioned quorum redirecting clients.
"""

import pytest

from foundationdb_tpu import flow
from foundationdb_tpu.client import run_transaction
from foundationdb_tpu.server import SimCluster
from foundationdb_tpu.server.coordination import (CoordinatedState,
                                                  ForwardRequest,
                                                  MovedValue, elect_leader)


def test_change_coordinators_under_live_traffic():
    """Round-3 VERDICT task 5: change the quorum under live traffic,
    kill a majority of the OLD coordinators, and prove the cluster
    still recovers — the coordinated state must now live entirely on
    the new quorum."""
    c = SimCluster(seed=601, n_coordinators=3, durable=True)
    try:
        db = c.client()
        stop = [False]

        async def traffic():
            i = 0
            while not stop[0]:
                async def body(tr, i=i):
                    tr.set(b"t%04d" % i, b"v%d" % i)
                await run_transaction(db, body, max_retries=500)
                i += 1
                await flow.delay(0.05)
            return i

        async def main():
            t = flow.spawn(traffic())
            # let some commits land
            await flow.delay(2.0)

            # stand up a fresh quorum and move the coordinated state
            new_refs = c.add_coordinators(3)
            epoch_before = c.cc.dbinfo.get().epoch
            await db.change_coordinators(new_refs)

            # an operator retry with the same set (e.g. after a client
            # timeout) is a no-op, NOT a self-forwarding brick
            await db.change_coordinators(new_refs)

            # the change forces a recovery onto the new quorum
            while c.cc.dbinfo.get().epoch == epoch_before or \
                    c.cc.dbinfo.get().recovery_state != "fully_recovered":
                await flow.delay(0.1)

            # a majority of the OLD coordinators dies — fatal before
            # the change, irrelevant after it
            for coord in c.coordinators[:2]:
                c.net.kill(coord.process)

            # recovery through the NEW quorum must still work
            epoch2 = c.cc.dbinfo.get().epoch
            c.kill_role("tlog")
            while c.cc.dbinfo.get().epoch <= epoch2 or \
                    c.cc.dbinfo.get().recovery_state != "fully_recovered":
                await flow.delay(0.1)

            await flow.delay(1.0)
            stop[0] = True
            n = await t

            # every acknowledged write survived both recoveries
            tr = db.create_transaction()
            rows = await tr.get_range(b"t", b"u")
            assert len(rows) >= n, (len(rows), n)
            for i in range(n):
                assert (b"t%04d" % i, b"v%d" % i) in rows
            return True

        assert c.run(main(), timeout_time=900)
    finally:
        c.shutdown()


def test_overlapping_change_and_change_back():
    """The standard operational cases: replace ONE coordinator of
    three (old and new sets overlap — shared members hold the
    tombstone as their newest register write and must serve its
    carried value, not chase it), then change BACK to a set containing
    previously-decommissioned hosts (their stale forwards must
    clear)."""
    c = SimCluster(seed=607, n_coordinators=3, durable=True)
    try:
        db = c.client()

        async def recovered_past(epoch):
            while c.cc.dbinfo.get().epoch <= epoch or \
                    c.cc.dbinfo.get().recovery_state != "fully_recovered":
                await flow.delay(0.1)

        async def main():
            async def put(k, v):
                async def body(tr):
                    tr.set(k, v)
                await run_transaction(db, body, max_retries=500)

            await put(b"a", b"1")
            old_refs = [c._coord_refs(x) for x in c.coordinators[:3]]
            (extra,) = c.add_coordinators(1, tag="x")

            # overlap change: {0,1,2} -> {1,2,extra}
            e0 = c.cc.dbinfo.get().epoch
            await db.change_coordinators([old_refs[1], old_refs[2],
                                          extra])
            await recovered_past(e0)
            await put(b"b", b"2")

            # change BACK to the original three: host 0 was
            # decommissioned (forwarding) and must rejoin cleanly
            e1 = c.cc.dbinfo.get().epoch
            await db.change_coordinators(old_refs)
            await recovered_past(e1)
            await put(b"c", b"3")

            # recovery still works on the final quorum
            e2 = c.cc.dbinfo.get().epoch
            c.kill_role("tlog")
            await recovered_past(e2)

            async def check(tr):
                rows = await tr.get_range(b"a", b"d")
                assert rows == [(b"a", b"1"), (b"b", b"2"), (b"c", b"3")]
            await run_transaction(db, check, max_retries=200)
            return True

        assert c.run(main(), timeout_time=900)
    finally:
        c.shutdown()


def test_moved_value_followed_after_partial_change():
    """Mid-move crash: the mover seeded the new quorum and wrote the
    MovedValue tombstone but died before any ForwardRequest landed. A
    reader of the OLD quorum must still find the state by following
    the tombstone (ref: MovableValue modes)."""
    c = SimCluster(seed=603, n_coordinators=3)
    try:
        async def main():
            old = [c._coord_refs(x) for x in c.coordinators[:3]]
            new = c.add_coordinators(3, tag="b")
            proc = c.net.new_process("mover", machine="mover")

            old_cs = CoordinatedState([(x[0], x[1]) for x in old], proc)
            cur = await old_cs.read()  # whatever the cluster wrote
            new_cs = CoordinatedState([(x[0], x[1]) for x in new], proc)
            await new_cs.read()
            await new_cs.set_exclusive(cur)
            await old_cs.set_exclusive(MovedValue(tuple(new), cur))
            # NO forwards sent: the mover "crashed" here

            reader = CoordinatedState([(x[0], x[1]) for x in old],
                                      c.net.new_process("r2", machine="r2"))
            got = await reader.read()
            assert got == cur
            # the reader is now retargeted at the new quorum: a write
            # through it must be visible via the new coordinators
            await reader.set_exclusive(("post-move", 1))
            check = CoordinatedState([(x[0], x[1]) for x in new],
                                     c.net.new_process("r3", machine="r3"))
            assert await check.read() == ("post-move", 1)
            return True

        assert c.run(main(), timeout_time=120)
    finally:
        c.shutdown()


def test_cli_does_not_reap_after_committed_timeout():
    """Advisor r4: a change_coordinators timeout can fire AFTER the
    move committed (tombstone in the old quorum). The CLI's failure
    cleanup must detect that and leave the new quorum alive — reaping
    it would brick the coordinated state (old set forwards to a dead
    set)."""
    from foundationdb_tpu.tools.cli import Cli
    c = SimCluster(seed=611, n_coordinators=3)
    try:
        cli = Cli.for_cluster(c)
        new = []

        async def setup():
            new.extend(c.add_coordinators(3, tag="t"))
            return True

        assert c.run(setup(), timeout_time=120)
        # before anything lands, a reap is safe (the guard drives the
        # sim loop itself — call it between runs, as the CLI does)
        assert not cli._move_may_have_landed(new)

        async def tombstone():
            # simulate the committed-but-timed-out race: the mover got
            # as far as the tombstone write into the old quorum
            proc = c.net.new_process("mv", machine="mv")
            old_refs = [c._coord_refs(x) for x in c.coordinators[:3]]
            old_cs = CoordinatedState([(x[0], x[1]) for x in old_refs],
                                      proc)
            for _ in range(20):   # the live CC races us on the register
                try:
                    cur = await old_cs.read()
                    await old_cs.set_exclusive(MovedValue(tuple(new), cur))
                    break
                except flow.FdbError:
                    await flow.delay(0.1)
            return True

        assert c.run(tombstone(), timeout_time=120)
        assert cli._move_may_have_landed(new)
    finally:
        c.shutdown()


def test_election_follows_forwarded_quorum():
    """A candidate electing against decommissioned coordinators is
    redirected to the new set and wins there."""
    c = SimCluster(seed=605, n_coordinators=3)
    try:
        async def main():
            old = [c._coord_refs(x) for x in c.coordinators[:3]]
            new = c.add_coordinators(3, tag="e")
            proc = c.net.new_process("cand", machine="cand")
            for x in old:
                await x[3].get_reply(ForwardRequest(tuple(new)), proc)
            final = await elect_leader(old, b"\xff/otherLeader",
                                       "cand", proc)
            assert len(final) == len(new)
            # the leadership was recorded on the NEW quorum: electing
            # a worse candidate there observes "cand" as the leader
            with pytest.raises(flow.FdbError):
                await elect_leader(new, b"\xff/otherLeader", "zzz", proc)
            return True

        assert c.run(main(), timeout_time=120)
    finally:
        c.shutdown()
