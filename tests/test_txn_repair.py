"""Server-side transaction repair (ISSUE 8 tentpole,
server/repair.py): eligibility contract, the directed
repaired-commit-without-client-round-trip path, the knob-off /
non-repairable fallbacks, the FIVE-backend bit-exact parity gate (a
repaired commit must equal a from-scratch re-execution), shadow
validation staying green under the repair paths, and the contention
goodput uplift the subsystem exists for.

Ref: arXiv:1403.5645 (Transaction Repair) — re-execute only the
invalidated reads instead of aborting.
"""

import struct

import pytest

from foundationdb_tpu import flow
from foundationdb_tpu.client import run_transaction
from foundationdb_tpu.models.native_backend import CONFLICT_BACKENDS
from foundationdb_tpu.server import SimCluster
from foundationdb_tpu.server.repair import repair_eligible
from foundationdb_tpu.server.types import (ADD_VALUE, CLEAR_RANGE,
                                           CommitRequest, MutationRef,
                                           SET_VALUE)

RANGE = ((b"hot", b"hot\x00"),)


def _pack(n):
    return struct.pack("<q", n)


# -- eligibility contract ----------------------------------------------

def test_repair_eligibility_contract():
    flow.set_seed(0)
    flow.reset_server_knobs(randomize=False)

    def req(**kw):
        base = dict(read_snapshot=0, read_conflict_ranges=RANGE,
                    write_conflict_ranges=RANGE,
                    mutations=(MutationRef(ADD_VALUE, b"hot", _pack(1)),),
                    repairable=True)
        base.update(kw)
        return CommitRequest(**base)

    assert repair_eligible(req(), RANGE)
    # the client must have declared the contract
    assert not repair_eligible(req(repairable=False), RANGE)
    # no attribution mask -> cause unknown -> abort
    assert not repair_eligible(req(), ())
    # attempt budget (REPAIR_MAX_ATTEMPTS default 2)
    assert repair_eligible(req(repair_attempt=1), RANGE)
    assert not repair_eligible(req(repair_attempt=2), RANGE)
    # read-only payloads and unknown mutation types never repair
    assert not repair_eligible(req(mutations=()), RANGE)
    assert not repair_eligible(
        req(mutations=(MutationRef(99, b"k", b"v"),)), RANGE)
    # blind sets/clears are value-independent and eligible
    assert repair_eligible(
        req(mutations=(MutationRef(SET_VALUE, b"k", b"v"),
                       MutationRef(CLEAR_RANGE, b"a", b"b"))), RANGE)


# -- directed end-to-end ------------------------------------------------

def _conflicted_repairable(db):
    """A repairable ADD on b"hot" that is guaranteed to conflict: a
    rival commits to b"hot" between the read and the commit."""
    async def scenario():
        async def seed(tr):
            tr.set(b"hot", _pack(0))
        await run_transaction(db, seed)
        tr = db.create_transaction()
        tr.set_option("automatic_repair")
        await tr.get(b"hot")
        tr.atomic_op(b"hot", _pack(5), ADD_VALUE)

        async def bump(t2):
            t2.atomic_op(b"hot", _pack(100), ADD_VALUE)
        await run_transaction(db, bump)
        version = await tr.commit()    # repaired: no exception

        async def read(t3):
            return await t3.get(b"hot")
        final = await run_transaction(db, read)
        status = await db.get_status()
        return version, struct.unpack("<q", final)[0], status
    return scenario


def test_repair_commits_without_client_round_trip():
    c = SimCluster(seed=901, durable=True)
    flow.SERVER_KNOBS.set("txn_repair", 1)
    try:
        db = c.client()
        version, final, status = c.run(_conflicted_repairable(db)(),
                                       timeout_time=120)
        # both effects present exactly once — the repaired commit is
        # the from-scratch re-execution's state, bit-exact
        assert final == 105, final
        assert version > 0
        px = status["cluster"]["proxies"][0]
        rep = px["repair"]
        assert rep["attempts"] == 1 and rep["committed"] == 1, rep
        assert rep["reread_rows"] >= 1, rep   # partial re-execution ran
        assert rep["in_flight"] == 0, rep
        doc = status["cluster"]["conflict_scheduling"]
        assert doc["repair_enabled"] == 1
        assert doc["repair_committed"] == 1, doc
    finally:
        flow.reset_server_knobs(randomize=False)
        c.shutdown()


def test_repair_knob_off_aborts_exactly_as_today():
    c = SimCluster(seed=902, durable=True)
    try:
        db = c.client()

        async def main():
            async def seed(tr):
                tr.set(b"hot", _pack(0))
            await run_transaction(db, seed)
            tr = db.create_transaction()
            tr.set_option("automatic_repair")
            await tr.get(b"hot")
            tr.atomic_op(b"hot", _pack(5), ADD_VALUE)

            async def bump(t2):
                t2.atomic_op(b"hot", _pack(100), ADD_VALUE)
            await run_transaction(db, bump)
            try:
                await tr.commit()
                raise AssertionError("expected not_committed")
            except flow.FdbError as e:
                assert e.name == "not_committed", e.name
            status = await db.get_status()
            return status

        status = c.run(main(), timeout_time=120)
        rep = status["cluster"]["proxies"][0]["repair"]
        assert rep["attempts"] == 0, rep
    finally:
        c.shutdown()


def test_non_repairable_conflict_still_aborts_with_repair_on():
    """Without the client declaration the pipeline is abort-only even
    with TXN_REPAIR armed — the contract is opt-in."""
    c = SimCluster(seed=903, durable=True)
    flow.SERVER_KNOBS.set("txn_repair", 1)
    try:
        db = c.client()

        async def main():
            async def seed(tr):
                tr.set(b"hot", b"0")
            await run_transaction(db, seed)
            tr = db.create_transaction()
            await tr.get(b"hot")
            tr.set(b"mine", b"v")

            async def bump(t2):
                t2.set(b"hot", b"x")
            await run_transaction(db, bump)
            try:
                await tr.commit()
                raise AssertionError("expected not_committed")
            except flow.FdbError as e:
                assert e.name == "not_committed", e.name
            return await db.get_status()

        status = c.run(main(), timeout_time=120)
        rep = status["cluster"]["proxies"][0]["repair"]
        assert rep["attempts"] == 0, rep
    finally:
        flow.reset_server_knobs(randomize=False)
        c.shutdown()


# -- acceptance: bit-exact parity across ALL FIVE backends -------------

@pytest.mark.parametrize("backend", CONFLICT_BACKENDS)
def test_repair_parity_from_scratch_reexecution(backend):
    """Acceptance criterion: zero repaired commits diverging from a
    from-scratch re-execution, on every conflict backend. N rivals
    race repairable ADDs against a stream of committed bumps; every
    one must be repaired into a commit and the final counter must be
    the EXACT sum — a double-applied or lost repair cannot hide. The
    serializability oracle is the same resolver/consistency machinery
    as every other test (check_consistency sweeps at the end)."""
    if backend == "native":
        pytest.importorskip("foundationdb_tpu.models.native_backend")
        from foundationdb_tpu.models.native_backend import native_available
        if not native_available():
            pytest.skip("native backend not built")
    c = SimCluster(seed=910, durable=True, conflict_backend=backend)
    flow.SERVER_KNOBS.set("txn_repair", 1)
    try:
        db = c.client()

        async def main():
            from foundationdb_tpu.server.consistency import \
                check_consistency

            async def seed(tr):
                tr.set(b"hot", _pack(0))
            await run_transaction(db, seed)
            expected = 0
            for i in range(4):
                tr = db.create_transaction()
                tr.set_option("automatic_repair")
                await tr.get(b"hot")
                tr.atomic_op(b"hot", _pack(i + 1), ADD_VALUE)
                expected += i + 1

                async def bump(t2):
                    t2.atomic_op(b"hot", _pack(1000), ADD_VALUE)
                await run_transaction(db, bump)
                expected += 1000
                await tr.commit()     # must repair, never raise

            async def read(t3):
                return await t3.get(b"hot")
            final = struct.unpack("<q", await run_transaction(db, read))[0]
            status = await db.get_status()
            cons = await check_consistency(c)
            return final, status, cons

        final, status, cons = c.run(main(), timeout_time=300)
        assert final == expected_total(), final
        rep = status["cluster"]["proxies"][0]["repair"]
        assert rep["committed"] == 4, rep
        assert cons["rows"] > 0
    finally:
        flow.reset_server_knobs(randomize=False)
        c.shutdown()


def expected_total():
    return sum(i + 1 for i in range(4)) + 4 * 1000


# -- shadow validation stays green under the repair paths --------------

def test_repair_with_shadow_validation_green():
    c = SimCluster(seed=911, durable=True, conflict_backend="tpu")
    flow.SERVER_KNOBS.set("txn_repair", 1)
    flow.SERVER_KNOBS.set("shadow_resolve_sample", 2)
    try:
        db = c.client()
        _v, final, status = c.run(_conflicted_repairable(db)(),
                                  timeout_time=300)
        assert final == 105, final
        res = status["cluster"]["resolvers"][0]
        fo = res.get("failover") or {}
        assert fo, "tpu backend should run under the failover controller"
        sh = fo["shadow"]
        assert sh["sampled"] > 0, sh
        assert sh["mismatches"] == 0, sh
        rep = status["cluster"]["proxies"][0]["repair"]
        assert rep["committed"] == 1, rep
    finally:
        flow.reset_server_knobs(randomize=False)
        c.shutdown()


# -- goodput: the abort tax converted ----------------------------------

def test_contention_goodput_uplift_scheduler_plus_repair():
    """A compact version of `smoke --contention`: the same seeded
    storm, abort-only vs scheduler+repair+windows, must show the
    committed-goodput uplift (the ISSUE 8 acceptance floor is 1.25x;
    the measured uplift at these parameters is several-fold) with the
    hot-key sum oracle exact in both runs."""
    from foundationdb_tpu.server.workloads import ContentionStorm

    def run_once(on):
        c = SimCluster(seed=912, durable=True)
        flow.SERVER_KNOBS.set("conflict_scheduling", int(on))
        flow.SERVER_KNOBS.set("client_conflict_windows", int(on))
        flow.SERVER_KNOBS.set("txn_repair", int(on))
        flow.SERVER_KNOBS.set("sched_hot_push_interval", 0.05)
        try:
            dbs = [c.client(f"g{i}") for i in range(3)]

            async def main():
                storm = ContentionStorm(dbs, flow.g_random,
                                        duration=2.0, rate=120.0)
                stats = await storm.run()
                total = await storm.read_hot_total(dbs[0])
                status = await dbs[0].get_status()
                return stats, total, status

            stats, total, status = c.run(main(), timeout_time=600)
            assert stats["committed"] <= total <= \
                stats["committed"] + stats["unknown"], (total, stats)
            return stats, status
        finally:
            flow.reset_server_knobs(randomize=False)
            c.shutdown()

    base, _ = run_once(False)
    on, status = run_once(True)
    assert base["conflicts"] > 0, base
    assert on["goodput_per_sec"] >= 1.25 * base["goodput_per_sec"], \
        (base, on)
    doc = status["cluster"]["conflict_scheduling"]
    assert doc["repair_committed"] > 0, doc
    assert doc["deferrals"] > 0, doc
