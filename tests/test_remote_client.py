"""Out-of-process access: RemoteCluster (full Python client over TCP)
and a genuinely separate server process driven by the CLI.

Ref: external fdbcli/clients reaching a cluster purely over the wire
(FlowTransport + MonitorLeader); fdbserver as the hosting process.
"""

import subprocess
import sys

import pytest

from foundationdb_tpu.client import run_transaction
from foundationdb_tpu.client.remote import RemoteCluster

from test_c_binding import GatewayedCluster


def test_remote_python_client_full_stack():
    """The unchanged Python client (RYW, routing, retry) over TCP:
    transactions, status, management — all cross-thread via
    RemoteCluster.call."""
    with GatewayedCluster(seed=81, n_storage=2, n_proxies=2) as gc:
        rc = RemoteCluster("127.0.0.1", gc.port)
        try:
            async def write(tr):
                tr.set(b"remote", b"yes")
                tr.set(b"\x90far", b"side")
            rc.call(run_transaction(rc.db, write))

            async def read(tr):
                assert await tr.get(b"remote") == b"yes"
                rows = await tr.get_range(b"", b"\xff")
                assert (b"\x90far", b"side") in rows
                return len(rows)
            assert rc.call(run_transaction(rc.db, read)) == 2

            # RYW + conflict semantics hold over the wire
            async def conflicting():
                t1 = rc.db.create_transaction()
                t2 = rc.db.create_transaction()
                await t1.get(b"occ")
                await t2.get(b"occ")
                t1.set(b"occ", b"a")
                await t1.commit()
                t2.set(b"occ", b"b")
                try:
                    await t2.commit()
                    return "committed"
                except Exception as e:  # noqa: BLE001
                    return getattr(e, "name", "?")
            assert rc.call(conflicting()) == "not_committed"

            status = rc.call(rc.db.get_status())
            assert status["cluster"]["recovery_state"] == "fully_recovered"
            assert len(status["cluster"]["storages"]) == 2
        finally:
            rc.close()


def test_cli_against_separate_server_process():
    """True multi-process: a tools.server subprocess hosts the cluster;
    the CLI connects over TCP from THIS process and reads back what it
    wrote (ref: fdbcli -C against a running fdbserver)."""
    import os
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [sys.executable, "-m", "foundationdb_tpu.tools.server",
         "--port", "0", "--seed", "83"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        env=env)
    try:
        line = proc.stdout.readline().strip()
        assert line.startswith("LISTENING "), line
        port = int(line.split()[1])

        from foundationdb_tpu.tools.cli import main as cli_main
        import io
        from contextlib import redirect_stdout

        buf = io.StringIO()
        with redirect_stdout(buf):
            rc = cli_main(["--connect", f"127.0.0.1:{port}", "--exec",
                           "set alpha one; set beta two; get alpha; "
                           "getrange a c; status"])
        assert rc == 0
        out = buf.getvalue()
        assert "`alpha' is `one'" in out
        assert "`beta' is `two'" in out
        assert "fully_recovered" in out or "Epoch" in out
    finally:
        proc.terminate()
        proc.wait(timeout=30)


def test_remote_client_over_mutual_tls(tmp_path):
    """The full remote client stack over mutually-authenticated TLS —
    a rogue client with an untrusted certificate cannot connect (ref:
    FDBLibTLS protecting every external connection)."""
    import subprocess

    from foundationdb_tpu.rpc.tcp import TlsConfig

    def make_cert(name):
        key = str(tmp_path / f"{name}-key.pem")
        cert = str(tmp_path / f"{name}-cert.pem")
        subprocess.run(
            ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
             "-keyout", key, "-out", cert, "-days", "2",
             "-subj", f"/CN=fdbtpu-{name}"],
            check=True, capture_output=True)
        return cert, key

    cert, key = make_cert("cluster")
    rogue_cert, rogue_key = make_cert("rogue")
    tls = TlsConfig(cert, key, cert)

    # GatewayedCluster with a TLS transport
    import foundationdb_tpu.rpc.gateway as gwmod

    class TlsGatewayedCluster(GatewayedCluster):
        def _main(self):
            import foundationdb_tpu.flow as fl
            from foundationdb_tpu.server.cluster import SimCluster
            gw = None
            c = None
            try:
                c = SimCluster(virtual=False, **self.kw)
                gw = gwmod.TcpGateway(c.client("gateway-host"), tls=tls)

                async def main():
                    gw.start()
                    self.q.put(gw.port)
                    while not self.stop.is_set():
                        await fl.delay(0.02)

                c.run(main())
            except BaseException as e:  # noqa: BLE001
                self.q.put(e)
            finally:
                if gw is not None:
                    gw.close()
                if c is not None:
                    c.shutdown()

    with TlsGatewayedCluster(seed=87) as gc:
        # generous boot window: RSA keygen + TLS handshakes under a
        # loaded machine can stretch startup well past the default
        rc = RemoteCluster("127.0.0.1", gc.port, tls=tls,
                           connect_timeout=120)
        try:
            async def write(tr):
                tr.set(b"secure", b"channel")
            rc.call(run_transaction(rc.db, write))

            async def read(tr):
                return await tr.get(b"secure")
            assert rc.call(run_transaction(rc.db, read)) == b"channel"
        finally:
            rc.close()

        # untrusted certificate: the connection dies AT THE HANDSHAKE —
        # a specific transport error, fast, not a connect-timeout
        from foundationdb_tpu import flow as fl
        with pytest.raises(fl.FdbError) as ei:
            RemoteCluster("127.0.0.1", gc.port, connect_timeout=60,
                          tls=TlsConfig(rogue_cert, rogue_key, cert))
        assert ei.value.name in ("broken_promise", "timed_out")


def test_server_process_sigkill_restart_keeps_data(tmp_path):
    """Operator durability: a tools.server process is SIGKILLed and a
    NEW process restarts on the same --data-dir; committed data
    survives (ref: restarting fdbserver on its data directory)."""
    import os
    import signal

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    data = str(tmp_path / "srvdata")

    def start():
        p = subprocess.Popen(
            [sys.executable, "-m", "foundationdb_tpu.tools.server",
             "--port", "0", "--seed", "84", "--data-dir", data],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
            env=env)
        line = p.stdout.readline().strip()
        assert line.startswith("LISTENING "), line
        return p, int(line.split()[1])

    proc, port = start()
    try:
        rc = RemoteCluster("127.0.0.1", port)
        try:
            async def write(tr):
                for i in range(30):
                    tr.set(b"dur%02d" % i, b"v%d" % i)
            rc.call(run_transaction(rc.db, write))
        finally:
            rc.close()
        proc.send_signal(signal.SIGKILL)   # no clean shutdown
        proc.wait(timeout=30)

        proc, port = start()               # fresh process, same dir
        rc = RemoteCluster("127.0.0.1", port)
        try:
            async def check(tr):
                rows = await tr.get_range(b"dur", b"dus")
                assert len(rows) == 30, len(rows)
                tr.set(b"post", b"1")
            rc.call(run_transaction(rc.db, check))
        finally:
            rc.close()
    finally:
        proc.terminate()
        proc.wait(timeout=30)


def test_monitor_restarts_crashed_server(tmp_path):
    """fdbmonitor analogue: the supervisor restarts a killed server
    process with backoff, and — thanks to --data-dir — the restarted
    child serves the same database (ref: fdbmonitor.cpp spawn/restart
    loop)."""
    import os
    import signal

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    data = str(tmp_path / "mondata")
    port = _free_port()

    mon = subprocess.Popen(
        [sys.executable, "-m", "foundationdb_tpu.tools.monitor",
         "--port", str(port), "--seed", "89", "--data-dir", data],
        stdout=subprocess.PIPE, text=True, env=env)
    try:
        def wait_listening():
            while True:
                line = mon.stdout.readline()
                assert line, "monitor died"
                if "LISTENING" in line:
                    return

        wait_listening()
        rc = RemoteCluster("127.0.0.1", port)
        try:
            async def write(tr):
                tr.set(b"monitored", b"yes")
            rc.call(run_transaction(rc.db, write))
        finally:
            rc.close()

        # find and kill the CHILD server process
        out = subprocess.run(
            ["pgrep", "-f", f"tools.server --port {port}"],
            capture_output=True, text=True)
        pids = [int(p) for p in out.stdout.split()]
        assert pids, "no child server found"
        for pid in pids:
            os.kill(pid, signal.SIGKILL)

        wait_listening()   # the monitor restarted it
        rc = RemoteCluster("127.0.0.1", port, connect_timeout=60)
        try:
            async def check(tr):
                assert await tr.get(b"monitored") == b"yes"
                tr.set(b"post-crash", b"1")
            rc.call(run_transaction(rc.db, check))
        finally:
            rc.close()
    finally:
        mon.send_signal(signal.SIGINT)
        try:
            mon.wait(timeout=30)
        except subprocess.TimeoutExpired:
            mon.kill()


def _free_port():
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def test_remote_watch_fires_over_the_gateway():
    """A watch armed by one remote client fires when another remote
    transaction changes the key — the long-poll forwarding path (ref:
    storage watches reaching external clients through FlowTransport)."""
    import foundationdb_tpu.flow as fl

    with GatewayedCluster(seed=818) as gc:
        rc = RemoteCluster("127.0.0.1", gc.port)
        try:
            async def arm():
                tr = rc.db.create_transaction()
                await tr.get(b"watched")
                w = tr.watch(b"watched")
                await tr.commit()
                return w
            w = rc.call(arm())

            async def write(tr):
                tr.set(b"watched", b"changed")
            rc.call(run_transaction(rc.db, write))

            async def await_watch():
                return await fl.timeout_error(w, 30.0)
            rc.call(await_watch())   # raises timed_out if never fired
        finally:
            rc.close()


def test_cluster_file_roundtrip(tmp_path):
    """fdb.cluster format (ref: MonitorLeader.actor.cpp:185 parsing
    tests): parse/write round-trip, comment tolerance, validation, and
    the CLI dialing a server through --cluster-file."""
    from foundationdb_tpu.client.cluster_file import (
        ClusterConnectionString, parse_connection_string,
        read_cluster_file, resolve_connect, write_cluster_file)

    conn = parse_connection_string(
        "# a comment\n  mydb:abc123@10.0.0.1:4500,10.0.0.2:4501\n")
    assert conn.description == "mydb"
    assert conn.cluster_id == "abc123"
    assert conn.addresses == (("10.0.0.1", 4500), ("10.0.0.2", 4501))
    assert str(conn) == "mydb:abc123@10.0.0.1:4500,10.0.0.2:4501"

    path = str(tmp_path / "fdb.cluster")
    write_cluster_file(path, conn)
    assert read_cluster_file(path) == conn
    assert resolve_connect(None, path) == ("10.0.0.1", 4500)
    assert resolve_connect("h:9", path) == ("h", 9)  # --connect wins
    assert resolve_connect(None, None) is None

    import pytest as _pytest
    for bad in ("nope", "a:b", "db:id@", "db:id@host:notaport",
                "db/x:id@h:1", "one:1@h:1\ntwo:2@h:2"):
        with _pytest.raises(ValueError):
            parse_connection_string(bad)

    # e2e: server writes the file; the CLI dials through it
    import subprocess
    import sys as _sys
    cf = str(tmp_path / "live.cluster")
    proc = subprocess.Popen(
        [_sys.executable, "-m", "foundationdb_tpu.tools.server",
         "--port", "0", "--cluster-file", cf],
        stdout=subprocess.PIPE, text=True)
    try:
        line = proc.stdout.readline()
        assert line.startswith("LISTENING"), line
        out = subprocess.run(
            [_sys.executable, "-m", "foundationdb_tpu.tools.cli",
             "--cluster-file", cf, "--exec", "set cf works; get cf"],
            capture_output=True, text=True, timeout=120)
        assert "works" in out.stdout, (out.stdout, out.stderr)
    finally:
        proc.terminate()
        proc.wait(timeout=30)
