"""Enforced admission control (ISSUE 10 / ROADMAP item 3): GRV token
buckets, strict priority ordering, bounded queues with retryable
rejection, tag throttling through \\xff\\x02/throttledTags/, the
ratekeeper's per-proxy budget split, client-honored backoff, and the
off-posture byte-identical GRV path.

Ref: fdbserver/GrvProxyServer.actor.cpp transactionStarter +
GrvTransactionRateInfo, GrvProxyTransactionTagThrottler,
fdbclient/TagThrottle.actor.cpp.
"""

from foundationdb_tpu import flow
from foundationdb_tpu.client import run_transaction
from foundationdb_tpu.server import SimCluster
from foundationdb_tpu.server import systemkeys as sk
from foundationdb_tpu.server.admission import (GrvAdmissionQueues,
                                               TokenBucket)
from foundationdb_tpu.server.tag_throttler import (
    ClientTagThrottleCache, client_throttle_counters)
from foundationdb_tpu.server.types import (GetReadVersionReply,
                                           GetReadVersionRequest,
                                           PRIORITY_BATCH,
                                           PRIORITY_DEFAULT,
                                           PRIORITY_IMMEDIATE)
from foundationdb_tpu.tools.cli import Cli


def _entry(count=1, prio=PRIORITY_DEFAULT, t0=0.0, tags=()):
    return (flow.Future(), count, prio, t0, tuple(tags))


def _queues():
    return GrvAdmissionQueues(None, flow.CounterCollection("adm_test"))


def _reset():
    flow.reset_server_knobs(randomize=False)


# -- token-bucket math (directed) --------------------------------------

def test_token_bucket_refill_and_burst():
    b = TokenBucket(rate=100.0, burst=50.0, now=0.0)
    assert b.available(0.0) == 0.0
    assert abs(b.available(0.1) - 10.0) < 1e-9
    assert b.try_take(5, 0.1)
    assert abs(b.tokens - 5.0) < 1e-9
    # refill caps at the burst allowance, however long the idle
    assert abs(b.available(10.0) - 50.0) < 1e-9
    assert not b.try_take(51, 10.0)        # over the cap: never
    assert b.try_take(50, 10.0)            # exactly the cap: fine


def test_token_bucket_rate_change_refills_at_old_rate():
    b = TokenBucket(rate=10.0, burst=100.0, now=0.0)
    b.set_rate(1000.0, 100.0, 1.0)
    # the elapsed second accrued at the OLD 10/s, not the new 1000/s
    assert abs(b.tokens - 10.0) < 1e-9


def test_token_bucket_zero_rate_is_full_stop():
    b = TokenBucket(rate=100.0, burst=100.0, now=0.0)
    assert b.available(1.0) == 100.0
    b.set_rate(0.0, 1.0, 1.0)
    # a zero rate confiscates accrued tokens too (emergency throttle)
    assert b.available(2.0) == 0.0
    assert not b.try_take(1, 3.0)


def test_token_bucket_debt_repaid_by_refill():
    b = TokenBucket(rate=10.0, burst=10.0, now=0.0)
    b.force_take(5, 0.0)
    assert b.tokens == -5.0
    assert not b.try_take(1, 0.4)          # -5 + 4 = -1: still in debt
    assert b.try_take(1, 1.0)              # -1 + 6 = 5: repaid


# -- strict priority ordering (directed; the acceptance pin) -----------

def test_immediate_never_queued_behind_default_or_batch():
    """A full default queue and a starved batch queue: an IMMEDIATE
    request submitted LAST is still admitted FIRST, paying no tokens;
    defaults take what the bucket affords; batch gets nothing while
    defaults drain (batch starves first)."""
    flow.SERVER_KNOBS.set("grv_admission_control", 1)
    try:
        q = _queues()
        defaults = [_entry(prio=PRIORITY_DEFAULT, t0=0.0)
                    for _ in range(20)]
        batch = [_entry(prio=PRIORITY_BATCH, t0=0.0) for _ in range(5)]
        for e in defaults + batch:
            q.submit(e, 0.0)
        imm = _entry(prio=PRIORITY_IMMEDIATE, t0=0.0)
        q.submit(imm, 0.0)
        # first tick (cold buckets, zero tokens): the immediate — and
        # ONLY the immediate — is admitted, instantly, uncharged
        out1 = q.tick(0.0, rate=2.0, batch_rate=1.0, interval=1.0)
        assert [e[0] for e in out1] == [imm[0]], out1
        # 2 tokens accrued at 2/s: two defaults admitted, batch starved
        out2 = q.tick(1.0, rate=2.0, batch_rate=1.0, interval=1.0)
        assert [e[2] for e in out2] == [PRIORITY_DEFAULT] * 2, out2
        # a late immediate still never waits, tick after tick, and
        # sorts strictly ahead of any simultaneously admitted class
        imm2 = _entry(prio=PRIORITY_IMMEDIATE, t0=1.0)
        q.submit(imm2, 1.0)
        out3 = q.tick(2.0, rate=2.0, batch_rate=1.0, interval=1.0)
        assert out3[0][0] is imm2[0], out3
        prios = [e[2] for e in out3]
        assert prios == sorted(prios, reverse=True)
    finally:
        _reset()


def test_batch_admits_only_after_defaults_drain():
    flow.SERVER_KNOBS.set("grv_admission_control", 1)
    try:
        q = _queues()
        for _ in range(3):
            q.submit(_entry(prio=PRIORITY_DEFAULT, t0=0.0), 0.0)
        for _ in range(3):
            q.submit(_entry(prio=PRIORITY_BATCH, t0=0.0), 0.0)
        q.tick(0.0, rate=100.0, batch_rate=100.0, interval=1.0)
        out = q.tick(1.0, rate=100.0, batch_rate=100.0, interval=1.0)
        # generous budget: everything admits, defaults strictly first
        prios = [e[2] for e in out]
        assert prios == [PRIORITY_DEFAULT] * 3 + [PRIORITY_BATCH] * 3
    finally:
        _reset()


def test_queue_depth_bound_rejects_retryable():
    flow.SERVER_KNOBS.set("grv_admission_control", 1)
    flow.SERVER_KNOBS.set("grv_queue_max", 2)
    try:
        q = _queues()
        entries = [_entry() for _ in range(3)]
        for e in entries:
            q.submit(e, 0.0)
        assert not entries[0][0].is_ready
        assert not entries[1][0].is_ready
        assert entries[2][0].is_error
        err = entries[2][0].exception()
        assert err.name == "proxy_memory_limit_exceeded"
        assert err.is_retryable()
        # immediate is EXEMPT from the depth bound: it drains every
        # tick and is never shed, whatever the bound says
        imms = [_entry(prio=PRIORITY_IMMEDIATE) for _ in range(5)]
        for e in imms:
            q.submit(e, 0.0)
        assert not any(e[0].is_ready for e in imms)
    finally:
        _reset()


def test_tag_gate_runs_before_class_depth_bound():
    """A pace-limited tagged request parks at the tag gate even while
    the class queue is full — it never occupies a class slot, so the
    depth bound must not judge it (review-found regression)."""
    flow.SERVER_KNOBS.set("grv_admission_control", 1)
    flow.SERVER_KNOBS.set("tag_throttling", 1)
    flow.SERVER_KNOBS.set("grv_queue_max", 2)
    try:
        q = _queues()
        q.tags.install([(b"t", 0.001, 1000.0, PRIORITY_DEFAULT, False)],
                       0.0)
        q.submit(_entry(tags=(b"t",)), 0.0)   # burst token, queued
        q.submit(_entry(), 0.0)               # class queue now full
        tagged = _entry(tags=(b"t",))
        q.submit(tagged, 0.0)
        assert not tagged[0].is_ready          # parked, not rejected
        assert q.tags.depth() == 1
    finally:
        _reset()


def test_wait_bound_sheds_queued_but_never_immediate():
    flow.SERVER_KNOBS.set("grv_admission_control", 1)
    flow.SERVER_KNOBS.set("grv_queue_max_wait", 2.0)
    try:
        q = _queues()
        stale = _entry(prio=PRIORITY_DEFAULT, t0=0.0)
        q.submit(stale, 0.0)
        imm = _entry(prio=PRIORITY_IMMEDIATE, t0=0.0)
        q.submit(imm, 0.0)
        out = q.tick(10.0, rate=0.0, batch_rate=0.0, interval=1.0)
        # the default was shed with the retryable overflow error; the
        # immediate (same age) was ADMITTED — never shed, never queued
        assert stale[0].is_error
        assert stale[0].exception().name == "proxy_memory_limit_exceeded"
        assert any(e[0] is imm[0] for e in out)
        # the wait bound is a live-read knob
        flow.SERVER_KNOBS.set("grv_queue_max_wait", 100.0)
        old = _entry(prio=PRIORITY_DEFAULT, t0=5.0)
        q.submit(old, 11.0)
        q.tick(12.0, rate=0.0, batch_rate=0.0, interval=1.0)
        assert not old[0].is_error   # 7s old, bound now 100s
    finally:
        _reset()


# -- tag throttling (directed) -----------------------------------------

def test_tag_bucket_paces_parks_and_releases():
    flow.SERVER_KNOBS.set("tag_throttling", 1)
    try:
        q = _queues()
        q.tags.install([(b"t", 2.0, 100.0, PRIORITY_DEFAULT, True)], 0.0)
        first = _entry(tags=(b"t",), t0=0.0)
        second = _entry(tags=(b"t",), t0=0.0)
        q.submit(first, 0.0)
        q.submit(second, 0.0)
        q.tick(0.0, rate=1e6, batch_rate=1e6, interval=0.001)  # warm up
        # first took the row's single burst token; second is parked
        out = q.tick(0.01, rate=1e6, batch_rate=1e6, interval=0.001)
        assert any(e[0] is first[0] for e in out)
        assert not any(e[0] is second[0] for e in out)
        assert q.tags.depth() == 1
        # at 2 tps the parked request releases after ~0.5s
        out2 = q.tick(0.6, rate=1e6, batch_rate=1e6, interval=0.001)
        assert any(e[0] is second[0] for e in out2)
        assert q.tags.depth() == 0
    finally:
        _reset()


def test_tag_throttle_expiry_frees_parked_requests():
    flow.SERVER_KNOBS.set("tag_throttling", 1)
    try:
        q = _queues()
        q.tags.install([(b"t", 0.001, 1.0, PRIORITY_DEFAULT, False)], 0.0)
        a = _entry(tags=(b"t",), t0=0.0)
        b = _entry(tags=(b"t",), t0=0.0)
        q.submit(a, 0.0)   # takes the burst token
        q.submit(b, 0.0)   # parked at 0.001 tps: effectively forever
        assert q.tags.depth() == 1
        q.tick(0.0, rate=1e6, batch_rate=1e6, interval=0.001)  # warm up
        # the row expires at t=1: the parked request flows immediately
        out = q.tick(1.5, rate=1e6, batch_rate=1e6, interval=0.001)
        assert any(e[0] is b[0] for e in out)
        assert not q.tags.rows
    finally:
        _reset()


def test_tag_queue_bound_is_live_read():
    flow.SERVER_KNOBS.set("tag_throttling", 1)
    flow.SERVER_KNOBS.set("tag_throttle_queue_max", 1)
    try:
        q = _queues()
        q.tags.install([(b"t", 0.001, 100.0, PRIORITY_DEFAULT, False)],
                       0.0)
        q.submit(_entry(tags=(b"t",)), 0.0)   # burst token
        parked = _entry(tags=(b"t",))
        q.submit(parked, 0.0)                 # parked (bound 1)
        rejected = _entry(tags=(b"t",))
        q.submit(rejected, 0.0)
        assert rejected[0].is_error
        assert rejected[0].exception().name == "tag_throttled"
        assert rejected[0].exception().is_retryable()
        # live-read: widen the bound, the next one parks instead
        flow.SERVER_KNOBS.set("tag_throttle_queue_max", 10)
        ok = _entry(tags=(b"t",))
        q.submit(ok, 0.0)
        assert not ok[0].is_ready
        assert q.tags.depth() == 2
    finally:
        _reset()


def test_tag_throttling_only_posture_still_enforces_budget():
    """With TAG_THROTTLING armed but GRV_ADMISSION_CONTROL off, every
    GRV routes through the admission plane INSTEAD of the legacy
    rate-gated batcher — so the class buckets must still charge the
    ratekeeper budget, or arming tag throttling alone would silently
    disable all rate enforcement (review-found regression)."""
    flow.SERVER_KNOBS.set("tag_throttling", 1)
    try:
        q = _queues()
        for _ in range(20):
            q.submit(_entry(prio=PRIORITY_DEFAULT, t0=0.0), 0.0)
        q.tick(0.0, rate=2.0, batch_rate=2.0, interval=1.0)
        out = q.tick(1.0, rate=2.0, batch_rate=2.0, interval=1.0)
        assert len(out) == 2, out      # the budget, not the queue
    finally:
        _reset()


def test_oversized_tag_head_releases_into_debt():
    """A client-coalesced GRV carrying several transactions under one
    throttled tag must still release (paced, into bucket debt) — a
    burst-1 bucket that can never afford count>=2 would wedge the tag
    queue until the wait bound sheds it (review-found regression)."""
    flow.SERVER_KNOBS.set("tag_throttling", 1)
    flow.SERVER_KNOBS.set("grv_queue_max_wait", 1000.0)
    try:
        q = _queues()
        q.tags.install([(b"t", 2.0, 1000.0, PRIORITY_DEFAULT, False)],
                       0.0)
        q.submit(_entry(count=1, tags=(b"t",)), 0.0)   # burst token
        big = _entry(count=3, tags=(b"t",))
        q.submit(big, 0.0)
        assert q.tags.depth() == 1
        # at 2 tps the bucket refills to its burst (1.0) after 0.5s and
        # the oversized head force-releases into debt
        q.tick(0.0, rate=1e6, batch_rate=1e6, interval=0.001)
        out = q.tick(0.6, rate=1e6, batch_rate=1e6, interval=0.001)
        assert any(e[0] is big[0] for e in out), out
        assert q.tags.depth() == 0
        # the debt keeps the average at the commanded pace: the next
        # single-count request waits out the 3-token debt (~1.5s more)
        nxt = _entry(count=1, tags=(b"t",))
        q.submit(nxt, 0.6)
        out2 = q.tick(1.0, rate=1e6, batch_rate=1e6, interval=0.001)
        assert not any(e[0] is nxt[0] for e in out2)
        out3 = q.tick(2.7, rate=1e6, batch_rate=1e6, interval=0.001)
        assert any(e[0] is nxt[0] for e in out3), out3
    finally:
        _reset()


def test_tag_parked_wait_bound_sheds_with_tag_error():
    """A tag-parked request past the wait bound was waiting on
    DESIGNED pacing, not proxy overload — it must shed with
    tag_throttled and count throttle_rejected, or the counters steer
    an operator at the wrong knob (review-found regression)."""
    flow.SERVER_KNOBS.set("tag_throttling", 1)
    flow.SERVER_KNOBS.set("grv_queue_max_wait", 2.0)
    try:
        q = _queues()
        q.tags.install([(b"t", 0.001, 1000.0, PRIORITY_DEFAULT, False)],
                       0.0)
        first = _entry(tags=(b"t",), t0=0.0)
        q.submit(first, 0.0)                       # burst token: queued
        parked = _entry(tags=(b"t",), t0=0.0)
        q.submit(parked, 0.0)
        q.tick(10.0, rate=1e6, batch_rate=1e6, interval=0.001)
        # the CLASS-queued entry aged out of the class queue: proxy
        # overflow is ITS honest label...
        assert first[0].is_error
        assert first[0].exception().name == "proxy_memory_limit_exceeded"
        # ...while the TAG-parked one was waiting on designed pacing:
        # it sheds with the tag error and the throttle counter
        assert parked[0].is_error
        assert parked[0].exception().name == "tag_throttled"
        snap = q.stats.snapshot()
        assert snap.get("throttle_rejected", 0) == 1, snap
        assert snap.get("admission_timed_out", 0) == 1, snap
    finally:
        _reset()


def test_tag_row_priority_scoping():
    """A batch-priority row throttles batch only; default and
    immediate pass untouched (a row applies at and below its class,
    and immediate is NEVER tag-throttled)."""
    flow.SERVER_KNOBS.set("tag_throttling", 1)
    try:
        q = _queues()
        q.tags.install([(b"t", 0.001, 100.0, PRIORITY_BATCH, False)], 0.0)
        assert q.tags.applying((b"t",), PRIORITY_DEFAULT, 0.0) is None
        assert q.tags.applying((b"t",), PRIORITY_IMMEDIATE, 0.0) is None
        assert q.tags.applying((b"t",), PRIORITY_BATCH, 0.0) is not None
        q.tags.install([(b"t", 0.001, 100.0, PRIORITY_DEFAULT, False)],
                       0.0)
        assert q.tags.applying((b"t",), PRIORITY_DEFAULT, 0.0) is not None
        assert q.tags.applying((b"t",), PRIORITY_IMMEDIATE, 0.0) is None
    finally:
        _reset()


def test_shutdown_breaks_all_queued_requests():
    flow.SERVER_KNOBS.set("grv_admission_control", 1)
    flow.SERVER_KNOBS.set("tag_throttling", 1)
    try:
        q = _queues()
        q.tags.install([(b"t", 0.001, 100.0, PRIORITY_DEFAULT, False)],
                       0.0)
        plain = _entry()
        q.submit(plain, 0.0)
        q.submit(_entry(tags=(b"t",)), 0.0)     # burst token
        parked = _entry(tags=(b"t",))
        q.submit(parked, 0.0)
        q.shutdown()
        for e in (plain, parked):
            assert e[0].is_error
            assert e[0].exception().name == "broken_promise"
        assert q.depth() == 0
    finally:
        _reset()


# -- systemkeys schema -------------------------------------------------

def test_throttle_row_schema_round_trip():
    key = sk.throttled_tag_key(b"web")
    assert sk.parse_throttled_tag_key(key) == b"web"
    assert sk.parse_throttled_tag_key(b"zzz") is None
    v = sk.encode_tag_throttle_value(12.5, 99.25, PRIORITY_DEFAULT, True)
    assert sk.parse_tag_throttle_value(v) == (12.5, 99.25,
                                              PRIORITY_DEFAULT, True)
    assert sk.parse_tag_throttle_value(b"garbage") is None
    assert sk.parse_tag_throttle_value(b"9|1|2|3|4") is None  # version
    # the range sits in the STORED system region (real durable rows)
    assert sk.is_stored_system(key)


# -- ratekeeper budget split -------------------------------------------

def test_rate_split_across_proxies():
    from foundationdb_tpu.server.ratekeeper import Ratekeeper

    class _Var:
        def __init__(self, v):
            self._v = v

        def get(self):
            return self._v

    class _Info:
        proxies = (1, 2)

    class _CC:
        pass

    fake = type("_RK", (), {})()
    fake.rate, fake.batch_rate = 100.0, 50.0
    fake.cc = _CC()
    fake.cc.dbinfo = _Var(_Info())
    try:
        # off-posture: the undivided rate, exactly as before
        assert Ratekeeper._served_rates(fake) == (100.0, 50.0)
        flow.SERVER_KNOBS.set("grv_admission_control", 1)
        assert Ratekeeper._served_rates(fake) == (50.0, 25.0)
        # the pre-batch-limit sentinel passes through undivided
        fake.batch_rate = -1.0
        assert Ratekeeper._served_rates(fake) == (50.0, -1.0)
    finally:
        _reset()


# -- client-honored backoff --------------------------------------------

def test_client_cache_paces_and_expires():
    flow.SERVER_KNOBS.set("tag_throttling", 1)
    try:
        cache = ClientTagThrottleCache()
        cache.update([(b"t", 2.0, 10.0)], 0.0)
        assert cache.delay((b"t",), 0.0) == 0.0       # burst-of-one
        d = cache.delay((b"t",), 0.1)
        assert abs(d - 0.4) < 1e-9                    # paced at 2 tps
        # untagged / unknown tags never wait
        assert cache.delay((b"x",), 0.2) == 0.0
        # expiry drops the row
        assert cache.delay((b"t",), 11.0) == 0.0
        assert cache.delay((b"t",), 11.0) == 0.0
        # the local wait is capped by the knob
        flow.SERVER_KNOBS.set("client_tag_backoff_max", 0.25)
        cache.update([(b"s", 0.1, 100.0)], 20.0)
        cache.delay((b"s",), 20.0)
        assert cache.delay((b"s",), 20.0) == 0.25
    finally:
        _reset()


def test_client_backoff_survives_on_error():
    """The backoff consults a DATABASE-scoped cache and the tags
    survive on_error's reset — a conflicted attempt's retry honors the
    throttle exactly like the first attempt did."""
    c = SimCluster(seed=5050, durable=True)
    try:
        flow.SERVER_KNOBS.set("tag_throttling", 1)
        db = c.client("cb")

        async def main():
            cache = ClientTagThrottleCache()
            cache.update([(b"bk", 5.0, flow.now() + 1000.0)], flow.now())
            db._tag_throttle_cache = cache
            before = client_throttle_counters().get("backoffs", 0)
            tr = db.create_transaction()
            tr.set_option("transaction_tag", b"bk")
            await tr.get(b"hot")
            tr.set(b"mine", b"v")

            async def bump(t2):
                t2.set(b"hot", b"x")
            await run_transaction(db, bump)
            try:
                await tr.commit()
                raise AssertionError("expected a conflict")
            except flow.FdbError as e:
                assert e.name == "not_committed", e.name
                await tr.on_error(e)
            assert tr._tags == (b"bk",)     # the tag survived
            await tr.get(b"hot")            # retry GRV: backs off again
            after = client_throttle_counters().get("backoffs", 0)
            assert after >= before + 1, (before, after)
            return True

        assert c.run(main(), timeout_time=120)
    finally:
        _reset()
        c.shutdown()


# -- system-keyspace round trip (manual throttles via cli) -------------

def test_manual_throttle_roundtrip_through_cli():
    c = SimCluster(seed=4040, durable=True)
    try:
        flow.SERVER_KNOBS.set("tag_throttling", 1)
        flow.SERVER_KNOBS.set("tag_throttle_poll_interval", 0.1)
        cli = Cli.for_cluster(c)
        out = cli.execute("throttle on webtag 5 default 60")
        assert "Throttle set" in out, out
        lst = cli.execute("throttle list")
        assert "webtag" in lst and "tps=5" in lst and "manual" in lst, lst

        db = c.client("mt")

        async def wait_installed():
            for _ in range(60):
                await flow.delay(0.2)
                st = await db.get_status()
                rows = (st["cluster"]["admission_control"]
                        ["throttled_tags"])
                if any(r["tag"] == b"webtag".hex() and not r["auto"]
                       for r in rows):
                    return st
            raise AssertionError("proxy never installed the manual row")

        st = c.run(wait_installed(), timeout_time=120)
        row = [r for r in st["cluster"]["admission_control"]
               ["throttled_tags"] if r["tag"] == b"webtag".hex()][0]
        assert row["tps"] == 5.0 and row["priority"] == "default", row

        assert "cleared" in cli.execute("throttle off webtag")
        assert "webtag" not in cli.execute("throttle list")

        async def wait_gone():
            for _ in range(60):
                await flow.delay(0.2)
                st = await db.get_status()
                rows = (st["cluster"]["admission_control"]
                        ["throttled_tags"])
                if not rows:
                    return True
            raise AssertionError("proxy never dropped the cleared row")

        assert c.run(wait_gone(), timeout_time=120)
    finally:
        _reset()
        c.shutdown()


# -- auto-throttler e2e ------------------------------------------------

def test_auto_throttler_writes_row_under_abuse():
    c = SimCluster(seed=7070, durable=True)
    try:
        flow.SERVER_KNOBS.set("tag_throttling", 1)
        flow.SERVER_KNOBS.set("auto_tag_throttling", 1)
        flow.SERVER_KNOBS.set("tag_throttle_update_interval", 0.2)
        flow.SERVER_KNOBS.set("tag_throttle_busy_rate", 5.0)
        flow.SERVER_KNOBS.set("tag_throttle_poll_interval", 0.1)
        db = c.client("auto")

        async def main():
            for i in range(40):        # ~20/s of one tag: abusive
                async def body(tr, i=i):
                    tr.set_option("transaction_tag", b"abuser")
                    tr.set(b"a%03d" % i, b"v")
                await run_transaction(db, body)
                await flow.delay(0.05)

            async def rows(tr):
                tr.set_option("read_system_keys")
                return await tr.get_range(sk.THROTTLED_TAGS_PREFIX,
                                          sk.THROTTLED_TAGS_END)
            got = await run_transaction(db, rows, max_retries=200)
            parsed = {}
            for key, value in got:
                tag = sk.parse_throttled_tag_key(key)
                v = sk.parse_tag_throttle_value(value)
                if tag is not None and v is not None:
                    parsed[tag] = v
            assert b"abuser" in parsed, sorted(parsed)
            tps, _expiry, prio, auto = parsed[b"abuser"]
            assert auto is True and prio == PRIORITY_DEFAULT
            assert tps >= float(flow.SERVER_KNOBS.tag_throttle_min_tps)
            st = await db.get_status()
            auto_doc = (st["cluster"]["admission_control"]
                        ["auto_throttler"])
            assert auto_doc["auto_throttles"] >= 1, auto_doc
            return True

        assert c.run(main(), timeout_time=300)
    finally:
        _reset()
        c.shutdown()


def test_manual_throttle_takes_precedence_over_auto():
    """A live MANUAL row for a busy tag is never overwritten by the
    auto-throttler — the operator's word stands (review-found
    regression: the blind auto SET used to replace it)."""
    c = SimCluster(seed=7171, durable=True)
    try:
        flow.SERVER_KNOBS.set("auto_tag_throttling", 1)
        flow.SERVER_KNOBS.set("tag_throttle_update_interval", 0.2)
        flow.SERVER_KNOBS.set("tag_throttle_busy_rate", 5.0)
        db = c.client("mp")

        async def main():
            async def setrow(tr):
                tr.set_option("access_system_keys")
                tr.set(sk.throttled_tag_key(b"abuser"),
                       sk.encode_tag_throttle_value(
                           2.0, flow.now() + 600.0, PRIORITY_DEFAULT,
                           auto=False))
            await run_transaction(db, setrow)
            for i in range(40):        # ~20/s of the tag: reads busy
                async def body(tr, i=i):
                    tr.set_option("transaction_tag", b"abuser")
                    tr.set(b"p%03d" % i, b"v")
                await run_transaction(db, body)
                await flow.delay(0.05)

            async def rows(tr):
                tr.set_option("read_system_keys")
                return await tr.get_range(sk.THROTTLED_TAGS_PREFIX,
                                          sk.THROTTLED_TAGS_END)
            got = await run_transaction(db, rows, max_retries=200)
            parsed = {sk.parse_throttled_tag_key(key):
                      sk.parse_tag_throttle_value(value)
                      for key, value in got}
            tps, _exp, _prio, auto = parsed[b"abuser"]
            assert auto is False and tps == 2.0, parsed
            st = await db.get_status()
            auto_doc = (st["cluster"]["admission_control"]
                        ["auto_throttler"])
            assert auto_doc["auto_throttles"] == 0, auto_doc
            return True

        assert c.run(main(), timeout_time=300)
    finally:
        _reset()
        c.shutdown()


# -- off posture: byte-identical GRV path ------------------------------

def test_off_posture_grv_path_byte_identical():
    """With every admission knob at its default 0: a tagged workload
    runs, the raw GRV reply is exactly the defaulted pre-subsystem
    shape (no windows, no throttle info), no request ever routes
    through the admission queues, and no backoff fires client-side."""
    c = SimCluster(seed=6060, durable=True)
    try:
        db = c.client("off")

        async def main():
            before = client_throttle_counters().get("backoffs", 0)

            async def body(tr):
                tr.set_option("transaction_tag", b"offtag")
                tr.set(b"k", b"v")
            await run_transaction(db, body)
            info = await db.info()
            reply = await info.proxies[0].grvs.get_reply(
                GetReadVersionRequest(1, PRIORITY_DEFAULT), db.process)
            assert reply == GetReadVersionReply(reply.version), reply
            assert reply.conflict_windows == ()
            assert reply.tag_throttles == ()
            st = await db.get_status()
            adm = st["cluster"]["admission_control"]
            assert adm["grv_admission_enabled"] == 0
            assert adm["tag_throttling_enabled"] == 0
            assert adm["queued_now"] == 0
            assert adm["rejected"] == 0 and adm["timed_out"] == 0
            assert sum(adm["admitted"].values()) == 0
            assert adm["throttled_tags"] == []
            assert client_throttle_counters().get("backoffs",
                                                  0) == before
            assert db._tag_throttle_cache is None
            return True

        assert c.run(main(), timeout_time=120)
    finally:
        c.shutdown()


# -- storm honesty + overload workload ---------------------------------

def test_overload_storm_accounting_is_exact():
    from foundationdb_tpu.server.workloads import OverloadStorm
    c = SimCluster(seed=808, durable=True)
    try:
        dbs = [c.client(f"ov{i}") for i in range(3)]

        async def main():
            storm = OverloadStorm(dbs, flow.g_random, duration=1.5,
                                  fair_rate=40.0, abusive_rate=80.0,
                                  n_clients=1000, max_inflight=256)
            return await storm.run()

        stats = c.run(main(), timeout_time=300)
        assert stats["issued"] > 30, stats
        done = (stats["completed"] + stats["conflicted"]
                + stats["grv_rejected"] + stats["tag_rejected"]
                + sum(stats["errors"].values()))
        # every arrival is accounted exactly once: open-loop honesty
        assert done + stats["shed"] == stats["issued"], stats
        assert stats["admitted"] + stats["shed"] == stats["issued"]
        assert 0.0 < stats["attainment"] <= 1.0
        assert stats["abusive_issued"] + stats["others_issued"] == \
            stats["issued"]
        assert stats["late_issued"] <= stats["issued"]
        assert "late_committed_per_sec" in stats
        assert stats["grv"]["others"]["count"] > 0
    finally:
        c.shutdown()


def test_open_loop_storm_reports_attainment():
    from foundationdb_tpu.server.workloads import OpenLoopStorm
    c = SimCluster(seed=809, durable=True)
    try:
        dbs = [c.client("at0")]

        async def main():
            storm = OpenLoopStorm(dbs, flow.g_random, duration=1.0,
                                  rate=2000.0, burst_rate=2000.0,
                                  burst_start=0.0, burst_len=1.0,
                                  keyspace=4, max_inflight=8)
            return await storm.run()

        stats = c.run(main(), timeout_time=300)
        # at saturation the cap converts offered load into shed load —
        # and the report SAYS so instead of silently going closed-loop
        assert stats["shed"] > 0, stats
        assert stats["admitted"] == stats["issued"] - stats["shed"]
        assert stats["attainment"] < 1.0, stats
    finally:
        c.shutdown()


# -- exporter families -------------------------------------------------

def test_admission_exporter_families_round_trip():
    from foundationdb_tpu.tools.exporter import (parse_prometheus,
                                                 render_prometheus)
    status = {"cluster": {
        "epoch": 1, "recovery_state": "fully_recovered",
        "admission_control": {
            "grv_admission_enabled": 1, "tag_throttling_enabled": 1,
            "auto_tag_throttling_enabled": 1,
            "admitted": {"immediate": 2, "default": 40, "batch": 3},
            "queued_now": 1, "rejected": 4, "timed_out": 2,
            "throttle_delayed": 7, "throttle_released": 6,
            "throttle_rejected": 1, "confirm_rounds": 9,
            "throttled_tags": [
                {"tag": "ab", "tps": 5.0, "expiry": 99.0,
                 "priority": "default", "auto": 1, "queued": 2}],
            "auto_throttler": {"enabled": 1, "auto_throttles": 3,
                               "auto_cleared": 1, "tracked_tags": 2,
                               "active_auto": ["ab"]},
            "client": {"backoffs": 11, "backoff_ms": 1200,
                       "updates": 5, "tags_cached": 1},
        },
        "proxies": [{
            "name": "proxy-e1-0", "counters": {},
            "latency_bands": {},
            "admission": {
                "grv_admission_enabled": 1, "tag_throttling_enabled": 1,
                "admitted": {"immediate": 2, "default": 40, "batch": 3},
                "queued": {"immediate": 0, "default": 1, "batch": 0},
                "rejected": 4, "timed_out": 2, "throttle_delayed": 7,
                "throttle_released": 6, "throttle_rejected": 1,
                "confirm_rounds": 9,
                "tag_rows": [{"tag": "ab", "tps": 5.0, "expiry": 99.0,
                              "priority": "default", "auto": 1,
                              "queued": 2}]}}],
    }}
    samples = parse_prometheus(render_prometheus(status))
    names = {n for n, _l, _v in samples}
    for need in ("fdbtpu_admission_enabled", "fdbtpu_admission_admitted",
                 "fdbtpu_admission_queued", "fdbtpu_admission_rejected",
                 "fdbtpu_admission_timed_out",
                 "fdbtpu_admission_confirm_rounds",
                 "fdbtpu_throttle_tags", "fdbtpu_throttle_tag_tps",
                 "fdbtpu_throttle_delayed", "fdbtpu_throttle_released",
                 "fdbtpu_throttle_rejected",
                 "fdbtpu_throttle_auto_written",
                 "fdbtpu_throttle_auto_cleared",
                 "fdbtpu_throttle_client", "fdbtpu_throttle_client_tags"):
        assert need in names, f"exporter missing {need}"
    tps = [(l, v) for n, l, v in samples if n == "fdbtpu_throttle_tag_tps"]
    assert tps == [({"tag": "ab", "priority": "default", "auto": "1"},
                    5.0)]
    admitted = {l["priority"]: v for n, l, v in samples
                if n == "fdbtpu_admission_admitted"}
    assert admitted == {"immediate": 2.0, "default": 40.0, "batch": 3.0}
