"""Epoch recovery under faults: kill transaction roles mid-workload on
durable clusters; the recovery state machine — not test scaffolding —
heals the cluster and no acknowledged commit is lost.

Ref: fdbserver/masterserver.actor.cpp masterCore (:1212),
TagPartitionedLogSystem.actor.cpp epochEnd (:1265), and the simulation
test strategy of workloads running *while* processes die
(fdbserver/workloads/MachineAttrition.actor.cpp, tests/fast/CycleTest.txt).
"""

import pytest

from foundationdb_tpu import flow
from foundationdb_tpu.client import run_transaction
from foundationdb_tpu.server import SimCluster


def _durable_cluster(seed, **kw):
    kw.setdefault("durable", True)
    return SimCluster(seed=seed, **kw)


@pytest.mark.parametrize("role,seed", [("tlog", 101), ("proxy", 102),
                                       ("resolver", 103)])
def test_kill_role_cluster_heals(role, seed):
    """Killing any transaction-subsystem role mid-stream triggers an
    epoch recovery; acknowledged writes survive, later writes work."""
    c = _durable_cluster(seed=seed)
    try:
        db = c.client()

        async def main():
            acked = []
            for i in range(5):
                async def body(tr, i=i):
                    tr.set(b"k%02d" % i, b"v%d" % i)
                await run_transaction(db, body)
                acked.append(i)
            c.kill_role(role)
            # commits must keep working through the recovery
            for i in range(5, 10):
                async def body(tr, i=i):
                    tr.set(b"k%02d" % i, b"v%d" % i)
                await run_transaction(db, body)
                acked.append(i)
            tr = db.create_transaction()
            got = await tr.get_range(b"k", b"l")
            assert got == [(b"k%02d" % i, b"v%d" % i) for i in acked]
            return True

        assert c.run(main(), timeout_time=600)
    finally:
        c.shutdown()


def test_kill_tlog_during_cycle_workload():
    """The Cycle invariant holds across a TLog kill mid-workload
    (ref: Cycle.actor.cpp stacked with Attrition)."""
    n = 6
    c = _durable_cluster(seed=7)
    try:
        db = c.client()
        dbs = [c.client(f"c{i}") for i in range(3)]

        async def setup():
            tr = db.create_transaction()
            for i in range(n):
                tr.set(b"cyc%02d" % i, b"%02d" % ((i + 1) % n))
            await tr.commit()

        async def swap_loop(db, iters):
            for _ in range(iters):
                async def body(tr):
                    a = flow.g_random.random_int(0, n)
                    b = int(await tr.get(b"cyc%02d" % a))
                    cc_ = int(await tr.get(b"cyc%02d" % b))
                    d = int(await tr.get(b"cyc%02d" % cc_))
                    tr.set(b"cyc%02d" % a, b"%02d" % cc_)
                    tr.set(b"cyc%02d" % cc_, b"%02d" % b)
                    tr.set(b"cyc%02d" % b, b"%02d" % d)
                await run_transaction(db, body, max_retries=200)

        async def killer():
            await flow.delay(0.05)
            c.kill_role("tlog")

        async def main():
            await setup()
            tasks = [flow.spawn(swap_loop(d, 6)) for d in dbs]
            tasks.append(flow.spawn(killer()))
            await flow.wait_for_all(tasks)

            async def check(tr):
                kvs = await tr.get_range(b"cyc", b"cyd")
                assert len(kvs) == n
                nxt = {int(k[3:]): int(v) for k, v in kvs}
                seen, cur = set(), 0
                while cur not in seen:
                    seen.add(cur)
                    cur = nxt[cur]
                assert len(seen) == n, f"cycle broken: {nxt}"
            await run_transaction(db, check, max_retries=50)
            return True

        assert c.run(main(), timeout_time=600)
    finally:
        c.shutdown()


def test_storage_worker_reboot_rejoins():
    """A killed storage worker auto-reboots, recovers its engine from
    disk, re-registers, and serves reads again — no epoch change
    needed."""
    c = _durable_cluster(seed=23)
    try:
        db = c.client()

        async def main():
            async def body(tr):
                tr.set(b"a", b"1")
                tr.set(b"b", b"2")
            await run_transaction(db, body)
            c.kill_role("storage")

            async def body2(tr):
                assert await tr.get(b"a") == b"1"
                tr.set(b"c", b"3")
            await run_transaction(db, body2, max_retries=200)
            tr = db.create_transaction()
            assert await tr.get(b"c") == b"3"
            return True

        assert c.run(main(), timeout_time=600)
    finally:
        c.shutdown()


def test_master_epoch_advances_on_kill():
    """Recovery bumps the epoch in the coordinated state and the
    broadcast dbinfo."""
    c = _durable_cluster(seed=41)
    try:
        db = c.client()

        async def main():
            async def body(tr):
                tr.set(b"x", b"1")
            await run_transaction(db, body)
            e0 = c.cc.dbinfo.get().epoch
            assert e0 >= 1
            c.kill_role("proxy")

            async def body2(tr):
                tr.set(b"y", b"2")
            await run_transaction(db, body2, max_retries=200)
            assert c.cc.dbinfo.get().epoch > e0
            return True

        assert c.run(main(), timeout_time=600)
    finally:
        c.shutdown()


def test_acked_commits_survive_power_loss_of_tlog():
    """Every acknowledged commit is readable after the TLog machine
    power-loses its unsynced writes and the cluster recovers (the
    durability contract end-to-end)."""
    c = _durable_cluster(seed=59)
    try:
        db = c.client()

        async def main():
            acked = {}
            for i in range(8):
                async def body(tr, i=i):
                    tr.set(b"p%02d" % i, b"v%d" % i)
                await run_transaction(db, body)
                acked[b"p%02d" % i] = b"v%d" % i
                if i == 4:
                    c.kill_role("tlog")
            tr = db.create_transaction()
            got = dict(await tr.get_range(b"p", b"q"))
            assert got == acked, (got, acked)
            return True

        assert c.run(main(), timeout_time=600)
    finally:
        c.shutdown()


def test_coordination_quorum_survives_minority_loss():
    """With 3 coordinators, killing one leaves the quorum working:
    recovery (coordinated-state read + exclusive write) still succeeds
    (ref: CoordinatedState majority quorums,
    CoordinatedState.actor.cpp:60-197)."""
    c = _durable_cluster(seed=211, n_coordinators=3)
    try:
        db = c.client()

        async def main():
            async def body(tr):
                tr.set(b"k", b"1")
            await run_transaction(db, body)
            # kill one coordinator (minority), then force a recovery
            c.net.kill(c.coordinators[0].process)
            c.kill_role("tlog")

            async def body2(tr):
                assert await tr.get(b"k") == b"1"
                tr.set(b"k2", b"2")
            await run_transaction(db, body2, max_retries=300)
            assert c.cc.dbinfo.get().epoch >= 2
            return True

        assert c.run(main(), timeout_time=600)
    finally:
        c.shutdown()


@pytest.mark.parametrize("seed", (91, 92, 93))
def test_whole_cluster_blackout_recovers_from_disks(seed):
    """Kill EVERY worker at the same instant mid-workload (total power
    event; only the coordinators/CC survive): the cluster must rebuild
    the transaction subsystem from the surviving disk stores with every
    acknowledged commit intact (ref: the simulation restart tests —
    recovery from durable state alone)."""
    c = _durable_cluster(seed, n_logs=2, n_storage=2, n_workers=6)
    try:
        db = c.client()

        async def main():
            acked = {}
            async def write(lo, hi):
                for i in range(lo, hi):
                    async def body(tr, i=i):
                        tr.set(b"bl%04d" % i, b"v%d" % i)
                    await run_transaction(db, body, max_retries=500)
                    acked[b"bl%04d" % i] = b"v%d" % i
            await write(0, 60)

            # total blackout: every worker dies in the same instant
            for name in list(c.workers):
                c.kill_worker(name)

            # auto-reboot + epoch recovery must heal from disks alone
            async def check(tr):
                rows = await tr.get_range(b"bl", b"bm")
                assert rows == sorted(acked.items()), (
                    len(rows), len(acked))
            await run_transaction(db, check, max_retries=800)

            # and the healed cluster keeps accepting commits
            await write(60, 80)
            await run_transaction(db, check, max_retries=500)
            return True

        assert c.run(main(), timeout_time=900)
    finally:
        c.shutdown()


def test_full_process_restart_from_real_disks(tmp_path):
    """The ULTIMATE durability test: the entire cluster object is
    discarded (process death) and a brand-new one boots from REAL
    on-disk state — coordinated state, log stores, storage stores —
    with every acknowledged commit intact (ref: the reference's restart
    tests: kill fdbserver, restart from the data directory)."""
    data = str(tmp_path / "data")

    def boot(seed):
        return SimCluster(seed=seed, durable=True, n_logs=2, n_storage=2,
                          data_dir=data)

    c1 = boot(201)
    try:
        db = c1.client()

        async def main():
            async def w(tr):
                for i in range(80):
                    tr.set(b"pr%03d" % i, b"v%d" % i)
            await run_transaction(db, w)
            # settle durability so the disks hold everything acked
            await c1.quiet_database()
            return True

        assert c1.run(main(), timeout_time=300)
    finally:
        c1.shutdown()

    # a completely new "process": fresh scheduler, network, CC,
    # coordinators — only the directory carries over
    c2 = boot(202)
    try:
        db2 = c2.client()

        async def main2():
            async def check(tr):
                rows = await tr.get_range(b"pr", b"ps")
                assert len(rows) == 80, len(rows)
                assert await tr.get(b"pr042") == b"v42"
                tr.set(b"after-restart", b"1")
            await run_transaction(db2, check, max_retries=500)
            # the restarted cluster recovered INTO a later epoch, not a
            # fresh database (the coordinated state survived)
            info = c2.cc.dbinfo.get()
            assert info.epoch >= 2, info.epoch

            async def check2(tr):
                assert await tr.get(b"after-restart") == b"1"
            await run_transaction(db2, check2, max_retries=500)
            return True

        assert c2.run(main2(), timeout_time=600)
    finally:
        c2.shutdown()
