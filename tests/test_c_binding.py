"""Native C client binding e2e: real sockets, real wire protocol.

A wall-clock SimCluster serves its client endpoints through a
TcpGateway (rpc/gateway.py) in a background thread; the C library
(bindings/c/fdb_tpu.cpp, loaded via ctypes) connects from the test
thread like any out-of-process client and must deliver the full client
contract — RYW, atomics, shard-routed range reads, selectors, OCC
conflicts, and the on_error retry protocol.

Ref: bindings/c/fdb_c.cpp + bindings/python/fdb (the binding surface),
fdbclient/NativeAPI.actor.cpp (the client logic the C library
re-implements), bindings/bindingtester (cross-binding parity — see
test_cross_binding_parity).
"""

import queue
import random
import threading

import pytest

from foundationdb_tpu.bindings.c_client import (CClientError, CDatabase,
                                                load_library)

pytestmark = pytest.mark.filterwarnings("ignore::pytest.PytestUnhandledThreadExceptionWarning")


class GatewayedCluster:
    """Wall-clock SimCluster + TcpGateway on a background thread."""

    def __init__(self, gateway_protocol: bytes = None, **kw):
        self.kw = kw
        self.gateway_protocol = gateway_protocol
        self.q: queue.Queue = queue.Queue()
        self.stop = threading.Event()
        self.thread = threading.Thread(target=self._main, daemon=True)
        self.port = None

    def __enter__(self):
        self.thread.start()
        item = self.q.get(timeout=120)
        if isinstance(item, BaseException):
            raise item
        self.port = item
        return self

    def __exit__(self, *exc):
        self.stop.set()
        self.thread.join(timeout=120)
        # surface any simulation-thread crash that happened after the
        # port was handed out — otherwise it shows up only as an opaque
        # C-client timeout
        while not self.q.empty():
            item = self.q.get_nowait()
            if isinstance(item, BaseException) and exc == (None, None, None):
                raise item

    def _main(self):
        import foundationdb_tpu.flow as fl
        from foundationdb_tpu.rpc.gateway import TcpGateway
        from foundationdb_tpu.server.cluster import SimCluster

        gw = None
        c = None
        try:
            c = SimCluster(virtual=False, **self.kw)
            db = c.client("gateway-host")
            gw = TcpGateway(db, protocol=self.gateway_protocol)

            async def main():
                gw.start()
                self.q.put(gw.port)
                while not self.stop.is_set():
                    await fl.delay(0.02)

            c.run(main())
        except BaseException as e:  # noqa: BLE001 — surface to the test
            self.q.put(e)
        finally:
            if gw is not None:
                gw.close()
            if c is not None:
                c.shutdown()


def test_c_client_end_to_end():
    load_library()
    with GatewayedCluster(seed=21, n_storage=2, n_proxies=2) as gc:
        db = CDatabase("127.0.0.1", gc.port)
        try:
            tr = db.create_transaction()

            # blind writes on both sides of the shard split + commit
            tr.set(b"alpha", b"1")
            tr.set(b"zeta", b"26")
            tr.set(b"beta", b"2")
            v1 = tr.commit()
            assert v1 > 0
            stamp = tr.get_versionstamp()
            assert len(stamp) == 10
            assert int.from_bytes(stamp[:8], "big") == v1

            # fresh transaction observes the commit; RYW overlays
            tr.reset()
            assert tr.get(b"alpha") == b"1"
            assert tr.get(b"missing") is None
            tr.set(b"alpha", b"overlaid")
            assert tr.get(b"alpha") == b"overlaid"
            tr.clear(b"beta")
            assert tr.get(b"beta") is None
            # cross-shard range read merges base + overlay
            rows = tr.get_range(b"a", b"zz")
            assert rows == [(b"alpha", b"overlaid"), (b"zeta", b"26")]
            rows_rev = tr.get_range(b"a", b"zz", reverse=True)
            assert rows_rev == rows[::-1]
            rows_lim = tr.get_range(b"a", b"zz", limit=1)
            assert rows_lim == [(b"alpha", b"overlaid")]
            tr.commit()

            # atomics: server-side apply + RYW fold
            tr.reset()
            tr.atomic_op(b"ctr", (5).to_bytes(8, "little"), 2)  # ADD
            assert tr.get(b"ctr") == (5).to_bytes(8, "little")
            tr.commit()
            tr.reset()
            tr.atomic_op(b"ctr", (7).to_bytes(8, "little"), 2)
            assert tr.get(b"ctr") == (12).to_bytes(8, "little")
            tr.commit()
            tr.reset()
            assert tr.get(b"ctr") == (12).to_bytes(8, "little")

            # selectors: firstGreaterThan walks to the next present key
            assert tr.get_key(b"alpha", True, 1) == b"ctr"
            # lastLessThan from beyond the end resolves the last key
            assert tr.get_key(b"\xfe", False, 0) == b"zeta"

            # OCC conflict: two readers of the same key, both write it
            t1 = db.create_transaction()
            t2 = db.create_transaction()
            assert t1.get(b"occ") is None
            assert t2.get(b"occ") is None
            t1.set(b"occ", b"first")
            t1.commit()
            t2.set(b"occ", b"second")
            with pytest.raises(CClientError) as ei:
                t2.commit()
            assert ei.value.code == 1020  # not_committed
            t2.on_error(ei.value.code)    # resets for retry
            assert t2.get(b"occ") == b"first"
            t2.set(b"occ", b"second")
            t2.commit()
            t1.destroy()
            t2.destroy()

            # explicit conflict ranges
            t3 = db.create_transaction()
            t3.get_read_version()  # snapshot predates t4's commit
            t3.add_conflict_range(b"occ", b"occ\x00", write=False)
            t3.set(b"unrelated", b"x")
            t4 = db.create_transaction()
            t4.set(b"occ", b"third")
            t4.commit()
            with pytest.raises(CClientError) as ei:
                t3.commit()
            assert ei.value.code == 1020
            t3.destroy()
            t4.destroy()

            # error table sanity
            lib = load_library()
            assert lib.fdb_tpu_get_error(1020) == b"not_committed"
            assert lib.fdb_tpu_error_retryable(1020) == 1
            assert lib.fdb_tpu_error_retryable(2000) == 0

            # system-keyspace gate parity with the Python client: \xff
            # reads/writes need the option; scans clamp at user space
            t5 = db.create_transaction()
            for op in (lambda: t5.get(b"\xff/x"),
                       lambda: t5.get_range(b"", b"\xff\xf0"),
                       lambda: t5.set(b"\xff\x02/own", b"x"),
                       lambda: t5.atomic_op(
                           b"\xff/x", (1).to_bytes(8, "little"), 2)):
                with pytest.raises(CClientError) as ei:
                    op()
                assert ei.value.code == 2004, ei.value
            # selectors walking off the end clamp to \xff, not \xff\x02
            assert t5.get_key(b"\xfe", False, 9) == b"\xff"
            with pytest.raises(CClientError) as ei:
                t5.set_option("bogus_option")
            assert ei.value.code == 2006
            t5.set_option("access_system_keys")
            t5.set(b"\xff\x02/own", b"x")       # stored subspace: allowed
            t5.commit()
            t5.reset()                           # options reset
            with pytest.raises(CClientError):
                t5.get(b"\xff\x02/own")
            t5.set_option("read_system_keys")
            assert t5.get(b"\xff\x02/own") == b"x"
            with pytest.raises(CClientError):
                t5.set(b"\xff\x02/own", b"y")    # read option: no writes
            t5.destroy()

            tr.destroy()
        finally:
            db.close()


def _make_script(seed: int, n_ops: int = 80):
    """Deterministic op script both bindings execute (the bindingtester
    idiom: same instruction stream, byte-compared outcomes)."""
    rng = random.Random(seed)
    keys = [b"bt/%02d" % i for i in range(14)] + \
           [b"bt/\x00bin", b"bt/\xfe\xff", b"bt/"]
    atomic_ops = [2, 6, 7, 8, 9, 12, 13, 16, 17, 18, 19, 20]

    def rkey():
        return rng.choice(keys)

    def rval():
        return bytes(rng.randrange(256) for _ in range(rng.randrange(0, 9)))

    script = []
    for _ in range(n_ops):
        c = rng.random()
        if c < 0.22:
            script.append(("set", rkey(), rval()))
        elif c < 0.30:
            script.append(("clear", rkey()))
        elif c < 0.36:
            a, b = sorted((rkey(), rkey()))
            script.append(("clear_range", a, b + b"\x00"))
        elif c < 0.56:
            script.append(("get", rkey()))
        elif c < 0.70:
            a, b = sorted((rkey(), rkey()))
            script.append(("get_range", a, b + b"\x00",
                           rng.choice([0, 1, 2, 5]),
                           rng.random() < 0.3))
        elif c < 0.78:
            script.append(("get_key", rkey(), rng.random() < 0.5,
                           rng.randrange(-2, 3)))
        elif c < 0.92:
            script.append(("atomic", rkey(), rval(),
                           rng.choice(atomic_ops)))
        else:
            script.append(("commit",))
    return script


def _run_script_python(script, seed):
    """Execute on the in-process Python binding (virtual-time cluster)."""
    from foundationdb_tpu.server.cluster import SimCluster
    from foundationdb_tpu.server.types import KeySelector

    c = SimCluster(seed=seed, n_storage=2)
    try:
        db = c.client()
        results = []

        async def main():
            tr = db.create_transaction()
            for op in script:
                if op[0] == "set":
                    tr.set(op[1], op[2])
                elif op[0] == "clear":
                    tr.clear(op[1])
                elif op[0] == "clear_range":
                    tr.clear_range(op[1], op[2])
                elif op[0] == "get":
                    results.append(("get", await tr.get(op[1])))
                elif op[0] == "get_range":
                    limit = op[3] if op[3] else 1 << 20
                    results.append(("range", await tr.get_range(
                        op[1], op[2], limit=limit, reverse=op[4])))
                elif op[0] == "get_key":
                    results.append(("key", await tr.get_key(
                        KeySelector(op[1], op[2], op[3]))))
                elif op[0] == "atomic":
                    tr.atomic_op(op[1], op[2], op[3])
                elif op[0] == "commit":
                    await tr.commit()
                    tr = db.create_transaction()
            await tr.commit()
            tr2 = db.create_transaction()
            results.append(("final", await tr2.get_range(b"", b"\xff")))
            return True

        assert c.run(main(), timeout_time=600)
        return results
    finally:
        c.shutdown()


def _run_script_c(script, seed):
    """Execute the same stream through the C binding over the gateway."""
    with GatewayedCluster(seed=seed, n_storage=2) as gc:
        db = CDatabase("127.0.0.1", gc.port)
        try:
            results = []
            tr = db.create_transaction()
            for op in script:
                if op[0] == "set":
                    tr.set(op[1], op[2])
                elif op[0] == "clear":
                    tr.clear(op[1])
                elif op[0] == "clear_range":
                    tr.clear_range(op[1], op[2])
                elif op[0] == "get":
                    results.append(("get", tr.get(op[1])))
                elif op[0] == "get_range":
                    results.append(("range", tr.get_range(
                        op[1], op[2], limit=op[3], reverse=op[4])))
                elif op[0] == "get_key":
                    results.append(("key", tr.get_key(op[1], op[2], op[3])))
                elif op[0] == "atomic":
                    tr.atomic_op(op[1], op[2], op[3])
                elif op[0] == "commit":
                    tr.commit()
                    tr.reset()
            tr.commit()
            tr.reset()
            results.append(("final", tr.get_range(b"", b"\xff")))
            tr.destroy()
            return results
        finally:
            db.close()


@pytest.mark.parametrize("seed", [101, 202, 303])
def test_cross_binding_parity(seed):
    """bindingtester analogue: an identical randomized instruction
    stream through the Python binding and the native C binding must
    produce byte-identical outcomes — every get, every range (with
    limits/reverse/RYW overlay/atomic folds), every selector
    resolution, and the final full scan (ref: bindings/bindingtester —
    same stack machine, compared results)."""
    load_library()
    script = _make_script(seed)
    py = _run_script_python(script, seed)
    cc = _run_script_c(script, seed)
    assert len(py) == len(cc)
    for i, (a, b) in enumerate(zip(py, cc)):
        assert a == b, f"op result {i} diverged: python={a!r} c={b!r}"


def test_c_binding_watch():
    """The C binding's blocking watch fires when another client writes
    the key (ref: fdb_transaction_watch; thread-safe blocking shape)."""
    import threading

    load_library()
    with GatewayedCluster(seed=22) as gc:
        db = CDatabase("127.0.0.1", gc.port)
        try:
            tr = db.create_transaction()
            tr.set(b"wkey", b"v0")
            tr.commit()
            tr.destroy()

            fired = []

            def watcher():
                db.watch(b"wkey", timeout_ms=30000)
                fired.append(True)

            t = threading.Thread(target=watcher)
            t.start()
            import time
            time.sleep(0.3)   # let the long poll arm
            assert not fired

            t2 = db.create_transaction()
            t2.set(b"wkey", b"v1")
            t2.commit()
            t2.destroy()
            t.join(timeout=30)
            assert fired, "watch never fired"
        finally:
            db.close()


def test_cross_binding_parity_deep():
    """A longer instruction stream (300 ops) through both bindings —
    the bindingtester's depth knob (kept to one seed so the suite
    stays fast; more seeds ran in round-3 sweeps)."""
    load_library()
    script = _make_script(911, n_ops=300)
    py = _run_script_python(script, 911)
    cc = _run_script_c(script, 911)
    assert py == cc
