"""Tuple layer + Subspace: round-trips, the order-preserving property,
and spec-pinned encodings (ref: fdbclient/Tuple.cpp, design/tuple.md,
bindings/python/fdb/tuple.py; Subspace.cpp)."""

import random
import uuid

import pytest

from foundationdb_tpu.layers import Subspace, Versionstamp, tuple_layer

pack = tuple_layer.pack
unpack = tuple_layer.unpack


def test_spec_pinned_encodings():
    # byte-for-byte values from the cross-binding tuple spec
    assert pack((None,)) == b"\x00"
    assert pack((b"foo\x00bar",)) == b"\x01foo\x00\xffbar\x00"
    assert pack(("FÔO",)) == b"\x02F\xc3\x94O\x00"
    assert pack((0,)) == b"\x14"
    assert pack((5,)) == b"\x15\x05"
    assert pack((-5,)) == b"\x13\xfa"
    assert pack((255,)) == b"\x15\xff"
    assert pack((256,)) == b"\x16\x01\x00"
    assert pack((True,)) == b"\x27"
    assert pack((False,)) == b"\x26"
    assert pack(((b"a", None),)) == b"\x05\x01a\x00\x00\xff\x00"


def test_roundtrip_random_tuples():
    rng = random.Random(77)

    def rand_val(depth=0):
        kind = rng.randrange(8 if depth < 2 else 7)
        if kind == 0:
            return None
        if kind == 1:
            return rng.choice([True, False])
        if kind == 2:
            return rng.randint(-(1 << 60), 1 << 60)
        if kind == 3:
            return bytes(rng.randrange(256) for _ in range(rng.randrange(6)))
        if kind == 4:
            return "".join(chr(rng.randrange(32, 1000))
                           for _ in range(rng.randrange(5)))
        if kind == 5:
            return rng.uniform(-1e10, 1e10)
        if kind == 6:
            return uuid.UUID(int=rng.getrandbits(128))
        return tuple(rand_val(depth + 1) for _ in range(rng.randrange(3)))

    for _ in range(300):
        t = tuple(rand_val() for _ in range(rng.randrange(4)))
        assert unpack(pack(t)) == t, t


def test_order_preserving():
    rng = random.Random(78)
    ints = sorted(rng.randint(-(1 << 50), 1 << 50) for _ in range(200))
    packed = [pack((i,)) for i in ints]
    assert packed == sorted(packed)

    floats = sorted(rng.uniform(-1e9, 1e9) for _ in range(200))
    packed = [pack((f,)) for f in floats]
    assert packed == sorted(packed)

    words = sorted(bytes(rng.randrange(1, 256) for _ in range(
        rng.randrange(1, 5))) for _ in range(100))
    packed = [pack((w,)) for w in words]
    assert packed == sorted(packed)

    # escaped NUL bytes keep ordering too
    ks = sorted([b"a", b"a\x00", b"a\x00b", b"a\x01", b"ab"])
    packed = [pack((k,)) for k in ks]
    assert packed == sorted(packed)


def test_versionstamp_roundtrip_and_order():
    a = Versionstamp(bytes(range(12)))
    b = Versionstamp(bytes(range(1, 13)))
    assert unpack(pack((a,))) == (a,)
    assert pack((a,)) < pack((b,))


def test_subspace():
    s = Subspace(("users",))
    k = s.pack((42, "bob"))
    assert s.contains(k)
    assert s.unpack(k) == (42, "bob")
    nested = s[42]
    assert nested.pack(("bob",)) == k
    b, e = s.range()
    assert b < k < e
    with pytest.raises(Exception):
        s.unpack(b"\x01zzz\x00")


def test_tuple_keys_through_the_database():
    """Tuple-packed keys sort correctly through a real cluster range
    read (the layer working end-to-end)."""
    from foundationdb_tpu.client import run_transaction
    from foundationdb_tpu.server import SimCluster

    c = SimCluster(seed=601)
    try:
        db = c.client()
        s = Subspace(("t",))

        async def main():
            rows = [(5, "a"), (5, "b"), (10, "a"), (-3, "z")]

            async def body(tr):
                for i, (n, w) in enumerate(rows):
                    tr.set(s.pack((n, w)), b"%d" % i)
            await run_transaction(db, body)
            tr = db.create_transaction()
            b, e = s.range()
            got = await tr.get_range(b, e)
            keys = [s.unpack(k) for k, _v in got]
            assert keys == sorted(rows), keys
            return True

        assert c.run(main(), timeout_time=120)
    finally:
        c.shutdown()
