"""End-to-end simulated cluster: the minimum slice running real
transactions through master -> proxy -> resolver -> tlog -> storage on
the deterministic loop (ref test strategy: whole-system simulation,
fdbserver/SimulatedCluster.actor.cpp; workload models: Cycle.actor.cpp,
Increment.actor.cpp, WriteDuringRead.actor.cpp)."""

import pytest

from foundationdb_tpu import flow
from foundationdb_tpu.client import run_transaction
from foundationdb_tpu.server import SimCluster


@pytest.fixture
def cluster():
    c = SimCluster(seed=1)
    yield c
    c.shutdown()


def test_set_get_commit(cluster):
    db = cluster.client()

    async def main():
        tr = db.create_transaction()
        tr.set(b"hello", b"world")
        v = await tr.commit()
        assert v > 0
        tr2 = db.create_transaction()
        got = await tr2.get(b"hello")
        assert got == b"world"
        assert await tr2.get(b"missing") is None
        return True

    assert cluster.run(main(), timeout_time=30)


def test_read_your_writes(cluster):
    db = cluster.client()

    async def main():
        tr = db.create_transaction()
        tr.set(b"a", b"1")
        assert await tr.get(b"a") == b"1"          # uncommitted write visible
        tr.clear(b"a")
        assert await tr.get(b"a") is None
        tr.set(b"b", b"2")
        tr.set(b"d", b"4")
        tr.clear_range(b"c", b"e")
        tr.set(b"d2", b"5")
        got = await tr.get_range(b"a", b"z")
        assert got == [(b"b", b"2"), (b"d2", b"5")]
        await tr.commit()
        tr2 = db.create_transaction()
        assert await tr2.get_range(b"a", b"z") == [(b"b", b"2"), (b"d2", b"5")]
        return True

    assert cluster.run(main(), timeout_time=30)


def test_conflicting_transactions(cluster):
    """Reader's snapshot invalidated by a concurrent write -> not_committed,
    then the retry loop succeeds (ref: OCC contract)."""
    db = cluster.client()

    async def main():
        setup = db.create_transaction()
        setup.set(b"k", b"0")
        await setup.commit()

        t1 = db.create_transaction()
        t2 = db.create_transaction()
        v1 = await t1.get(b"k")
        v2 = await t2.get(b"k")
        assert v1 == v2 == b"0"
        t1.set(b"k", b"t1")
        t2.set(b"k", b"t2")
        await t1.commit()
        with pytest.raises(flow.FdbError) as ei:
            await t2.commit()
        assert ei.value.name == "not_committed"
        return True

    assert cluster.run(main(), timeout_time=30)


def test_snapshot_reads_do_not_conflict(cluster):
    db = cluster.client()

    async def main():
        setup = db.create_transaction()
        setup.set(b"k", b"0")
        await setup.commit()
        t1 = db.create_transaction()
        t2 = db.create_transaction()
        await t1.get(b"k", snapshot=True)
        await t2.get(b"k")
        t1.set(b"k", b"t1")
        t2.set(b"other", b"x")
        await t2.commit()
        await t1.commit()  # snapshot read: no conflict
        return True

    assert cluster.run(main(), timeout_time=30)


def test_increment_workload(cluster):
    """N concurrent clients increment shared counters; the sum must equal
    the number of successful increments (ref: Increment.actor.cpp)."""
    dbs = [cluster.client(f"client{i}") for i in range(5)]
    done = []

    async def incr_loop(db, n):
        for _ in range(n):
            async def body(tr):
                k = b"ctr%d" % (flow.g_random.random_int(0, 3),)
                cur = await tr.get(k)
                tr.set(k, b"%d" % (int(cur or b"0") + 1))
            await run_transaction(db, body)
            done.append(1)

    async def main():
        tasks = [flow.spawn(incr_loop(db, 10)) for db in dbs]
        await flow.wait_for_all(tasks)
        tr = dbs[0].create_transaction()
        kvs = await tr.get_range(b"ctr", b"cts")
        total = sum(int(v) for _, v in kvs)
        assert total == 50, (total, kvs)
        return True

    assert cluster.run(main(), timeout_time=120)


def test_cycle_workload(cluster):
    """The Cycle invariant: keys form a permutation cycle; transactions
    rotate pointers; the cycle stays intact (ref: Cycle.actor.cpp)."""
    n = 8
    db = cluster.client()
    dbs = [cluster.client(f"c{i}") for i in range(3)]

    async def setup():
        tr = db.create_transaction()
        for i in range(n):
            tr.set(b"cyc%02d" % i, b"%02d" % ((i + 1) % n))
        await tr.commit()

    async def swap_loop(db, iters):
        for _ in range(iters):
            async def body(tr):
                # pick a random node a -> b -> c -> d; swap b and c
                a = flow.g_random.random_int(0, n - 1)
                b = int(await tr.get(b"cyc%02d" % a))
                c = int(await tr.get(b"cyc%02d" % b))
                d = int(await tr.get(b"cyc%02d" % c))
                tr.set(b"cyc%02d" % a, b"%02d" % c)
                tr.set(b"cyc%02d" % c, b"%02d" % b)
                tr.set(b"cyc%02d" % b, b"%02d" % d)
            await run_transaction(db, body)

    async def check():
        tr = db.create_transaction()
        kvs = await tr.get_range(b"cyc", b"cyd")
        assert len(kvs) == n
        nxt = {int(k[3:]): int(v) for k, v in kvs}
        seen, cur = set(), 0
        while cur not in seen:
            seen.add(cur)
            cur = nxt[cur]
        assert len(seen) == n, f"cycle broken: {nxt}"

    async def main():
        await setup()
        await flow.wait_for_all([flow.spawn(swap_loop(d, 8)) for d in dbs])
        await check()
        return True

    assert cluster.run(main(), timeout_time=240)


def test_random_ops_vs_model():
    """Sequential random transactions cross-checked against a model dict
    (ref: WriteDuringRead.actor.cpp memoryDatabase replay)."""
    c = SimCluster(seed=7)
    try:
        db = c.client()
        model = {}

        async def main():
            rng = flow.g_random
            for _round in range(40):
                tr = db.create_transaction()
                staged = dict(model)
                for _op in range(rng.random_int(1, 6)):
                    op = rng.random_int(0, 3)
                    k = b"%c" % (0x61 + rng.random_int(0, 9))
                    if op == 0:
                        v = b"v%d" % rng.random_int(0, 99)
                        tr.set(k, v)
                        staged[k] = v
                    elif op == 1:
                        tr.clear(k)
                        staged.pop(k, None)
                    elif op == 2:
                        got = await tr.get(k)
                        assert got == staged.get(k), (k, got, staged.get(k))
                    else:
                        e = b"%c" % (0x61 + rng.random_int(0, 9))
                        if k > e:
                            k, e = e, k
                        got = await tr.get_range(k, e)
                        want = sorted((kk, vv) for kk, vv in staged.items()
                                      if k <= kk < e)
                        assert got == want, (k, e, got, want)
                await tr.commit()
                model.clear()
                model.update(staged)
            return True

        assert c.run(main(), timeout_time=120)
    finally:
        c.shutdown()


def test_clogged_network_still_correct():
    c = SimCluster(seed=3)
    try:
        db = c.client()

        async def main():
            tr = db.create_transaction()
            tr.set(b"x", b"1")
            await tr.commit()
            # clog links between worker machines mid-run
            c.net.clog_pair("w0", "w1", 2.0)
            c.net.clog_pair("w0", "w2", 1.0)
            tr2 = db.create_transaction()
            tr2.set(b"x", b"2")
            await tr2.commit()
            tr3 = db.create_transaction()
            assert await tr3.get(b"x") == b"2"
            return True

        assert c.run(main(), timeout_time=60)
    finally:
        c.shutdown()


def test_determinism_same_seed_same_schedule():
    """Seed replay: identical task counts, versions, and message counts
    (the determinism oracle, ref: sim2 + DeterministicRandom)."""

    def one_run(seed):
        c = SimCluster(seed=seed)
        try:
            dbs = [c.client(f"c{i}") for i in range(3)]

            async def incr(db, n):
                for _ in range(n):
                    async def body(tr):
                        cur = await tr.get(b"k")
                        tr.set(b"k", b"%d" % (int(cur or b"0") + 1))
                    await run_transaction(db, body)

            async def main():
                await flow.wait_for_all(
                    [flow.spawn(incr(db, 5)) for db in dbs])
                tr = dbs[0].create_transaction()
                val = await tr.get(b"k")
                return (val, c.sched.now(), c.sched.tasks_run,
                        c.net.messages_sent)

            return c.run(main(), timeout_time=120)
        finally:
            c.shutdown()

    a = one_run(42)
    b = one_run(42)
    d = one_run(43)
    assert a == b, f"seed replay diverged: {a} != {b}"
    assert a[0] == b"15" == d[0]
    assert a != d  # different seed explores a different schedule


@pytest.mark.parametrize("backend", ["tpu", "native"])
def test_cluster_with_accelerated_resolver(backend):
    """The same cluster with the TPU (and native C++) conflict backend
    plugged into the resolver role — the plugin seam working end-to-end
    (ref: LoadPlugin boundary; backend parity is separately fuzzed)."""
    if backend == "native":
        from foundationdb_tpu.models import native_available
        if not native_available():
            pytest.skip("native backend unavailable")
    c = SimCluster(seed=11, conflict_backend=backend)
    try:
        db = c.client()

        async def main():
            setup = db.create_transaction()
            setup.set(b"k", b"0")
            await setup.commit()
            t1 = db.create_transaction()
            t2 = db.create_transaction()
            assert await t1.get(b"k") == b"0"
            assert await t2.get(b"k") == b"0"
            t1.set(b"k", b"t1")
            t2.set(b"k", b"t2")
            await t1.commit()
            try:
                await t2.commit()
                raise AssertionError("expected not_committed")
            except flow.FdbError as e:
                assert e.name == "not_committed"
            # and the retry loop converges
            for i in range(10):
                async def body(tr, i=i):
                    cur = await tr.get(b"k")
                    tr.set(b"k", cur + b".%d" % i)
                await run_transaction(db, body)
            tr = db.create_transaction()
            final = await tr.get(b"k")
            assert final == b"t1" + b"".join(b".%d" % i for i in range(10))
            return True

        assert c.run(main(), timeout_time=120)
    finally:
        c.shutdown()


def test_multi_resolver_cluster():
    """Key-range split across 3 resolver roles with min-combined verdicts
    (ref: ResolutionRequestBuilder / combine at :585-592): same outcomes
    as single-resolver, including cross-shard conflict ranges."""
    c = SimCluster(seed=17, n_resolvers=3)
    try:
        db = c.client()

        async def main():
            tr = db.create_transaction()
            # keys on different resolver shards (split at 0x55, 0xaa)
            tr.set(b"\x10a", b"1")
            tr.set(b"\x80b", b"2")
            tr.set(b"\xf0c", b"3")
            await tr.commit()
            # cross-shard range read conflicts with a write on shard 2
            t1 = db.create_transaction()
            t2 = db.create_transaction()
            got = await t1.get_range(b"\x00", b"\xff")
            assert len(got) == 3
            await t2.get(b"\x80b")
            t1.set(b"sentinel", b"x")
            t2.set(b"\x10a", b"22")
            await t2.commit()   # invalidates t1's range read
            try:
                await t1.commit()
                raise AssertionError("expected not_committed")
            except flow.FdbError as e:
                assert e.name == "not_committed"
            # increments across shards still converge
            for i in range(6):
                async def body(tr, i=i):
                    k = bytes([40 * i]) + b"k"
                    cur = await tr.get(k)
                    tr.set(k, b"%d" % (int(cur or b"0") + 1))
                await run_transaction(db, body)
            return True

        assert c.run(main(), timeout_time=120)
    finally:
        c.shutdown()


def test_long_key_rejected_batch_does_not_wedge_cluster():
    """ADVICE r1 (medium): with the tpu conflict backend, a key wider
    than the backend's key bucket used to raise inside the resolver
    actor, dropping the reply and wedging every later batch. Now the
    batch is conflicted (clients retry/fail) and the pipeline advances."""
    c = SimCluster(seed=31, conflict_backend="tpu")
    try:
        db = c.client()

        async def main():
            async def good(tr):
                tr.set(b"ok1", b"v")
            await run_transaction(db, good)

            # wider than the 32-byte tpu bucket: must fail, not wedge
            tr = db.create_transaction()
            tr.set(b"x" * 64, b"v")
            rejected = False
            try:
                await tr.commit()
            except flow.FdbError:
                rejected = True
            assert rejected

            # the pipeline must still be live for later transactions
            async def after(tr):
                tr.set(b"ok2", b"w")
            await run_transaction(db, after)

            async def check(tr):
                return (await tr.get(b"ok1"), await tr.get(b"ok2"))
            assert await run_transaction(db, check) == (b"v", b"w")
            return True

        assert c.run(main(), timeout_time=60)
    finally:
        c.shutdown()


def test_range_limit_clamps_read_conflict():
    """A limited range read only conflicts on the portion actually
    observed (ADVICE r1: the full [begin,end) was recorded, producing
    spurious conflicts)."""
    c = SimCluster(seed=32)
    try:
        db = c.client()

        async def main():
            async def seed_data(tr):
                for i in range(5):
                    tr.set(b"rl%02d" % i, b"v")
            await run_transaction(db, seed_data)

            # reader observes only the first row of the range...
            tr = db.create_transaction()
            rows = await tr.get_range(b"rl", b"rm", limit=1)
            assert [k for k, _ in rows] == [b"rl00"]
            # ...while a concurrent write lands far past the observed key
            tr2 = db.create_transaction()
            tr2.set(b"rl04", b"clobber")
            await tr2.commit()
            tr.set(b"unrelated", b"x")
            await tr.commit()  # must NOT conflict

            # control: observing the written key does conflict
            tr3 = db.create_transaction()
            await tr3.get_range(b"rl", b"rm", limit=5)
            tr4 = db.create_transaction()
            tr4.set(b"rl02", b"c2")
            await tr4.commit()
            tr3.set(b"unrelated2", b"y")
            try:
                await tr3.commit()
            except flow.FdbError as e:
                return e.name
            return "committed"

        assert c.run(main(), timeout_time=60) == "not_committed"
    finally:
        c.shutdown()


def test_tlog_tolerates_reordered_pushes():
    """The proxy releases its logging interlock at push time, so two
    TLogCommitRequests can be in flight and the network may deliver the
    LATER one first. The TLog must sequence them via queue_version
    without wedging (review r2: a serial commit loop deadlocked here)."""
    from foundationdb_tpu.server.tlog import TLog
    from foundationdb_tpu.server.types import (TLogCommitRequest, MutationRef,
                                           SET_VALUE, TaggedMutation)

    import foundationdb_tpu.flow as fl
    from foundationdb_tpu.rpc import SimNetwork

    s = fl.Scheduler(virtual=True)
    fl.set_scheduler(s)
    try:
        net = SimNetwork(s, fl.g_random)
        proc = net.new_process("tlog", machine="m")
        tlog = TLog(proc)
        tlog.start()

        async def main():
            m = (TaggedMutation((0,), MutationRef(SET_VALUE, b"k", b"v")),)
            # deliver the SECOND batch first
            f2 = tlog.commits.ref().get_reply(
                TLogCommitRequest(100, 200, m), proc)
            await fl.delay(0.01)
            f1 = tlog.commits.ref().get_reply(
                TLogCommitRequest(0, 100, m), proc)
            v2 = await f2
            v1 = await f1
            assert v1 >= 100 and v2 >= 200
            assert [v for v, _m, _s in tlog.entries] == [100, 200]
            return True

        t = s.spawn(main())
        assert s.run(until=t, timeout_time=10)
    finally:
        fl.set_scheduler(None)


def test_commit_batches_close_on_byte_limit():
    """COMMIT_TRANSACTION_BATCH_BYTES_MAX bounds batch payloads: large
    transactions still commit correctly when every batch closes early."""
    c = SimCluster(seed=95)
    flow.SERVER_KNOBS.init("COMMIT_TRANSACTION_BATCH_BYTES_MAX", 2048)
    try:
        db = c.client()

        async def main():
            big = b"B" * 900
            async def body(tr):
                for i in range(8):
                    tr.set(b"byte%02d" % i, big)
            await run_transaction(db, body)

            async def check(tr):
                rows = await tr.get_range(b"byte", b"bytf")
                assert len(rows) == 8
                assert all(v == big for _k, v in rows)
            await run_transaction(db, check)
            return True

        assert c.run(main(), timeout_time=120)
    finally:
        c.shutdown()
        flow.reset_server_knobs()


def test_resolver_state_pressure_is_surfaced():
    """A conflict history beyond RESOLVER_STATE_MEMORY_LIMIT (rows,
    here) raises the ResolverStatePressure trace — the GC-behind red
    flag (ref: Resolver.actor.cpp memory back-pressure)."""
    c = SimCluster(seed=96)
    flow.SERVER_KNOBS.init("RESOLVER_STATE_MEMORY_LIMIT", 50)
    try:
        db = c.client()

        async def main():
            async def body(tr):
                for i in range(200):
                    tr.set(b"pr%04d" % i, b"x")
            await run_transaction(db, body)
            for _ in range(40):
                if flow.g_trace.counts.get("ResolverStatePressure", 0):
                    return True
                async def more(tr):
                    tr.set(b"prx", b"y")
                await run_transaction(db, more)
                await flow.delay(0.2)
            raise AssertionError("pressure never traced")

        assert c.run(main(), timeout_time=120)
    finally:
        c.shutdown()
        flow.reset_server_knobs()


def test_sim_validation_catches_broken_maps():
    """The always-on validator (ref: sim_validation.cpp) fails fast on
    a gapped shard map, duplicate tags, or a regressed epoch — and its
    live instance has actually been checking this cluster."""
    from foundationdb_tpu.server.sim_validation import validate_dbinfo

    c = SimCluster(seed=97, n_storage=2)
    try:
        db = c.client()

        async def main():
            await db.info()
            info = c.cc.dbinfo.get()
            validate_dbinfo(info, {})   # the real picture passes

            dup = info._replace(storages=(
                info.storages[0],
                info.storages[1]._replace(tag=info.storages[0].tag)))
            with pytest.raises(AssertionError, match="duplicate"):
                validate_dbinfo(dup, {})

            with pytest.raises(AssertionError, match="seq"):
                validate_dbinfo(info, {"seq": info.seq})

            with pytest.raises(AssertionError, match="epoch"):
                validate_dbinfo(info, {"epoch": info.epoch + 1})

            # THIS cluster's validator is live: it observed the current
            # broadcast sequence (per-cluster state, not a global)
            assert c.validator_state.get("seq") == c.cc.dbinfo.get().seq
            assert c.validator_state.get("checked", 0) > 0
            return True

        assert c.run(main(), timeout_time=60)

        # e2e: a BROKEN publish mid-run fails the simulation itself —
        # the live validator's error surfaces through c.run
        async def poison():
            info = c.cc.dbinfo.get()
            gapped = info._replace(storages=(
                info.storages[0]._replace(end=b"\x40", replicas=tuple(
                    r._replace(end=b"\x40")
                    for r in info.storages[0].replicas)),
                info.storages[1]))
            c.cc.publish(gapped)
            await flow.delay(1.0)
            return True

        with pytest.raises(AssertionError, match="gap"):
            c.run(poison(), timeout_time=30)
    finally:
        c.shutdown()


def test_abandoned_watches_expire():
    """A watch nobody is waiting on (client gone) expires after
    WATCH_TIMEOUT instead of pinning the storage watch map forever
    (ref: the database watch timeout)."""
    c = SimCluster(seed=98)
    flow.SERVER_KNOBS.init("WATCH_TIMEOUT", 5.0)
    try:
        db = c.client()

        async def main():
            tr = db.create_transaction()
            await tr.get(b"wexp")
            w = tr.watch(b"wexp")
            await tr.commit()
            # nothing ever writes the key; the registration must expire
            with pytest.raises(flow.FdbError) as ei:
                await flow.timeout_error(w, 120.0)
            assert ei.value.name == "timed_out"
            info = c.cc.dbinfo.get()
            for s in info.storages:
                for rep in s.replicas:
                    obj = c.cc._storage_objs[rep.name]
                    assert not obj._watch_map, obj._watch_map
            return True

        assert c.run(main(), timeout_time=300)
    finally:
        c.shutdown()
