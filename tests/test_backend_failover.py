"""Conflict-backend fault tolerance: checkpoint/restore parity across
every backend, mid-window failover with bit-identical verdicts (the
version chain makes deterministic replay exact by construction),
retry/reattach, and shadow validation catching a sabotaged backend.

Ref: the determinism/replay discipline of the simulator applied to the
accelerator backend (ROADMAP north star: the TPU path must be
replayable against the CPU baseline), and the runtime cross-checking
argued for by "Early Detection for MVCC Conflicts" (arXiv:2301.06181).
"""

import random

import pytest

from foundationdb_tpu.flow.knobs import SERVER_KNOBS
from foundationdb_tpu.flow.rng import set_seed
from foundationdb_tpu.models import (
    FailoverConflictSet,
    PyConflictSet,
    ShadowResolveMismatch,
    create_conflict_set,
    native_available,
)
from foundationdb_tpu.models.conflict_set import (
    COMMITTED,
    TOO_OLD,
    ConflictSetCheckpoint,
    ResolverTransaction,
)
from foundationdb_tpu.models.point_resolver import PointConflictSet
from foundationdb_tpu.models.tpu_resolver import TpuConflictSet
from foundationdb_tpu.ops.fault_injection import g_device_faults
from foundationdb_tpu.parallel import ShardedTpuConflictSet


def txn(snapshot, reads=(), writes=()):
    return ResolverTransaction(snapshot, tuple(reads), tuple(writes))


def rand_batches(seed, n_batches, point=False, n_keys=40, max_txns=8,
                 version_stride=2000, window=5000):
    """Batches with keys across the whole byte range (all shards see
    traffic), empty batches, and sub-window snapshots (tooOld)."""
    rng = random.Random(seed)
    out = []
    v = 0

    def key():
        return bytes([rng.randrange(256)]) + b"%02d" % rng.randrange(n_keys)

    def rd():
        k = key()
        if point:
            return (k, k + b"\x00")
        return (k, k + bytes([rng.randrange(1, 8)]))

    for _ in range(n_batches):
        v += rng.randrange(1, version_stride)
        batch = []
        for _ in range(rng.randrange(0, max_txns)):
            reads = [rd() for _ in range(rng.randrange(0, 3))]
            writes = [rd() for _ in range(rng.randrange(0, 3))]
            snap = max(0, v - rng.randrange(0, 2 * window))
            batch.append(txn(snap, reads, writes))
        out.append((batch, v, max(0, v - window)))
    return out


BACKENDS = ("python", "tpu", "sharded")


def mk(name, point=False, **kw):
    if name == "python":
        return PyConflictSet(**kw)
    if name == "tpu":
        return TpuConflictSet(**kw)
    if name == "point":
        return PointConflictSet(**kw)
    if name == "native":
        return create_conflict_set("native", **kw)
    return ShardedTpuConflictSet(capacity=kw.pop("capacity", 1024), **kw)


@pytest.fixture
def knobs():
    """Set failover knobs for a test; restore the defaults after."""
    names = ("device_fault_injection", "device_fault_retries",
             "conflict_checkpoint_versions", "conflict_replay_log_max",
             "conflict_device_reattach", "device_reattach_backoff",
             "shadow_resolve_sample", "shadow_resolve_fail_stop",
             "resolve_pipeline_depth")
    prev = {n: getattr(SERVER_KNOBS, n) for n in names}
    yield SERVER_KNOBS.set
    for n, v in prev.items():
        SERVER_KNOBS.set(n, v)
    g_device_faults.clear()


# -- checkpoint / restore parity ---------------------------------------

@pytest.mark.parametrize("producer", BACKENDS)
@pytest.mark.parametrize("restorer", BACKENDS)
def test_checkpoint_restore_cross_backend_parity(producer, restorer):
    """A checkpoint taken on ANY backend restores into ANY backend and
    the two resolve the identical verdict stream from then on."""
    batches = rand_batches(3, 30)
    a = mk(producer)
    for b, v, o in batches[:20]:
        a.resolve(b, v, o)
    ck = a.checkpoint()
    r = mk(restorer)
    r.restore(ck)
    assert r.oldest_version == a.oldest_version
    for b, v, o in batches[20:]:
        assert r.resolve(b, v, o) == a.resolve(b, v, o)


def test_checkpoint_restore_native_parity():
    if not native_available():
        pytest.skip("native backend unavailable")
    batches = rand_batches(5, 30)
    a = mk("native")
    ref = mk("python")
    for b, v, o in batches[:20]:
        assert a.resolve(b, v, o) == ref.resolve(b, v, o)
    # both directions: native -> python and python -> native
    r_py = mk("python")
    r_py.restore(a.checkpoint())
    r_nat = mk("native")
    r_nat.restore(ref.checkpoint())
    for b, v, o in batches[20:]:
        want = a.resolve(b, v, o)
        assert r_py.resolve(b, v, o) == want
        assert r_nat.resolve(b, v, o) == want


def test_point_checkpoint_roundtrip_and_cross_restore():
    """Point-backend checkpoints restore into every interval backend;
    an interval checkpoint of a point-shaped history restores back into
    the point backend."""
    batches = rand_batches(7, 30, point=True)
    a = mk("point")
    for b, v, o in batches[:20]:
        a.resolve(b, v, o)
    ck = a.checkpoint()
    restored = {n: mk(n, point=True) for n in
                ("python", "tpu", "point", "sharded")}
    for r in restored.values():
        r.restore(ck)
    # and interval -> point for the same point-shaped history
    iv = mk("tpu")
    for b, v, o in batches[:20]:
        iv.resolve(b, v, o)
    back = mk("point")
    back.restore(iv.checkpoint())
    for b, v, o in batches[20:]:
        want = a.resolve(b, v, o)
        for name, r in restored.items():
            assert r.resolve(b, v, o) == want, name
        assert back.resolve(b, v, o) == want


def test_checkpoint_drains_inflight_pipeline(knobs):
    """A checkpoint taken with tickets in flight reflects every
    submitted batch (it drains the window first)."""
    knobs("resolve_pipeline_depth", 8)
    batches = rand_batches(9, 8)
    a = mk("tpu")
    tickets = [a.submit(b, v, o) for b, v, o in batches]
    ck = a.checkpoint()
    assert ck.last_commit == batches[-1][1]
    r = mk("python")
    r.restore(ck)
    serial = mk("tpu")
    for b, v, o in batches:
        serial.resolve(b, v, o)
    assert r.checkpoint().assignments == serial.checkpoint().assignments
    # the pre-checkpoint tickets still drain idempotently
    drained = [a.drain(t) for t in tickets]
    fresh = mk("tpu")
    assert drained == [fresh.resolve(b, v, o) for b, v, o in batches]


def test_restore_rejects_non_point_checkpoint():
    iv = mk("tpu")
    iv.resolve([txn(0, writes=[(b"a", b"q")])], 100, 0)
    with pytest.raises(ValueError):
        mk("point").restore(iv.checkpoint())


def test_restore_after_rebase_window():
    """Checkpoints taken after the int32 re-base still restore exactly
    (absolute versions round-trip through the offset encoding)."""
    MWTLV = 5_000_000
    a = mk("tpu")
    ref = mk("python")
    rng = random.Random(13)
    v = 0
    for _ in range(12):
        v += 300_000_000
        batch = [txn(v - rng.randrange(0, MWTLV // 2),
                     reads=[(b"a", b"c")] if rng.random() < 0.5 else [],
                     writes=[(b"b", b"b\x00")] if rng.random() < 0.5 else [])
                 for _ in range(5)]
        assert a.resolve(batch, v, v - MWTLV) == \
            ref.resolve(batch, v, v - MWTLV)
    assert a._base > 0
    r = mk("tpu")
    r.restore(a.checkpoint())
    r2 = mk("python")
    r2.restore(a.checkpoint())
    for _ in range(4):
        v += 300_000_000
        batch = [txn(v - rng.randrange(0, MWTLV // 2),
                     reads=[(b"a", b"c")], writes=[(b"d", b"e")])]
        want = a.resolve(batch, v, v - MWTLV)
        assert r.resolve(batch, v, v - MWTLV) == want
        assert r2.resolve(batch, v, v - MWTLV) == want


# -- failover determinism ----------------------------------------------

FAULT_BACKENDS = ("tpu", "point", "sharded")


def _factory(backend):
    if backend == "tpu":
        return lambda: TpuConflictSet()
    if backend == "point":
        return lambda: PointConflictSet()
    return lambda: ShardedTpuConflictSet(capacity=1024)


def _run_pipelined(cs, batches, window=4):
    got, pending = [], []
    for b, v, o in batches:
        pending.append(cs.submit(b, v, o))
        if len(pending) >= window:
            got.append(cs.drain(pending.pop(0)))
    got.extend(cs.drain(t) for t in pending)
    return got


@pytest.mark.parametrize("backend", FAULT_BACKENDS)
@pytest.mark.parametrize("point_of_fault",
                         ("submit", "materialize", "drain"))
def test_midwindow_failover_is_bit_identical(backend, point_of_fault,
                                             knobs):
    """Scheduled device faults at each seam with 4 batches in flight:
    the verdict stream equals the fault-free run — the rebuild replays
    the logged batches over the checkpoint, and the version chain makes
    replayed verdicts bit-identical by construction."""
    knobs("resolve_pipeline_depth", 4)
    knobs("conflict_checkpoint_versions", 6000)
    knobs("conflict_replay_log_max", 64)
    set_seed(42)
    point = backend == "point"
    batches = rand_batches(11, 40, point=point)
    plain = _factory(backend)()
    want = [plain.resolve(b, v, o) for b, v, o in batches]

    fo = FailoverConflictSet(_factory(backend), backend_name=backend)
    faulted = 0
    got, pending = [], []
    for i, (b, v, o) in enumerate(batches):
        if i in (5, 13, 27):
            g_device_faults.schedule(point_of_fault)
            faulted += 1
        pending.append(fo.submit(b, v, o))
        if len(pending) >= 4:
            got.append(fo.drain(pending.pop(0)))
    got.extend(fo.drain(t) for t in pending)
    assert got == want
    st = fo.failover_stats()
    assert st["device_faults"] >= faulted, st
    assert st["replayed_batches"] > 0, st


def test_seeded_faults_failover_to_cpu_and_reattach(knobs):
    """Probabilistic seeded faults with zero device retries: the
    wrapper declares the device dead, serves bit-identical verdicts
    from the CPU fallback, and reattaches once the device is healthy."""
    set_seed(7)
    knobs("device_fault_retries", 0)
    knobs("conflict_device_reattach", 0)
    knobs("conflict_checkpoint_versions", 6000)
    batches = rand_batches(11, 40)
    plain = TpuConflictSet()
    want = [plain.resolve(b, v, o) for b, v, o in batches]
    fo = FailoverConflictSet(lambda: TpuConflictSet(),
                             backend_name="tpu")
    # arm faults only for the wrapped run (a bare backend would just
    # propagate the injected error — that is exactly what the wrapper
    # exists to absorb)
    knobs("device_fault_injection", 0.15)
    assert [fo.resolve(b, v, o) for b, v, o in batches] == want
    st = fo.failover_stats()
    assert st["failovers"] >= 1 and not st["on_primary"], st
    assert st["active_backend"] == "python"

    # device healthy again: the next submits move back to the primary
    SERVER_KNOBS.set("device_fault_injection", 0.0)
    SERVER_KNOBS.set("conflict_device_reattach", 1)
    v0 = batches[-1][1]
    tail = [(b, v0 + v, max(0, v0 + v - 5000))
            for b, v, _o in rand_batches(12, 5)]
    for b, v, o in tail:
        assert fo.resolve(b, v, o) == plain.resolve(b, v, o)
    st = fo.failover_stats()
    assert st["on_primary"] and st["reattaches"] == 1, st


@pytest.mark.parametrize("bad_batch", [
    [(b"x" * 33, b"x" * 33 + b"\x00")],   # key wider than the bucket
    [(b"a", b"z")],                       # non-point range
], ids=["wide-key", "interval-range"])
def test_fallback_enforces_primary_input_contract(bad_batch, knobs):
    """While failed over, batches the device backend would reject must
    ALSO be rejected by the permissive CPU fallback — the resolver
    role's batch-reject path then behaves identically on both sides of
    the failover boundary, and nothing un-replayable-on-device enters
    the log (a poisoned log would make every reattach rebuild raise)."""
    knobs("device_fault_retries", 0)
    knobs("conflict_device_reattach", 1)
    knobs("device_reattach_backoff", 0.0)
    fo = FailoverConflictSet(lambda: PointConflictSet(),
                             backend_name="tpu-point")
    fo.resolve([txn(0, writes=[(b"a", b"a\x00")])], 100, 0)
    g_device_faults.schedule("submit")
    fo.resolve([txn(50, writes=[(b"b", b"b\x00")])], 200, 0)
    assert not fo.on_primary
    with pytest.raises(ValueError):
        fo.resolve([txn(150, writes=bad_batch)], 300, 0)
    # the rejected batch was never logged: serving continues and the
    # reattach rebuild replays cleanly back onto the device backend
    assert fo.resolve([txn(150, writes=[(b"c", b"c\x00")])], 300, 0) \
        == [COMMITTED]
    st = fo.failover_stats()
    assert st["on_primary"] and st["reattach_failures"] == 0, st


def test_fallback_skips_contract_check_for_too_old(knobs):
    """A malformed range inside a tooOld transaction is never
    marshalled by the device backend, so the fallback must accept it
    too (exact batch-reject parity, not a stricter approximation)."""
    knobs("device_fault_retries", 0)
    knobs("conflict_device_reattach", 0)
    wide = (b"x" * 33, b"x" * 33 + b"\x00")
    want = None
    for faulted in (False, True):
        cs = FailoverConflictSet(lambda: PointConflictSet(),
                                 backend_name="tpu-point")
        cs.resolve([txn(0, writes=[(b"a", b"a\x00")])], 100, 50)
        if faulted:
            g_device_faults.schedule("submit")
        cs.resolve([txn(60, writes=[(b"b", b"b\x00")])], 150, 50)
        assert cs.on_primary is (not faulted)
        got = cs.resolve([txn(10, reads=[wide], writes=[wide])], 200, 50)
        want = got if want is None else want
        assert got == want == [TOO_OLD]


def test_attributed_batches_survive_failover(knobs):
    """Attribution (report_conflicting_keys) rides the replay too."""
    knobs("conflict_checkpoint_versions", 10 ** 9)
    set_seed(21)
    batches = rand_batches(5, 20)
    plain = TpuConflictSet()
    want = [plain.resolve_with_attribution(b, v, o) for b, v, o in batches]
    fo = FailoverConflictSet(lambda: TpuConflictSet(), backend_name="tpu")
    got = []
    for i, (b, v, o) in enumerate(batches):
        if i in (4, 11):
            g_device_faults.schedule("materialize")
        got.append(fo.resolve_with_attribution(b, v, o))
    assert got == want
    assert fo.failover_stats()["device_faults"] >= 2


# -- shadow validation --------------------------------------------------

class _SabotagedBackend(PyConflictSet):
    """A backend whose kernel 'went wrong': state evolves by its own
    (wrong) beliefs while verdicts claim everything committed."""

    BACKEND = "sabotaged"

    def _resolve(self, txns, commit_version, new_oldest_version, collect):
        from foundationdb_tpu.models import COMMITTED
        out = super()._resolve(txns, commit_version, new_oldest_version,
                               collect)
        return [COMMITTED for _ in out]


def test_shadow_validation_catches_sabotaged_backend(knobs):
    knobs("shadow_resolve_sample", 1)
    knobs("conflict_checkpoint_versions", 6000)
    set_seed(33)
    batches = rand_batches(3, 30)
    fo = FailoverConflictSet(lambda: _SabotagedBackend(),
                             backend_name="sabotaged")
    for b, v, o in batches:
        fo.resolve(b, v, o)
    st = fo.failover_stats()["shadow"]
    assert st["sampled"] > 0
    assert st["mismatches"] > 0, st
    assert fo.last_mismatch is not None
    assert fo.last_mismatch["got"] != fo.last_mismatch["want"]


def test_shadow_validation_passes_honest_backend(knobs):
    """No false positives: an honest device backend sampled on every
    batch never mismatches (the shadow rebuild replays the same
    deterministic chain)."""
    knobs("shadow_resolve_sample", 1)
    knobs("conflict_checkpoint_versions", 6000)
    set_seed(34)
    for backend in FAULT_BACKENDS:
        fo = FailoverConflictSet(_factory(backend), backend_name=backend)
        batches = rand_batches(4, 25, point=(backend == "point"))
        _run_pipelined(fo, batches, window=4)
        st = fo.failover_stats()["shadow"]
        assert st["sampled"] > 0
        assert st["mismatches"] == 0, (backend, st)


def test_shadow_fail_stop_halts(knobs):
    knobs("shadow_resolve_sample", 1)
    knobs("shadow_resolve_fail_stop", 1)
    set_seed(35)
    fo = FailoverConflictSet(lambda: _SabotagedBackend(),
                             backend_name="sabotaged")
    with pytest.raises(ShadowResolveMismatch):
        for b, v, o in rand_batches(3, 30):
            fo.resolve(b, v, o)


# -- the cluster surface ------------------------------------------------

def test_cluster_failover_counters_in_status_and_exporter(knobs):
    """A tpu-backed SimCluster with seeded fault injection: commits
    keep succeeding, and the failover/shadow counters surface in
    status, `status details`, the health messages, and the exporter."""
    from foundationdb_tpu import flow
    from foundationdb_tpu.client import run_transaction
    from foundationdb_tpu.server import SimCluster
    from foundationdb_tpu.tools.cli import Cli
    from foundationdb_tpu.tools.exporter import (parse_prometheus,
                                                 render_prometheus)

    cluster = SimCluster(seed=606, durable=True, conflict_backend="tpu")
    # knobs AFTER SimCluster re-initializes them
    flow.SERVER_KNOBS.set("device_fault_injection", 0.05)
    flow.SERVER_KNOBS.set("conflict_checkpoint_versions", 200_000)
    flow.SERVER_KNOBS.set("shadow_resolve_sample", 2)
    cli = Cli.for_cluster(cluster)
    try:
        db = cluster.client("fo")

        async def main():
            for i in range(25):
                async def body(tr, i=i):
                    await tr.get(b"fo%02d" % (i % 7))
                    tr.set(b"fo%02d" % (i % 7), b"v%d" % i)
                await run_transaction(db, body, max_retries=200)
            return await db.get_status()

        status = cluster.run(main(), timeout_time=600)
        res = status["cluster"]["resolvers"]
        assert res
        fo = res[0]["failover"]
        assert fo, "no failover section for a device backend"
        assert fo["shadow"]["sample"] == 2
        assert fo["shadow"]["sampled"] > 0
        assert fo["shadow"]["mismatches"] == 0, fo
        assert fo["checkpoints"] >= 0
        details = cli.execute("status details")
        assert "Backend failover:" in details
        assert "active=" in details
        names = {n for n, _, _ in
                 parse_prometheus(render_prometheus(status))}
        for need in ("fdbtpu_conflict_failover_on_primary",
                     "fdbtpu_conflict_failover_device_faults",
                     "fdbtpu_shadow_resolve_sampled",
                     "fdbtpu_shadow_resolve_mismatches"):
            assert need in names, need
    finally:
        flow.SERVER_KNOBS.set("device_fault_injection", 0.0)
        flow.SERVER_KNOBS.set("shadow_resolve_sample", 0)
        cluster.shutdown()
