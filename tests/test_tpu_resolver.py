"""TPU conflict-set backend specifics: capacity growth, version rebasing,
key-width limits, and heavier randomized parity at larger batch sizes
(the directed + cross-backend semantics live in test_conflict_semantics)."""

import random

import pytest

from foundationdb_tpu.models import (
    COMMITTED,
    CONFLICT,
    BruteForceConflictSet,
    ResolverTransaction,
    create_conflict_set,
)
from foundationdb_tpu.models.tpu_resolver import TpuConflictSet

MWTLV = 5_000_000


def txn(snapshot, reads=(), writes=()):
    return ResolverTransaction(snapshot, tuple(reads), tuple(writes))


def test_factory_builds_tpu_backend():
    cs = create_conflict_set("tpu")
    assert isinstance(cs, TpuConflictSet)
    assert cs.resolve([txn(0, writes=[(b"a", b"b")])], 100, 0) == [COMMITTED]


def test_capacity_growth_preserves_history():
    cs = TpuConflictSet(capacity=1024)
    # >1024 distinct boundary keys force at least one doubling
    v = 0
    for i in range(40):
        v += 10
        writes = [(b"k%04d" % (i * 40 + j), b"k%04d\x00" % (i * 40 + j))
                  for j in range(40)]
        cs.resolve([txn(v - 10, writes=writes)], v, 0)
    assert cs._cap > 1024
    # every one of those writes is still visible to an old snapshot
    rng = random.Random(7)
    for _ in range(20):
        k = b"k%04d" % rng.randrange(40 * 40)
        got = cs.resolve([txn(0, reads=[(k, k + b"\x00")])], v + 1, 0)
        assert got == [CONFLICT]


def test_rebase_at_large_versions():
    """Versions past 2^30 must keep working via int32 offset rebasing."""
    cs = TpuConflictSet()
    brute = BruteForceConflictSet()
    v = 0
    rng = random.Random(3)
    for _ in range(12):
        v += 300_000_000  # crosses the 2^30 rebase threshold repeatedly
        oldest = v - MWTLV
        batch = [txn(v - rng.randrange(0, MWTLV // 2),
                     reads=[(b"a", b"c")] if rng.random() < 0.5 else [],
                     writes=[(b"b", b"b\x00")] if rng.random() < 0.5 else [])
                 for _ in range(5)]
        assert cs.resolve(batch, v, oldest) == brute.resolve(batch, v, oldest)
    assert cs._base > 0  # a rebase actually happened


def test_recovery_style_version_jump():
    """A single huge version jump WITH an advanced window must resolve
    (regression: rebase previously consulted only the stale oldest)."""
    cs = TpuConflictSet()
    brute = BruteForceConflictSet()
    for impl in (cs, brute):
        impl.resolve([txn(0, writes=[(b"a", b"b")])], 100, 0)
    v = (1 << 31) + 500
    old = v - MWTLV
    batch = [txn(v - 10, reads=[(b"a", b"b")]), txn(50, reads=[(b"a", b"b")]),
             txn(v - 10, writes=[(b"c", b"d")])]
    assert cs.resolve(batch, v, old) == brute.resolve(batch, v, old)


def test_giant_version_jump_beyond_int32():
    """Jumps whose base shift exceeds int32 range entirely (regression:
    jnp.int32(delta) overflowed)."""
    cs = TpuConflictSet()
    brute = BruteForceConflictSet()
    for impl in (cs, brute):
        impl.resolve([txn(0, writes=[(b"a", b"b")])], 100, 0)
    for jump in (1 << 32, 1 << 33):
        old = jump - MWTLV
        batch = [txn(jump - 10, reads=[(b"a", b"b")]),
                 txn(jump - 10, writes=[(b"c", b"d")])]
        assert cs.resolve(batch, jump, old) == brute.resolve(batch, jump, old)
    # post-jump writes must be visible at exact versions
    v = (1 << 33) + 50
    batch = [txn((1 << 33) - 5, reads=[(b"c", b"d")])]
    assert cs.resolve(batch, v, v - MWTLV) == \
        brute.resolve(batch, v, v - MWTLV) == [CONFLICT]


def test_window_must_advance_past_threshold():
    cs = TpuConflictSet()
    cs.resolve([txn(0, writes=[(b"a", b"b")])], 100, 0)
    with pytest.raises(OverflowError):
        # huge version jump with a stale window: cannot rebase
        cs.resolve([txn(0, writes=[(b"a", b"b")])], 1 << 31, 0)


def test_key_longer_than_width_rejected():
    cs = TpuConflictSet(key_bytes=16)
    with pytest.raises(ValueError):
        cs.resolve([txn(0, writes=[(b"x" * 17, b"y" * 17)])], 100, 0)


def test_commit_version_regression_rejected():
    cs = TpuConflictSet()
    cs.resolve([txn(0, writes=[(b"a", b"b")])], 100, 0)
    with pytest.raises(ValueError):
        cs.resolve([txn(0, writes=[(b"a", b"b")])], 50, 0)


def test_empty_batch_advances_window():
    cs = TpuConflictSet()
    assert cs.resolve([], 100, 40) == []
    assert cs.oldest_version == 40


@pytest.mark.parametrize("seed", [21, 22])
def test_randomized_parity_large_batches(seed):
    """Bigger batches than the cross-backend suite: exercises the
    intra-batch fixpoint at real batch sizes and periodic compaction."""
    rng = random.Random(seed)
    tpu = TpuConflictSet(capacity=1024)
    brute = BruteForceConflictSet()
    version = 0

    def rrange():
        a = bytes([rng.randrange(10), rng.randrange(10)])
        b = bytes([rng.randrange(10), rng.randrange(10)])
        if a > b:
            a, b = b, a
        if a == b:
            b = a + b"\x00"
        return a, b

    for _ in range(12):
        version += rng.randrange(1, 400_000)
        oldest = max(0, version - MWTLV)
        batch = [txn(max(0, version - rng.randrange(0, MWTLV)),
                     [rrange() for _ in range(rng.randrange(0, 5))],
                     [rrange() for _ in range(rng.randrange(0, 5))])
                 for _ in range(100)]
        assert tpu.resolve(batch, version, oldest) == \
            brute.resolve(batch, version, oldest)
