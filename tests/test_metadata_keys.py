"""Management through the system keyspace.

Ref: fdbclient/SystemData.cpp (\\xff/conf/ keys), ManagementAPI
changeConfig building system-key transactions,
fdbserver/ApplyMetadataMutation.h (the proxy interpreting system-key
mutations during commit). Round-4 VERDICT Missing #7 / task 6: the
committed keys ARE the coordination medium — a raw transaction on
\\xff/conf/ must reconfigure the cluster, and the bespoke
ConfigureRequest RPC is gone.
"""

import pytest

from foundationdb_tpu import flow
from foundationdb_tpu.client import run_transaction
from foundationdb_tpu.server import SimCluster


def test_configure_request_rpc_is_gone():
    import foundationdb_tpu.server.cluster_controller as cc
    assert not hasattr(cc, "ConfigureRequest")
    assert not hasattr(cc, "ExcludeRequest")


def _wait_recovered_past(c, epoch):
    async def w():
        while c.cc.dbinfo.get().epoch <= epoch or \
                c.cc.dbinfo.get().recovery_state != "fully_recovered":
            await flow.delay(0.1)
    return w()


def test_raw_conf_transaction_reconfigures_cluster():
    """A plain ACCESS_SYSTEM_KEYS transaction on \\xff/conf/proxies —
    no management API involved — must trigger an epoch recovery into
    the new shape, and the row must read back as committed data."""
    c = SimCluster(seed=6100, n_workers=5)
    try:
        db = c.client()

        async def main():
            await _wait_recovered_past(c, 0)   # initial boot recovery
            e0 = c.cc.dbinfo.get().epoch

            async def body(tr):
                tr.set_option("access_system_keys")
                tr.set(b"\xff/conf/proxies", b"2")
            await run_transaction(db, body, max_retries=200)

            await _wait_recovered_past(c, e0)
            info = c.cc.dbinfo.get()
            assert len(info.proxies) == 2
            assert c.cc.config.n_proxies == 2

            # the committed row is real, versioned data
            tr = db.create_transaction()
            tr.set_option("read_system_keys")
            assert await tr.get(b"\xff/conf/proxies") == b"2"

            # writes still work through the reshaped pipeline
            async def body2(tr):
                tr.set(b"after", b"reconfig")
            await run_transaction(db, body2, max_retries=200)
            return True

        assert c.run(main(), timeout_time=300)
    finally:
        c.shutdown()


def test_invalid_conf_value_is_clamped_not_honored():
    """Garbage in \\xff/conf/ commits (the keyspace is real data) but
    the CC ignores unrecruitable values with a trace instead of
    bricking recovery; the seeder then repairs the row to the live
    truth after the next recovery."""
    c = SimCluster(seed=6200, n_workers=4)
    try:
        db = c.client()

        async def main():
            await _wait_recovered_past(c, 0)   # initial boot recovery
            e0 = c.cc.dbinfo.get().epoch

            async def body(tr):
                tr.set_option("access_system_keys")
                tr.set(b"\xff/conf/logs", b"ninety-nine")  # not an int
                tr.set(b"\xff/conf/proxies", b"99")        # > workers
            await run_transaction(db, body, max_retries=200)
            await flow.delay(2.0)
            # neither value was honored, no recovery was provoked
            assert c.cc.config.n_logs == 1
            assert c.cc.config.n_proxies == 1
            assert c.cc.dbinfo.get().epoch == e0
            assert flow.trace.g_trace.counts.get(
                "MetadataConfigIgnored", 0) >= 1
            return True

        assert c.run(main(), timeout_time=120)
    finally:
        c.shutdown()


def test_configure_and_exclude_survive_recovery_roundtrip():
    """db.configure / db.exclude ride transactions end-to-end: rows
    appear, the CC reacts, re-include clears the row."""
    c = SimCluster(seed=6300, n_workers=5)
    try:
        db = c.client()

        async def main():
            await _wait_recovered_past(c, 0)   # initial boot recovery
            e0 = c.cc.dbinfo.get().epoch
            await db.configure(n_resolvers=2)
            await _wait_recovered_past(c, e0)
            assert c.cc.config.n_resolvers == 2

            # pick a worker with no current txn roles; exclude it
            victim = None
            for name, wi in c.cc.workers.items():
                if not wi.worker.roles and wi.worker.process.alive:
                    victim = name
                    break
            if victim is None:
                victim = next(iter(c.cc.workers))
            await db.exclude(victim)
            await flow.delay(1.0)
            assert victim in c.cc.excluded
            tr = db.create_transaction()
            tr.set_option("read_system_keys")
            assert await tr.get(
                b"\xff/excluded/" + victim.encode()) == b""

            await db.exclude(victim, exclude=False)
            await flow.delay(1.0)
            assert victim not in c.cc.excluded
            tr = db.create_transaction()
            tr.set_option("read_system_keys")
            assert await tr.get(
                b"\xff/excluded/" + victim.encode()) is None
            return True

        assert c.run(main(), timeout_time=300)
    finally:
        c.shutdown()


def test_lost_proxy_notice_is_reconciled_from_rows():
    """The one-way proxy notice is only the low-latency trigger: with
    it suppressed entirely, the CC's reconcile loop must still adopt a
    committed \\xff/conf change from the stored rows (the keys are the
    medium, not the RPC — ref: the reference reading configuration
    from the system keyspace)."""
    c = SimCluster(seed=6500, n_workers=5)
    try:
        db = c.client()

        async def main():
            await _wait_recovered_past(c, 0)
            # sever every proxy's management notice — a crashed proxy
            # loses the datagram the same way
            for p in c.cc.dbinfo.get().proxies:
                for wi in c.cc.workers.values():
                    obj = wi.worker.roles.get(p.name)
                    if obj is not None:
                        obj._management_ref = None
            e0 = c.cc.dbinfo.get().epoch

            async def body(tr):
                tr.set_option("access_system_keys")
                tr.set(b"\xff/conf/resolvers", b"2")
            await run_transaction(db, body, max_retries=200)
            await _wait_recovered_past(c, e0)   # sync loop picks it up
            assert c.cc.config.n_resolvers == 2
            return True

        assert c.run(main(), timeout_time=300)
    finally:
        c.shutdown()


def test_conf_rows_survive_shard_movement():
    """Stored system rows are first-class shard data now: a split and
    merge cycle of the rightmost shard must carry \\xff/conf/ rows
    (they used to be silently dropped — snapshot_range capped at
    \\xff)."""
    c = SimCluster(seed=6400, durable=True, n_storage=1, n_workers=5)
    flow.SERVER_KNOBS.init("DD_SHARD_SPLIT_BYTES", 1200)
    try:
        db = c.client()

        async def main():
            # wait for the conf seed to land
            for _ in range(100):
                tr = db.create_transaction()
                tr.set_option("read_system_keys")
                if await tr.get(b"\xff/conf/proxies") is not None:
                    break
                await flow.delay(0.2)

            async def seed(tr):
                for i in range(300):
                    tr.set(b"mv%04d" % i, b"v%d" % i)
            await run_transaction(db, seed, max_retries=200)
            for _ in range(120):
                await flow.delay(0.5)
                if len(c.cc.dbinfo.get().storages) >= 2:
                    break
            else:
                raise AssertionError("never split")

            async def wipe(tr):
                tr.clear_range(b"", b"\xff")
            await run_transaction(db, wipe, max_retries=200)
            for _ in range(120):
                await flow.delay(0.5)
                if len(c.cc.dbinfo.get().storages) == 1:
                    break

            tr = db.create_transaction()
            tr.set_option("read_system_keys")
            assert await tr.get(b"\xff/conf/proxies") == b"1"
            rows = await tr.get_range(b"\xff/conf/", b"\xff/conf0")
            assert len(rows) >= 8, rows
            return True

        assert c.run(main(), timeout_time=600)
    finally:
        flow.reset_server_knobs()
        c.shutdown()
