"""QoS & saturation telemetry plane: the promoted Smoother (tau
behavior + the non-increasing-clock clamp), per-role QosSample signals
in status.cluster.qos, tag & priority traffic accounting, Ratekeeper
RkUpdate decision traces with limiting reasons, the open-loop storm
workload, and the zero-overhead-off posture.

Ref: fdbrpc/Smoother.h, Ratekeeper.actor.cpp updateRate (RkUpdate +
limitReason_t), fdbserver/TransactionTagCounter.
"""

import math

import pytest

from foundationdb_tpu import flow
from foundationdb_tpu.client import run_transaction
from foundationdb_tpu.flow.smoother import (SmoothedQueue, SmoothedRate,
                                            Smoother)
from foundationdb_tpu.server import SimCluster
from foundationdb_tpu.server.ratekeeper import LIMIT_REASONS

# the signal inventory each role kind publishes (README QoS telemetry
# section documents the same table — this test is the schema pin)
STORAGE_SIGNALS = {"queue_bytes", "durability_lag_versions",
                   "read_rate", "mutation_rate", "write_bandwidth"}
# armed-only storage heat additions (ISSUE 13): present exactly while
# STORAGE_HEAT_TRACKING is on — the armed-schema pin lives in
# tests/test_storage_heat.py (test_armed_plane_end_to_end_status_qos_cli
# asserts the armed set equals STORAGE_SIGNALS | STORAGE_HEAT_SIGNALS)
STORAGE_HEAT_SIGNALS = {"read_bytes_per_sec", "read_ops_per_sec",
                        "read_hot_ranges", "busiest_read_tag_busyness"}
TLOG_SIGNALS = {"queue_bytes", "queue_entries",
                "fsync_backlog_versions", "commit_rate"}
PROXY_SIGNALS = {"grv_queue_depth", "commit_batch_occupancy",
                 "resolve_in_flight", "grv_rate", "commit_rate",
                 "tps_budget"}
RESOLVER_SIGNALS = {"pipeline_occupancy", "pipeline_in_flight",
                    "pipeline_depth", "forced_drain_rate", "batch_rate",
                    "txn_rate", "state_rows"}
RK_INPUTS = {"worst_storage_queue_bytes", "worst_tlog_queue_bytes",
             "worst_durability_lag_versions", "pipeline_occupancy",
             "pipeline_forced_drain_rate", "sched_deferred_depth",
             "worst_read_hot", "busiest_read_tag_busyness",
             "dead_replicas"}


# -- Smoother (satellite: promotion + clamp) ---------------------------

def test_smoother_tau_decay_directed():
    """exp decay toward the newest sample: after exactly one tau the
    old value retains weight e^-1; tau<=0 snaps."""
    s = Smoother()
    assert s.sample(1000.0, 0.0, 1.0) == 1000.0
    v = s.sample(0.0, 1.0, 1.0)
    assert abs(v - 1000.0 * math.exp(-1)) < 1e-9
    # larger tau decays slower at the same dt
    s2 = Smoother()
    s2.sample(1000.0, 0.0, 10.0)
    assert s2.sample(0.0, 1.0, 10.0) > v
    # tau 0: no smoothing, the sample IS the value
    s3 = Smoother()
    s3.sample(5.0, 0.0, 0.0)
    assert s3.sample(7.0, 0.0, 0.0) == 7.0


def test_smoother_clamps_non_increasing_clock():
    """A non-increasing `now` (sim clock replay / duplicate tick) must
    clamp dt to 0 — the value holds still instead of amplifying through
    a positive exponent (the unguarded delta bug this PR fixes)."""
    s = Smoother()
    s.sample(1000.0, 10.0, 1.0)
    held = s.sample(0.0, 10.0, 1.0)       # duplicate tick: dt == 0
    assert held == 1000.0
    back = s.sample(0.0, 5.0, 1.0)        # clock went BACKWARDS
    assert back == 1000.0                 # not 1000 * e^+5 ~ 148k
    # the smoother keeps working once time advances again
    fwd = s.sample(0.0, 6.0, 1.0)
    assert abs(fwd - 1000.0 * math.exp(-1)) < 1e-9


def test_smoothed_rate_from_totals():
    r = SmoothedRate(tau=0.0)    # tau 0: instantaneous rate
    r.sample_total(0, 0.0)
    assert r.sample_total(100, 1.0) == 100.0
    assert r.sample_total(150, 1.5) == 100.0
    # a counter reset (role restart) re-baselines, never goes negative
    assert r.sample_total(10, 2.0) == 100.0   # held, not -280/s
    assert r.sample_total(60, 2.5) == 100.0   # 100/s again from fresh base
    # a non-advancing clock holds the rate too
    assert r.sample_total(1000, 2.5) == 100.0


def test_smoothed_queue_uses_knob_tau():
    q = SmoothedQueue()
    flow.SERVER_KNOBS.set("qos_smoothing_tau", 1.0)
    try:
        q.sample(1000.0, 0.0)
        v = q.sample(0.0, 1.0)
        assert abs(v - 1000.0 * math.exp(-1)) < 1e-9
        # live knob change applies to the existing smoother
        flow.SERVER_KNOBS.set("qos_smoothing_tau", 0.0)
        assert q.sample(7.0, 1.0) == 7.0
    finally:
        flow.reset_server_knobs(randomize=False)


def test_ratekeeper_reexports_smoother():
    """Back-compat: the Smoother is historically ratekeeper vocabulary."""
    from foundationdb_tpu.server import ratekeeper
    assert ratekeeper.Smoother is Smoother


# -- status.cluster.qos schema ----------------------------------------

def _run_workload_and_status(c, n_txns=8):
    db = c.client()

    async def main():
        # spread the traffic across several QoS sample intervals so the
        # smoothed RATE signals see live deltas, not a finished burst
        for i in range(n_txns):
            async def body(tr, i=i):
                await tr.get(b"q%d" % (i % 3))
                tr.set(b"q%d" % (i % 3), b"v%d" % i)
            await run_transaction(db, body)
            await flow.delay(0.2)
        return await db.get_status()
    return c.run(main(), timeout_time=120)


def test_qos_status_schema_pins_signals_and_reason():
    c = SimCluster(seed=701, durable=True)
    flow.SERVER_KNOBS.set("qos_sample_interval", 0.25)
    try:
        status = _run_workload_and_status(c)
        qos = status["cluster"]["qos"]
        assert qos["transactions_per_second_limit"] is not None
        assert qos["batch_transactions_per_second_limit"] is not None
        assert qos["limiting_reason"] in LIMIT_REASONS
        assert set(qos["inputs"]) == RK_INPUTS
        roles = qos["roles"]
        for kind, want in (("storage", STORAGE_SIGNALS),
                           ("tlog", TLOG_SIGNALS),
                           ("proxy", PROXY_SIGNALS),
                           ("resolver", RESOLVER_SIGNALS)):
            assert roles.get(kind), (kind, roles.keys())
            for name, signals in roles[kind].items():
                assert set(signals) == want | {"sampled_at"}, \
                    (kind, name, signals)
        # the workload actually moved the signals
        sto = next(iter(roles["storage"].values()))
        assert sto["mutation_rate"] >= 0
        res = next(iter(roles["resolver"].values()))
        assert res["batch_rate"] > 0, res
        # priorities always present (zeros included) for dashboards
        assert set(qos["priorities"]) == {"batch", "default", "immediate"}
        assert qos["priorities"]["default"]["committed"] > 0
    finally:
        flow.reset_server_knobs(randomize=False)
        c.shutdown()


def test_qos_plane_off_is_empty_and_costless():
    """QOS_SAMPLE_INTERVAL=0 empties the plane; QOS_TAG_ACCOUNTING=0
    keeps tagged traffic out of the table — the knobs-off posture the
    PERF.md note pins."""
    c = SimCluster(seed=703, durable=True)
    flow.SERVER_KNOBS.set("qos_sample_interval", 0)
    flow.SERVER_KNOBS.set("qos_tag_accounting", 0)
    try:
        db = c.client()

        async def main():
            async def body(tr):
                tr.set_option("transaction_tag", b"offtag")
                tr.set(b"k", b"v")
            await run_transaction(db, body)
            await flow.delay(2.0)
            return await db.get_status()

        qos = c.run(main(), timeout_time=120)["cluster"]["qos"]
        assert qos["roles"] == {}, qos["roles"]
        assert qos["tags"] == [], qos["tags"]
        # the rate surface itself stays (it predates the plane)
        assert qos["transactions_per_second_limit"] is not None
        # and no per-priority counters accumulated on any proxy
        assert all(v["started"] == 0
                   for v in qos["priorities"].values()), qos["priorities"]
    finally:
        flow.reset_server_knobs(randomize=False)
        c.shutdown()


# -- tag & priority accounting ----------------------------------------

def test_tag_and_priority_accounting_in_status():
    c = SimCluster(seed=705, durable=True)
    try:
        db = c.client()

        async def main():
            # tagged committed traffic at two priorities
            for i in range(4):
                async def body(tr, i=i):
                    tr.set_option("transaction_tag", b"web")
                    if i % 2:
                        tr.set_option("priority_batch")
                    tr.set(b"t%d" % i, b"v")
                await run_transaction(db, body)
            # one tagged CONFLICTED transaction (not retried)
            tr = db.create_transaction()
            tr.set_option("transaction_tag", b"web")
            await tr.get(b"hot")
            tr.set(b"mine", b"v")

            async def bump(t2):
                t2.set(b"hot", b"x")
            await run_transaction(db, bump)
            try:
                await tr.commit()
                raise AssertionError("expected a conflict")
            except flow.FdbError as e:
                assert e.name == "not_committed", e.name
            await flow.delay(1.5)
            return await db.get_status()

        qos = c.run(main(), timeout_time=120)["cluster"]["qos"]
        rows = {r["tag"]: r for r in qos["tags"]}
        web = rows[b"web".hex()]
        assert web["started"] == 5, web
        assert web["committed"] == 4, web
        assert web["conflicted"] == 1, web
        assert web["busyness"] > 0, web
        prios = qos["priorities"]
        assert prios["batch"]["committed"] == 2, prios
        assert prios["default"]["committed"] >= 3, prios   # incl. bumps
        assert prios["default"]["conflicted"] >= 1, prios
        assert prios["batch"]["started"] >= 2, prios
    finally:
        c.shutdown()


def test_tag_counter_bounds_and_decay():
    from foundationdb_tpu.server.proxy import TransactionTagCounter
    tc = TransactionTagCounter(half_life=1.0, max_entries=3)
    c = SimCluster(seed=707)   # a scheduler for flow.now()
    try:
        async def main():
            for i in range(6):
                tc.record(b"t%d" % i, "started", flow.now())
            assert len(tc._entries) == 3   # bounded: coldest evicted
            tc.record(b"hot", "started", flow.now(), weight=100.0)
            top = tc.top(1)
            assert top[0]["tag"] == b"hot".hex()
            score0 = top[0]["busyness"]
            await flow.delay(2.0)          # two half-lives
            score1 = tc.top(1)[0]["busyness"]
            assert score1 == pytest.approx(score0 / 4, rel=0.05)
            return True
        assert c.run(main(), timeout_time=60)
    finally:
        c.shutdown()


def test_transaction_tag_option_validation():
    c = SimCluster(seed=709)
    try:
        db = c.client()
        tr = db.create_transaction()
        with pytest.raises(flow.FdbError) as ei:
            tr.set_option("transaction_tag",
                          b"x" * (int(flow.SERVER_KNOBS
                                      .max_transaction_tag_length) + 1))
        assert ei.value.name == "tag_too_long"
        for i in range(int(flow.SERVER_KNOBS.max_tags_per_transaction)):
            tr.set_option("transaction_tag", b"t%d" % i)
        tr.set_option("transaction_tag", b"t0")   # duplicate: collapses
        with pytest.raises(flow.FdbError) as ei:
            tr.set_option("transaction_tag", b"one-too-many")
        assert ei.value.name == "too_many_tags"
        with pytest.raises(flow.FdbError):
            tr.set_option("transaction_tag", b"")
        # str form is accepted and encoded
        tr2 = db.create_transaction()
        tr2.set_option("transaction_tag", "strtag")
        assert tr2._tags == (b"strtag",)
    finally:
        c.shutdown()


# -- ratekeeper decision tracing --------------------------------------

def test_rk_update_traces_with_limiting_reason():
    """A storage queue held over a tiny target: RkUpdate events carry
    the computed rate, every input signal, and limiting_reason
    storage_queue; status.cluster.qos mirrors the decision."""
    c = SimCluster(seed=711, durable=True)
    flow.SERVER_KNOBS.set("rk_target_storage_queue_bytes", 500)
    flow.SERVER_KNOBS.set("rk_spring_storage_queue_bytes", 100)
    try:
        db = c.client()

        async def main():
            for i in range(12):
                async def body(tr, i=i):
                    tr.set(b"rk%02d" % i, b"v" * 100)
                await run_transaction(db, body)
            await flow.delay(1.5)    # several RK update intervals
            return await db.get_status()

        status = c.run(main(), timeout_time=120)
        ups = [e for e in flow.g_trace.events
               if e.get("Type") == "RkUpdate"]
        assert ups, "no RkUpdate traces"
        for e in ups:
            assert "TPSLimit" in e and "BatchTPSLimit" in e, e
            assert e["LimitingReason"] in LIMIT_REASONS, e
            # every input signal rides the trace, CamelCased
            for f in ("WorstStorageQueueBytes", "WorstTlogQueueBytes",
                      "WorstDurabilityLagVersions", "PipelineOccupancy",
                      "PipelineForcedDrainRate", "DeadReplicas"):
                assert f in e, (f, e)
        limited = [e for e in ups if e["LimitingReason"] == "storage_queue"]
        assert limited, [e["LimitingReason"] for e in ups]
        assert limited[-1]["TPSLimit"] < flow.SERVER_KNOBS.rk_max_rate
        qos = status["cluster"]["qos"]
        assert qos["limiting_reason"] == "storage_queue", qos
        assert qos["inputs"]["worst_storage_queue_bytes"] > 500, qos
        assert qos["transactions_per_second_limit"] < \
            flow.SERVER_KNOBS.rk_max_rate
    finally:
        flow.reset_server_knobs(randomize=False)
        c.shutdown()


def test_rk_dead_replica_reports_durability_lag():
    c = SimCluster(seed=713, durable=True, auto_reboot=False)
    try:
        db = c.client()

        async def main():
            async def body(tr):
                tr.set(b"x", b"1")
            await run_transaction(db, body)
            c.kill_role("storage")
            await flow.delay(0.5)
            return await db.get_status()

        qos = c.run(main(), timeout_time=120)["cluster"]["qos"]
        assert qos["limiting_reason"] == "durability_lag", qos
        assert qos["inputs"]["dead_replicas"] >= 1, qos
        assert qos["transactions_per_second_limit"] == \
            flow.SERVER_KNOBS.rk_min_rate
    finally:
        c.shutdown()


# -- open-loop storm workload -----------------------------------------

def test_open_loop_storm_runs_and_measures():
    from foundationdb_tpu.server.workloads import OpenLoopStorm
    c = SimCluster(seed=715, durable=True)
    try:
        dbs = [c.client(f"s{i}") for i in range(3)]

        async def main():
            storm = OpenLoopStorm(dbs, flow.g_random, duration=1.5,
                                  rate=60.0, burst_rate=200.0,
                                  burst_start=0.5, burst_len=0.5,
                                  keyspace=16, max_inflight=64)
            return await storm.run()

        stats = c.run(main(), timeout_time=300)
        assert stats["issued"] > 30, stats
        done = (stats["completed"] + stats["conflicted"]
                + sum(stats["errors"].values()))
        assert done + stats["shed"] == stats["issued"], stats
        assert stats["completed"] > 0, stats
        assert stats["grv"]["count"] > 0
        assert stats["grv"]["p99"] >= stats["grv"]["p50"] >= 0
        assert stats["commit"]["p99"] >= 0
    finally:
        c.shutdown()


def test_storm_sheds_at_inflight_cap():
    """max_inflight bounds the open-loop backlog: arrivals past the cap
    are counted as shed, not silently dropped or unboundedly queued."""
    from foundationdb_tpu.server.workloads import OpenLoopStorm
    c = SimCluster(seed=717, durable=True)
    try:
        dbs = [c.client("shed0")]

        async def main():
            storm = OpenLoopStorm(dbs, flow.g_random, duration=1.0,
                                  rate=2000.0, burst_rate=2000.0,
                                  burst_start=0.0, burst_len=1.0,
                                  keyspace=4, max_inflight=8)
            return await storm.run()

        stats = c.run(main(), timeout_time=300)
        assert stats["shed"] > 0, stats
    finally:
        c.shutdown()


# -- operator surfaces -------------------------------------------------

def test_cli_qos_view_and_status_details_ratekeeper():
    from foundationdb_tpu.tools.cli import Cli
    c = SimCluster(seed=719, durable=True)
    try:
        cli = Cli.for_cluster(c)
        db = c.client()

        async def main():
            async def body(tr):
                tr.set_option("transaction_tag", b"cli")
                tr.set(b"k", b"v")
            await run_transaction(db, body)
            await flow.delay(1.5)
            return True

        assert c.run(main(), timeout_time=120)
        view = cli.execute("qos")
        for section in ("Ratekeeper:", "limited_by=", "Decision inputs:",
                        "Storage signals:", "Tlog signals:",
                        "Proxy signals:", "Resolver signals:",
                        "Tag traffic", b"cli".hex(),
                        "Priority classes:"):
            assert str(section) in view, (section, view)
        details = cli.execute("status details")
        assert "Ratekeeper:" in details
        assert "limited_by=" in details
        assert "tps_limit=" in details
    finally:
        c.shutdown()


def test_rk_batch_only_throttle_reports_its_reason():
    """Storage queue inside the BATCH spring zone but below the normal
    one: BatchTPSLimit drops while TPSLimit stays at max_rate — the
    decision must report storage_queue, not none (a batch-only
    throttle is still a throttle; "none" here was the review-fixed
    misleading posture)."""
    from foundationdb_tpu.server.ratekeeper import Ratekeeper

    class _Gauge:
        def __init__(self, v):
            self._v = v

        def get(self):
            return self._v

    class _Obj:
        pass

    mut = _Obj()                      # 984 + 0 + 16 = 1000 queue bytes
    mut.param1, mut.param2 = b"x" * 984, b""
    sto = _Obj()
    sto.process = _Obj()
    sto.process.alive = True
    sto.kv = object()
    sto.version = _Gauge(0)
    sto.durable_version = _Gauge(0)
    sto._lag = 0
    sto._pending = [(0, [mut])]

    rep = _Obj()
    rep.name = "s0"
    shard = _Obj()
    shard.replicas = [rep]
    info = _Obj()
    info.storages = [shard]
    info.epoch = 1

    cc = _Obj()
    cc.dbinfo = _Gauge(info)
    cc._storage_objs = {"s0": sto}
    cc.tlog_objs = lambda: []
    cc.workers = {}

    flow.set_seed(0)
    s = flow.Scheduler()
    flow.set_scheduler(s)
    flow.reset_server_knobs(randomize=False)
    k = flow.SERVER_KNOBS
    k.set("rk_target_storage_queue_bytes", 2000)
    k.set("rk_spring_storage_queue_bytes", 100)
    k.set("rk_batch_target_fraction", 0.5)   # batch zone ends at 1000
    try:
        proc = _Obj()
        proc.name = "rk-test"
        proc.register = lambda stream: object()   # RequestStream endpoint
        rk = Ratekeeper(proc, cc)
        tps, batch_tps = rk._compute_rates()
        assert tps >= k.rk_max_rate, tps            # normal: unthrottled
        assert batch_tps <= k.rk_min_rate, batch_tps    # batch: floored
        d = rk.last_decision
        assert d["limiting_reason"] == "storage_queue", d
        assert d["inputs"]["worst_storage_queue_bytes"] == 1000.0, d
    finally:
        flow.reset_server_knobs(randomize=False)
        flow.set_scheduler(None)
