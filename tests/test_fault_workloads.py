"""Stacked fault workloads across many seeds: correctness workloads run
WHILE roles die, links clog, and BUGGIFY distorts timings — the
reference's core test strategy (ref: tests/fast/CycleTest.txt stacking
Cycle + Attrition + RandomClogging; fdbrpc/sim2.actor.cpp:1222-1406;
flow/Knobs.cpp BUGGIFY randomization).
"""

import pytest

from foundationdb_tpu import flow
from foundationdb_tpu.client import run_transaction
from foundationdb_tpu.server import SimCluster
from foundationdb_tpu.server.consistency import check_consistency

N = 6  # cycle length


async def _cycle_setup(db):
    tr = db.create_transaction()
    for i in range(N):
        tr.set(b"cyc%02d" % i, b"%02d" % ((i + 1) % N))
    await tr.commit()


async def _cycle_swaps(db, iters):
    for _ in range(iters):
        async def body(tr):
            a = flow.g_random.random_int(0, N)
            b = int(await tr.get(b"cyc%02d" % a))
            c = int(await tr.get(b"cyc%02d" % b))
            d = int(await tr.get(b"cyc%02d" % c))
            tr.set(b"cyc%02d" % a, b"%02d" % c)
            tr.set(b"cyc%02d" % c, b"%02d" % b)
            tr.set(b"cyc%02d" % b, b"%02d" % d)
        await run_transaction(db, body, max_retries=500)


async def _cycle_check(db):
    async def check(tr):
        kvs = await tr.get_range(b"cyc", b"cyd")
        assert len(kvs) == N, kvs
        nxt = {int(k[3:]): int(v) for k, v in kvs}
        seen, cur = set(), 0
        while cur not in seen:
            seen.add(cur)
            cur = nxt[cur]
        assert len(seen) == N, f"cycle broken: {nxt}"
    await run_transaction(db, check, max_retries=200)


async def _attrition(c, kills, machines):
    """Random role kills + link clogs, spaced so recovery can make
    progress between faults (ref: MachineAttrition + RandomClogging)."""
    rng = flow.g_random
    for _ in range(kills):
        await flow.delay(0.2 + rng.random01() * 0.4)
        op = rng.random_int(0, 6)
        try:
            if op == 0:
                c.kill_role("tlog")
            elif op == 1:
                c.kill_role("proxy")
            elif op == 2:
                c.kill_role("resolver")
            elif op == 3:
                c.kill_role("storage")
            else:
                a = machines[rng.random_int(0, len(machines))]
                b = machines[rng.random_int(0, len(machines))]
                c.net.clog_pair(a, b, rng.random01() * 0.5)
        except KeyError:
            pass  # nothing of that kind alive right now


@pytest.mark.parametrize("seed", range(20))
def test_cycle_survives_attrition(seed):
    """20 seeds of Cycle + attrition + BUGGIFY on a durable cluster."""
    c = SimCluster(seed=1000 + seed, durable=True, buggify=True,
                   n_workers=5)
    try:
        db = c.client()
        dbs = [c.client(f"c{i}") for i in range(2)]
        machines = [f"w{i}" for i in range(c.n_workers)]

        async def main():
            await _cycle_setup(db)
            tasks = [flow.spawn(_cycle_swaps(d, 5)) for d in dbs]
            tasks.append(flow.spawn(_attrition(c, 2, machines)))
            await flow.wait_for_all(tasks)
            await _cycle_check(db)
            # post-workload replica sweep (ref: tester.actor.cpp:741
            # running ConsistencyCheck after sim tests)
            await check_consistency(c)
            return True

        assert c.run(main(), timeout_time=900)
    finally:
        c.shutdown()


@pytest.mark.parametrize("backend,seed", [("tpu", 4101),
                                          ("tpu-point", 4102),
                                          ("sharded-tpu", 4103)])
def test_cycle_survives_device_faults_mid_pipeline(backend, seed):
    """Stacked device faults into an accelerator-backed cluster: the
    resolve pipeline runs 4 deep under BUGGIFY while the fault injector
    fires at the submit/materialize/drain seams with seeded
    probability, frequent checkpoints keep the replay log short, and
    shadow validation cross-checks sampled batches throughout. The
    cycle invariant and a full consistency sweep must hold after the
    failover machinery has been exercised — and the shadow must have
    found NOTHING (the device backends are honest; only the fault
    timing is hostile)."""
    c = SimCluster(seed=seed, durable=True, buggify=True, n_workers=5,
                   conflict_backend=backend)
    # knobs AFTER SimCluster re-randomizes them: a 4-deep pipeline with
    # faults mid-window is the scenario under test
    knob_names = ("resolve_pipeline_depth", "device_fault_injection",
                  "conflict_checkpoint_versions", "shadow_resolve_sample")
    prev_knobs = {n: getattr(flow.SERVER_KNOBS, n) for n in knob_names}
    flow.SERVER_KNOBS.set("resolve_pipeline_depth", 4)
    flow.SERVER_KNOBS.set("device_fault_injection", 0.03)
    flow.SERVER_KNOBS.set("conflict_checkpoint_versions", 150_000)
    flow.SERVER_KNOBS.set("shadow_resolve_sample", 3)
    try:
        db = c.client()

        async def main():
            await _cycle_setup(db)
            await _cycle_swaps(db, 8)
            await _cycle_check(db)
            # post-workload replica sweep (ref: tester.actor.cpp:741)
            await check_consistency(c)
            status = await db.get_status()
            return status

        status = c.run(main(), timeout_time=900)
        # the machinery actually ran: every resolver reports failover
        # accounting, sampled shadow batches, zero mismatches
        resolvers = status["cluster"]["resolvers"]
        assert resolvers
        for r in resolvers:
            fo = r["failover"]
            assert fo, "device backend not wrapped"
            assert fo["shadow"]["mismatches"] == 0, fo
            assert fo["shadow"]["errors"] == 0, fo
        assert not any(m["name"] == "shadow_resolve_mismatch"
                       for m in status["cluster"]["messages"])
    finally:
        for n, v in prev_knobs.items():
            flow.SERVER_KNOBS.set(n, v)
        c.shutdown()


@pytest.mark.parametrize("seed", [3, 11])
def test_replicated_sharded_cycle_attrition(seed):
    """The full shape (2 logs, 2 shards, 2 resolvers) under attrition."""
    c = SimCluster(seed=2000 + seed, durable=True, buggify=True,
                   n_logs=2, n_storage=2, n_resolvers=2, n_workers=6)
    try:
        db = c.client()
        machines = [f"w{i}" for i in range(c.n_workers)]

        async def main():
            await _cycle_setup(db)
            tasks = [flow.spawn(_cycle_swaps(db, 6))]
            tasks.append(flow.spawn(_attrition(c, 3, machines)))
            await flow.wait_for_all(tasks)
            await _cycle_check(db)
            # post-workload replica sweep (ref: tester.actor.cpp:741
            # running ConsistencyCheck after sim tests)
            await check_consistency(c)
            return True

        assert c.run(main(), timeout_time=900)
    finally:
        c.shutdown()


@pytest.mark.parametrize("seed", [5, 17])
def test_marker_exactness_under_kills(seed):
    """Atomic all-or-nothing commits under faults: each transaction
    writes a unique marker + increments a counter; on
    commit_unknown_result the client re-reads the marker to learn the
    outcome (the reference's idempotency pattern). The final counter
    must equal the number of markers present."""
    c = SimCluster(seed=3000 + seed, durable=True, buggify=True,
                   n_workers=5)
    try:
        db = c.client()

        async def main():
            applied = 0
            for i in range(15):
                marker = b"mark%04d" % i
                tr = db.create_transaction()
                committed = None
                for _attempt in range(100):
                    try:
                        cur = int(await tr.get(b"counter") or b"0")
                        tr.set(b"counter", b"%d" % (cur + 1))
                        tr.set(marker, b"1")
                        await tr.commit()
                        committed = True
                        break
                    except flow.FdbError as e:
                        if e.name == "commit_unknown_result":
                            # did it actually apply?
                            async def probe(tr2, marker=marker):
                                return await tr2.get(marker)
                            got = await run_transaction(db, probe,
                                                        max_retries=200)
                            if got is not None:
                                committed = True
                                break
                            await tr.on_error(e)
                        else:
                            await tr.on_error(e)
                assert committed is not None, "txn never decided"
                applied += 1
                if i in (4, 9):
                    try:
                        c.kill_role("tlog" if i == 4 else "proxy")
                    except KeyError:
                        pass

            async def check(tr):
                n = int(await tr.get(b"counter") or b"0")
                marks = await tr.get_range(b"mark", b"marl")
                assert n == len(marks) == applied, (n, len(marks), applied)
            await run_transaction(db, check, max_retries=200)
            return True

        assert c.run(main(), timeout_time=900)
    finally:
        c.shutdown()


@pytest.mark.parametrize("seed", range(10))
def test_random_cluster_shapes_survive_attrition(seed):
    """Per-seed random cluster shape — replication, shard count,
    resolvers, proxies, engine, buggify — running Cycle + attrition
    (ref: SimulationConfig::generateNormalConfig,
    SimulatedCluster.actor.cpp:782: random cluster shapes per seed are
    the reference's way of covering the configuration space)."""
    import random as _random

    shape_rng = _random.Random(9000 + seed)
    kw = {
        "durable": True,
        "buggify": shape_rng.random() < 0.5,
        "n_logs": shape_rng.choice([1, 2, 3]),
        "n_storage": shape_rng.choice([1, 2, 3]),
        "n_resolvers": shape_rng.choice([1, 2]),
        "n_proxies": shape_rng.choice([1, 2]),
        "storage_engine": shape_rng.choice(["memory", "btree"]),
    }
    kw["n_workers"] = max(5, kw["n_logs"] + 2, kw["n_storage"] + 1)
    c = SimCluster(seed=9000 + seed, **kw)
    try:
        db = c.client()
        machines = [f"w{i}" for i in range(c.n_workers)]

        async def main():
            await _cycle_setup(db)
            tasks = [flow.spawn(_cycle_swaps(db, 5))]
            tasks.append(flow.spawn(_attrition(c, 2, machines)))
            await flow.wait_for_all(tasks)
            await _cycle_check(db)
            # post-workload replica sweep (ref: tester.actor.cpp:741
            # running ConsistencyCheck after sim tests)
            await check_consistency(c)
            return True

        assert c.run(main(), timeout_time=900), kw
    finally:
        c.shutdown()


@pytest.mark.parametrize("seed", (3301, 3302, 3303))
def test_dd_split_merge_vacate_under_attrition(seed):
    """Data distribution's structural operations — shard SPLITS (fresh
    tags), exclusion VACATES, and cold MERGES — racing role kills and
    link clogs: every acknowledged write survives, and the published
    shard map stays contiguous with unique tags throughout (ref:
    moveKeys + MachineAttrition stacked, the reference's DD churn
    coverage)."""
    c = SimCluster(seed=seed, durable=True, n_storage=1, n_workers=7)
    flow.SERVER_KNOBS.init("DD_SHARD_SPLIT_BYTES", 1000)
    try:
        db = c.client()
        machines = [f"w{i}" for i in range(c.n_workers)]

        def check_map():
            info = c.cc.dbinfo.get()
            tags = [s.tag for s in info.storages]
            assert len(set(tags)) == len(tags), tags
            assert info.storages[0].begin == b""
            assert info.storages[-1].end is None
            for i in range(len(info.storages) - 1):
                assert info.storages[i].end == \
                    info.storages[i + 1].begin, info.storages

        async def main():
            acked = {}

            async def writer(lo, hi):
                for i in range(lo, hi):
                    k, v = b"dd%05d" % i, b"v%d" % i

                    async def body(tr, k=k, v=v):
                        tr.set(k, v)
                    await run_transaction(db, body, max_retries=500)
                    acked[k] = v

            # phase 1: grow a hot shard while killing things — splits
            # happen mid-attrition
            at = flow.spawn(_attrition(c, 6, machines))
            await writer(0, 300)
            await at
            for _ in range(120):
                await flow.delay(0.5)
                check_map()
                if len(c.cc.dbinfo.get().storages) >= 2:
                    break
            else:
                raise AssertionError("no split under attrition")

            # phase 2: exclude a storage-hosting worker mid-churn
            info = c.cc.dbinfo.get()
            victim = None
            for name, wi in c.cc.workers.items():
                if any(rn.startswith("storage") for rn in wi.worker.roles) \
                        and wi.worker.process.alive:
                    victim = name
                    break
            if victim is not None:
                try:
                    await db.exclude(victim)
                except flow.FdbError:
                    pass   # refused exclusions (too few workers) are fine
                at = flow.spawn(_attrition(c, 4, machines))
                await writer(300, 380)
                await at
                if victim in c.cc.excluded:
                    for _ in range(240):
                        await flow.delay(0.5)
                        check_map()
                        hosts = {w for w, wi in c.cc.workers.items()
                                 for s in c.cc.dbinfo.get().storages
                                 for r in s.replicas
                                 if r.name in wi.worker.roles}
                        if victim not in hosts:
                            break
                    else:
                        raise AssertionError("vacate stalled")
                    await db.exclude(victim, exclude=False)

            # phase 3: cool the keyspace — merges fold shards back
            async def wipe(tr):
                tr.clear_range(b"dd", b"de")
            await run_transaction(db, wipe, max_retries=500)
            acked.clear()

            async def keep(tr):
                tr.set(b"keep", b"1")
            await run_transaction(db, keep, max_retries=500)
            for _ in range(240):
                await flow.delay(0.5)
                check_map()
                if len(c.cc.dbinfo.get().storages) == 1:
                    break
            # merge-back is best-effort under churn; the map must still
            # be consistent and every surviving key correct either way
            check_map()

            async def check(tr):
                assert await tr.get(b"keep") == b"1"
                rows = await tr.get_range(b"dd", b"de")
                assert rows == sorted(acked.items()), (
                    len(rows), len(acked))
            await run_transaction(db, check, max_retries=500)
            return True

        assert c.run(main(), timeout_time=1200)
    finally:
        c.shutdown()


@pytest.mark.parametrize("seed", (3401, 3402))
def test_dd_churn_with_buggify(seed):
    """The DD structural operations under BUGGIFY: randomized knobs
    (tiny batch windows, distorted thresholds) + injected delays while
    shards split and roles die (ref: BUGGIFY as the chaos amplifier in
    every simulation run)."""
    c = SimCluster(seed=seed, durable=True, n_storage=1, n_workers=6,
                   buggify=True)
    flow.SERVER_KNOBS.init("DD_SHARD_SPLIT_BYTES", 900)
    try:
        db = c.client()
        machines = [f"w{i}" for i in range(c.n_workers)]

        async def main():
            acked = {}
            at = flow.spawn(_attrition(c, 4, machines))
            for i in range(200):
                async def body(tr, i=i):
                    tr.set(b"bg%05d" % i, b"v%d" % i)
                await run_transaction(db, body, max_retries=800)
                acked[b"bg%05d" % i] = b"v%d" % i
            await at
            for _ in range(200):
                await flow.delay(0.5)
                info = c.cc.dbinfo.get()
                tags = [s.tag for s in info.storages]
                assert len(set(tags)) == len(tags)
                if len(info.storages) >= 2:
                    break

            async def check(tr):
                rows = await tr.get_range(b"bg", b"bh")
                assert rows == sorted(acked.items()), (
                    len(rows), len(acked))
            await run_transaction(db, check, max_retries=800)
            return True

        assert c.run(main(), timeout_time=1800)
    finally:
        c.shutdown()


@pytest.mark.parametrize("seed", (3501, 3502))
def test_multikey_atomicity_under_attrition(seed):
    """Writers update a GROUP of keys to the same stamp in one
    transaction while readers continuously assert the group is always
    internally consistent — atomicity is never violated even while
    roles die and links clog (ref: the Atomic*/WriteDuringRead family
    of consistency workloads)."""
    c = SimCluster(seed=seed, durable=True, n_logs=2, n_storage=2,
                   n_workers=6)
    try:
        writer_db = c.client("writer")
        reader_db = c.client("reader")
        machines = [f"w{i}" for i in range(c.n_workers)]
        GROUP = [b"atom/a", b"atom/b", b"atom/c"]

        async def main():
            async def init(tr):
                for k in GROUP:
                    tr.set(k, b"stamp0")
            await run_transaction(writer_db, init, max_retries=500)

            stop = [False]
            checked = [0]

            async def writer():
                i = 1
                while not stop[0]:
                    async def body(tr, i=i):
                        for k in GROUP:
                            tr.set(k, b"stamp%d" % i)
                    await run_transaction(writer_db, body, max_retries=800)
                    i += 1
                    await flow.delay(0.01)

            async def reader():
                while not stop[0]:
                    async def body(tr):
                        vals = [await tr.get(k) for k in GROUP]
                        assert len(set(vals)) == 1, vals  # all-or-nothing
                    await run_transaction(reader_db, body, max_retries=800)
                    checked[0] += 1
                    await flow.delay(0.01)

            w = flow.spawn(writer())
            r = flow.spawn(reader())
            await _attrition(c, 6, machines)
            await flow.delay(1.0)
            stop[0] = True
            await flow.wait_for_all([w, r])
            assert checked[0] > 20, checked[0]
            return True

        assert c.run(main(), timeout_time=1200)
    finally:
        c.shutdown()
