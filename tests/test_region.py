"""Multi-region: LogRouter-style async replication + region failover.

Ref: fdbserver/LogRouter.actor.cpp, TagPartitionedLogSystem remote log
sets, SimulatedCluster.actor.cpp:790 (region configs). The contract
under test is the fearless-async guarantee: after a full primary
blackout, the promoted region serves every write the router had
shipped (version <= the remote frontier) — losses are bounded by the
advertised lag — and the promoted region is a live transaction system
(commits, conflicts) afterwards.
"""

import pytest

from foundationdb_tpu import flow
from foundationdb_tpu.client import run_transaction
from foundationdb_tpu.server import SimCluster
from foundationdb_tpu.server.region import RemoteRegion


def _blackout_primary(c):
    """Kill every region-A process: workers, CC, coordinators."""
    for w in list(c.workers.values()):
        if w.process.alive:
            c.net.kill(w.process)
    c.net.kill(c.cc.process)
    for coord in c.coordinators:
        if coord.process.alive:
            c.net.kill(coord.process)


def test_region_failover_preserves_shipped_writes():
    c = SimCluster(seed=801, durable=True, auto_reboot=False)
    try:
        db = c.client()

        async def main():
            region = RemoteRegion(c)
            await region.start()

            committed = {}   # key -> commit version
            for i in range(40):
                tr = db.create_transaction()
                tr.set(b"k%03d" % i, b"v%d" % i)
                v = await tr.commit()
                committed[b"k%03d" % i] = v
                if i % 5 == 0:
                    await flow.delay(0.05)

            # advertised lag is a real number while replicating
            assert region.lag() >= 0

            # let the router ship at least the first 30 writes, then
            # cut region A off mid-stream
            target = committed[b"k%03d" % 29]
            deadline = flow.now() + 60
            while region._pushed_to < target:
                assert flow.now() < deadline, "router never caught up"
                tr = db.create_transaction()   # nudges known_committed
                tr.set(b"nudge", b"x")
                await tr.commit()
                await flow.delay(0.05)

            _blackout_primary(c)
            promoted = await region.promote()
            rv = promoted.recovery_version

            # the guarantee: every write at or below the remote
            # frontier survived the blackout
            rows = dict(await promoted.get_range(b"k", b"l"))
            for key, v in committed.items():
                if v <= rv:
                    assert rows.get(key) == b"v%d" % int(key[1:]), \
                        (key, v, rv)
            # at least the forced-shipped prefix is there
            for i in range(30):
                assert b"k%03d" % i in rows

            # region B is a live transaction system: commit + read
            grv = await promoted.get_read_version()
            from foundationdb_tpu.server.types import (MutationRef,
                                                       SET_VALUE)
            nk = (b"post-failover", b"post-failover\x00")
            v2 = await promoted.commit(
                grv, (), (nk,),
                (MutationRef(SET_VALUE, b"post-failover", b"yes"),))
            await promoted.wait_applied(v2)
            assert await promoted.get(b"post-failover") == b"yes"

            # ...with real conflict detection: two writers of one key
            # from the same snapshot — second one aborts
            grv2 = await promoted.get_read_version()
            ck = (b"occ", b"occ\x00")
            await promoted.commit(grv2, (ck,), (ck,),
                                  (MutationRef(SET_VALUE, b"occ", b"a"),))
            with pytest.raises(flow.FdbError) as ei:
                await promoted.commit(grv2, (ck,), (ck,),
                                      (MutationRef(SET_VALUE, b"occ",
                                                   b"b"),))
            assert ei.value.name == "not_committed"
            return True

        assert c.run(main(), timeout_time=900)
    finally:
        c.shutdown()


def test_router_survives_primary_recovery():
    """The log stream crosses primary epoch changes: a tlog kill and
    recovery mid-replication must not leave a hole in the remote copy
    (ref: the log router draining old generations before the current
    one)."""
    c = SimCluster(seed=803, durable=True)
    try:
        db = c.client()

        async def main():
            region = RemoteRegion(c)
            await region.start()

            for i in range(15):
                async def body(tr, i=i):
                    tr.set(b"r%03d" % i, b"w%d" % i)
                await run_transaction(db, body, max_retries=500)
            c.kill_role("tlog")
            last_v = 0
            for i in range(15, 30):
                async def body(tr, i=i):
                    tr.set(b"r%03d" % i, b"w%d" % i)
                await run_transaction(db, body, max_retries=500)
            tr = db.create_transaction()
            tr.set(b"final", b"1")
            last_v = await tr.commit()

            # ship everything, then compare the remote replica's data
            deadline = flow.now() + 120
            while region._pushed_to < last_v or \
                    region.storage.version.get() < last_v:
                assert flow.now() < deadline, (
                    region._pushed_to, region.storage.version.get(),
                    last_v)
                tr = db.create_transaction()
                tr.set(b"nudge", b"x")
                await tr.commit()
                await flow.delay(0.05)

            from foundationdb_tpu.server.types import \
                StorageGetRangeRequest
            rows = dict(await region.storage.ranges.ref().get_reply(
                StorageGetRangeRequest(b"r", b"s",
                                       region.storage.version.get(),
                                       1 << 20), db.process))
            for i in range(30):
                assert rows.get(b"r%03d" % i) == b"w%d" % i, i
            await region.stop()
            return True

        assert c.run(main(), timeout_time=900)
    finally:
        c.shutdown()
