"""Multi-region: LogRouter-style async replication + failover THROUGH
the recovery machinery.

Ref: fdbserver/LogRouter.actor.cpp, TagPartitionedLogSystem remote log
sets (epochEnd recovering from them, :1265), SimulatedCluster
.actor.cpp:790 (region configs), fdbcli force_recovery_with_data_loss.

The contract under test is the fearless-async guarantee plus the
round-5 requirements: after a full primary blackout, promotion is a
COORDINATED-STATE RECOVERY (new CC elected over the surviving
coordinator quorum, remote log locked, roles recruited), the promoted
region is sharded like the primary (>= 2 storage shards), every write
the router shipped survives, and a concurrent client rides the
transition on its ordinary retry loop by re-finding the controller
through the coordinators.
"""

import pytest

from foundationdb_tpu import flow
from foundationdb_tpu.client import run_transaction
from foundationdb_tpu.server import SimCluster
from foundationdb_tpu.server.dbinfo import FULLY_RECOVERED
from foundationdb_tpu.server.region import RemoteRegion


def _blackout_primary(c, keep_coordinators=()):
    """Kill every region-A process: workers, CC, and the primary-side
    coordinators (the survivors model the reference's fearless layouts
    placing a coordinator majority outside the primary DC)."""
    for w in list(c.workers.values()):
        if w.process.alive:
            c.net.kill(w.process)
    c.net.kill(c.cc.process)
    for i, coord in enumerate(c.coordinators):
        if i not in keep_coordinators and coord.process.alive:
            c.net.kill(coord.process)


def test_region_failover_through_recovery():
    c = SimCluster(seed=801, durable=True, auto_reboot=False,
                   n_coordinators=5, n_storage=2)
    try:
        db = c.client()

        async def main():
            region = RemoteRegion(c)
            await region.start()
            # attach was a recovery: the epoch moved and the region's
            # log store is in the coordinated state
            cstate = await c.cc._cstate.read()
            assert cstate.region_logs == region.log_stores()

            committed = {}   # key -> commit version
            for i in range(40):
                # alternate halves of the keyspace so the stream feeds
                # BOTH remote shards (split at 0x80)
                key = (b"k%03d" if i % 2 else b"\xc8%03d") % i
                tr = db.create_transaction()
                tr.set(key, b"v%d" % i)
                v = await tr.commit()
                committed[key] = v
                if i % 5 == 0:
                    await flow.delay(0.05)
            assert region.lag() >= 0

            # a concurrent client that never stops: its writes ride
            # the ordinary retry loop across the blackout
            progress = {"before": 0, "after": 0}
            phase = ["before"]
            stop = [False]

            async def writer():
                n = 0
                while not stop[0]:
                    async def body(tr, n=n):
                        tr.set(b"live-%04d" % n, b"x")
                    await run_transaction(db, body, max_retries=100000)
                    progress[phase[0]] += 1
                    n += 1
                    await flow.delay(0.1)

            writer_task = flow.spawn(writer(), name="concurrentWriter")

            # let the router ship at least the first 30 writes, then
            # cut region A off mid-stream
            target = committed[b"k%03d" % 29]
            deadline = flow.now() + 60
            while region._pushed_to < target:
                assert flow.now() < deadline, "router never caught up"
                tr = db.create_transaction()   # nudges known_committed
                tr.set(b"nudge", b"x")
                await tr.commit()
                await flow.delay(0.05)

            old_epoch = c.cc.dbinfo.get().epoch
            _blackout_primary(c, keep_coordinators=(2, 3, 4))
            phase[0] = "after"
            writes_at_blackout = progress["before"]

            promoted = await region.promote()
            rv = promoted.recovery_version

            # promotion WAS a recovery: a fresh epoch above the
            # primary's, fully recovered, committed into the same
            # coordinated state
            info = promoted.cc.dbinfo.get()
            assert info.epoch > old_epoch
            assert info.recovery_state == FULLY_RECOVERED
            cstate2 = await promoted.cc._cstate.read()
            assert cstate2.epoch == info.epoch
            # ...and the promoted region is SHARDED like the primary
            assert len(info.storages) >= 2
            assert len({s.tag for s in info.storages}) == len(info.storages)

            # the guarantee: every write at or below the remote
            # frontier survived the blackout
            pdb = promoted.client()

            async def read_all(tr):
                lo = await tr.get_range(b"k", b"l")
                hi = await tr.get_range(b"\xc8", b"\xc9")
                return list(lo) + list(hi)
            rows = dict(await run_transaction(pdb, read_all,
                                              max_retries=500))
            for key, v in committed.items():
                if v <= rv:
                    assert rows.get(key) == b"v%d" % int(key[1:]), \
                        (key, v, rv)
            for i in range(30):
                key = (b"k%03d" if i % 2 else b"\xc8%03d") % i
                assert key in rows

            # the data really is spread across BOTH remote shards
            per_shard = []
            for s in region.storage_objs():
                lo, hi = s.shard_begin, s.shard_end or b"\xff"
                per_shard.append(sum(1 for k in rows
                                     if lo <= k < hi))
            assert all(n > 0 for n in per_shard), per_shard

            # the concurrent client survived the transition: its loop
            # keeps committing against the promoted cluster with no
            # new handle — it re-found the CC through the coordinators
            deadline = flow.now() + 120
            while progress["after"] < 3:
                assert flow.now() < deadline, \
                    "writer never recovered after failover"
                await flow.delay(0.5)
            stop[0] = True
            await flow.catch_errors(writer_task)
            assert progress["after"] >= 3
            # at least one of its post-blackout writes is readable
            async def read_live(tr):
                return await tr.get_range(b"live-", b"live.\xff")
            live = dict(await run_transaction(pdb, read_live,
                                              max_retries=500))
            assert len(live) >= progress["after"] - 1
            _ = writes_at_blackout  # (diagnostic)

            # the promoted region is a live transaction system with
            # real conflict detection: two writers of one key from the
            # same snapshot — the second aborts
            tr1 = pdb.create_transaction()
            tr2 = pdb.create_transaction()
            assert (await tr1.get(b"occ")) is None
            assert (await tr2.get(b"occ")) is None
            tr1.set(b"occ", b"a")
            tr2.set(b"occ", b"b")
            await tr1.commit()
            with pytest.raises(flow.FdbError) as ei:
                await tr2.commit()
            assert ei.value.name == "not_committed"
            return True

        assert c.run(main(), timeout_time=900)
    finally:
        c.shutdown()


def test_router_survives_primary_recovery():
    """The log stream crosses primary epoch changes: a tlog kill and
    recovery mid-replication must not leave a hole in the remote copy
    (ref: the log router draining old generations before the current
    one)."""
    c = SimCluster(seed=803, durable=True)
    try:
        db = c.client()

        async def main():
            region = RemoteRegion(c)
            await region.start()

            for i in range(15):
                async def body(tr, i=i):
                    tr.set(b"r%03d" % i, b"w%d" % i)
                await run_transaction(db, body, max_retries=500)
            c.kill_role("tlog")
            last_v = 0
            for i in range(15, 30):
                async def body(tr, i=i):
                    tr.set(b"r%03d" % i, b"w%d" % i)
                await run_transaction(db, body, max_retries=500)
            tr = db.create_transaction()
            tr.set(b"final", b"1")
            last_v = await tr.commit()

            # ship everything, then compare the remote copy
            deadline = flow.now() + 120
            while region._pushed_to < last_v or \
                    region.applied_version() < last_v:
                assert flow.now() < deadline, (
                    region._pushed_to, region.applied_version(), last_v)
                tr = db.create_transaction()
                tr.set(b"nudge", b"x")
                await tr.commit()
                await flow.delay(0.05)

            rows = {}
            from foundationdb_tpu.server.types import \
                StorageGetRangeRequest
            for s in region.storage_objs():
                part = await s.ranges.ref().get_reply(
                    StorageGetRangeRequest(b"r", b"s",
                                           s.version.get(), 1 << 20),
                    db.process)
                rows.update(dict(part))
            for i in range(30):
                assert rows.get(b"r%03d" % i) == b"w%d" % i, i
            await region.stop()
            return True

        assert c.run(main(), timeout_time=900)
    finally:
        c.shutdown()


def test_satellite_failover_loses_nothing():
    """Satellite log replicas (ref: satelliteTagLocations,
    TagPartitionedLogSystem.actor.cpp:156-220): with satellites, a
    primary blackout loses NO acked commit even when the region router
    has shipped nothing — promotion locks the surviving satellite
    replicas, which hold the complete acked stream, and recovers at
    their frontier (the fearless guarantee, not just the async one)."""
    c = SimCluster(seed=811, durable=True, auto_reboot=False,
                   n_coordinators=5, n_storage=2)
    try:
        db = c.client()

        async def main():
            region = RemoteRegion(c, n_satellites=2)
            await region.start()
            # attach recruited satellite replicas into the log set
            info = c.cc.dbinfo.get()
            sat_stores = [s for s, _m in (info.logs.stores or ())
                          if "-sat" in s]
            assert len(sat_stores) == 2, info.logs.stores

            # model maximum router lag: the remote DC receives nothing
            region._router_task.cancel()

            committed = {}
            for i in range(24):
                key = (b"k%03d" if i % 2 else b"\xc8%03d") % i
                tr = db.create_transaction()
                tr.set(key, b"v%d" % i)
                committed[key] = await tr.commit()
            assert region._pushed_to < max(committed.values())

            old_epoch = c.cc.dbinfo.get().epoch
            _blackout_primary(c, keep_coordinators=(2, 3, 4))

            promoted = await region.promote()
            # zero loss: the recovery frontier covers EVERY acked commit
            assert promoted.recovery_version >= max(committed.values()), (
                promoted.recovery_version, max(committed.values()))
            info2 = promoted.cc.dbinfo.get()
            assert info2.epoch > old_epoch

            pdb = promoted.client()

            async def read_all(tr):
                rows = dict(await tr.get_range(b"", b"\xff"))
                for k in committed:
                    assert rows.get(k) is not None, (k, len(rows))
            await run_transaction(pdb, read_all, max_retries=500)
            return True

        assert c.run(main(), timeout_time=900)
    finally:
        c.shutdown()


def test_satellite_death_recovers_and_commits_resume():
    """A satellite replica is a critical process: its death ends the
    epoch; the next recovery recruits replicas on the surviving
    satellite workers and commits resume (ref: recruitment degrading
    across satellite failures rather than wedging the push)."""
    c = SimCluster(seed=813, durable=True, n_coordinators=3)
    try:
        db = c.client()

        async def main():
            region = RemoteRegion(c, n_satellites=2)
            await region.start()
            epoch0 = c.cc.dbinfo.get().epoch

            async def w(tr):
                tr.set(b"a", b"1")
            await run_transaction(db, w)

            # kill one satellite worker outright
            c.net.kill(region.satellite_workers[0].process)

            # commits keep working across the triggered recovery
            for i in range(5):
                async def body(tr, i=i):
                    tr.set(b"k%d" % i, b"v%d" % i)
                await run_transaction(db, body, max_retries=1000)

            deadline = flow.now() + 60
            while c.cc.dbinfo.get().epoch == epoch0:
                assert flow.now() < deadline, "no recovery after sat death"
                await flow.delay(0.2)
            info = c.cc.dbinfo.get()
            sat_stores = [s for s, _m in (info.logs.stores or ())
                          if "-sat" in s]
            # the dead satellite is gone from the set; the survivor
            # carries the replica
            assert len(sat_stores) == 1, info.logs.stores

            tr = db.create_transaction()
            assert await tr.get(b"k4") == b"v4"
            return True

        assert c.run(main(), timeout_time=900)
    finally:
        c.shutdown()
