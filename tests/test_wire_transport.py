"""Wire format + transports: encoding round-trips, the simulated
network's serialize-everything hook, and a localhost TCP smoke test.

Ref: flow/serialize.h (byte encodings for every RPC struct),
fdbrpc/FlowTransport.actor.cpp:200/:517 (ConnectPacket handshake,
token-addressed delivery), SURVEY §4 ("no mock-RPC layer — the real
FlowTransport runs over simulated connections, so wire bugs are in
scope").
"""

import pytest

import foundationdb_tpu.flow as fl
from foundationdb_tpu.rpc import SimNetwork, wire
from foundationdb_tpu.server.types import (CommitRequest, KeySelector,
                                           MutationRef, SET_VALUE,
                                           TLogCommitRequest, TLogPeekReply,
                                           TaggedMutation)


def test_roundtrip_primitives_and_messages():
    samples = [
        None, True, False, 0, -1, 1 << 40, -(1 << 70), 3.5, b"", b"abc",
        "héllo", (1, b"x", None), [1, 2, 3], {b"k": (1, 2)},
        MutationRef(SET_VALUE, b"k", b"v"),
        CommitRequest(7, ((b"a", b"b"),), (), (
            MutationRef(SET_VALUE, b"k", b"v"),)),
        TLogCommitRequest(1, 2, (TaggedMutation(
            (0, 3), MutationRef(SET_VALUE, b"k", b"v")),), 5),
        TLogPeekReply(((5, (MutationRef(SET_VALUE, b"a", b"1"),)),), 9, 3),
        KeySelector(b"k", True, -2),
    ]
    for s in samples:
        got = wire.from_bytes(wire.to_bytes(s), None)
        assert got == s, (s, got)


def test_unregistered_type_is_rejected():
    class Sneaky:
        pass

    with pytest.raises(wire.WireError):
        wire.to_bytes(Sneaky())


def test_network_ref_roundtrips_through_sim():
    fl.set_seed(3)
    s = fl.Scheduler(virtual=True)
    fl.set_scheduler(s)
    try:
        net = SimNetwork(s, fl.g_random)
        from foundationdb_tpu.rpc import RequestStream
        proc = net.new_process("svc", machine="m")
        stream = RequestStream(proc)
        ref = stream.ref()
        got = wire.from_bytes(wire.to_bytes(ref), net)
        assert got.endpoint.process is proc
        assert got.endpoint.token == ref.endpoint.token
        # a ref to a vanished process resolves to a dead tombstone
        ghost = wire.from_bytes(wire.to_bytes(ref), net)
        del net.processes["svc"]
        ghost2 = wire.from_bytes(wire.to_bytes(ref), net)
        assert not ghost2.endpoint.process.alive
        assert ghost.endpoint.process.alive  # resolved before the vanish
    finally:
        fl.set_scheduler(None)


def test_sim_delivery_serializes_messages():
    """The simulated network round-trips every request and reply, so a
    mutable object sent by reference CANNOT leak shared state across
    the 'wire'."""
    fl.set_seed(5)
    s = fl.Scheduler(virtual=True)
    fl.set_scheduler(s)
    try:
        net = SimNetwork(s, fl.g_random)
        from foundationdb_tpu.rpc import RequestStream
        server = net.new_process("server", machine="a")
        client = net.new_process("client", machine="b")
        stream = RequestStream(server)

        received = []

        async def serve():
            req, reply = await stream.pop()
            received.append(req)
            reply.send(req)

        async def main():
            t = fl.spawn(serve())
            m = MutationRef(SET_VALUE, b"k", b"v")
            echoed = await stream.ref().get_reply(m, client)
            await t
            assert echoed == m
            assert received[0] == m
            assert received[0] is not m      # a copy crossed the wire
            assert echoed is not received[0]  # and another on the way back
            return True

        t = s.spawn(main())
        assert s.run(until=t, timeout_time=10)
    finally:
        fl.set_scheduler(None)


def test_tcp_connection_death_fails_pending_and_reconnects():
    """A dying server connection fails in-flight requests with
    broken_promise (the sim's closed-connection semantics) and a later
    request transparently reconnects."""
    from foundationdb_tpu.rpc.tcp import TcpRequestStream, TcpTransport

    fl.set_seed(13)
    s = fl.Scheduler(virtual=False)
    fl.set_scheduler(s)
    server = TcpTransport()
    client = TcpTransport()
    try:
        stream = TcpRequestStream(server)
        server.start()
        client.start()

        async def serve():
            while True:
                req, reply = await stream.pop()
                if req == "die":
                    # kill every server-side connection abruptly
                    for c in list(server._conns.values()):
                        c._die()
                    # also close sockets accepted server-side
                    reply.send(None)  # may or may not arrive
                else:
                    reply.send(req)

        async def main():
            fl.spawn(serve())
            ref = client.ref("127.0.0.1", server.port, stream.token)
            assert await ref.get_reply(41) == 41
            # sever from the CLIENT side mid-flight: pending must break
            f = ref.get_reply(42)
            for c in list(client._conns.values()):
                c._die()
            with pytest.raises(fl.FdbError) as ei:
                await f
            assert ei.value.name == "broken_promise"
            # a later request reconnects and succeeds
            assert await ref.get_reply(43) == 43
            return True

        t = s.spawn(main())
        assert s.run(until=t, timeout_time=30)
    finally:
        server.close()
        client.close()
        fl.set_scheduler(None)


def test_tcp_localhost_smoke():
    """A counter service served over REAL localhost TCP sockets with
    the wire format — request/reply framing, protocol handshake, and
    concurrent clients (the production-transport seam)."""
    from foundationdb_tpu.rpc.tcp import TcpRequestStream, TcpTransport

    fl.set_seed(9)
    s = fl.Scheduler(virtual=False)   # wall clock: real sockets
    fl.set_scheduler(s)
    transport = TcpTransport()
    try:
        stream = TcpRequestStream(transport)
        transport.start()
        state = {"n": 0}

        async def serve():
            while True:
                req, reply = await stream.pop()
                if req is None:
                    reply.send(state["n"])
                else:
                    state["n"] += req
                    reply.send(state["n"])

        async def main():
            fl.spawn(serve())
            ref = transport.ref("127.0.0.1", transport.port, stream.token)
            futs = [ref.get_reply(i) for i in range(1, 6)]
            await fl.wait_for_all(futs)
            total = await ref.get_reply(None)
            assert total == 15, total
            # an unknown token breaks like a closed connection
            bad = transport.ref("127.0.0.1", transport.port, 999)
            with pytest.raises(fl.FdbError):
                await bad.get_reply(None)
            return True

        t = s.spawn(main())
        assert s.run(until=t, timeout_time=30)
    finally:
        transport.close()
        fl.set_scheduler(None)


# -- TLS (ref: FDBLibTLS — mutual certificate verification under the
# transport's connect handshake) ---------------------------------------

def _make_cert(tmp_path, name):
    import subprocess
    key = str(tmp_path / f"{name}-key.pem")
    cert = str(tmp_path / f"{name}-cert.pem")
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", key, "-out", cert, "-days", "2",
         "-subj", f"/CN=fdbtpu-{name}"],
        check=True, capture_output=True)
    return cert, key


def test_tcp_tls_mutual_auth(tmp_path):
    """Request/reply over mutually-authenticated TLS; a client with an
    untrusted certificate is rejected at the handshake."""
    from foundationdb_tpu.rpc.tcp import (TcpRequestStream, TcpTransport,
                                          TlsConfig)

    cert, key = _make_cert(tmp_path, "cluster")
    rogue_cert, rogue_key = _make_cert(tmp_path, "rogue")
    tls = TlsConfig(cert, key, cert)

    fl.set_seed(17)
    s = fl.Scheduler(virtual=False)
    fl.set_scheduler(s)
    server = TcpTransport(tls=tls)
    client = TcpTransport(tls=tls)
    # trusts the cluster CA but presents a cert the server won't trust
    rogue = TcpTransport(tls=TlsConfig(rogue_cert, rogue_key, cert))
    try:
        stream = TcpRequestStream(server)
        server.start()
        client.start()
        rogue.start()

        async def serve():
            while True:
                req, reply = await stream.pop()
                reply.send(req * 2)

        async def main():
            fl.spawn(serve())
            ref = client.ref("127.0.0.1", server.port, stream.token)
            assert await ref.get_reply(21) == 42
            bad = rogue.ref("127.0.0.1", server.port, stream.token)
            with pytest.raises(fl.FdbError) as ei:
                await bad.get_reply(1)
            assert ei.value.name == "broken_promise"
            # the trusted client is unaffected by the rejected peer
            assert await ref.get_reply(100) == 200
            return True

        t = s.spawn(main())
        assert s.run(until=t, timeout_time=240)  # loaded machines starve TLS handshakes
    finally:
        server.close()
        client.close()
        rogue.close()
        fl.set_scheduler(None)
