"""Layer recipes: pubsub and queues (ref: layers/pubsub +
recipes/python-recipes in the reference)."""

import pytest

from foundationdb_tpu import flow
from foundationdb_tpu.client import run_transaction
from foundationdb_tpu.layers.pubsub import PubSub
from foundationdb_tpu.layers.queue import PriorityQueue, Queue
from foundationdb_tpu.server import SimCluster


def test_pubsub_fanout_and_watermarks():
    c = SimCluster(seed=210)
    try:
        db = c.client()
        ps = PubSub()

        async def main():
            # two inboxes subscribe; posts BEFORE a subscription are
            # not delivered to it
            async def pre(tr):
                await ps.post(tr, "news", b"ancient history")
            await run_transaction(db, pre)

            async def sub(tr):
                await ps.subscribe(tr, "alice", "news")
                await ps.subscribe(tr, "bob", "news")
                await ps.subscribe(tr, "bob", "sports")
            await run_transaction(db, sub)

            async def post(tr):
                await ps.post(tr, "news", b"headline 1")
                await ps.post(tr, "sports", b"score 2-1")
            await run_transaction(db, post)

            async def read_alice(tr):
                return await ps.read_inbox(tr, "alice")
            got = await run_transaction(db, read_alice)
            assert got == [("news", b"headline 1")]

            # a second read drains nothing new (watermark advanced)
            got = await run_transaction(db, read_alice)
            assert got == []

            async def read_bob(tr):
                return await ps.read_inbox(tr, "bob")
            got = await run_transaction(db, read_bob)
            assert sorted(got) == [("news", b"headline 1"),
                                   ("sports", b"score 2-1")]

            # unsubscribe stops delivery
            async def unsub(tr):
                ps.unsubscribe(tr, "bob", "news")
                await ps.post(tr, "news", b"headline 2")
            await run_transaction(db, unsub)
            got = await run_transaction(db, read_bob)
            assert got == []
            got = await run_transaction(db, read_alice)
            assert got == [("news", b"headline 2")]
            return True

        assert c.run(main(), timeout_time=120)
    finally:
        c.shutdown()


def test_priority_queue_ordering_and_exactly_once():
    c = SimCluster(seed=211)
    try:
        db = c.client()
        pq = PriorityQueue()

        async def main():
            async def fill(tr):
                await pq.push(tr, b"low-a", priority=5)
                await pq.push(tr, b"hi-a", priority=1)
                await pq.push(tr, b"hi-b", priority=1)
                await pq.push(tr, b"mid", priority=3)
            await run_transaction(db, fill)

            async def peek(tr):
                return await pq.peek(tr)
            assert await run_transaction(db, peek) == (1, b"hi-a")

            async def pop(tr):
                return await pq.pop(tr)
            order = [await run_transaction(db, pop) for _ in range(5)]
            assert order == [b"hi-a", b"hi-b", b"mid", b"low-a", None]

            # exactly-once: two racing pops of one item — one wins, one
            # retries onto emptiness
            async def refill(tr):
                await pq.push(tr, b"only", priority=0)
            await run_transaction(db, refill)
            t1 = db.create_transaction()
            t2 = db.create_transaction()
            r1 = await pq.pop(t1)
            r2 = await pq.pop(t2)
            assert r1 == r2 == b"only"
            await t1.commit()
            with pytest.raises(flow.FdbError):
                await t2.commit()
            return True

        assert c.run(main(), timeout_time=120)
    finally:
        c.shutdown()


def test_fifo_queue():
    c = SimCluster(seed=212)
    try:
        db = c.client()
        q = Queue()

        async def main():
            async def fill(tr):
                for i in range(5):
                    await q.push(tr, b"item%d" % i)
            await run_transaction(db, fill)

            async def pop(tr):
                return await q.pop(tr)
            got = [await run_transaction(db, pop) for _ in range(6)]
            assert got == [b"item0", b"item1", b"item2", b"item3",
                           b"item4", None]
            return True

        assert c.run(main(), timeout_time=120)
    finally:
        c.shutdown()


def test_networktest_tool_smoke():
    """The transport microbench runs and reports sane numbers (ref:
    fdbserver -r networktest)."""
    from foundationdb_tpu.tools.networktest import run_networktest

    r = run_networktest(requests=200, parallel=4, payload_bytes=32)
    assert r["requests"] == 200
    assert r["requests_per_second"] > 0
    assert r["p50_ms"] >= 0
