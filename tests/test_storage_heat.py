"""Storage heat plane (ISSUE 13): read-bandwidth sampling, read-hot
sub-range detection, per-storage tag busyness, the typed metrics wire
endpoints, the QoS/status/ratekeeper surfaces, and the storage-aware
auto-throttler input.

Ref: StorageMetrics.actor (bytesReadSample, getReadHotRanges density
math), fdbserver/TransactionTagCounter on the storage server, and the
ratekeeper reading tag busyness from storage queues.
"""

import pytest

from foundationdb_tpu import flow
from foundationdb_tpu.client import run_transaction
from foundationdb_tpu.server import SimCluster
from foundationdb_tpu.server.storage import StorageMetrics


@pytest.fixture
def knobs():
    flow.set_seed(3)
    yield flow.SERVER_KNOBS
    flow.reset_server_knobs()


# -- read sample + meters (unit) ---------------------------------------

def _heat_up(m, hot_reads=400, cold_reads=40, t0=0.0):
    """Uniform byte sample over 64 keys; reads concentrated on the
    first 4 keys, a trickle across the rest."""
    for i in range(64):
        m.note_set(b"k%03d" % i, 110)
    t = t0
    for r in range(hot_reads):
        m.note_read(b"k%03d" % (r % 4), 110, t)
        t += 0.002
    for r in range(cold_reads):
        m.note_read(b"k%03d" % (4 + r % 60), 110, t)
        t += 0.002
    return t


def test_read_hot_detection_flags_hot_bucket(knobs):
    m = StorageMetrics()
    now = _heat_up(m)
    rows = m.read_hot_ranges(b"", b"\xff", now)
    assert rows, "hot bucket never flagged"
    b, e, density, read_bps = rows[0]
    # the flagged range covers the hammered keys and the density
    # crossed the knob ratio
    assert b <= b"k000" and e > b"k003", rows[0]
    assert density >= flow.SERVER_KNOBS.read_hot_range_ratio
    assert read_bps > 0


def test_read_hot_detection_quiet_when_uniform(knobs):
    m = StorageMetrics()
    for i in range(64):
        m.note_set(b"k%03d" % i, 110)
    t = 0.0
    for r in range(640):
        m.note_read(b"k%03d" % (r % 64), 110, t)
        t += 0.002
    assert m.read_hot_ranges(b"", b"\xff", t) == []


def test_read_sample_deterministic_across_replicas(knobs):
    """Deterministic crc32 inclusion: two replicas fed the identical
    read stream at identical times report identical hot ranges and
    identical smoothed rates (the sim-replay/replica contract)."""
    a, b = StorageMetrics(), StorageMetrics()
    ta = _heat_up(a)
    tb = _heat_up(b)
    assert ta == tb
    assert a.read_hot_ranges(b"", b"\xff", ta) == \
        b.read_hot_ranges(b"", b"\xff", tb)
    assert a.read_bytes_per_sec(ta) == b.read_bytes_per_sec(tb)
    assert a.read_ops_per_sec(ta) == b.read_ops_per_sec(tb)


def test_read_meters_decay_and_reset(knobs):
    m = StorageMetrics()
    for t in range(10):
        m.note_read(b"k", 1000, float(t))     # ~1000 B/s, 1 op/s
    r = m.read_bytes_per_sec(10.0)
    assert 500 < r < 1500, r
    assert 0.5 < m.read_ops_per_sec(10.0) < 1.5
    assert m.read_bytes_per_sec(60.0) < 10    # decays when idle
    # reset_rate clears the READ side exactly like the write meter
    # (shrink_to: the departed range's traffic must stop counting)
    m.note_write(500, 10.0)
    m.reset_rate()
    assert m.read_bytes_per_sec(10.0) == 0.0
    assert m.read_ops_per_sec(10.0) == 0.0
    assert m.write_bytes_per_sec(10.0) == 0.0
    assert m._read_sample == {}


def test_read_sample_bounded_at_knob(knobs):
    flow.SERVER_KNOBS.set("read_sample_max_keys", 8)
    m = StorageMetrics()
    for i in range(100):
        m.note_read(b"r%04d" % i, 500, float(i) * 0.01)
    assert len(m._read_sample) <= 8


def test_read_accounting_off_the_serve_path_guard(knobs):
    """The plane's off posture: _serve_get/_serve_range never call
    note_read while STORAGE_HEAT_TRACKING is 0 (the guard is the whole
    per-read cost — PERF.md posture table)."""
    c = SimCluster(seed=1605, durable=True)
    try:
        db = c.client()

        async def main():
            async def seed(tr):
                tr.set(b"g", b"v")
            await run_transaction(db, seed)

            async def rd(tr):
                tr.set_option("transaction_tag", b"off")
                await tr.get(b"g")
                return await tr.get_range(b"a", b"z")
            await run_transaction(db, rd)
            return True

        assert c.run(main(), timeout_time=120)
        for obj in c.cc._storage_objs.values():
            assert obj.metrics._read_sample == {}
            assert obj.metrics.read_bytes_per_sec(flow.now()) == 0.0
            assert obj.tag_counter.top() == []
    finally:
        c.shutdown()


# -- end to end: tags, wire endpoints, status, cli ----------------------

def _drive_hot_reads(c, db, rounds=12):
    async def main():
        async def seed(tr):
            for i in range(48):
                tr.set(b"h%03d" % i, b"V" * 100)
        await run_transaction(db, seed)
        for r in range(rounds):
            async def body(tr, r=r):
                tr.set_option("transaction_tag", b"hotreader")
                # hammer the first two keys, graze the rest
                await tr.get(b"h000")
                await tr.get(b"h001")
                await tr.get(b"h%03d" % (2 + r % 46))
            await run_transaction(db, body)
            await flow.delay(0.15)
        await flow.delay(1.0)   # QoS sampler + heat rollup ticks
        return await db.get_status()
    return c.run(main(), timeout_time=300)


def test_armed_plane_end_to_end_status_qos_cli():
    c = SimCluster(seed=1607, durable=True)
    flow.SERVER_KNOBS.set("storage_heat_tracking", 1)
    flow.SERVER_KNOBS.set("qos_sample_interval", 0.25)
    try:
        db = c.client()
        status = _drive_hot_reads(c, db)
        cl = status["cluster"]

        # the per-storage tag counter charged the read tag
        obj = next(iter(c.cc._storage_objs.values()))
        tag, busy = obj.busiest_read_tag()
        assert tag == b"hotreader" and busy > 0

        # heat signals ride the storage QosSample — the ARMED schema
        # pin: exactly the base inventory plus the heat additions
        # (test_qos_telemetry.py pins the disarmed set)
        from test_qos_telemetry import (STORAGE_HEAT_SIGNALS,
                                        STORAGE_SIGNALS)
        sto = next(iter(cl["qos"]["roles"]["storage"].values()))
        assert set(sto) == STORAGE_SIGNALS | STORAGE_HEAT_SIGNALS | \
            {"sampled_at"}, sto
        assert sto["read_bytes_per_sec"] > 0, sto
        assert sto["busiest_read_tag_busyness"] > 0, sto

        # the cluster rollup names the hot tag; the replicas report
        # read meters in the storages section
        heat = cl["storage_heat"]
        assert heat["tracking_enabled"] == 1
        assert any(r["tag"] == b"hotreader".hex()
                   for r in heat["busiest_read_tags"]), heat
        rep = cl["storages"][0]["replicas"][0]
        assert rep["read_bytes_per_sec"] > 0, rep
        assert rep["read_ops_per_sec"] > 0, rep

        # ratekeeper observe-only input picked the tag up
        assert cl["qos"]["inputs"]["busiest_read_tag_busyness"] > 0
        assert cl["qos"]["busiest_read_tag"] == b"hotreader".hex()

        # cli heat renders the armed view
        from foundationdb_tpu.tools.cli import _render_heat
        view = _render_heat(cl)
        assert "Storage heat (STORAGE_HEAT_TRACKING=on)" in view
        assert b"hotreader".hex() in view, view
    finally:
        flow.reset_server_knobs(randomize=False)
        c.shutdown()


def test_metrics_wire_endpoints_round_trip():
    """The typed probes (StorageMetricsRequest / ReadHotRangesRequest /
    SplitMetricsRequest) served by the storage role."""
    from foundationdb_tpu.server.types import (
        READ_HOT_RANGES_REQUEST, SPLIT_METRICS_REQUEST,
        STORAGE_METRICS_REQUEST)
    c = SimCluster(seed=1609, durable=True)
    flow.SERVER_KNOBS.set("storage_heat_tracking", 1)
    try:
        db = c.client()

        async def main():
            async def seed(tr):
                for i in range(32):
                    tr.set(b"w%03d" % i, b"V" * 100)
            await run_transaction(db, seed)

            async def rd(tr):
                tr.set_option("transaction_tag", b"probe")
                await tr.get(b"w000")
            await run_transaction(db, rd)
            obj = next(iter(c.cc._storage_objs.values()))
            ref = obj.metrics_requests.ref()
            m = await ref.get_reply(STORAGE_METRICS_REQUEST, db.process)
            hot = await ref.get_reply(READ_HOT_RANGES_REQUEST, db.process)
            split = await ref.get_reply(SPLIT_METRICS_REQUEST, db.process)
            return m, hot, split

        m, hot, split = c.run(main(), timeout_time=120)
        assert m.sampled_bytes > 0
        assert m.read_bytes_per_sec > 0
        assert m.read_ops_per_sec > 0
        assert m.busiest_read_tag == b"probe"
        assert m.busiest_read_tag_rate > 0
        assert isinstance(hot.ranges, tuple)
        assert split.split_key is not None and \
            b"w000" < split.split_key < b"w031"
    finally:
        flow.reset_server_knobs(randomize=False)
        c.shutdown()


def test_read_tags_ride_requests_only_when_armed():
    """Byte-identical off posture at the wire vocabulary: the read
    requests carry () tags while the plane is off, and the tag set
    only while armed."""
    from foundationdb_tpu.server.types import StorageGetRequest
    assert StorageGetRequest(b"k", 1) == \
        StorageGetRequest(b"k", 1, None, ())
    c = SimCluster(seed=1611, durable=True)
    try:
        db = c.client()
        tr = db.create_transaction()
        tr.set_option("transaction_tag", b"t")
        assert tr._read_tags() == ()          # off: never attached
        flow.SERVER_KNOBS.set("storage_heat_tracking", 1)
        assert tr._read_tags() == (b"t",)     # armed: the tag set
    finally:
        flow.reset_server_knobs(randomize=False)
        c.shutdown()


# -- storage-aware auto-throttling -------------------------------------

def test_storage_busyness_prefers_per_ss_signal():
    """A read-heavy tenant: few transactions (cluster-wide rate far
    below TAG_THROTTLE_BUSY_RATE) each hammering one shard with many
    reads. With TAG_THROTTLE_STORAGE_BUSYNESS armed the auto-throttler
    must still write the tag's throttle row — the per-SS read-request
    rate is what crosses the line (ref: the ratekeeper reading tag
    busyness from storage servers, ROADMAP item 3)."""
    from foundationdb_tpu.server import systemkeys as sk
    c = SimCluster(seed=1613, durable=True)
    for name, v in (("storage_heat_tracking", 1),
                    ("auto_tag_throttling", 1),
                    ("tag_throttle_storage_busyness", 1),
                    ("tag_throttle_update_interval", 0.25),
                    ("tag_throttle_busy_rate", 25.0),
                    ("tag_throttle_duration", 30.0)):
        flow.SERVER_KNOBS.set(name, v)
    try:
        db = c.client()

        async def main():
            async def seed(tr):
                for i in range(40):
                    tr.set(b"s%03d" % i, b"V" * 64)
            await run_transaction(db, seed)
            # ~3 txn/s for 3s, each doing 40 point reads: per-SS read
            # rate ~120/s >> 25, txn rate ~3/s << 25
            for r in range(9):
                async def body(tr):
                    tr.set_option("transaction_tag", b"scanner")
                    for i in range(40):
                        await tr.get(b"s%03d" % i)
                await run_transaction(db, body)
                await flow.delay(0.3)
            await flow.delay(1.0)

            async def rows(tr):
                tr.set_option("read_system_keys")
                return await tr.get_range(sk.THROTTLED_TAGS_PREFIX,
                                          sk.THROTTLED_TAGS_END)
            return await run_transaction(db, rows, max_retries=200)

        rows = c.run(main(), timeout_time=300)
        throttled = {}
        for key, value in rows:
            tag = sk.parse_throttled_tag_key(key)
            parsed = sk.parse_tag_throttle_value(value)
            if tag is not None and parsed is not None:
                throttled[tag] = parsed
        assert b"scanner" in throttled, sorted(throttled)
        assert throttled[b"scanner"][3] is True   # auto row
        from foundationdb_tpu.flow import coverage
        assert coverage.hits("tag_throttler.storage_busyness") > 0
    finally:
        flow.reset_server_knobs(randomize=False)
        c.shutdown()


# -- HotShardStorm ------------------------------------------------------

def test_hot_shard_storm_schedule_deterministic():
    from foundationdb_tpu.server.workloads import HotShardStorm
    flow.set_seed(515)
    a = HotShardStorm([], flow.g_random, duration=2.0).draw_schedule()
    flow.set_seed(515)
    b = HotShardStorm([], flow.g_random, duration=2.0).draw_schedule()
    assert a == b
    times, hot, keys = a
    assert len(times) == len(hot) == len(keys)
    assert any(hot) and not all(hot)
    # hot arrivals stay inside the declared hot range
    storm = HotShardStorm([], flow.g_random, duration=2.0)
    hb, he = storm.hot_range
    for i in range(len(times)):
        if hot[i]:
            assert hb <= keys[i] < he, (i, keys[i])


def test_hot_shard_storm_runs_and_names_heat():
    from foundationdb_tpu.server.workloads import HotShardStorm
    c = SimCluster(seed=1615, durable=True)
    flow.SERVER_KNOBS.set("storage_heat_tracking", 1)
    flow.SERVER_KNOBS.set("qos_sample_interval", 0.25)
    try:
        dbs = [c.client(f"h{i}") for i in range(2)]

        async def main():
            storm = HotShardStorm(dbs, flow.g_random, duration=2.0,
                                  hot_rate=120.0, background_rate=30.0)
            await storm.seed(dbs[0])
            stats = await storm.run()
            await flow.delay(1.0)
            status = await dbs[0].get_status()
            return storm, stats, status

        storm, stats, status = c.run(main(), timeout_time=300)
        assert stats["issued"] > 50, stats
        assert stats["completed"] > 0, stats
        assert stats["hot_issued"] > stats["background_issued"], stats
        heat = status["cluster"]["storage_heat"]
        assert heat["ranges"], heat
        hb, he = storm.hot_range
        top = heat["ranges"][0]
        assert bytes.fromhex(top["begin"]) < he and \
            bytes.fromhex(top["end"]) > hb, (top, hb, he)
        assert all(r["tag"] == storm.hot_tag.hex()
                   for r in heat["busiest_read_tags"]), heat
    finally:
        flow.reset_server_knobs(randomize=False)
        c.shutdown()
