"""The sim-perf attribution plane (ISSUE 11 / ROADMAP item 6).

What is pinned here, in order:

- **Profiling never perturbs the sim**: the SAME seeded ChaosStorm
  with SIM_TASK_STATS armed vs off yields an IDENTICAL chaos event
  schedule and keyspace digest (the PR 7 same-seed oracle) — the
  plane reads only the wall clock, never the sim timeline.
- **Bounded tables**: task names beyond the cap fold into "(other)"
  (and indexed spawns fold by family); message types likewise.
- **Priority-band rollup**: steps land in the highest named
  TaskPriority level at or below their popped priority.
- **SlowTask stacks**: a slow step's entry carries the coroutine
  suspension stack (code location, not just the task label).
- **Off-posture timing**: with every profiling consumer off
  (threshold 0, plane off) the loop skips per-step timing yet
  busy_seconds stays correct through coarse accounting.
- **Exporter round-trip**: the fdbtpu_task_* / fdbtpu_net_* /
  fdbtpu_sim_* families render and re-parse with exact values.
- **The regression gate**: tools/simprof.py --compare exits non-zero
  on an injected wall-time regression and zero otherwise.
"""

import time as _t

import pytest

from foundationdb_tpu import flow
from foundationdb_tpu.flow.scheduler import (Scheduler, TaskPriority,
                                             priority_band)
from foundationdb_tpu.rpc import SimNetwork
from foundationdb_tpu.server import SimCluster
from foundationdb_tpu.server.chaos import SCENARIOS
from foundationdb_tpu.server.workloads import ChaosStorm
from foundationdb_tpu.tools.exporter import (parse_prometheus,
                                             render_prometheus)
from foundationdb_tpu.tools.simprof import (baseline_row, compare_reports)


def _run_chaos(armed: bool, seed: int) -> dict:
    kwargs = dict(SCENARIOS["partition_minority"].cluster_kwargs)
    c = SimCluster(seed=seed, **kwargs)
    if armed:
        c.sched.start_task_stats()
        c.net.arm_message_stats()
    try:
        dbs = [c.client(f"chaos{i}") for i in range(3)]
        storm = ChaosStorm(c, dbs, flow.g_random, "partition_minority")
        return c.run(storm.run(), timeout_time=900)
    finally:
        c.shutdown()


def test_armed_vs_off_same_seed_identical(sim_seed):
    """The acceptance oracle: arming the plane must not move a single
    sim event — same seed, identical fault schedule and keyspace
    digest, identical storm outcome."""
    seed = sim_seed(101)
    off = _run_chaos(armed=False, seed=seed)
    on = _run_chaos(armed=True, seed=seed)
    assert on["events"] == off["events"], (seed, off["events"][:3])
    assert on["digest"] == off["digest"], seed
    assert on["storm"]["issued"] == off["storm"]["issued"]
    assert on["storm"]["completed"] == off["storm"]["completed"]
    # ...and the armed run actually attributed the wall time
    sp = on["sim_perf"]
    assert sp["tasks_run"] > 0 and sp["wall_seconds"] > 0
    assert sp.get("top_tasks"), sp
    assert sp.get("top_messages"), sp
    assert sp.get("priority_bands"), sp
    # the off run carries the budget too, just without the tables
    assert off["sim_perf"]["tasks_run"] == sp["tasks_run"]
    assert "top_tasks" not in off["sim_perf"]


# -- bounded tables -------------------------------------------------------

def test_task_table_bounds_and_name_folding():
    flow.set_seed(5)
    s = Scheduler(virtual=True)
    s.start_task_stats(max_names=3)

    async def nop():
        return None

    # indexed spawns fold into one family...
    for i in range(4):
        s.spawn(nop(), name=f"txn-{i}")
    # ...distinct families beyond the cap share "(other)"
    for name in ("alpha", "beta", "gamma", "delta"):
        s.spawn(nop(), name=name)
    s.run()
    rep = s.stop_task_stats()
    table = {r["task"]: r for r in rep["tasks"]}
    assert table["txn-*"]["steps"] == 4, table
    assert "(other)" in table, table
    assert len(table) <= 4, table    # cap + the overflow bucket
    assert rep["dropped_names"] >= 1
    total = sum(r["steps"] for r in rep["tasks"])
    assert total == 8, rep           # every step attributed somewhere
    assert s.task_stats_armed is False


def test_message_table_bounds():
    flow.set_seed(6)
    s = Scheduler(virtual=True)
    net = SimNetwork(s, flow.g_random)
    net.arm_message_stats(max_types=2)
    for t in ("A", "A", "B", "C", "D"):
        net._count_msg(t)
    rep = net.message_stats_report()
    by = {r["type"]: r["count"] for r in rep["types"]}
    assert by == {"A": 2, "B": 1, "(other)": 2}, by
    assert rep["dropped_types"] == 2
    assert rep["armed"] == 1
    # population gauges are pull-computed from the scheduler heaps
    s.delay(1.0)
    assert net.message_stats_report()["timers_now"] == 1


# -- priority bands -------------------------------------------------------

def test_priority_band_rollup():
    assert priority_band(TaskPriority.STORAGE) == "storage"
    # between two named levels -> the level it outranks
    assert priority_band(TaskPriority.PROXY_COMMIT + 5) == "proxy_commit"
    assert priority_band(-7) == "zero"
    assert priority_band(TaskPriority.MAX + 1) == "max"

    flow.set_seed(7)
    s = Scheduler(virtual=True)
    s.start_task_stats()

    async def nop():
        return None

    s.spawn(nop(), priority=TaskPriority.STORAGE, name="st")
    s.spawn(nop(), priority=TaskPriority.PROXY_COMMIT, name="pc")
    s.spawn(nop(), priority=TaskPriority.PROXY_COMMIT + 3, name="pc2")
    s.run()
    bands = {b["band"]: b for b in s.task_stats_report()["bands"]}
    assert bands["storage"]["steps"] == 1, bands
    assert bands["proxy_commit"]["steps"] == 2, bands


# -- SlowTask suspension stacks -------------------------------------------

def test_slow_task_captures_suspension_stack():
    flow.set_seed(8)
    s = Scheduler(virtual=True)
    s.slow_task_threshold = 0.005
    flow.set_scheduler(s)
    try:
        async def hog():
            _t.sleep(0.012)          # the blocking anti-pattern
            await flow.delay(0.0)    # suspends here -> frame captured

        t = s.spawn(hog(), name="stackHog")
        s.run(until=t, timeout_time=10)
        assert s.slow_task_count >= 1
        entries = [e for e in s.slow_tasks if e[0] == "stackHog"]
        assert entries, s.slow_tasks
        _name, secs, stack = entries[0]
        assert secs >= 0.005
        assert "hog" in stack and ".py:" in stack, stack
        # the trace event carries it too
        evs = [e for e in flow.g_trace.events
               if e["Type"] == "SlowTask" and e["TaskName"] == "stackHog"]
        assert evs and "hog" in evs[-1]["Stack"], evs
    finally:
        flow.set_scheduler(None)


# -- off-posture timing ---------------------------------------------------

def test_all_consumers_off_skips_fine_timing_keeps_busy_seconds():
    """Threshold 0 + plane off: no slow-task sampling fires (it used
    to flag EVERY step at threshold 0), and busy_seconds still
    advances via the coarse window."""
    flow.set_seed(9)
    s = Scheduler(virtual=True)
    s.slow_task_threshold = 0.0

    async def spin():
        x = 0
        for _ in range(20_000):
            x += 1
        return x

    for i in range(50):
        s.spawn(spin(), name=f"spin{i}")
    s.run()
    assert s.slow_task_count == 0
    assert s.slow_tasks == []
    assert s.tasks_run == 50
    assert s.busy_seconds > 0.0        # coarse accounting flushed
    # arming mid-life flips back to fine timing + attribution
    s.start_task_stats()
    s.spawn(spin(), name="late")
    s.run()
    table = {r["task"] for r in s.task_stats_report()["tasks"]}
    assert "late" in table


# -- exporter round-trip --------------------------------------------------

def test_exporter_families_round_trip():
    status = {"cluster": {
        "run_loop": {
            "tasks_run": 10, "busy_seconds": 0.5, "sim_seconds": 2.0,
            "sim_per_busy": 4.0, "slow_task_count": 1,
            "slow_task_threshold": 0.05,
            "slow_tasks": [{"task": "hog", "seconds": 0.06,
                            "stack": "hog (x.py:12)"}],
            "task_stats": {
                "armed": 1,
                "tasks": [{"task": "commit", "steps": 5,
                           "busy_us": 123.5, "max_us": 50.0}],
                "bands": [{"band": "storage", "steps": 5,
                           "busy_us": 123.5}],
                "dropped_names": 2}},
        "network": {
            "armed": 1,
            "types": [{"type": "CommitRequest", "count": 3},
                      {"type": "CommitRequest.reply", "count": 3}],
            "dropped_types": 0, "messages_sent": 6,
            "messages_dropped": 1, "messages_duplicated": 0,
            "timers_now": 4, "ready_now": 2},
    }}
    samples = parse_prometheus(render_prometheus(status))
    val = {}
    for n, labels, v in samples:
        val[(n, tuple(sorted(labels.items())))] = v
    assert val[("fdbtpu_sim_seconds", ())] == 2.0
    assert val[("fdbtpu_sim_per_busy_second", ())] == 4.0
    assert val[("fdbtpu_task_steps", (("task", "commit"),))] == 5
    assert val[("fdbtpu_task_busy_us", (("task", "commit"),))] == 123.5
    assert val[("fdbtpu_task_max_step_us", (("task", "commit"),))] == 50.0
    assert val[("fdbtpu_task_band_steps", (("band", "storage"),))] == 5
    assert val[("fdbtpu_task_names_dropped", ())] == 2
    assert val[("fdbtpu_net_messages",
                (("type", "CommitRequest"),))] == 3
    assert val[("fdbtpu_net_messages",
                (("type", "CommitRequest.reply"),))] == 3
    assert val[("fdbtpu_net_messages_dropped", ())] == 1
    assert val[("fdbtpu_net_delivery_timers", ())] == 4
    assert val[("fdbtpu_net_ready_tasks", ())] == 2
    # the slow-task row carries its stack as a label
    assert val[("fdbtpu_run_loop_slow_task_seconds",
                (("stack", "hog (x.py:12)"), ("task", "hog")))] == 0.06


# -- the --compare regression gate ----------------------------------------

def test_compare_flags_injected_regression():
    base = {"open_loop": {"seed": 1, "sim_seconds": 3.0,
                          "wall_seconds": 1.0, "sim_per_wall": 3.0,
                          "tasks_run": 1000, "tasks_per_wall_sec": 1000.0,
                          "messages_sent": 500}}
    ok_run = {n: dict(r) for n, r in base.items()}
    regs, lines = compare_reports(ok_run, base, tolerance=2.0)
    assert not regs and any("[ok]" in ln for ln in lines)
    bad_run = {n: dict(r) for n, r in base.items()}
    bad_run["open_loop"]["wall_seconds"] = 3.5   # 3.5x > 2x tolerance
    regs, lines = compare_reports(bad_run, base, tolerance=2.0)
    assert regs and "open_loop" in regs[0], (regs, lines)
    assert any("REGRESSED" in ln for ln in lines)
    # a run on a DIFFERENT seed is a different workload shape: never
    # gated against this baseline, reported as skipped instead
    mismatch = {n: dict(r) for n, r in base.items()}
    mismatch["open_loop"]["seed"] = 2
    mismatch["open_loop"]["wall_seconds"] = 99.0
    regs, lines = compare_reports(mismatch, base, tolerance=2.0)
    assert not regs, regs
    assert any("not comparable" in ln for ln in lines), lines


def test_profile_folded_is_root_first():
    """Collapsed stacks must read root->leaf or flamegraphs merge by
    leaf and group unrelated call paths together."""
    flow.set_seed(10)
    s = Scheduler(virtual=True)
    flow.set_scheduler(s)
    try:
        async def inner_leaf():
            await flow.delay(0.001)

        async def outer_root():
            await inner_leaf()

        s.start_profiler(sample_every=1)
        t = s.spawn(outer_root(), name="root task")
        s.run(until=t, timeout_time=5)
        folded = s.profile_folded()
        line = next(ln for ln in folded.splitlines()
                    if "inner_leaf" in ln and "outer_root" in ln)
        frames = line.rsplit(" ", 1)[0].split(";")
        assert frames[0] == "roottask", frames   # task label, sanitized
        outer_i = next(i for i, f in enumerate(frames)
                       if "outer_root" in f)
        inner_i = next(i for i, f in enumerate(frames)
                       if "inner_leaf" in f)
        assert outer_i < inner_i, frames
    finally:
        flow.set_scheduler(None)


@pytest.mark.slow
def test_simprof_main_exit_codes(tmp_path):
    """The end-to-end gate: a real storm run compared against a
    doctored baseline — tiny baseline wall -> exit 1; huge -> exit 0."""
    import json

    from foundationdb_tpu.tools import simprof

    def run_main(baseline_wall: float) -> int:
        bpath = tmp_path / f"base_{baseline_wall}.json"
        bpath.write_text(json.dumps({
            "round": "r01", "tolerance": 2.0,
            "storms": {"open_loop": {
                "seed": 6262, "sim_seconds": 2.0,
                "wall_seconds": baseline_wall, "sim_per_wall": 1.0,
                "tasks_run": 1, "tasks_per_wall_sec": 1.0,
                "messages_sent": 1}}}))
        return simprof.main([
            "--storm", "open_loop", "--duration", "1.0",
            "--compare", str(bpath),
            "--json", str(tmp_path / "r.json"),
            "--report", str(tmp_path / "r.txt"),
            "--folded", str(tmp_path / "r.folded")])

    assert run_main(baseline_wall=1e-6) == 1     # injected regression
    assert run_main(baseline_wall=1e6) == 0
    # the folded output is flamegraph-shaped: "frames... count"
    folded = (tmp_path / "r.folded").read_text().strip()
    assert folded, "no folded stacks"
    for line in folded.splitlines():
        frames, _, count = line.rpartition(" ")
        assert frames and count.isdigit(), line


def test_baseline_file_committed_and_comparable():
    """SIMPERF_r01.json: present, >= 3 named storms, rows carry the
    comparable slice baseline_row produces."""
    import json
    import os

    path = os.path.join(os.path.dirname(__file__), "..",
                        "SIMPERF_r01.json")
    with open(path) as fh:
        doc = json.load(fh)
    storms = doc["storms"]
    assert len(storms) >= 3, sorted(storms)
    for name, row in storms.items():
        for field in ("seed", "sim_seconds", "wall_seconds",
                      "sim_per_wall", "tasks_run",
                      "tasks_per_wall_sec"):
            assert field in row, (name, field)
        assert row["wall_seconds"] > 0, (name, row)
    fake = {n: dict(r) for n, r in storms.items()}
    regs, _lines = compare_reports(fake, storms,
                                   tolerance=float(doc["tolerance"]))
    assert not regs


def test_baseline_row_slices_report():
    rep = {"seed": 3, "sim_perf": {
        "sim_seconds": 1.0, "wall_seconds": 0.5, "sim_per_wall": 2.0,
        "tasks_run": 10, "tasks_per_wall_sec": 20.0,
        "messages_sent": 7, "top_tasks": [{"task": "x"}]}}
    row = baseline_row(rep)
    assert row == {"seed": 3, "sim_seconds": 1.0, "wall_seconds": 0.5,
                   "sim_per_wall": 2.0, "tasks_run": 10,
                   "tasks_per_wall_sec": 20.0, "messages_sent": 7}
