"""Actor-combinator fuzz: random compositions of the flow combinators
under random cancellation/timing must neither deadlock, leak errors
past their handlers, nor diverge across seed replays (ref:
fdbrpc/actorFuzz.py generating ActorFuzz.actor.cpp control-flow
fuzz)."""

import pytest

import foundationdb_tpu.flow as fl


def _build_random_actor(rng, depth=0):
    """Compose a random actor-coroutine factory out of delay/all_of/
    first_of/timeout/streams/locks/cancellation."""

    choice = rng.random_int(0, 7 if depth < 3 else 3)

    if choice == 0:
        async def leaf():
            await fl.delay(rng.random01() * 0.01)
            return 1
        return leaf
    if choice == 1:
        async def leaf_err():
            await fl.delay(rng.random01() * 0.01)
            try:
                raise fl.error("operation_failed")
            except fl.FdbError:
                return 1   # handled locally
        return leaf_err
    if choice == 2:
        async def stream_actor():
            ps = fl.PromiseStream()

            async def feeder():
                for i in range(3):
                    await fl.delay(rng.random01() * 0.005)
                    ps.send(i)
            t = fl.spawn(feeder())
            total = 0
            for _ in range(3):
                total += await ps.stream.pop()
            await t
            return 1
        return stream_actor
    if choice == 3:
        async def lock_actor():
            lock = fl.FlowLock()

            async def worker():
                await lock.take()
                await fl.delay(rng.random01() * 0.005)
                lock.release()
                return 1
            ts = [fl.spawn(worker()) for _ in range(3)]
            await fl.wait_for_all(ts)
            return 1
        return lock_actor

    subs = [_build_random_actor(rng, depth + 1)
            for _ in range(rng.random_int(1, 4))]
    if choice == 4:
        async def par():
            await fl.all_of([fl.spawn(sub()) for sub in subs])
            return 1
        return par
    if choice == 5:
        async def race():
            futs = [fl.spawn(sub()) for sub in subs]
            await fl.first_of(*futs)
            for f in futs:
                f.cancel()
            return 1
        return race
    if choice == 6:
        async def timed():
            got = await fl.timeout(fl.spawn(subs[0]()),
                                   rng.random01() * 0.02, default=None)
            return 1 if got is not None else 0   # 0 = the timeout fired
        return timed

    async def cancelled():
        t = fl.spawn(subs[0]())
        await fl.delay(rng.random01() * 0.01)
        t.cancel()
        return 1
    return cancelled


@pytest.mark.parametrize("seed", range(15))
def test_fuzzed_actor_trees_complete(seed):
    def one_run(s_):
        fl.set_seed(s_)
        sched = fl.Scheduler(virtual=True)
        fl.set_scheduler(sched)
        try:
            rng = fl.g_random
            results = []

            async def main():
                for _ in range(8):
                    factory = _build_random_actor(rng)
                    results.append(await fl.spawn(factory()))
                return True

            t = sched.spawn(main())
            assert sched.run(until=t, timeout_time=60)
            return (results, sched.tasks_run, sched.now())
        finally:
            fl.set_scheduler(None)

    a = one_run(5000 + seed)
    b = one_run(5000 + seed)
    assert a == b, "seed replay diverged"
