"""Knob registry hygiene + BUGGIFY distortion coverage.

Ref: flow/Knobs.cpp `init(NAME, default)` with `if(randomize && BUGGIFY)`
distortions. Two properties the round-3 verdict asked to make real:
every registered knob is actually READ by code (a dead knob is a lie
about the tunable surface), and the distortion machinery actually
produces distorted values under a buggified seed."""

import pathlib
import re
import subprocess

from foundationdb_tpu import flow
from foundationdb_tpu.flow.knobs import make_server_knobs

REPO = pathlib.Path(__file__).resolve().parent.parent


def test_every_knob_is_consumed():
    k = make_server_knobs()
    unconsumed = []
    for name in k._defaults:
        r = subprocess.run(
            ["grep", "-rl", name.lower(), "foundationdb_tpu/", "bench.py",
             "--include=*.py"], capture_output=True, text=True, cwd=REPO)
        files = [f for f in r.stdout.split()
                 if not f.endswith("flow/knobs.py")]
        if not files:
            unconsumed.append(name)
    assert not unconsumed, f"dead knobs (registered, never read): {unconsumed}"


def test_knob_surface_size():
    k = make_server_knobs()
    assert len(k._defaults) >= 78, len(k._defaults)
    # distortion surface: at least a quarter of the knobs can be
    # BUGGIFY-randomized (control-flow knobs)
    src = (REPO / "foundationdb_tpu/flow/knobs.py").read_text()
    assert len(re.findall(r"lambda", src)) >= 25


def test_buggify_actually_distorts():
    """Across a handful of seeds, SOME knob must come up distorted —
    and with buggify off, none may."""
    try:
        distorted = set()
        for seed in range(12):
            flow.set_seed(seed, buggify_enabled=True)
            k = make_server_knobs(randomize=True)
            for name, default in k._defaults.items():
                if getattr(k, name.lower()) != default:
                    distorted.add(name)
        assert len(distorted) >= 3, distorted

        flow.set_seed(0, buggify_enabled=False)
        k = make_server_knobs(randomize=False)
        for name, default in k._defaults.items():
            assert getattr(k, name.lower()) == default, name
    finally:
        # restore the ambient registry for later tests in this process
        flow.set_seed(0, buggify_enabled=False)
        flow.reset_server_knobs(randomize=False)
