"""Observability integration: kernel profiling counters, the resolver/
tlog latency surfaces in status, the periodic traceCounters rollup, and
the cli `status details` / `metrics` views (ref: flow/Stats.actor.cpp
traceCounters, fdbserver/Status.actor.cpp clusterGetStatus)."""

from foundationdb_tpu import flow
from foundationdb_tpu.client import run_transaction
from foundationdb_tpu.server import SimCluster


def test_kernel_profile_records_occupancy_and_compiles():
    """The TPU backend accounts per-batch pad shapes (real rows vs
    padded slots), and the jitted kernel wrapper accounts compiles and
    fenced execute time per shape bucket."""
    from foundationdb_tpu.models.conflict_set import ResolverTransaction
    from foundationdb_tpu.models.tpu_resolver import TpuConflictSet

    flow.SERVER_KNOBS.set("KERNEL_PROFILE_EVERY", 1)  # fence every call
    try:
        cs = TpuConflictSet()
        for v in range(1, 4):
            txns = [ResolverTransaction(
                v - 1, ((b"k%d" % v, b"k%d\x00" % v),),
                ((b"k%d" % v, b"k%d\x00" % v),))]
            cs.resolve(txns, v * 10, 0)
        ks = cs.kernel_stats()
        assert ks["backend"] == "tpu"
        assert ks["platform"]            # jax backend name, e.g. "cpu"
        assert ks["batches"] == 3
        # 3 real txns over 3 batches of 16 slots each
        assert ks["counts"]["txns"] == 3
        assert ks["counts"]["txn_slots"] == 48
        assert ks["occupancy"]["txn"] == round(3 / 48, 4)
        # compile/execute accounting is PER PROCESS (the lru-cached
        # jitted kernels are shared across instances), kept out of the
        # per-instance stats so status never double-attributes it
        assert "kernels" not in ks
        from foundationdb_tpu.ops.conflict_kernel import g_kernel_counters
        kernels = g_kernel_counters.snapshot()
        # the minimum-size bucket all three batches land in (other
        # tests in this process may have populated other buckets):
        # verdict-only (resolve goes through the noattr variant) with
        # the donated history carry the model wrappers request — via
        # the packed single-buffer feed entry point (ISSUE 14), the
        # default interval dispatch family
        bucket = "resolve_packed[1024c/16t/32r/32w/noattr/don]"
        assert kernels[f"{bucket}.compiles"] >= 1
        assert kernels[f"{bucket}.calls"] >= 3
        # the compile was timed via the block_until_ready fence
        assert kernels[f"{bucket}.compile_us"] > 0
        # with KERNEL_PROFILE_EVERY=1 the post-compile calls are fenced
        assert kernels[f"{bucket}.timed_calls"] >= 1
        assert kernels[f"{bucket}.execute_us"] >= 0
    finally:
        flow.SERVER_KNOBS.set("KERNEL_PROFILE_EVERY", 64)


def test_status_folds_resolver_bands_kernel_and_tlog_bands():
    """The status document carries the full per-stage latency picture:
    proxy grv/commit, resolver resolve bands + kernel occupancy, tlog
    fsync bands, storage read bands — with reservoir percentiles."""
    c = SimCluster(seed=93, conflict_backend="tpu")
    try:
        db = c.client()

        async def main():
            for i in range(8):
                async def body(tr, i=i):
                    await tr.get(b"ob%d" % i)
                    tr.set(b"ob%d" % i, b"x")
                await run_transaction(db, body)
            status = await db.get_status()
            cl = status["cluster"]
            # resolver section: bands + percentiles + kernel profile
            assert cl["resolvers"], cl.keys()
            r = cl["resolvers"][0]
            bands = r["latency_bands"]["resolve"]
            assert bands["total"] >= 8
            assert "p99" in bands and "p50" in bands
            assert list(bands["bands"].values())[-1] == bands["total"]
            kern = r["kernel"]
            assert kern["backend"] == "tpu"
            assert kern["batches"] >= 8
            assert 0 < kern["occupancy"]["txn"] <= 1
            # process-wide compile accounting rides at cluster level
            assert any(k.endswith(".compiles") for k in cl["kernels"])
            # tlog fsync latency appears on the log entries
            lg = cl["logs"][0]
            assert lg["latency_bands"]["commit"]["total"] >= 8
            assert lg["latency_bands"]["commit"]["p50"] >= 0
            # proxy/storage surfaces gained percentiles too
            px = cl["proxies"][0]["latency_bands"]
            assert px["commit"]["p99"] > 0
            reads = [rep["latency_bands"]["read"]
                     for s in cl["storages"]
                     for rep in s["replicas"] if "latency_bands" in rep]
            assert reads and all("p90" in b for b in reads)
            return True

        assert c.run(main(), timeout_time=240)
    finally:
        c.shutdown()


def test_trace_counters_loop_emits_rate_rollups():
    """The CC's traceCounters loop periodically rolls every role's
    CounterCollection into *Metrics TraceEvents carrying values and
    per-interval rates (ref: traceCounters)."""
    c = SimCluster(seed=95)
    try:
        db = c.client()

        async def main():
            for i in range(6):
                async def body(tr, i=i):
                    tr.set(b"tc%d" % i, b"x")
                await run_transaction(db, body)
            await flow.delay(
                4 * flow.SERVER_KNOBS.trace_counters_interval)
            return True

        assert c.run(main(), timeout_time=120)
        for ev_type in ("ProxyMetrics", "TLogMetrics", "ResolverMetrics",
                        "StorageMetrics"):
            assert flow.g_trace.counts.get(ev_type, 0) >= 1, \
                (ev_type, flow.g_trace.counts)
        px = [e for e in flow.g_trace.events
              if e["Type"] == "ProxyMetrics"
              and e.get("transactions_committed", 0) >= 6]
        assert px, "rollup never saw the committed transactions"
        # rates are computed once a previous snapshot exists
        assert any("transactions_committed_per_sec" in e for e in px)
        # the rollup events carry the emitting role instance as ID
        assert all(e["ID"].startswith("proxy-") for e in px)
    finally:
        c.shutdown()


def test_trace_counters_reset_emits_no_negative_rate():
    """A role restarting under the same name zeroes its counters; the
    rollup must re-baseline instead of emitting negative rates."""
    cc = flow.CounterCollection("proxy")
    cc.counter("x").add(100)
    snap = cc.trace(id="p0")
    restarted = flow.CounterCollection("proxy")      # fresh counters
    snap2 = restarted.trace(id="p0", elapsed=1.0, prev=snap)
    ev = [e for e in flow.g_trace.events if e["Type"] == "ProxyMetrics"
          and e["ID"] == "p0"][-1]
    assert "x_per_sec" not in ev                     # reset: no rate
    restarted.counter("x").add(5)
    restarted.trace(id="p0", elapsed=1.0, prev=snap2)
    ev = [e for e in flow.g_trace.events if e["Type"] == "ProxyMetrics"
          and e["ID"] == "p0"][-1]
    assert ev["x_per_sec"] == 5.0                    # re-baselined


def test_resolver_counts_batches_and_latency():
    c = SimCluster(seed=97)
    try:
        db = c.client()

        async def main():
            for i in range(5):
                async def body(tr, i=i):
                    tr.set(b"rb%d" % i, b"x")
                await run_transaction(db, body)
            status = await db.get_status()
            r = status["cluster"]["resolvers"][0]
            assert r["counters"]["batches_resolved"] >= 5
            assert r["counters"]["transactions_resolved"] >= 5
            assert r["kernel"] == {}     # python backend: no device
            return True

        assert c.run(main(), timeout_time=120)
    finally:
        c.shutdown()


def test_cli_status_details_and_metrics_views():
    """`status details` renders the per-stage latency table and the
    kernel profile; `metrics` renders the counter time series."""
    from foundationdb_tpu.tools.cli import Cli

    c = SimCluster(seed=99, conflict_backend="tpu", durable=True)
    try:
        cli = Cli.for_cluster(c)
        for i in range(5):
            assert cli.execute(f"set cd{i} v{i}") == "Committed"
        assert cli.execute("get cd0").endswith("`v0'")
        out = cli.execute("status details")
        assert "Latency (seconds):" in out
        assert "grv" in out and "commit" in out
        assert "resolve" in out and "logfsync" in out and "read" in out
        assert "p99=" in out
        assert "Resolver kernels:" in out
        assert "backend=tpu" in out
        assert "occ[" in out
        assert "Kernel compile/execute (process-wide):" in out
        # the metric sampler needs a few virtual seconds of runway
        async def wait_samples():
            await flow.delay(3.5)
            return True
        assert c.run(wait_samples(), timeout_time=60)
        out = cli.execute("metrics")
        assert "transactions_committed" in out
        # plain status still works
        assert "Epoch" in cli.execute("status")
    finally:
        c.shutdown()
