"""Shard movement: boundary shifts via dual-tagging + AddingShard
backfill + durable ownership flip (ref: MoveKeys.actor.cpp,
storageserver fetchKeys :1862 / AddingShard :149,
DataDistributionTracker split decisions)."""

import pytest

from foundationdb_tpu import flow
from foundationdb_tpu.client import run_transaction
from foundationdb_tpu.server import SimCluster


def _shard_objs(c):
    info = c.cc.dbinfo.get()
    return [c.cc._storage_objs[s.replicas[0].name] for s in info.storages]


def test_dd_moves_boundary_to_balance_load():
    """All data lands in shard 0's half; DD shifts the boundary so
    shard 1 takes part of it; reads/writes stay correct throughout."""
    c = SimCluster(seed=1101, durable=True, n_storage=2)
    try:
        db = c.client()

        async def main():
            # everything below 0x80: shard 0 holds 100%, shard 1 empty
            async def seed_data(tr):
                for i in range(400):
                    tr.set(b"\x10k%04d" % i, b"v%d" % i)
            await run_transaction(db, seed_data)

            # let the DD loop notice and move
            moved = False
            for _ in range(100):
                await flow.delay(0.5)
                info = c.cc.dbinfo.get()
                if info.storages[1].begin < b"\x80":
                    moved = True
                    break
            assert moved, "data distribution never moved the boundary"

            # both shards now hold part of the data; reads see all of it
            async def check(tr):
                got = await tr.get_range(b"", b"\xff")
                assert len(got) == 400
                assert got[0][0] == b"\x10k0000"
                assert got[-1][0] == b"\x10k0399"
            await run_transaction(db, check, max_retries=200)
            objs = _shard_objs(c)
            a = objs[0].approx_rows()
            b_ = objs[1].approx_rows()
            assert a > 0 and b_ > 0, (a, b_)

            # writes keep flowing to the right owners afterwards
            async def more(tr):
                for i in range(400, 450):
                    tr.set(b"\x10k%04d" % i, b"v%d" % i)
            await run_transaction(db, more, max_retries=200)

            async def check2(tr):
                got = await tr.get_range(b"\x10k0390", b"\x10k0450")
                assert len(got) == 60
            await run_transaction(db, check2, max_retries=200)
            return True

        assert c.run(main(), timeout_time=900)
    finally:
        c.shutdown()


def test_moved_data_survives_dst_crash_after_move():
    """The ownership flip is durable on the destination BEFORE the
    source shrinks — killing the destination after a move must bring
    back the moved rows from ITS disk."""
    c = SimCluster(seed=1103, durable=True, n_storage=2)
    try:
        db = c.client()

        async def main():
            async def seed_data(tr):
                for i in range(400):
                    tr.set(b"\x10k%04d" % i, b"v%d" % i)
            await run_transaction(db, seed_data)
            for _ in range(100):
                await flow.delay(0.5)
                if c.cc.dbinfo.get().storages[1].begin < b"\x80":
                    break
            else:
                raise AssertionError("no move happened")
            # give durability a beat, then crash the destination
            await flow.delay(1.0)
            c.kill_role("storage")

            async def check(tr):
                got = await tr.get_range(b"", b"\xff")
                assert len(got) == 400, len(got)
            await run_transaction(db, check, max_retries=300)
            return True

        assert c.run(main(), timeout_time=900)
    finally:
        c.shutdown()


def test_writes_during_move_are_not_lost():
    """A client keeps writing into the moving range while the move is
    in flight; every acknowledged write is readable afterwards."""
    c = SimCluster(seed=1107, durable=True, n_storage=2)
    try:
        db = c.client()
        writer_db = c.client("writer")

        async def main():
            async def seed_data(tr):
                for i in range(300):
                    tr.set(b"\x10k%04d" % i, b"v%d" % i)
            await run_transaction(db, seed_data)

            stop = [False]
            written = []

            async def writer():
                i = 1000
                while not stop[0]:
                    async def body(tr, i=i):
                        tr.set(b"\x10w%04d" % i, b"x")
                    await run_transaction(writer_db, body, max_retries=300)
                    written.append(i)
                    i += 1
                    await flow.delay(0.05)

            wtask = flow.spawn(writer())
            for _ in range(100):
                await flow.delay(0.5)
                if c.cc.dbinfo.get().storages[1].begin < b"\x80":
                    break
            else:
                raise AssertionError("no move happened")
            await flow.delay(1.0)
            stop[0] = True
            await wtask

            async def check(tr):
                got = await tr.get_range(b"\x10w", b"\x10x")
                assert len(got) == len(written), (len(got), len(written))
            await run_transaction(db, check, max_retries=200)
            return True

        assert c.run(main(), timeout_time=900)
    finally:
        c.shutdown()


def _worker_hosting(c, role_name):
    for name, wi in c.cc.workers.items():
        if role_name in wi.worker.roles:
            return name
    return None


def test_exclusion_vacates_storage_replica():
    """Excluding a worker that hosts a storage replica makes DD
    re-home the replica on an included worker — whole-shard fetchKeys:
    snapshot + buffered log replay, pinned TLog records, published team
    swap, old role retired — with data intact and writes continuing
    (ref: exclude + DataDistribution re-replication, MoveKeys)."""
    c = SimCluster(seed=1301, durable=True, n_storage=2, n_workers=6)
    try:
        db = c.client()

        async def main():
            async def seed(tr):
                for i in range(120):
                    tr.set(b"k%04d" % i, b"v%d" % i)
                tr.set(b"\xf0far", b"high")
            await run_transaction(db, seed)

            info = c.cc.dbinfo.get()
            victim_role = info.storages[0].replicas[0].name
            victim_worker = _worker_hosting(c, victim_role)
            assert victim_worker is not None
            await db.exclude(victim_worker)

            # DD must vacate EVERY shard replica off the worker (one
            # re-home per DD tick)
            for _ in range(120):
                await flow.delay(0.5)
                info = c.cc.dbinfo.get()
                hosts = {_worker_hosting(c, r.name)
                         for s in info.storages for r in s.replicas}
                if victim_worker not in hosts and None not in hosts:
                    break
            else:
                raise AssertionError("exclusion never vacated the replica")
            assert victim_role not in c.cc.workers[
                victim_worker].worker.roles, "old role not retired"

            # every row survived the re-home, and writes still flow
            async def check(tr):
                rows = await tr.get_range(b"k", b"l")
                assert len(rows) == 120, len(rows)
                assert await tr.get(b"k0042") == b"v42"
                assert await tr.get(b"\xf0far") == b"high"
                tr.set(b"k9999", b"after-vacate")
            await run_transaction(db, check)

            # the excluded worker can now die with zero data impact
            c.kill_worker(victim_worker)
            await flow.delay(1.0)

            async def check2(tr):
                assert await tr.get(b"k9999") == b"after-vacate"
                assert await tr.get(b"k0000") == b"v0"
            await run_transaction(db, check2)
            return True

        assert c.run(main(), timeout_time=600)
    finally:
        c.shutdown()


def test_exclusion_vacates_one_of_replicated_team():
    """With storage_replicas=2, excluding one team member re-homes only
    that replica; the surviving teammate serves as the fetch source."""
    c = SimCluster(seed=1302, durable=True, n_storage=1,
                   storage_replicas=2, n_workers=6)
    try:
        db = c.client()

        async def main():
            async def seed(tr):
                for i in range(60):
                    tr.set(b"r%03d" % i, b"w%d" % i)
            await run_transaction(db, seed)

            info = c.cc.dbinfo.get()
            victim_role = info.storages[0].replicas[0].name
            keep_role = info.storages[0].replicas[1].name
            victim_worker = _worker_hosting(c, victim_role)
            await db.exclude(victim_worker)

            for _ in range(120):
                await flow.delay(0.5)
                info = c.cc.dbinfo.get()
                names = [r.name for r in info.storages[0].replicas]
                if victim_role not in names:
                    break
            else:
                raise AssertionError("replica never vacated")
            names = [r.name for r in info.storages[0].replicas]
            assert keep_role in names  # the teammate was untouched

            async def check(tr):
                rows = await tr.get_range(b"r", b"s")
                assert len(rows) == 60
            await run_transaction(db, check)
            return True

        assert c.run(main(), timeout_time=600)
    finally:
        c.shutdown()


def test_dd_splits_hot_shard_with_fresh_tag():
    """A shard over the split threshold gets divided: DD mints a fresh
    tag, recruits a new team, dual-tags the transition, and publishes
    an extra shard — with reads/writes correct throughout and the new
    tag live in the proxies' routing (ref: dataDistributionTracker
    shardSplitter + moveKeys to a new team)."""
    from foundationdb_tpu.flow import SERVER_KNOBS

    c = SimCluster(seed=1401, durable=True, n_storage=1, n_workers=5)
    try:
        db = c.client()
        SERVER_KNOBS.init("DD_SHARD_SPLIT_BYTES", 1200)

        async def main():
            async def seed(tr):
                for i in range(300):
                    tr.set(b"s%04d" % i, b"v%d" % i)
            await run_transaction(db, seed)

            for _ in range(120):
                await flow.delay(0.5)
                info = c.cc.dbinfo.get()
                if len(info.storages) >= 2:
                    break
            else:
                raise AssertionError("hot shard never split")
            info = c.cc.dbinfo.get()
            tags = [s.tag for s in info.storages]
            assert len(set(tags)) == len(tags)
            assert max(tags) >= 1          # a fresh tag was minted
            assert info.storages[0].end == info.storages[1].begin

            # all rows survive, routed across the split
            async def check(tr):
                rows = await tr.get_range(b"s", b"t")
                assert len(rows) == 300, len(rows)
                # a write on each side of the new boundary
                tr.set(b"s0000x", b"left")
                tr.set(b"s0299x", b"right")
            await run_transaction(db, check)

            async def check2(tr):
                assert await tr.get(b"s0000x") == b"left"
                assert await tr.get(b"s0299x") == b"right"
            await run_transaction(db, check2)
            return True

        assert c.run(main(), timeout_time=600)
    finally:
        c.shutdown()


def test_dd_merges_cold_split_back():
    """After the data that forced a split is cleared, DD merges the
    extra shard away (never below the configured count), retiring the
    right team and its tag (ref: shardMerger)."""
    from foundationdb_tpu.flow import SERVER_KNOBS

    c = SimCluster(seed=1402, durable=True, n_storage=1, n_workers=5)
    try:
        db = c.client()
        SERVER_KNOBS.init("DD_SHARD_SPLIT_BYTES", 1200)

        async def main():
            async def seed(tr):
                for i in range(300):
                    tr.set(b"m%04d" % i, b"v%d" % i)
            await run_transaction(db, seed)
            for _ in range(120):
                await flow.delay(0.5)
                if len(c.cc.dbinfo.get().storages) >= 2:
                    break
            else:
                raise AssertionError("never split")
            right_names = [r.name
                           for r in c.cc.dbinfo.get().storages[1].replicas]

            # empty the keyspace: both shards go cold -> merge
            async def wipe(tr):
                tr.clear_range(b"", b"\xff")
                tr.set(b"survivor", b"1")
            await run_transaction(db, wipe)
            for _ in range(120):
                await flow.delay(0.5)
                if len(c.cc.dbinfo.get().storages) == 1:
                    break
            else:
                raise AssertionError("cold shards never merged")

            # the right team retired: roles gone from every worker
            for name in right_names:
                assert all(name not in wi.worker.roles
                           for wi in c.cc.workers.values()), name

            async def check(tr):
                assert await tr.get(b"survivor") == b"1"
                # no resurrection: the left team's kv held the m-rows
                # from before the split; the merge install must not let
                # them shine through under the (cleared) snapshot
                assert await tr.get_range(b"m", b"n") == []
                tr.set(b"post-merge", b"2")
            await run_transaction(db, check)
            return True

        assert c.run(main(), timeout_time=600)
    finally:
        c.shutdown()
