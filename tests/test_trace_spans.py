"""Trace plumbing: collector flushing, TraceEvent severity floor and
context-manager form, TraceBatch spill ordering, parented commit spans,
and the latency band/sample primitives (ref: flow/Trace.h TraceBatch,
flow/Tracing.h Span, fdbserver/LatencyBandConfig.cpp)."""

import json
import os

from foundationdb_tpu import flow
from foundationdb_tpu.flow import trace as trace_mod
from foundationdb_tpu.flow.latency import LatencyBands, LatencySample


def test_trace_collector_flushes_per_emit(tmp_path):
    """File output is line-buffered: every emitted event reaches the
    file without an explicit close (the old handle leaked on interpreter
    exit and buffered writes were lost)."""
    path = str(tmp_path / "trace.json")
    tc = trace_mod.TraceCollector(path=path, keep_in_memory=10)
    tc.emit({"Type": "A", "Severity": 10, "Time": 0.0, "ID": ""})
    tc.emit({"Type": "B", "Severity": 10, "Time": 1.0, "ID": ""})
    tc.flush()
    with open(path) as fh:
        rows = [json.loads(l) for l in fh.read().splitlines()]
    assert [r["Type"] for r in rows] == ["A", "B"]
    # close is idempotent and final
    tc.close()
    tc.close()
    assert tc._fh is None


def test_trace_json_escape_fuzz(tmp_path):
    """Every event line must be valid JSON no matter what detail()
    was handed: raw non-UTF8 bytes (keys!), newlines, quotes,
    backslashes, control chars, lone surrogates, foreign objects.
    Fuzzes random byte payloads through a file-backed collector and
    json.loads's every line back (ref: the JsonTraceLogFormatter
    escaping Trace.cpp relies on)."""
    path = str(tmp_path / "fuzz.json")
    rng = flow.DeterministicRandom(4242)
    payloads = [rng.random_bytes(rng.random_int(0, 64))
                for _ in range(200)]
    payloads += [b"\xff\xfe\x00\n\"\\'", b"\n\r\t", b'"}{',
                 bytes(range(256))]
    with trace_mod.TraceCollector(path=path, keep_in_memory=0) as tc:
        old, trace_mod.g_trace = trace_mod.g_trace, tc
        try:
            for i, p in enumerate(payloads):
                trace_mod.TraceEvent("Fuzz", str(i)).detail(
                    Key=p, Note='line\nbreak "quoted" \\ back',
                    Surrogate="bad\udc80str", Obj=object()).log()
        finally:
            trace_mod.g_trace = old
    with open(path, "rb") as fh:
        lines = fh.read().splitlines()
    assert len(lines) == len(payloads)
    for line in lines:
        row = json.loads(line)        # raises on any malformed line
        assert row["Type"] == "Fuzz"
        assert isinstance(row["Key"], str)
        assert row["Note"] == 'line\nbreak "quoted" \\ back'
    # bytes render with the cli's \xNN convention (printable ASCII
    # stays readable)
    row = json.loads(lines[-1])
    assert "\\x00" in row["Key"] and "\\xff" in row["Key"]
    assert "A" in row["Key"]


def test_trace_collector_context_manager(tmp_path):
    path = str(tmp_path / "t.json")
    with trace_mod.TraceCollector(path=path) as tc:
        tc.emit({"Type": "X", "Severity": 10, "Time": 0.0, "ID": ""})
    assert tc._fh is None
    assert os.path.getsize(path) > 0


def test_trace_event_context_manager_logs_once():
    n0 = flow.g_trace.counts.get("CtxEvent", 0)
    with flow.TraceEvent("CtxEvent", "t1") as ev:
        ev.detail(K=1)
    assert flow.g_trace.counts.get("CtxEvent", 0) == n0 + 1
    # a second .log() on the same event is a no-op
    ev.log()
    assert flow.g_trace.counts.get("CtxEvent", 0) == n0 + 1


def test_trace_event_context_manager_records_error():
    try:
        with flow.TraceEvent("CtxFail", "t2"):
            raise RuntimeError("boom")
    except RuntimeError:
        pass
    ev = [e for e in flow.g_trace.events if e["Type"] == "CtxFail"][-1]
    assert "boom" in ev["Error"]


def test_trace_severity_floor_drops_cheaply():
    """trace_severity_min filters events at construction: a suppressed
    event allocates no dict and never reaches the collector."""
    flow.SERVER_KNOBS.set("TRACE_SEVERITY_MIN", flow.trace.SevInfo)
    try:
        before = dict(flow.g_trace.counts)
        ev = flow.TraceEvent("HotLoopDebug", "x",
                             severity=flow.trace.SevDebug)
        assert ev._ev is None          # nothing materialized
        ev.detail(Huge="payload").log()
        assert flow.g_trace.counts.get("HotLoopDebug", 0) == \
            before.get("HotLoopDebug", 0)
        # at-or-above the floor still logs
        flow.TraceEvent("StillLogged", "x",
                        severity=flow.trace.SevInfo).log()
        assert flow.g_trace.counts.get("StillLogged", 0) == \
            before.get("StillLogged", 0) + 1
    finally:
        flow.SERVER_KNOBS.set("TRACE_SEVERITY_MIN", 0)


def test_trace_batch_spill_oldest_half_in_order():
    """Events past MAX_BUFFERED spill OLDEST-HALF-FIRST into the trace
    stream (in-flight stitches keep recent legs queryable), and the
    spilled TraceEvents preserve insertion order."""
    tb = trace_mod.TraceBatch()
    n0 = flow.g_trace.counts.get("SpillDebug", 0)
    total = tb.MAX_BUFFERED + 1
    for i in range(total):
        tb.add_event("SpillDebug", i, f"loc-{i}")
    spilled = tb.MAX_BUFFERED // 2
    assert flow.g_trace.counts.get("SpillDebug", 0) == n0 + spilled
    # the newest events are still queryable in memory...
    assert tb.events(total - 1) == [(0.0, "SpillDebug",
                                     f"loc-{total - 1}")]
    # ...the oldest are not (they spilled)
    assert tb.events(0) == []
    # and the spilled ids are exactly the oldest half, in order
    ids = [e["ID"] for e in flow.g_trace.events
           if e["Type"] == "SpillDebug"][-spilled:]
    assert ids == [str(i) for i in range(spilled)]


def test_trace_batch_same_tick_stitches_in_insertion_order():
    """Same-virtual-tick events must stitch causally (by _seq), not
    alphabetically by location: 'Z' before 'A' if Z happened first."""
    tb = trace_mod.TraceBatch()
    tb.add_event("CommitDebug", 7, "Zeta.first")
    tb.add_event("CommitDebug", 7, "Alpha.second")
    tb.add_event("CommitDebug", 7, "Mid.third")
    locs = [loc for _t, _et, loc in tb.events(7)]
    assert locs == ["Zeta.first", "Alpha.second", "Mid.third"]


def test_span_parenting_and_chain_reassembly():
    """Nested spans auto-parent on the innermost open span of the same
    debug id; span_chain rebuilds the tree with depths."""
    tb = trace_mod.TraceBatch()
    root = tb.begin_span(42, "client")
    child = tb.begin_span(42, "proxy")
    leaf = tb.begin_span(42, "resolver")
    leaf.finish()
    with tb.begin_span(42, "tlog"):       # sibling of resolver
        pass
    child.finish()
    root.finish()
    chain = tb.span_chain(42)
    assert [(s["location"], s["parent"], s["depth"]) for s in chain] == [
        ("client", None, 0),
        ("proxy", "client", 1),
        ("resolver", "proxy", 2),
        ("tlog", "proxy", 2),
    ]
    # another debug id is untouched
    assert tb.span_chain(43) == []
    tb.clear()
    assert tb.span_chain(42) == []


def test_concurrent_same_location_spans_are_siblings():
    """Two tlogs fsync the same sampled commit concurrently: leg B
    begins while leg A's identical-location span is still open. They
    must come out as SIBLINGS under the proxy span, not nested."""
    tb = trace_mod.TraceBatch()
    root = tb.begin_span(8, "proxy")
    a = tb.begin_span(8, "tlog")
    b = tb.begin_span(8, "tlog")       # a still open
    b.finish()
    a.finish()
    root.finish()
    chain = tb.span_chain(8)
    assert [(s["location"], s["parent"], s["depth"]) for s in chain] == [
        ("proxy", None, 0),
        ("tlog", "proxy", 1),
        ("tlog", "proxy", 1),
    ]


def test_latency_bands_bucket_known_distribution():
    lb = LatencyBands("t", bands=(0.001, 0.01, 0.1))
    for s in (0.0005, 0.0009, 0.005, 0.05, 0.5):
        lb.record(s)
    snap = lb.snapshot()
    assert snap["total"] == 5
    assert snap["bands"] == {"<=0.001s": 2, "<=0.01s": 3, "<=0.1s": 4}
    assert snap["max_seconds"] == 0.5
    # an exact-threshold latency counts inside its band (<=)
    lb.record(0.01)
    assert lb.snapshot()["bands"]["<=0.01s"] == 4
    # reconfiguring the thresholds resets the histogram
    lb.add_threshold(0.025)
    snap2 = lb.snapshot()
    assert snap2["total"] == 0
    assert "<=0.025s" in snap2["bands"]


def test_latency_sample_percentiles():
    ls = LatencySample("t", size=100)
    for i in range(1, 101):                 # 1ms .. 100ms
        ls.record(i / 1000.0)
    snap = ls.snapshot()
    assert snap["count"] == 100
    assert abs(snap["p50"] - 0.051) < 0.005
    assert abs(snap["p90"] - 0.091) < 0.005
    assert snap["max_seconds"] == 0.1
    # the reservoir slides: after 100 more fast samples the old tail
    # is forgotten but count/max persist
    for _ in range(100):
        ls.record(0.001)
    snap = ls.snapshot()
    assert snap["count"] == 200
    assert snap["p99"] == 0.001
    assert snap["max_seconds"] == 0.1


def test_simulated_commit_emits_full_span_chain():
    """A sampled commit through the simulated cluster produces the
    complete client -> proxy -> {resolver, tlog} span tree with
    monotonic virtual-clock timestamps (the tentpole acceptance
    criterion), alongside the classic commit-debug stations."""
    from foundationdb_tpu.server import SimCluster

    c = SimCluster(seed=91)
    try:
        db = c.client()

        async def main():
            tr = db.create_transaction()
            tr.set_option("debug_transaction_identifier", 5150)
            await tr.get(b"span-k")
            tr.set(b"span-k", b"v")
            await tr.commit()
            return True

        assert c.run(main(), timeout_time=120)
        chain = flow.g_trace_batch.span_chain(5150)
        by_loc = {s["location"]: s for s in chain}
        assert set(by_loc) == {"NativeAPI.commit",
                               "MasterProxyServer.commitBatch",
                               "Resolver.resolveBatch",
                               "TLog.tLogCommit"}
        root = by_loc["NativeAPI.commit"]
        proxy = by_loc["MasterProxyServer.commitBatch"]
        res = by_loc["Resolver.resolveBatch"]
        tlog = by_loc["TLog.tLogCommit"]
        assert root["parent"] is None and root["depth"] == 0
        assert proxy["parent"] == "NativeAPI.commit" and proxy["depth"] == 1
        assert res["parent"] == "MasterProxyServer.commitBatch"
        assert tlog["parent"] == "MasterProxyServer.commitBatch"
        assert res["depth"] == tlog["depth"] == 2
        # virtual-clock sanity: begins are causally ordered and every
        # span closed at/after it opened, inside its parent's extent
        assert root["begin"] <= proxy["begin"] <= res["begin"] \
            <= tlog["begin"]
        for s in chain:
            assert s["end"] is not None and s["end"] >= s["begin"]
        assert proxy["end"] <= root["end"]
        assert res["end"] <= proxy["end"] and tlog["end"] <= proxy["end"]
        # resolution happens before the log fsync completes
        assert res["end"] <= tlog["end"]
        # the sampled read hit the storage stations too
        locs = [l for _t, _et, l in flow.g_trace_batch.events(5150)]
        assert "NativeAPI.getValue.Before" in locs
        assert "StorageServer.getValue.DoRead" in locs
        assert "StorageServer.getValue.AfterRead" in locs
        # an unsampled commit opens no spans
        assert flow.g_trace_batch.span_chain(None) == []
    finally:
        flow.g_trace_batch.clear()
        c.shutdown()
