"""TEST() coverage sites + the run-loop slow-task profiler.

Ref: flow/UnitTest.h TEST(intro) + the coverage tool's "every annotated
rare path must fire in simulation" discipline; flow/Profiler.actor.cpp
and Net2 slow-task sampling surfaced through status.
"""

import pytest

from foundationdb_tpu import flow
from foundationdb_tpu.client import run_transaction
from foundationdb_tpu.flow import coverage as cov
from foundationdb_tpu.server import SimCluster


def test_coverage_sites_fire_in_simulation():
    """Drive the scenarios behind the annotated rare paths and assert
    each site fired — the in-suite CoverageTool check."""
    cov.reset_hits()

    # -- conflict + retry sites -----------------------------------------
    c = SimCluster(seed=41, durable=True)
    try:
        db = c.client()

        async def main():
            tr1 = db.create_transaction()
            tr2 = db.create_transaction()
            await tr1.get(b"cov")
            await tr2.get(b"cov")
            tr1.set(b"cov", b"1")
            await tr1.commit()
            tr2.set(b"cov", b"2")
            with pytest.raises(flow.FdbError) as ei:
                await tr2.commit()
            await tr2.on_error(ei.value)     # client.retry.conflict

            # -- stale picture + epoch sites: kill the tlog mid-stream
            c.kill_role("tlog")
            async def w(tr):
                tr.set(b"cov2", b"x")
            await run_transaction(db, w, max_retries=300)
            return True

        assert c.run(main(), timeout_time=300)
    finally:
        c.shutdown()

    # -- torn-tail + locked-tlog sites ----------------------------------
    from foundationdb_tpu.rpc import SimNetwork
    from foundationdb_tpu.server.diskqueue import DiskQueue
    from foundationdb_tpu.server.tlog import TLog
    from foundationdb_tpu.server.types import (TLogCommitRequest,
                                               TLogLockRequest)
    flow.set_seed(7)
    s = flow.Scheduler(virtual=True)
    flow.set_scheduler(s)
    try:
        net = SimNetwork(s, flow.g_random)
        disk = net.disk("m1")
        tl_proc = net.new_process("tl", machine="m1")
        cl_proc = net.new_process("cl", machine="m2")
        tlog = TLog(tl_proc)
        tlog.start()

        async def locked_commit():
            await tlog.locks.ref().get_reply(TLogLockRequest(), cl_proc)
            with pytest.raises(flow.FdbError) as ei:
                await tlog.commits.ref().get_reply(
                    TLogCommitRequest(0, 1, (), 1), cl_proc)
            assert ei.value.name == "tlog_stopped"
            return True

        t = s.spawn(locked_commit())
        assert s.run(until=t, timeout_time=60)

        async def torn():
            dq = DiskQueue(disk, "q")
            await dq.recover()
            for i in range(8):
                await dq.push(b"r%d" % i)
            await dq.commit()
            # corrupt the tail: flip a byte in a live file's durable
            # image (bit-rot — the checksum must catch it)
            for name, f in disk.files.items():
                if name.startswith("q.dq") and len(f._durable) > 40:
                    f._durable[-3] ^= 0xFF
            dq2 = DiskQueue(disk, "q")
            await dq2.recover()              # diskqueue.torn_tail_dropped
            return True

        t = s.spawn(torn())
        assert s.run(until=t, timeout_time=60)
    finally:
        flow.set_scheduler(None)

    rep = cov.report()
    for site in ("proxy.commit.conflict", "client.retry.conflict",
                 "client.refresh_stale_picture", "cc.epoch_failed",
                 "tlog.commit.stopped", "diskqueue.torn_tail_dropped"):
        assert cov.hits(site) > 0, (site, rep)
    # declared-but-unhit sites are visible to the report (the coverage
    # tool's gap list) — they exist but this run didn't take them
    assert "unhit" in rep


def test_slow_task_profiler_samples_hogs():
    """A step that blocks past SLOW_TASK_THRESHOLD emits a SlowTask
    TraceEvent carrying the task's label and elapsed µs, and rolls up
    into the status document's run_loop section (count + threshold)
    and the exporter."""
    import time

    from foundationdb_tpu.tools.exporter import (parse_prometheus,
                                                 render_prometheus)

    c = SimCluster(seed=42)
    try:
        c.sched.slow_task_threshold = 0.01   # pin over the knob
        db = c.client()

        async def main():
            async def hog():
                time.sleep(0.03)   # a blocking step (the anti-pattern)
            await flow.spawn(hog(), name="testHog")
            status = await db.get_status()
            rl = status["cluster"]["run_loop"]
            assert rl["tasks_run"] > 0
            assert rl["busy_seconds"] > 0
            assert rl["slow_task_count"] >= 1, rl
            assert rl["slow_task_threshold"] == 0.01, rl
            assert any(s["seconds"] >= 0.01 for s in rl["slow_tasks"]), rl
            assert flow.g_trace.counts.get("SlowTask", 0) > 0
            evs = [e for e in flow.g_trace.events
                   if e["Type"] == "SlowTask" and e["TaskName"] == "testHog"]
            assert evs and evs[-1]["ElapsedUs"] >= 10_000, evs
            samples = parse_prometheus(render_prometheus(status))
            by_name = {n: v for n, l, v in samples if not l}
            assert by_name["fdbtpu_run_loop_slow_tasks"] >= 1
            assert by_name[
                "fdbtpu_run_loop_slow_task_threshold_seconds"] == 0.01
            assert any(n == "fdbtpu_run_loop_slow_task_seconds"
                       and l.get("task") == "testHog"
                       for n, l, v in samples)
            return True

        assert c.run(main(), timeout_time=120)
    finally:
        c.shutdown()


def test_slow_task_threshold_follows_knob():
    """Unpinned, the scheduler reads SLOW_TASK_THRESHOLD live; raising
    it suppresses SlowTask sampling for the same hog."""
    import time

    c = SimCluster(seed=43)
    try:
        assert c.sched.slow_task_threshold is None   # knob-following
        old = flow.SERVER_KNOBS.slow_task_threshold
        db = c.client()

        async def main():
            flow.SERVER_KNOBS.set("slow_task_threshold", 0.01)

            async def hog():
                time.sleep(0.02)
            await flow.spawn(hog(), name="knobHog")
            count = c.sched.slow_task_count
            assert count >= 1
            # a sky-high threshold stops further sampling
            flow.SERVER_KNOBS.set("slow_task_threshold", 10.0)
            await flow.spawn(hog(), name="knobHog2")
            assert c.sched.slow_task_count == count
            st = await db.get_status()
            assert st["cluster"]["run_loop"]["slow_task_threshold"] == 10.0
            return True

        assert c.run(main(), timeout_time=120)
        flow.SERVER_KNOBS.set("slow_task_threshold", old)
    finally:
        c.shutdown()


def test_sampling_profiler_captures_actor_stacks():
    """The on-demand sampling profiler (ref: flow/Profiler.actor.cpp's
    SIGPROF sampler, expressed cooperatively): every Nth task step
    records the stepped task's coroutine suspension stack; the report
    ranks (task, stack) pairs by samples."""
    from foundationdb_tpu.client import run_transaction
    from foundationdb_tpu.server import SimCluster

    c = SimCluster(seed=71)
    try:
        db = c.client()

        async def main():
            flow.g().start_profiler(sample_every=2)
            for i in range(30):
                async def body(tr, i=i):
                    tr.set(b"p%d" % i, b"x")
                await run_transaction(db, body)
            report = flow.g().stop_profiler()
            assert report, "no samples"
            total = sum(e["samples"] for e in report)
            assert total >= 20, total
            # stacks name real code locations, not just task labels
            assert any(".py:" in e["stack"] for e in report), report[:3]
            # role actors appear among the sampled tasks
            names = " ".join(e["task"] for e in report)
            assert "batcher" in names or "updateStorage" in names or \
                "resolve" in names, names
            # off after stop: no further accumulation
            before = len(flow.g()._profile_samples)
            async def body2(tr):
                tr.set(b"after", b"x")
            await run_transaction(db, body2)
            assert len(flow.g()._profile_samples) == before
            return True

        assert c.run(main(), timeout_time=120)
    finally:
        c.shutdown()
