"""Prometheus exporter: status -> text exposition format -> parse.

Ref: the fdb-exporter pattern (scrape `status json`, re-emit as
Prometheus metrics); here the render is first-party and must stay
parseable — the parse_prometheus round trip is the same well-formedness
gate the CI smoke runs against a live cluster."""

import urllib.request

import pytest

from foundationdb_tpu.tools.exporter import (ExporterServer,
                                             parse_prometheus,
                                             render_prometheus)


def _canned_status():
    return {"cluster": {
        "epoch": 3,
        "recovery_state": "fully_recovered",
        "qos": {
            "transactions_per_second_limit": 1000.0,
            "batch_transactions_per_second_limit": 500.0,
            "limiting_reason": "storage_queue",
            "inputs": {"worst_storage_queue_bytes": 2048.5,
                       "worst_tlog_queue_bytes": 100.0,
                       "worst_durability_lag_versions": 0,
                       "pipeline_occupancy": 0.75,
                       "pipeline_forced_drain_rate": 1.25,
                       "dead_replicas": 0},
            "roles": {
                "storage": {"storage-0-r0": {
                    "queue_bytes": 2048.5, "durability_lag_versions": 3.0,
                    "read_rate": 12.5, "mutation_rate": 40.0,
                    "sampled_at": 9.5}},
                "proxy": {"proxy-e3-0": {
                    "grv_queue_depth": 1.5, "commit_batch_occupancy": 4.0,
                    "resolve_in_flight": 2, "grv_rate": 80.0,
                    "commit_rate": 75.0, "tps_budget": 1000.0,
                    "sampled_at": 9.5}}},
            "tags": [{"tag": "776562", "busyness": 3.5, "started": 10,
                      "committed": 8, "conflicted": 2}],
            "priorities": {
                "batch": {"started": 3, "committed": 2, "conflicted": 0},
                "default": {"started": 9, "committed": 8,
                            "conflicted": 1},
                "immediate": {"started": 0, "committed": 0,
                              "conflicted": 0}}},
        "proxies": [{
            "name": "proxy-e3-0",
            "counters": {"transactions_committed": 42,
                         "transactions_conflicted": 7},
            "latency_bands": {"commit": {
                "total": 49, "max_seconds": 0.2,
                "p50": 0.01, "p90": 0.05, "p99": 0.1,
                "bands": {"<=0.005s": 10, "<=0.1s": 45}}}}],
        "resolvers": [{
            "name": "resolver-e3-0",
            "counters": {"batches_resolved": 12},
            "latency_bands": {"resolve": {"total": 12, "bands": {}}},
            "hot_spots": [],
            "kernel": {"backend": "tpu", "platform": "cpu",
                       "capacity": 1024, "state_rows": 17, "batches": 12,
                       "occupancy": {"txn": 0.5, "read": None}}}],
        "logs": [{"store": "tlog-e3-0", "queue_length": 2,
                  "counters": {"commits": 30},
                  "latency_bands": {"commit": {"total": 30, "bands": {}}}}],
        "storages": [
            {"tag": 0, "replicas": [
                {"name": "storage-0-r0", "counters": {"get_queries": 5},
                 "latency_bands": {"read": {"total": 5, "bands": {}}}}]},
            # same server under a second shard: must not double-emit
            {"tag": 1, "replicas": [
                {"name": "storage-0-r0", "counters": {"get_queries": 5},
                 "latency_bands": {"read": {"total": 5, "bands": {}}}}]}],
        "kernels": {"resolve[1024c/16t/32r/32w].compiles": 1,
                    "resolve[1024c/16t/32r/32w].calls": 12},
        "latency_probe": {"transaction_start_seconds": 0.001,
                          "read_seconds": 0.002, "commit_seconds": 0.01,
                          "rounds": 4, "probed_at": 12.0,
                          "bands": {"grv": {"total": 4, "bands": {}}}},
        "conflict_hot_spots": [
            {"begin": "686f74", "end": "686f7400", "score": 2.5,
             "total": 6}],
        "messages": [{"name": "high_conflict_rate", "severity": 30,
                      "description": "x"}],
        "run_loop": {"tasks_run": 1000, "busy_seconds": 0.5},
    }}


def test_render_is_parseable_and_covers_roles():
    text = render_prometheus(_canned_status())
    samples = parse_prometheus(text)
    names = {n for n, _, _ in samples}
    for need in ("fdbtpu_cluster_epoch", "fdbtpu_role_counter",
                 "fdbtpu_request_latency_seconds_bucket",
                 "fdbtpu_request_latency_seconds_count",
                 "fdbtpu_kernel_profile", "fdbtpu_latency_probe_seconds",
                 "fdbtpu_conflict_hot_spot_score",
                 "fdbtpu_health_message", "fdbtpu_resolver_state_rows"):
        assert need in names, (need, sorted(names))
    # one sample per (name, labelset): duplicates are a scrape error
    keys = [(n, tuple(sorted(l.items()))) for n, l, _ in samples]
    assert len(keys) == len(set(keys))
    # roles from every section are labeled
    roles = {l.get("role") for n, l, _ in samples
             if n == "fdbtpu_role_counter"}
    assert {"proxy-e3-0", "resolver-e3-0", "tlog-e3-0",
            "storage-0-r0"} <= roles


def test_qos_and_tag_families_round_trip():
    """The PR 6 QoS plane through the parser round trip: budgets, the
    one-hot limiting-reason enum, RkUpdate input signals, the per-role
    QosSample surface, and the tag/priority traffic families — every
    value must survive render -> parse bit-exactly, with no duplicate
    (name, labelset) pairs (already pinned suite-wide above)."""
    qos = _canned_status()["cluster"]["qos"]
    samples = parse_prometheus(render_prometheus(_canned_status()))
    names = {n for n, _, _ in samples}
    for need in ("fdbtpu_qos_transactions_per_second_limit",
                 "fdbtpu_qos_batch_transactions_per_second_limit",
                 "fdbtpu_qos_limiting_reason", "fdbtpu_qos_input",
                 "fdbtpu_qos_signal", "fdbtpu_tag_busyness",
                 "fdbtpu_tag_transactions",
                 "fdbtpu_qos_priority_transactions"):
        assert need in names, (need, sorted(names))
    # limiting reason is a one-hot enum over the full vocabulary
    from foundationdb_tpu.server.ratekeeper import LIMIT_REASONS
    hot = {l["reason"]: v for n, l, v in samples
           if n == "fdbtpu_qos_limiting_reason"}
    assert set(hot) == set(LIMIT_REASONS)
    assert hot["storage_queue"] == 1 and sum(hot.values()) == 1
    # every decision input rides with its value intact
    inputs = {l["input"]: v for n, l, v in samples
              if n == "fdbtpu_qos_input"}
    assert inputs == qos["inputs"]
    # per-role signals keep (kind, role, signal) labels; sampled_at is
    # bookkeeping, not a metric
    sig = {(l["kind"], l["role"], l["signal"]): v
           for n, l, v in samples if n == "fdbtpu_qos_signal"}
    assert sig[("storage", "storage-0-r0", "queue_bytes")] == 2048.5
    assert sig[("proxy", "proxy-e3-0", "commit_batch_occupancy")] == 4.0
    assert not any(s == "sampled_at" for _k, _r, s in sig)
    assert len(sig) == 10    # 4 storage + 6 proxy signals
    # tag family: busyness gauge + one counter per outcome
    (busy,) = [v for n, l, v in samples
               if n == "fdbtpu_tag_busyness" and l["tag"] == "776562"]
    assert busy == 3.5
    tag_counts = {l["outcome"]: v for n, l, v in samples
                  if n == "fdbtpu_tag_transactions"
                  and l["tag"] == "776562"}
    assert tag_counts == {"started": 10, "committed": 8, "conflicted": 2}
    prio = {(l["priority"], l["outcome"]): v for n, l, v in samples
            if n == "fdbtpu_qos_priority_transactions"}
    assert prio[("default", "committed")] == 8
    assert prio[("immediate", "started")] == 0   # zeros still emitted


def test_histogram_buckets_are_cumulative_with_inf():
    text = render_prometheus(_canned_status())
    buckets = [(l["le"], v) for n, l, v in parse_prometheus(text)
               if n == "fdbtpu_request_latency_seconds_bucket"
               and l.get("role") == "proxy-e3-0"]
    by_le = dict(buckets)
    assert by_le["+Inf"] == 49
    assert by_le["0.005"] == 10 and by_le["0.1"] == 45


def test_histogram_well_formed_round_trip():
    """The full Prometheus histogram contract, verified through a
    parser round trip over a LIVE LatencyBands recording: buckets
    cumulative and ordered by le, a final +Inf bucket equal to _count,
    and a _sum sample; the raw per-band counts additionally ride the
    *_band series."""
    from foundationdb_tpu.flow.latency import RequestLatency
    rl = RequestLatency("commit")
    for s in (0.0001, 0.002, 0.004, 0.03, 0.2, 2.0):   # one past 1.0s
        rl.record(s)
    st = {"cluster": {"epoch": 1, "recovery_state": "fully_recovered",
                      "proxies": [{"name": "p0", "counters": {},
                                   "latency_bands": {
                                       "commit": rl.snapshot()}}]}}
    samples = parse_prometheus(render_prometheus(st))
    buckets = [(float("inf") if l["le"] == "+Inf" else float(l["le"]), v)
               for n, l, v in samples
               if n == "fdbtpu_request_latency_seconds_bucket"]
    assert buckets == sorted(buckets), buckets
    counts = [v for _le, v in buckets]
    assert counts == sorted(counts), "buckets must be cumulative"
    assert buckets[-1][0] == float("inf")
    (count,) = [v for n, l, v in samples
                if n == "fdbtpu_request_latency_seconds_count"]
    assert buckets[-1][1] == count == 6
    (total,) = [v for n, l, v in samples
                if n == "fdbtpu_request_latency_seconds_sum"]
    assert abs(total - 2.2361) < 1e-6, total
    # the 2.0s sample fits no finite band: +Inf must exceed the last
    # finite bucket
    assert buckets[-1][1] > buckets[-2][1]
    # per-band series preserved beside the histogram
    band = {l["band"]: v for n, l, v in samples
            if n == "fdbtpu_request_latency_band"}
    assert band["0.005"] == 3 and band["1"] == 5, band


def test_value_escaping():
    st = _canned_status()
    st["cluster"]["proxies"][0]["name"] = 'weird"role\\name'
    text = render_prometheus(st)
    samples = parse_prometheus(text)
    assert any(l.get("role", "").startswith("weird")
               for _n, l, _v in samples)


def test_duplicate_health_messages_aggregate():
    """Two conditions of the same kind must not emit identical label
    sets (a real Prometheus server rejects duplicate samples — exactly
    when the cluster is unhealthy)."""
    st = _canned_status()
    st["cluster"]["messages"] = [
        {"name": "storage_behind_tlog", "severity": 30, "storage": "a"},
        {"name": "storage_behind_tlog", "severity": 30, "storage": "b"},
        {"name": "saturated_resolver", "severity": 30}]
    samples = parse_prometheus(render_prometheus(st))
    keys = [(n, tuple(sorted(l.items()))) for n, l, _ in samples]
    assert len(keys) == len(set(keys))
    vals = {l["name"]: v for n, l, v in samples
            if n == "fdbtpu_health_message"}
    assert vals == {"storage_behind_tlog": 2, "saturated_resolver": 1}


def test_parse_rejects_malformed():
    with pytest.raises(ValueError):
        parse_prometheus('bad_metric{le=0.1} 4')   # unquoted label
    with pytest.raises(ValueError):
        parse_prometheus('name with spaces 4')


def test_http_server_serves_metrics():
    text = render_prometheus(_canned_status())
    srv = ExporterServer(lambda: text)
    srv.start()
    try:
        url = f"http://127.0.0.1:{srv.port}/metrics"
        resp = urllib.request.urlopen(url, timeout=10)
        assert resp.headers["Content-Type"].startswith("text/plain")
        assert resp.read().decode() == text
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/other", timeout=10)
    finally:
        srv.close()


def test_http_server_survives_scrape_errors():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("status unavailable")
        return "ok_metric 1\n"

    srv = ExporterServer(flaky)
    srv.start()
    try:
        url = f"http://127.0.0.1:{srv.port}/metrics"
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(url, timeout=10)
        assert urllib.request.urlopen(
            url, timeout=10).read() == b"ok_metric 1\n"
    finally:
        srv.close()


# -- ISSUE 18: escaping + version-skew federation --------------------------

def test_hostile_label_values_round_trip():
    """Backslashes, quotes, newlines, commas and braces in a label
    value must survive render -> parse EXACTLY: the exposition format
    escapes only \\, " and newline, and commas/braces are legal raw
    inside a quoted value — the parser must scan the quoted string,
    not split the body on commas."""
    hostile = 'a\\b"c\nd,e{f}=g'
    st = _canned_status()
    st["cluster"]["proxies"][0]["name"] = hostile
    samples = parse_prometheus(render_prometheus(st))
    roles = {l["role"] for _n, l, _v in samples if "role" in l}
    assert hostile in roles, roles


def test_parse_rejects_bad_escapes_and_unterminated():
    with pytest.raises(ValueError):
        parse_prometheus('m{a="bad\\q"} 1')      # unknown escape
    with pytest.raises(ValueError):
        parse_prometheus('m{a="dangling\\')
    with pytest.raises(ValueError):
        parse_prometheus('m{a="unterminated} 1')


def test_federate_tolerates_version_skew():
    """A worker doc from an OLDER build lacks the newer sections
    (process_metrics, flightrec, even counters): federation must fill
    defaults — no KeyError anywhere downstream — and the filled
    defaults must not alias between docs."""
    from foundationdb_tpu.tools.exporter import (federate_status,
                                                 normalize_proc_doc,
                                                 render_federated)
    old_worker = {"process": "client-0:100", "role": "client-0",
                  "pid": 100}
    new_worker = {"process": "client-1:200", "role": "client-1",
                  "pid": 200, "up": 1, "counters": {"committed": 7},
                  "process_metrics": {"role": "client-1", "pid": 200,
                                      "cpu_seconds": 1.5,
                                      "rss_bytes": 1024,
                                      "open_fds": 9,
                                      "gc_collections": 3,
                                      "loop_lag_ms": 0.25,
                                      "uptime_seconds": 12.0},
                  "flightrec": {"armed": 1, "size": 512,
                                "buffered": 40, "noted": 99,
                                "dumps": 1}}
    fed = federate_status(_canned_status(), [old_worker, new_worker])
    procs = fed["cluster"]["processes"]
    for name, p in procs.items():
        for key in ("counters", "grv", "commit", "process_metrics",
                    "flightrec", "up", "uptime_s"):
            assert key in p, (name, key)
    # filled defaults are fresh dicts, never shared
    procs["client-0:100"]["counters"]["x"] = 1
    assert "x" not in normalize_proc_doc({})["counters"]

    # the federated scrape renders BOTH docs and parses; the new
    # worker's telemetry families carry its identity labels
    text = render_federated(_canned_status(), [old_worker, new_worker])
    samples = parse_prometheus(text)
    cpu = {(l.get("role"), l.get("pid")): v for n, l, v in samples
           if n == "fdbtpu_process_cpu_seconds"}
    assert cpu.get(("client-1", "200")) == 1.5, cpu
    rec = {n for n, _l, _v in samples if n.startswith("fdbtpu_flightrec")}
    assert {"fdbtpu_flightrec_buffered", "fdbtpu_flightrec_noted_total",
            "fdbtpu_flightrec_dumps_total"} <= rec, rec
    # the old worker still contributes its liveness row
    ups = {l.get("role") for n, l, _v in samples
           if n == "fdbtpu_process_up"}
    assert "client-0" in ups, ups
