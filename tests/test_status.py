"""Status document + counters (ref: fdbserver/Status.actor.cpp
clusterGetStatus :1802, flow/Stats.actor.cpp CounterCollection)."""

from foundationdb_tpu import flow
from foundationdb_tpu.client import run_transaction
from foundationdb_tpu.server import SimCluster


def test_status_reflects_cluster_and_workload():
    c = SimCluster(seed=701, durable=True, n_storage=2)
    try:
        db = c.client()

        async def main():
            for i in range(6):
                async def body(tr, i=i):
                    tr.set(b"s%d" % i, b"v")
                await run_transaction(db, body)
            tr = db.create_transaction()
            await tr.get(b"s0")
            status = await db.get_status()
            cl = status["cluster"]
            assert cl["epoch"] == 1
            assert cl["recovery_state"] == "fully_recovered"
            assert cl["configuration"]["storage_shards"] == 2
            assert len(cl["storages"]) == 2
            assert len(cl["logs"]) == 1
            assert cl["logs"][0]["counters"]["commits"] >= 6
            px = cl["proxies"][0]["counters"]
            assert px["transactions_committed"] >= 6
            assert px["transactions_started"] >= 6
            total_gets = sum(r["counters"].get("get_queries", 0)
                             for s in cl["storages"]
                             for r in s["replicas"] if "counters" in r)
            assert total_gets >= 1
            assert cl["qos"]["transactions_per_second_limit"] is not None
            return True

        assert c.run(main(), timeout_time=120)
    finally:
        c.shutdown()


def test_status_shows_failure_and_recovery():
    c = SimCluster(seed=703, durable=True)
    try:
        db = c.client()

        async def main():
            async def body(tr):
                tr.set(b"x", b"1")
            await run_transaction(db, body)
            c.kill_role("tlog")

            async def body2(tr):
                tr.set(b"y", b"2")
            await run_transaction(db, body2, max_retries=300)
            status = await db.get_status()
            cl = status["cluster"]
            assert cl["epoch"] >= 2
            assert cl["recovery_state"] == "fully_recovered"
            # the new generation's log is the one reported
            assert cl["logs"][0]["store"].startswith(
                f"tlog-e{cl['epoch']}")
            return True

        assert c.run(main(), timeout_time=300)
    finally:
        c.shutdown()


def test_status_latency_probe():
    """The CC's periodic probe transaction reports real GRV/read/commit
    latencies in status (ref: Status.actor.cpp:983 latencyProbe)."""
    from foundationdb_tpu import flow
    from foundationdb_tpu.server import SimCluster

    c = SimCluster(seed=71)
    try:
        db = c.client()

        async def main():
            for _ in range(40):
                status = await db.get_status()
                probe = status["cluster"]["latency_probe"]
                if probe:
                    assert probe["transaction_start_seconds"] >= 0
                    assert probe["commit_seconds"] > 0
                    return True
                await flow.delay(1.0)
            raise AssertionError("latency probe never reported")

        assert c.run(main(), timeout_time=120)
    finally:
        c.shutdown()


def test_latency_bands_in_status():
    """Banded GRV/read/commit latencies appear in the status document
    once traffic has flowed (ref: fdbserver/LatencyBandConfig.cpp)."""
    from foundationdb_tpu.client import run_transaction
    c = SimCluster(seed=63)
    try:
        db = c.client()

        async def main():
            for i in range(10):
                async def body(tr, i=i):
                    await tr.get(b"lb%d" % i)
                    tr.set(b"lb%d" % i, b"x")
                await run_transaction(db, body)
            status = await db.get_status()
            proxies = status["cluster"]["proxies"]
            assert proxies
            for p in proxies:
                bands = p["latency_bands"]
                assert bands["grv"]["total"] >= 10
                assert bands["commit"]["total"] >= 10
                # cumulative bands: the widest band covers everything
                widest = list(bands["commit"]["bands"].values())[-1]
                assert widest == bands["commit"]["total"]
            reads = [rep["latency_bands"]["read"]
                     for s in status["cluster"]["storages"]
                     for rep in s["replicas"] if "latency_bands" in rep]
            assert reads and sum(b["total"] for b in reads) >= 10
            return True

        assert c.run(main(), timeout_time=120)
    finally:
        c.shutdown()


def test_full_mutation_vocabulary():
    """21/21 mutation types (ref: CommitTransaction.h:49-109): V2 op
    codes apply, debug/no-op types are inert, and the never-legal
    types fail the transaction instead of poisoning the pipeline."""
    import pytest as _pytest

    from foundationdb_tpu.client import run_transaction
    from foundationdb_tpu.server.types import (AND_V2,
                                               AVAILABLE_FOR_REUSE,
                                               CommitRequest, DEBUG_KEY,
                                               DEBUG_KEY_RANGE, MIN_V2,
                                               MutationRef, NO_OP,
                                               RESERVED_LOG_PROTOCOL,
                                               SET_VALUE)
    c = SimCluster(seed=65)
    try:
        db = c.client()

        async def main():
            # V2 atomic codes through the client API
            async def setup(tr):
                tr.set(b"v2", (9).to_bytes(8, "little"))
            await run_transaction(db, setup)

            async def ops(tr):
                tr.atomic_op(b"v2", (4).to_bytes(8, "little"), MIN_V2)
                tr.atomic_op(b"missing_v2", b"\xf0", AND_V2)
            await run_transaction(db, ops)

            tr = db.create_transaction()
            assert await tr.get(b"v2") == (4).to_bytes(8, "little")
            # AND_V2 on an absent key takes the operand (V2 semantics)
            assert await tr.get(b"missing_v2") == b"\xf0"

            # inert types commit cleanly and change nothing
            info = await tr._get_info()
            proxy = info.proxies[0]
            await proxy.commits.get_reply(CommitRequest(
                0, (), ((b"inert", b"inert\x00"),),
                (MutationRef(NO_OP, b"", b""),
                 MutationRef(DEBUG_KEY, b"v2", b""),
                 MutationRef(DEBUG_KEY_RANGE, b"a", b"z"))),
                db.process)
            tr2 = db.create_transaction()
            assert await tr2.get(b"v2") == (4).to_bytes(8, "little")

            # never-legal types fail the txn loudly
            for t in (AVAILABLE_FOR_REUSE, RESERVED_LOG_PROTOCOL):
                with _pytest.raises(flow.FdbError) as ei:
                    await proxy.commits.get_reply(CommitRequest(
                        0, (), ((b"bad", b"bad\x00"),),
                        (MutationRef(t, b"bad", b"x"),)), db.process)
                assert ei.value.name == "client_invalid_operation"
            # and the client API refuses them outright
            tr3 = db.create_transaction()
            with _pytest.raises(flow.FdbError):
                tr3.atomic_op(b"k", b"x", AVAILABLE_FOR_REUSE)
            return True

        assert c.run(main(), timeout_time=120)
    finally:
        c.shutdown()


def test_time_series_metrics():
    """TDMetric-style multi-resolution counter series (ref:
    flow/TDMetric.actor.h levels): level 0 fine-grained, each level
    above 4x coarser; sampled from live roles into status."""
    # unit: the cascade
    ts = flow.TimeSeries(samples_per_level=8, n_levels=3)
    for i in range(32):
        ts.append(float(i), float(i))
    assert len(ts.series(0)) == 8          # ring holds the newest 8
    assert ts.latest() == (31.0, 31.0)
    l1 = ts.series(1)
    assert l1 and len(l1) == 8             # 32/4 = 8 cascaded samples
    assert l1[-1][1] == (28 + 29 + 30 + 31) / 4.0
    assert len(ts.series(2)) == 2          # 32/16

    # integration: the CC samples role counters into series
    from foundationdb_tpu.client import run_transaction
    c = SimCluster(seed=67)
    try:
        db = c.client()

        async def main():
            for i in range(5):
                async def body(tr, i=i):
                    tr.set(b"m%d" % i, b"x")
                await run_transaction(db, body)
            await flow.delay(3.5)   # a few sample intervals
            status = await db.get_status()
            metrics = status["cluster"]["metrics"]
            commit_series = [v for k, v in metrics.items()
                             if k.endswith("/transactions_committed")]
            assert commit_series, list(metrics)[:10]
            s = commit_series[0]
            assert s["latest"][1] >= 5
            assert len(s["tail"]) >= 2     # multiple samples over time
            return True

        assert c.run(main(), timeout_time=120)
    finally:
        c.shutdown()


def test_trace_batch_stitches_commit_path():
    """Sampled-transaction latency stitching (ref: g_traceBatch,
    flow/Trace.h:107 — a debug id rides the commit through client,
    proxy, and resolver; the stations reassemble in time order)."""
    c = SimCluster(seed=69)
    try:
        db = c.client()

        async def main():
            tr = db.create_transaction()
            tr.set_option("debug_transaction_identifier", 4242)
            tr.set(b"dbg", b"1")
            await tr.commit()
            events = flow.g_trace_batch.events(4242)
            locations = [loc for _t, _et, loc in events]
            for expect in ("NativeAPI.commit.Before",
                           "MasterProxyServer.commitBatch.Before",
                           "MasterProxyServer.commitBatch.GotCommitVersion",
                           "Resolver.resolveBatch.AfterQueueSorted",
                           "Resolver.resolveBatch.After",
                           "MasterProxyServer.commitBatch.AfterResolution",
                           "MasterProxyServer.commitBatch.AfterLogPush",
                           "NativeAPI.commit.After"):
                assert expect in locations, (expect, locations)
            # stations are stitched in causal (time) order
            idx = [locations.index(l) for l in (
                "NativeAPI.commit.Before",
                "MasterProxyServer.commitBatch.Before",
                "Resolver.resolveBatch.AfterQueueSorted",
                "MasterProxyServer.commitBatch.AfterLogPush",
                "NativeAPI.commit.After")]
            assert idx == sorted(idx), locations
            times = [t for t, _et, _loc in events]
            assert times == sorted(times)
            # an unsampled transaction adds nothing
            tr2 = db.create_transaction()
            tr2.set(b"plain", b"1")
            await tr2.commit()
            assert flow.g_trace_batch.events(None) == []

            # the debug id survives an on_error retry (the retry is
            # the interesting attempt)
            t3 = db.create_transaction()
            t3.set_option("debug_transaction_identifier", 777)
            await t3.get(b"dbg")
            side = db.create_transaction()
            side.set(b"dbg", b"2")
            await side.commit()
            t3.set(b"dbg", b"mine")
            try:
                await t3.commit()
            except flow.FdbError as e:
                await t3.on_error(e)
            t3.set(b"dbg", b"mine")
            await t3.commit()
            locs = [l for _t, _et, l in flow.g_trace_batch.events(777)]
            assert locs.count("NativeAPI.commit.After") >= 1, locs
            return True

        assert c.run(main(), timeout_time=120)
    finally:
        flow.g_trace_batch.clear()
        c.shutdown()
