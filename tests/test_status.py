"""Status document + counters (ref: fdbserver/Status.actor.cpp
clusterGetStatus :1802, flow/Stats.actor.cpp CounterCollection)."""

from foundationdb_tpu.client import run_transaction
from foundationdb_tpu.server import SimCluster


def test_status_reflects_cluster_and_workload():
    c = SimCluster(seed=701, durable=True, n_storage=2)
    try:
        db = c.client()

        async def main():
            for i in range(6):
                async def body(tr, i=i):
                    tr.set(b"s%d" % i, b"v")
                await run_transaction(db, body)
            tr = db.create_transaction()
            await tr.get(b"s0")
            status = await db.get_status()
            cl = status["cluster"]
            assert cl["epoch"] == 1
            assert cl["recovery_state"] == "fully_recovered"
            assert cl["configuration"]["storage_shards"] == 2
            assert len(cl["storages"]) == 2
            assert len(cl["logs"]) == 1
            assert cl["logs"][0]["counters"]["commits"] >= 6
            px = cl["proxies"][0]["counters"]
            assert px["transactions_committed"] >= 6
            assert px["transactions_started"] >= 6
            total_gets = sum(r["counters"].get("get_queries", 0)
                             for s in cl["storages"]
                             for r in s["replicas"] if "counters" in r)
            assert total_gets >= 1
            assert cl["qos"]["transactions_per_second_limit"] is not None
            return True

        assert c.run(main(), timeout_time=120)
    finally:
        c.shutdown()


def test_status_shows_failure_and_recovery():
    c = SimCluster(seed=703, durable=True)
    try:
        db = c.client()

        async def main():
            async def body(tr):
                tr.set(b"x", b"1")
            await run_transaction(db, body)
            c.kill_role("tlog")

            async def body2(tr):
                tr.set(b"y", b"2")
            await run_transaction(db, body2, max_retries=300)
            status = await db.get_status()
            cl = status["cluster"]
            assert cl["epoch"] >= 2
            assert cl["recovery_state"] == "fully_recovered"
            # the new generation's log is the one reported
            assert cl["logs"][0]["store"].startswith(
                f"tlog-e{cl['epoch']}")
            return True

        assert c.run(main(), timeout_time=300)
    finally:
        c.shutdown()


def test_status_latency_probe():
    """The CC's periodic probe transaction reports real GRV/read/commit
    latencies in status (ref: Status.actor.cpp:983 latencyProbe)."""
    from foundationdb_tpu import flow
    from foundationdb_tpu.server import SimCluster

    c = SimCluster(seed=71)
    try:
        db = c.client()

        async def main():
            for _ in range(40):
                status = await db.get_status()
                probe = status["cluster"]["latency_probe"]
                if probe:
                    assert probe["transaction_start_seconds"] >= 0
                    assert probe["commit_seconds"] > 0
                    return True
                await flow.delay(1.0)
            raise AssertionError("latency probe never reported")

        assert c.run(main(), timeout_time=120)
    finally:
        c.shutdown()
