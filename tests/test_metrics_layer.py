"""layers/metrics.py: persisted counter series (ref: MetricLogger).

New this round: time-bounded `read_series` range queries and the
`extra` channel `log_counters` uses to persist the latency-probe and
conflict hot-spot series the status assembler exposes."""

from foundationdb_tpu import flow
from foundationdb_tpu.layers import metrics
from foundationdb_tpu.server import SimCluster


def test_read_series_time_bounds_and_extra_series():
    c = SimCluster(seed=811)
    try:
        db = c.client()

        async def main():
            col = flow.CounterCollection("proxy")
            col.counter("transactions_committed").add(3)
            # two samples ~2s apart; the extra channel carries the
            # probe/hot-spot style series with no CounterCollection
            t0 = flow.now()
            await metrics.log_counters(
                db, [col],
                extra={"latency_probe": {"grv_us": 1500},
                       "conflict_hot_spots": {"total": 6}})
            await flow.delay(2.0)
            col.counter("transactions_committed").add(2)
            t1 = flow.now()
            await metrics.log_counters(
                db, [col], extra={"latency_probe": {"grv_us": 900}})

            full = await metrics.read_series(db, "proxy",
                                             "transactions_committed")
            assert [v for _t, v in full] == [3, 5]

            probe = await metrics.read_series(db, "latency_probe",
                                              "grv_us")
            assert [v for _t, v in probe] == [1500, 900]
            hot = await metrics.read_series(db, "conflict_hot_spots",
                                            "total")
            assert [v for _t, v in hot] == [6]

            # start/end in ms, half-open [start, end)
            cut = int((t0 + 1.0) * 1000)
            early = await metrics.read_series(
                db, "latency_probe", "grv_us", end=cut)
            late = await metrics.read_series(
                db, "latency_probe", "grv_us", start=cut)
            assert [v for _t, v in early] == [1500]
            assert [v for _t, v in late] == [900]
            both = await metrics.read_series(
                db, "latency_probe", "grv_us",
                start=int(t0 * 1000), end=int((t1 + 1) * 1000))
            assert [v for _t, v in both] == [1500, 900]
            empty = await metrics.read_series(
                db, "latency_probe", "grv_us",
                start=int((t1 + 10) * 1000))
            assert empty == []
            return True

        assert c.run(main(), timeout_time=120)
    finally:
        c.shutdown()


def test_metric_logger_extra_fn():
    c = SimCluster(seed=812)
    try:
        db = c.client()

        async def main():
            col = flow.CounterCollection("resolver")
            col.counter("batches_resolved").add(1)
            rounds = {"n": 0}

            def extra():
                rounds["n"] += 1
                return {"latency_probe": {"rounds": rounds["n"]}}

            task = flow.spawn(metrics.metric_logger(
                db, [col], interval=0.5, extra_fn=extra))
            await flow.delay(1.8)
            task.cancel()
            series = await metrics.read_series(db, "latency_probe",
                                               "rounds")
            assert len(series) >= 2
            assert [v for _t, v in series] == \
                list(range(1, len(series) + 1))
            return True

        assert c.run(main(), timeout_time=120)
    finally:
        c.shutdown()
