"""tools/monitor.py supervise loop: restart-with-backoff semantics.

Ref: fdbmonitor/fdbmonitor.cpp:501-790 — the daemon restarts a dying
fdbserver with exponential backoff, resets the backoff after a healthy
run, relays child output, and shuts the child down cleanly on SIGINT.
Previously untested; the fakes below pin each behavior without spawning
real processes."""

from typing import List, Optional

import pytest

from foundationdb_tpu.tools import monitor


class FakeTime:
    """monotonic()/sleep() on a virtual clock; sleeps are recorded —
    they ARE the backoff schedule under test."""

    def __init__(self):
        self.t = 0.0
        self.sleeps: List[float] = []

    def monotonic(self) -> float:
        return self.t

    def sleep(self, seconds: float) -> None:
        self.sleeps.append(seconds)
        self.t += seconds


class FakeProc:
    def __init__(self, rc: int, run_seconds: float, clock: FakeTime,
                 lines=(), interrupt: bool = False):
        self.rc = rc
        self.run_seconds = run_seconds
        self.clock = clock
        self.stdout = list(lines)
        self.interrupt = interrupt
        self.terminated = False
        self.killed = False
        self._interrupted_once = False

    def wait(self, timeout: Optional[float] = None):
        if self.interrupt and not self._interrupted_once:
            self._interrupted_once = True
            raise KeyboardInterrupt()
        self.clock.t += self.run_seconds
        return self.rc

    def terminate(self):
        self.terminated = True

    def kill(self):
        self.killed = True


class FakePopen:
    """Successive Popen calls pop scripted children; records the argv
    each spawn used."""

    PIPE = object()

    def __init__(self, script: List[FakeProc]):
        self.script = list(script)
        self.calls: List[List[str]] = []

    def Popen(self, cmd, stdout=None, text=None):  # noqa: N802
        self.calls.append(list(cmd))
        return self.script.pop(0)

    class TimeoutExpired(Exception):
        pass


@pytest.fixture
def patched(monkeypatch):
    clock = FakeTime()
    monkeypatch.setattr(monitor, "time", clock)

    def install(procs):
        fake = FakePopen(procs)
        fake.TimeoutExpired = monitor.subprocess.TimeoutExpired
        monkeypatch.setattr(monitor, "subprocess", fake)
        return fake

    return clock, install


def test_backoff_doubles_on_crash_loop(patched):
    clock, install = patched
    procs = [FakeProc(1, 0.0, clock) for _ in range(4)]
    install(procs)
    out: List[str] = []
    rc = monitor.supervise(["--port", "4500"], max_restarts=3,
                           announce=lambda *a, **k: out.append(a[0]))
    assert rc == 1
    # initial 0.5 doubling toward the 30s cap (knob defaults)
    assert clock.sleeps == [0.5, 1.0, 2.0]
    assert sum("starting" in line for line in out) == 4


def test_backoff_caps_at_maximum(patched):
    clock, install = patched
    install([FakeProc(1, 0.0, clock) for _ in range(10)])
    rc = monitor.supervise([], max_restarts=9,
                           announce=lambda *a, **k: None)
    assert rc == 1
    assert max(clock.sleeps) <= 30.0
    assert clock.sleeps[-1] == 30.0 or clock.sleeps[-1] == min(
        0.5 * 2 ** (len(clock.sleeps) - 1), 30.0)


def test_backoff_resets_after_healthy_run(patched):
    clock, install = patched
    # crash, crash (backoff 0.5 then 1.0), healthy 20s run, crash again:
    # the next backoff must be back at the initial 0.5
    install([FakeProc(1, 0.0, clock), FakeProc(1, 0.0, clock),
             FakeProc(1, 20.0, clock), FakeProc(1, 0.0, clock),
             FakeProc(1, 0.0, clock)])
    rc = monitor.supervise([], max_restarts=4,
                           announce=lambda *a, **k: None)
    assert rc == 1
    # third sleep restarts the doubling from the initial 0.5 — without
    # the reset it would read [0.5, 1.0, 2.0, 4.0]
    assert clock.sleeps == [0.5, 1.0, 0.5, 1.0]


def test_keyboard_interrupt_terminates_child(patched):
    clock, install = patched
    child = FakeProc(0, 0.0, clock, interrupt=True)
    install([child])
    out: List[str] = []
    rc = monitor.supervise([], announce=lambda *a, **k: out.append(a[0]))
    assert rc == 0
    assert child.terminated
    assert any("stopped" in line for line in out)


def test_child_output_is_relayed(patched):
    clock, install = patched
    install([FakeProc(1, 0.0, clock,
                      lines=["listening on 4500\n", "ready\n"])])
    out: List[str] = []
    rc = monitor.supervise([], max_restarts=0,
                           announce=lambda *a, **k: out.append(a[0]))
    assert rc == 1
    relayed = [line for line in out if "child:" in line]
    assert any("listening on 4500" in line for line in relayed)


def test_server_args_forwarded(patched):
    clock, install = patched
    fake = install([FakeProc(1, 0.0, clock)])
    monitor.supervise(["--port", "4555", "--data-dir", "/tmp/x"],
                      max_restarts=0, announce=lambda *a, **k: None,
                      python="py3")
    cmd = fake.calls[0]
    assert cmd[:3] == ["py3", "-m", "foundationdb_tpu.tools.server"]
    assert cmd[3:] == ["--port", "4555", "--data-dir", "/tmp/x"]
