"""KeyValueStoreBTree: randomized model checking + crash recovery via
the shadow-paging superblock flip (ref: fdbserver/VersionedBTree
.actor.cpp + IndirectShadowPager; test style: KVStoreTest workload)."""

import random

import pytest

import foundationdb_tpu.flow as fl
from foundationdb_tpu.rpc import SimNetwork
from foundationdb_tpu.server.btree import KeyValueStoreBTree


def _env(seed):
    fl.set_seed(seed)
    s = fl.Scheduler(virtual=True)
    fl.set_scheduler(s)
    net = SimNetwork(s, fl.g_random)
    proc = net.new_process("kvs", machine="m")
    return s, net, proc


def _run(s, coro, timeout=600):
    t = s.spawn(coro)
    assert s.run(until=t, timeout_time=timeout)
    return t.get()


def test_basic_ops_and_recovery():
    s, net, proc = _env(21)
    try:
        kv = KeyValueStoreBTree(net.disk("m"), "bt", owner=proc)

        async def main():
            await kv.recover()
            for i in range(200):
                kv.set(b"k%04d" % i, b"v%d" % i)
            await kv.commit()
            assert kv.get(b"k0042") == b"v42"
            assert kv.get(b"nope") is None
            rows = kv.get_range(b"k0010", b"k0013")
            assert rows == [(b"k0010", b"v10"), (b"k0011", b"v11"),
                            (b"k0012", b"v12")]
            kv.clear_range(b"k0010", b"k0190")
            kv.set(b"k0100", b"back")
            await kv.commit()
            # reopen from disk
            kv2 = KeyValueStoreBTree(net.disk("m"), "bt", owner=proc)
            await kv2.recover()
            assert kv2.get(b"k0005") == b"v5"
            assert kv2.get(b"k0050") is None
            assert kv2.get(b"k0100") == b"back"
            assert kv2.get(b"k0195") == b"v195"
            assert len(kv2.get_range(b"", b"\xff")) == \
                len(kv.get_range(b"", b"\xff"))
            return True

        _run(s, main())
    finally:
        fl.set_scheduler(None)


@pytest.mark.parametrize("seed", [1, 2, 3, 4, 5])
def test_randomized_vs_model_with_crashes(seed):
    """Random op batches vs a dict model; a power loss between commits
    must recover EXACTLY the last committed state (the shadow-paging
    guarantee)."""
    s, net, proc = _env(100 + seed)
    try:
        async def main():
            rng = random.Random(seed)
            kv = KeyValueStoreBTree(net.disk("m"), "bt", owner=proc)
            await kv.recover()
            committed = {}
            model = {}
            for _round in range(30):
                for _ in range(rng.randrange(1, 12)):
                    if rng.random() < 0.75:
                        k = b"%03d" % rng.randrange(150)
                        v = b"v%d" % rng.randrange(1000)
                        kv.set(k, v)
                        model[k] = v
                    else:
                        a = b"%03d" % rng.randrange(150)
                        b = b"%03d" % rng.randrange(150)
                        if a > b:
                            a, b = b, a
                        kv.clear_range(a, b)
                        for k in [k for k in model if a <= k < b]:
                            del model[k]
                # reads see staged state
                probe = b"%03d" % rng.randrange(150)
                assert kv.get(probe) == model.get(probe)
                if rng.random() < 0.7:
                    await kv.commit()
                    committed = dict(model)
                if rng.random() < 0.25:
                    # crash: unsynced writes are lost; recover and
                    # compare against the last committed state
                    net.disk("m").power_loss(fl.g_random, owner=proc)
                    kv = KeyValueStoreBTree(net.disk("m"), "bt",
                                            owner=proc)
                    await kv.recover()
                    got = dict(kv.get_range(b"", b"\xff"))
                    assert got == committed, (
                        _round, len(got), len(committed))
                    model = dict(committed)
            return True

        _run(s, main())
    finally:
        fl.set_scheduler(None)


def test_btree_as_storage_engine_in_cluster():
    """The engine slots in behind the storage server like the memory
    engine does."""
    from foundationdb_tpu.client import run_transaction
    from foundationdb_tpu.server import SimCluster

    c = SimCluster(seed=31, durable=True, storage_engine="btree")
    try:
        db = c.client()

        async def main():
            async def body(tr):
                for i in range(50):
                    tr.set(b"bt%02d" % i, b"v%d" % i)
            await run_transaction(db, body)
            c.kill_role("storage")

            async def check(tr):
                got = await tr.get_range(b"bt", b"bu")
                assert len(got) == 50
            await run_transaction(db, check, max_retries=300)
            return True

        assert c.run(main(), timeout_time=600)
    finally:
        c.shutdown()


def test_reverse_paging_returns_rows_nearest_end():
    """Reverse limited scans must yield the window's LAST rows — the
    contract the storage server's reverse paging depends on (code
    review r3)."""
    s, net, proc = _env(41)
    try:
        kv = KeyValueStoreBTree(net.disk("m"), "bt", owner=proc)

        async def main():
            await kv.recover()
            for i in range(300):
                kv.set(b"r%04d" % i, b"v")
            await kv.commit()
            page = kv.get_range(b"", b"\xff", limit=64, reverse=True)
            assert page[0][0] == b"r0299"
            assert page[-1][0] == b"r0236"
            # paging backward covers everything exactly once
            seen = []
            cursor = b"\xff"
            while True:
                pg = kv.get_range(b"", cursor, limit=64, reverse=True)
                if not pg:
                    break
                seen.extend(k for k, _ in pg)
                cursor = pg[-1][0]
            assert seen == [b"r%04d" % i for i in range(299, -1, -1)]
            return True

        _run(s, main())
    finally:
        fl.set_scheduler(None)


def test_large_values_split_by_bytes():
    """Values near the per-item limit force byte-aware splits instead
    of page overflow (code review r3)."""
    s, net, proc = _env(43)
    try:
        kv = KeyValueStoreBTree(net.disk("m"), "bt", owner=proc)

        async def main():
            await kv.recover()
            big = b"x" * 1900
            for i in range(60):
                kv.set(b"big%02d" % i, big + b"%02d" % i)
            await kv.commit()
            for i in range(60):
                assert kv.get(b"big%02d" % i) == big + b"%02d" % i
            kv2 = KeyValueStoreBTree(net.disk("m"), "bt", owner=proc)
            await kv2.recover()
            assert len(kv2.get_range(b"", b"\xff")) == 60
            with pytest.raises(ValueError):
                kv.set(b"k", b"y" * 3000)
            with pytest.raises(ValueError):
                kv.set(b"k" * 2000, b"v")
            return True

        _run(s, main())
    finally:
        fl.set_scheduler(None)


def test_free_list_survives_heavy_churn():
    """Large clears free more pages than one superblock holds; the
    overflow stays reusable so the file stops growing under churn
    (code review r3)."""
    s, net, proc = _env(47)
    try:
        kv = KeyValueStoreBTree(net.disk("m"), "bt", owner=proc)

        async def main():
            await kv.recover()
            sizes = []
            for _cycle in range(6):
                for i in range(800):
                    kv.set(b"c%04d" % i, b"v%d" % i)
                await kv.commit()
                kv.clear_range(b"", b"\xff")
                await kv.commit()
                sizes.append(kv._next_page)
            # allocation reuses freed pages: the page-id high-water mark
            # stabilizes instead of growing every cycle
            assert sizes[-1] == sizes[-2] == sizes[-3], sizes
            return True

        _run(s, main(), timeout=1200)
    finally:
        fl.set_scheduler(None)
