"""Conflict prediction & admission scheduling (ISSUE 8 tentpole,
server/scheduler.py): the ConflictHotSpots live-knob audit, the
predictor's probability math, the proxy's deferral queues (bounds,
priority order, release-marker round trip), the CC hot-spot push loop,
and the ratekeeper's deferral-pressure throttle input.

Ref: arXiv:2409.01675 (conflict-prediction scheduling); the hot-spot
table is PR 2's attribution aggregate turned actionable.
"""

import pytest

from foundationdb_tpu import flow
from foundationdb_tpu.client import run_transaction
from foundationdb_tpu.server import SimCluster
from foundationdb_tpu.server.resolver_role import ConflictHotSpots
from foundationdb_tpu.server.scheduler import (AdmissionScheduler,
                                               ConflictPredictor)
from foundationdb_tpu.server.types import (PRIORITY_BATCH,
                                           PRIORITY_DEFAULT,
                                           PRIORITY_IMMEDIATE,
                                           CommitRequest, MutationRef,
                                           SET_VALUE)


def _sched_env():
    flow.set_seed(0)
    s = flow.Scheduler()
    flow.set_scheduler(s)
    flow.reset_server_knobs(randomize=False)
    return s


def _teardown():
    flow.reset_server_knobs(randomize=False)
    flow.set_scheduler(None)


# -- satellite: ConflictHotSpots live-read knobs -----------------------

def test_hot_spots_half_life_is_live_read():
    """The PR 6 Smoother audit applied here: half-life must be read
    per use, not frozen at construction — a SimCluster (or operator)
    retuning HOT_SPOT_HALF_LIFE must change the decay immediately."""
    _sched_env()
    try:
        k = flow.SERVER_KNOBS
        k.set("hot_spot_half_life", 2.0)
        hs = ConflictHotSpots()          # defaults -> live knob reads
        assert hs._decayed(100.0, 0.0, 2.0) == pytest.approx(50.0)
        k.set("hot_spot_half_life", 4.0)  # retune AFTER construction
        assert hs.half_life == 4.0
        assert hs._decayed(100.0, 0.0, 4.0) == pytest.approx(50.0)
        assert hs._decayed(100.0, 0.0, 2.0) == pytest.approx(
            100.0 * 0.5 ** 0.5)
        # an explicit construction pin still wins (directed tests and
        # the legacy signature rely on it)
        pinned = ConflictHotSpots(half_life=1.0)
        k.set("hot_spot_half_life", 100.0)
        assert pinned.half_life == 1.0
    finally:
        _teardown()


def test_hot_spots_capacity_and_top_k_are_live_read():
    _sched_env()
    try:
        k = flow.SERVER_KNOBS
        k.set("hot_spot_max_entries", 64)
        hs = ConflictHotSpots()
        for i in range(6):
            hs.record(b"k%d" % i, b"k%d\x00" % i)
        assert len(hs._entries) == 6
        # shrink the capacity knob: the NEXT record drains the excess
        k.set("hot_spot_max_entries", 3)
        hs.record(b"k9", b"k9\x00")
        assert len(hs._entries) == 3
        k.set("hot_spot_top_k", 2)
        assert len(hs.top()) == 2       # top() live-reads top-K
    finally:
        _teardown()


def test_hot_spots_rows_carry_last_conflict_version():
    _sched_env()
    try:
        hs = ConflictHotSpots(half_life=10.0)
        hs.record(b"a", b"b", version=100)
        hs.record(b"a", b"b", version=700)
        hs.record(b"a", b"b", version=400)   # never regresses
        rows = hs.rows()
        assert rows[0][0] == b"a" and rows[0][4] == 700
        # top() output shape is unchanged (status/exporter consumers)
        assert set(hs.top()[0]) == {"begin", "end", "score", "total"}
    finally:
        _teardown()


# -- predictor ----------------------------------------------------------

def test_predictor_probability_math():
    _sched_env()
    try:
        flow.SERVER_KNOBS.set("sched_hot_score_scale", 5.0)
        p = ConflictPredictor()
        p.update([(b"a", b"b", 5.0, 10, 7), (b"x", b"y", 95.0, 10, 9)],
                 0.0)
        # score==scale -> 0.5 per range; non-overlapping -> 0
        prob, hot = p.score([(b"a", b"a\x00")])
        assert prob == pytest.approx(0.5) and hot == (b"a", b"b")
        prob, hot = p.score([(b"m", b"n")])
        assert prob == 0.0 and hot is None
        # overlapping both: 1 - 0.5*0.05; hottest range wins the key
        prob, hot = p.score([(b"a", b"a\x00"), (b"x", b"x\x00")])
        assert prob == pytest.approx(1 - 0.5 * 0.05)
        assert hot == (b"x", b"y")
    finally:
        _teardown()


# -- admission scheduler ------------------------------------------------

def _req(prio=PRIORITY_DEFAULT, attempt=0):
    return CommitRequest(0, ((b"a", b"a\x00"),), (),
                         (MutationRef(SET_VALUE, b"a", b"v"),),
                         priority=prio, repair_attempt=attempt)


class _Proc:
    name = "p0"


def test_scheduler_defers_bounds_and_priority_order():
    s = _sched_env()
    try:
        k = flow.SERVER_KNOBS
        k.set("conflict_scheduling", 1)
        k.set("sched_conflict_threshold", 0.5)
        k.set("sched_queue_max", 2)
        k.set("sched_release_spacing", 0.001)
        k.set("sched_max_delay", 1.0)
        stats = flow.CounterCollection("proxy")
        released = []
        sched = AdmissionScheduler(
            _Proc(), stats, lambda req, reply: released.append(reply))
        sched.predictor.update([(b"a", b"b", 100.0, 10, 5)], 0.0)
        r_batch, r_def, r_over = object(), object(), object()
        # IMMEDIATE and repair resubmissions never defer
        assert not sched.consider(_req(PRIORITY_IMMEDIATE), object())
        assert not sched.consider(_req(attempt=1), object())
        assert sched.consider(_req(PRIORITY_BATCH), r_batch)
        assert sched.consider(_req(), r_def)
        assert sched.queue_depth() == 2
        # queue cap -> bounded-delay overflow: admitted immediately
        assert not sched.consider(_req(), r_over)
        assert stats.snapshot()["sched_overflow"] == 1

        async def drain():
            await flow.delay(0.1)
        s.run(until=flow.spawn(drain()))
        # default released before batch (priority-aware), both out
        assert released == [r_def, r_batch]
        assert sched.queue_depth() == 0
        # the release marker makes the round trip admit exactly once
        assert not sched.consider(_req(), r_def)
        assert sched.consider(_req(), r_def)
    finally:
        _teardown()


def test_scheduler_off_or_cold_predictor_never_defers():
    _sched_env()
    try:
        k = flow.SERVER_KNOBS
        stats = flow.CounterCollection("proxy")
        sched = AdmissionScheduler(_Proc(), stats,
                                   lambda req, reply: None)
        # knob off (default): no deferral even with a hot predictor
        sched.predictor.update([(b"a", b"b", 100.0, 10, 5)], 0.0)
        k.set("conflict_scheduling", 0)
        assert not sched.consider(_req(), object())
        # knob on but cold predictor: nothing to key a queue on
        k.set("conflict_scheduling", 1)
        sched.predictor.update([], 0.0)
        assert not sched.consider(_req(), object())
        assert stats.snapshot().get("sched_deferrals", 0) == 0
    finally:
        _teardown()


def test_scheduler_shutdown_breaks_held_commits():
    _sched_env()
    try:
        k = flow.SERVER_KNOBS
        k.set("conflict_scheduling", 1)
        k.set("sched_release_spacing", 10.0)   # hold them
        k.set("sched_max_delay", 100.0)
        stats = flow.CounterCollection("proxy")
        sched = AdmissionScheduler(_Proc(), stats,
                                   lambda req, reply: None)
        sched.predictor.update([(b"a", b"b", 100.0, 10, 5)], 0.0)
        errs = []

        class _Reply:
            def send_error(self, e):
                errs.append(e.name)
        assert sched.consider(_req(), _Reply())
        sched.shutdown()
        assert errs == ["broken_promise"]
        assert sched.queue_depth() == 0
    finally:
        _teardown()


# -- end to end: deferral under real contention ------------------------

def test_scheduler_defers_under_contention_and_liveness_holds():
    """With scheduling armed, a burst of hot-key commits gets deferred
    (counters + status prove it) and every transaction still settles —
    bounded delay means deferral can never wedge a commit."""
    c = SimCluster(seed=808, durable=True)
    flow.SERVER_KNOBS.set("conflict_scheduling", 1)
    flow.SERVER_KNOBS.set("sched_hot_push_interval", 0.05)
    flow.SERVER_KNOBS.set("sched_conflict_threshold", 0.3)
    try:
        db = c.client()

        async def main():
            async def seed(tr):
                tr.set(b"hot", b"0")
            await run_transaction(db, seed)
            # heat the table: repeated conflicts on b"hot"
            for _ in range(8):
                tr = db.create_transaction()
                await tr.get(b"hot")
                tr.set(b"mine", b"v")

                async def bump(t2):
                    t2.set(b"hot", b"x")
                await run_transaction(db, bump)
                try:
                    await tr.commit()
                except flow.FdbError as e:
                    assert e.name == "not_committed", e.name
            await flow.delay(0.3)   # pushes land at the proxy
            # now a conflicting-range commit gets deferred yet commits
            done = 0
            for i in range(6):
                async def body(tr, i=i):
                    await tr.get(b"hot")
                    tr.set(b"hot", b"w%d" % i)
                await run_transaction(db, body)
                done += 1
            status = await db.get_status()
            return done, status

        done, status = c.run(main(), timeout_time=300)
        assert done == 6
        px = status["cluster"]["proxies"][0]
        sched = px["scheduler"]
        assert sched["enabled"] == 1
        assert sched["pushes"] > 0, sched
        assert sched["hot_rows"] > 0, sched
        assert sched["deferrals"] > 0, sched
        assert sched["released"] == sched["deferrals"], sched
        assert sched["deferred_now"] == 0, sched
        doc = status["cluster"]["conflict_scheduling"]
        assert doc["scheduling_enabled"] == 1 and doc["deferrals"] > 0
    finally:
        flow.reset_server_knobs(randomize=False)
        c.shutdown()


# -- ratekeeper deferral-pressure input --------------------------------

def test_ratekeeper_throttles_on_deferral_pressure():
    """A deep deferred-commit queue becomes a first-class limiting
    reason: spring-zone throttle over the smoothed depth, reported as
    conflict_deferrals in the decision (and RkUpdate/qos mirrors)."""
    from foundationdb_tpu.server.ratekeeper import LIMIT_REASONS, Ratekeeper
    assert "conflict_deferrals" in LIMIT_REASONS
    c = SimCluster(seed=809, durable=True)
    try:
        db = c.client()

        async def main():
            async def body(tr):
                tr.set(b"x", b"1")
            await run_transaction(db, body)
            k = flow.SERVER_KNOBS
            k.set("rk_sched_defer_limit", 4.0)
            k.set("rk_sched_defer_spring", 2.0)
            k.set("rk_smoothing_seconds", 0.0)
            rk = None
            from foundationdb_tpu.server.proxy import Proxy
            for wi in c.cc.workers.values():
                for role in wi.worker.roles.values():
                    if isinstance(role, Ratekeeper):
                        rk = role
                    elif isinstance(role, Proxy):
                        role.scheduler._depth = 10   # fabricated depth
            assert rk is not None
            rk._sched_smooth.clear()
            rate, _batch = rk._compute_rates()
            d = rk.last_decision
            assert d["limiting_reason"] == "conflict_deferrals", d
            assert d["inputs"]["sched_deferred_depth"] == 10, d
            assert rate == k.rk_min_rate, rate
            return True

        assert c.run(main(), timeout_time=120)
    finally:
        flow.reset_server_knobs(randomize=False)
        c.shutdown()
