"""Longitudinal observability pins (ISSUE 17).

The contracts: (1) the TimeKeeper/metric-history key+value codecs
round-trip (including '/'-bearing signal names and negative deltas) and
skip foreign rows; (2) an armed sim cluster persists a version<->clock
map whose interpolated lookups invert, plus signal series a reader can
replay from the keyspace; (3) same-seed armed runs record BIT-IDENTICAL
series (the recorder samples the sim clock, not the host's); (4) the
default METRIC_HISTORY=0 posture adds NOTHING — no recorder, no system
rows, and same-seed runs stay bit-identical across digest/steps/
messages; (5) the SLO math is directed — multiwindow burn rates trip
only when fast AND slow windows burn, ceilings need a sustained window,
insufficient data never pages; (6) one janitor trims all three
longitudinal keyspaces; (7) an incident bundle snapshots the breach
window version-aligned; (8) rolled trace segments re-stamp their
process identity and tracemerge reads .N segments in numeric order.
"""

import json
import os

from foundationdb_tpu import flow
from foundationdb_tpu.client import run_transaction
from foundationdb_tpu.flow import trace as trace_mod
from foundationdb_tpu.layers import metrics as metrics_layer
from foundationdb_tpu.server import SimCluster
from foundationdb_tpu.server import slo as slo_mod
from foundationdb_tpu.server import systemkeys as sk
from foundationdb_tpu.server import timekeeper as tk
from foundationdb_tpu.server.chaos import database_digest
from foundationdb_tpu.tools import tracemerge


# -- codecs (pure) ---------------------------------------------------------

def test_timekeeper_key_roundtrip_and_order():
    k1 = sk.timekeeper_key(1_000)
    k2 = sk.timekeeper_key(2_000)
    assert sk.TIMEKEEPER_PREFIX < k1 < k2 < sk.TIMEKEEPER_END
    assert sk.parse_timekeeper_key(k1) == (sk.TIMEKEEPER_VERSION, 1_000)
    # the cutoff key IS the first key at that timestamp: clear_range
    # up to it removes strictly-older rows only
    assert sk.timekeeper_cutoff_key(2_000) == k2
    # foreign shapes parse to None, never raise
    assert sk.parse_timekeeper_key(b"\xff\x02/other/1") is None
    assert sk.parse_timekeeper_key(sk.TIMEKEEPER_PREFIX + b"junk") is None
    assert sk.parse_timekeeper_key(
        sk.TIMEKEEPER_PREFIX + b"1/zz/extra") is None


def test_metric_chunk_codec_roundtrip():
    # negative deltas both axes... no — time is monotone, VALUES dip
    # (a gauge falling, a counter re-baselining after restart)
    samples = [(1_000, 50), (2_000, 75), (3_500, 60), (3_600, 0)]
    enc = sk.encode_metric_chunk(samples)
    assert sk.decode_metric_chunk(enc) == samples
    assert sk.decode_metric_chunk(sk.encode_metric_chunk(
        [(7, -3)])) == [(7, -3)]
    # foreign / future-version rows decode to None (reader skips)
    assert sk.decode_metric_chunk(b"gibberish") is None
    assert sk.decode_metric_chunk(b"9|1|2|") is None
    # signals carry '/' — the key parse splits the ts off the RIGHT
    key = sk.metric_history_key("latency/commit/p99_ms", 42_000)
    assert sk.parse_metric_history_key(key) == \
        (sk.METRIC_HISTORY_VERSION, "latency/commit/p99_ms", 42_000)
    assert sk.parse_metric_history_key(b"\xff\x02/metrics/zz") is None
    assert key.startswith(
        sk.metric_history_signal_prefix("latency/commit/p99_ms"))


def test_timekeeper_pure_lookup_interpolates_and_extrapolates():
    tmap = [(10.0, 1_000_000), (20.0, 11_000_000)]
    # interior: linear between the rows
    assert tk.version_at_time_from_map(tmap, 15.0) == 6_000_000
    assert tk.time_at_version_from_map(tmap, 6_000_000) == 15.0
    # past the ends: nominal 1e6 versions/second slope
    assert tk.version_at_time_from_map(tmap, 22.0) == 13_000_000
    assert tk.version_at_time_from_map(tmap, 9.0) == 0  # clamped >= 0
    assert tk.time_at_version_from_map(tmap, 12_000_000) == 21.0
    assert tk.version_at_time_from_map([], 5.0) is None
    assert tk.time_at_version_from_map([], 5) is None


# -- SLO math (pure, directed) ---------------------------------------------

def _mk_burn(budget=0.01):
    return slo_mod.SloRule(
        "r", "burn_rate", "bad", total_signal="total", budget=budget,
        fast_window_s=10.0, slow_window_s=60.0, fast_rate=14.0,
        slow_rate=3.0)


def test_burn_rate_directed_math():
    now = 100_000
    total = [(40_000, 0), (90_000, 500), (100_000, 600)]
    # fast window: 20 bad / 100 total = 20x budget; slow: 30/600 = 5x
    bad = [(40_000, 0), (90_000, 10), (100_000, 30)]
    assert slo_mod.burn_rate(bad, total, now, 10.0, 0.01) == 20.0
    assert slo_mod.burn_rate(bad, total, now, 60.0, 0.01) == 5.0
    doc = slo_mod._eval_rule(_mk_burn(), {"bad": bad, "total": total},
                             now)
    assert doc["ok"] is False and doc["value"] == 20.0 \
        and doc["slow_value"] == 5.0

    # slow window still burning but the fast window cooled: NO page
    # (the multiwindow shape — a resolved incident stops alerting)
    bad2 = [(40_000, 0), (90_000, 28), (100_000, 30)]
    doc2 = slo_mod._eval_rule(_mk_burn(), {"bad": bad2, "total": total},
                              now)
    assert doc2["ok"] is True and doc2["value"] == 2.0

    # under two samples in a window -> no verdict, rule stays ok
    doc3 = slo_mod._eval_rule(
        _mk_burn(), {"bad": [(99_000, 5)], "total": total}, now)
    assert doc3["ok"] is True and doc3["value"] is None
    assert slo_mod.burn_rate([], total, now, 10.0, 0.01) is None


def test_ceiling_zero_and_recovery_rules():
    now = 100_000
    ceil = slo_mod.SloRule("p99", "ceiling", "g", threshold=250.0,
                           window_s=10.0)
    over = {"g": [(95_000, 300), (100_000, 310)]}
    blip = {"g": [(95_000, 300), (100_000, 200)]}
    one = {"g": [(100_000, 9_999)]}
    assert slo_mod._eval_rule(ceil, over, now)["ok"] is False
    assert slo_mod._eval_rule(ceil, blip, now)["ok"] is True
    # a single over-limit sample never pages a sustained ceiling
    assert slo_mod._eval_rule(ceil, one, now)["ok"] is True

    zero = slo_mod.SloRule("div", "zero", "m")
    assert slo_mod._eval_rule(zero, {"m": [(1, 0)]}, now)["ok"] is True
    assert slo_mod._eval_rule(zero, {"m": [(1, 2)]}, now)["ok"] is False
    assert slo_mod._eval_rule(zero, {}, now)["ok"] is True

    # recovery age: window_s=0 means instantaneous (the signal already
    # integrates time — one over-limit sample IS a sustained outage)
    rec = slo_mod.SloRule("rec", "ceiling", "age", threshold=5_000.0,
                          window_s=0.0)
    assert slo_mod._eval_rule(rec, {"age": [(now, 6_000)]},
                              now)["ok"] is False
    assert slo_mod._eval_rule(rec, {"age": [(now, 0)]},
                              now)["ok"] is True

    # empty series under the shipped rule table -> state ok
    v = slo_mod.evaluate(slo_mod.default_rules(), {}, now)
    assert v["state"] == "ok" and v["breached"] == []


# -- armed sim: record, translate, read back -------------------------------

def _armed_workload(c, horizon=13.0, capture=None):
    db = c.client("lg")

    async def main():
        for i in range(int(horizon / 0.25)):
            tr = db.create_transaction()
            tr.set(b"lg/%03d" % (i % 40), b"%d" % i)
            await tr.commit()
            await flow.delay(0.25)
        if capture is not None:
            return await capture(db)
        return True

    return db, main


def test_armed_sim_records_and_translates(sim_seed):
    seed = sim_seed(1701)
    c = SimCluster(seed=seed, metric_history=True)
    try:
        async def capture(db):
            tmap = await tk.read_time_map(db)
            sigs = await metrics_layer.list_history_signals(db)
            committed = await metrics_layer.read_history(
                db, "cluster/txn_committed")
            status = await db.get_status()
            return tmap, sigs, committed, status

        db, main = _armed_workload(c, capture=capture)
        tmap, sigs, committed, status = c.run(main(), timeout_time=600)
    finally:
        c.shutdown()

    # the map landed and is monotone on both axes
    assert len(tmap) >= 3, tmap
    assert tmap == sorted(tmap)
    assert [v for _t, v in tmap] == sorted(v for _t, v in tmap)
    # interpolated lookup inverts: clock -> version -> clock
    mid = (tmap[0][0] + tmap[-1][0]) / 2
    v_mid = tk.version_at_time_from_map(tmap, mid)
    assert tmap[0][1] <= v_mid <= tmap[-1][1]
    assert abs(tk.time_at_version_from_map(tmap, v_mid) - mid) < 0.5

    # the recorder's vocabulary persisted and replays in order
    for need in ("cluster/txn_committed", "latency/commit/total",
                 "latency/commit/p99_ms", "cluster/shadow_mismatches",
                 "chaos/events"):
        assert need in sigs, sigs
    assert len(committed) >= 8, committed
    assert committed == sorted(committed)
    assert committed[-1][1] > 0   # the workload's commits are visible
    assert [v for _t, v in committed] == \
        sorted(v for _t, v in committed)

    slo_doc = status["cluster"]["slo"]
    assert slo_doc["enabled"] == 1
    assert slo_doc["state"] == "ok", slo_doc
    assert slo_doc["timekeeper_rows"] >= 3
    assert slo_doc["recorder"]["rows_written"] > 0
    assert {r["name"] for r in slo_doc["rules"]} >= \
        {"commit_p99", "no_divergence", "commit_error_budget"}


def _series_fingerprint(seed):
    c = SimCluster(seed=seed, metric_history=True)
    try:
        async def capture(db):
            sigs = await metrics_layer.list_history_signals(db)
            series = {}
            for s in sigs:
                series[s] = await metrics_layer.read_history(db, s)
            tmap = await tk.read_time_map(db)
            digest = await database_digest(db)
            return series, tmap, digest

        _db, main = _armed_workload(c, capture=capture)
        series, tmap, digest = c.run(main(), timeout_time=600)
        return {"series": series, "tmap": tmap, "digest": digest,
                "sched_steps": c.sched.tasks_run,
                "net_messages": c.net.messages_sent}
    finally:
        c.shutdown()


def test_same_seed_series_bit_identical(sim_seed):
    seed = sim_seed(1702)
    a, b = _series_fingerprint(seed), _series_fingerprint(seed)
    assert a == b, "armed same-seed runs must record identical series"
    assert a["series"]["cluster/txn_committed"], a["series"].keys()


def test_off_posture_adds_nothing(sim_seed):
    """METRIC_HISTORY=0 (the default): no recorder object, a disabled
    status stanza, ZERO rows in any longitudinal keyspace, and two
    same-seed runs stay bit-identical — the feature's presence is
    unobservable until armed."""
    seed = sim_seed(1703)

    def run_off():
        c = SimCluster(seed=seed)
        try:
            async def capture(db):
                async def body(tr):
                    tr.set_option("read_system_keys")
                    tk_rows = await tr.get_range(
                        sk.TIMEKEEPER_PREFIX, sk.TIMEKEEPER_END)
                    mh_rows = await tr.get_range(
                        sk.METRIC_HISTORY_PREFIX, sk.METRIC_HISTORY_END)
                    return tk_rows, mh_rows
                tk_rows, mh_rows = await run_transaction(db, body)
                status = await db.get_status()
                digest = await database_digest(db)
                return tk_rows, mh_rows, status, digest

            _db, main = _armed_workload(c, horizon=6.0, capture=capture)
            tk_rows, mh_rows, status, digest = c.run(main(),
                                                     timeout_time=600)
            assert c.cc.metric_recorder is None
            return (tk_rows, mh_rows, status["cluster"]["slo"], digest,
                    c.sched.tasks_run, c.net.messages_sent)
        finally:
            c.shutdown()

    a, b = run_off(), run_off()
    tk_rows, mh_rows, slo_doc, _digest, _steps, _msgs = a
    assert tk_rows == [] and mh_rows == []
    assert slo_doc == {"enabled": 0}
    assert a == b, "off-posture same-seed runs must stay bit-identical"


# -- retention: one janitor, three keyspaces -------------------------------

def test_janitor_trims_all_three_keyspaces(sim_seed):
    seed = sim_seed(1704)
    c = SimCluster(seed=seed, metric_history=True)
    try:
        db = c.client("jt")

        async def main():
            # populate all three planes: history + timekeeper via the
            # armed CC loops, the legacy tuple space via log_counters
            col = flow.CounterCollection("proxy")
            for i in range(40):
                tr = db.create_transaction()
                tr.set(b"jt/%d" % (i % 8), b"v")
                await tr.commit()
                if i % 8 == 0:
                    col.counter("transactions_committed").add(1)
                    await metrics_layer.log_counters(db, [col])
                await flow.delay(0.3)
            before_h = await metrics_layer.read_history(
                db, "cluster/txn_committed")
            before_tk = await tk.read_time_map(db)
            before_leg = await metrics_layer.read_series(
                db, "proxy", "transactions_committed")
            assert before_h and before_tk and before_leg

            cutoff_ms = int(flow.now() * 1000) + 1
            h = await metrics_layer.trim_history(db, cutoff_ms)
            leg = await metrics_layer.trim_series(db, cutoff_ms)
            t = await tk.trim_timekeeper(db, flow.now() + 1)
            assert h > 0 and leg > 0 and t > 0, (h, leg, t)

            after_h = await metrics_layer.read_history(
                db, "cluster/txn_committed",
                end_ms=cutoff_ms)
            after_tk = await tk.read_time_map(db, end_ts=flow.now())
            after_leg = await metrics_layer.read_series(
                db, "proxy", "transactions_committed")
            # trims are chunk-granular for history (a straddling chunk
            # survives whole); timekeeper + legacy clear fully
            assert len(after_tk) == 0, after_tk
            assert after_leg == [], after_leg
            assert len(after_h) < len(before_h)
            return True

        assert c.run(main(), timeout_time=600)
    finally:
        c.shutdown()


def test_metrics_janitor_loop_trims(sim_seed):
    seed = sim_seed(1705)
    c = SimCluster(seed=seed, metric_history=True)
    try:
        flow.SERVER_KNOBS.set("metric_retention_seconds", 3.0)
        flow.SERVER_KNOBS.set("timekeeper_retention", 3.0)
        flow.SERVER_KNOBS.set("metric_janitor_interval", 2.0)
        jan = metrics_layer.MetricsJanitor(c)
        jan.start()
        try:
            _db, main = _armed_workload(c, horizon=14.0)
            assert c.run(main(), timeout_time=600)
        finally:
            jan.stop()
        assert jan.rounds > 0
        assert jan.rows_trimmed > 0, "janitor never trimmed a row"
    finally:
        c.shutdown()


# -- incident bundles ------------------------------------------------------

def test_incident_bundle_contents(sim_seed, tmp_path):
    from foundationdb_tpu.tools import incident
    seed = sim_seed(1706)
    out_dir = str(tmp_path / "bundle")
    c = SimCluster(seed=seed, metric_history=True)
    try:
        async def capture(db):
            t1 = flow.now()
            status = await db.get_status()
            verdict = {"state": "breach", "breached": ["commit_p99"]}
            return await incident.capture_bundle(
                db, out_dir, (t1 - 6.0, t1 - 1.0), status_doc=status,
                verdict=verdict, reason="test")

        _db, main = _armed_workload(c, capture=capture)
        manifest = c.run(main(), timeout_time=600)
    finally:
        c.shutdown()

    assert manifest["reason"] == "test"
    w = manifest["window"]
    assert w["version_at_t0"] is not None \
        and w["version_at_t0"] <= w["version_at_t1"]
    assert manifest["samples"] > 0 and manifest["signals"]
    assert manifest["timekeeper_rows"] > 0
    for name in ("manifest.json", "series.json", "timekeeper.json",
                 "status.json", "chaos.json"):
        assert name in manifest["contents"], manifest["contents"]
        assert os.path.exists(os.path.join(out_dir, name))
    series = json.load(open(os.path.join(out_dir, "series.json")))
    t0_ms, t1_ms = int(w["t0"] * 1000), int(w["t1"] * 1000)
    for sig, samples in series.items():
        for ts, _v in samples:
            assert t0_ms <= ts <= t1_ms + 1, (sig, ts, w)
    verdict = json.load(open(os.path.join(
        out_dir, "manifest.json")))["verdict"]
    assert verdict["breached"] == ["commit_p99"]


# -- trace rolling + grouped merge -----------------------------------------

def test_roll_restamps_identity_and_merge_reads_segments(tmp_path):
    path = str(tmp_path / "trace.roller.7.jsonl")
    trace_mod.set_process_identity("roller", pid=7)
    col = trace_mod.TraceCollector(path, roll_size=400)
    try:
        for i in range(30):
            col.emit({"Severity": 10, "Time": float(i), "Type": "Span",
                      "Process": "roller:7", "SpanID": i + 1,
                      "ParentID": None, "ID": f"d{i}",
                      "Location": "RolledWork", "Begin": float(i),
                      "End": i + 0.5})
        col.flush()
        assert col.rolled_files, "roll never triggered"
        # every rolled-fresh segment re-stamps the identity header so
        # each file is self-describing
        with open(path) as fh:
            first = json.loads(fh.readline())
        assert first["Type"] == "ProcessIdentity"
        assert first["ID"] == "roller:7"
    finally:
        col.close()
        trace_mod.clear_process_identity()

    # the whole segment family merges under ONE process, nothing falls
    # back to the local-process bucket
    merged = tracemerge.merge(str(tmp_path))
    assert merged["processes"] == ["roller:7"]
    assert len(merged["chains"]) == 30


def test_tracemerge_segment_numeric_order(tmp_path):
    """.10 sorts AFTER .2 (numeric, not lexicographic), the bare file
    is the newest segment, and an identity header in the OLDEST
    segment covers the whole group."""
    base = "trace.m.1.jsonl"
    names = [f"{base}.{i}" for i in (1, 2, 10)] + [base]
    for n, name in enumerate(names):
        rows = []
        if name.endswith(".1"):
            rows.append({"Type": "ProcessIdentity", "ID": "m:1"})
        rows.append({"Type": "Span", "Process": "m:1",
                     "SpanID": n + 1, "ParentID": None,
                     "ID": f"d{n}", "Location": f"Seg{n}",
                     "Begin": 10.0 + n, "End": 10.5 + n})
        with open(tmp_path / name, "w") as fh:
            for r in rows:
                fh.write(json.dumps(r) + "\n")

    groups = tracemerge.trace_file_groups(str(tmp_path))
    assert len(groups) == 1
    assert [os.path.basename(p) for p in groups[0]] == \
        [f"{base}.1", f"{base}.2", f"{base}.10", base]
    merged = tracemerge.merge(str(tmp_path))
    assert merged["processes"] == ["m:1"]
    assert tracemerge.LOCAL_PROCESS not in merged["processes"]
    assert len(merged["chains"]) == 4
