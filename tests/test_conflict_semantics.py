"""Conflict-resolution semantics: directed cases + randomized cross-backend
parity (ref test model: workloads/ConflictRange.actor.cpp randomized
conflict-or-not checks vs a model, and -r skiplisttest self-check vs
SlowConflictSet, SkipList.cpp:1412-1551)."""

import importlib.util
import random

import pytest

from foundationdb_tpu.models import (
    COMMITTED,
    CONFLICT,
    TOO_OLD,
    BruteForceConflictSet,
    PyConflictSet,
    ResolverTransaction,
    create_conflict_set,
    native_available,
)

MWTLV = 5_000_000  # MAX_WRITE_TRANSACTION_LIFE_VERSIONS (ref: Knobs.cpp:35)


def txn(snapshot, reads=(), writes=()):
    return ResolverTransaction(snapshot, tuple(reads), tuple(writes))


def backends():
    out = [("python", PyConflictSet), ("brute", BruteForceConflictSet)]
    if native_available():
        from foundationdb_tpu.models import NativeConflictSet
        out.append(("native", NativeConflictSet))
    if importlib.util.find_spec("jax") is not None:
        from foundationdb_tpu.models.tpu_resolver import TpuConflictSet
        out.append(("tpu", TpuConflictSet))
    return out


@pytest.fixture(params=[name for name, _ in backends()])
def cs_factory(request):
    mapping = dict(backends())
    return mapping[request.param]


# ---------------------------------------------------------------- directed --
def test_blind_write_commits(cs_factory):
    cs = cs_factory()
    v = cs.resolve([txn(0, writes=[(b"a", b"b")])], 100, 0)
    assert v == [COMMITTED]


def test_read_after_write_conflicts(cs_factory):
    cs = cs_factory()
    cs.resolve([txn(0, writes=[(b"k", b"k\x00")])], 100, 0)
    # snapshot 50 < write version 100 -> conflict
    v = cs.resolve([txn(50, reads=[(b"k", b"k\x00")], writes=[(b"x", b"y")])], 200, 0)
    assert v == [CONFLICT]


def test_read_at_or_after_commit_version_ok(cs_factory):
    cs = cs_factory()
    cs.resolve([txn(0, writes=[(b"k", b"k\x00")])], 100, 0)
    # snapshot == write version: maxVersion > snapshot is FALSE (strict)
    v = cs.resolve([txn(100, reads=[(b"k", b"k\x00")])], 200, 0)
    assert v == [COMMITTED]


def test_disjoint_ranges_no_conflict(cs_factory):
    cs = cs_factory()
    cs.resolve([txn(0, writes=[(b"a", b"b")])], 100, 0)
    v = cs.resolve([txn(50, reads=[(b"b", b"c")])], 200, 0)
    assert v == [COMMITTED]


def test_half_open_boundary(cs_factory):
    """Write [a,b) then read [b,c): end key excluded -> no conflict."""
    cs = cs_factory()
    cs.resolve([txn(0, writes=[(b"a", b"b")])], 100, 0)
    v = cs.resolve(
        [txn(0, reads=[(b"b", b"c")]), txn(0, reads=[(b"a\xff", b"b")])], 200, 0)
    assert v == [COMMITTED, CONFLICT]


def test_range_overlap_conflicts(cs_factory):
    cs = cs_factory()
    cs.resolve([txn(0, writes=[(b"d", b"m")])], 100, 0)
    assert cs.resolve([txn(0, reads=[(b"a", b"e")])], 200, 0) == [CONFLICT]
    assert cs.resolve([txn(50, reads=[(b"l", b"z")])], 300, 0) == [CONFLICT]
    assert cs.resolve([txn(150, reads=[(b"f", b"g")])], 400, 0) == [COMMITTED]
    assert cs.resolve([txn(0, reads=[(b"m", b"z")])], 500, 0) == [COMMITTED]


def test_intra_batch_read_after_earlier_write(cs_factory):
    """Later txn in a batch reading what an earlier txn writes -> conflict."""
    cs = cs_factory()
    v = cs.resolve(
        [txn(0, writes=[(b"k", b"k\x00")]),
         txn(0, reads=[(b"k", b"k\x00")], writes=[(b"z", b"z\x00")])], 100, 0)
    assert v == [COMMITTED, CONFLICT]


def test_intra_batch_order_matters(cs_factory):
    """Earlier txn reading what a LATER txn writes -> no conflict."""
    cs = cs_factory()
    v = cs.resolve(
        [txn(0, reads=[(b"k", b"k\x00")]),
         txn(0, writes=[(b"k", b"k\x00")])], 100, 0)
    assert v == [COMMITTED, COMMITTED]


def test_intra_batch_conflicted_writes_excluded(cs_factory):
    """A conflicted txn's writes must not conflict later txns in the batch
    (ref: checkIntraBatchConflicts skips conflicted txns entirely)."""
    cs = cs_factory()
    cs.resolve([txn(0, writes=[(b"a", b"a\x00")])], 100, 0)
    v = cs.resolve(
        [txn(50, reads=[(b"a", b"a\x00")], writes=[(b"b", b"b\x00")]),  # ext conflict
         txn(150, reads=[(b"b", b"b\x00")])],  # b was NOT actually written
        200, 0)
    assert v == [CONFLICT, COMMITTED]


def test_intra_batch_chain(cs_factory):
    """t0 writes A; t1 reads A (conflict), writes B; t2 reads B commits
    because t1 was removed; t3 reads t2's write C -> conflict."""
    cs = cs_factory()
    v = cs.resolve(
        [txn(0, writes=[(b"a", b"a\x00")]),
         txn(0, reads=[(b"a", b"a\x00")], writes=[(b"b", b"b\x00")]),
         txn(0, reads=[(b"b", b"b\x00")], writes=[(b"c", b"c\x00")]),
         txn(0, reads=[(b"c", b"c\x00")])], 100, 0)
    assert v == [COMMITTED, CONFLICT, COMMITTED, CONFLICT]


def test_too_old(cs_factory):
    cs = cs_factory()
    cs.resolve([txn(0, writes=[(b"a", b"b")])], 10_000_000, 10_000_000 - MWTLV)
    # snapshot below oldestVersion (5e6) with reads -> too old
    v = cs.resolve(
        [txn(4_000_000, reads=[(b"q", b"r")]),
         txn(4_000_000, writes=[(b"q", b"r")]),  # blind write: NOT too old
         txn(6_000_000, reads=[(b"q", b"r")]),   # reads txn1's intra-batch write
         txn(6_000_000, reads=[(b"s", b"t")])],  # disjoint: fine
        11_000_000, 11_000_000 - MWTLV)
    assert v == [TOO_OLD, COMMITTED, CONFLICT, COMMITTED]


def test_too_old_writes_not_merged(cs_factory):
    """A tooOld txn's writes are dropped (ref: addTransaction tooOld branch
    records no ranges)."""
    cs = cs_factory()
    cs.resolve([txn(0, writes=[(b"a", b"b")])], 10_000_000, 10_000_000 - MWTLV)
    cs.resolve([txn(0, reads=[(b"x", b"y")], writes=[(b"k", b"k\x00")])],
               11_000_000, 11_000_000 - MWTLV)  # too old, write dropped
    v = cs.resolve([txn(10_500_000, reads=[(b"k", b"k\x00")])],
                   12_000_000, 12_000_000 - MWTLV)
    assert v == [COMMITTED]


def test_empty_and_inverted_ranges_ignored(cs_factory):
    cs = cs_factory()
    cs.resolve([txn(0, writes=[(b"a", b"z")])], 100, 0)
    v = cs.resolve(
        [txn(0, reads=[(b"m", b"m")]),           # empty
         txn(0, reads=[(b"z", b"a")]),           # inverted
         txn(0, writes=[(b"q", b"q")])], 200, 0)
    assert v == [COMMITTED, COMMITTED, COMMITTED]


def test_empty_transaction_commits(cs_factory):
    cs = cs_factory()
    assert cs.resolve([txn(0)], 100, 0) == [COMMITTED]


def test_initial_version_covers_keyspace(cs_factory):
    """After init at version V, reads below V conflict everywhere
    (ref: clearConflictSet / SkipList(v) header maxVersion)."""
    cs = cs_factory(1000)
    assert cs.resolve([txn(500, reads=[(b"anything", b"anythinh")])], 2000, 0) == [CONFLICT]
    assert cs.resolve([txn(1000, reads=[(b"anything", b"anythinh")])], 2000, 0) == [COMMITTED]


def test_write_versions_accumulate_max(cs_factory):
    """Later write to a sub-range: queries over the larger range see the max."""
    cs = cs_factory()
    cs.resolve([txn(0, writes=[(b"a", b"z")])], 100, 0)
    cs.resolve([txn(100, writes=[(b"m", b"n")])], 200, 0)
    assert cs.resolve([txn(150, reads=[(b"a", b"c")])], 300, 0) == [COMMITTED]
    assert cs.resolve([txn(150, reads=[(b"a", b"z")])], 400, 0) == [CONFLICT]


# -------------------------------------------------------------- randomized --
def _random_key(rng, space, klen):
    return bytes(rng.randrange(space) for _ in range(klen))


def _random_range(rng, space, klen, point_bias=0.5):
    if rng.random() < point_bias:
        k = _random_key(rng, space, klen)
        return (k, k + b"\x00")
    a, b = _random_key(rng, space, klen), _random_key(rng, space, klen)
    if a > b:
        a, b = b, a
    return (a, b + b"\x00") if a == b else (a, b)


def _random_batch(rng, version, oldest, n_txns, space=6, klen=3):
    out = []
    for _ in range(n_txns):
        snapshot = version - rng.randrange(1, int(1.5 * MWTLV)) \
            if rng.random() < 0.15 else version - rng.randrange(0, MWTLV // 2)
        reads = [_random_range(rng, space, klen) for _ in range(rng.randrange(0, 4))]
        writes = [_random_range(rng, space, klen) for _ in range(rng.randrange(0, 4))]
        out.append(txn(max(0, snapshot), reads, writes))
    return out


@pytest.mark.parametrize("seed", [1, 2, 3, 4, 5])
def test_randomized_parity_small_keyspace(seed):
    """Tiny keyspace maximizes collisions; every backend must agree with the
    brute-force model on every verdict of every batch."""
    rng = random.Random(seed)
    impls = {name: cls() for name, cls in backends()}
    version = 0
    for batch_idx in range(60):
        version += rng.randrange(1, 300_000)
        oldest = max(0, version - MWTLV)
        batch = _random_batch(rng, version, oldest, rng.randrange(1, 12))
        results = {name: cs.resolve(batch, version, oldest)
                   for name, cs in impls.items()}
        ref = results["brute"]
        for name, got in results.items():
            assert got == ref, (
                f"backend {name} diverged at batch {batch_idx}: {got} != {ref}\n"
                f"batch={batch}, version={version}, oldest={oldest}")


@pytest.mark.parametrize("seed", [11, 12])
def test_randomized_parity_long_keys(seed):
    """Variable-length keys incl. shared prefixes and \\x00/\\xff bytes."""
    rng = random.Random(seed)
    impls = {name: cls() for name, cls in backends()}

    def rkey():
        base = bytes(rng.choice(b"\x00ab\xff") for _ in range(rng.randrange(0, 5)))
        return base

    def rrange():
        a, b = rkey(), rkey()
        if a > b:
            a, b = b, a
        if a == b:
            b = a + b"\x00"
        return a, b

    version = 0
    for _ in range(40):
        version += rng.randrange(1, 200_000)
        oldest = max(0, version - MWTLV)
        batch = [
            txn(max(0, version - rng.randrange(0, 2 * MWTLV)),
                [rrange() for _ in range(rng.randrange(0, 3))],
                [rrange() for _ in range(rng.randrange(0, 3))])
            for _ in range(rng.randrange(1, 8))
        ]
        results = {name: cs.resolve(batch, version, oldest)
                   for name, cs in impls.items()}
        ref = results["brute"]
        for name, got in results.items():
            assert got == ref, f"{name} diverged: {got} != {ref}\n{batch}"


def test_native_backend_loads():
    assert native_available(), "native C++ backend failed to build/load"
    cs = create_conflict_set("native")
    assert cs.resolve([txn(0, writes=[(b"a", b"b")])], 100, 0) == [COMMITTED]
