"""Tests for the deterministic actor runtime (ref test model: flow/UnitTest.h
TEST_CASEs and fdbrpc/dsltest.actor.cpp flow DSL tests)."""

import pytest

from foundationdb_tpu import flow
from foundationdb_tpu.flow import (
    ActorCancelled,
    AsyncVar,
    FdbError,
    FlowLock,
    Future,
    NotifiedVersion,
    Promise,
    PromiseStream,
    Scheduler,
    TaskPriority,
    all_of,
    error,
    first_of,
    set_scheduler,
    timeout,
)


@pytest.fixture()
def sched():
    s = Scheduler()
    set_scheduler(s)
    yield s
    set_scheduler(None)


def test_future_basic():
    p = Promise()
    seen = []
    p.future.on_ready(lambda f: seen.append(f.get()))
    p.send(42)
    assert seen == [42]
    assert p.future.get() == 42


def test_future_error():
    p = Promise()
    p.send_error(error("not_committed"))
    with pytest.raises(FdbError) as ei:
        p.future.get()
    assert ei.value.code == 1020


def test_broken_promise():
    p = Promise()
    p.drop()
    assert p.future.is_error
    assert p.future.exception().code == 1100


def test_actor_returns_value(sched):
    async def actor():
        return 7

    t = sched.spawn(actor())
    assert sched.run(until=t) == 7


def test_actor_awaits_promise(sched):
    p = Promise()

    async def consumer():
        v = await p.future
        return v + 1

    async def producer():
        await flow.delay(1.0)
        p.send(10)

    t = sched.spawn(consumer())
    sched.spawn(producer())
    assert sched.run(until=t) == 11
    assert sched.now() == 1.0


def test_virtual_time_ordering(sched):
    log = []

    async def at(t, label):
        await flow.delay(t)
        log.append((label, sched.now()))

    done = all_of([sched.spawn(at(3.0, "c")), sched.spawn(at(1.0, "a")),
                   sched.spawn(at(2.0, "b"))])
    sched.run(until=done)
    assert log == [("a", 1.0), ("b", 2.0), ("c", 3.0)]


def test_priority_ordering(sched):
    """Higher-priority ready tasks run first (ref: flow/network.h priorities)."""
    log = []

    async def lo():
        log.append("lo")

    async def hi():
        log.append("hi")

    sched.spawn(lo(), TaskPriority.LOW_PRIORITY)
    sched.spawn(hi(), TaskPriority.WRITE_SOCKET)
    sched.run()
    assert log == ["hi", "lo"]


def test_error_propagates_through_actor(sched):
    async def failing():
        raise error("io_error")

    async def caller():
        try:
            await sched.spawn(failing())
        except FdbError as e:
            return e.code

    t = sched.spawn(caller())
    assert sched.run(until=t) == 1510


def test_cancel_actor(sched):
    state = []

    async def victim():
        try:
            await flow.delay(100.0)
        except ActorCancelled:
            state.append("cancelled")
            raise

    t = sched.spawn(victim())
    async def canceller():
        await flow.delay(1.0)
        t.cancel()

    sched.spawn(canceller())
    sched.run()
    assert state == ["cancelled"]
    assert t.is_error


def test_timeout_fires(sched):
    p = Promise()

    async def waiter():
        return await timeout(p.future, 5.0, default="timed")

    t = sched.spawn(waiter())
    assert sched.run(until=t) == "timed"
    assert sched.now() == 5.0


def test_timeout_beaten(sched):
    p = Promise()

    async def waiter():
        return await timeout(p.future, 5.0, default="timed")

    async def sender():
        await flow.delay(1.0)
        p.send("won")

    t = sched.spawn(waiter())
    sched.spawn(sender())
    assert sched.run(until=t) == "won"


def test_first_of(sched):
    a, b = Promise(), Promise()

    async def waiter():
        return await first_of(a.future, b.future)

    async def sender():
        await flow.delay(1.0)
        b.send("bee")

    t = sched.spawn(waiter())
    sched.spawn(sender())
    assert sched.run(until=t) == (1, "bee")


def test_notified_version(sched):
    nv = NotifiedVersion(0)
    log = []

    async def waiter(v):
        await nv.when_at_least(v)
        log.append(v)

    done = all_of([sched.spawn(waiter(5)), sched.spawn(waiter(3)),
                   sched.spawn(waiter(10))])

    async def setter():
        await flow.delay(0.1)
        nv.set(4)
        await flow.delay(0.1)
        nv.set(10)

    sched.spawn(setter())
    sched.run(until=done)
    assert log == [3, 5, 10]


def test_promise_stream(sched):
    ps = PromiseStream()
    got = []

    async def consumer():
        while True:
            try:
                got.append(await ps.stream.pop())
            except FdbError as e:
                assert e.code == 1  # end_of_stream
                return

    async def producer():
        for i in range(5):
            ps.send(i)
            await flow.delay(0.01)
        ps.close()

    t = sched.spawn(consumer())
    sched.spawn(producer())
    sched.run(until=t)
    assert got == [0, 1, 2, 3, 4]


def test_async_var(sched):
    av = AsyncVar(1)

    async def watcher():
        await av.on_change()
        return av.get()

    async def setter():
        await flow.delay(0.5)
        av.set(99)

    t = sched.spawn(watcher())
    sched.spawn(setter())
    assert sched.run(until=t) == 99


def test_flow_lock(sched):
    lock = FlowLock(2)
    order = []

    async def worker(i):
        await lock.take()
        order.append(("start", i))
        await flow.delay(1.0)
        order.append(("end", i))
        lock.release()

    done = all_of([sched.spawn(worker(i)) for i in range(4)])
    sched.run(until=done)
    # only 2 concurrent: workers 2,3 start after 0,1 finish
    assert order[:2] == [("start", 0), ("start", 1)]
    assert set(order[2:4]) == {("end", 0), ("end", 1)}


def test_deadlock_detection(sched):
    p = Promise()

    async def stuck():
        await p.future

    t = sched.spawn(stuck())
    with pytest.raises(FdbError):
        sched.run(until=t)


def test_determinism_same_seed():
    """Same seed => identical execution trace (ref: §4 determinism oracle)."""

    def run_once(seed):
        flow.set_seed(seed)
        s = Scheduler()
        set_scheduler(s)
        log = []

        async def noisy(i):
            for _ in range(5):
                await flow.delay(flow.g_random.random01())
                log.append((i, round(s.now(), 9)))

        done = all_of([s.spawn(noisy(i)) for i in range(4)])
        s.run(until=done)
        set_scheduler(None)
        return log

    assert run_once(1234) == run_once(1234)
    assert run_once(1234) != run_once(99)


def test_flow_lock_cancelled_waiter_no_leak(sched):
    """A cancelled queued taker must not be granted (and leak) permits."""
    lock = FlowLock(1)
    got = []

    async def holder():
        await lock.take()
        await flow.delay(1.0)
        lock.release()

    async def waiter(i):
        await lock.take()
        got.append(i)
        lock.release()

    sched.spawn(holder())
    victim = sched.spawn(waiter(1))
    survivor = sched.spawn(waiter(2))

    async def canceller():
        await flow.delay(0.5)
        victim.cancel()

    sched.spawn(canceller())
    sched.run(until=survivor)
    assert got == [2]
    assert lock.active == 0


def test_delay_priority_resumes_waiter(sched):
    """delay(0, prio) resumes its waiter at the delay's priority (ref: delay(t, taskID))."""
    log = []

    async def a():
        await flow.delay(0.0, TaskPriority.LOW_PRIORITY)
        log.append("low")

    async def b():
        await flow.delay(0.0, TaskPriority.WRITE_SOCKET)
        log.append("high")

    done = all_of([sched.spawn(a()), sched.spawn(b())])
    sched.run(until=done)
    assert log == ["high", "low"]


def test_actor_collection_reaps():
    from foundationdb_tpu.flow import ActorCollection, Scheduler, set_scheduler
    s = Scheduler()
    set_scheduler(s)
    ac = ActorCollection()

    async def quick(i):
        return i

    for i in range(100):
        ac.add(s.spawn(quick(i)))
    s.run()
    assert ac.tasks == []
    set_scheduler(None)


def test_cancel_one_waiter_of_shared_future(sched):
    """Cancelling one waiter must not cancel the shared producer (ref: flow
    cancels only when the last reference drops)."""
    async def producer():
        await flow.delay(2.0)
        return "product"

    p = sched.spawn(producer())

    async def consumer():
        return await p

    a = sched.spawn(consumer())
    b = sched.spawn(consumer())

    async def canceller():
        await flow.delay(1.0)
        a.cancel()

    sched.spawn(canceller())
    assert sched.run(until=b) == "product"
    assert not p.is_error


def test_cancel_all_cancels_every_member(sched):
    from foundationdb_tpu.flow import ActorCollection
    ac = ActorCollection()
    states = []

    async def member(i):
        try:
            await flow.delay(100.0)
        except ActorCancelled:
            states.append(i)
            raise

    for i in range(3):
        ac.add(sched.spawn(member(i)))

    async def canceller():
        await flow.delay(1.0)
        ac.cancel_all()

    sched.spawn(canceller())
    sched.run()
    assert sorted(states) == [0, 1, 2]


def test_run_timeout_does_not_execute_past_deadline(sched):
    fired = []

    async def late():
        await flow.delay(10.0)
        fired.append("late")

    sched.spawn(late())
    with pytest.raises(FdbError) as ei:
        sched.run(until=Future(), timeout_time=5.0)
    assert ei.value.code == 1004
    assert fired == []
    assert sched.now() == 5.0


def test_stream_value_survives_lost_race_with_deadline(sched):
    """A value delivered to a pop() waiter that lost a first_of race must
    be re-queued, not dropped (ADVICE r1: the proxy batcher's
    first_of(nxt, deadline) pattern lost commit requests that tied with
    the batch deadline)."""
    ps = PromiseStream()
    got = []

    async def batcher():
        # round 1: deadline wins; the pending pop is abandoned
        nxt = ps.stream.pop()
        deadline = flow.delay(1.0)
        idx, _ = await first_of(nxt, deadline)
        assert idx == 1  # deadline fired first
        # a value arrives AFTER the deadline won, into the abandoned waiter
        # (the producer below sends at t=2.0)
        await flow.delay(2.0)
        # round 2: the value must still be obtainable
        got.append(await ps.stream.pop())

    async def producer():
        await flow.delay(2.0)
        ps.send("precious")

    t = sched.spawn(batcher())
    sched.spawn(producer())
    sched.run(until=t)
    assert got == ["precious"]


def test_timeout_abandons_stream_waiter(sched):
    """timeout(stream.pop(), ...) hitting the deadline must not eat the
    next value sent into the stream."""
    ps = PromiseStream()

    async def consumer():
        v = await timeout(ps.stream.pop(), 0.5, default="none")
        assert v == "none"
        await flow.delay(1.0)  # value arrives at t=1.0 (after abandon)
        return await ps.stream.pop()

    async def producer():
        await flow.delay(1.0)
        ps.send(41)

    t = sched.spawn(consumer())
    sched.spawn(producer())
    assert sched.run(until=t) == 41


def test_reused_pop_waiter_after_abandon_still_delivers(sched):
    """pop() re-adopts a previously abandoned pending waiter; direct
    delivery into it must work again."""
    ps = PromiseStream()

    async def consumer():
        v = await timeout(ps.stream.pop(), 0.5, default=None)
        assert v is None
        return await ps.stream.pop()  # re-adopted waiter, direct delivery

    async def producer():
        await flow.delay(1.0)
        ps.send("direct")

    t = sched.spawn(consumer())
    sched.spawn(producer())
    assert sched.run(until=t) == "direct"


def test_knob_reset_in_place():
    from foundationdb_tpu.flow import SERVER_KNOBS, reset_server_knobs
    old = SERVER_KNOBS.versions_per_second
    got = reset_server_knobs()
    assert got is SERVER_KNOBS
    assert SERVER_KNOBS.versions_per_second == old


def test_thread_pool_offload():
    """IThreadPool (ref: flow/IThreadPool.h + AsyncFileEIO's pool):
    blocking work runs on worker threads; results and errors arrive as
    futures resolved ON the scheduler thread; the loop keeps running
    while a worker blocks."""
    import threading
    import time as _time

    from foundationdb_tpu import flow
    from foundationdb_tpu.flow.threadpool import ThreadPool

    sched = flow.Scheduler(virtual=False)   # wall clock: real threads
    flow.set_scheduler(sched)
    try:
        pool = ThreadPool(n_threads=2, name="testpool")
        pool.start()
        main_thread = threading.get_ident()
        seen = {}

        async def main():
            def work(x):
                assert threading.get_ident() != main_thread
                _time.sleep(0.15)
                return x * 2

            # two blocking tasks overlap on the pool while the loop
            # stays live: serial execution is >= 0.3s, so finishing
            # well under that proves concurrency with generous margin
            t0 = _time.perf_counter()
            a = pool.run(work, 21)
            b = pool.run(work, 100)
            ra = await a
            rb = await b
            assert (ra, rb) == (42, 200)
            assert _time.perf_counter() - t0 < 0.28

            def boom():
                raise RuntimeError("disk exploded")
            try:
                await pool.run(boom)
            except flow.FdbError as e:
                seen["err"] = e.name
            return True

        task = flow.spawn(main(), name="poolMain")
        assert sched.run(until=task, timeout_time=None) is True
        assert seen["err"] == "io_error"
        pool.close()
    finally:
        flow.set_scheduler(None)
