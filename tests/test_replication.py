"""Storage replication: same-tag replica teams, replica-read failover,
and per-replica log pops (ref: §2.6 item 6 replica read parallelism /
fdbrpc/LoadBalance.actor.h; teams in DataDistribution.actor.cpp:539)."""

from foundationdb_tpu import flow
from foundationdb_tpu.client import run_transaction
from foundationdb_tpu.server import SimCluster


def test_replicas_serve_identical_data_and_failover():
    """Reads keep working when one replica of a shard dies — WITHOUT an
    epoch recovery (replica failover, not healing)."""
    c = SimCluster(seed=1301, durable=True, storage_replicas=2,
                   auto_reboot=False)
    try:
        db = c.client()

        async def main():
            async def body(tr):
                for i in range(60):
                    tr.set(b"rep%02d" % i, b"v%d" % i)
            await run_transaction(db, body)
            # both replicas converge to the same data
            shard = c.cc.dbinfo.get().storages[0]
            objs = [c.cc._storage_objs[r.name] for r in shard.replicas]
            await flow.delay(0.5)
            views = []
            for o in objs:
                v = o.version.get()
                views.append(o.data.get_range(b"", b"\xff", v, 1000))
            assert views[0] == views[1] and len(views[0]) == 60
            epoch_before = c.cc.dbinfo.get().epoch

            # kill ONE replica: reads fail over to the survivor
            c.net.kill(objs[0].process)
            for i in range(60):
                async def rbody(tr, i=i):
                    assert await tr.get(b"rep%02d" % i) == b"v%d" % i
                await run_transaction(db, rbody, max_retries=200)
            # storage death is not a transaction-subsystem failure
            assert c.cc.dbinfo.get().epoch == epoch_before
            return True

        assert c.run(main(), timeout_time=600)
    finally:
        c.shutdown()


def test_lagging_replica_is_not_starved_by_pops():
    """The TLog frees a tag's records only once EVERY replica has
    popped past them: clog one replica's machine and verify it still
    catches up afterwards (per-replica pop bookkeeping)."""
    c = SimCluster(seed=1303, durable=True, storage_replicas=2)
    try:
        db = c.client()

        async def main():
            await db.info()   # wait for recruitment
            shard = c.cc.dbinfo.get().storages[0]
            objs = [c.cc._storage_objs[r.name] for r in shard.replicas]
            lag_machine = objs[1].process.machine
            # clog the laggard's links to everything for a while
            for i in range(c.n_workers):
                c.net.clog_pair(lag_machine, f"w{i}", 3.0)
            c.net.clog_pair(lag_machine, "cc", 3.0)

            async def body(tr):
                for i in range(40):
                    tr.set(b"lag%02d" % i, b"v%d" % i)
            await run_transaction(db, body)
            # wait long enough for durability + pops on the fast replica
            await flow.delay(5.0)
            # the laggard catches up: its view converges
            for _ in range(60):
                v = objs[1].version.get()
                rows = objs[1].data.get_range(b"lag", b"lah", v, 100)
                if len(rows) == 40:
                    break
                await flow.delay(0.5)
            assert len(rows) == 40, len(rows)
            return True

        assert c.run(main(), timeout_time=600)
    finally:
        c.shutdown()


def test_replicated_cluster_survives_attrition():
    c = SimCluster(seed=1307, durable=True, storage_replicas=2,
                   n_logs=2, n_workers=6, buggify=True)
    try:
        db = c.client()

        async def main():
            acked = {}
            for i in range(10):
                async def body(tr, i=i):
                    tr.set(b"a%02d" % i, b"v%d" % i)
                await run_transaction(db, body, max_retries=300)
                acked[b"a%02d" % i] = b"v%d" % i
                if i == 3:
                    c.kill_role("storage")
                if i == 6:
                    c.kill_role("tlog")

            async def check(tr):
                got = dict(await tr.get_range(b"a", b"b"))
                assert got == acked, (len(got), len(acked))
            await run_transaction(db, check, max_retries=300)
            return True

        assert c.run(main(), timeout_time=900)
    finally:
        c.shutdown()


def test_backup_requests_mask_a_slow_replica():
    """Load balance (ref: fdbrpc/LoadBalance.actor.h): when the chosen
    replica is slow (clogged links), a duplicate request to the other
    replica answers within the backup window — far sooner than the 5s
    request timeout — and the latency model steers later reads away
    from the slow replica."""
    c = SimCluster(seed=1304, storage_replicas=2)
    try:
        db = c.client()

        async def main():
            async def seed(tr):
                for i in range(10):
                    tr.set(b"bk%02d" % i, b"v%d" % i)
            await run_transaction(db, seed)

            shard = (await db.info()).storages[0]
            objs = [c.cc._storage_objs[r.name] for r in shard.replicas]
            slow = objs[0]
            slow_machine = slow.process.machine
            client_machine = db.process.machine
            # only the CLIENT'S link to the slow replica clogs: pulls
            # and peer traffic stay healthy, so this is purely a read-
            # latency event, not a failure
            c.net.clog_pair(client_machine, slow_machine, 30.0)

            t0 = flow.now()
            async def read_all(tr):
                for i in range(10):
                    assert await tr.get(b"bk%02d" % i) == b"v%d" % i
            await run_transaction(db, read_all)
            elapsed = flow.now() - t0
            # without backup requests the first read against the slow
            # replica eats the full 5s REQUEST_TIMEOUT
            assert elapsed < 4.0, elapsed

            # the model now prefers the healthy replica outright: the
            # abandoned slow request recorded a penalty sample, so both
            # replicas are modeled and the healthy one sorts first
            ema = db._latency_ema
            healthy = shard.replicas[1].name
            slow_name = shard.replicas[0].name
            assert healthy in ema and slow_name in ema, ema
            assert ema[healthy] < ema[slow_name], ema
            return True

        assert c.run(main(), timeout_time=300)
    finally:
        c.shutdown()
