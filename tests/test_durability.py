"""Durability layer: sim files with power-loss semantics, the DiskQueue
WAL, and the memory KV engine (ref: fdbrpc/AsyncFileNonDurable.actor.h,
fdbserver/DiskQueue.actor.cpp, KeyValueStoreMemory.actor.cpp; test
strategy: crash-recovery invariants under randomized kills)."""

import pytest

from foundationdb_tpu import flow
from foundationdb_tpu.rpc import SimNetwork
from foundationdb_tpu.server.diskqueue import DiskQueue
from foundationdb_tpu.server.kvstore import KeyValueStoreMemory


@pytest.fixture
def sim():
    flow.set_seed(0)
    s = flow.Scheduler(virtual=True)
    flow.set_scheduler(s)
    net = SimNetwork(s, flow.g_random)
    yield s, net
    flow.set_scheduler(None)


def drive(s, coro, timeout=60):
    t = s.spawn(coro)
    return s.run(until=t, timeout_time=timeout)


def test_simfile_sync_and_power_loss(sim):
    s, net = sim
    disk = net.disk("m1")

    async def main():
        f = disk.open("f")
        await f.write(0, b"hello")
        await f.sync()
        await f.write(5, b"world")  # unsynced
        assert await f.read(0, 10) == b"helloworld"  # own writes visible
        return True

    assert drive(s, main())
    disk.power_loss(flow.g_random)
    f2 = disk.open("f")

    async def check():
        data = await f2.read(0, 10)
        # synced prefix always survives; the unsynced tail may or may not
        assert data[:5] == b"hello"
        assert data in (b"hello", b"helloworld")
        return True

    assert drive(s, check())


def test_diskqueue_roundtrip_and_pop(sim):
    s, net = sim
    disk = net.disk("m1")

    async def main():
        dq = DiskQueue(disk, "q", file_size_limit=256)
        assert await dq.recover() == []
        for i in range(20):
            await dq.push(b"rec%03d" % i)
        await dq.commit()
        dq.pop(9)  # discard the first 10
        dq2 = DiskQueue(disk, "q", file_size_limit=256)
        got = await dq2.recover()
        # un-popped records must all be there; popped ones may survive
        # until physical reclaim, but the recovered list is a contiguous
        # run ending at the last push
        assert got[-10:] == [b"rec%03d" % i for i in range(10, 20)]
        return True

    assert drive(s, main())


def test_diskqueue_commit_survives_power_loss(sim):
    s, net = sim
    disk = net.disk("m1")

    async def write_phase():
        dq = DiskQueue(disk, "q")
        await dq.recover()
        for i in range(10):
            await dq.push(b"committed%02d" % i)
        await dq.commit()
        for i in range(5):
            await dq.push(b"unsynced%02d" % i)  # never committed
        return True

    assert drive(s, write_phase())
    disk.power_loss(flow.g_random)

    async def recover_phase():
        dq = DiskQueue(disk, "q")
        got = await dq.recover()
        committed = [b"committed%02d" % i for i in range(10)]
        # all committed records survive, in order, as a prefix
        assert got[:10] == committed
        # anything beyond is a contiguous prefix of the unsynced pushes
        assert got[10:] == [b"unsynced%02d" % i for i in range(len(got) - 10)]
        return True

    assert drive(s, recover_phase())


@pytest.mark.parametrize("seed", [1, 2, 3, 4, 5, 6])
def test_diskqueue_randomized_crash_recovery(seed):
    """Property: after any crash, recovery yields a contiguous prefix of
    everything pushed that includes at least every committed record."""
    flow.set_seed(seed)
    s = flow.Scheduler(virtual=True)
    flow.set_scheduler(s)
    try:
        net = SimNetwork(s, flow.g_random)
        disk = net.disk("m")
        rng = flow.g_random
        pushed = []
        committed_count = [0]
        popped = [-1]

        async def phase():
            dq = DiskQueue(disk, "q", file_size_limit=512)
            await dq.recover()
            # lost unsynced pushes: their seqs will be reused — forget them
            del pushed[dq.next_seq:]
            committed_count[0] = min(committed_count[0], len(pushed))
            for _ in range(rng.random_int(5, 40)):
                r = rng.random01()
                if r < 0.55:
                    payload = bytes([rng.random_int(65, 90)]) * rng.random_int(1, 40)
                    await dq.push(payload)
                    pushed.append(payload)
                elif r < 0.8:
                    await dq.commit()
                    committed_count[0] = len(pushed)
                elif dq.records:
                    k = rng.random_int(0, len(dq.records))
                    seq = dq.records[k][0]
                    dq.pop(seq)
                    popped[0] = max(popped[0], seq)
            return True

        for _round in range(4):
            t = s.spawn(phase())
            assert s.run(until=t, timeout_time=600)
            disk.power_loss(flow.g_random)  # crash between phases

        async def final_check():
            dq = DiskQueue(disk, "q", file_size_limit=512)
            await dq.recover()
            recs = dq.records
            # every surviving record matches what was pushed at that seq,
            # and seqs are contiguous
            for j, (seq, payload) in enumerate(recs):
                assert seq == recs[0][0] + j, "seq gap in recovery"
                assert payload == pushed[seq], f"payload mismatch at {seq}"
            # every committed, unpopped record survived
            assert dq.next_seq >= committed_count[0], (
                f"lost committed records: next_seq {dq.next_seq}, "
                f"committed {committed_count[0]}")
            if recs:
                assert recs[0][0] <= max(popped[0] + 1, 0)
            return True

        t = s.spawn(final_check())
        assert s.run(until=t, timeout_time=600)
    finally:
        flow.set_scheduler(None)


def test_kvstore_recover_and_snapshot(sim):
    s, net = sim
    disk = net.disk("m1")

    async def main():
        kv = KeyValueStoreMemory(disk, "sq", snapshot_threshold=512)
        await kv.recover()
        for i in range(50):
            kv.set(b"k%03d" % i, b"v%03d" % i)
            await kv.commit()  # many commits -> snapshot threshold crossed
        kv.clear_range(b"k010", b"k020")
        await kv.commit()
        return True

    assert drive(s, main())
    disk.power_loss(flow.g_random)

    async def check():
        kv = KeyValueStoreMemory(disk, "sq")
        await kv.recover()
        assert kv.get(b"k005") == b"v005"
        assert kv.get(b"k015") is None  # cleared
        rng = kv.get_range(b"k", b"l")
        assert len(rng) == 40
        assert kv.get_range(b"k000", b"k003", reverse=True) == [
            (b"k002", b"v002"), (b"k001", b"v001"), (b"k000", b"v000")]
        return True

    assert drive(s, check())
