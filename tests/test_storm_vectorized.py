"""Simulator hot-path overhaul (ISSUE 12): vectorized storm schedules,
pooled client actors, the coalesced-timer helper, the lean timer path,
and the typed bare-payload envelopes.

What is pinned here, in order:

- **Schedule determinism**: a storm's vectorized schedule is a pure
  function of its seed — drawing it twice (same-seeded flow RNG)
  yields byte-identical arrival/key/flag arrays, a different seed a
  different schedule, and searchsorted inversion matches the scalar
  zipf_rank bisect rank-for-rank.
- **Same-seed replay for every refactored storm**: two fresh clusters
  on one seed produce identical outcome counts, identical keyspace
  digests, identical run-loop step and network message counts —
  OpenLoopStorm, ContentionStorm, OverloadStorm (the PR 7 oracle,
  re-pinned across the vectorized/pooled code path).

  Re-baseline note (the one-time schedule move): the pre-refactor
  per-arrival path drew every decision from the SHARED flow RNG,
  interleaved with the network's latency draws — committed as
  SIMPERF_r01.json's deterministic columns (open_loop 30095 steps /
  3923 msgs, contention 52730 / 8624, overload 83374 / 4845 at the
  same seeds). A schedule drawn up front in one pass cannot reproduce
  that interleaving by construction, so those recorded values moved
  once (r02 records the new ones); what this file pins is the oracle
  that must NEVER move again — same seed => same storm, bit-exact.
- **Pooled client actors**: the worker pool reuses at most
  `max_inflight` workers across all arrivals (spawn count == peak
  concurrency, not arrival count), sheds at saturation exactly like
  the old inflight cap, keeps a fixed small task-name set that folds
  into one `<label>-*` family, and propagates worker failures from
  drain() like the old wait_for_all did.
- **WakeSignal + call_at**: the coalesced-timer helper wakes parked
  loops without busy ticking, and call_at callbacks fire in (time,
  seq) order interleaved with ordinary delay() timers.
- **Typed bare payloads**: armed message accounting REJECTS a
  None-payload delivery (lint assert), and a full storm under the
  armed plane shows zero `NoneType` rows.
- **Client multiplexing**: an OverloadStorm block of
  `clients_per_arrival` logical clients walks the whole population
  (distinct_clients == n_clients once draws cover the pools) and
  charges the proxy's admission accounting for the full block weight
  through the GRV wire request.
"""

import pytest

from foundationdb_tpu import flow
from foundationdb_tpu.flow.scheduler import Scheduler, WakeSignal
from foundationdb_tpu.rpc import SimNetwork
from foundationdb_tpu.server import SimCluster
from foundationdb_tpu.server.chaos import database_digest
from foundationdb_tpu.server.workloads import (ClientActorPool,
                                               ContentionStorm,
                                               OpenLoopStorm,
                                               OverloadStorm,
                                               make_zipf_cdf, zipf_rank)


# -- schedule determinism -------------------------------------------------

def _openloop_schedule(seed):
    flow.set_seed(seed)
    storm = OpenLoopStorm([], flow.g_random, duration=2.0, rate=100.0,
                          burst_rate=400.0, burst_start=0.5,
                          burst_len=0.5, repairable_fraction=0.25)
    return storm.draw_schedule()


def test_schedule_is_pure_function_of_seed():
    a = _openloop_schedule(1234)
    b = _openloop_schedule(1234)
    assert a == b, "same seed must draw the identical schedule"
    c = _openloop_schedule(4321)
    assert a[0] != c[0], "a different seed must move the schedule"
    times, keys, batch, repair = a
    assert len(times) == len(keys) == len(batch) == len(repair)
    assert len(times) > 100          # ~100/s * 2s + burst
    assert all(0.0 <= t < 2.0 for t in times)
    assert all(times[i] < times[i + 1] for i in range(len(times) - 1))
    assert any(batch) and not all(batch)
    assert any(repair) and not all(repair)


def test_repair_fraction_leaves_arrivals_untouched():
    """Arming automatic_repair must not move the arrival/key/priority
    schedule (the repair flags are drawn LAST)."""
    flow.set_seed(77)
    off = OpenLoopStorm([], flow.g_random, duration=2.0,
                        rate=120.0).draw_schedule()
    flow.set_seed(77)
    on = OpenLoopStorm([], flow.g_random, duration=2.0, rate=120.0,
                       repairable_fraction=0.5).draw_schedule()
    assert on[0] == off[0] and on[1] == off[1] and on[2] == off[2]
    assert not any(off[3]) and any(on[3])


def test_searchsorted_matches_scalar_zipf_rank():
    import numpy as np
    cdf = make_zipf_cdf(64, 1.2)
    g = np.random.Generator(np.random.PCG64(9))
    us = g.random(2000)
    vec = np.searchsorted(np.asarray(cdf), us, side="left").tolist()
    for u, r in zip(us.tolist(), vec):
        assert r == zipf_rank(cdf, u), (u, r)


def test_overload_schedule_modes():
    flow.set_seed(5150)
    classic = OverloadStorm([], flow.g_random, duration=2.0,
                            n_clients=1000).draw_schedule()
    times, abusive, keys, batch, cids = classic
    assert cids is not None and len(cids) == len(times)
    n_ab = max(1, 1000 // 10)
    for i, cid in enumerate(cids):
        if abusive[i]:
            assert 0 <= cid < n_ab
        else:
            assert n_ab <= cid < 1000
    flow.set_seed(5150)
    mux = OverloadStorm([], flow.g_random, duration=2.0, n_clients=1000,
                        clients_per_arrival=8).draw_schedule()
    assert mux[4] is None            # cursor mode: no cid draws
    assert mux[0] == times           # arrivals unchanged by multiplexing


# -- same-seed replay across the refactored storms ------------------------

def _run_openloop(seed):
    c = SimCluster(seed=seed, durable=True)
    try:
        dbs = [c.client(f"ol{i}") for i in range(3)]
        storm = OpenLoopStorm(dbs, flow.g_random, duration=2.0,
                              rate=60.0, burst_rate=250.0,
                              burst_start=0.5, burst_len=0.5,
                              max_inflight=128)

        async def main():
            rep = await storm.run()
            rep["digest"] = await database_digest(dbs[0])
            return rep

        rep = c.run(main(), timeout_time=600)
        rep["net_messages"] = c.net.messages_sent
        rep["sched_steps"] = c.sched.tasks_run
        return rep
    finally:
        c.shutdown()


def _run_contention(seed):
    c = SimCluster(seed=seed, durable=True)
    try:
        dbs = [c.client(f"ct{i}") for i in range(3)]
        storm = ContentionStorm(dbs, flow.g_random, duration=2.0,
                                rate=80.0)

        async def main():
            rep = await storm.run()
            rep["hot_total"] = await storm.read_hot_total(dbs[0])
            rep["digest"] = await database_digest(dbs[0])
            return rep

        rep = c.run(main(), timeout_time=600)
        rep["net_messages"] = c.net.messages_sent
        rep["sched_steps"] = c.sched.tasks_run
        return rep
    finally:
        c.shutdown()


def _run_overload(seed, armed_stats=False, knobs=None, duration=2.0,
                  **kw):
    c = SimCluster(seed=seed, durable=True, n_proxies=2)
    # knob overrides go AFTER construction: SimCluster re-initializes
    # SERVER_KNOBS in __init__
    for k, v in (knobs or {}).items():
        flow.SERVER_KNOBS.set(k, v)
    if armed_stats:
        c.sched.start_task_stats()
        c.net.arm_message_stats()
    try:
        dbs = [c.client(f"ov{i}") for i in range(4)]
        storm = OverloadStorm(dbs, flow.g_random, duration=duration,
                              fair_rate=40.0, abusive_rate=120.0,
                              n_clients=5000, **kw)

        async def main():
            rep = await storm.run()
            rep["digest"] = await database_digest(dbs[0])
            return rep

        rep = c.run(main(), timeout_time=600)
        rep["net_messages"] = c.net.messages_sent
        rep["sched_steps"] = c.sched.tasks_run
        if armed_stats:
            rep["msg_types"] = dict(c.net.msg_stats)
        return rep
    finally:
        c.shutdown()


_REPLAY_KEYS = ("issued", "completed", "conflicted", "shed",
                "digest", "net_messages", "sched_steps")


def _slice(rep, keys=_REPLAY_KEYS):
    return {k: rep[k] for k in keys if k in rep}


def test_openloop_same_seed_replay(sim_seed):
    seed = sim_seed(2801)
    a, b = _run_openloop(seed), _run_openloop(seed)
    assert _slice(a) == _slice(b), (seed, _slice(a), _slice(b))
    assert a["completed"] > 0


def test_contention_same_seed_replay(sim_seed):
    seed = sim_seed(2802)
    keys = _REPLAY_KEYS + ("committed", "conflicts", "attempts",
                           "hot_total")
    a, b = _run_contention(seed), _run_contention(seed)
    assert _slice(a, keys) == _slice(b, keys), seed
    assert a["committed"] > 0
    # the goodput bit-exactness oracle survives pooling: hot-key sum
    # equals committed (modulo deliberately unsettled unknowns)
    assert a["committed"] <= a["hot_total"] \
        <= a["committed"] + a["unknown"], a


def test_overload_same_seed_replay_and_armed_equivalence(sim_seed):
    seed = sim_seed(2803)
    keys = _REPLAY_KEYS + ("distinct_clients",)
    a, b = _run_overload(seed), _run_overload(seed)
    assert _slice(a, keys) == _slice(b, keys), seed
    # arming the attribution plane must not move a single sim event —
    # and the armed table must show ONLY typed message rows
    armed = _run_overload(seed, armed_stats=True)
    assert _slice(armed, keys) == _slice(a, keys), seed
    assert armed["msg_types"], armed
    assert not any("NoneType" in t for t in armed["msg_types"]), \
        sorted(armed["msg_types"])


# -- pooled client actors -------------------------------------------------

def _pool_env():
    flow.set_seed(31)
    s = Scheduler(virtual=True)
    flow.set_scheduler(s)
    return s


def test_pool_reuses_workers_and_sheds_at_limit():
    s = _pool_env()
    try:
        ran = []

        async def job(i, hold):
            ran.append(i)
            if hold:
                await flow.delay(1.0)

        pool = ClientActorPool(job, limit=2, label="pt")

        async def main():
            # two held jobs fill the pool; the third arrival sheds
            assert pool.dispatch((0, True))
            assert pool.dispatch((1, True))
            assert not pool.dispatch((2, True)), "limit must shed"
            await flow.delay(1.5)      # both workers park idle
            # sequential jobs REUSE the two workers
            for i in range(3, 9):
                assert pool.dispatch((i, False))
                await flow.delay(0.01)
            await pool.drain()

        s.run(s.spawn(main(), name="main"), timeout_time=60)
        assert sorted(ran) == [0, 1, 3, 4, 5, 6, 7, 8]
        assert pool.size == 2, "spawns == peak concurrency, not jobs"
        names = {t.name for t in pool._tasks}
        assert names == {"pt-0", "pt-1"}, names  # fixed small name set
    finally:
        flow.set_scheduler(None)


def test_pool_drain_propagates_worker_failure_without_leaking_slot():
    s = _pool_env()
    try:
        ran = []

        async def job(i):
            if i == 1:
                raise RuntimeError("boom")
            ran.append(i)

        pool = ClientActorPool(job, limit=2)

        async def main():
            pool.dispatch((0,))
            pool.dispatch((1,))       # dies — must NOT leak its slot
            await flow.delay(0.01)
            # both workers still serve (capacity preserved, like the
            # old finally-based inflight decrement)
            assert pool.dispatch((2,))
            assert pool.dispatch((3,))
            await pool.drain()

        with pytest.raises(RuntimeError):
            s.run(s.spawn(main(), name="main"), timeout_time=60)
        assert sorted(ran) == [0, 2, 3]
        assert pool.size == 2
    finally:
        flow.set_scheduler(None)


def test_pool_names_fold_into_one_family():
    s = _pool_env()
    s.start_task_stats()
    try:
        async def job(i):
            await flow.delay(0.001)

        pool = ClientActorPool(job, limit=8, label="storm-txn")

        async def main():
            for i in range(32):
                assert pool.dispatch((i,))
                await flow.delay(0.002)
            await pool.drain()

        s.run(s.spawn(main(), name="main"), timeout_time=60)
        table = {r["task"]: r for r in s.task_stats_report()["tasks"]}
        fams = [n for n in table if n.startswith("storm-txn")]
        assert fams == ["storm-txn-*"], fams
        assert s.task_stats_dropped == 0
    finally:
        flow.set_scheduler(None)


# -- WakeSignal + call_at -------------------------------------------------

def test_wake_signal_parks_and_wakes():
    flow.set_seed(32)
    s = Scheduler(virtual=True)
    flow.set_scheduler(s)
    try:
        sig = WakeSignal()
        log = []

        async def loop():
            while True:
                seen = sig.count
                await sig.wait_beyond(seen)
                log.append((flow.now(), sig.count))
                if sig.count >= 3:
                    return

        async def producer():
            for _ in range(3):
                await flow.delay(1.0)
                sig.touch()

        t = s.spawn(loop(), name="loop")
        s.spawn(producer(), name="prod")
        s.run(until=t, timeout_time=60)
        assert [c for _t, c in log] == [1, 2, 3]
        assert [t for t, _c in log] == [1.0, 2.0, 3.0]
        # a pre-touched signal returns immediately (no park)
        assert sig.wait_beyond(0).is_ready
        assert not sig.wait_beyond(sig.count).is_ready
    finally:
        flow.set_scheduler(None)


def test_call_at_fires_in_time_seq_order_with_delays():
    flow.set_seed(33)
    s = Scheduler(virtual=True)
    flow.set_scheduler(s)
    try:
        order = []
        s.call_at(2.0, order.append, "cb@2")
        s.call_at(1.0, order.append, "cb@1a")
        s.call_at(1.0, order.append, "cb@1b")   # same time: seq order

        async def waiter():
            await flow.delay(1.0)
            order.append("task@1")
            await flow.delay(2.0)
            order.append("task@3")

        t = s.spawn(waiter(), name="w")
        s.run(until=t, timeout_time=60)
        assert order == ["cb@1a", "cb@1b", "task@1", "cb@2", "task@3"], \
            order
        assert s.now() == 3.0
    finally:
        flow.set_scheduler(None)


# -- typed bare payloads --------------------------------------------------

def test_armed_count_msg_rejects_untyped_delivery():
    flow.set_seed(34)
    s = Scheduler(virtual=True)
    net = SimNetwork(s, flow.g_random)
    net.arm_message_stats()
    with pytest.raises(AssertionError):
        net._count_msg("NoneType")
    net._count_msg("PingRequest")     # typed: fine
    assert net.msg_stats["PingRequest"] == 1


def test_wire_cache_serves_fieldless_singletons():
    from foundationdb_tpu.server.types import (GET_RATE_REQUEST,
                                               PING_REQUEST, PingRequest)
    flow.set_seed(35)
    s = Scheduler(virtual=True)
    net = SimNetwork(s, flow.g_random)
    a = net._wire(PING_REQUEST)
    b = net._wire(PING_REQUEST)
    assert type(a) is PingRequest
    assert a is b, "second delivery must hit the per-type cache"
    assert net._wire(None) is None
    assert type(net._wire(GET_RATE_REQUEST)).__name__ == "GetRateRequest"


# -- client multiplexing --------------------------------------------------

def test_multiplexed_overload_covers_whole_population(sim_seed):
    """A multiplexed storm's block cursors walk the entire client
    population: distinct_clients == n_clients once draws cover the
    pools — the 10^6-client path, scaled to test size — and the GRV
    weight charges admission accounting for every logical client."""
    seed = sim_seed(2804)
    # coverage needs the FAIR pool (90% of ids at 25% of the rate)
    # covered too: ~80 fair arrivals x 100 >= 4500-id pool, with margin
    rep = _run_overload(seed, clients_per_arrival=100)
    n = rep["issued"]
    assert rep["others_issued"] * 100 >= 4500, rep["others_issued"]
    assert rep["distinct_clients"] == 5000, rep["distinct_clients"]
    assert rep["clients_per_arrival"] == 100
    assert rep["logical_clients_offered"] == n * 100
    assert rep["completed"] > 0
    # the rotating block leader must not alias the tag modulus: every
    # tenant tag carries traffic even when the stride shares a factor
    # with the tag count (100 % 3 != 0 here, so also pin the aliasing
    # shape directly below)
    assert len(rep["tags_seen"]) == 4, rep["tags_seen"]


def test_armed_stats_with_auto_throttling_storm(sim_seed):
    """The armed-mode untyped-delivery assert must hold on EVERY wire
    path, including the ratekeeper auto-throttler's raw-committed
    probe (a None payload hid there until this combination — armed
    message stats + auto tag throttling under abusive load — ran)."""
    seed = sim_seed(2806)
    try:
        rep = _run_overload(seed, armed_stats=True, duration=4.0,
                            knobs={"grv_admission_control": 1,
                                   "tag_throttling": 1,
                                   "auto_tag_throttling": 1,
                                   "tag_throttle_busy_rate": 0.5,
                                   "tag_throttle_update_interval": 0.25})
        assert not any("NoneType" in t for t in rep["msg_types"]), \
            sorted(rep["msg_types"])
        assert rep["issued"] > 0
        # the throttler must have SURVIVED to enforce (an untyped
        # probe under the armed assert kills the throttler actor
        # before it writes any auto row, so zero rejections here is
        # how that bug manifests end to end)
        assert rep["tag_rejected"] > 0, rep
        assert "RawCommittedRequest" in rep["msg_types"], \
            sorted(rep["msg_types"])
    finally:
        flow.reset_server_knobs(randomize=False)


def test_multiplex_stride_does_not_alias_tags(sim_seed):
    """B divisible by len(tenant_tags) (the overload_million shape,
    B=600): the rotating leader must still spread fair traffic over
    every tenant tag."""
    seed = sim_seed(2805)
    rep = _run_overload(seed, clients_per_arrival=60)
    assert len(rep["tags_seen"]) == 4, rep["tags_seen"]


def test_grv_batch_weight_charges_full_block():
    """One weighted transaction must charge transactions_started for
    the whole block (the wire GetReadVersionRequest carries the
    multiplexed transaction_count)."""
    c = SimCluster(seed=414, durable=True)
    try:
        db = c.client("mux")

        async def main():
            tr = db.create_transaction()
            tr.set_option("grv_batch_weight", 25)
            await tr.get_read_version()
            tr.set(b"mux/k", b"v")
            await tr.commit()
            status = await db.get_status()
            return status["cluster"]["proxies"][0]["counters"]

        counters = c.run(main(), timeout_time=120)
        assert counters["transactions_started"] >= 25, counters
    finally:
        c.shutdown()


def test_grv_batch_weight_rejects_bad_values():
    c = SimCluster(seed=415)
    try:
        db = c.client("muxbad")
        tr = db.create_transaction()
        with pytest.raises(flow.FdbError):
            tr.set_option("grv_batch_weight", 0)
        with pytest.raises(flow.FdbError):
            tr.set_option("grv_batch_weight", "nope")
        tr.set_option("grv_batch_weight", 3)   # legal
    finally:
        c.shutdown()


# -- the 10^6-client acceptance cell (scaled nightly proof runs in CI) ----

@pytest.mark.slow
def test_million_client_storm_cell():
    """The ISSUE 12 acceptance configuration end to end via the same
    entry point CI uses: 10^6 distinct clients, 10x horizon, zero
    NoneType message rows, inside the nightly budget."""
    from foundationdb_tpu.tools.simprof import run_storm
    rep = run_storm("overload_million")
    stats = rep["stats"]
    assert stats["distinct_clients"] == 1_000_000, stats
    assert stats["completed"] > 0
    types = [r["type"] for r in rep["message_stats"]["types"]]
    assert types and not any("NoneType" in t for t in types), types
    assert rep["sim_perf"]["sim_seconds"] >= 29.0   # 10x horizon


def test_simprof_overrides_reach_the_storm():
    """--clients/--horizon/--multiplex plumb through run_storm so any
    population/horizon cell is reproducible from the CLI."""
    from foundationdb_tpu.tools.simprof import run_storm
    rep = run_storm("overload", duration=1.0, clients=2000,
                    horizon=2.0, multiplex=10)
    stats = rep["stats"]
    assert stats["n_clients"] == 2000
    assert stats["clients_per_arrival"] == 10
    assert rep["sim_perf"]["sim_seconds"] >= 2.0    # 1.0s x 2.0 horizon
