"""Ratekeeper admission control: GRVs are batched and rate-gated; an
unhealthy cluster throttles instead of growing queues without bound.

Ref: fdbserver/Ratekeeper.actor.cpp (updateRate :150-635),
MasterProxyServer.actor.cpp transactionStarter (:1102, GRV batching).
"""

from foundationdb_tpu import flow
from foundationdb_tpu.client import run_transaction
from foundationdb_tpu.server import SimCluster


def test_healthy_cluster_grvs_flow_freely():
    c = SimCluster(seed=401)
    try:
        db = c.client()

        async def main():
            async def body(tr):
                tr.set(b"x", b"1")
            await run_transaction(db, body)
            served = 0
            end = flow.now() + 0.5
            while flow.now() < end:
                tr = db.create_transaction()
                await tr.get_read_version()
                served += 1
            assert served > 50, served
            return True

        assert c.run(main(), timeout_time=120)
    finally:
        c.shutdown()


def test_dead_storage_throttles_admission():
    """With a shard dead (auto-reboot off), the ratekeeper drops the
    budget to a trickle: GRV admission — and therefore the TLog's
    unpopped backlog — stays bounded instead of growing with demand
    (round-2 VERDICT task 10)."""
    c = SimCluster(seed=409, durable=True, auto_reboot=False)
    try:
        db = c.client()

        async def main():
            async def body(tr):
                tr.set(b"x", b"1")
            await run_transaction(db, body)
            c.kill_role("storage")
            await flow.delay(0.3)   # let the ratekeeper notice

            served = [0]

            async def flood(cl):
                end = flow.now() + 2.0
                while flow.now() < end:
                    tr = cl.create_transaction()
                    try:
                        await flow.timeout_error(
                            flow.spawn(tr.get_read_version()), 3.0)
                        served[0] += 1
                    except flow.FdbError:
                        return
            clients = [c.client(f"fl{i}") for i in range(10)]
            await flow.wait_for_all([flow.spawn(flood(cl))
                                     for cl in clients])
            # trickle: ~10 tps * 2s, plus scheduling slack — nowhere
            # near the hundreds/second a healthy cluster serves
            assert served[0] <= 60, served[0]
            logs = c.cc.tlog_objs()
            assert logs and all(len(t.entries) < 1000 for t in logs)
            return True

        assert c.run(main(), timeout_time=300)
    finally:
        c.shutdown()


def test_grv_priority_classes():
    """BATCH priority is throttled first when the rate budget runs dry;
    IMMEDIATE bypasses the gate entirely (ref: TransactionPriority +
    the per-class budgets in transactionStarter/Ratekeeper)."""
    from foundationdb_tpu.server.proxy import Proxy

    c = SimCluster(seed=61)
    try:
        db = c.client()

        async def main():
            await db.info()   # wait for recruitment
            # choke the admission rate at its SOURCE — the proxies
            # re-poll the ratekeeper every 100ms, so patching their
            # cached copy alone would be overwritten
            from foundationdb_tpu.server.ratekeeper import Ratekeeper
            proxies = [role for wi in c.cc.workers.values()
                       for role in wi.worker.roles.values()
                       if isinstance(role, Proxy)]
            for wi in c.cc.workers.values():
                for role in wi.worker.roles.values():
                    if isinstance(role, Ratekeeper):
                        role._compute_rates = lambda: (0.0, 0.0)
            for p in proxies:
                p._rate = 0.0
                p._batch_rate = 0.0
            await flow.delay(0.3)   # let the zero rate propagate

            tr_b = db.create_transaction()
            tr_b.set_option("priority_batch")
            tr_i = db.create_transaction()
            tr_i.set_option("priority_system_immediate")

            # immediate sails through a zero-rate gate
            fi = flow.spawn(tr_i.get_read_version())
            fb = flow.spawn(tr_b.get_read_version())
            await flow.delay(1.0)
            assert fi.is_ready and not fi.is_error
            assert not fb.is_ready          # batch is throttled

            # restoring the budget (at the source) releases the batch
            for wi in c.cc.workers.values():
                for role in wi.worker.roles.values():
                    if isinstance(role, Ratekeeper):
                        role._compute_rates = lambda: (1e9, 1e9)
            for p in proxies:
                p._rate = 1e9
                p._batch_rate = 1e9
            await flow.delay(1.0)
            assert fb.is_ready and not fb.is_error
            return True

        assert c.run(main(), timeout_time=120)
    finally:
        c.shutdown()


def test_spring_zone_and_batch_limits():
    """Unit-level controller shape (ref: updateRate's spring zones):
    full speed below the zone, linear decay inside, trickle above —
    and the batch limit collapses before the default limit."""
    from foundationdb_tpu.server.ratekeeper import Ratekeeper

    k = flow.SERVER_KNOBS
    mx, mn = k.rk_max_rate, k.rk_min_rate
    sl = Ratekeeper._spring_limit
    assert sl(0, 1000, 200, mx, mn) == mx             # far below target
    assert sl(799, 1000, 200, mx, mn) == mx           # at the zone edge
    mid = sl(900, 1000, 200, mx, mn)
    assert mn < mid < mx                              # inside the zone
    assert sl(1000, 1000, 200, mx, mn) == mn          # at target
    assert sl(5000, 1000, 200, mx, mn) == mn          # above target
    # monotone decay through the zone
    assert sl(850, 1000, 200, mx, mn) > sl(950, 1000, 200, mx, mn)


def test_batch_throttles_before_default_under_storage_queue():
    """With a storage queue held between the batch target and the
    default target, the ratekeeper publishes batch_tps < tps, and the
    proxy's gate throttles ONLY batch traffic."""
    c = SimCluster(seed=415)
    try:
        db = c.client()

        async def main():
            async def body(tr):
                tr.set(b"x", b"1")
            await run_transaction(db, body)

            # hold the smoothed storage queue between the two targets:
            # batch target = target * fraction < q < target - spring
            from foundationdb_tpu.server.ratekeeper import Ratekeeper
            k = flow.SERVER_KNOBS
            k.set("RK_TARGET_STORAGE_QUEUE_BYTES", 1000)
            k.set("RK_SPRING_STORAGE_QUEUE_BYTES", 100)
            k.set("RK_BATCH_TARGET_FRACTION", 0.5)
            k.set("RK_SMOOTHING_SECONDS", 0.0)   # no lag in the test
            rk = None
            for wi in c.cc.workers.values():
                for role in wi.worker.roles.values():
                    if isinstance(role, Ratekeeper):
                        rk = role
            assert rk is not None
            # fabricate the queue reading: 700 bytes pending
            from foundationdb_tpu.server.types import (MutationRef,
                                                       SET_VALUE)
            for obj in c.cc._storage_objs.values():
                obj._pending = [(1, tuple(
                    MutationRef(SET_VALUE, b"k" * 10, b"v" * 340)
                    for _ in range(2)))]
            rk._storage_smooth.clear()   # fresh, unsmoothed read
            rate, batch = rk._compute_rates()
            assert batch < rate, (batch, rate)
            assert rate == k.rk_max_rate      # default unthrottled
            assert batch == k.rk_min_rate     # above the batch target
            return True

        assert c.run(main(), timeout_time=120)
    finally:
        flow.reset_server_knobs(randomize=False)
        c.shutdown()


def test_smoothing_decays_spikes():
    from foundationdb_tpu.server.ratekeeper import Smoother
    s = Smoother()
    assert s.sample(1000.0, 0.0, 1.0) == 1000.0
    # the sample decays toward a new level with tau=1s
    v1 = s.sample(0.0, 1.0, 1.0)
    assert 300 < v1 < 400        # 1000 * e^-1 ~ 368
    v2 = s.sample(0.0, 4.0, 1.0)
    assert v2 < 25               # mostly forgotten after 3 more taus
