"""Ratekeeper admission control: GRVs are batched and rate-gated; an
unhealthy cluster throttles instead of growing queues without bound.

Ref: fdbserver/Ratekeeper.actor.cpp (updateRate :150-635),
MasterProxyServer.actor.cpp transactionStarter (:1102, GRV batching).
"""

from foundationdb_tpu import flow
from foundationdb_tpu.client import run_transaction
from foundationdb_tpu.server import SimCluster


def test_healthy_cluster_grvs_flow_freely():
    c = SimCluster(seed=401)
    try:
        db = c.client()

        async def main():
            async def body(tr):
                tr.set(b"x", b"1")
            await run_transaction(db, body)
            served = 0
            end = flow.now() + 0.5
            while flow.now() < end:
                tr = db.create_transaction()
                await tr.get_read_version()
                served += 1
            assert served > 50, served
            return True

        assert c.run(main(), timeout_time=120)
    finally:
        c.shutdown()


def test_dead_storage_throttles_admission():
    """With a shard dead (auto-reboot off), the ratekeeper drops the
    budget to a trickle: GRV admission — and therefore the TLog's
    unpopped backlog — stays bounded instead of growing with demand
    (round-2 VERDICT task 10)."""
    c = SimCluster(seed=409, durable=True, auto_reboot=False)
    try:
        db = c.client()

        async def main():
            async def body(tr):
                tr.set(b"x", b"1")
            await run_transaction(db, body)
            c.kill_role("storage")
            await flow.delay(0.3)   # let the ratekeeper notice

            served = [0]

            async def flood(cl):
                end = flow.now() + 2.0
                while flow.now() < end:
                    tr = cl.create_transaction()
                    try:
                        await flow.timeout_error(
                            flow.spawn(tr.get_read_version()), 3.0)
                        served[0] += 1
                    except flow.FdbError:
                        return
            clients = [c.client(f"fl{i}") for i in range(10)]
            await flow.wait_for_all([flow.spawn(flood(cl))
                                     for cl in clients])
            # trickle: ~10 tps * 2s, plus scheduling slack — nowhere
            # near the hundreds/second a healthy cluster serves
            assert served[0] <= 60, served[0]
            logs = c.cc.tlog_objs()
            assert logs and all(len(t.entries) < 1000 for t in logs)
            return True

        assert c.run(main(), timeout_time=300)
    finally:
        c.shutdown()
