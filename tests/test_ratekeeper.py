"""Ratekeeper admission control: GRVs are batched and rate-gated; an
unhealthy cluster throttles instead of growing queues without bound.

Ref: fdbserver/Ratekeeper.actor.cpp (updateRate :150-635),
MasterProxyServer.actor.cpp transactionStarter (:1102, GRV batching).
"""

from foundationdb_tpu import flow
from foundationdb_tpu.client import run_transaction
from foundationdb_tpu.server import SimCluster


def test_healthy_cluster_grvs_flow_freely():
    c = SimCluster(seed=401)
    try:
        db = c.client()

        async def main():
            async def body(tr):
                tr.set(b"x", b"1")
            await run_transaction(db, body)
            served = 0
            end = flow.now() + 0.5
            while flow.now() < end:
                tr = db.create_transaction()
                await tr.get_read_version()
                served += 1
            assert served > 50, served
            return True

        assert c.run(main(), timeout_time=120)
    finally:
        c.shutdown()


def test_dead_storage_throttles_admission():
    """With a shard dead (auto-reboot off), the ratekeeper drops the
    budget to a trickle: GRV admission — and therefore the TLog's
    unpopped backlog — stays bounded instead of growing with demand
    (round-2 VERDICT task 10)."""
    c = SimCluster(seed=409, durable=True, auto_reboot=False)
    try:
        db = c.client()

        async def main():
            async def body(tr):
                tr.set(b"x", b"1")
            await run_transaction(db, body)
            c.kill_role("storage")
            await flow.delay(0.3)   # let the ratekeeper notice

            served = [0]

            async def flood(cl):
                end = flow.now() + 2.0
                while flow.now() < end:
                    tr = cl.create_transaction()
                    try:
                        await flow.timeout_error(
                            flow.spawn(tr.get_read_version()), 3.0)
                        served[0] += 1
                    except flow.FdbError:
                        return
            clients = [c.client(f"fl{i}") for i in range(10)]
            await flow.wait_for_all([flow.spawn(flood(cl))
                                     for cl in clients])
            # trickle: ~10 tps * 2s, plus scheduling slack — nowhere
            # near the hundreds/second a healthy cluster serves
            assert served[0] <= 60, served[0]
            logs = c.cc.tlog_objs()
            assert logs and all(len(t.entries) < 1000 for t in logs)
            return True

        assert c.run(main(), timeout_time=300)
    finally:
        c.shutdown()


def test_grv_priority_classes():
    """BATCH priority is throttled first when the rate budget runs dry;
    IMMEDIATE bypasses the gate entirely (ref: TransactionPriority +
    the per-class budgets in transactionStarter/Ratekeeper)."""
    from foundationdb_tpu.server.proxy import Proxy

    c = SimCluster(seed=61)
    try:
        db = c.client()

        async def main():
            await db.info()   # wait for recruitment
            # choke the admission rate at its SOURCE — the proxies
            # re-poll the ratekeeper every 100ms, so patching their
            # cached copy alone would be overwritten
            from foundationdb_tpu.server.ratekeeper import Ratekeeper
            proxies = [role for wi in c.cc.workers.values()
                       for role in wi.worker.roles.values()
                       if isinstance(role, Proxy)]
            for wi in c.cc.workers.values():
                for role in wi.worker.roles.values():
                    if isinstance(role, Ratekeeper):
                        role._compute_rate = lambda: 0.0
            for p in proxies:
                p._rate = 0.0
            await flow.delay(0.3)   # let the zero rate propagate

            tr_b = db.create_transaction()
            tr_b.set_option("priority_batch")
            tr_i = db.create_transaction()
            tr_i.set_option("priority_system_immediate")

            # immediate sails through a zero-rate gate
            fi = flow.spawn(tr_i.get_read_version())
            fb = flow.spawn(tr_b.get_read_version())
            await flow.delay(1.0)
            assert fi.is_ready and not fi.is_error
            assert not fb.is_ready          # batch is throttled

            # restoring the budget (at the source) releases the batch
            for wi in c.cc.workers.values():
                for role in wi.worker.roles.values():
                    if isinstance(role, Ratekeeper):
                        role._compute_rate = lambda: 1e9
            for p in proxies:
                p._rate = 1e9
            await flow.delay(1.0)
            assert fb.is_ready and not fb.is_error
            return True

        assert c.run(main(), timeout_time=120)
    finally:
        c.shutdown()
