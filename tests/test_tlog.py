"""TLog unit tests: tag partitioning, lock (epoch end), per-tag pop.

Ref: fdbserver/TLogServer.actor.cpp tLogPeekMessages (:1138, per-tag),
tLogPop (:1050), TLogLock / epochEnd
(TagPartitionedLogSystem.actor.cpp:1265).
"""

import pytest

import foundationdb_tpu.flow as fl
from foundationdb_tpu.rpc import SimNetwork
from foundationdb_tpu.server.tlog import TLog
from foundationdb_tpu.server.types import (MutationRef, SET_VALUE,
                                           TLogCommitRequest,
                                           TLogLockRequest, TLogPeekRequest,
                                           TLogPopRequest, TaggedMutation)


def _tm(tag, key, val):
    return TaggedMutation((tag,), MutationRef(SET_VALUE, key, val))


@pytest.fixture
def env():
    fl.set_seed(11)
    s = fl.Scheduler(virtual=True)
    fl.set_scheduler(s)
    net = SimNetwork(s, fl.g_random)
    proc = net.new_process("tlog", machine="m")
    client = net.new_process("client", machine="c")
    tlog = TLog(proc)
    tlog.start()
    yield s, tlog, client
    fl.set_scheduler(None)


def test_per_tag_peek_and_pop(env):
    s, tlog, client = env

    async def main():
        await tlog.commits.ref().get_reply(
            TLogCommitRequest(0, 10, (_tm(0, b"a", b"1"), _tm(1, b"x", b"9"))),
            client)
        await tlog.commits.ref().get_reply(
            TLogCommitRequest(10, 20, (_tm(1, b"y", b"8"),)), client)
        r0 = await tlog.peeks.ref().get_reply(TLogPeekRequest(1, 0), client)
        assert [v for v, _ in r0.entries] == [10]
        assert r0.entries[0][1] == (MutationRef(SET_VALUE, b"a", b"1"),)
        r1 = await tlog.peeks.ref().get_reply(TLogPeekRequest(1, 1), client)
        assert [v for v, _ in r1.entries] == [10, 20]
        # tag 0 pops past everything it has; entries with tag-1 data stay
        tlog.pops.ref().send(TLogPopRequest(20, 0), client)
        await fl.delay(0.05)
        assert [e[0] for e in tlog.entries] == [10, 20]
        tlog.pops.ref().send(TLogPopRequest(10, 1), client)
        await fl.delay(0.05)
        assert [e[0] for e in tlog.entries] == [20]
        tlog.pops.ref().send(TLogPopRequest(20, 1), client)
        await fl.delay(0.05)
        assert tlog.entries == []
        return True

    t = s.spawn(main())
    assert s.run(until=t, timeout_time=30)


def test_lock_waits_for_inflight_fsync(env):
    """A commit accepted but not yet fsynced when the lock arrives must
    be covered by the lock's end_version — otherwise the commit could be
    acked to a client after recovery chose a lower end (code review r3:
    acked-data loss)."""
    s, tlog, client = env

    async def main():
        f = tlog.commits.ref().get_reply(
            TLogCommitRequest(0, 10, (_tm(0, b"a", b"1"),)), client)
        # lock races the in-flight fsync
        lock = await tlog.locks.ref().get_reply(TLogLockRequest(), client)
        assert lock.end_version == 10
        assert await f == 10  # the ack and the lock agree
        return True

    t = s.spawn(main())
    assert s.run(until=t, timeout_time=30)


def test_lock_wakes_parked_commit_waiter(env):
    """A reordered push parked on queue_version must fail out with
    tlog_stopped when the lock arrives, not hang forever (code review
    r3: the gap will never be filled by a dead proxy)."""
    s, tlog, client = env

    async def main():
        # later batch arrives first and parks awaiting prev_version=10
        f2 = tlog.commits.ref().get_reply(
            TLogCommitRequest(10, 20, (_tm(0, b"b", b"2"),)), client)
        await fl.delay(0.01)
        await tlog.locks.ref().get_reply(TLogLockRequest(), client)
        with pytest.raises(fl.FdbError) as ei:
            await f2
        assert ei.value.name == "tlog_stopped"
        return True

    t = s.spawn(main())
    assert s.run(until=t, timeout_time=30)


def test_lock_wakes_parked_peek(env):
    """A long-poll peek already parked when the lock arrives returns
    (empty) instead of blocking the storage drain forever (code review
    r3)."""
    s, tlog, client = env

    async def main():
        f = tlog.peeks.ref().get_reply(TLogPeekRequest(1, 0), client)
        await fl.delay(0.01)
        await tlog.locks.ref().get_reply(TLogLockRequest(), client)
        r = await f
        assert r.entries == ()
        return True

    t = s.spawn(main())
    assert s.run(until=t, timeout_time=30)


def test_lock_stops_commits_keeps_peeks(env):
    s, tlog, client = env

    async def main():
        await tlog.commits.ref().get_reply(
            TLogCommitRequest(0, 10, (_tm(0, b"a", b"1"),)), client)
        lock = await tlog.locks.ref().get_reply(TLogLockRequest(), client)
        assert lock.end_version == 10
        with pytest.raises(fl.FdbError) as ei:
            await tlog.commits.ref().get_reply(
                TLogCommitRequest(10, 20, (_tm(0, b"b", b"2"),)), client)
        assert ei.value.name == "tlog_stopped"
        # peeks still served, and return immediately even past the end
        r = await tlog.peeks.ref().get_reply(TLogPeekRequest(1, 0), client)
        assert [v for v, _ in r.entries] == [10]
        r2 = await tlog.peeks.ref().get_reply(TLogPeekRequest(11, 0), client)
        assert r2.entries == ()
        return True

    t = s.spawn(main())
    assert s.run(until=t, timeout_time=30)


def test_peek_below_popped_stalls_with_error_trace(env):
    """Peeking at/below the tag's freed floor must emit a SevError
    TLogPeekBelowPopped event and reply with the watermark clamped below
    the hole — not crash the peek actor (advisor r4: flow.SevError was
    an AttributeError, so the safeguard died exactly when it fired)."""
    s, tlog, client = env

    async def main():
        for i in range(1, 6):
            await tlog.commits.ref().get_reply(
                TLogCommitRequest(i - 1, i, (_tm(0, b"k%d" % i, b"v"),),
                                  i - 1), client)
        tlog.pops.ref().send(TLogPopRequest(3, 0), client)
        await fl.delay(0.05)
        before = fl.trace.g_trace.counts.get("TLogPeekBelowPopped", 0)
        r = await tlog.peeks.ref().get_reply(TLogPeekRequest(2, 0), client)
        # clamped below begin: the reader cannot advance past the hole
        assert r.entries == () and r.committed_version == 1
        assert fl.trace.g_trace.counts.get(
            "TLogPeekBelowPopped", 0) == before + 1
        return True

    t = s.spawn(main())
    assert s.run(until=t, timeout_time=30)


def test_spill_bounds_memory_and_peeks_from_disk():
    """Once payload bytes exceed TLOG_SPILL_THRESHOLD the oldest durable
    entries spill: memory keeps only DiskQueue positions, a lagging
    reader's peek re-reads payloads from disk bit-exactly, pops still
    reclaim, and recovery after a crash still sees everything (ref:
    TLogServer updatePersistentData spill-by-reference)."""
    fl.set_seed(23)
    s = fl.Scheduler(virtual=True)
    fl.set_scheduler(s)
    try:
        net = SimNetwork(s, fl.g_random)
        proc = net.new_process("tlog-spill", machine="ms")
        client = net.new_process("client", machine="mc")
        disk = net.disk("ms")
        fl.SERVER_KNOBS.init("TLOG_SPILL_THRESHOLD", 2000)
        tlog = TLog(proc, disk=disk, name="tlog-sp")
        tlog.start()

        async def main():
            await tlog.recovered()
            val = b"v" * 100
            for i in range(1, 41):   # ~4.6KB of payload >> 2KB threshold
                await tlog.commits.ref().get_reply(
                    TLogCommitRequest(i - 1, i, (_tm(0, b"k%03d" % i, val),),
                                      i - 1), client)
            assert tlog.mem_bytes <= 2000 + 200, tlog.mem_bytes
            spilled = sum(1 for _v, m, _s in tlog.entries if m is None)
            assert spilled >= 20, spilled

            # a reader from the beginning sees every record, including
            # the spilled prefix served from disk
            reply = await tlog.peeks.ref().get_reply(
                TLogPeekRequest(1, 0), client)
            got = [(v, ms[0].param1, ms[0].param2) for v, ms in reply.entries]
            assert got == [(i, b"k%03d" % i, val) for i in range(1, 41)]

            # pops reclaim spilled records too
            tlog.set_expected_replicas({0: ("r1",)})
            tlog.pops.ref().send(TLogPopRequest(20, 0, "r1"), client)
            await fl.delay(0.1)
            assert tlog._versions[0] == 21

            # recover from the durable image alone: 21..40 survive
            tlog2 = TLog(proc, disk=disk, name="tlog-sp")
            tlog2.start()
            await tlog2.recovered()
            reply2 = await tlog2.peeks.ref().get_reply(
                TLogPeekRequest(1, 0), client)
            vs = [v for v, _ms in reply2.entries]
            assert vs[-1] == 40 and 21 in vs
            return True

        t = s.spawn(main())
        assert s.run(until=t, timeout_time=120)
    finally:
        fl.reset_server_knobs()
        fl.set_scheduler(None)


def test_peek_replies_are_size_bounded():
    """DESIRED_TOTAL_BYTES chunks big peeks: a far-behind reader drains
    in multiple rounds, the reply watermark is clamped to what was
    delivered, and no version is ever skipped."""
    fl.set_seed(29)
    s = fl.Scheduler(virtual=True)
    fl.set_scheduler(s)
    try:
        net = SimNetwork(s, fl.g_random)
        proc = net.new_process("tlog-chunk", machine="mc")
        client = net.new_process("client", machine="cc2")
        fl.SERVER_KNOBS.init("DESIRED_TOTAL_BYTES", 500)
        tlog = TLog(proc)
        tlog.start()

        async def main():
            val = b"v" * 100
            for i in range(1, 21):
                await tlog.commits.ref().get_reply(
                    TLogCommitRequest(i - 1, i,
                                      (_tm(0, b"c%03d" % i, val),), i - 1),
                    client)
            got = []
            begin = 1
            rounds = 0
            while True:
                rounds += 1
                reply = await tlog.peeks.ref().get_reply(
                    TLogPeekRequest(begin, 0), client)
                got.extend(v for v, _ms in reply.entries)
                if reply.committed_version >= 20:
                    break
                assert reply.committed_version >= begin - 1
                begin = reply.committed_version + 1
            assert got == list(range(1, 21)), got  # nothing skipped
            assert rounds >= 3, rounds             # actually chunked
            return True

        t = s.spawn(main())
        assert s.run(until=t, timeout_time=60)
    finally:
        fl.reset_server_knobs()
        fl.set_scheduler(None)
