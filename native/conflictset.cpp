// Native CPU conflict-set backend for foundationdb_tpu.
//
// Semantics contract: identical verdicts to models/conflict_set.py
// (see that file's docstring for the reference-behavior citations:
// fdbserver/SkipList.cpp addTransaction/detectConflicts and
// fdbserver/Resolver.actor.cpp resolveBatch). This is an original
// implementation — the version history is an ordered std::map step
// function (boundary key -> max commit version of [key, next_key)),
// not a skiplist; the batch pipeline (external check, sequential
// intra-batch, interval-union merge, window GC) matches the reference's
// observable behavior exactly.
//
// Exposed as a plain C ABI consumed via ctypes (the plugin boundary,
// analogous to fdbrpc/LoadPlugin.h:29-44 loading ITLSPlugin-style
// backends by symbol).

#include <cstdint>
#include <cstring>
#include <map>
#include <string>
#include <vector>

namespace {

using Key = std::string;

struct StepFunction {
    // Invariant: always contains "" ; value covers [key, next_key).
    std::map<Key, int64_t> m;

    explicit StepFunction(int64_t init_version) { m.emplace(Key(), init_version); }

    int64_t range_max(const Key& begin, const Key& end) const {
        auto it = m.upper_bound(begin);
        --it;  // interval containing `begin` (safe: "" is always present)
        int64_t vmax = it->second;
        for (++it; it != m.end() && it->first < end; ++it)
            if (it->second > vmax) vmax = it->second;
        return vmax;
    }

    void assign(const Key& begin, const Key& end, int64_t version) {
        auto it_e = m.upper_bound(end);
        --it_e;
        int64_t v_end = it_e->second;  // version of the interval containing `end`
        m.erase(m.lower_bound(begin), m.lower_bound(end));
        m[begin] = version;
        m.emplace(end, v_end);  // no-op if `end` is already a boundary
    }

    // Merge adjacent intervals that are both dead (< oldest) or equal-valued.
    // Dead intervals cannot conflict with any non-tooOld read, so collapsing
    // them (keeping the max) is invisible (ref: removeBefore window GC).
    void compact(int64_t oldest) {
        auto it = m.begin();
        auto prev = it++;
        while (it != m.end()) {
            if ((it->second < oldest && prev->second < oldest) ||
                it->second == prev->second) {
                if (it->second > prev->second) prev->second = it->second;
                it = m.erase(it);
            } else {
                prev = it++;
            }
        }
    }
};

struct Range {
    Key begin, end;
};

struct ConflictSet {
    StepFunction history;
    int64_t oldest_version;
    uint64_t batches = 0;

    // init_version baselines the history (ref: clearConflictSet/SkipList(v));
    // oldestVersion starts at 0 regardless (ref: ConflictSet ctor).
    explicit ConflictSet(int64_t init_version)
        : history(init_version), oldest_version(0) {}
};

// Sorted disjoint interval set for the intra-batch written-key union.
struct IntervalSet {
    std::map<Key, Key> iv;  // begin -> end, disjoint, coalesced

    bool overlaps(const Key& b, const Key& e) const {
        auto it = iv.upper_bound(b);
        if (it != iv.begin()) {
            auto p = std::prev(it);
            if (p->second > b) return true;
        }
        return it != iv.end() && it->first < e;
    }

    void add(Key b, Key e) {
        auto it = iv.upper_bound(b);
        if (it != iv.begin()) {
            auto p = std::prev(it);
            if (p->second >= b) it = p;
        }
        while (it != iv.end() && it->first <= e) {
            if (it->first < b) b = it->first;
            if (it->second > e) e = it->second;
            it = iv.erase(it);
        }
        iv.emplace(std::move(b), std::move(e));
    }
};

// Shared batch pipeline. `read_hits_out` (nullable) is one byte per
// FLATTENED read range in txn order; attribution semantics match
// models/conflict_set.py resolve_with_attribution: a range is a cause
// iff it conflicts against the pre-batch history OR overlaps a write
// of an earlier non-conflicted transaction — evaluated for every
// non-tooOld transaction (externally-conflicted ones included), with
// no short-circuiting, so the set is identical across backends.
static void resolve_impl(ConflictSet& cs, int64_t commit_version,
                         int64_t new_oldest_version, int32_t txn_count,
                         const int64_t* snapshots,
                         const int32_t* read_counts,
                         const int32_t* write_counts,
                         const uint8_t* key_blob,
                         const int64_t* read_ranges,
                         const int64_t* write_ranges,
                         uint8_t* verdicts_out,
                         uint8_t* read_hits_out) {
    auto key_at = [&](const int64_t* quad, int which) {
        return Key(reinterpret_cast<const char*>(key_blob) + quad[which * 2],
                   static_cast<size_t>(quad[which * 2 + 1]));
    };

    std::vector<uint8_t> too_old(txn_count, 0), conflict(txn_count, 0);

    // tooOld pass (ref: addTransaction)
    for (int32_t t = 0; t < txn_count; t++)
        if (snapshots[t] < cs.oldest_version && read_counts[t] > 0)
            too_old[t] = 1;

    // (1) external check against history. Attribution checks EVERY
    // range; verdict-only mode keeps the original short-circuit.
    {
        const int64_t* rr = read_ranges;
        int64_t ri = 0;
        for (int32_t t = 0; t < txn_count; t++) {
            for (int32_t r = 0; r < read_counts[t]; r++, rr += 4, ri++) {
                if (too_old[t]) continue;
                if (conflict[t] && read_hits_out == nullptr) continue;
                Key b = key_at(rr, 0), e = key_at(rr, 1);
                if (b < e && cs.history.range_max(b, e) > snapshots[t]) {
                    conflict[t] = 1;
                    if (read_hits_out) read_hits_out[ri] = 1;
                }
            }
        }
    }

    // (2) intra-batch, sequential in batch order; (3) collect surviving
    // writes. Attribution also checks already-conflicted transactions'
    // reads against the written set at their turn (their writes still
    // never join it).
    IntervalSet written;
    {
        const int64_t* rr = read_ranges;
        const int64_t* wr = write_ranges;
        int64_t ri = 0;
        for (int32_t t = 0; t < txn_count; t++) {
            if (conflict[t] || too_old[t]) {
                if (read_hits_out && conflict[t] && !too_old[t]) {
                    for (int32_t r = 0; r < read_counts[t]; r++, rr += 4, ri++) {
                        Key b = key_at(rr, 0), e = key_at(rr, 1);
                        if (b < e && written.overlaps(b, e))
                            read_hits_out[ri] = 1;
                    }
                } else {
                    rr += 4 * static_cast<int64_t>(read_counts[t]);
                    ri += read_counts[t];
                }
                if (!conflict[t]) conflict[t] = 1;  // tooOld: writes dropped
                wr += 4 * static_cast<int64_t>(write_counts[t]);
                continue;
            }
            bool c = false;
            for (int32_t r = 0; r < read_counts[t]; r++, rr += 4, ri++) {
                if (c && read_hits_out == nullptr) continue;
                Key b = key_at(rr, 0), e = key_at(rr, 1);
                if (b < e && written.overlaps(b, e)) {
                    c = true;
                    if (read_hits_out) read_hits_out[ri] = 1;
                }
            }
            conflict[t] = c ? 1 : 0;
            for (int32_t w = 0; w < write_counts[t]; w++, wr += 4) {
                if (c) continue;
                Key b = key_at(wr, 0), e = key_at(wr, 1);
                if (b < e) written.add(std::move(b), std::move(e));
            }
        }
    }

    for (const auto& [b, e] : written.iv) cs.history.assign(b, e, commit_version);

    // (4) window GC
    if (new_oldest_version > cs.oldest_version) cs.oldest_version = new_oldest_version;
    if (++cs.batches % 16 == 0) cs.history.compact(cs.oldest_version);

    for (int32_t t = 0; t < txn_count; t++)
        verdicts_out[t] = too_old[t] ? 1 : (conflict[t] ? 0 : 2);
}

}  // namespace

extern "C" {

void* fdbtpu_conflictset_new(int64_t init_version) {
    return new ConflictSet(init_version);
}

void fdbtpu_conflictset_destroy(void* cs) { delete static_cast<ConflictSet*>(cs); }

int64_t fdbtpu_conflictset_oldest(void* cs) {
    return static_cast<ConflictSet*>(cs)->oldest_version;
}

int64_t fdbtpu_conflictset_interval_count(void* cs) {
    return static_cast<int64_t>(static_cast<ConflictSet*>(cs)->history.m.size());
}

// State export for checkpoint/restore: the step function as sorted
// boundary keys + versions. Two-phase: size the buffers, then fill.
//   fdbtpu_conflictset_export_rows:      boundary count
//   fdbtpu_conflictset_export_key_bytes: sum of boundary-key lengths
//   fdbtpu_conflictset_export:           fill key_blob_out (concatenated
//       key bytes), key_lens_out (one int64 per boundary), versions_out
int64_t fdbtpu_conflictset_export_rows(void* cs) {
    return static_cast<int64_t>(static_cast<ConflictSet*>(cs)->history.m.size());
}

int64_t fdbtpu_conflictset_export_key_bytes(void* cs) {
    int64_t total = 0;
    for (const auto& [k, v] : static_cast<ConflictSet*>(cs)->history.m)
        total += static_cast<int64_t>(k.size());
    return total;
}

void fdbtpu_conflictset_export(void* cs, uint8_t* key_blob_out,
                               int64_t* key_lens_out,
                               int64_t* versions_out) {
    int64_t i = 0;
    for (const auto& [k, v] : static_cast<ConflictSet*>(cs)->history.m) {
        std::memcpy(key_blob_out, k.data(), k.size());
        key_blob_out += k.size();
        key_lens_out[i] = static_cast<int64_t>(k.size());
        versions_out[i] = v;
        i++;
    }
}

// Resolve one batch.
//   key_blob:      all range-endpoint bytes, concatenated
//   read_ranges:   per read range, 4 int64s (begin_off, begin_len, end_off, end_len)
//   write_ranges:  same layout
//   read_counts /
//   write_counts:  per-transaction range counts (length = txn_count)
//   snapshots:     per-transaction read snapshot versions
//   verdicts_out:  per-transaction verdict {0=conflict, 1=too_old, 2=committed}
void fdbtpu_conflictset_resolve(void* cs_, int64_t commit_version,
                                int64_t new_oldest_version, int32_t txn_count,
                                const int64_t* snapshots,
                                const int32_t* read_counts,
                                const int32_t* write_counts,
                                const uint8_t* key_blob,
                                const int64_t* read_ranges,
                                const int64_t* write_ranges,
                                uint8_t* verdicts_out) {
    resolve_impl(*static_cast<ConflictSet*>(cs_), commit_version,
                 new_oldest_version, txn_count, snapshots, read_counts,
                 write_counts, key_blob, read_ranges, write_ranges,
                 verdicts_out, nullptr);
}

// Resolve + conflict attribution (ref: report_conflicting_keys).
//   read_hits_out: one byte per flattened read range (txn order);
//   set to 1 when that range caused its transaction's conflict.
//   Caller zero-initializes.
void fdbtpu_conflictset_resolve_attributed(
    void* cs_, int64_t commit_version, int64_t new_oldest_version,
    int32_t txn_count, const int64_t* snapshots,
    const int32_t* read_counts, const int32_t* write_counts,
    const uint8_t* key_blob, const int64_t* read_ranges,
    const int64_t* write_ranges, uint8_t* verdicts_out,
    uint8_t* read_hits_out) {
    resolve_impl(*static_cast<ConflictSet*>(cs_), commit_version,
                 new_oldest_version, txn_count, snapshots, read_counts,
                 write_counts, key_blob, read_ranges, write_ranges,
                 verdicts_out, read_hits_out);
}

}  // extern "C"
