"""`fdb`-style Python binding surface.

Reference: bindings/python/fdb — the API programmers actually use:
``fdb.open()``, ``@fdb.transactional``, ``db[key]`` sugar, and the
tuple/subspace layers under ``fdb.tuple`` / ``fdb.Subspace``. The
reference binding is blocking over the C ABI's network thread; this
framework's client is cooperative, so the surface is async — a
``@transactional`` function is an async function whose first argument
is bound to a retried Transaction, and the item sugar lives on the
async Transaction itself.
"""

from __future__ import annotations

import functools

from ..client import Database, Transaction, run_transaction
from ..layers import Subspace
from ..layers import tuple_layer as tuple  # noqa: A001 — mirrors fdb.tuple
from ..server.types import KeySelector

__all__ = ["open", "transactional", "Database", "Transaction",
           "Subspace", "tuple", "KeySelector", "api_version",
           "threadsafe_database"]

# -- API versioning (ref: fdb.api_version + the MultiVersion client's
# version selection, fdbclient/MultiVersionTransaction.actor.cpp:
# the binding locks to one API version per process; a conflicting
# second selection is an error). Version numbers track the reference's
# (520+ = versionstamp ops in tuples, 610+ = current surface).
CURRENT_API_VERSION = 710
_selected_api_version = None


def api_version(version: int) -> None:
    global _selected_api_version
    if _selected_api_version is not None:
        if version != _selected_api_version:
            raise RuntimeError(
                f"API version already selected: {_selected_api_version}")
        return
    if not 500 <= version <= CURRENT_API_VERSION:
        raise RuntimeError(
            f"API version {version} not supported (500..."
            f"{CURRENT_API_VERSION})")
    _selected_api_version = version


def threadsafe_database(host: str, port: int):
    """A THREAD-SAFE blocking Database handle — the native C client over
    a cluster's TcpGateway (ref: ThreadSafeDatabase in
    fdbclient/ThreadSafeTransaction.cpp — the layer OS-thread callers
    use; here that layer IS the C binding, whose connection owns its
    reader thread and whose calls may come from any thread)."""
    from .c_client import CDatabase
    return CDatabase(host, port)


def open(cluster, name: str = "fdb-client"):  # noqa: A001 — mirrors fdb.open
    """A Database handle onto a running cluster (ref: fdb.open — the
    cluster-file argument becomes the SimCluster here)."""
    return cluster.client(name)


def transactional(func):
    """(ref: @fdb.transactional — the wrapped function receives a
    transaction as its first argument and is retried on retryable
    errors; passing a Database starts the retry loop, passing a
    Transaction composes without a nested loop)"""

    @functools.wraps(func)
    async def wrapper(db_or_tr, *args, **kwargs):
        if isinstance(db_or_tr, Transaction):
            return await func(db_or_tr, *args, **kwargs)

        async def body(tr):
            return await func(tr, *args, **kwargs)
        return await run_transaction(db_or_tr, body)

    return wrapper
