"""`fdb`-style Python binding surface.

Reference: bindings/python/fdb — the API programmers actually use:
``fdb.open()``, ``@fdb.transactional``, ``db[key]`` sugar, and the
tuple/subspace layers under ``fdb.tuple`` / ``fdb.Subspace``. The
reference binding is blocking over the C ABI's network thread; this
framework's client is cooperative, so the surface is async — a
``@transactional`` function is an async function whose first argument
is bound to a retried Transaction, and the item sugar lives on the
async Transaction itself.
"""

from __future__ import annotations

import functools

from ..client import Database, Transaction, run_transaction
from ..layers import Subspace
from ..layers import tuple_layer as tuple  # noqa: A001 — mirrors fdb.tuple
from ..server.types import KeySelector

__all__ = ["open", "transactional", "Database", "Transaction",
           "Subspace", "tuple", "KeySelector"]


def open(cluster, name: str = "fdb-client"):  # noqa: A001 — mirrors fdb.open
    """A Database handle onto a running cluster (ref: fdb.open — the
    cluster-file argument becomes the SimCluster here)."""
    return cluster.client(name)


def transactional(func):
    """(ref: @fdb.transactional — the wrapped function receives a
    transaction as its first argument and is retried on retryable
    errors; passing a Database starts the retry loop, passing a
    Transaction composes without a nested loop)"""

    @functools.wraps(func)
    async def wrapper(db_or_tr, *args, **kwargs):
        if isinstance(db_or_tr, Transaction):
            return await func(db_or_tr, *args, **kwargs)

        async def body(tr):
            return await func(tr, *args, **kwargs)
        return await run_transaction(db_or_tr, body)

    return wrapper
