"""ctypes harness for the native C client (bindings/c/fdb_tpu.cpp).

Reference: the reference's Python binding sits on fdb_c via ctypes
(bindings/python/fdb/impl.py loading libfdb_c); this module is the same
seam pointed at this framework's C library, used by the cross-binding
parity tests and available as a C-backed client for out-of-process
access through a cluster's TcpGateway.

Calls are blocking (the C library is a synchronous native client), so
use this from a plain thread — NOT from inside the flow scheduler, which
must stay free to serve the cluster the C client is talking to.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import List, Optional, Tuple

_REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
_SRC_DIR = os.path.join(_REPO_ROOT, "bindings", "c")
_LIB_PATH = os.path.join(_SRC_DIR, "build", "libfdb_tpu_c.so")

_lib: Optional[ctypes.CDLL] = None


class CClientError(Exception):
    def __init__(self, code: int, name: str):
        super().__init__(f"{name} ({code})")
        self.code = code
        self.name = name


def load_library(build_if_missing: bool = True) -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    if build_if_missing:
        try:
            subprocess.run(["make", "-C", _SRC_DIR], check=True,
                           capture_output=True)
        except (FileNotFoundError, subprocess.CalledProcessError):
            # missing or failing toolchain: a prebuilt library may still
            # serve; with none, the build failure is the real error
            if not os.path.exists(_LIB_PATH):
                raise
    _lib = load_library_at(_LIB_PATH)
    return _lib


def load_library_at(path: str) -> ctypes.CDLL:
    """dlopen + configure a C client library at an arbitrary path —
    the seam the MultiVersion shim uses to hold several
    protocol-versioned copies at once (ref: MultiVersionApi's
    externalClients, each its own dlopen of a versioned libfdb_c)."""
    lib = ctypes.CDLL(path)
    _configure(lib)
    return lib


def _configure(lib: ctypes.CDLL) -> None:
    u8p = ctypes.POINTER(ctypes.c_uint8)
    try:
        lib.fdb_tpu_get_protocol.restype = ctypes.c_char_p
        lib.fdb_tpu_get_protocol.argtypes = []
    except AttributeError:
        pass  # an older library without the protocol export
    lib.fdb_tpu_get_error.restype = ctypes.c_char_p
    lib.fdb_tpu_get_error.argtypes = [ctypes.c_int]
    lib.fdb_tpu_error_retryable.restype = ctypes.c_int
    lib.fdb_tpu_error_retryable.argtypes = [ctypes.c_int]
    lib.fdb_tpu_create_database.restype = ctypes.c_int
    lib.fdb_tpu_create_database.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.POINTER(ctypes.c_void_p)]
    lib.fdb_tpu_database_destroy.argtypes = [ctypes.c_void_p]
    lib.fdb_tpu_database_create_transaction.restype = ctypes.c_int
    lib.fdb_tpu_database_create_transaction.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p)]
    lib.fdb_tpu_transaction_destroy.argtypes = [ctypes.c_void_p]
    lib.fdb_tpu_transaction_reset.argtypes = [ctypes.c_void_p]
    lib.fdb_tpu_transaction_set_option.restype = ctypes.c_int
    lib.fdb_tpu_transaction_set_option.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p]
    lib.fdb_tpu_transaction_get_read_version.restype = ctypes.c_int
    lib.fdb_tpu_transaction_get_read_version.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64)]
    lib.fdb_tpu_transaction_get.restype = ctypes.c_int
    lib.fdb_tpu_transaction_get.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
        ctypes.POINTER(ctypes.c_int), ctypes.POINTER(u8p),
        ctypes.POINTER(ctypes.c_int)]
    lib.fdb_tpu_transaction_get_key.restype = ctypes.c_int
    lib.fdb_tpu_transaction_get_key.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.POINTER(u8p),
        ctypes.POINTER(ctypes.c_int)]
    lib.fdb_tpu_transaction_get_range.restype = ctypes.c_int
    lib.fdb_tpu_transaction_get_range.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p,
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_int)]
    lib.fdb_tpu_transaction_set.restype = ctypes.c_int
    lib.fdb_tpu_transaction_set.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p,
        ctypes.c_int]
    lib.fdb_tpu_transaction_clear.restype = ctypes.c_int
    lib.fdb_tpu_transaction_clear.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
    lib.fdb_tpu_transaction_clear_range.restype = ctypes.c_int
    lib.fdb_tpu_transaction_clear_range.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p,
        ctypes.c_int]
    lib.fdb_tpu_transaction_atomic_op.restype = ctypes.c_int
    lib.fdb_tpu_transaction_atomic_op.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p,
        ctypes.c_int, ctypes.c_int]
    lib.fdb_tpu_transaction_add_conflict_range.restype = ctypes.c_int
    lib.fdb_tpu_transaction_add_conflict_range.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p,
        ctypes.c_int, ctypes.c_int]
    lib.fdb_tpu_transaction_commit.restype = ctypes.c_int
    lib.fdb_tpu_transaction_commit.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64)]
    lib.fdb_tpu_transaction_get_versionstamp.restype = ctypes.c_int
    lib.fdb_tpu_transaction_get_versionstamp.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(u8p), ctypes.POINTER(ctypes.c_int)]
    lib.fdb_tpu_transaction_on_error.restype = ctypes.c_int
    lib.fdb_tpu_transaction_on_error.argtypes = [ctypes.c_void_p,
                                                 ctypes.c_int]
    lib.fdb_tpu_database_watch.restype = ctypes.c_int
    lib.fdb_tpu_database_watch.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_int]
    lib.fdb_tpu_free.argtypes = [ctypes.c_void_p]
    lib.fdb_tpu_free_keyvalues.argtypes = [ctypes.c_void_p, ctypes.c_int]


class _KeyValue(ctypes.Structure):
    _fields_ = [("key", ctypes.POINTER(ctypes.c_uint8)),
                ("key_length", ctypes.c_int),
                ("value", ctypes.POINTER(ctypes.c_uint8)),
                ("value_length", ctypes.c_int)]


def _check(lib, code: int) -> None:
    if code != 0:
        raise CClientError(code, lib.fdb_tpu_get_error(code).decode())


def _take_bytes(lib, ptr, length: int) -> bytes:
    try:
        return ctypes.string_at(ptr, length) if length else b""
    finally:
        lib.fdb_tpu_free(ptr)


class CDatabase:
    """Out-of-process database handle over a TcpGateway."""

    def __init__(self, host: str, port: int, lib: ctypes.CDLL = None,
                 connect_timeout: float = 5.0):
        self.lib = lib if lib is not None else load_library()
        handle = ctypes.c_void_p()
        # connection establishment retries transient failures for a
        # bounded window (ref: the client connecting to a cluster keeps
        # trying through recoveries/boot; a cluster mid-recovery may
        # drop or stall the first describe)
        import time
        deadline = time.monotonic() + connect_timeout
        while True:
            code = self.lib.fdb_tpu_create_database(
                host.encode(), port, ctypes.byref(handle))
            if code == 0:
                break
            if (not self.lib.fdb_tpu_error_retryable(code)
                    or time.monotonic() > deadline):
                _check(self.lib, code)
            time.sleep(0.1)
        self._h = handle

    def close(self) -> None:
        if self._h:
            self.lib.fdb_tpu_database_destroy(self._h)
            self._h = None

    def create_transaction(self) -> "CTransaction":
        handle = ctypes.c_void_p()
        _check(self.lib, self.lib.fdb_tpu_database_create_transaction(
            self._h, ctypes.byref(handle)))
        return CTransaction(self.lib, handle)

    def watch(self, key: bytes, timeout_ms: int = 60000) -> None:
        """Block until the key's value changes (or timed_out raises)."""
        _check(self.lib, self.lib.fdb_tpu_database_watch(
            self._h, key, len(key), timeout_ms))

    def run(self, body, max_retries: int = 100):
        """The standard retry loop over the C on_error protocol."""
        tr = self.create_transaction()
        try:
            for _ in range(max_retries):
                try:
                    result = body(tr)
                    tr.commit()
                    return result
                except CClientError as e:
                    tr.on_error(e.code)
        finally:
            tr.destroy()
        raise CClientError(1031, "transaction_timed_out")


class CTransaction:
    def __init__(self, lib, handle):
        self.lib = lib
        self._h = handle

    def destroy(self) -> None:
        if self._h:
            self.lib.fdb_tpu_transaction_destroy(self._h)
            self._h = None

    def reset(self) -> None:
        self.lib.fdb_tpu_transaction_reset(self._h)

    def set_option(self, option: str) -> None:
        _check(self.lib, self.lib.fdb_tpu_transaction_set_option(
            self._h, option.encode()))

    def get_read_version(self) -> int:
        out = ctypes.c_int64()
        _check(self.lib, self.lib.fdb_tpu_transaction_get_read_version(
            self._h, ctypes.byref(out)))
        return out.value

    def get(self, key: bytes, snapshot: bool = False) -> Optional[bytes]:
        present = ctypes.c_int()
        val = ctypes.POINTER(ctypes.c_uint8)()
        vlen = ctypes.c_int()
        _check(self.lib, self.lib.fdb_tpu_transaction_get(
            self._h, key, len(key), int(snapshot), ctypes.byref(present),
            ctypes.byref(val), ctypes.byref(vlen)))
        if not present.value:
            return None
        return _take_bytes(self.lib, val, vlen.value)

    def get_key(self, key: bytes, or_equal: bool, offset: int,
                snapshot: bool = False) -> bytes:
        out = ctypes.POINTER(ctypes.c_uint8)()
        olen = ctypes.c_int()
        _check(self.lib, self.lib.fdb_tpu_transaction_get_key(
            self._h, key, len(key), int(or_equal), offset, int(snapshot),
            ctypes.byref(out), ctypes.byref(olen)))
        return _take_bytes(self.lib, out, olen.value)

    def get_range(self, begin: bytes, end: bytes, limit: int = 0,
                  reverse: bool = False,
                  snapshot: bool = False) -> List[Tuple[bytes, bytes]]:
        arr = ctypes.c_void_p()
        count = ctypes.c_int()
        _check(self.lib, self.lib.fdb_tpu_transaction_get_range(
            self._h, begin, len(begin), end, len(end), limit, int(reverse),
            int(snapshot), ctypes.byref(arr), ctypes.byref(count)))
        try:
            kvs = ctypes.cast(arr, ctypes.POINTER(_KeyValue))
            out = []
            for i in range(count.value):
                kv = kvs[i]
                out.append((
                    ctypes.string_at(kv.key, kv.key_length)
                    if kv.key_length else b"",
                    ctypes.string_at(kv.value, kv.value_length)
                    if kv.value_length else b""))
            return out
        finally:
            self.lib.fdb_tpu_free_keyvalues(arr, count.value)

    def set(self, key: bytes, value: bytes) -> None:
        _check(self.lib, self.lib.fdb_tpu_transaction_set(
            self._h, key, len(key), value, len(value)))

    def clear(self, key: bytes) -> None:
        _check(self.lib, self.lib.fdb_tpu_transaction_clear(
            self._h, key, len(key)))

    def clear_range(self, begin: bytes, end: bytes) -> None:
        _check(self.lib, self.lib.fdb_tpu_transaction_clear_range(
            self._h, begin, len(begin), end, len(end)))

    def atomic_op(self, key: bytes, param: bytes, op_type: int) -> None:
        _check(self.lib, self.lib.fdb_tpu_transaction_atomic_op(
            self._h, key, len(key), param, len(param), op_type))

    def add_conflict_range(self, begin: bytes, end: bytes,
                           write: bool) -> None:
        _check(self.lib, self.lib.fdb_tpu_transaction_add_conflict_range(
            self._h, begin, len(begin), end, len(end), int(write)))

    def commit(self) -> int:
        out = ctypes.c_int64()
        _check(self.lib, self.lib.fdb_tpu_transaction_commit(
            self._h, ctypes.byref(out)))
        return out.value

    def get_versionstamp(self) -> bytes:
        out = ctypes.POINTER(ctypes.c_uint8)()
        olen = ctypes.c_int()
        _check(self.lib, self.lib.fdb_tpu_transaction_get_versionstamp(
            self._h, ctypes.byref(out), ctypes.byref(olen)))
        return _take_bytes(self.lib, out, olen.value)

    def on_error(self, code: int) -> None:
        err = self.lib.fdb_tpu_transaction_on_error(self._h, code)
        if err != 0:
            raise CClientError(err, self.lib.fdb_tpu_get_error(err).decode())
