"""MultiVersion client: select the protocol-versioned C library that
matches the cluster.

Reference: fdbclient/MultiVersionTransaction.h:351 (MultiVersionApi) —
the reference ships every release's libfdb_c side by side; the
multi-version layer dlopens them all, discovers the cluster's protocol
version, and routes the application's API calls through the matching
client, so an application built before a cluster upgrade keeps working
after it. Here the same shape over this framework's wire protocol:

- every connection starts with an 8-byte protocol tag
  (rpc/tcp.py PROTOCOL_VERSION); a server answers a recognizable but
  mismatched tag with ITS OWN tag before closing (the
  getServerProtocol analogue), so discovery needs no compatible
  library at all;
- versioned copies of the C library are built with
  `make versioned PROTOCOL=fdbtpuNN` (bindings/c/Makefile), each
  exporting fdb_tpu_get_protocol();
- MultiVersionClient dlopens every copy it is given, probes the
  cluster, and hands out CDatabase handles backed by the matching
  library.
"""

from __future__ import annotations

import socket
from typing import Dict, Optional

from ..rpc.tcp import _read_exact
from .c_client import CDatabase, load_library_at

#: a tag with the right magic but a version no release ever shipped:
#: every server mismatches it and answers with its own tag
PROBE_TAG = b"fdbtpu??"


def probe_cluster_protocol(host: str, port: int,
                           timeout: float = 10.0) -> Optional[bytes]:
    """Discover the cluster's wire-protocol tag (ref:
    getServerProtocol): send a never-matching probe tag; the server
    replies with its own tag and closes. Returns None when the peer
    gives nothing back (pre-versioning server or not our protocol)."""
    with socket.create_connection((host, port), timeout=timeout) as s:
        s.sendall(PROBE_TAG)
        s.settimeout(timeout)
        got = _read_exact(s, len(PROBE_TAG))
    return got


class MultiVersionClient:
    """Holds protocol-versioned C libraries and opens databases through
    whichever one speaks the cluster's protocol (ref: MultiVersionApi
    + MultiVersionDatabase routing to the matching external client)."""

    def __init__(self, library_paths):
        """`library_paths`: iterable of .so paths (each a versioned
        build of bindings/c). Tags are read from the libraries
        themselves via fdb_tpu_get_protocol()."""
        self.libs: Dict[bytes, object] = {}
        #: (path, reason) for libraries that could not be versioned —
        #: a pre-versioning build has no discoverable protocol, so it
        #: can never be route target; keep the evidence for errors
        self.skipped: list = []
        for path in library_paths:
            lib = load_library_at(path)
            try:
                tag = lib.fdb_tpu_get_protocol()
            except AttributeError:
                self.skipped.append(
                    (path, "predates protocol versioning "
                           "(no fdb_tpu_get_protocol export)"))
                continue
            self.libs[tag] = lib

    def protocols(self):
        return sorted(self.libs)

    def open(self, host: str, port: int) -> CDatabase:
        """Probe the cluster, select the matching library, connect.
        Raises RuntimeError when no loaded library speaks the
        cluster's protocol (the reference surfaces the same as an
        incompatible-client error)."""
        tag = probe_cluster_protocol(host, port)
        if tag is None:
            raise RuntimeError(
                "cluster protocol undiscoverable (peer answered the "
                "probe with nothing)")
        lib = self.libs.get(tag)
        if lib is None:
            extra = "".join(f"; skipped {p} ({why})"
                            for p, why in self.skipped)
            raise RuntimeError(
                f"no client library for cluster protocol {tag!r}; "
                f"loaded: {self.protocols()}{extra}")
        return CDatabase(host, port, lib=lib)
