"""Error model: numbered errors matching the reference's registry.

Reference: flow/error_definitions.h, flow/Error.h. Error codes are kept
numerically identical so that clients/tools written against the reference's
error surface behave the same here.
"""

from __future__ import annotations


class FdbError(Exception):
    """A numbered framework error (ref: flow/Error.h `class Error`)."""

    __slots__ = ("code", "name")

    def __init__(self, name: str, code: int, message: str = ""):
        super().__init__(message or name)
        self.name = name
        self.code = code

    def __repr__(self) -> str:  # pragma: no cover
        return f"FdbError({self.name}, {self.code})"

    def __eq__(self, other) -> bool:
        return isinstance(other, FdbError) and other.code == self.code

    def __hash__(self) -> int:
        return hash(self.code)

    def is_retryable(self) -> bool:
        """Client retry classification (ref: fdbclient/NativeAPI.actor.cpp onError)."""
        return self.code in _RETRYABLE

    def clone(self) -> "FdbError":
        return FdbError(self.name, self.code, str(self))


_REGISTRY: dict[str, tuple[int, str]] = {}


def _define(name: str, code: int, message: str) -> None:
    _REGISTRY[name] = (code, message)


# Subset of flow/error_definitions.h used by this framework; codes identical.
_define("success", 0, "Success")
_define("end_of_stream", 1, "End of stream")
_define("operation_failed", 1000, "Operation failed")
_define("wrong_shard_server", 1001, "Shard is not available from this server")
_define("timed_out", 1004, "Operation timed out")
_define("coordinated_state_conflict", 1005, "Conflict occurred while changing coordination information")
_define("all_alternatives_failed", 1006, "All alternatives failed")
_define("transaction_too_old", 1007, "Transaction is too old to perform reads or be committed")
_define("no_more_servers", 1008, "Not enough physical servers available")
_define("future_version", 1009, "Request for future version")
_define("tlog_stopped", 1011, "TLog stopped")
_define("proxy_memory_limit_exceeded", 1042,
        "Proxy commit memory limit exceeded")
_define("server_request_queue_full", 1012, "Server request queue is full")
_define("not_committed", 1020, "Transaction not committed due to conflict with another transaction")
_define("commit_unknown_result", 1021, "Transaction may or may not have committed")
_define("transaction_cancelled", 1025, "Operation aborted because the transaction was cancelled")
_define("connection_failed", 1026, "Network connection failed")
_define("coordinators_changed", 1027, "Coordination servers have changed")
_define("request_maybe_delivered", 1030, "Request may or may not have been delivered")
_define("transaction_timed_out", 1031, "Operation aborted because the transaction timed out")
_define("process_behind", 1037, "Storage process does not have recent mutations")
_define("database_locked", 1038, "Database is locked")
_define("broken_promise", 1100, "Broken promise")
_define("operation_cancelled", 1101, "Asynchronous operation cancelled")
_define("future_released", 1102, "Future has been released")
_define("worker_removed", 1202, "Normal worker shut down")
_define("master_recovery_failed", 1203, "Master recovery failed")
_define("master_tlog_failed", 1205, "Master terminating because a TLog failed")
_define("please_reboot", 1207, "Reboot of server process requested")
_define("please_reboot_delete", 1208, "Reboot of server process requested, with deletion of state")
_define("master_proxy_failed", 1209, "Master terminating because a Proxy failed")
_define("master_resolver_failed", 1210, "Master terminating because a Resolver failed")
_define("tag_throttled", 1213, "Transaction tag is being throttled")
_define("platform_error", 1500, "Platform error")
_define("io_error", 1510, "Disk i/o operation failed")
_define("file_not_found", 1511, "File not found")
_define("checksum_failed", 1520, "A data checksum failed")
_define("io_timeout", 1521, "A disk IO operation failed to complete in a timely manner")
_define("file_corrupt", 1522, "A structurally corrupt data file was detected")
_define("client_invalid_operation", 2000, "Invalid API call")
_define("key_outside_legal_range", 2004, "Key outside legal range")
_define("inverted_range", 2005, "Range begin key larger than end key")
_define("invalid_option_value", 2006, "Option set with an invalid value")
_define("too_many_tags", 2114, "Too many tags set on transaction")
_define("tag_too_long", 2115, "Tag set on transaction is too long")
_define("used_during_commit", 2017, "Operation issued while a commit was outstanding")
_define("key_too_large", 2102, "Key length exceeds limit")
_define("value_too_large", 2103, "Value length exceeds limit")
_define("transaction_too_large", 2101, "Transaction exceeds byte limit")
_define("unknown_error", 4000, "An unknown error occurred")
_define("internal_error", 4100, "An internal error occurred")

# Errors on which fdb clients retry the transaction (ref: NativeAPI onError
# retries exactly: transaction_too_old, future_version, not_committed,
# commit_unknown_result, process_behind, database_locked,
# proxy_memory_limit_exceeded, tag_throttled):
_RETRYABLE = frozenset({1007, 1009, 1020, 1021, 1037, 1038, 1042, 1213})


def error(name: str, message: str = "") -> FdbError:
    """Construct a fresh error instance by name, e.g. ``error("not_committed")``.

    ``message`` overrides the registry's default text (the code always
    comes from the registry, so diagnosis-carrying errors stay
    numerically identical to plain ones)."""
    code, msg = _REGISTRY[name]
    return FdbError(name, code, message or msg)


class ActorCancelled(FdbError):
    """Raised inside an actor when it is cancelled (ref: actor_cancelled).

    Distinct subclass so the scheduler can throw it into coroutines and
    distinguish cancellation from user errors.
    """

    def __init__(self):
        super().__init__("operation_cancelled", 1101, "Asynchronous operation cancelled")


def internal_error(msg: str = "") -> FdbError:
    return FdbError("internal_error", 4100, msg or "An internal error occurred")
