"""Deterministic event loop with task priorities and virtual time.

Reference: flow/Net2.actor.cpp (`Net2::run` :558, ready/timer queues
:183-191) and flow/network.h:33-76 (numeric task priorities). Unlike the
reference, virtual time is the *default* — the deterministic simulator is
the primary runtime (ref: fdbrpc/sim2.actor.cpp), and wall-clock execution
is a mode layered on top.

Determinism contract: given the same seed and the same spawn/send sequence,
the loop executes steps in an identical order. Ready tasks run
highest-priority first, FIFO within a priority; timers fire in (time, seq)
order; time advances only when no task is ready.
"""

from __future__ import annotations

import heapq
import time as _time
from bisect import bisect_right
from typing import Any, Coroutine, Optional

from .error import FdbError, error
from .future import Future, Task

# Task priorities (ref: flow/network.h:33-76). Higher runs first.
class TaskPriority:
    MAX = 1000000
    RUN_LOOP = 30000
    WRITE_SOCKET = 10000
    READ_SOCKET = 9000
    COORDINATION_REPLY = 8810
    COORDINATION = 8800
    FAILURE_MONITOR = 8700
    RESOLUTION_METRICS = 8700
    CLUSTER_CONTROLLER = 8650
    PROXY_COMMIT_DISPATCH = 8640
    TLOG_QUEUING_METRICS = 8620
    TLOG_POP = 8610
    TLOG_PEEK_REPLY = 8600
    TLOG_PEEK = 8590
    TLOG_COMMIT_REPLY = 8580
    TLOG_COMMIT = 8570
    PROXY_GET_RAW_COMMITTED_VERSION = 8565
    PROXY_RESOLVER_REPLY = 8560
    PROXY_COMMIT_BATCHER = 8550
    PROXY_COMMIT = 8540
    TLOG_CONFIRM_RUNNING_REPLY = 8530
    TLOG_CONFIRM_RUNNING = 8520
    PROXY_GRV_TIMER = 8510
    PROXY_GET_CONSISTENT_READ_VERSION = 8500
    DISK_IO_LATENCY = 8100
    DEFAULT_PROMISE_ENDPOINT = 8000
    DEFAULT_ON_MAIN_THREAD = 7500
    DEFAULT_ENDPOINT = 7000
    UNKNOWN_ENDPOINT = 6000
    MOVE_KEYS = 3550
    DATA_DISTRIBUTION_LAUNCH = 3530
    RATEKEEPER = 3510
    DATA_DISTRIBUTION = 3500
    STORAGE = 3000
    UPDATE_STORAGE = 3000
    LOW_PRIORITY = 2000
    ZERO = 0


# Priority bands for the task-stats rollup: every named TaskPriority
# level, deduplicated (first name wins for aliases like
# STORAGE/UPDATE_STORAGE) and sorted ascending. A step's band is the
# highest named level at or below its popped priority, so custom
# priorities between levels fold into the level they outrank.
def _build_priority_bands():
    seen: dict = {}
    for n, v in vars(TaskPriority).items():
        if not n.startswith("_") and isinstance(v, int):
            seen.setdefault(v, n.lower())
    return sorted(seen.items())


_PRIORITY_BANDS = _build_priority_bands()
_PRIORITY_BAND_KEYS = [v for v, _n in _PRIORITY_BANDS]


def priority_band(priority: int) -> str:
    """The named TaskPriority band a numeric priority rolls up into."""
    i = bisect_right(_PRIORITY_BAND_KEYS, priority) - 1
    return _PRIORITY_BANDS[max(i, 0)][1]


# steps per coarse busy-accounting window (see Scheduler._flush_coarse)
_COARSE_WINDOW = 4096


class WakeSignal:
    """Coalesced-timer helper for periodic run loops (the sim-perf
    plane's top band was fixed-interval polling loops ticking through
    empty queues — ROADMAP item 6). A loop that would otherwise poll
    every interval parks on the signal while its queues are empty and
    is resumed by the producer's ``touch()``:

        while True:
            if queue_empty:
                await signal.wait_beyond(signal.count)
            await flow.delay(interval, prio)
            ... drain ...

    ``touch()`` is O(1) and allocation-free when nothing is parked (the
    hot producer path pays a counter bump and an empty-list check);
    parking allocates one Future per idle period, not per interval.
    Waiters resume through the ordinary ready queue at their task
    priority, so adopting the helper never reorders a loop relative to
    the priority band it already ran in."""

    __slots__ = ("_count", "_waiters")

    def __init__(self):
        self._count = 0
        self._waiters: list = []

    @property
    def count(self) -> int:
        """Monotone touch counter — snapshot before parking."""
        return self._count

    def touch(self) -> None:
        """Record one producer event and wake every parked waiter."""
        self._count += 1
        if self._waiters:
            waiters, self._waiters = self._waiters, []
            for f in waiters:
                if not f.is_ready:
                    f.send(None)

    def wait_beyond(self, seen: int) -> Future:
        """Future that is ready once ``count`` exceeds `seen` (already
        ready if it has). The caller re-checks its own queues after the
        wait — a wake is a hint, not a handoff."""
        if self._count > seen:
            f = Future()
            f.send(None)
            return f
        f = Future()
        self._waiters.append(f)
        return f


class _TimerCall:
    """A heap entry that runs a plain callback when its deadline fires
    — the allocation-lean alternative to a _TimerFuture + on_ready
    closure for fire-and-forget deadlines (the sim network's delivery
    timers). Quacks like an unready Future so the timer pump needs no
    extra branch."""

    __slots__ = ("fn", "args")
    is_ready = False

    def __init__(self, fn, args):
        self.fn = fn
        self.args = args

    def send(self, _value) -> None:
        self.fn(*self.args)


_knobs = None    # cached handle: the slow-task threshold is read per
                 # step and must not pay the import machinery each time


def _slow_task_threshold_knob() -> float:
    """The SLOW_TASK_THRESHOLD knob, read live (operators flip it at
    runtime); only the module lookup is cached — same idiom as the
    trace severity floor."""
    global _knobs
    if _knobs is None:
        try:
            from .knobs import SERVER_KNOBS
        except Exception:
            return 0.05
        _knobs = SERVER_KNOBS
    return float(_knobs.slow_task_threshold)


class Scheduler:
    """Single-threaded deterministic run loop (Net2 + sim2 in one).

    ``virtual=True`` (default): time advances instantly to the next timer —
    whole-system simulation. ``virtual=False``: timers wait on the wall
    clock (for real deployments/benchmarks).
    """

    def __init__(self, start_time: float = 0.0, virtual: bool = True):
        self._now = start_time
        self.virtual = virtual
        # Maps the virtual timeline onto the wall clock for virtual=False:
        # wall_time_of(t) = _wall_anchor + t.
        self._wall_anchor = _time.monotonic() - start_time
        self._ready: list = []  # heap of (-priority, seq, fn, args)
        self._timers: list = []  # heap of (time, seq, promise)
        self._seq = 0
        self._current_task: Optional[Task] = None
        self._stopped = False
        self.tasks_run = 0
        # run-loop profiler (ref: flow/Profiler.actor.cpp + Net2's slow-
        # task sampling): wall seconds spent executing steps, and the
        # worst offenders over the threshold. None follows the
        # SLOW_TASK_THRESHOLD knob live; an explicit value (tests, the
        # cli) pins it for this scheduler. A threshold of 0 disables
        # slow-task sampling entirely (it used to flag EVERY step).
        self._busy_accum = 0.0
        self.slow_task_threshold: Optional[float] = None
        self.slow_task_count = 0       # total steps over the threshold
        self.slow_tasks: list = []     # (name, seconds, suspension
        #                                stack), worst kept
        # coarse busy accounting: with every profiling consumer off
        # (no task stats, threshold 0) the loop skips the per-step
        # monotonic() pair and instead times windows of up to
        # _COARSE_WINDOW steps — two clock reads per window instead of
        # two per step — flushed whenever busy_seconds is read, the
        # loop idles/sleeps, or run() exits (so wall time spent OUTSIDE
        # the loop never counts as busy)
        self._coarse_anchor: Optional[float] = None
        self._coarse_steps = 0
        # on-demand sampling profiler (ref: flow/Profiler.actor.cpp —
        # the SIGPROF stack sampler, expressed cooperatively: every
        # Nth task step records the task's coroutine suspension stack)
        self._profile_every = 0        # 0 = off
        self._profile_samples: dict = {}
        self._profile_countdown = 0
        # per-task attribution plane (SIM_TASK_STATS — ROADMAP item 6's
        # "profile the run loop before refactoring it"): armed via
        # start_task_stats(), each step folds its wall µs into a
        # BOUNDED per-task-name table plus a per-TaskPriority-band
        # rollup. None = off (the default posture pays nothing here).
        self._task_stats: Optional[dict] = None  # name -> [steps, µs, max µs]
        self._task_stats_max = 256
        self._band_stats: dict = {}    # band -> [steps, µs]
        self._band_cache: dict = {}    # priority int -> band name
        self.task_stats_dropped = 0    # folds routed to "(other)"
        self._fold_cache: dict = {}    # raw task name -> folded family
        self._frame_cache: dict = {}   # code object @ lineno -> frame str

    # -- time ---------------------------------------------------------------
    def now(self) -> float:
        return self._now

    # -- busy accounting -----------------------------------------------------
    @property
    def busy_seconds(self) -> float:
        """Wall seconds the loop spent executing steps. Fine-grained
        (per step) while a profiling consumer is armed; coarse
        (windowed) otherwise — reading it flushes any open window."""
        if self._coarse_anchor is not None:
            self._flush_coarse()
        return self._busy_accum

    @busy_seconds.setter
    def busy_seconds(self, value: float) -> None:
        self._coarse_anchor = None
        self._coarse_steps = 0
        self._busy_accum = value

    def _flush_coarse(self) -> None:
        a = self._coarse_anchor
        if a is not None:
            self._busy_accum += _time.monotonic() - a
            self._coarse_anchor = None
            self._coarse_steps = 0

    # -- spawning -----------------------------------------------------------
    def spawn(self, coro: Coroutine, priority: int = TaskPriority.DEFAULT_ENDPOINT,
              name: str = "") -> Task:
        """Start an actor; returns its Task (a Future of the return value)."""
        t = Task(coro, self, priority, name)
        self._schedule_step(t, None, None)
        return t

    def _schedule_step(self, task: Task, value, exc, priority: Optional[int] = None) -> None:
        self._seq += 1
        if priority is None:
            priority = task.priority
        heapq.heappush(self._ready, (-priority, self._seq, task, value, exc))

    def call_at_priority(self, priority: int, fn, *args) -> None:
        """Run a plain callable from the loop at the given priority."""
        async def _runner():
            fn(*args)
        self.spawn(_runner(), priority, name=getattr(fn, "__name__", "call"))

    # -- timers -------------------------------------------------------------
    def delay(self, seconds: float, priority: int = TaskPriority.DEFAULT_ENDPOINT) -> Future:
        """Future that becomes ready `seconds` from now (ref: flow delay())."""
        if seconds < 0:
            seconds = 0.0
        f = _TimerFuture(self, priority)
        f.resume_priority = priority  # waiter resumes at the delay's priority
        self._seq += 1
        entry = (self._now + seconds, self._seq, f)
        f._entry = entry
        heapq.heappush(self._timers, entry)
        return f

    def yield_now(self, priority: int = TaskPriority.DEFAULT_ENDPOINT) -> Future:
        return self.delay(0.0, priority)

    def call_at(self, seconds: float, fn, *args) -> None:
        """Run `fn(*args)` when the deadline fires, straight from the
        timer pump — no Future, no waiter, no closure. The lean path
        for fire-and-forget deadlines (per-message delivery timers):
        ordering relative to delay() timers is identical (one shared
        (time, seq) heap), and the callback runs at the same point the
        equivalent _TimerFuture's on_ready callbacks would have."""
        if seconds < 0:
            seconds = 0.0
        self._seq += 1
        heapq.heappush(self._timers,
                       (self._now + seconds, self._seq, _TimerCall(fn, args)))

    # -- run loop -----------------------------------------------------------
    def _run_one(self, max_time: Optional[float] = None) -> bool:
        """Execute one step. Returns False when no work remains (or none
        before `max_time` — virtual time then rests at `max_time`)."""
        # Fire all timers due at or before now.
        while self._timers and (self._timers[0][0] <= self._now or not self._ready):
            if self._timers[0][0] > self._now:
                if self._ready:
                    break
                # advance time
                t = self._timers[0][0]
                if max_time is not None and t > max_time:
                    if not self.virtual:
                        self._flush_coarse()
                        _time.sleep(max(
                            0.0, (self._wall_anchor + max_time) - _time.monotonic()))
                    self._now = max_time  # deadline reached before any work
                    return False
                if not self.virtual:
                    self._flush_coarse()  # sleeping is not busy time
                    _time.sleep(max(0.0, (self._wall_anchor + t) - _time.monotonic()))
                self._now = t
            _, _, fut = heapq.heappop(self._timers)
            if not fut.is_ready:
                fut.send(None)
        if not self._ready:
            self._flush_coarse()   # the loop is about to go idle
            return False
        neg_prio, _, task, value, exc = heapq.heappop(self._ready)
        self.tasks_run += 1
        if self._profile_every:
            self._profile_countdown -= 1
            if self._profile_countdown <= 0:
                self._profile_countdown = self._profile_every
                self._profile_sample(task)
        stats = self._task_stats
        thr = self.slow_task_threshold
        if thr is None:
            thr = _slow_task_threshold_knob()
        if stats is None and thr <= 0.0:
            # every profiling consumer is off: skip the per-step
            # monotonic() pair — busy time accrues through the coarse
            # window (two clock reads per _COARSE_WINDOW steps)
            if self._coarse_anchor is None:
                self._coarse_anchor = _time.monotonic()
            task._step(value, exc)
            self._coarse_steps += 1
            if self._coarse_steps >= _COARSE_WINDOW:
                self._flush_coarse()
            return True
        self._flush_coarse()   # a mid-window arm must not double-count
        t0 = _time.monotonic()
        task._step(value, exc)
        dt = _time.monotonic() - t0
        self._busy_accum += dt
        if stats is not None:
            self._fold_task_stat(task, -neg_prio, dt)
        if thr > 0.0 and dt >= thr:
            # a step that hogs the loop starves every other actor — the
            # reference's slow-task profiler samples exactly this
            name = getattr(task, "name", "") or "?"
            # the coroutine is suspended at its next await (or done):
            # the suspension stack names the code location of the hog,
            # not just the actor label
            stack = self._suspension_stack(task)
            self.slow_task_count += 1
            self.slow_tasks.append((name, dt, stack))
            if len(self.slow_tasks) > 32:
                self.slow_tasks = sorted(
                    self.slow_tasks, key=lambda s: -s[1])[:16]
            from .trace import SevWarn
            from . import trace as _trace
            _trace.g_trace.emit({
                "Type": "SlowTask", "Severity": SevWarn,
                "Machine": "runloop", "TaskName": name,
                "Seconds": round(dt, 4),
                "ElapsedUs": int(dt * 1e6),
                "Stack": stack})
        return True

    def run(self, until: Optional[Future] = None, timeout_time: Optional[float] = None) -> Any:
        """Run until `until` is ready (returning its value), or until idle.

        Raises ``timed_out`` if virtual time passes `timeout_time` first, and
        ``operation_failed`` on deadlock (until-future pending but no work).
        """
        try:
            while not self._stopped:
                if until is not None and until.is_ready:
                    return until.get()
                if timeout_time is not None and self._now >= timeout_time:
                    raise error("timed_out")
                if not self._run_one(max_time=timeout_time):
                    if timeout_time is not None and \
                            self._now >= timeout_time:
                        raise error("timed_out")
                    break
        finally:
            # close any open coarse window: wall time between run()
            # calls must never read as loop busy time
            self._flush_coarse()
        if until is not None:
            if until.is_ready:
                return until.get()
            raise FdbError("operation_failed", 1000,
                           "simulation deadlock: awaited future never became ready")
        return None

    def stop(self) -> None:
        self._stopped = True

    # -- per-task attribution (SIM_TASK_STATS) ------------------------------
    def start_task_stats(self, max_names: Optional[int] = None) -> None:
        """Arm per-task run-loop accounting: every step folds its wall
        µs into a bounded per-task-name table (trailing digits collapse
        — `storm-txn-17` folds into `storm-txn-*`) and a per-
        TaskPriority-band rollup. Costless until armed."""
        if max_names is None:
            try:
                from .knobs import SERVER_KNOBS
                max_names = int(SERVER_KNOBS.sim_task_stats_max_names)
            except Exception:
                max_names = 256
        self._task_stats_max = max(1, max_names)
        self._task_stats = {}
        self._band_stats = {}
        self._band_cache = {}
        self.task_stats_dropped = 0

    @property
    def task_stats_armed(self) -> bool:
        return self._task_stats is not None

    def stop_task_stats(self) -> dict:
        """Disarm and return the final report."""
        report = self.task_stats_report()
        self._task_stats = None
        return report

    def _fold_task_stat(self, task, priority: int, dt: float) -> None:
        st = self._task_stats
        raw = getattr(task, "name", "") or "?"
        # the rstrip + compare per step adds up at 10^5 steps/sec; raw
        # names repeat heavily (pooled actors, role loops), so the
        # folded family is memoized (bounded: one-shot names fold to a
        # small family set, but a pathological namer must not grow it)
        name = self._fold_cache.get(raw)
        if name is None:
            base = raw.rstrip("0123456789")
            # indexed spawns fold into one family
            name = base + "*" if base != raw else raw
            if len(self._fold_cache) >= 4096:
                self._fold_cache.clear()
            self._fold_cache[raw] = name
        rec = st.get(name)
        if rec is None:
            if len(st) >= self._task_stats_max:
                # bounded table: late-arriving names share one bucket
                self.task_stats_dropped += 1
                name = "(other)"
                rec = st.get(name)
            if rec is None:
                st[name] = rec = [0, 0.0, 0.0]
        us = dt * 1e6
        rec[0] += 1
        rec[1] += us
        if us > rec[2]:
            rec[2] = us
        band = self._band_cache.get(priority)
        if band is None:
            band = self._band_cache[priority] = priority_band(priority)
        brec = self._band_stats.get(band)
        if brec is None:
            self._band_stats[band] = brec = [0, 0.0]
        brec[0] += 1
        brec[1] += us

    def task_stats_report(self, top_k: Optional[int] = None) -> dict:
        """-> {armed, tasks: [{task, steps, busy_us, max_us}] (busiest
        first), bands: [{band, steps, busy_us}], dropped_names}."""
        tasks = [{"task": n, "steps": r[0], "busy_us": round(r[1], 1),
                  "max_us": round(r[2], 1)}
                 for n, r in (self._task_stats or {}).items()]
        tasks.sort(key=lambda row: (-row["busy_us"], row["task"]))
        if top_k is not None:
            tasks = tasks[:top_k]
        bands = [{"band": b, "steps": r[0], "busy_us": round(r[1], 1)}
                 for b, r in sorted(self._band_stats.items(),
                                    key=lambda kv: (-kv[1][1], kv[0]))]
        return {"armed": int(self._task_stats is not None),
                "tasks": tasks, "bands": bands,
                "dropped_names": self.task_stats_dropped}

    # -- sampling profiler --------------------------------------------------
    def _frame_walk(self, task) -> list:
        """The coroutine suspension stack, innermost last — shared by
        the sampling profiler and the SlowTask capture."""
        frames = []
        coro = getattr(task, "_coro", None)
        depth = 0
        cache = self._frame_cache
        while coro is not None and depth < 32:
            frame = getattr(coro, "cr_frame", None)
            if frame is None:
                break
            code = frame.f_code
            # suspension points repeat across samples: memoize the
            # formatted frame per (code, lineno) so the sampling
            # profiler stops re-rendering the same few hot locations
            key = (code, frame.f_lineno)
            s = cache.get(key)
            if s is None:
                if len(cache) >= 4096:
                    cache.clear()
                s = cache[key] = (
                    f"{code.co_name} "
                    f"({code.co_filename.rsplit('/', 1)[-1]}"
                    f":{frame.f_lineno})")
            frames.append(s)
            coro = getattr(coro, "cr_await", None)
            depth += 1
        return frames

    def _suspension_stack(self, task) -> str:
        return " <- ".join(reversed(self._frame_walk(task))) or "?"

    def _profile_sample(self, task) -> None:
        key = (getattr(task, "name", "") or "?",
               self._suspension_stack(task))
        self._profile_samples[key] = self._profile_samples.get(key, 0) + 1

    def start_profiler(self, sample_every: int = 16) -> None:
        """Sample every Nth task step until stop_profiler() (ref: the
        on-demand ProfilerRequest turning SIGPROF sampling on)."""
        self._profile_every = max(1, sample_every)
        self._profile_countdown = 1
        self._profile_samples = {}

    def stop_profiler(self) -> list:
        """-> [{task, stack, samples}] sorted by sample count."""
        self._profile_every = 0
        out = [{"task": t, "stack": st, "samples": n}
               for (t, st), n in self._profile_samples.items()]
        out.sort(key=lambda e: -e["samples"])
        return out

    def profile_folded(self) -> str:
        """The sampling profiler's stacks in collapsed/folded format
        (`frame;frame;frame count`, root first — flamegraph.pl /
        speedscope ready). The display stacks read leaf-first
        ("inner <- outer"), so they re-reverse here. Frames are
        space-stripped: the folded format splits the trailing count
        on whitespace."""
        lines = []
        for (t, st), n in sorted(self._profile_samples.items()):
            frames = [t.replace(" ", "").replace(";", ":") or "?"]
            if st != "?":
                frames.extend(f.strip().replace(" ", "")
                              .replace(";", ":")
                              for f in reversed(st.split(" <- ")))
            lines.append(";".join(frames) + f" {n}")
        return "\n".join(lines)


class _TimerFuture(Future):
    __slots__ = ("_sched", "_entry", "resume_priority")

    def __init__(self, sched: Scheduler, priority: int):
        super().__init__()
        self._sched = sched
        self._entry = None
        self.resume_priority = priority

    def cancel(self) -> None:
        if not self.is_ready:
            self.send_error(FdbError("operation_cancelled", 1101))


# --- ambient scheduler -----------------------------------------------------
# One active scheduler per THREAD (like g_network): the simulator owns
# its thread's loop, while an out-of-process client (client/remote.py)
# may host a second wall-clock loop on its own thread in the same
# process without clobbering the sim's.
import threading as _threading


class _Ambient(_threading.local):
    current: Optional[Scheduler] = None


_tls = _Ambient()


def set_scheduler(s: Optional[Scheduler]) -> None:
    _tls.current = s


def get_scheduler() -> Optional[Scheduler]:
    """The thread's ambient scheduler, or None — the save half of the
    save/restore discipline tools hosting their OWN loop must follow
    (tools/networktest.py, tools/clusterbench.py): a tool that leaves
    its private scheduler installed corrupts whatever flow-driven
    caller invoked it."""
    return _tls.current


def g() -> Scheduler:
    if _tls.current is None:
        raise error("internal_error")
    return _tls.current


def now() -> float:
    return g().now()


def delay(seconds: float, priority: int = TaskPriority.DEFAULT_ENDPOINT) -> Future:
    return g().delay(seconds, priority)


def spawn(coro, priority: int = TaskPriority.DEFAULT_ENDPOINT, name: str = "") -> Task:
    return g().spawn(coro, priority, name)
