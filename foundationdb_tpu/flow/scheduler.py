"""Deterministic event loop with task priorities and virtual time.

Reference: flow/Net2.actor.cpp (`Net2::run` :558, ready/timer queues
:183-191) and flow/network.h:33-76 (numeric task priorities). Unlike the
reference, virtual time is the *default* — the deterministic simulator is
the primary runtime (ref: fdbrpc/sim2.actor.cpp), and wall-clock execution
is a mode layered on top.

Determinism contract: given the same seed and the same spawn/send sequence,
the loop executes steps in an identical order. Ready tasks run
highest-priority first, FIFO within a priority; timers fire in (time, seq)
order; time advances only when no task is ready.
"""

from __future__ import annotations

import heapq
import time as _time
from typing import Any, Coroutine, Optional

from .error import FdbError, error
from .future import Future, Task

# Task priorities (ref: flow/network.h:33-76). Higher runs first.
class TaskPriority:
    MAX = 1000000
    RUN_LOOP = 30000
    WRITE_SOCKET = 10000
    READ_SOCKET = 9000
    COORDINATION_REPLY = 8810
    COORDINATION = 8800
    FAILURE_MONITOR = 8700
    RESOLUTION_METRICS = 8700
    CLUSTER_CONTROLLER = 8650
    PROXY_COMMIT_DISPATCH = 8640
    TLOG_QUEUING_METRICS = 8620
    TLOG_POP = 8610
    TLOG_PEEK_REPLY = 8600
    TLOG_PEEK = 8590
    TLOG_COMMIT_REPLY = 8580
    TLOG_COMMIT = 8570
    PROXY_GET_RAW_COMMITTED_VERSION = 8565
    PROXY_RESOLVER_REPLY = 8560
    PROXY_COMMIT_BATCHER = 8550
    PROXY_COMMIT = 8540
    TLOG_CONFIRM_RUNNING_REPLY = 8530
    TLOG_CONFIRM_RUNNING = 8520
    PROXY_GRV_TIMER = 8510
    PROXY_GET_CONSISTENT_READ_VERSION = 8500
    DISK_IO_LATENCY = 8100
    DEFAULT_PROMISE_ENDPOINT = 8000
    DEFAULT_ON_MAIN_THREAD = 7500
    DEFAULT_ENDPOINT = 7000
    UNKNOWN_ENDPOINT = 6000
    MOVE_KEYS = 3550
    DATA_DISTRIBUTION_LAUNCH = 3530
    RATEKEEPER = 3510
    DATA_DISTRIBUTION = 3500
    STORAGE = 3000
    UPDATE_STORAGE = 3000
    LOW_PRIORITY = 2000
    ZERO = 0


_knobs = None    # cached handle: the slow-task threshold is read per
                 # step and must not pay the import machinery each time


def _slow_task_threshold_knob() -> float:
    """The SLOW_TASK_THRESHOLD knob, read live (operators flip it at
    runtime); only the module lookup is cached — same idiom as the
    trace severity floor."""
    global _knobs
    if _knobs is None:
        try:
            from .knobs import SERVER_KNOBS
        except Exception:
            return 0.05
        _knobs = SERVER_KNOBS
    return float(_knobs.slow_task_threshold)


class Scheduler:
    """Single-threaded deterministic run loop (Net2 + sim2 in one).

    ``virtual=True`` (default): time advances instantly to the next timer —
    whole-system simulation. ``virtual=False``: timers wait on the wall
    clock (for real deployments/benchmarks).
    """

    def __init__(self, start_time: float = 0.0, virtual: bool = True):
        self._now = start_time
        self.virtual = virtual
        # Maps the virtual timeline onto the wall clock for virtual=False:
        # wall_time_of(t) = _wall_anchor + t.
        self._wall_anchor = _time.monotonic() - start_time
        self._ready: list = []  # heap of (-priority, seq, fn, args)
        self._timers: list = []  # heap of (time, seq, promise)
        self._seq = 0
        self._current_task: Optional[Task] = None
        self._stopped = False
        self.tasks_run = 0
        # run-loop profiler (ref: flow/Profiler.actor.cpp + Net2's slow-
        # task sampling): wall seconds spent executing steps, and the
        # worst offenders over the threshold. None follows the
        # SLOW_TASK_THRESHOLD knob live; an explicit value (tests, the
        # cli) pins it for this scheduler.
        self.busy_seconds = 0.0
        self.slow_task_threshold: Optional[float] = None
        self.slow_task_count = 0       # total steps over the threshold
        self.slow_tasks: list = []     # (task name, seconds), worst kept
        # on-demand sampling profiler (ref: flow/Profiler.actor.cpp —
        # the SIGPROF stack sampler, expressed cooperatively: every
        # Nth task step records the task's coroutine suspension stack)
        self._profile_every = 0        # 0 = off
        self._profile_samples: dict = {}
        self._profile_countdown = 0

    # -- time ---------------------------------------------------------------
    def now(self) -> float:
        return self._now

    # -- spawning -----------------------------------------------------------
    def spawn(self, coro: Coroutine, priority: int = TaskPriority.DEFAULT_ENDPOINT,
              name: str = "") -> Task:
        """Start an actor; returns its Task (a Future of the return value)."""
        t = Task(coro, self, priority, name)
        self._schedule_step(t, None, None)
        return t

    def _schedule_step(self, task: Task, value, exc, priority: Optional[int] = None) -> None:
        self._seq += 1
        if priority is None:
            priority = task.priority
        heapq.heappush(self._ready, (-priority, self._seq, task, value, exc))

    def call_at_priority(self, priority: int, fn, *args) -> None:
        """Run a plain callable from the loop at the given priority."""
        async def _runner():
            fn(*args)
        self.spawn(_runner(), priority, name=getattr(fn, "__name__", "call"))

    # -- timers -------------------------------------------------------------
    def delay(self, seconds: float, priority: int = TaskPriority.DEFAULT_ENDPOINT) -> Future:
        """Future that becomes ready `seconds` from now (ref: flow delay())."""
        if seconds < 0:
            seconds = 0.0
        f = _TimerFuture(self, priority)
        f.resume_priority = priority  # waiter resumes at the delay's priority
        self._seq += 1
        entry = (self._now + seconds, self._seq, f)
        f._entry = entry
        heapq.heappush(self._timers, entry)
        return f

    def yield_now(self, priority: int = TaskPriority.DEFAULT_ENDPOINT) -> Future:
        return self.delay(0.0, priority)

    # -- run loop -----------------------------------------------------------
    def _run_one(self, max_time: Optional[float] = None) -> bool:
        """Execute one step. Returns False when no work remains (or none
        before `max_time` — virtual time then rests at `max_time`)."""
        # Fire all timers due at or before now.
        while self._timers and (self._timers[0][0] <= self._now or not self._ready):
            if self._timers[0][0] > self._now:
                if self._ready:
                    break
                # advance time
                t = self._timers[0][0]
                if max_time is not None and t > max_time:
                    if not self.virtual:
                        _time.sleep(max(
                            0.0, (self._wall_anchor + max_time) - _time.monotonic()))
                    self._now = max_time  # deadline reached before any work
                    return False
                if not self.virtual:
                    _time.sleep(max(0.0, (self._wall_anchor + t) - _time.monotonic()))
                self._now = t
            _, _, fut = heapq.heappop(self._timers)
            if not fut.is_ready:
                fut.send(None)
        if not self._ready:
            return False
        _, _, task, value, exc = heapq.heappop(self._ready)
        self.tasks_run += 1
        if self._profile_every:
            self._profile_countdown -= 1
            if self._profile_countdown <= 0:
                self._profile_countdown = self._profile_every
                self._profile_sample(task)
        t0 = _time.monotonic()
        task._step(value, exc)
        dt = _time.monotonic() - t0
        self.busy_seconds += dt
        thr = self.slow_task_threshold
        if thr is None:
            thr = _slow_task_threshold_knob()
        if dt >= thr:
            # a step that hogs the loop starves every other actor — the
            # reference's slow-task profiler samples exactly this
            name = getattr(task, "name", "") or "?"
            self.slow_task_count += 1
            self.slow_tasks.append((name, dt))
            if len(self.slow_tasks) > 32:
                self.slow_tasks = sorted(
                    self.slow_tasks, key=lambda s: -s[1])[:16]
            from .trace import SevWarn
            from . import trace as _trace
            _trace.g_trace.emit({
                "Type": "SlowTask", "Severity": SevWarn,
                "Machine": "runloop", "TaskName": name,
                "Seconds": round(dt, 4),
                "ElapsedUs": int(dt * 1e6)})
        return True

    def run(self, until: Optional[Future] = None, timeout_time: Optional[float] = None) -> Any:
        """Run until `until` is ready (returning its value), or until idle.

        Raises ``timed_out`` if virtual time passes `timeout_time` first, and
        ``operation_failed`` on deadlock (until-future pending but no work).
        """
        while not self._stopped:
            if until is not None and until.is_ready:
                return until.get()
            if timeout_time is not None and self._now >= timeout_time:
                raise error("timed_out")
            if not self._run_one(max_time=timeout_time):
                if timeout_time is not None and self._now >= timeout_time:
                    raise error("timed_out")
                break
        if until is not None:
            if until.is_ready:
                return until.get()
            raise FdbError("operation_failed", 1000,
                           "simulation deadlock: awaited future never became ready")
        return None

    def stop(self) -> None:
        self._stopped = True

    # -- sampling profiler --------------------------------------------------
    def _profile_sample(self, task) -> None:
        frames = []
        coro = getattr(task, "_coro", None)
        depth = 0
        while coro is not None and depth < 32:
            frame = getattr(coro, "cr_frame", None)
            if frame is None:
                break
            code = frame.f_code
            frames.append(f"{code.co_name} "
                          f"({code.co_filename.rsplit('/', 1)[-1]}"
                          f":{frame.f_lineno})")
            coro = getattr(coro, "cr_await", None)
            depth += 1
        key = (getattr(task, "name", "") or "?",
               " <- ".join(reversed(frames)) or "?")
        self._profile_samples[key] = self._profile_samples.get(key, 0) + 1

    def start_profiler(self, sample_every: int = 16) -> None:
        """Sample every Nth task step until stop_profiler() (ref: the
        on-demand ProfilerRequest turning SIGPROF sampling on)."""
        self._profile_every = max(1, sample_every)
        self._profile_countdown = 1
        self._profile_samples = {}

    def stop_profiler(self) -> list:
        """-> [{task, stack, samples}] sorted by sample count."""
        self._profile_every = 0
        out = [{"task": t, "stack": st, "samples": n}
               for (t, st), n in self._profile_samples.items()]
        out.sort(key=lambda e: -e["samples"])
        return out


class _TimerFuture(Future):
    __slots__ = ("_sched", "_entry", "resume_priority")

    def __init__(self, sched: Scheduler, priority: int):
        super().__init__()
        self._sched = sched
        self._entry = None
        self.resume_priority = priority

    def cancel(self) -> None:
        if not self.is_ready:
            self.send_error(FdbError("operation_cancelled", 1101))


# --- ambient scheduler -----------------------------------------------------
# One active scheduler per THREAD (like g_network): the simulator owns
# its thread's loop, while an out-of-process client (client/remote.py)
# may host a second wall-clock loop on its own thread in the same
# process without clobbering the sim's.
import threading as _threading


class _Ambient(_threading.local):
    current: Optional[Scheduler] = None


_tls = _Ambient()


def set_scheduler(s: Optional[Scheduler]) -> None:
    _tls.current = s


def g() -> Scheduler:
    if _tls.current is None:
        raise error("internal_error")
    return _tls.current


def now() -> float:
    return g().now()


def delay(seconds: float, priority: int = TaskPriority.DEFAULT_ENDPOINT) -> Future:
    return g().delay(seconds, priority)


def spawn(coro, priority: int = TaskPriority.DEFAULT_ENDPOINT, name: str = "") -> Task:
    return g().spawn(coro, priority, name)
