"""Structured trace events.

Reference: flow/Trace.h:140 (`TraceEvent(severity, name, id).detail(...)`),
FileTraceLogWriter / JsonTraceLogFormatter. Events are structured dicts
collected in-memory (for tests/simulation) and optionally streamed to a
JSON-lines file (the reference's JSON trace format).
"""

from __future__ import annotations

import json
from typing import Any, Optional

SevDebug = 5
SevInfo = 10
SevWarn = 20
SevWarnAlways = 30
SevError = 40


class TraceCollector:
    def __init__(self, path: Optional[str] = None, keep_in_memory: int = 10000):
        self.events: list[dict] = []
        self.keep = keep_in_memory
        self._fh = open(path, "a") if path else None
        self.counts: dict[str, int] = {}

    def emit(self, ev: dict) -> None:
        self.counts[ev["Type"]] = self.counts.get(ev["Type"], 0) + 1
        if self.keep:
            self.events.append(ev)
            if len(self.events) > self.keep:
                del self.events[: self.keep // 2]
        if self._fh:
            self._fh.write(json.dumps(ev) + "\n")

    def close(self) -> None:
        if self._fh:
            self._fh.close()
            self._fh = None

    def reset(self, path: Optional[str] = None) -> None:
        """Clear state and retarget the output file, in place (the ambient
        g_trace is shared by reference across modules)."""
        self.close()
        self.events.clear()
        self.counts.clear()
        self._fh = open(path, "a") if path else None


g_trace = TraceCollector()


def reset_trace(path: Optional[str] = None) -> TraceCollector:
    g_trace_batch.dump()   # sampled events survive into the stream
    g_trace.reset(path)
    return g_trace


class TraceEvent:
    """``TraceEvent("Name", id).detail(Key=value)...`` — emits on __del__ or .log()."""

    __slots__ = ("_ev", "_logged")

    def __init__(self, name: str, id: str = "", severity: int = SevInfo):
        t = None
        try:  # time is the scheduler's virtual clock when one is running
            from .scheduler import g
            t = g().now()
        except Exception:
            t = 0.0
        self._ev = {"Severity": severity, "Time": t, "Type": name, "ID": id}
        self._logged = False

    def detail(self, **kwargs: Any) -> "TraceEvent":
        self._ev.update(kwargs)
        return self

    def log(self) -> None:
        if not self._logged:
            self._logged = True
            g_trace.emit(self._ev)

    def __del__(self):
        try:
            self.log()
        except Exception:
            pass


class TraceBatch:
    """Cross-role latency stitching for SAMPLED transactions (ref:
    g_traceBatch, flow/Trace.h:107 — attach/event pairs with a shared
    debug id let a tool reassemble one transaction's path across the
    client, proxy, resolver, and log). Events buffer here (bounded —
    the oldest spill into the trace stream, like the reference's
    periodic dump) and can be flushed or queried by id."""

    MAX_BUFFERED = 4096

    def __init__(self):
        self._events: list = []
        self._seq = 0   # insertion order: same-tick events must stitch
                        # causally, not alphabetically by location

    def add_event(self, event_type: str, debug_id, location: str) -> None:
        t = 0.0
        try:
            from .scheduler import g
            t = g().now()
        except Exception:
            pass
        self._seq += 1
        self._events.append((t, self._seq, event_type, debug_id, location))
        if len(self._events) > self.MAX_BUFFERED:
            # spill the OLDEST half only: in-flight stitches keep their
            # recent legs queryable in memory
            self.dump(self._events[:self.MAX_BUFFERED // 2])
            del self._events[:self.MAX_BUFFERED // 2]

    def add_events(self, debug_ids, event_type: str, location: str) -> None:
        for d in debug_ids:
            self.add_event(event_type, d, location)

    def events(self, debug_id) -> list:
        """Causally-ordered (time, type, location) for one debug id."""
        return [(t, et, loc) for t, seq, et, d, loc
                in sorted(e for e in self._events if e[3] == debug_id)]

    def clear(self) -> None:
        self._events.clear()

    def dump(self, events=None) -> None:
        """Flush events as TraceEvents (ref: TraceBatch::dump); with no
        argument, flushes and clears the whole buffer."""
        batch = self._events if events is None else events
        for t, _seq, et, d, loc in batch:
            ev = TraceEvent(et, str(d))
            ev._ev["Time"] = t
            ev.detail(Location=loc).log()
        if events is None:
            self._events.clear()


g_trace_batch = TraceBatch()
