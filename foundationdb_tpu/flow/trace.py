"""Structured trace events.

Reference: flow/Trace.h:140 (`TraceEvent(severity, name, id).detail(...)`),
FileTraceLogWriter / JsonTraceLogFormatter. Events are structured dicts
collected in-memory (for tests/simulation) and optionally streamed to a
JSON-lines file (the reference's JSON trace format).
"""

from __future__ import annotations

import json
from typing import Any, Optional

SevDebug = 5
SevInfo = 10
SevWarn = 20
SevWarnAlways = 30
SevError = 40


class TraceCollector:
    def __init__(self, path: Optional[str] = None, keep_in_memory: int = 10000):
        self.events: list[dict] = []
        self.keep = keep_in_memory
        self._fh = open(path, "a") if path else None
        self.counts: dict[str, int] = {}

    def emit(self, ev: dict) -> None:
        self.counts[ev["Type"]] = self.counts.get(ev["Type"], 0) + 1
        if self.keep:
            self.events.append(ev)
            if len(self.events) > self.keep:
                del self.events[: self.keep // 2]
        if self._fh:
            self._fh.write(json.dumps(ev) + "\n")

    def close(self) -> None:
        if self._fh:
            self._fh.close()
            self._fh = None

    def reset(self, path: Optional[str] = None) -> None:
        """Clear state and retarget the output file, in place (the ambient
        g_trace is shared by reference across modules)."""
        self.close()
        self.events.clear()
        self.counts.clear()
        self._fh = open(path, "a") if path else None


g_trace = TraceCollector()


def reset_trace(path: Optional[str] = None) -> TraceCollector:
    g_trace.reset(path)
    return g_trace


class TraceEvent:
    """``TraceEvent("Name", id).detail(Key=value)...`` — emits on __del__ or .log()."""

    __slots__ = ("_ev", "_logged")

    def __init__(self, name: str, id: str = "", severity: int = SevInfo):
        t = None
        try:  # time is the scheduler's virtual clock when one is running
            from .scheduler import g
            t = g().now()
        except Exception:
            t = 0.0
        self._ev = {"Severity": severity, "Time": t, "Type": name, "ID": id}
        self._logged = False

    def detail(self, **kwargs: Any) -> "TraceEvent":
        self._ev.update(kwargs)
        return self

    def log(self) -> None:
        if not self._logged:
            self._logged = True
            g_trace.emit(self._ev)

    def __del__(self):
        try:
            self.log()
        except Exception:
            pass
