"""Structured trace events.

Reference: flow/Trace.h:140 (`TraceEvent(severity, name, id).detail(...)`),
FileTraceLogWriter / JsonTraceLogFormatter. Events are structured dicts
collected in-memory (for tests/simulation) and optionally streamed to a
JSON-lines file (the reference's JSON trace format). `TraceBatch` keeps
the cross-role commit-debug stitching for sampled transactions, and the
span layer on top of it (`Span` / `begin_span`) reassembles one sampled
commit's full proxy -> resolver -> tlog path as a parented tree (ref:
flow/Tracing.h Span + the g_traceBatch commit-debug locations).
"""

from __future__ import annotations

import atexit
import json
from typing import Any, Optional

from .flightrec import g_flightrec as _flightrec

SevDebug = 5
SevInfo = 10
SevWarn = 20
SevWarnAlways = 30
SevError = 40


def _now() -> float:
    try:  # time is the scheduler's virtual clock when one is running
        from .scheduler import g
        return g().now()
    except Exception:
        return 0.0


_knobs = None    # cached knobs handle: suppression must not pay the
                 # import machinery per event in hot loops


def _severity_floor() -> int:
    """Events below this severity are dropped at construction — the
    cheap filter hot loops rely on (ref: the trace file's minimum
    severity, flow/Trace.cpp suppression). The knob is read live (tests
    and operators flip it at runtime); only the module lookup is
    cached."""
    global _knobs
    if _knobs is None:
        try:
            from .knobs import SERVER_KNOBS
        except Exception:
            return 0
        _knobs = SERVER_KNOBS
    return int(_knobs.trace_severity_min)


def _roll_size_knob() -> int:
    """Max trace-file bytes before a roll (ref: FDB's trace_roll_size,
    10 MB by default — FileTraceLogWriter renames the full file and
    starts a fresh one). Same cached-handle live read as the severity
    floor; 0 disables rolling."""
    global _knobs
    if _knobs is None:
        try:
            from .knobs import SERVER_KNOBS
        except Exception:
            return 0
        _knobs = SERVER_KNOBS
    return int(_knobs.trace_roll_size)


def trace_json_escape(value):
    """``json.dumps`` fallback for TraceEvent fields that are not JSON
    types. Detail values routinely carry raw KEYS — arbitrary bytes,
    not UTF-8 — and an event line that fails to serialize (or writes a
    broken line) poisons the whole JSON-lines stream for every
    downstream parser. Bytes render with the \\xNN convention the cli
    uses for keys (printable ASCII stays readable); anything else
    falls back to repr. Always returns a str, so every event line is
    valid JSON no matter what a detail() call was handed."""
    if isinstance(value, (bytes, bytearray)):
        return "".join(chr(c) if 32 <= c < 127 and c != 0x5C
                       else f"\\x{c:02x}" for c in bytes(value))
    return repr(value)


class TraceCollector:
    def __init__(self, path: Optional[str] = None, keep_in_memory: int = 10000,
                 roll_size: Optional[int] = None):
        self.events: list[dict] = []
        self.keep = keep_in_memory
        self.counts: dict[str, int] = {}
        #: None = follow the trace_roll_size knob; explicit value wins
        self.roll_size = roll_size
        self.rolled_files: list[str] = []
        self._fh = None
        self._path: Optional[str] = None
        self._bytes = 0
        self._rolls = 0
        self._roll_broken = False   # a failed rename disables rolling
        self._set_file(path)

    def _set_file(self, path: Optional[str]) -> None:
        # line-buffered: every emitted event line reaches the OS without
        # waiting for a close that __del__-era code never guaranteed.
        # The atexit hook (registered only while a file is open, and
        # unregistered on close so short-lived collectors aren't pinned
        # for process lifetime) covers whatever the OS still buffers
        # when the interpreter goes down.
        self._path = path
        self._bytes = 0
        if path:
            self._fh = open(path, "a", buffering=1)
            try:
                import os
                self._bytes = os.fstat(self._fh.fileno()).st_size
            except OSError:
                pass   # appending to an unstattable stream: size 0
            atexit.register(self.close)

    def _roll(self) -> None:
        """Rotate the full trace file aside and start a fresh one,
        keeping the flush/atexit semantics (the atexit hook stays
        registered — it closes whichever file is current at exit)."""
        import os
        self._rolls += 1
        rolled = f"{self._path}.{self._rolls}"
        self._fh.flush()
        self._fh.close()
        atexit.unregister(self.close)   # _set_file re-registers
        try:
            os.replace(self._path, rolled)
            self.rolled_files.append(rolled)
        except OSError:
            # un-renamable target (directory went read-only, file held
            # elsewhere): stop trying — retrying would turn EVERY emit
            # into open/close/failed-rename churn against the same
            # over-limit file
            self._roll_broken = True
        self._set_file(self._path)
        if _process_identity is not None and self._fh:
            # the rolled-away segment carried the ProcessIdentity
            # header; re-stamp the fresh file so every segment is
            # self-describing (tracemerge attributes spans per segment
            # group, and a headerless segment would fall back to the
            # local-process bucket)
            self.emit({"Severity": SevInfo, "Time": _now(),
                       "Type": "ProcessIdentity", "ID": process_name(),
                       "Role": _process_identity["role"],
                       "Pid": _process_identity["pid"],
                       "Addr": _process_identity["addr"]})

    def emit(self, ev: dict) -> None:
        self.counts[ev["Type"]] = self.counts.get(ev["Type"], 0) + 1
        if _flightrec.armed:   # one attribute check while disarmed
            _flightrec.note(ev)
        if self.keep:
            self.events.append(ev)
            if len(self.events) > self.keep:
                del self.events[: self.keep // 2]
        if self._fh:
            # ensure_ascii (the default) keeps lone surrogates and
            # control characters escaped, so the line is pure ASCII;
            # the default= hook covers bytes and foreign objects
            line = json.dumps(ev, default=trace_json_escape) + "\n"
            self._fh.write(line)
            self._bytes += len(line)
            limit = (self.roll_size if self.roll_size is not None
                     else _roll_size_knob())
            if limit and self._bytes >= limit and not self._roll_broken:
                self._roll()

    @property
    def path(self) -> Optional[str]:
        """Current output file path (None while memory-only) — callers
        that retarget the shared collector save this to restore it."""
        return self._path

    def flush(self) -> None:
        if self._fh:
            self._fh.flush()

    def close(self) -> None:
        if self._fh:
            self._fh.flush()
            self._fh.close()
            self._fh = None
            atexit.unregister(self.close)

    def __enter__(self) -> "TraceCollector":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def reset(self, path: Optional[str] = None) -> None:
        """Clear state and retarget the output file, in place (the ambient
        g_trace is shared by reference across modules)."""
        self.close()
        self.events.clear()
        self.counts.clear()
        self.rolled_files.clear()
        self._rolls = 0
        self._roll_broken = False
        self._set_file(path)


g_trace = TraceCollector()


def reset_trace(path: Optional[str] = None) -> TraceCollector:
    g_trace_batch.dump()   # sampled events survive into the stream
    g_trace.reset(path)
    return g_trace


# -- process identity (ISSUE 16) ------------------------------------------
# One flow scheduler == one "process" for cross-process tracing. Span ids
# are per-process sequential, so a span is only globally unique as
# (process, span_id); roles stamp their identity here once and every
# span dump / wire hop carries it. None (the default) keeps span dump
# lines byte-identical to the pre-identity format — in-sim tests and
# same-seed replay baselines never see the new fields unless a tool
# opted in.
_process_identity: Optional[dict] = None


def set_process_identity(role: str, pid: Optional[int] = None,
                         addr: str = "") -> dict:
    """Stamp this OS process for cross-process trace reassembly: role
    name, pid, and (optionally) the gateway address it talks to. Emits
    a ProcessIdentity header event so a trace file is self-describing
    even before its first span."""
    global _process_identity
    if pid is None:
        import os
        pid = os.getpid()
    _process_identity = {"role": role, "pid": int(pid), "addr": addr}
    TraceEvent("ProcessIdentity", process_name()).detail(
        Role=role, Pid=int(pid), Addr=addr).log()
    return _process_identity


def clear_process_identity() -> None:
    global _process_identity
    _process_identity = None


def process_name() -> str:
    """The compact `role:pid` token spans and wire hops are stamped
    with ("" while no identity is set)."""
    if _process_identity is None:
        return ""
    return f"{_process_identity['role']}:{_process_identity['pid']}"


class TraceEvent:
    """``TraceEvent("Name", id).detail(Key=value)...`` — emits on
    ``.log()``, on ``__del__``, or at ``with`` exit. Events below the
    ``trace_severity_min`` knob are dropped at construction: ``detail``
    and ``log`` become no-ops, so a SevDebug event in a hot loop costs
    one knob read and a compare — no timestamp, no dict work."""

    __slots__ = ("_ev", "_logged")

    def __init__(self, name: str, id: str = "", severity: int = SevInfo):
        if severity < _severity_floor():
            self._ev = None
            self._logged = True   # suppressed: nothing to emit, ever
            return
        self._ev = {"Severity": severity, "Time": _now(),
                    "Type": name, "ID": id}
        self._logged = False

    def detail(self, **kwargs: Any) -> "TraceEvent":
        if self._ev is not None:
            self._ev.update(kwargs)
        return self

    def log(self) -> None:
        if not self._logged:
            self._logged = True
            g_trace.emit(self._ev)

    def __enter__(self) -> "TraceEvent":
        return self

    def __exit__(self, exc_type, exc, _tb) -> None:
        # the explicit form the __del__ fallback can't guarantee: emit
        # deterministically at scope exit, recording a failure if one
        # ended the scope (ref: TraceEvent::~TraceEvent logging errors).
        # An event already emitted inside the block is left untouched —
        # mutating it would diverge the in-memory copy from the file
        if exc is not None and self._ev is not None and not self._logged:
            self._ev.setdefault("Error", repr(exc))
        self.log()

    def __del__(self):
        try:
            self.log()
        except Exception:
            pass


class Span:
    """One timed leg of a sampled transaction's path (ref: flow/Tracing.h
    `Span` — begin/end timestamps plus a parent link; the commit-debug
    locations mark instants, spans mark extents). Created through
    ``TraceBatch.begin_span``; ``finish()`` (or ``with``) stamps the end
    time and files the span for ``span_chain`` reassembly."""

    __slots__ = ("batch", "debug_id", "location", "span_id", "parent_id",
                 "begin", "end", "remote_parent")

    def __init__(self, batch: "TraceBatch", debug_id, location: str,
                 span_id: int, parent_id: Optional[int],
                 remote_parent=None):
        self.batch = batch
        self.debug_id = debug_id
        self.location = location
        self.span_id = span_id
        self.parent_id = parent_id
        #: (process_name, span_id) in ANOTHER process, when this leg's
        #: parent arrived over a traced TCP frame (ISSUE 16)
        self.remote_parent = remote_parent
        self.begin = _now()
        self.end: Optional[float] = None

    def finish(self) -> None:
        if self.end is not None:
            return
        self.end = _now()
        self.batch._finish_span(self)

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, *exc) -> None:
        self.finish()


class TraceBatch:
    """Cross-role latency stitching for SAMPLED transactions (ref:
    g_traceBatch, flow/Trace.h:107 — attach/event pairs with a shared
    debug id let a tool reassemble one transaction's path across the
    client, proxy, resolver, and log). Events buffer here (bounded —
    the oldest spill into the trace stream, like the reference's
    periodic dump) and can be flushed or queried by id. Spans ride the
    same buffer discipline: roles open parented spans around their leg
    of a commit, and `span_chain` rebuilds the tree."""

    MAX_BUFFERED = 4096
    MAX_REMOTE_PARENTS = 4096

    def __init__(self):
        self._events: list = []
        self._seq = 0   # insertion order: same-tick events must stitch
                        # causally, not alphabetically by location
        self._spans: list = []            # finished spans
        self._open: dict = {}             # debug_id -> stack of open Spans
        self._span_seq = 0
        #: debug_id -> (process_name, span_id): the still-open parent
        #: span in the SENDING process, delivered by a traced TCP frame
        #: (rpc/tcp.py) just before the request dispatches locally.
        #: Bounded: sampled ids are rare, but a long soak must not grow
        #: this without bound — oldest entries evict first
        self._remote_parents: dict = {}

    def add_event(self, event_type: str, debug_id, location: str) -> None:
        self._seq += 1
        self._events.append((_now(), self._seq, event_type, debug_id,
                             location))
        if len(self._events) > self.MAX_BUFFERED:
            # spill the OLDEST half only: in-flight stitches keep their
            # recent legs queryable in memory
            self.dump(self._events[:self.MAX_BUFFERED // 2])
            del self._events[:self.MAX_BUFFERED // 2]

    def add_events(self, debug_ids, event_type: str, location: str) -> None:
        for d in debug_ids:
            self.add_event(event_type, d, location)

    def events(self, debug_id) -> list:
        """Causally-ordered (time, type, location) for one debug id."""
        return [(t, et, loc) for t, seq, et, d, loc
                in sorted(e for e in self._events if e[3] == debug_id)]

    # -- spans ----------------------------------------------------------
    def begin_span(self, debug_id, location: str,
                   parent: Optional["Span"] = None) -> Span:
        """Open a parented span for one debug id. With no explicit
        parent, the innermost still-open span of the same debug id is
        the parent — in the deterministic sim a commit's legs nest
        (client > proxy > {resolver, tlog}), so auto-parenting rebuilds
        the reference's trace tree without threading span tokens
        through every RPC type. Same-location open spans are SIBLINGS,
        not ancestors: with two tlogs (or a txn split across
        resolvers), leg B begins while leg A's identical-location span
        is still open, and both must parent onto the proxy span.

        With NO local parent at all, a remote parent noted for this
        debug id (ISSUE 16: the sending process's open span, carried by
        a traced TCP frame) attaches instead, so a cross-process leg
        still joins the same commit tree when tracemerge reassembles
        the per-process files."""
        self._span_seq += 1
        stack = self._open.setdefault(debug_id, [])
        remote = None
        if parent is not None:
            pid = parent.span_id
        else:
            pid = None
            for s in reversed(stack):
                if s.location != location:
                    pid = s.span_id
                    break
            if pid is None:
                remote = self._remote_parents.get(debug_id)
        span = Span(self, debug_id, location, self._span_seq, pid,
                    remote_parent=remote)
        stack.append(span)
        return span

    def note_remote_parent(self, debug_id, process: str,
                           span_id: int) -> None:
        """Record that `debug_id`'s innermost open span lives in
        another process — called by the TCP transport when a traced
        request frame arrives, BEFORE the request dispatches into the
        local role (so the role's begin_span sees it)."""
        if len(self._remote_parents) >= self.MAX_REMOTE_PARENTS and \
                debug_id not in self._remote_parents:
            # evict the oldest noted id (insertion order)
            self._remote_parents.pop(next(iter(self._remote_parents)))
        self._remote_parents[debug_id] = (process, span_id)

    def open_span_id(self, debug_id) -> Optional[int]:
        """The innermost still-open span id for one debug id (None when
        no span is open) — what a traced TCP request carries as the
        receiving process's remote parent."""
        stack = self._open.get(debug_id)
        return stack[-1].span_id if stack else None

    def begin_spans(self, debug_ids, location: str) -> list:
        return [self.begin_span(d, location) for d in debug_ids]

    @staticmethod
    def finish_spans(spans) -> None:
        for s in spans:
            s.finish()

    def _finish_span(self, span: Span) -> None:
        stack = self._open.get(span.debug_id)
        if stack and span in stack:
            stack.remove(span)
            if not stack:
                del self._open[span.debug_id]
        self._spans.append(span)
        if len(self._spans) > self.MAX_BUFFERED:
            self._dump_spans(self._spans[:self.MAX_BUFFERED // 2])
            del self._spans[:self.MAX_BUFFERED // 2]

    def spans(self, debug_id) -> list:
        """Finished spans for one debug id, ordered by (begin, open
        order) — the monotonic virtual clock makes this the causal
        order of the legs."""
        return sorted((s for s in self._spans if s.debug_id == debug_id),
                      key=lambda s: (s.begin, s.span_id))

    def span_chain(self, debug_id) -> list:
        """The reassembled tree for one sampled transaction: dicts with
        location/begin/end/parent/depth in causal order. `parent` is
        the parent span's location (None at the root); `depth` is the
        distance to the root, so a test can assert the exact
        client->proxy->resolver/tlog shape."""
        spans = self.spans(debug_id)
        by_id = {s.span_id: s for s in spans}
        out = []
        for s in spans:
            depth = 0
            p = s.parent_id
            while p is not None and p in by_id:
                depth += 1
                p = by_id[p].parent_id
            parent = by_id.get(s.parent_id)
            out.append({"location": s.location,
                        "begin": s.begin, "end": s.end,
                        "parent": parent.location if parent else None,
                        "depth": depth})
        return out

    def clear(self) -> None:
        self._events.clear()
        self._spans.clear()
        self._open.clear()
        self._remote_parents.clear()

    def dump(self, events=None) -> None:
        """Flush events as TraceEvents (ref: TraceBatch::dump); with no
        argument, flushes and clears the whole buffer (finished spans
        included)."""
        batch = self._events if events is None else events
        for t, _seq, et, d, loc in batch:
            ev = TraceEvent(et, str(d))
            if ev._ev is not None:
                ev._ev["Time"] = t
            ev.detail(Location=loc).log()
        if events is None:
            self._dump_spans(self._spans)
            self._spans.clear()
            self._events.clear()

    def _dump_spans(self, spans) -> None:
        proc = process_name()
        for s in spans:
            ev = TraceEvent("Span", str(s.debug_id))
            if ev._ev is not None:
                ev._ev["Time"] = s.begin
            ev.detail(Location=s.location, Begin=s.begin, End=s.end,
                      SpanID=s.span_id, ParentID=s.parent_id)
            # identity-less processes keep the pre-ISSUE-16 line format
            # byte-for-byte (pinned by the same-seed merge test)
            if proc:
                ev.detail(Process=proc)
            if s.remote_parent is not None:
                ev.detail(RemoteParentProcess=s.remote_parent[0],
                          RemoteParentID=s.remote_parent[1])
            ev.log()


g_trace_batch = TraceBatch()
