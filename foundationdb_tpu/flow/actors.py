"""Generic actor combinators and async containers.

Reference: flow/genericactors.actor.h (delay/timeout/getAll/AsyncVar/
AsyncTrigger), flow/flow.h:766,843 (PromiseStream/FutureStream),
fdbclient/Notified.h (NotifiedVersion), flow/ActorCollection.h.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Iterable, Optional

from .error import ActorCancelled, FdbError, error
from .future import Future, Promise, Task, error_future, ready_future
from .scheduler import TaskPriority, delay, g, spawn


def all_of(futures: Iterable[Future]) -> Future:
    """Future of list of results; errors propagate (ref: getAll)."""
    futures = list(futures)
    out = Future()
    n = len(futures)
    if n == 0:
        out.send([])
        return out
    remaining = [n]

    def on_one(f: Future):
        if out.is_ready:
            return
        if f.is_error:
            out.send_error(f.exception())
            return
        remaining[0] -= 1
        if remaining[0] == 0:
            out.send([fu.get() for fu in futures])

    for f in futures:
        f.on_ready(on_one)
    return out


def wait_for_all(futures: Iterable[Future]) -> Future:
    return all_of(futures)


def first_of(*futures: Future) -> Future:
    """Future of (index, value) of the first ready input (ref: choose/when).

    Losing inputs still pending when one wins are marked abandoned so
    a FutureStream waiter among them re-queues later deliveries instead
    of losing them (see Future.abandon)."""
    out = Future()

    def make(i):
        def cb(f: Future):
            if out.is_ready:
                return
            if f.is_error:
                out.send_error(f.exception())
            else:
                out.send((i, f.get()))
            for other in futures:
                if not other.is_ready:
                    other.abandon()
        return cb

    for i, f in enumerate(futures):
        f.on_ready(make(i))
    return out


def catch_errors(fut: Future) -> Future:
    """Future of the input future itself once settled — never errors
    (ref: genericactors errorOr / waitForAllReady): callers inspect
    is_error/get on the settled inner future."""
    out = Future()

    def on_ready(f: Future):
        if not out.is_ready:
            out.send(f)

    fut.on_ready(on_ready)
    return out


def timeout(fut: Future, seconds: float, default: Any = None,
            priority: int = TaskPriority.DEFAULT_ENDPOINT) -> Future:
    """Value of `fut`, or `default` after `seconds` (ref: genericactors timeout)."""
    out = Future()
    timer = delay(seconds, priority)

    def on_fut(f: Future):
        if out.is_ready:
            return
        timer.cancel()
        if f.is_error:
            out.send_error(f.exception())
        else:
            out.send(f.get())

    def on_timer(t: Future):
        if out.is_ready or t.is_error:
            return
        out.send(default)
        fut.abandon()  # a stream waiter must re-queue later deliveries

    fut.on_ready(on_fut)
    timer.on_ready(on_timer)
    return out


def timeout_error(fut: Future, seconds: float,
                  err_name: str = "timed_out") -> Future:
    out = Future()
    timer = delay(seconds)

    def on_fut(f: Future):
        if out.is_ready:
            return
        timer.cancel()
        if f.is_error:
            out.send_error(f.exception())
        else:
            out.send(f.get())

    def on_timer(t: Future):
        if not out.is_ready and not t.is_error:
            out.send_error(error(err_name))
            fut.abandon()

    fut.on_ready(on_fut)
    timer.on_ready(on_timer)
    return out


class AsyncVar:
    """A mutable value with change notification (ref: genericactors AsyncVar)."""

    def __init__(self, value: Any = None):
        self._value = value
        self._on_change = Promise()

    def get(self) -> Any:
        return self._value

    def set(self, value: Any) -> None:
        if value != self._value:
            self._value = value
            self.trigger()

    def trigger(self) -> None:
        p, self._on_change = self._on_change, Promise()
        p.send(None)

    def on_change(self) -> Future:
        return self._on_change.future


class AsyncTrigger:
    def __init__(self):
        self._p = Promise()

    def trigger(self) -> None:
        p, self._p = self._p, Promise()
        p.send(None)

    def on_trigger(self) -> Future:
        return self._p.future


class NotifiedVersion:
    """Versioned wait queue: when_at_least(v) (ref: fdbclient/Notified.h:28)."""

    def __init__(self, version: int = 0):
        self._version = version
        self._waiters: list[tuple[int, Future]] = []  # kept sorted by version

    def get(self) -> int:
        return self._version

    def set(self, version: int) -> None:
        if version < self._version:
            raise error("internal_error")
        self._version = version
        if self._waiters:
            still = []
            for v, f in self._waiters:
                if v <= version:
                    if not f.is_ready:
                        f.send(version)
                else:
                    still.append((v, f))
            self._waiters = still

    def when_at_least(self, version: int) -> Future:
        if self._version >= version:
            return ready_future(self._version)
        f = Future()
        self._waiters.append((version, f))
        return f

    def rollback(self, version: int) -> None:
        """Epoch recovery rewound this counter: waiters at or below the
        new value fire; higher waiters came from requests whose read
        versions the recovery invalidated — they error with
        transaction_too_old so their clients retry with a fresh snapshot
        (ref: storageserver rollback semantics)."""
        self._version = version
        waiters, self._waiters = self._waiters, []
        for v, f in waiters:
            if f.is_ready:
                continue
            if v <= version:
                f.send(version)
            else:
                f.send_error(error("transaction_too_old"))


class FutureStream:
    """Multi-value async queue, read side (ref: flow/flow.h:766)."""

    def __init__(self):
        self._queue: deque = deque()
        self._waiter: Optional[Future] = None
        self._closed: Optional[BaseException] = None

    def _push(self, value: Any) -> None:
        if (self._waiter is not None and not self._waiter.is_ready
                and not self._waiter.is_abandoned):
            w, self._waiter = self._waiter, None
            w.send(value)
        else:
            # no live waiter (none, already delivered, or abandoned by a
            # losing choose/when branch): queue, never lose the value
            if self._waiter is not None and self._waiter.is_abandoned:
                self._waiter = None
            self._queue.append(value)

    def _close(self, err: BaseException) -> None:
        self._closed = err
        if self._waiter is not None and not self._waiter.is_ready:
            w, self._waiter = self._waiter, None
            if not w.is_abandoned:
                w.send_error(err)

    def pop(self) -> Future:
        """Future of the next value (ref: waitNext)."""
        if self._queue:
            return ready_future(self._queue.popleft())
        if self._closed is not None:
            return error_future(self._closed)
        if self._waiter is None or self._waiter.is_ready:
            self._waiter = Future()
        else:
            # a new pop re-adopts a previously abandoned pending waiter
            self._waiter._abandoned = False
        return self._waiter

    def is_empty(self) -> bool:
        return not self._queue


class PromiseStream:
    """Write side (ref: flow/flow.h:843)."""

    def __init__(self):
        self.stream = FutureStream()

    def send(self, value: Any = None) -> None:
        self.stream._push(value)

    def send_error(self, err: BaseException) -> None:
        self.stream._close(err)

    def close(self) -> None:
        self.stream._close(error("end_of_stream"))


class _LockWaiter(Future):
    """Waiter future that removes itself from the lock queue when cancelled,
    so a cancelled taker cannot be granted (and leak) permits."""

    __slots__ = ("_lock", "_amount")

    def __init__(self, lock: "FlowLock", amount: int):
        super().__init__()
        self._lock = lock
        self._amount = amount

    def cancel(self) -> None:
        if not self.is_ready:
            try:
                self._lock._waiters.remove((self._amount, self))
            except ValueError:
                pass
            self.send_error(ActorCancelled())


class FlowLock:
    """Async counting semaphore (ref: flow/genericactors FlowLock)."""

    def __init__(self, permits: int = 1):
        self.permits = permits
        self.active = 0
        self._waiters: deque[tuple[int, _LockWaiter]] = deque()

    def take(self, amount: int = 1) -> Future:
        if self.active + amount <= self.permits and not self._waiters:
            self.active += amount
            return ready_future(None)
        f = _LockWaiter(self, amount)
        self._waiters.append((amount, f))
        return f

    def release(self, amount: int = 1) -> None:
        self.active -= amount
        while self._waiters:
            amt, f = self._waiters[0]
            if self.active + amt <= self.permits:
                self._waiters.popleft()
                self.active += amt
                if not f.is_ready:
                    f.send(None)
            else:
                break


class ActorCollection:
    """Holds running actors; propagates their errors (ref: flow/ActorCollection.h)."""

    def __init__(self):
        self.tasks: list[Task] = []
        self._error = Future()

    def add(self, task: Task) -> None:
        self.tasks.append(task)

        def on_done(f: Future):
            try:
                self.tasks.remove(f)
            except ValueError:
                pass
            if f.is_error and not isinstance(f.exception(), ActorCancelled) \
                    and not self._error.is_ready:
                self._error.send_error(f.exception())
        task.on_ready(on_done)

    def get_result(self) -> Future:
        """Never-ready future that errors if any member errors."""
        return self._error

    def cancel_all(self) -> None:
        # cancel() fires on_done synchronously, which mutates self.tasks —
        # iterate a snapshot.
        for t in list(self.tasks):
            t.cancel()
        self.tasks.clear()
