"""TEST() coverage macro: marking and counting rare-path hits.

Reference: flow/UnitTest.h `TEST(intro)` — annotates a rarely-taken
code path; every build collects the annotated sites and the coverage
tool (tests in CI) verifies important ones actually fire across
simulation runs, because an untested error path is where bugs live.

Python sites self-declare at import time via ``declare()`` (the
compile-time registration analogue) and mark hits with ``cover()``;
``report()`` yields hit/unhit site sets for the suite-level coverage
assertion (tests/test_coverage.py).
"""

from __future__ import annotations

from typing import Dict, Set

_declared: Set[str] = set()
_hits: Dict[str, int] = {}


def declare(*comments: str) -> None:
    """Register coverage sites (module import time), hit or not."""
    _declared.update(comments)


def cover(comment: str, condition: bool = True) -> bool:
    """TEST() — count a hit when `condition` holds; returns it so the
    macro can wrap an if-expression the way the reference's does."""
    _declared.add(comment)
    if condition:
        _hits[comment] = _hits.get(comment, 0) + 1
    return condition


def hits(comment: str) -> int:
    return _hits.get(comment, 0)


def report() -> dict:
    return {
        "declared": sorted(_declared),
        "hit": {c: n for c, n in sorted(_hits.items())},
        "unhit": sorted(_declared - set(_hits)),
    }


def reset_hits() -> None:
    _hits.clear()


# The framework's annotated rare paths (the compile-time site registry
# the reference's coverage tool extracts from TEST() macros). A site
# added via cover() without a listing here still registers on first
# execution; listing it keeps it visible in report()["unhit"] for runs
# that never take the path.
declare(
    "proxy.commit.conflict",
    "proxy.commit.too_old",
    "proxy.commit.report_conflicting",
    "resolver.reply_cache.hit",
    "resolver.reply_cache.aged_out",
    "resolver.batch.rejected",
    "tlog.commit.stopped",
    "storage.rollback",
    "diskqueue.torn_tail_dropped",
    "client.retry.conflict",
    "client.refresh_stale_picture",
    "cc.epoch_failed",
)
