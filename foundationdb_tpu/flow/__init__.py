"""Deterministic actor runtime (ref: flow/ — Promise/Future, Net2, knobs, trace)."""

from .error import ActorCancelled, FdbError, error, internal_error
from .future import Future, Promise, Task, error_future, ready_future
from .scheduler import (Scheduler, TaskPriority, WakeSignal, delay, g,
                        get_scheduler, now, set_scheduler, spawn)
from .actors import (
    ActorCollection,
    AsyncTrigger,
    AsyncVar,
    FlowLock,
    FutureStream,
    NotifiedVersion,
    PromiseStream,
    all_of,
    catch_errors,
    first_of,
    timeout,
    timeout_error,
    wait_for_all,
)
from .rng import DeterministicRandom, buggify, g_random, set_seed
from .knobs import SERVER_KNOBS, Knobs, make_server_knobs, reset_server_knobs
from .stats import Counter, CounterCollection, TimeSeries
from .smoother import Smoother, SmoothedQueue, SmoothedRate
from .latency import (DEFAULT_BANDS, LatencyBands, LatencySample,
                      RequestLatency)
from .trace import Span, g_trace_batch
from .trace import TraceEvent, g_trace, reset_trace
from .flightrec import FlightRecorder, g_flightrec
from .coverage import cover, declare
from . import coverage, trace

__all__ = [
    "ActorCancelled", "FdbError", "error", "internal_error",
    "Future", "Promise", "Task", "error_future", "ready_future",
    "Scheduler", "TaskPriority", "WakeSignal", "delay", "g",
    "get_scheduler", "now", "set_scheduler", "spawn",
    "ActorCollection", "AsyncTrigger", "AsyncVar", "FlowLock", "FutureStream",
    "NotifiedVersion", "PromiseStream", "all_of", "catch_errors",
    "first_of", "timeout",
    "timeout_error", "wait_for_all",
    "DeterministicRandom", "buggify", "g_random", "set_seed",
    "SERVER_KNOBS", "Knobs", "make_server_knobs", "reset_server_knobs",
    "TraceEvent", "g_trace", "reset_trace",
    "Counter", "CounterCollection", "TimeSeries",
    "Smoother", "SmoothedQueue", "SmoothedRate",
    "DEFAULT_BANDS", "LatencyBands", "LatencySample", "RequestLatency",
    "Span", "g_trace_batch",
    "FlightRecorder", "g_flightrec",
]
