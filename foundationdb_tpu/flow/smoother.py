"""Exponential smoothing primitives for saturation telemetry.

Reference: fdbrpc/Smoother.h — the `Smoother` every Ratekeeper input
rides through (storage queue bytes, tlog queue bytes, durability lag),
and its `SmoothedRate` cousin that turns a monotone total into a
smoothed derivative. Promoted out of server/ratekeeper.py so every
role can publish smoothed QoS signals through the same math the
control loop consumes — a signal smoothed two different ways would
make the Ratekeeper argue with its own telemetry.

Time never runs backwards here: a non-increasing `now` (sim clock
replay, a duplicate tick after checkpoint restore) clamps the delta to
zero instead of amplifying the old value through a positive exponent.
"""

from __future__ import annotations

import math
from typing import Optional


def _default_tau() -> float:
    from .knobs import SERVER_KNOBS
    return float(SERVER_KNOBS.qos_smoothing_tau)


class Smoother:
    """Exponential smoothing toward the newest sample with time
    constant `tau` seconds (ref: fdbrpc/Smoother.h)."""

    __slots__ = ("_t", "value")

    def __init__(self):
        self._t = None
        self.value = 0.0

    def sample(self, x: float, now: float, tau: float) -> float:
        # tau comes in per sample so a live knob change applies to
        # existing smoothers (a frozen tau would make the knob a no-op)
        if self._t is None or tau <= 0:
            self.value = x
        else:
            # clamp dt >= 0: a non-increasing clock (sim replay /
            # duplicate tick) must decay nothing, not explode the old
            # value through exp(+dt/tau)
            dt = now - self._t
            if dt < 0.0:
                dt = 0.0
            a = math.exp(-dt / tau)
            self.value = x + (self.value - x) * a
        self._t = now
        return self.value


class SmoothedQueue:
    """A smoothed level gauge (queue bytes, lag versions, queue depth):
    `sample(value, now)` folds the newest reading through a Smoother at
    the QOS_SMOOTHING_TAU knob (or an explicit tau) and keeps the
    smoothed level in `.value`."""

    __slots__ = ("_sm", "_tau")

    def __init__(self, tau: Optional[float] = None):
        self._sm = Smoother()
        self._tau = tau  # None: read the knob per sample (live-tunable)

    @property
    def value(self) -> float:
        return self._sm.value

    def sample(self, x: float, now: float) -> float:
        return self._sm.sample(
            x, now, self._tau if self._tau is not None else _default_tau())


class SmoothedRate:
    """A smoothed derivative of a monotone counter (ref: Smoother's
    smoothRate applied to totals): feed the cumulative total at each
    sample time and read `.rate` in units/sec. A total below its
    baseline means the role restarted — the rate re-baselines instead
    of going hugely negative (the same reset rule the trace-counters
    rollup applies)."""

    __slots__ = ("_sm", "_tau", "_last_total", "_last_t")

    def __init__(self, tau: Optional[float] = None):
        self._sm = Smoother()
        self._tau = tau
        self._last_total: Optional[float] = None
        self._last_t: Optional[float] = None

    @property
    def rate(self) -> float:
        return self._sm.value

    def sample_total(self, total: float, now: float,
                     tau: Optional[float] = None) -> float:
        # per-call tau wins so callers smoothing under a different knob
        # (ratekeeper's rk_smoothing_seconds) stay live-tunable
        if tau is None:
            tau = self._tau if self._tau is not None else _default_tau()
        if self._last_total is None or total < self._last_total or \
                self._last_t is None or now <= self._last_t:
            # first sample, a counter reset, or a non-advancing clock:
            # re-baseline without fabricating a rate
            self._last_total = total
            self._last_t = now
            return self._sm.value
        inst = (total - self._last_total) / (now - self._last_t)
        self._last_total = total
        self._last_t = now
        return self._sm.sample(inst, now, tau)
