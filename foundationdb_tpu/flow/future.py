"""Single-assignment async values and actor tasks.

Reference: flow/flow.h — `SAV<T>` (:352), `Future<T>` (:596), `Promise<T>`
(:715), `Actor<T>` (:920). Re-designed for Python: actors are ``async def``
coroutines awaiting :class:`Future` objects; a :class:`Task` drives a
coroutine and is itself a Future of the actor's return value.

Unlike asyncio, everything here is deterministic: continuations are resumed
through the scheduler's priority queues in a fixed order, and time is
virtual by default (the simulator *is* the runtime, as in the reference's
sim2 design).
"""

from __future__ import annotations

from typing import Any, Callable, Generator, Optional

from .error import ActorCancelled, FdbError, error

_PENDING = 0
_VALUE = 1
_ERROR = 2


class Future:
    """A single-assignment asynchronous value (ref: flow/flow.h:352 SAV).

    Becomes ready exactly once, with either a value or an error. Callbacks
    registered via :meth:`on_ready` fire when the future becomes ready (in
    registration order, synchronously from :meth:`send`).
    """

    __slots__ = ("_state", "_result", "_callbacks", "_abandoned")

    def __init__(self):
        self._state = _PENDING
        self._result: Any = None
        self._callbacks: Optional[list] = None
        self._abandoned = False

    # -- inspection ---------------------------------------------------------
    @property
    def is_ready(self) -> bool:
        return self._state != _PENDING

    @property
    def is_error(self) -> bool:
        return self._state == _ERROR

    def get(self) -> Any:
        """Return the value; raises if not ready or completed with an error."""
        if self._state == _VALUE:
            return self._result
        if self._state == _ERROR:
            raise self._result
        raise error("future_released")

    def exception(self) -> Optional[BaseException]:
        return self._result if self._state == _ERROR else None

    # -- completion ---------------------------------------------------------
    def send(self, value: Any = None) -> None:
        if self._state != _PENDING:
            raise error("internal_error")
        self._state = _VALUE
        self._result = value
        self._fire()

    def send_error(self, err: BaseException) -> None:
        if self._state != _PENDING:
            raise error("internal_error")
        self._state = _ERROR
        self._result = err
        self._fire()

    def _fire(self) -> None:
        cbs, self._callbacks = self._callbacks, None
        if cbs:
            for cb in cbs:
                cb(self)

    def on_ready(self, cb: Callable[["Future"], None]) -> None:
        if self._state != _PENDING:
            cb(self)
        elif self._callbacks is None:
            self._callbacks = [cb]
        else:
            self._callbacks.append(cb)

    def remove_callback(self, cb) -> None:
        if self._callbacks is not None:
            try:
                self._callbacks.remove(cb)
            except ValueError:
                pass

    # -- awaiting -----------------------------------------------------------
    def __await__(self) -> Generator["Future", None, Any]:
        if self._state == _PENDING:
            yield self  # Task picks this up and subscribes
        if self._state == _ERROR:
            raise self._result
        return self._result

    def cancel(self) -> None:
        """Cancel the computation producing this future (no-op for plain futures)."""

    # -- abandonment ---------------------------------------------------------
    # The reference's choose/when unhooks losing callbacks from a stream
    # before any value can be delivered into them; combinators here mark
    # losing branches "abandoned" instead, and FutureStream re-queues a
    # value rather than deliver it into an abandoned waiter (otherwise a
    # commit request racing a batch deadline is silently lost).
    def abandon(self) -> None:
        """Declare that no one will consume this future's value."""
        self._abandoned = True

    @property
    def is_abandoned(self) -> bool:
        return self._abandoned


def ready_future(value: Any = None) -> Future:
    f = Future()
    f.send(value)
    return f


def error_future(err: BaseException) -> Future:
    f = Future()
    f.send_error(err)
    return f


class Promise:
    """The write side of a Future (ref: flow/flow.h:715).

    Dropping a Promise without sending breaks the future with
    ``broken_promise``; call :meth:`drop` explicitly for that behavior.
    """

    __slots__ = ("future",)

    def __init__(self):
        self.future = Future()

    def send(self, value: Any = None) -> None:
        self.future.send(value)

    def send_error(self, err: BaseException) -> None:
        self.future.send_error(err)

    @property
    def is_set(self) -> bool:
        return self.future.is_ready

    def drop(self) -> None:
        if not self.future.is_ready:
            self.future.send_error(error("broken_promise"))


class Task(Future):
    """Drives an actor coroutine; IS the future of its return value.

    Ref: flow/flow.h:920 `Actor<ReturnValue> : SAV<ReturnValue>` — the
    compiled actor object is both the state machine and the result.
    """

    __slots__ = ("_coro", "_sched", "priority", "_waiting_on", "_resume_cb", "name")

    def __init__(self, coro, scheduler, priority: int, name: str = ""):
        super().__init__()
        self._coro = coro
        self._sched = scheduler
        self.priority = priority
        self._waiting_on: Optional[Future] = None
        self._resume_cb = None
        self.name = name or getattr(coro, "__name__", "actor")

    def _step(self, value: Any = None, exc: Optional[BaseException] = None) -> None:
        self._waiting_on = None
        self._resume_cb = None
        if self.is_ready:  # cancelled while queued
            self._coro.close()
            return
        prev = self._sched._current_task
        self._sched._current_task = self
        try:
            if exc is not None:
                waiting = self._coro.throw(exc)
            else:
                waiting = self._coro.send(value)
        except StopIteration as e:
            if not self.is_ready:
                self.send(e.value)
            return
        except ActorCancelled as e:
            if not self.is_ready:
                self.send_error(e)
            return
        except BaseException as e:  # noqa: BLE001 - actor errors flow into the future
            if not self.is_ready:
                self.send_error(e)
            return
        finally:
            self._sched._current_task = prev
        # The coroutine yielded a Future it is waiting on.
        self._waiting_on = waiting
        self._resume_cb = cb = self._make_resume(waiting)
        waiting.on_ready(cb)

    def _make_resume(self, fut: Future):
        def cb(f: Future, self=self):
            # Resume through the scheduler ready queue (deterministic order,
            # bounded stack depth). A delay() future carries the priority its
            # waiter should resume at (ref: delay(t, taskID) semantics);
            # otherwise the task's own priority applies.
            self._waiting_on = None  # now queued, not waiting: see cancel()
            self._resume_cb = None
            prio = getattr(f, "resume_priority", None)
            if prio is None:
                prio = self.priority
            if f._state == _ERROR:
                self._sched._schedule_step(self, None, f._result, prio)
            else:
                self._sched._schedule_step(self, f._result, None, prio)
        return cb

    def cancel(self) -> None:
        """Cancel the actor (ref: Actor::cancel — actor_cancelled is thrown at the wait point)."""
        if self.is_ready:
            return
        if self._waiting_on is not None:
            w, cb = self._waiting_on, self._resume_cb
            self._waiting_on = None
            self._resume_cb = None
            w.remove_callback(cb)
            # Cancel downstream only if nobody else is waiting on it (ref:
            # flow cancels an actor when the *last* Future reference drops).
            if not w._callbacks:
                w.cancel()
            self._step(exc=ActorCancelled())
        else:
            # Running or queued: mark done; _step will close the coroutine.
            self.send_error(ActorCancelled())
