"""Latency measurement primitives: reservoirs and threshold bands.

Reference: fdbserver/LatencyBandConfig.{h,cpp} + the `LatencyBands`
counters folded into status, and fdbrpc/Stats.h `LatencySample` (a
sketch of recent request latencies served as percentiles). Every
request-serving role keeps one of each per request class; the cluster
controller folds their snapshots into the status document and the
periodic counter rollup, so a regression shows up per pipeline stage
instead of as one end-to-end number.
"""

from __future__ import annotations

from bisect import bisect_left, insort
from math import ceil
from typing import Tuple

# thresholds in seconds (ref: LatencyBandConfig's default band edges —
# status reports how many requests finished within each band)
DEFAULT_BANDS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                 0.25, 0.5, 1.0)


class LatencySample:
    """Sliding reservoir of the most recent latencies (ref: fdbrpc
    Stats.h LatencySample — the reference keeps a DDSketch; a bounded
    ring of raw samples gives the same p50/p90/p99/max surface at sim
    scale). `record` is O(1); percentiles sort on demand."""

    __slots__ = ("name", "size", "count", "max_seen", "_buf", "_next")

    def __init__(self, name: str, size: int = 512):
        self.name = name
        self.size = int(size)
        self.count = 0          # total recorded, beyond the reservoir
        self.max_seen = 0.0
        self._buf: list[float] = []
        self._next = 0          # ring cursor once the reservoir is full

    def record(self, seconds: float) -> None:
        self.count += 1
        if seconds > self.max_seen:
            self.max_seen = seconds
        if len(self._buf) < self.size:
            self._buf.append(seconds)
        else:
            self._buf[self._next] = seconds
            self._next = (self._next + 1) % self.size

    @staticmethod
    def _pick(s: list, p: float) -> float:
        # nearest-rank (ceil(p*n) - 1): int(p*n) would sit one rank
        # high and collapse p90/p99 to the max on small reservoirs
        if not s:
            return 0.0
        return s[min(len(s) - 1, max(0, ceil(p * len(s)) - 1))]

    def percentile(self, p: float) -> float:
        """p in [0, 1] over the reservoir (recent history)."""
        return self._pick(sorted(self._buf), p)

    def snapshot(self) -> dict:
        s = sorted(self._buf)   # one sort serves all three percentiles
        return {"count": self.count,
                "p50": round(self._pick(s, 0.50), 6),
                "p90": round(self._pick(s, 0.90), 6),
                "p99": round(self._pick(s, 0.99), 6),
                "max_seconds": round(self.max_seen, 6)}


class LatencyBands:
    """Banded latency histogram (ref: fdbserver/LatencyBandConfig.cpp +
    the latency_band_included counters in status): each recorded
    latency increments every band whose threshold it fits under, plus
    a total — so a consumer reads "fraction under X seconds" directly.
    Thresholds are configurable; adding one resets the counts, exactly
    like the reference reacting to a LatencyBandConfig change."""

    __slots__ = ("name", "bands", "counts", "total", "max_seen",
                 "sum_seconds")

    def __init__(self, name: str, bands: Tuple[float, ...] = DEFAULT_BANDS):
        self.name = name
        self.bands = tuple(sorted(bands))
        self.counts = [0] * len(self.bands)
        self.total = 0
        self.max_seen = 0.0
        self.sum_seconds = 0.0

    def add_threshold(self, seconds: float) -> None:
        """(ref: LatencyBands::addThreshold — reconfiguring the band
        edges resets the histogram: mixed-edge counts are meaningless)"""
        if seconds in self.bands:
            return
        bands = list(self.bands)
        insort(bands, seconds)
        self.bands = tuple(bands)
        self.clear()

    def clear(self) -> None:
        self.counts = [0] * len(self.bands)
        self.total = 0
        self.max_seen = 0.0
        self.sum_seconds = 0.0

    def record(self, seconds: float) -> None:
        self.total += 1
        self.sum_seconds += seconds   # the histogram's _sum sample
        if seconds > self.max_seen:
            self.max_seen = seconds
        for i in range(bisect_left(self.bands, seconds),
                       len(self.bands)):
            self.counts[i] += 1

    def snapshot(self) -> dict:
        return {"total": self.total,
                "max_seconds": round(self.max_seen, 6),
                "sum_seconds": round(self.sum_seconds, 6),
                "bands": {f"<={t:g}s": c
                          for t, c in zip(self.bands, self.counts)}}


class RequestLatency:
    """One request class's full latency surface: bands + reservoir with
    a single `record`. Roles keep one per request kind (grv, commit,
    resolve, read, log-commit); status folds both snapshots."""

    __slots__ = ("name", "bands", "sample")

    def __init__(self, name: str, bands: Tuple[float, ...] = DEFAULT_BANDS,
                 sample_size: int = 512):
        self.name = name
        self.bands = LatencyBands(name, bands)
        self.sample = LatencySample(name, sample_size)

    def record(self, seconds: float) -> None:
        self.bands.record(seconds)
        self.sample.record(seconds)

    def snapshot(self) -> dict:
        # one count ("total") and one max (the bands'): the sample's
        # duplicates are derivable and would silently shadow on merge
        d = self.bands.snapshot()
        s = self.sample.snapshot()
        for k in ("p50", "p90", "p99"):
            d[k] = s[k]
        return d
