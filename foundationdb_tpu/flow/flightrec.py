"""Flight recorder: a bounded in-memory ring of recent trace events.

Reference: the reference's `--knob_trace_synth`-era debugging pattern
and the classic avionics black box — keep the last N structured trace
events in memory regardless of file rotation or severity filtering
downstream, and dump them on demand: a SevError, an SLO breach (the
incident bundle, tools/incident.py), or an operator command
(`cli flightrec`). The ring is process-local and independent of the
trace FILE: a worker whose trace file rolled away (or was never
opened) still carries its recent history, so a kill -9 post-mortem or
a breach bundle gets the last moments even when the file tail is gone.

Cost discipline: while disarmed (the default — nothing arms it unless
CRITICAL_PATH is on or a tool opts in), the only cost anywhere is one
attribute check per emitted trace event in `TraceCollector.emit`.
Stdlib-only on purpose: flow/trace.py imports this module, so it must
not import trace (or anything else in flow) back.
"""

from __future__ import annotations

import json
import os
from collections import deque
from typing import Optional

#: severity at/above which an armed recorder auto-dumps (SevError)
AUTO_DUMP_SEVERITY = 40

#: hard cap on unattended auto-dumps per process: a crash loop must
#: not fill the disk with one dump per SevError
MAX_AUTO_DUMPS = 5


class FlightRecorder:
    def __init__(self, size: int = 512):
        self.armed = False
        self.size = int(size)
        self._ring: deque = deque(maxlen=self.size)
        self.dump_dir: Optional[str] = None
        self.name = ""                 # role:pid token for dump names
        self.noted = 0                 # events ever noted (ring churn)
        self.dumps: list[str] = []     # paths written, in order
        self._auto_dumps_left = MAX_AUTO_DUMPS
        self._dumping = False          # a dump's own events don't recurse

    def arm(self, size: Optional[int] = None,
            dump_dir: Optional[str] = None, name: str = "") -> None:
        """Start recording. `size` overrides the ring capacity (falls
        back to the FLIGHTREC_SIZE knob when importable); `dump_dir`
        is where SevError auto-dumps and argument-less `dump()` calls
        land; `name` tags dump filenames (role:pid style)."""
        if size is None:
            try:
                from .knobs import SERVER_KNOBS
                size = int(SERVER_KNOBS.flightrec_size)
            except Exception:
                size = self.size
        if int(size) != self.size:
            self.size = int(size)
            self._ring = deque(self._ring, maxlen=self.size)
        if dump_dir is not None:
            self.dump_dir = dump_dir
        if name:
            self.name = name
        self.armed = True

    def disarm(self, clear: bool = True) -> None:
        self.armed = False
        if clear:
            self._ring.clear()
            self.noted = 0
            self._auto_dumps_left = MAX_AUTO_DUMPS

    def note(self, ev: dict) -> None:
        """File one trace event into the ring (called by
        TraceCollector.emit while armed); a SevError event triggers a
        bounded auto-dump so the moments BEFORE the error survive even
        if the process dies right after."""
        if self._dumping:
            return
        self.noted += 1
        self._ring.append(ev)
        if ev.get("Severity", 0) >= AUTO_DUMP_SEVERITY and \
                self._auto_dumps_left > 0 and self.dump_dir:
            self._auto_dumps_left -= 1
            self.dump(reason="sev_error")

    def snapshot(self) -> list:
        """The ring's current contents, oldest first."""
        return list(self._ring)

    def status(self) -> dict:
        return {"armed": int(self.armed), "size": self.size,
                "buffered": len(self._ring), "noted": self.noted,
                "dumps": len(self.dumps)}

    def dump(self, directory: Optional[str] = None,
             reason: str = "manual") -> Optional[str]:
        """Write the ring as JSON lines (header row first: who, why,
        how much) into `directory` (default: the armed dump_dir).
        Returns the path, or None when there is nowhere to write or
        nothing recorded. Never raises — a full disk must not turn a
        diagnostic into a crash."""
        directory = directory or self.dump_dir
        if not directory or not self._ring:
            return None
        tag = (self.name or str(os.getpid())).replace(":", ".")
        path = os.path.join(
            directory, f"flightrec.{tag}.{len(self.dumps) + 1}.jsonl")
        self._dumping = True
        try:
            os.makedirs(directory, exist_ok=True)
            with open(path, "w") as fh:
                fh.write(json.dumps(
                    {"Type": "FlightRecorderDump", "Reason": reason,
                     "Name": self.name, "Pid": os.getpid(),
                     "Events": len(self._ring),
                     "Noted": self.noted}) + "\n")
                for ev in self._ring:
                    fh.write(json.dumps(ev, default=repr) + "\n")
        except OSError:
            return None
        finally:
            self._dumping = False
        self.dumps.append(path)
        return path


g_flightrec = FlightRecorder()
