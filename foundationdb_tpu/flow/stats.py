"""Counters: rate-tracked event counters per role.

Reference: flow/Stats.actor.cpp — `Counter` (value + rolling rate +
roughness) grouped in a `CounterCollection`, traced periodically and
folded into the status document. The sim reads them directly for
status; a trace loop would emit them as TraceEvents in production.
"""

from __future__ import annotations

from bisect import bisect_left
from collections import deque
from typing import Dict, Tuple

class Counter:
    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0

    def add(self, n: int = 1) -> None:
        self.value += n

    def set(self, n: int) -> None:
        """Gauge semantics (ref: TDMetric gauges beside counters)."""
        self.value = n


class CounterCollection:
    """(ref: CounterCollection — named counters for one role)"""

    def __init__(self, role: str):
        self.role = role
        self.counters: Dict[str, Counter] = {}

    def counter(self, name: str) -> Counter:
        c = self.counters.get(name)
        if c is None:
            c = self.counters[name] = Counter(name)
        return c

    def snapshot(self) -> Dict[str, int]:
        return {n: c.value for n, c in self.counters.items()}


# thresholds in seconds (ref: LatencyBandConfig's default band edges —
# status reports how many requests finished within each band)
DEFAULT_BANDS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                 0.25, 0.5, 1.0)


class LatencyBands:
    """Banded latency histogram (ref: fdbserver/LatencyBandConfig.cpp +
    the latency_band_included counters in status): each recorded
    latency increments every band whose threshold it fits under, plus
    a total — so a consumer reads "fraction under X seconds" directly.
    """

    __slots__ = ("name", "bands", "counts", "total", "max_seen")

    def __init__(self, name: str, bands: Tuple[float, ...] = DEFAULT_BANDS):
        self.name = name
        self.bands = tuple(bands)
        self.counts = [0] * len(self.bands)
        self.total = 0
        self.max_seen = 0.0

    def record(self, seconds: float) -> None:
        self.total += 1
        if seconds > self.max_seen:
            self.max_seen = seconds
        for i in range(bisect_left(self.bands, seconds),
                       len(self.bands)):
            self.counts[i] += 1

    def snapshot(self) -> dict:
        return {"total": self.total,
                "max_seconds": round(self.max_seen, 6),
                "bands": {f"<={t:g}s": c
                          for t, c in zip(self.bands, self.counts)}}


class TimeSeries:
    """Multi-resolution time series (ref: flow/TDMetric.actor.h — a
    metric keeps LEVELS of samples, each level 4x coarser than the one
    below, so recent history is fine-grained and old history cheap).
    Level 0 holds the newest `samples_per_level` raw samples; every
    CASCADE-th append to a level emits one aggregated sample (the mean
    of the cascade window) to the level above."""

    CASCADE = 4

    __slots__ = ("samples_per_level", "levels", "_carry")

    def __init__(self, samples_per_level: int = 64, n_levels: int = 4):
        self.samples_per_level = samples_per_level
        self.levels = [deque(maxlen=samples_per_level)
                       for _ in range(n_levels)]
        self._carry = [[] for _ in range(n_levels)]

    def append(self, t: float, value: float) -> None:
        self._append_level(0, t, value)

    def _append_level(self, lvl: int, t: float, value: float) -> None:
        self.levels[lvl].append((t, value))
        if lvl + 1 >= len(self.levels):
            return
        carry = self._carry[lvl]
        carry.append((t, value))
        if len(carry) >= self.CASCADE:
            mean = sum(v for _t, v in carry) / len(carry)
            self._append_level(lvl + 1, carry[-1][0], mean)
            carry.clear()

    def series(self, level: int = 0):
        return list(self.levels[level])

    def latest(self):
        return self.levels[0][-1] if self.levels[0] else None
