"""Counters: rate-tracked event counters per role.

Reference: flow/Stats.actor.cpp — `Counter` (value + rolling rate +
roughness) grouped in a `CounterCollection`, traced periodically via
`traceCounters` and folded into the status document. The sim reads
them directly for status; the cluster controller's trace-counters loop
rolls every role's collection into periodic `*Metrics` TraceEvents
with per-interval rates (see CounterCollection.trace).
"""

from __future__ import annotations

from collections import deque
from typing import Dict, Optional

class Counter:
    __slots__ = ("name", "value", "gauge")

    def __init__(self, name: str):
        self.name = name
        self.value = 0
        self.gauge = False

    def add(self, n: int = 1) -> None:
        self.value += n

    def set(self, n: int) -> None:
        """Gauge semantics (ref: TDMetric gauges beside counters).
        Marks the counter as a gauge: a level, not a flow — the
        trace-counters rollup must not derive a *_per_sec from it."""
        self.gauge = True
        self.value = n


class CounterCollection:
    """(ref: CounterCollection — named counters for one role)"""

    def __init__(self, role: str):
        self.role = role
        self.counters: Dict[str, Counter] = {}

    def counter(self, name: str) -> Counter:
        c = self.counters.get(name)
        if c is None:
            c = self.counters[name] = Counter(name)
        return c

    def snapshot(self) -> Dict[str, int]:
        return {n: c.value for n, c in self.counters.items()}

    def trace(self, id: str = "", elapsed: Optional[float] = None,
              prev: Optional[Dict[str, int]] = None) -> Dict[str, int]:
        """Roll this collection into one TraceEvent (ref: traceCounters,
        flow/Stats.actor.cpp — "ProxyMetrics"/"TLogMetrics"/... events
        carrying every counter plus its per-interval rate). `prev` is
        the previous interval's snapshot and `elapsed` the seconds since
        it was taken; returns the fresh snapshot for the caller's next
        round, so rates need no state inside the counters themselves."""
        from .trace import TraceEvent
        snap = self.snapshot()
        # role -> event prefix; "tlog".capitalize() would diverge from
        # the reference's TLogMetrics spelling
        prefix = {"tlog": "TLog"}.get(self.role, self.role.capitalize())
        ev = TraceEvent(f"{prefix}Metrics", id)
        details = dict(snap)
        if prev is not None and elapsed:
            for n, v in snap.items():
                # gauges are levels, not flows: no rate. For true
                # counters, a value below its baseline means a reset
                # (role restarted under the same name): emit no rate
                # this interval and let the fresh snapshot re-baseline,
                # instead of a large negative rate
                if self.counters[n].gauge:
                    continue
                p = prev.get(n, 0)
                if v >= p:
                    details[f"{n}_per_sec"] = round((v - p) / elapsed, 3)
        ev.detail(**details).log()
        return snap


class TimeSeries:
    """Multi-resolution time series (ref: flow/TDMetric.actor.h — a
    metric keeps LEVELS of samples, each level 4x coarser than the one
    below, so recent history is fine-grained and old history cheap).
    Level 0 holds the newest `samples_per_level` raw samples; every
    CASCADE-th append to a level emits one aggregated sample (the mean
    of the cascade window) to the level above."""

    CASCADE = 4

    __slots__ = ("samples_per_level", "levels", "_carry")

    def __init__(self, samples_per_level: int = 64, n_levels: int = 4):
        self.samples_per_level = samples_per_level
        self.levels = [deque(maxlen=samples_per_level)
                       for _ in range(n_levels)]
        self._carry = [[] for _ in range(n_levels)]

    def append(self, t: float, value: float) -> None:
        self._append_level(0, t, value)

    def _append_level(self, lvl: int, t: float, value: float) -> None:
        self.levels[lvl].append((t, value))
        if lvl + 1 >= len(self.levels):
            return
        carry = self._carry[lvl]
        carry.append((t, value))
        if len(carry) >= self.CASCADE:
            mean = sum(v for _t, v in carry) / len(carry)
            self._append_level(lvl + 1, carry[-1][0], mean)
            carry.clear()

    def series(self, level: int = 0):
        return list(self.levels[level])

    def latest(self):
        return self.levels[0][-1] if self.levels[0] else None
