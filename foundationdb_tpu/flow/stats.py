"""Counters: rate-tracked event counters per role.

Reference: flow/Stats.actor.cpp — `Counter` (value + rolling rate +
roughness) grouped in a `CounterCollection`, traced periodically and
folded into the status document. The sim reads them directly for
status; a trace loop would emit them as TraceEvents in production.
"""

from __future__ import annotations

from typing import Dict

class Counter:
    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0

    def add(self, n: int = 1) -> None:
        self.value += n

    def set(self, n: int) -> None:
        """Gauge semantics (ref: TDMetric gauges beside counters)."""
        self.value = n


class CounterCollection:
    """(ref: CounterCollection — named counters for one role)"""

    def __init__(self, role: str):
        self.role = role
        self.counters: Dict[str, Counter] = {}

    def counter(self, name: str) -> Counter:
        c = self.counters.get(name)
        if c is None:
            c = self.counters[name] = Counter(name)
        return c

    def snapshot(self) -> Dict[str, int]:
        return {n: c.value for n, c in self.counters.items()}
